"""Advisor benchmark: recommendation quality + the adaptive-routing
no-regression gate on the TPC-DS slice (docs/advisor.md).

Three measured phases over one session with the routing ledger recording
throughout:

1. **raw** — indexes disabled; every query's source-scan wall lands in
   the ledger as the raw EMA;
2. **indexed** — indexes enabled, demotion suppressed (demoteRatio
   raised sky-high) so the phase measures the PURE indexed path; walls
   land as the indexed EMA;
3. **routed** — demoteRatio restored: signatures whose indexed path
   measured slower than raw are demoted to source scans, the rest keep
   their indexed plans.

The gate: with routing enabled, NO query may regress below
``GATE_MIN_RATIO`` (0.95) of its raw-scan time — the sub-1x rewrite tail
is eliminated structurally, because a demoted query simply runs the raw
plan it is being compared against. Results are asserted identical across
all three phases.

Recommendation quality rides the same run: a synthetic hot table (filter
queries, no index) must earn a ``create`` recommendation, and a
deliberately cold index (never queried) must earn a ``drop``
recommendation, from the workload the phases recorded.

Writes BENCH_ADVISOR.json; ``--smoke`` runs sf=0.05 with a query subset
(the CI `advisor` job), the default runs sf=1 over the full slice.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
from benchmarks.harness import assert_same_results, log, timed as _timed

GATE_MIN_RATIO = 0.95
# Absolute allowance on the ratio gate: at smoke scale queries run in
# single-digit milliseconds where scheduler jitter alone swings 20%+;
# the regression the gate exists to catch is STRUCTURAL (0.33x on
# 100ms-3s queries), where 5ms is invisible. routed <= raw/0.95 + EPS.
GATE_EPS_S = 0.005
SMOKE_QUERIES = 10
SUPPRESS_RATIO = 1e9  # demotion off while the indexed phase measures
# A "clear indexed win" (kept-plan gate): phase-2 wall under this
# fraction of raw is beyond noise and must NOT be demoted.
CLEAR_WIN_RATIO = 0.9


def _best_of(session, plan, reps: int):
    """Two untimed warmups OUTSIDE the routing ledger (cold parquet
    reads, first-shape jit compiles AND the second-pass device-cache
    derived builds would poison the EMA with costs every later run stops
    paying), then best of `reps` with recording on."""
    session.conf.set("hyperspace.advisor.routing.enabled", False)
    try:
        session.run(plan)
        session.run(plan)
    finally:
        session.conf.set("hyperspace.advisor.routing.enabled", True)
    times = []
    out = None
    for _ in range(reps):
        t, out = _timed(lambda: session.run(plan), warmup=0, reps=1)
        times.append(t)
    return min(times), out


def main(smoke: bool = False, out_path: str = "BENCH_ADVISOR.json") -> int:
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from benchmarks.tpcds import cached_tpcds, tpcds_indexes, tpcds_queries
    from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig, col

    sf = 0.05 if smoke else 1.0
    reps = 2 if smoke else 3
    tmp = Path(tempfile.mkdtemp(prefix="hs_adv_"))
    try:
        roots = cached_tpcds(sf=sf)
        session = HyperspaceSession(
            system_path=str(tmp / "indexes"), num_buckets=8 if smoke else 64
        )
        hs = Hyperspace(session)
        scans = {name: session.parquet(root) for name, root in roots.items()}
        t0 = time.perf_counter()
        tpcds_indexes(hs, scans)
        log(f"tpcds index builds (sf={sf:g}): {time.perf_counter() - t0:.1f}s")

        # Advisor fixtures: a HOT raw table (queried, uncovered -> the
        # analyzer must recommend creating its index) and a COLD index
        # (never queried -> it must recommend dropping it).
        rng = np.random.default_rng(5)
        hot_root = tmp / "hot_events"
        hot_root.mkdir()
        n_hot = 60_000
        pq.write_table(
            pa.table({
                "event_type": rng.integers(0, 400, n_hot),
                "tenant": rng.integers(0, 50, n_hot),
                "amount": rng.standard_normal(n_hot),
            }),
            hot_root / "part0.parquet",
        )
        hot = session.parquet(hot_root)
        cold_root = tmp / "cold_audit"
        cold_root.mkdir()
        pq.write_table(
            pa.table({
                "audit_id": np.arange(2000, dtype=np.int64),
                "blob": rng.standard_normal(2000),
            }),
            cold_root / "part0.parquet",
        )
        hs.create_index(
            session.parquet(cold_root), IndexConfig("cold_audit_idx", ["audit_id"], ["blob"])
        )

        all_queries = tpcds_queries(scans)
        names = list(all_queries)[:SMOKE_QUERIES] if smoke else list(all_queries)
        queries = {name: all_queries[name] for name in names}
        for i in range(4):
            queries[f"hot{i}"] = hot.filter(col("event_type") == 17 * i).select(
                "event_type", "amount"
            )

        session.conf.set("hyperspace.advisor.routing.enabled", True)
        session.conf.set("hyperspace.advisor.routing.demoteRatio", SUPPRESS_RATIO)

        # Phase 1: raw walls into the ledger.
        session.disable_hyperspace()
        raw: dict = {}
        for name, q in queries.items():
            raw[name] = _best_of(session, q, reps)
            log(f"raw      {name}: {raw[name][0]:.3f}s")
        # Phase 2: indexed walls (demotion suppressed — pure indexed path).
        session.enable_hyperspace()
        indexed: dict = {}
        for name, q in queries.items():
            indexed[name] = _best_of(session, q, reps)
            assert_same_results(name, raw[name][1], indexed[name][1])
            log(f"indexed  {name}: {indexed[name][0]:.3f}s")
        # Phase 3: adaptive routing live.
        session.conf.set("hyperspace.advisor.routing.demoteRatio", 1.0)
        ledger = session.routing_ledger()
        demoted_sigs = set(ledger.demoted_signatures())
        routed: dict = {}
        decisions: dict = {}
        for name, q in queries.items():
            routed[name] = _best_of(session, q, reps)
            assert_same_results(name, raw[name][1], routed[name][1])
            st = dict(session.last_query_stats)
            decisions[name] = st.get("advisor_routing", {})
            log(
                f"routed   {name}: {routed[name][0]:.3f}s "
                f"({decisions[name].get('decision')})"
            )
        ledger.flush()

        rows = []
        worst_ratio = float("inf")
        routing_ok = True
        kept_indexed_ok = True
        for name in queries:
            t_raw, t_idx, t_routed = raw[name][0], indexed[name][0], routed[name][0]
            ratio_vs_raw = t_raw / max(t_routed, 1e-12)
            worst_ratio = min(worst_ratio, ratio_vs_raw)
            query_ok = t_routed <= t_raw / GATE_MIN_RATIO + GATE_EPS_S
            routing_ok = routing_ok and query_ok
            demoted = bool(decisions[name].get("demoted"))
            if t_idx < CLEAR_WIN_RATIO * t_raw and demoted:
                # A clear indexed win (beyond noise) must keep its plan.
                kept_indexed_ok = False
            rows.append({
                "query": name,
                "raw_s": round(t_raw, 4),
                "indexed_s": round(t_idx, 4),
                "routed_s": round(t_routed, 4),
                "indexed_speedup": round(t_raw / max(t_idx, 1e-12), 3),
                "routed_vs_raw": round(ratio_vs_raw, 3),
                "gate_ok": query_ok,
                "decision": decisions[name].get("decision"),
                "demoted": demoted,
            })
        gate_pass = routing_ok and kept_indexed_ok

        # Recommendation quality over the recorded workload.
        recs = hs.recommend()
        creates = [
            r for r in recs
            if r.kind == "create" and r.source_root == str(hot_root)
        ]
        drops = [r for r in recs if r.kind == "drop" and r.index_name == "cold_audit_idx"]
        recs_pass = bool(creates) and bool(drops)
        log(
            f"recommendations: {len(recs)} total, hot-create={len(creates)}, "
            f"cold-drop={len(drops)}"
        )

        artifact = {
            "metric": "advisor_routing_min_ratio_vs_raw",
            "value": round(worst_ratio, 3),
            "unit": "x",
            "sf": sf,
            "smoke": smoke,
            "cpus": os.cpu_count(),
            "gate": {
                "min_ratio_required": GATE_MIN_RATIO,
                "eps_s": GATE_EPS_S,
                "worst_routed_vs_raw": round(worst_ratio, 3),
                "kept_indexed_ok": kept_indexed_ok,
                "routing_pass": gate_pass,
                "recommendations_pass": recs_pass,
            },
            "demoted_signatures": len(demoted_sigs),
            "queries": rows,
            "recommendations": [r.to_json() for r in recs],
            "ledger": {
                "entries": len(ledger.snapshot()["entries"]),
                "demoted": len(ledger.demoted_signatures()),
            },
        }
        print(json.dumps(artifact, indent=2))
        Path(out_path).write_text(json.dumps(artifact, indent=2) + "\n")
        if not gate_pass:
            log(f"GATE FAILED: worst routed/raw ratio {worst_ratio:.3f} < {GATE_MIN_RATIO}")
            return 1
        if not recs_pass:
            log("GATE FAILED: expected >=1 hot create rec and >=1 cold drop rec")
            return 1
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main(
        smoke="--smoke" in sys.argv,
        out_path=next(
            (a.split("=", 1)[1] for a in sys.argv if a.startswith("--out=")),
            "BENCH_ADVISOR.json",
        ),
    ))
