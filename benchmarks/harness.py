"""Shared benchmark harness: timing protocol and the raw-vs-indexed
result-equality gate every query bench applies (the analog of the
reference's verifyIndexUsage equality assertion,
E2EHyperspaceRulesTests.scala:324-340)."""

from __future__ import annotations

import sys
import time


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def timed(fn, warmup=1, reps=2):
    for _ in range(warmup):
        out = fn()
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn()
    return (time.perf_counter() - t0) / reps, out


def assert_same_results(name: str, raw, indexed) -> None:
    """Decoded result dicts must be identical (float columns to 1e-9)."""
    import numpy as np

    a, b = raw.decode(), indexed.decode()
    assert set(a) == set(b), (name, set(a), set(b))
    for c in a:
        av, bv = np.asarray(a[c]), np.asarray(b[c])
        assert len(av) == len(bv), (name, c, len(av), len(bv))
        if av.dtype.kind in "fc":
            np.testing.assert_allclose(av, bv, rtol=1e-9, err_msg=f"{name}.{c}")
        else:
            assert (av == bv).all(), (name, c)
