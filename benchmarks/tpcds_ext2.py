"""TPC-DS round-5 second expansion: the cross-channel year-over-year,
returns-netting, and set-membership slices — q4 q5 q8 q10 q11 q26 q31
q35 q49 q58 q66 q75 q77 q78 q80. Same dataset and conventions as
benchmarks/tpcds.py / tpcds_ext.py (qgen-style substitutions for this
dataset's domains; IR-forced reformulations noted per query — the ss
channel's net-paid measures stand in ss_ext_sales_price [- coupon] for
the ungenerated ext_list/discount pair, as q74 established).
"""

from __future__ import annotations


def tpcds_extra_queries2(t: dict) -> dict:
    from hyperspace_tpu import AggSpec, col, date_lit, lit, when
    from hyperspace_tpu.plan.nodes import Union

    ss, dd, item, store = t["store_sales"], t["date_dim"], t["item"], t["store"]
    cs, ws = t["catalog_sales"], t["web_sales"]
    sr, cr, wr = t["store_returns"], t["catalog_returns"], t["web_returns"]
    cd, ca = t["customer_demographics"], t["customer_address"]
    cust, promo = t["customer"], t["promotion"]
    wh, sm = t["warehouse"], t["ship_mode"]
    web_site, wp, cp = t["web_site"], t["web_page"], t["catalog_page"]

    one = lit(1)

    # ---- q8: store sales in zips shared by the probe list and zips
    # with >10 preferred customers (INTERSECT of zip5 sets, joined to
    # stores on the zip2 prefix).
    # The published ~400-zip probe list, scaled to this dataset's uniform
    # 10000-99999 zip domain (400 consecutive zip5s); the preferred-
    # customer HAVING threshold scales with the ~1-customer-per-zip
    # density the same way qgen rescales parameters per SF.
    probe_zips = (
        ca.select(("zip5", col("ca_zip").substr(1, 5)))
        .filter(col("zip5").isin([str(z) for z in range(55000, 55400)]))
    )
    pref_zips = (
        cust.select("c_customer_sk", "c_current_addr_sk", "c_preferred_cust_flag")
        .filter(col("c_preferred_cust_flag") == lit("Y"))
        .join(ca.select("ca_address_sk", "ca_zip"),
              ["c_current_addr_sk"], ["ca_address_sk"])
        .select(("zip5", col("ca_zip").substr(1, 5)))
        .aggregate(["zip5"], [AggSpec.of("count", None, "cnt")])
        .filter(col("cnt") > lit(1))
        .select("zip5")
    )
    both_zips = probe_zips.intersect(pref_zips).select(("zip2", col("zip5").substr(1, 2)))
    q8 = (
        ss.select("ss_sold_date_sk", "ss_store_sk", "ss_net_profit")
        .join(
            dd.select("d_date_sk", "d_qoy", "d_year").filter(
                (col("d_qoy") == lit(2)) & (col("d_year") == lit(1998))
            ),
            ["ss_sold_date_sk"], ["d_date_sk"],
        )
        .join(
            store.select("s_store_sk", "s_store_name", ("s_zip2", col("s_zip").substr(1, 2)))
            .join(both_zips, ["s_zip2"], ["zip2"], how="semi"),
            ["ss_store_sk"], ["s_store_sk"],
        )
        .aggregate(["s_store_name"], [AggSpec.of("sum", "ss_net_profit", "sum_np")])
        .sort([("s_store_name", True)])
        .limit(100)
    )

    # ---- q10 / q35: county customers with a store purchase AND a
    # web-or-catalog purchase in the window (the OR of two EXISTS rides
    # LEFT-join flags), profiled by demographics.
    def active_in(fact, dk, ck, months):
        return (
            fact.select(dk, ck)
            .join(
                dd.select("d_date_sk", "d_year", "d_moy").filter(
                    (col("d_year") == lit(2002)) & col("d_moy").between(*months)
                ),
                [dk], ["d_date_sk"],
            )
            .select(ck)
        )

    ws_buyers = (
        active_in(ws, "ws_sold_date_sk", "ws_bill_customer_sk", (1, 4))
        .distinct().select(("ws_cust", col("ws_bill_customer_sk")), ("ws_flag", one))
    )
    cs_buyers = (
        active_in(cs, "cs_sold_date_sk", "cs_bill_customer_sk", (1, 4))
        .distinct().select(("cs_cust", col("cs_bill_customer_sk")), ("cs_flag", one))
    )

    def demo_profile(group_cols, aggs, county_pred, sort_keys):
        return (
            cust.select("c_customer_sk", "c_current_addr_sk", "c_current_cdemo_sk")
            .join(ca.select("ca_address_sk", "ca_county", "ca_state").filter(county_pred),
                  ["c_current_addr_sk"], ["ca_address_sk"])
            .join(active_in(ss, "ss_sold_date_sk", "ss_customer_sk", (1, 4)),
                  ["c_customer_sk"], ["ss_customer_sk"], how="semi")
            .join(ws_buyers, ["c_customer_sk"], ["ws_cust"], how="left")
            .join(cs_buyers, ["c_customer_sk"], ["cs_cust"], how="left")
            .filter(col("ws_flag").is_not_null() | col("cs_flag").is_not_null())
            .join(
                cd.select("cd_demo_sk", "cd_gender", "cd_marital_status",
                          "cd_education_status", "cd_purchase_estimate",
                          "cd_credit_rating", "cd_dep_count"),
                ["c_current_cdemo_sk"], ["cd_demo_sk"],
            )
            .aggregate(group_cols, aggs)
            .sort(sort_keys)
            .limit(100)
        )

    q10 = demo_profile(
        ["cd_gender", "cd_marital_status", "cd_education_status",
         "cd_purchase_estimate", "cd_credit_rating", "cd_dep_count"],
        [AggSpec.of("count", None, "cnt1")],
        col("ca_county").isin(["Ziebach County", "Luce County", "Fairfield County",
                               "Dona Ana County", "Barrow County"]),
        [("cd_gender", True), ("cd_marital_status", True),
         ("cd_education_status", True), ("cd_purchase_estimate", True)],
    )
    # q35 profiles by state with dep-count stats (published carries three
    # dep-count columns; this dataset generates one — noted adaptation).
    q35 = demo_profile(
        ["ca_state", "cd_gender", "cd_marital_status"],
        [
            AggSpec.of("count", None, "cnt1"),
            AggSpec.of("mean", "cd_dep_count", "avg_dep"),
            AggSpec.of("max", "cd_dep_count", "max_dep"),
            AggSpec.of("sum", "cd_dep_count", "sum_dep"),
        ],
        col("ca_state").isin(list("TX OH OR CA WA NM KY VA FL GA MI IL".split())),
        [("ca_state", True), ("cd_gender", True), ("cd_marital_status", True)],
    )

    # ---- q11 / q4: year-over-year per-customer growth across channels
    # (ss measure = ss_ext_sales_price - ss_coupon_amt standing in for
    # the ungenerated ext_list/discount pair).
    def chan_year_total(fact, dk, ck, measure, year, id_alias, tot_alias,
                        keep_names=False):
        p = (
            fact
            .join(dd.select("d_date_sk", "d_year").filter(col("d_year") == lit(year)),
                  [dk], ["d_date_sk"])
            .join(cust.select("c_customer_sk", "c_customer_id", "c_first_name",
                              "c_last_name", "c_birth_country"),
                  [ck], ["c_customer_sk"])
            .select("c_customer_id", "c_first_name", "c_last_name",
                    "c_birth_country", ("__m", measure))
            .aggregate(["c_customer_id", "c_first_name", "c_last_name",
                        "c_birth_country"],
                       [AggSpec.of("sum", "__m", tot_alias)])
        )
        cols = [(id_alias, col("c_customer_id")), tot_alias]
        if keep_names:
            cols = [(id_alias, col("c_customer_id")), "c_first_name",
                    "c_last_name", "c_birth_country", tot_alias]
        return p.select(*cols)

    ss_m = col("ss_ext_sales_price") - col("ss_coupon_amt")
    ws_m = col("ws_ext_list_price") - col("ws_ext_discount_amt")
    cs_m = col("cs_ext_list_price") - col("cs_ext_discount_amt")
    ss_sel = ss.select("ss_sold_date_sk", "ss_customer_sk", "ss_ext_sales_price",
                       "ss_coupon_amt")
    ws_sel = ws.select("ws_sold_date_sk", "ws_bill_customer_sk",
                       "ws_ext_list_price", "ws_ext_discount_amt")
    cs_sel = cs.select("cs_sold_date_sk", "cs_bill_customer_sk",
                       "cs_ext_list_price", "cs_ext_discount_amt")

    def yoy(parts, growth_pairs, select_cols, sort_keys):
        """Join per-channel per-year totals on customer id and keep
        customers where EVERY listed channel's year-over-year growth
        beats the store channel's (the q11/q4 shape)."""
        joined = parts[0][0]
        for p, id_alias in parts[1:]:
            joined = joined.join(p, [parts[0][1]], [id_alias])
        snum, sden = growth_pairs[0]
        cond = col(sden) > lit(0.0)
        for num, den in growth_pairs[1:]:
            cond = cond & (col(den) > lit(0.0)) & (
                (col(num) / col(den)) > (col(snum) / col(sden))
            )
        return joined.filter(cond).select(*select_cols).sort(sort_keys).limit(100)

    q11 = yoy(
        [
            (chan_year_total(ss_sel, "ss_sold_date_sk", "ss_customer_sk", ss_m,
                             1999, "cid", "s1", keep_names=True), "cid"),
            (chan_year_total(ss_sel, "ss_sold_date_sk", "ss_customer_sk", ss_m,
                             2000, "cid_s2", "s2"), "cid_s2"),
            (chan_year_total(ws_sel, "ws_sold_date_sk", "ws_bill_customer_sk", ws_m,
                             1999, "cid_w1", "w1"), "cid_w1"),
            (chan_year_total(ws_sel, "ws_sold_date_sk", "ws_bill_customer_sk", ws_m,
                             2000, "cid_w2", "w2"), "cid_w2"),
        ],
        [("s2", "s1"), ("w2", "w1")],
        ["cid", "c_first_name", "c_last_name", "c_birth_country"],
        [("cid", True), ("c_first_name", True), ("c_last_name", True)],
    )
    q4 = yoy(
        [
            (chan_year_total(ss_sel, "ss_sold_date_sk", "ss_customer_sk", ss_m,
                             1999, "cid", "s1", keep_names=True), "cid"),
            (chan_year_total(ss_sel, "ss_sold_date_sk", "ss_customer_sk", ss_m,
                             2000, "cid_s2", "s2"), "cid_s2"),
            (chan_year_total(cs_sel, "cs_sold_date_sk", "cs_bill_customer_sk", cs_m,
                             1999, "cid_c1", "c1"), "cid_c1"),
            (chan_year_total(cs_sel, "cs_sold_date_sk", "cs_bill_customer_sk", cs_m,
                             2000, "cid_c2", "c2"), "cid_c2"),
            (chan_year_total(ws_sel, "ws_sold_date_sk", "ws_bill_customer_sk", ws_m,
                             1999, "cid_w1", "w1"), "cid_w1"),
            (chan_year_total(ws_sel, "ws_sold_date_sk", "ws_bill_customer_sk", ws_m,
                             2000, "cid_w2", "w2"), "cid_w2"),
        ],
        [("s2", "s1"), ("c2", "c1"), ("w2", "w1")],
        ["cid", "c_first_name", "c_last_name", "c_birth_country"],
        [("cid", True), ("c_first_name", True), ("c_last_name", True)],
    )

    # ---- q26: catalog buyer demographics averages (q7's catalog twin).
    q26 = (
        cs.select("cs_sold_date_sk", "cs_item_sk", "cs_bill_cdemo_sk",
                  "cs_promo_sk", "cs_quantity", "cs_list_price", "cs_coupon_amt",
                  "cs_sales_price")
        .join(
            cd.select("cd_demo_sk", "cd_gender", "cd_marital_status",
                      "cd_education_status").filter(
                (col("cd_gender") == lit("M"))
                & (col("cd_marital_status") == lit("S"))
                & (col("cd_education_status") == lit("College"))
            ),
            ["cs_bill_cdemo_sk"], ["cd_demo_sk"],
        )
        .join(dd.select("d_date_sk", "d_year").filter(col("d_year") == lit(2000)),
              ["cs_sold_date_sk"], ["d_date_sk"])
        .join(item.select("i_item_sk", "i_item_id"), ["cs_item_sk"], ["i_item_sk"])
        .join(
            promo.select("p_promo_sk", "p_channel_email", "p_channel_event").filter(
                (col("p_channel_email") == lit("N")) | (col("p_channel_event") == lit("N"))
            ),
            ["cs_promo_sk"], ["p_promo_sk"],
        )
        .aggregate(
            ["i_item_id"],
            [
                AggSpec.of("mean", "cs_quantity", "agg1"),
                AggSpec.of("mean", "cs_list_price", "agg2"),
                AggSpec.of("mean", "cs_coupon_amt", "agg3"),
                AggSpec.of("mean", "cs_sales_price", "agg4"),
            ],
        )
        .sort(["i_item_id"])
        .limit(100)
    )

    # ---- q31: county-level quarterly growth, web vs store.
    def county_qoy(fact, dk, ak, price, qoy, alias, county_out):
        return (
            fact.select(dk, ak, price)
            .join(
                dd.select("d_date_sk", "d_qoy", "d_year").filter(
                    (col("d_qoy") == lit(qoy)) & (col("d_year") == lit(2000))
                ),
                [dk], ["d_date_sk"],
            )
            .join(ca.select("ca_address_sk", "ca_county"), [ak], ["ca_address_sk"])
            .aggregate(["ca_county"], [AggSpec.of("sum", price, alias)])
            .select((county_out, col("ca_county")), alias)
        )

    ss1 = county_qoy(ss, "ss_sold_date_sk", "ss_addr_sk", "ss_ext_sales_price", 1, "ss1", "cty")
    ss2 = county_qoy(ss, "ss_sold_date_sk", "ss_addr_sk", "ss_ext_sales_price", 2, "ss2", "cty2")
    ss3 = county_qoy(ss, "ss_sold_date_sk", "ss_addr_sk", "ss_ext_sales_price", 3, "ss3", "cty3")
    ws1 = county_qoy(ws, "ws_sold_date_sk", "ws_bill_addr_sk", "ws_ext_sales_price", 1, "ws1", "wcty1")
    ws2 = county_qoy(ws, "ws_sold_date_sk", "ws_bill_addr_sk", "ws_ext_sales_price", 2, "ws2", "wcty2")
    ws3 = county_qoy(ws, "ws_sold_date_sk", "ws_bill_addr_sk", "ws_ext_sales_price", 3, "ws3", "wcty3")
    q31 = (
        ss1.join(ss2, ["cty"], ["cty2"]).join(ss3, ["cty"], ["cty3"])
        .join(ws1, ["cty"], ["wcty1"]).join(ws2, ["cty"], ["wcty2"])
        .join(ws3, ["cty"], ["wcty3"])
        .filter(
            (col("ss1") > lit(0.0)) & (col("ss2") > lit(0.0))
            & (col("ws1") > lit(0.0)) & (col("ws2") > lit(0.0))
            & ((col("ws2") / col("ws1")) > (col("ss2") / col("ss1")))
            & ((col("ws3") / col("ws2")) > (col("ss3") / col("ss2")))
        )
        .select("cty", ("web_q1_q2_increase", col("ws2") / col("ws1")),
                ("store_q1_q2_increase", col("ss2") / col("ss1")),
                ("web_q2_q3_increase", col("ws3") / col("ws2")),
                ("store_q2_q3_increase", col("ss3") / col("ss2")))
        .sort([("cty", True)])
        .limit(100)
    )

    # ---- q49: worst return ratios per channel, rank-unioned.
    def return_ratios(fact, rt, s_order, s_item, r_order, r_item, qty, price,
                      r_qty, r_amt, dk, channel):
        base = (
            fact
            .join(
                dd.select("d_date_sk", "d_year", "d_moy").filter(
                    (col("d_year") == lit(2000)) & (col("d_moy") == lit(12))
                ),
                [dk], ["d_date_sk"],
            )
            .filter((col(price) > lit(1.0)) & (col(qty) > lit(0)))
            .join(
                rt.select(r_order, r_item, r_qty, r_amt),
                [s_order, s_item], [r_order, r_item], how="left",
            )
            .select(
                (f"item", col(s_item)),
                ("ret_qty", when(col(r_qty).is_not_null(), col(r_qty)).otherwise(0)),
                ("ret_amt", when(col(r_amt).is_not_null(), col(r_amt)).otherwise(0.0)),
                ("qty", col(qty)),
                ("amt", col(price) * col(qty)),
            )
            .aggregate(
                ["item"],
                [
                    AggSpec.of("sum", "ret_qty", "srq"), AggSpec.of("sum", "qty", "sq"),
                    AggSpec.of("sum", "ret_amt", "sra"), AggSpec.of("sum", "amt", "sa"),
                ],
            )
            .select("item",
                    ("return_ratio", (col("srq") * lit(1.0)) / col("sq")),
                    ("currency_ratio", col("sra") / col("sa")))
            .window([], order_by=[("return_ratio", True)],
                    funcs=[("rank", None, "return_rank")])
            .window([], order_by=[("currency_ratio", True)],
                    funcs=[("rank", None, "currency_rank")])
            .filter((col("return_rank") <= lit(10)) | (col("currency_rank") <= lit(10)))
        )
        return base.select(("channel", lit(channel)), "item", "return_ratio",
                           "currency_ratio", "return_rank", "currency_rank")

    q49 = (
        Union([
            return_ratios(
                ws.select("ws_sold_date_sk", "ws_order_number", "ws_item_sk",
                          "ws_quantity", "ws_net_paid"),
                wr, "ws_order_number", "ws_item_sk", "wr_order_number", "wr_item_sk",
                "ws_quantity", "ws_net_paid", "wr_return_quantity", "wr_return_amt",
                "ws_sold_date_sk", "web"),
            return_ratios(
                cs.select("cs_sold_date_sk", "cs_order_number", "cs_item_sk",
                          "cs_quantity", "cs_net_paid"),
                cr, "cs_order_number", "cs_item_sk", "cr_order_number", "cr_item_sk",
                "cs_quantity", "cs_net_paid", "cr_return_quantity", "cr_return_amt",
                "cs_sold_date_sk", "catalog"),
            return_ratios(
                ss.select("ss_sold_date_sk", "ss_ticket_number", "ss_item_sk",
                          "ss_quantity", "ss_sales_price"),
                sr, "ss_ticket_number", "ss_item_sk", "sr_ticket_number", "sr_item_sk",
                "ss_quantity", "ss_sales_price", "sr_return_quantity", "sr_return_amt",
                "ss_sold_date_sk", "store"),
        ])
        .sort([("channel", True), ("return_rank", True), ("currency_rank", True),
               ("item", True)])
        .limit(100)
    )

    # ---- q58: items whose one-week revenue is within 10% of the
    # three-channel average (the week-of-date subquery as a semi join).
    wk58 = (
        dd.select("d_week_seq", "d_date")
        .filter(col("d_date") == date_lit("2000-01-03"))
        .select("d_week_seq")
    )
    dates58 = (
        dd.select("d_date_sk", "d_week_seq")
        .join(wk58, ["d_week_seq"], ["d_week_seq"], how="semi")
        .select("d_date_sk")
    )

    def item_rev(fact, dk, ik, price, id_out, rev_out):
        return (
            fact.select(dk, ik, price)
            .join(dates58, [dk], ["d_date_sk"], how="semi")
            .join(item.select("i_item_sk", "i_item_id"), [ik], ["i_item_sk"])
            .aggregate(["i_item_id"], [AggSpec.of("sum", price, rev_out)])
            .select((id_out, col("i_item_id")), rev_out)
        )

    q58 = (
        item_rev(ss, "ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price",
                 "item_id", "ss_item_rev")
        .join(item_rev(cs, "cs_sold_date_sk", "cs_item_sk", "cs_ext_sales_price",
                       "item_id_c", "cs_item_rev"), ["item_id"], ["item_id_c"])
        .join(item_rev(ws, "ws_sold_date_sk", "ws_item_sk", "ws_ext_sales_price",
                       "item_id_w", "ws_item_rev"), ["item_id"], ["item_id_w"])
        .filter(
            col("ss_item_rev").between(col("cs_item_rev") * lit(0.9), col("cs_item_rev") * lit(1.1))
            & col("ss_item_rev").between(col("ws_item_rev") * lit(0.9), col("ws_item_rev") * lit(1.1))
            & col("cs_item_rev").between(col("ss_item_rev") * lit(0.9), col("ss_item_rev") * lit(1.1))
            & col("cs_item_rev").between(col("ws_item_rev") * lit(0.9), col("ws_item_rev") * lit(1.1))
            & col("ws_item_rev").between(col("ss_item_rev") * lit(0.9), col("ss_item_rev") * lit(1.1))
            & col("ws_item_rev").between(col("cs_item_rev") * lit(0.9), col("cs_item_rev") * lit(1.1))
        )
        .select("item_id", "ss_item_rev", "cs_item_rev", "ws_item_rev",
                ("average", (col("ss_item_rev") + col("cs_item_rev") + col("ws_item_rev")) / lit(3.0)))
        .sort([("item_id", True), ("ss_item_rev", True)])
        .limit(100)
    )

    # ---- q66: warehouse shipping pivot by carrier band and month.
    def wh_monthly(fact, dk, tk, whk, smk, qty, price, net, prefix):
        monthly = [
            AggSpec.of(
                "sum",
                when(col("d_moy") == lit(m), col(price) * col(qty)).otherwise(0.0),
                f"{prefix}_sales_m{m}",
            )
            for m in range(1, 13)
        ] + [
            AggSpec.of(
                "sum",
                when(col("d_moy") == lit(m), col(net) * col(qty)).otherwise(0.0),
                f"{prefix}_net_m{m}",
            )
            for m in range(1, 13)
        ]
        return (
            fact.select(dk, tk, whk, smk, qty, price, net)
            .join(dd.select("d_date_sk", "d_year", "d_moy").filter(col("d_year") == lit(2000)),
                  [dk], ["d_date_sk"])
            .join(t["time_dim"].select("t_time_sk", "t_hour").filter(
                col("t_hour").between(8, 16)), [tk], ["t_time_sk"])
            .join(sm.select("sm_ship_mode_sk", "sm_carrier").filter(
                col("sm_carrier").isin(["carrier0", "carrier1"])),
                [smk], ["sm_ship_mode_sk"])
            .join(wh.select("w_warehouse_sk", "w_warehouse_name", "w_warehouse_sq_ft",
                            "w_city", "w_county", "w_state", "w_country"),
                  [whk], ["w_warehouse_sk"])
            .aggregate(
                ["w_warehouse_name", "w_warehouse_sq_ft", "w_city", "w_county",
                 "w_state", "w_country"],
                monthly,
            )
        )

    ws66 = wh_monthly(ws, "ws_sold_date_sk", "ws_sold_time_sk", "ws_warehouse_sk",
                      "ws_ship_mode_sk", "ws_quantity", "ws_ext_sales_price",
                      "ws_net_paid", "x")
    cs66 = wh_monthly(cs, "cs_sold_date_sk", "cs_sold_time_sk", "cs_warehouse_sk",
                      "cs_ship_mode_sk", "cs_quantity", "cs_ext_sales_price",
                      "cs_net_paid", "x")
    q66 = (
        Union([ws66, cs66])
        .aggregate(
            ["w_warehouse_name", "w_warehouse_sq_ft", "w_city", "w_county",
             "w_state", "w_country"],
            [AggSpec.of("sum", f"x_sales_m{m}", f"sales_m{m}") for m in range(1, 13)]
            + [AggSpec.of("sum", f"x_net_m{m}", f"net_m{m}") for m in range(1, 13)],
        )
        .sort([("w_warehouse_name", True)])
        .limit(100)
    )

    # ---- q75: prior-year manufacturer decline across all channels,
    # sales net of returns at (year, brand, class, category, manufact).
    def chan_net(fact, dk, ik, qty, price, rt, s_order, r_order, s_item, r_item,
                 r_qty, r_amt):
        return (
            fact
            .join(dd.select("d_date_sk", "d_year").filter(
                col("d_year").isin([1999, 2000])), [dk], ["d_date_sk"])
            .join(item.select("i_item_sk", "i_brand_id", "i_class", "i_category_id",
                              "i_category", "i_manufact_id").filter(
                col("i_category") == lit("Books")), [ik], ["i_item_sk"])
            .join(rt.select(r_order, r_item, r_qty, r_amt),
                  [s_order, s_item], [r_order, r_item], how="left")
            .select(
                "d_year", "i_brand_id", "i_class", "i_category_id", "i_manufact_id",
                ("net_qty", col(qty) - when(col(r_qty).is_not_null(), col(r_qty)).otherwise(0)),
                ("net_amt", col(price) * col(qty)
                 - when(col(r_amt).is_not_null(), col(r_amt)).otherwise(0.0)),
            )
        )

    all_net = Union([
        chan_net(ss.select("ss_sold_date_sk", "ss_item_sk", "ss_ticket_number",
                           "ss_quantity", "ss_sales_price"),
                 "ss_sold_date_sk", "ss_item_sk", "ss_quantity", "ss_sales_price",
                 sr, "ss_ticket_number", "sr_ticket_number", "ss_item_sk",
                 "sr_item_sk", "sr_return_quantity", "sr_return_amt"),
        chan_net(cs.select("cs_sold_date_sk", "cs_item_sk", "cs_order_number",
                           "cs_quantity", "cs_sales_price"),
                 "cs_sold_date_sk", "cs_item_sk", "cs_quantity", "cs_sales_price",
                 cr, "cs_order_number", "cr_order_number", "cs_item_sk",
                 "cr_item_sk", "cr_return_quantity", "cr_return_amt"),
        chan_net(ws.select("ws_sold_date_sk", "ws_item_sk", "ws_order_number",
                           "ws_quantity", "ws_sales_price"),
                 "ws_sold_date_sk", "ws_item_sk", "ws_quantity", "ws_sales_price",
                 wr, "ws_order_number", "wr_order_number", "ws_item_sk",
                 "wr_item_sk", "wr_return_quantity", "wr_return_amt"),
    ])
    yearly = all_net.aggregate(
        ["d_year", "i_brand_id", "i_class", "i_category_id", "i_manufact_id"],
        [AggSpec.of("sum", "net_qty", "qty"), AggSpec.of("sum", "net_amt", "amt")],
    )
    prev = yearly.filter(col("d_year") == lit(1999)).select(
        ("b2", col("i_brand_id")), ("cl2", col("i_class")),
        ("cat2", col("i_category_id")), ("m2", col("i_manufact_id")),
        ("prev_qty", col("qty")), ("prev_amt", col("amt")),
    )
    q75 = (
        yearly.filter(col("d_year") == lit(2000))
        .join(prev, ["i_brand_id", "i_class", "i_category_id", "i_manufact_id"],
              ["b2", "cl2", "cat2", "m2"])
        .filter((col("qty") * lit(10)) < (col("prev_qty") * lit(9)))  # <0.9x
        .select("i_brand_id", "i_class", "i_category_id", "i_manufact_id",
                "prev_qty", "qty", ("qty_diff", col("qty") - col("prev_qty")),
                ("amt_diff", col("amt") - col("prev_amt")))
        .sort([("qty_diff", True), ("i_brand_id", True)])
        .limit(100)
    )

    # ---- q77 / q80 / q5: channel sales-vs-returns rollups.
    dd30 = dd.select("d_date_sk", "d_date").filter(
        (col("d_date") >= date_lit("2000-08-03"))
        & (col("d_date") <= date_lit("2000-09-02"))
    )

    def sales_part(fact, dk, gk, price, profit, id_out):
        return (
            fact.select(dk, gk, price, profit)
            .join(dd30, [dk], ["d_date_sk"])
            .aggregate([gk], [AggSpec.of("sum", price, "sales"),
                              AggSpec.of("sum", profit, "profit")])
            .select((id_out, col(gk)), "sales", "profit")
        )

    def returns_part(rt, dk, gk, amt, loss, id_out):
        return (
            rt.select(dk, gk, amt, loss)
            .join(dd30, [dk], ["d_date_sk"])
            .aggregate([gk], [AggSpec.of("sum", amt, "returns_"),
                              AggSpec.of("sum", loss, "profit_loss")])
            .select((id_out, col(gk)), "returns_", "profit_loss")
        )

    ss77 = sales_part(ss, "ss_sold_date_sk", "ss_store_sk", "ss_ext_sales_price",
                      "ss_net_profit", "sid")
    sr77 = returns_part(sr, "sr_returned_date_sk", "sr_store_sk", "sr_return_amt",
                        "sr_net_loss", "sid_r")
    store_chan = (
        ss77.join(sr77, ["sid"], ["sid_r"], how="left")
        .select(("channel", lit("store channel")), ("id", col("sid")),
                "sales",
                ("returns_", when(col("returns_").is_not_null(), col("returns_")).otherwise(0.0)),
                ("profit", col("profit")
                 - when(col("profit_loss").is_not_null(), col("profit_loss")).otherwise(0.0)))
    )
    cs77 = sales_part(cs, "cs_sold_date_sk", "cs_call_center_sk",
                      "cs_ext_sales_price", "cs_net_profit", "ccid")
    cr77 = returns_part(cr, "cr_returned_date_sk", "cr_call_center_sk",
                        "cr_return_amt", "cr_net_loss", "ccid_r")
    catalog_chan = (
        cs77.join(cr77, ["ccid"], ["ccid_r"], how="left")
        .select(("channel", lit("catalog channel")), ("id", col("ccid")),
                "sales",
                ("returns_", when(col("returns_").is_not_null(), col("returns_")).otherwise(0.0)),
                ("profit", col("profit")
                 - when(col("profit_loss").is_not_null(), col("profit_loss")).otherwise(0.0)))
    )
    ws77 = sales_part(ws, "ws_sold_date_sk", "ws_web_page_sk", "ws_ext_sales_price",
                      "ws_net_profit", "wpid")
    wr77 = returns_part(wr, "wr_returned_date_sk", "wr_web_page_sk", "wr_return_amt",
                        "wr_net_loss", "wpid_r")
    web_chan = (
        ws77.join(wr77, ["wpid"], ["wpid_r"], how="left")
        .select(("channel", lit("web channel")), ("id", col("wpid")),
                "sales",
                ("returns_", when(col("returns_").is_not_null(), col("returns_")).otherwise(0.0)),
                ("profit", col("profit")
                 - when(col("profit_loss").is_not_null(), col("profit_loss")).otherwise(0.0)))
    )
    q77 = (
        Union([store_chan, catalog_chan, web_chan])
        .rollup(["channel", "id"],
                [AggSpec.of("sum", "sales", "sales_total"),
                 AggSpec.of("sum", "returns_", "returns_total"),
                 AggSpec.of("sum", "profit", "profit_total")])
        .sort([("channel", True), ("id", True)])
        .limit(100)
    )

    # q80: like q77 at (channel, promotion-filtered item grain) keyed by
    # the business ids, netting per-ROW returns via the order/ticket link.
    def chan_net_rollup(fact, dk, ik, pk, price, profit, rt, s_order, r_order,
                        s_item, r_item, r_amt, r_loss, dim, dim_sk, dim_id, fk,
                        channel):
        return (
            fact
            .join(dd30, [dk], ["d_date_sk"])
            .join(item.select("i_item_sk", "i_current_price").filter(
                col("i_current_price") > lit(50.0)), [ik], ["i_item_sk"])
            .join(promo.select("p_promo_sk", "p_channel_tv").filter(
                col("p_channel_tv") == lit("N")), [pk], ["p_promo_sk"])
            .join(rt.select(r_order, r_item, r_amt, r_loss),
                  [s_order, s_item], [r_order, r_item], how="left")
            .join(dim.select(dim_sk, dim_id), [fk], [dim_sk])
            .select(
                ("channel", lit(channel)), ("id", col(dim_id)),
                ("sales", col(price)),
                ("returns_", when(col(r_amt).is_not_null(), col(r_amt)).otherwise(0.0)),
                ("profit", col(profit)
                 - when(col(r_loss).is_not_null(), col(r_loss)).otherwise(0.0)),
            )
        )

    q80 = (
        Union([
            chan_net_rollup(
                ss.select("ss_sold_date_sk", "ss_item_sk", "ss_promo_sk",
                          "ss_ticket_number", "ss_store_sk", "ss_ext_sales_price",
                          "ss_net_profit"),
                "ss_sold_date_sk", "ss_item_sk", "ss_promo_sk",
                "ss_ext_sales_price", "ss_net_profit",
                sr, "ss_ticket_number", "sr_ticket_number", "ss_item_sk",
                "sr_item_sk", "sr_return_amt", "sr_net_loss",
                store, "s_store_sk", "s_store_id", "ss_store_sk", "store channel"),
            chan_net_rollup(
                cs.select("cs_sold_date_sk", "cs_item_sk", "cs_promo_sk",
                          "cs_order_number", "cs_catalog_page_sk",
                          "cs_ext_sales_price", "cs_net_profit"),
                "cs_sold_date_sk", "cs_item_sk", "cs_promo_sk",
                "cs_ext_sales_price", "cs_net_profit",
                cr, "cs_order_number", "cr_order_number", "cs_item_sk",
                "cr_item_sk", "cr_return_amt", "cr_net_loss",
                cp, "cp_catalog_page_sk", "cp_catalog_page_id",
                "cs_catalog_page_sk", "catalog channel"),
            chan_net_rollup(
                ws.select("ws_sold_date_sk", "ws_item_sk", "ws_promo_sk",
                          "ws_order_number", "ws_web_site_sk", "ws_ext_sales_price",
                          "ws_net_profit"),
                "ws_sold_date_sk", "ws_item_sk", "ws_promo_sk",
                "ws_ext_sales_price", "ws_net_profit",
                wr, "ws_order_number", "wr_order_number", "ws_item_sk",
                "wr_item_sk", "wr_return_amt", "wr_net_loss",
                web_site, "web_site_sk", "web_site_id", "ws_web_site_sk",
                "web channel"),
        ])
        .rollup(["channel", "id"],
                [AggSpec.of("sum", "sales", "sales_total"),
                 AggSpec.of("sum", "returns_", "returns_total"),
                 AggSpec.of("sum", "profit", "profit_total")])
        .sort([("channel", True), ("id", True)])
        .limit(100)
    )

    # q5: the sales-and-returns union PER ROW (returns enter as negative-
    # profit rows), rolled up by channel/id over a 14-day window.
    dd14 = dd.select("d_date_sk", "d_date").filter(
        (col("d_date") >= date_lit("2000-08-19"))
        & (col("d_date") <= date_lit("2000-09-02"))
    )

    def rowset(fact, dk, gk, sales, profit, ret, loss):
        return (
            fact
            .join(dd14, [dk], ["d_date_sk"])
            .select((("gk"), col(gk)), ("sales", sales), ("ret", ret),
                    ("profit", profit), ("loss", loss))
        )

    store_rows = Union([
        rowset(ss.select("ss_sold_date_sk", "ss_store_sk", "ss_ext_sales_price",
                         "ss_net_profit"),
               "ss_sold_date_sk", "ss_store_sk", col("ss_ext_sales_price"),
               col("ss_net_profit"), lit(0.0), lit(0.0)),
        rowset(sr.select("sr_returned_date_sk", "sr_store_sk", "sr_return_amt",
                         "sr_net_loss"),
               "sr_returned_date_sk", "sr_store_sk", lit(0.0), lit(0.0),
               col("sr_return_amt"), col("sr_net_loss")),
    ])
    s5 = (
        store_rows.join(store.select("s_store_sk", "s_store_id"), ["gk"], ["s_store_sk"])
        .aggregate(["s_store_id"],
                   [AggSpec.of("sum", "sales", "sales_t"), AggSpec.of("sum", "ret", "ret_t"),
                    AggSpec.of("sum", "profit", "p_t"), AggSpec.of("sum", "loss", "l_t")])
        .select(("channel", lit("store channel")), ("id", col("s_store_id")),
                ("sales", col("sales_t")), ("returns_", col("ret_t")),
                ("profit", col("p_t") - col("l_t")))
    )
    catalog_rows = Union([
        rowset(cs.select("cs_sold_date_sk", "cs_catalog_page_sk",
                         "cs_ext_sales_price", "cs_net_profit"),
               "cs_sold_date_sk", "cs_catalog_page_sk", col("cs_ext_sales_price"),
               col("cs_net_profit"), lit(0.0), lit(0.0)),
        rowset(cr.select("cr_returned_date_sk", "cr_catalog_page_sk",
                         "cr_return_amt", "cr_net_loss"),
               "cr_returned_date_sk", "cr_catalog_page_sk", lit(0.0), lit(0.0),
               col("cr_return_amt"), col("cr_net_loss")),
    ])
    c5 = (
        catalog_rows.join(cp.select("cp_catalog_page_sk", "cp_catalog_page_id"),
                          ["gk"], ["cp_catalog_page_sk"])
        .aggregate(["cp_catalog_page_id"],
                   [AggSpec.of("sum", "sales", "sales_t"), AggSpec.of("sum", "ret", "ret_t"),
                    AggSpec.of("sum", "profit", "p_t"), AggSpec.of("sum", "loss", "l_t")])
        .select(("channel", lit("catalog channel")), ("id", col("cp_catalog_page_id")),
                ("sales", col("sales_t")), ("returns_", col("ret_t")),
                ("profit", col("p_t") - col("l_t")))
    )
    # Web returns reach the site through their sale (item+order link).
    wr_site = (
        wr.select("wr_returned_date_sk", "wr_item_sk", "wr_order_number",
                  "wr_return_amt", "wr_net_loss")
        .join(ws.select("ws_item_sk", "ws_order_number", "ws_web_site_sk"),
              ["wr_item_sk", "wr_order_number"], ["ws_item_sk", "ws_order_number"])
    )
    web_rows = Union([
        rowset(ws.select("ws_sold_date_sk", "ws_web_site_sk", "ws_ext_sales_price",
                         "ws_net_profit"),
               "ws_sold_date_sk", "ws_web_site_sk", col("ws_ext_sales_price"),
               col("ws_net_profit"), lit(0.0), lit(0.0)),
        rowset(wr_site.select("wr_returned_date_sk", "ws_web_site_sk",
                              "wr_return_amt", "wr_net_loss"),
               "wr_returned_date_sk", "ws_web_site_sk", lit(0.0), lit(0.0),
               col("wr_return_amt"), col("wr_net_loss")),
    ])
    w5 = (
        web_rows.join(web_site.select("web_site_sk", "web_site_id"), ["gk"], ["web_site_sk"])
        .aggregate(["web_site_id"],
                   [AggSpec.of("sum", "sales", "sales_t"), AggSpec.of("sum", "ret", "ret_t"),
                    AggSpec.of("sum", "profit", "p_t"), AggSpec.of("sum", "loss", "l_t")])
        .select(("channel", lit("web channel")), ("id", col("web_site_id")),
                ("sales", col("sales_t")), ("returns_", col("ret_t")),
                ("profit", col("p_t") - col("l_t")))
    )
    q5 = (
        Union([s5, c5, w5])
        .rollup(["channel", "id"],
                [AggSpec.of("sum", "sales", "sales_total"),
                 AggSpec.of("sum", "returns_", "returns_total"),
                 AggSpec.of("sum", "profit", "profit_total")])
        .sort([("channel", True), ("id", True)])
        .limit(100)
    )

    # ---- q78: unreturned sales per (item, customer) across channels,
    # store-vs-web+catalog ratio for year 2000.
    def unreturned(fact, dk, ik, ck, linkk, rt, r_link, r_item, qty, price,
                   i_out, c_out, q_out, a_out):
        return (
            fact
            .join(rt.select(r_link, r_item, ("__rflag", one)),
                  [linkk, ik], [r_link, r_item], how="left")
            .filter(col("__rflag").is_null())
            .join(dd.select("d_date_sk", "d_year").filter(col("d_year") == lit(2000)),
                  [dk], ["d_date_sk"])
            .aggregate([ik, ck], [AggSpec.of("sum", qty, q_out),
                                  AggSpec.of("sum", price, a_out)])
            .select((i_out, col(ik)), (c_out, col(ck)), q_out, a_out)
        )

    ss78 = unreturned(
        ss.select("ss_sold_date_sk", "ss_item_sk", "ss_customer_sk",
                  "ss_ticket_number", "ss_quantity", "ss_sales_price"),
        "ss_sold_date_sk", "ss_item_sk", "ss_customer_sk", "ss_ticket_number",
        sr, "sr_ticket_number", "sr_item_sk", "ss_quantity", "ss_sales_price",
        "s_item", "s_cust", "ss_qty", "ss_amt")
    ws78 = unreturned(
        ws.select("ws_sold_date_sk", "ws_item_sk", "ws_bill_customer_sk",
                  "ws_order_number", "ws_quantity", "ws_sales_price"),
        "ws_sold_date_sk", "ws_item_sk", "ws_bill_customer_sk", "ws_order_number",
        wr, "wr_order_number", "wr_item_sk", "ws_quantity", "ws_sales_price",
        "w_item", "w_cust", "ws_qty", "ws_amt")
    cs78 = unreturned(
        cs.select("cs_sold_date_sk", "cs_item_sk", "cs_bill_customer_sk",
                  "cs_order_number", "cs_quantity", "cs_sales_price"),
        "cs_sold_date_sk", "cs_item_sk", "cs_bill_customer_sk", "cs_order_number",
        cr, "cr_order_number", "cr_item_sk", "cs_quantity", "cs_sales_price",
        "c_item", "c_cust", "cs_qty", "cs_amt")
    q78 = (
        ss78.join(ws78, ["s_item", "s_cust"], ["w_item", "w_cust"])
        .join(cs78, ["s_item", "s_cust"], ["c_item", "c_cust"])
        .filter((col("ws_qty") > lit(0)) & (col("cs_qty") > lit(0)))
        .select(
            "s_item", "s_cust", "ss_qty", "ss_amt", "ws_qty", "cs_qty",
            ("ratio", (col("ss_qty") * lit(1.0)) / (col("ws_qty") + col("cs_qty"))),
        )
        .sort([("ratio", False), ("ss_qty", False), ("s_item", True)])
        .limit(100)
    )

    return {
        "q4": q4, "q5": q5, "q8": q8, "q10": q10, "q11": q11, "q26": q26,
        "q31": q31, "q35": q35, "q49": q49, "q58": q58, "q66": q66,
        "q75": q75, "q77": q77, "q78": q78, "q80": q80,
    }
