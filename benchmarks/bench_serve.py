"""Serving-plane benchmark: throughput + tail latency under concurrency.

Measures the QueryServer (docs/serving.md) at 1/4/16 concurrent clients
over a point-lookup workload of distinct plans, cold vs warm plan cache:

- **cold**: fresh PlanCache — every distinct query pays `optimized_plan`
  (rule matching, index-log reads, pushdown/prune) before execution;
- **warm**: same submission pattern again — every plan is a versioned-key
  hit and goes straight to the executor.

XLA compilation and the decoded-table/device caches are warmed before
measurement, so the cold-vs-warm delta isolates exactly the work the
plan cache amortizes. Writes BENCH_SERVE.json; `--smoke` runs a quick
4-client correctness pass (the CI `serving` job) and additionally boots
the runtime health plane (`hyperspace.obs.http.enabled`), scrapes
/metrics + /healthz over the real socket mid-load, and asserts the
serve gauges and a computed SLO burn rate are present — the CI
`observability` job's live-endpoint gate (docs/observability.md).

**Fleet mode** (`--fleet N [--clients M] [--smoke]`, docs/serving.md
"fleet topology"): N REAL worker processes over one index store, each
running its own session + QueryServer wired through the shared
disk-backed plan/result caches (serve/fleet/). Four regimes, written to
BENCH_FLEET.json with hard gates:

1. *throughput* — the same work through 1 process and through N;
   results must be digest-identical to serial execution, and on >=2-CPU
   hosts aggregate fleet qps must beat the single process;
2. *refresh churn* — workers serve a point query while this process
   appends rows and runs `refresh()` repeatedly: every returned result
   must match ONE legitimate version, and any query beginning after a
   refresh commit must reflect it (zero stale serves — the
   multi-process staleness proof at load);
3. *overload* — more clients than capacity against a small queue with
   shedding + tenant quotas: every refusal must be a typed
   AdmissionRejected/QuotaExceeded (zero untyped errors) and completed
   p99 must stay bounded — graceful saturation, never collapse;
4. *takeover* — a SIGKILLed single-flight lease holder must be
   recovered by lease takeover.
"""

from __future__ import annotations

import json
import shutil
import sys
import tempfile
import threading
import time
from pathlib import Path


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _gen_data(root: Path, rows: int, files: int) -> None:
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(7)
    per = rows // files
    root.mkdir(parents=True)
    for f in range(files):
        t = pa.table(
            {
                "id": pa.array(np.arange(f * per, (f + 1) * per, dtype=np.int64)),
                "key": pa.array(rng.integers(0, 1024, per, dtype=np.int64)),
                "value": pa.array(rng.standard_normal(per)),
                "amount": pa.array(rng.integers(0, 10_000, per, dtype=np.int64)),
            }
        )
        pq.write_table(t, root / f"part-{f}.parquet")


def _stats(lat_s: list[float], wall_s: float) -> dict:
    import numpy as np

    arr = np.sort(np.asarray(lat_s))
    return {
        "queries": len(arr),
        "throughput_qps": round(len(arr) / wall_s, 2),
        "p50_ms": round(float(np.percentile(arr, 50)) * 1e3, 3),
        "p95_ms": round(float(np.percentile(arr, 95)) * 1e3, 3),
        "p99_ms": round(float(np.percentile(arr, 99)) * 1e3, 3),
        "mean_ms": round(float(arr.mean()) * 1e3, 3),
    }


def _scrape(endpoint, expect_burn: bool) -> dict:
    """Scrape /metrics and /healthz over the real socket and assert the
    health plane is live: serve gauges in the Prometheus text, scheduler
    saturation in the healthz document, and — once traffic has flowed
    between two scrapes — a computed (non-sentinel) SLO burn rate."""
    import json as _json
    import urllib.request

    with urllib.request.urlopen(endpoint.url("/metrics"), timeout=10) as r:
        metrics_text = r.read().decode()
    for needle in (
        "hyperspace_serve_inflight",
        "hyperspace_serve_queue_depth",
        "hyperspace_serve_latency_seconds_bucket",
        "hyperspace_slo_serve_availability_burn_rate",
        "hyperspace_proc_map_count",
        "hyperspace_jit_live_executables",
    ):
        assert needle in metrics_text, f"{needle} missing from /metrics"
    with urllib.request.urlopen(endpoint.url("/healthz"), timeout=10) as r:
        doc = _json.loads(r.read().decode())
    assert doc["status"] in ("ok", "degraded"), doc["status"]
    assert doc["scheduler"] and doc["scheduler"][0]["workers"] == 4, doc["scheduler"]
    burn = [
        ln.rsplit(" ", 1)[1]
        for ln in metrics_text.splitlines()
        if ln.startswith("hyperspace_slo_serve_availability_burn_rate ")
    ][0]
    if expect_burn:
        assert float(burn) >= 0.0, f"burn rate not computed: {burn}"
    return {"status": doc["status"], "availability_burn": float(burn)}


def _run_phase(server, queries, n_clients: int, reps: int) -> dict:
    """Each client submits its share of `queries` x reps; per-query
    latency is submit→result as a client sees it."""
    lat: list[float] = []
    lock = threading.Lock()
    errors: list[BaseException] = []

    def client(cid: int):
        mine = [q for i, q in enumerate(queries) if i % n_clients == cid]
        out: list[float] = []
        try:
            for _ in range(reps):
                for q in mine:
                    t0 = time.perf_counter()
                    server.submit(q).result(timeout=600)
                    out.append(time.perf_counter() - t0)
        except BaseException as e:
            errors.append(e)
        with lock:
            lat.extend(out)

    t0 = time.perf_counter()
    threads = [threading.Thread(target=client, args=(i,)) for i in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    if errors:
        raise errors[0]
    return _stats(lat, wall)


# -- fleet mode (docs/serving.md "fleet topology") ----------------------------

def _digest(table) -> str:
    """Order-insensitive content digest of a ColumnTable: the
    byte-identical-results gate compares these across processes."""
    import hashlib

    import numpy as np

    d = table.decode()
    cols = sorted(d)
    rows = sorted(zip(*[np.asarray(d[c]).tolist() for c in cols])) if cols else []
    payload = json.dumps([cols, rows], default=str)
    return hashlib.md5(payload.encode()).hexdigest()


def _fleet_worker(ctx, data_root, system_path, n_keys, opts, work_q, res_q):
    """One fleet member: session + QueryServer over the shared store,
    wired through the shared disk caches; client threads pull work items
    `(query_id, tenant)` and report `(kind, worker, qid, begin_ts, lat,
    payload)` tuples. A `None` work item stops one client thread."""
    import queue as _queue
    import threading as _threading

    from hyperspace_tpu import HyperspaceSession
    from hyperspace_tpu import col as _col
    from hyperspace_tpu.exceptions import AdmissionRejected
    from hyperspace_tpu.serve import fleet as _fleet

    session = HyperspaceSession(system_path=system_path, num_buckets=16)
    session.conf.set("hyperspace.obs.http.enabled", "true")  # port=0: ephemeral
    session.enable_hyperspace()
    df = session.parquet(data_root)
    queries = [
        df.filter(_col("key") == int(k)).select("key", "value", "amount")
        for k in range(n_keys)
    ]
    plans, results = _fleet.shared_caches(session)
    quotas = None
    if opts.get("quota_rate"):
        from hyperspace_tpu.serve.fleet.quota import TenantQuotas

        quotas = TenantQuotas(rate=opts["quota_rate"], burst=opts.get("quota_burst", 4))
    server_kwargs = dict(
        workers=opts.get("workers", 2),
        max_queue_depth=opts.get("max_queue_depth", 256),
        plan_cache=plans,
        result_cache=results if opts.get("result_cache", True) else False,
        quotas=quotas,
        shed_depth_ratio=opts.get("shed_ratio", 1.0),
    )
    with session.serve(**server_kwargs) as server:
        _fleet.register_worker(ctx.fleet_dir, ctx.worker_id, server.health_endpoint.port)

        def client_loop():
            while True:
                try:
                    item = work_q.get(timeout=1.0)
                except _queue.Empty:
                    if ctx.stop_event.is_set():
                        return
                    continue
                if item is None:
                    return
                qid, tenant = item
                begin_ts = time.time()  # cross-process ordering vs refresh commits
                t0 = time.perf_counter()
                try:
                    out = server.submit(queries[qid], tenant=tenant).result(timeout=600)
                    res_q.put(("ok", ctx.worker_id, qid, begin_ts,
                               time.perf_counter() - t0, _digest(out)))
                except AdmissionRejected as e:
                    # The typed saturation surface (QuotaExceeded included).
                    res_q.put(("rejected", ctx.worker_id, qid, begin_ts,
                               time.perf_counter() - t0, type(e).__name__))
                except BaseException as e:
                    res_q.put(("error", ctx.worker_id, qid, begin_ts, 0.0,
                               f"{type(e).__name__}: {e}"))

        threads = [
            _threading.Thread(target=client_loop, daemon=True)
            for _ in range(opts.get("clients_per_worker", 2))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()


def _bench_lease_holder(sf_dir, name, ready_q):
    """Child for the takeover gate: claim the lease, report, hang until
    SIGKILLed (a crashed holder gets no cleanup)."""
    from pathlib import Path as _Path

    from hyperspace_tpu.serve.fleet.lease import FileLease
    from hyperspace_tpu.serve.fleet.singleflight import key_name

    lease = FileLease(_Path(sf_dir) / f"{key_name(name)}.lease", ttl_s=300)
    ready_q.put("held" if lease.try_acquire() is not None else "failed")
    time.sleep(300)


def _collect(res_q, expect: int, timeout_s: float = 600.0) -> list[tuple]:
    import queue as _queue

    out: list[tuple] = []
    deadline = time.monotonic() + timeout_s
    while len(out) < expect and time.monotonic() < deadline:
        try:
            out.append(res_q.get(timeout=1.0))
        except _queue.Empty:
            continue
    if len(out) < expect:
        raise RuntimeError(f"fleet phase collected {len(out)}/{expect} results")
    return out


def _run_fleet_phase(
    fleet_dir, data_root, system_path, n_keys, n_workers, opts, work_items,
    phase_timeout_s: float = 600.0,
):
    """Spawn `n_workers` fleet members, feed `work_items`, and collect
    every result. Returns (records, wall_s) with the warmup pass (one
    item per key per worker, XLA + shared-cache fill) excluded from the
    measured wall."""
    from hyperspace_tpu.serve import fleet as _fleet

    ctx_mp = __import__("multiprocessing").get_context("spawn")
    work_q, res_q = ctx_mp.Queue(), ctx_mp.Queue()
    clients = opts.get("clients_per_worker", 2)
    sup = _fleet.FleetSupervisor(
        _fleet_worker, fleet_dir=str(fleet_dir), n=n_workers,
        args=(str(data_root), str(system_path), n_keys, opts, work_q, res_q),
        max_restarts=0,
    )
    sup.start()
    try:
        warm = [(k, None) for k in range(n_keys)] * n_workers
        for item in warm:
            work_q.put(item)
        _collect(res_q, len(warm), timeout_s=phase_timeout_s)
        t0 = time.perf_counter()
        for item in work_items:
            work_q.put(item)
        records = _collect(res_q, len(work_items), timeout_s=phase_timeout_s)
        wall = time.perf_counter() - t0
        # Fleet-wide health right after the rated load drained: every
        # member's /healthz (scraped over its registered ephemeral port)
        # must not be paging — 503-on-page is the LB overload signal,
        # and rated traffic must not trip it.
        health = sup.fleet_health()
        for _ in range(n_workers * clients):
            work_q.put(None)
    finally:
        sup.stop(timeout=60)
    return records, wall, health


def fleet_main(n_fleet: int, n_clients: int, smoke: bool) -> int:
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    import os
    import signal

    import numpy as np

    from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig, col
    from hyperspace_tpu import stats as hs_stats
    from hyperspace_tpu.serve.fleet.singleflight import SingleFlight

    rows = 40_000 if smoke else 200_000
    n_keys = 8 if smoke else 32
    reps = 4 if smoke else 12
    cpus = os.cpu_count() or 1

    tmp = Path(tempfile.mkdtemp(prefix="hs_benchfleet_"))
    results_doc: dict = {
        "fleet": n_fleet, "clients": n_clients, "rows": rows,
        "distinct_queries": n_keys, "cpus": cpus, "gates": {},
    }
    try:
        data = tmp / "events"
        _gen_data(data, rows, 8)
        system_path = tmp / "indexes"
        session = HyperspaceSession(system_path=str(system_path), num_buckets=16)
        hs = Hyperspace(session)
        df = session.parquet(data)
        hs.create_index(df, IndexConfig("events_key", ["key"], ["value", "amount"]))
        session.enable_hyperspace()
        queries = [
            df.filter(col("key") == int(k)).select("key", "value", "amount")
            for k in range(n_keys)
        ]
        serial_digests = {k: _digest(session.run(queries[k])) for k in range(n_keys)}

        # -- regime 1: throughput, 1 process vs N --------------------------
        work = [(k, None) for k in range(n_keys)] * reps
        per_worker_clients = max(1, n_clients // max(1, n_fleet))
        base_opts = {"workers": 2, "clients_per_worker": per_worker_clients,
                     "max_queue_depth": 1024}
        rec1, wall1, _h1 = _run_fleet_phase(
            tmp / "fleet1", data, system_path, n_keys, 1,
            {**base_opts, "clients_per_worker": n_clients}, work)
        recN, wallN, healthN = _run_fleet_phase(
            tmp / "fleetN", data, system_path, n_keys, n_fleet, base_opts, work)
        ok1 = [r for r in rec1 if r[0] == "ok"]
        okN = [r for r in recN if r[0] == "ok"]
        identical = all(serial_digests[r[2]] == r[5] for r in ok1 + okN)
        qps_1 = round(len(ok1) / wall1, 2)
        qps_n = round(len(okN) / wallN, 2)
        results_doc["throughput"] = {
            "queries": len(work),
            "single_process_qps": qps_1,
            "fleet_qps": qps_n,
            "speedup": round(qps_n / qps_1, 3) if qps_1 else None,
            "errors": [r for r in rec1 + recN if r[0] == "error"][:5],
        }
        results_doc["gates"]["results_identical_to_serial"] = identical
        # The qps gate needs real parallel hardware: on a 1-CPU host N
        # processes time-slice one core (the same build-pipeline caveat
        # BENCH_PIPELINE records) — the gate is enforced on >=2 CPUs and
        # recorded as informational otherwise.
        qps_gate_enforced = cpus >= 2
        results_doc["throughput"]["qps_gate_enforced"] = qps_gate_enforced
        results_doc["gates"]["fleet_qps_beats_single"] = (
            qps_n > qps_1 if qps_gate_enforced else None
        )
        results_doc["throughput"]["fleet_health"] = {
            "status": healthN["status"],
            "alive": healthN["alive"],
            "saturation": healthN["saturation"],
        }
        results_doc["gates"]["slo_unpaged_at_rated_load"] = (
            healthN["status"] in ("ok", "degraded")
            and healthN["alive"] == n_fleet
        )
        log(f"throughput: 1-proc {qps_1} qps | fleet({n_fleet}) {qps_n} qps "
            f"| identical={identical} | health={healthN['status']} (cpus={cpus})")

        # -- regime 2: concurrent refresh churn ----------------------------
        import pyarrow as pa
        import pyarrow.parquet as pq

        churn_key = 7 % n_keys
        n_churn_q = 24 if smoke else 96
        n_refresh = 2 if smoke else 4
        ctx_mp = __import__("multiprocessing").get_context("spawn")
        work_q, res_q = ctx_mp.Queue(), ctx_mp.Queue()
        from hyperspace_tpu.serve import fleet as _fleet

        churn_workers = min(2, max(1, n_fleet))
        mid_batch = n_churn_q // 2
        feeder_stop = threading.Event()
        sup = _fleet.FleetSupervisor(
            _fleet_worker, fleet_dir=str(tmp / "fleet_churn"), n=churn_workers,
            args=(str(data), str(system_path), n_keys,
                  {"workers": 2, "clients_per_worker": 2}, work_q, res_q),
            max_restarts=0,
        )
        sup.start()
        try:
            work_q.put((churn_key, None))
            _collect(res_q, 1)  # workers up and serving
            legit = [serial_digests[churn_key]]
            commits: list[float] = []

            def feeder():
                # Queries racing the refreshes — interleaved with the
                # commits below.
                for _ in range(mid_batch):
                    if feeder_stop.is_set():
                        return
                    work_q.put((churn_key, None))
                    time.sleep(0.05)

            ft = threading.Thread(target=feeder, daemon=True)
            ft.start()
            next_id = 1_000_000
            for i in range(n_refresh):
                extra = pa.table({
                    "id": pa.array(np.arange(next_id, next_id + 16, dtype=np.int64)),
                    "key": pa.array(np.full(16, churn_key, dtype=np.int64)),
                    "value": pa.array(np.linspace(0.0, 1.0, 16)),
                    "amount": pa.array(np.arange(16, dtype=np.int64)),
                })
                pq.write_table(extra, data / f"churn-{i}.parquet")
                next_id += 16
                hs.refresh_index("events_key")
                commits.append(time.time())
                legit.append(_digest(session.run(queries[churn_key])))
                time.sleep(0.2)
            ft.join()
            # A guaranteed post-final-commit batch: every one of these
            # begins after the last refresh, so each MUST return the
            # final version — the stale-serve gate has teeth even when
            # the racing batch finished early.
            for _ in range(n_churn_q - mid_batch):
                work_q.put((churn_key, None))
            churn = _collect(res_q, n_churn_q)
            for _ in range(churn_workers * 2):
                work_q.put(None)
        finally:
            feeder_stop.set()
            sup.stop(timeout=60)
        ok_churn = [r for r in churn if r[0] == "ok"]
        version_of = {d: i for i, d in enumerate(legit)}
        wrong_version = [r for r in ok_churn if r[5] not in version_of]
        stale = []
        for r in ok_churn:
            begin = r[3]
            floor = sum(1 for c in commits if begin > c)  # versions committed first
            if r[5] in version_of and version_of[r[5]] < floor:
                stale.append((r[1], begin, version_of[r[5]], floor))
        results_doc["refresh_churn"] = {
            "queries": len(ok_churn), "refreshes": n_refresh,
            "errors": [r for r in churn if r[0] == "error"][:5],
            "wrong_version": len(wrong_version), "stale_serves": len(stale),
        }
        results_doc["gates"]["zero_wrong_version_results"] = not wrong_version
        results_doc["gates"]["zero_stale_serves"] = not stale
        log(f"refresh churn: {len(ok_churn)} queries over {n_refresh} refreshes, "
            f"wrong_version={len(wrong_version)}, stale={len(stale)}")

        # -- regime 3: overload (graceful saturation) ----------------------
        n_over = 160 if smoke else 600
        over_opts = {
            "workers": 2, "clients_per_worker": 8, "max_queue_depth": 8,
            "shed_ratio": 0.5, "result_cache": False,
            "quota_rate": 50.0, "quota_burst": 8,
        }
        tenants = [f"tenant-{i % 4}" for i in range(n_over)]
        over_work = [(i % n_keys, tenants[i]) for i in range(n_over)]
        rec_over, wall_over, _hover = _run_fleet_phase(
            tmp / "fleet_over", data, system_path, n_keys, 1, over_opts, over_work)
        ok_over = sorted(r[4] for r in rec_over if r[0] == "ok")
        rejected = [r for r in rec_over if r[0] == "rejected"]
        errors_over = [r for r in rec_over if r[0] == "error"]
        p99_over = ok_over[int(len(ok_over) * 0.99)] if ok_over else None
        warm_p95 = sorted(r[4] for r in okN if r[0] == "ok")
        warm_p95 = warm_p95[int(len(warm_p95) * 0.95)] if warm_p95 else 0.1
        p99_bound_s = max(10.0 * warm_p95, 5.0)
        results_doc["overload"] = {
            "offered": n_over, "completed": len(ok_over),
            "rejected": len(rejected),
            "rejection_types": sorted({r[5] for r in rejected}),
            "untyped_errors": errors_over[:5],
            "p99_s": round(p99_over, 4) if p99_over is not None else None,
            "p99_bound_s": round(p99_bound_s, 4),
            "wall_s": round(wall_over, 3),
        }
        results_doc["gates"]["overload_typed_rejections"] = len(rejected) > 0
        results_doc["gates"]["overload_zero_untyped_errors"] = not errors_over
        results_doc["gates"]["overload_p99_bounded"] = (
            p99_over is not None and p99_over <= p99_bound_s
        )
        log(f"overload: {len(ok_over)} ok, {len(rejected)} typed rejections "
            f"({results_doc['overload']['rejection_types']}), "
            f"{len(errors_over)} untyped, p99={p99_over and round(p99_over, 4)}s "
            f"(bound {round(p99_bound_s, 2)}s)")

        # -- regime 4: SIGKILLed single-flight holder ----------------------
        sf_dir = tmp / "fleet_sf"
        ready = ctx_mp.Queue()
        holder = ctx_mp.Process(
            target=_bench_lease_holder, args=(str(sf_dir), "hot", ready))
        holder.start()
        assert ready.get(timeout=120) == "held"
        os.kill(holder.pid, signal.SIGKILL)
        holder.join(timeout=30)
        time.sleep(0.7)
        sf = SingleFlight(sf_dir, lease_ttl_s=0.5, wait_s=10)
        t_before = hs_stats.get("fleet.singleflight.takeovers")
        recovered = sf.run("hot", build=lambda: "recovered", check=lambda: None)
        takeover_ok = (
            recovered == "recovered"
            and hs_stats.get("fleet.singleflight.takeovers") == t_before + 1
        )
        results_doc["takeover"] = {"recovered": takeover_ok}
        results_doc["gates"]["sigkill_holder_recovered_by_takeover"] = takeover_ok
        log(f"takeover: SIGKILLed holder recovered={takeover_ok}")

        out = Path(__file__).resolve().parent.parent / "BENCH_FLEET.json"
        out.write_text(json.dumps(results_doc, indent=2, default=str) + "\n")
        log(f"wrote {out}")
        failed = [k for k, v in results_doc["gates"].items() if v is False]
        if failed:
            log(f"FLEET GATES FAILED: {failed}")
            return 1
        log("fleet gates OK: " + ", ".join(
            f"{k}={v}" for k, v in results_doc["gates"].items()))
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def main(smoke: bool = False) -> int:
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    import numpy as np

    from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig, col
    from hyperspace_tpu.serve import PlanCache

    rows = 40_000 if smoke else 400_000
    n_keys = 16 if smoke else 96
    reps = 1 if smoke else 3
    client_counts = [4] if smoke else [1, 4, 16]

    tmp = Path(tempfile.mkdtemp(prefix="hs_benchserve_"))
    try:
        data = tmp / "events"
        _gen_data(data, rows, 8)
        session = HyperspaceSession(system_path=str(tmp / "indexes"), num_buckets=16)
        hs = Hyperspace(session)
        df = session.parquet(data)
        hs.create_index(df, IndexConfig("events_key", ["key"], ["value", "amount"]))
        session.enable_hyperspace()

        queries = [
            df.filter(col("key") == int(k)).select("key", "value", "amount")
            for k in range(n_keys)
        ]
        # Warm XLA + table/device caches so cold-vs-warm isolates the
        # planning cost (all point lookups share one jitted program).
        serial = [session.run(q) for q in queries[: min(4, n_keys)]]

        if smoke:
            session.conf.set("hyperspace.obs.http.enabled", "true")
            with session.serve(workers=4, max_queue_depth=256) as server:
                endpoint = server.health_endpoint
                _scrape(endpoint, expect_burn=False)  # first SLO sample
                for i, q in enumerate(queries[: len(serial)]):
                    out = server.submit(q).result(timeout=600).decode()
                    ref = serial[i].decode()
                    assert set(out) == set(ref)
                    for c in out:
                        assert np.array_equal(
                            np.asarray(out[c]), np.asarray(ref[c])
                        ), f"smoke mismatch in {c}"
                st = _run_phase(server, queries, n_clients=4, reps=2)
                scraped = _scrape(endpoint, expect_burn=True)
            log(f"smoke OK: 4 clients, {st['queries']} queries, "
                f"p95 {st['p95_ms']}ms, {st['throughput_qps']} qps; "
                f"health plane OK: {scraped}")
            return 0

        results: dict = {
            "rows": rows,
            "distinct_queries": n_keys,
            "workers": 4,
            "reps_per_phase": reps,
            "clients": {},
        }
        for nc in client_counts:
            cache = PlanCache(max_entries=256)
            with session.serve(workers=4, max_queue_depth=1024, plan_cache=cache) as server:
                cold = _run_phase(server, queries, n_clients=nc, reps=1)
                cold["plan_cache"] = dict(cache.stats())
                warm = _run_phase(server, queries, n_clients=nc, reps=reps)
                warm["plan_cache"] = dict(cache.stats())
            results["clients"][str(nc)] = {"cold": cold, "warm": warm}
            log(
                f"{nc:>2} client(s): cold p95 {cold['p95_ms']:8.3f}ms "
                f"{cold['throughput_qps']:8.2f} qps | warm p95 "
                f"{warm['p95_ms']:8.3f}ms {warm['throughput_qps']:8.2f} qps"
            )

        out = Path(__file__).resolve().parent.parent / "BENCH_SERVE.json"
        out.write_text(json.dumps(results, indent=2) + "\n")
        log(f"wrote {out}")
        for nc, r in results["clients"].items():
            if r["warm"]["p95_ms"] >= r["cold"]["p95_ms"]:
                log(f"WARNING: warm p95 not below cold at {nc} clients")
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _arg(name: str, default: int | None = None) -> int | None:
    for i, a in enumerate(sys.argv):
        if a == name and i + 1 < len(sys.argv):
            return int(sys.argv[i + 1])
        if a.startswith(name + "="):
            return int(a.split("=", 1)[1])
    return default


if __name__ == "__main__":
    _fleet_n = _arg("--fleet")
    if _fleet_n:
        sys.exit(fleet_main(
            n_fleet=_fleet_n,
            n_clients=_arg("--clients", max(2, 2 * _fleet_n)),
            smoke="--smoke" in sys.argv,
        ))
    sys.exit(main(smoke="--smoke" in sys.argv))
