"""Serving-plane benchmark: throughput + tail latency under concurrency.

Measures the QueryServer (docs/serving.md) at 1/4/16 concurrent clients
over a point-lookup workload of distinct plans, cold vs warm plan cache:

- **cold**: fresh PlanCache — every distinct query pays `optimized_plan`
  (rule matching, index-log reads, pushdown/prune) before execution;
- **warm**: same submission pattern again — every plan is a versioned-key
  hit and goes straight to the executor.

XLA compilation and the decoded-table/device caches are warmed before
measurement, so the cold-vs-warm delta isolates exactly the work the
plan cache amortizes. Writes BENCH_SERVE.json; `--smoke` runs a quick
4-client correctness pass (the CI `serving` job) and additionally boots
the runtime health plane (`hyperspace.obs.http.enabled`), scrapes
/metrics + /healthz over the real socket mid-load, and asserts the
serve gauges and a computed SLO burn rate are present — the CI
`observability` job's live-endpoint gate (docs/observability.md).
"""

from __future__ import annotations

import json
import shutil
import sys
import tempfile
import threading
import time
from pathlib import Path


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _gen_data(root: Path, rows: int, files: int) -> None:
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(7)
    per = rows // files
    root.mkdir(parents=True)
    for f in range(files):
        t = pa.table(
            {
                "id": pa.array(np.arange(f * per, (f + 1) * per, dtype=np.int64)),
                "key": pa.array(rng.integers(0, 1024, per, dtype=np.int64)),
                "value": pa.array(rng.standard_normal(per)),
                "amount": pa.array(rng.integers(0, 10_000, per, dtype=np.int64)),
            }
        )
        pq.write_table(t, root / f"part-{f}.parquet")


def _stats(lat_s: list[float], wall_s: float) -> dict:
    import numpy as np

    arr = np.sort(np.asarray(lat_s))
    return {
        "queries": len(arr),
        "throughput_qps": round(len(arr) / wall_s, 2),
        "p50_ms": round(float(np.percentile(arr, 50)) * 1e3, 3),
        "p95_ms": round(float(np.percentile(arr, 95)) * 1e3, 3),
        "p99_ms": round(float(np.percentile(arr, 99)) * 1e3, 3),
        "mean_ms": round(float(arr.mean()) * 1e3, 3),
    }


def _scrape(endpoint, expect_burn: bool) -> dict:
    """Scrape /metrics and /healthz over the real socket and assert the
    health plane is live: serve gauges in the Prometheus text, scheduler
    saturation in the healthz document, and — once traffic has flowed
    between two scrapes — a computed (non-sentinel) SLO burn rate."""
    import json as _json
    import urllib.request

    with urllib.request.urlopen(endpoint.url("/metrics"), timeout=10) as r:
        metrics_text = r.read().decode()
    for needle in (
        "hyperspace_serve_inflight",
        "hyperspace_serve_queue_depth",
        "hyperspace_serve_latency_seconds_bucket",
        "hyperspace_slo_serve_availability_burn_rate",
        "hyperspace_proc_map_count",
        "hyperspace_jit_live_executables",
    ):
        assert needle in metrics_text, f"{needle} missing from /metrics"
    with urllib.request.urlopen(endpoint.url("/healthz"), timeout=10) as r:
        doc = _json.loads(r.read().decode())
    assert doc["status"] in ("ok", "degraded"), doc["status"]
    assert doc["scheduler"] and doc["scheduler"][0]["workers"] == 4, doc["scheduler"]
    burn = [
        ln.rsplit(" ", 1)[1]
        for ln in metrics_text.splitlines()
        if ln.startswith("hyperspace_slo_serve_availability_burn_rate ")
    ][0]
    if expect_burn:
        assert float(burn) >= 0.0, f"burn rate not computed: {burn}"
    return {"status": doc["status"], "availability_burn": float(burn)}


def _run_phase(server, queries, n_clients: int, reps: int) -> dict:
    """Each client submits its share of `queries` x reps; per-query
    latency is submit→result as a client sees it."""
    lat: list[float] = []
    lock = threading.Lock()
    errors: list[BaseException] = []

    def client(cid: int):
        mine = [q for i, q in enumerate(queries) if i % n_clients == cid]
        out: list[float] = []
        try:
            for _ in range(reps):
                for q in mine:
                    t0 = time.perf_counter()
                    server.submit(q).result(timeout=600)
                    out.append(time.perf_counter() - t0)
        except BaseException as e:
            errors.append(e)
        with lock:
            lat.extend(out)

    t0 = time.perf_counter()
    threads = [threading.Thread(target=client, args=(i,)) for i in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    if errors:
        raise errors[0]
    return _stats(lat, wall)


def main(smoke: bool = False) -> int:
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    import numpy as np

    from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig, col
    from hyperspace_tpu.serve import PlanCache

    rows = 40_000 if smoke else 400_000
    n_keys = 16 if smoke else 96
    reps = 1 if smoke else 3
    client_counts = [4] if smoke else [1, 4, 16]

    tmp = Path(tempfile.mkdtemp(prefix="hs_benchserve_"))
    try:
        data = tmp / "events"
        _gen_data(data, rows, 8)
        session = HyperspaceSession(system_path=str(tmp / "indexes"), num_buckets=16)
        hs = Hyperspace(session)
        df = session.parquet(data)
        hs.create_index(df, IndexConfig("events_key", ["key"], ["value", "amount"]))
        session.enable_hyperspace()

        queries = [
            df.filter(col("key") == int(k)).select("key", "value", "amount")
            for k in range(n_keys)
        ]
        # Warm XLA + table/device caches so cold-vs-warm isolates the
        # planning cost (all point lookups share one jitted program).
        serial = [session.run(q) for q in queries[: min(4, n_keys)]]

        if smoke:
            session.conf.set("hyperspace.obs.http.enabled", "true")
            with session.serve(workers=4, max_queue_depth=256) as server:
                endpoint = server.health_endpoint
                _scrape(endpoint, expect_burn=False)  # first SLO sample
                for i, q in enumerate(queries[: len(serial)]):
                    out = server.submit(q).result(timeout=600).decode()
                    ref = serial[i].decode()
                    assert set(out) == set(ref)
                    for c in out:
                        assert np.array_equal(
                            np.asarray(out[c]), np.asarray(ref[c])
                        ), f"smoke mismatch in {c}"
                st = _run_phase(server, queries, n_clients=4, reps=2)
                scraped = _scrape(endpoint, expect_burn=True)
            log(f"smoke OK: 4 clients, {st['queries']} queries, "
                f"p95 {st['p95_ms']}ms, {st['throughput_qps']} qps; "
                f"health plane OK: {scraped}")
            return 0

        results: dict = {
            "rows": rows,
            "distinct_queries": n_keys,
            "workers": 4,
            "reps_per_phase": reps,
            "clients": {},
        }
        for nc in client_counts:
            cache = PlanCache(max_entries=256)
            with session.serve(workers=4, max_queue_depth=1024, plan_cache=cache) as server:
                cold = _run_phase(server, queries, n_clients=nc, reps=1)
                cold["plan_cache"] = dict(cache.stats())
                warm = _run_phase(server, queries, n_clients=nc, reps=reps)
                warm["plan_cache"] = dict(cache.stats())
            results["clients"][str(nc)] = {"cold": cold, "warm": warm}
            log(
                f"{nc:>2} client(s): cold p95 {cold['p95_ms']:8.3f}ms "
                f"{cold['throughput_qps']:8.2f} qps | warm p95 "
                f"{warm['p95_ms']:8.3f}ms {warm['throughput_qps']:8.2f} qps"
            )

        out = Path(__file__).resolve().parent.parent / "BENCH_SERVE.json"
        out.write_text(json.dumps(results, indent=2) + "\n")
        log(f"wrote {out}")
        for nc, r in results["clients"].items():
            if r["warm"]["p95_ms"] >= r["cold"]["p95_ms"]:
                log(f"WARNING: warm p95 not below cold at {nc} clients")
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main(smoke="--smoke" in sys.argv))
