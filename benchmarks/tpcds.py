"""TPC-DS star-schema slice: datagen + 30 published queries in the plan IR.

Tables follow the TPC-DS schema (store_sales fact + date_dim / item /
store / customer / customer_demographics / household_demographics /
time_dim / customer_address / promotion dimensions) with dsdgen-style
surrogate keys (date_dim julian numbering, cd demographics as a cycling
cartesian product, store_sales rows grouped into multi-item tickets) and
synthetic value distributions. SF1 store_sales = 2,879,987 rows.

The queries are the store-channel subset of the published 99 — q3, q6,
q7, q13, q19, q27 (real ROLLUP form), q33, q34, q36, q42, q43, q44,
q46, q48, q52, q53, q55, q59, q60, q63, q65, q67, q68, q70, q73, q79,
q89, q96, q98 plus the q88 time-band pivot — expressed in the plan IR with computed
projections, window functions, grouping sets, and (for the published
scalar subqueries) explicit two-step scalar evaluation. Each star join
is written with the most selective dimension innermost so the index
rewrite turns it into a bucket-aligned zero-exchange SMJ. The reference
claims serde coverage of all TPC-DS queries
(index/serde/package.scala:47-50); BASELINE config 3 is the SF1000
99-query geomean this slice builds toward.
"""

from __future__ import annotations

from pathlib import Path

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

SS_SF1_ROWS = 2_879_987
ITEM_SF1_ROWS = 18_000
CUSTOMER_SF1_ROWS = 100_000
CA_SF1_ROWS = 50_000
CD_ROWS = 1_920_800  # fixed cartesian size in TPC-DS
HD_ROWS = 7_200
DD_ROWS = 73_049  # 1900-01-02 .. 2100-01-01
DD_SK0 = 2_415_022  # julian day number of the first date_dim row
STORE_ROWS = 12
# Sold-date window every sales channel draws from (julian d_date_sk for
# 1998-01-01 .. 2002-12-31 — the years the published queries probe).
SOLD_DATE_LO = DD_SK0 + int((np.datetime64("1998-01-01") - np.datetime64("1900-01-02")) // np.timedelta64(1, "D"))
SOLD_DATE_HI = DD_SK0 + int((np.datetime64("2002-12-31") - np.datetime64("1900-01-02")) // np.timedelta64(1, "D"))

_CATEGORIES = np.array(
    ["Books", "Children", "Electronics", "Home", "Jewelry",
     "Men", "Music", "Shoes", "Sports", "Women"], dtype=object
)
_GENDER = np.array(["M", "F"], dtype=object)
_MARITAL = np.array(["M", "S", "D", "W", "U"], dtype=object)
_EDUCATION = np.array(
    ["Primary", "Secondary", "College", "2 yr Degree", "4 yr Degree",
     "Advanced Degree", "Unknown"], dtype=object
)
_BUY_POTENTIAL = np.array(
    [">10000", "5001-10000", "1001-5000", "501-1000", "0-500", "Unknown"], dtype=object
)
_STATES = np.array(
    ["TX", "OH", "OR", "CA", "WA", "NM", "KY", "VA", "FL", "GA", "MI", "IL"], dtype=object
)
_STORE_NAMES = np.array(
    ["ought", "able", "pri", "ese", "anti", "cally", "ation", "eing",
     "ought", "able", "ese", "bar"], dtype=object
)
_CITIES = np.array(
    ["Midway", "Fairview", "Oak Grove", "Five Points", "Pleasant Hill",
     "Centerville", "Liberty", "Salem", "Union", "Riverside"], dtype=object
)


def _parts(t: pa.Table, root: Path, files: int) -> int:
    from benchmarks.datagen import _write_parts

    _write_parts(t, root, files)
    return t.nbytes


def gen_date_dim(root: Path) -> int:
    """Deterministic calendar: one row per day 1900-01-02..2100-01-01,
    julian d_date_sk numbering as dsdgen emits. d_month_seq/d_week_seq
    are the running month/week ordinals the published queries window on
    (q6's month pick, q59's week join, q98's 30-day month_seq spans)."""
    days = np.arange(DD_ROWS, dtype=np.int64)
    d64 = np.datetime64("1900-01-02") + days
    years = d64.astype("datetime64[Y]").astype(np.int64) + 1970
    months0 = d64.astype("datetime64[M]").astype(np.int64)
    moy = months0 % 12 + 1
    dom = (d64 - d64.astype("datetime64[M]")).astype(np.int64) + 1
    dow = (d64.astype("datetime64[D]").astype(np.int64) + 4) % 7  # 0=Sunday
    names = np.array(
        ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday", "Friday", "Saturday"],
        dtype=object,
    )
    month_seq = months0 - int(
        np.datetime64("1900-01", "M").astype(np.int64)
    )  # 0 at Jan 1900, as dsdgen counts
    week_seq = (days + 1) // 7  # week ordinal from the calendar origin
    t = pa.table(
        {
            "d_date_sk": DD_SK0 + days,
            "d_date": pa.array(
                (d64 - np.datetime64("1970-01-01")).astype(np.int32), type=pa.date32()
            ),
            "d_year": years.astype(np.int32),
            "d_moy": moy.astype(np.int32),
            "d_dom": dom.astype(np.int32),
            "d_qoy": ((moy - 1) // 3 + 1).astype(np.int32),
            "d_day_name": pa.array(names[dow]),
            "d_month_seq": month_seq.astype(np.int32),
            "d_week_seq": week_seq.astype(np.int32),
            "d_dow": dow.astype(np.int32),
        }
    )
    return _parts(t, root, 1)


def item_rows(sf: float) -> int:
    """item scales sublinearly in TPC-DS; pinned to the SF1 size above
    SF1 (good enough for this slice) and proportionally below."""
    return max(int(ITEM_SF1_ROWS * min(sf, 1.0)), 100)


def gen_item(root: Path, sf: float = 1.0, seed: int = 61) -> int:
    n = item_rows(sf)
    rng = np.random.default_rng(seed)
    manufact = rng.integers(1, 1001, n).astype(np.int32)
    brand_id = (manufact * 1000 + rng.integers(1, 1000, n)).astype(np.int32)
    cat_id = rng.integers(1, 11, n).astype(np.int32)
    t = pa.table(
        {
            "i_item_sk": np.arange(1, n + 1, dtype=np.int64),
            "i_item_id": pa.array(
                np.char.add("AAAAAAAA", np.arange(n).astype("U8")).astype(object)
            ),
            "i_brand_id": brand_id,
            "i_brand": pa.array(
                np.char.add("brandbrand#", brand_id.astype("U8")).astype(object)
            ),
            "i_manufact_id": manufact,
            "i_manager_id": rng.integers(1, 101, n).astype(np.int32),
            "i_category_id": cat_id,
            "i_category": pa.array(_CATEGORIES[cat_id - 1]),
            "i_class": pa.array(
                np.char.add("class", rng.integers(1, 17, n).astype("U2")).astype(object)
            ),
            "i_current_price": np.round(rng.random(n) * 99 + 1, 2),
            "i_item_desc": pa.array(
                np.char.add("desc", (np.arange(n) % 997).astype("U4")).astype(object)
            ),
            "i_color": pa.array(
                np.array(["maroon", "burnished", "dim", "sky", "navajo", "chiffon",
                          "slate", "blanched", "tan", "forest", "lace", "misty",
                          "cream", "dark", "powder", "frosted", "almond", "smoke"],
                         dtype=object)[rng.integers(0, 18, n)]
            ),
            "i_units": pa.array(
                np.array(["Each", "Dozen", "Case", "Pallet", "Gross", "Ton",
                          "Ounce", "Bunch"], dtype=object)[rng.integers(0, 8, n)]
            ),
        }
    )
    return _parts(t, root, 1)


def gen_store(root: Path) -> int:
    n = STORE_ROWS
    t = pa.table(
        {
            "s_store_sk": np.arange(1, n + 1, dtype=np.int64),
            "s_store_id": pa.array(
                np.char.add("AAAAAAAA", np.arange(n).astype("U2")).astype(object)
            ),
            "s_store_name": pa.array(_STORE_NAMES[:n]),
            "s_state": pa.array(_STATES[:n]),
            "s_zip": pa.array(
                np.char.add("55", (np.arange(n) * 137 % 1000).astype("U3")).astype(object)
            ),
            "s_gmt_offset": np.full(n, -5.0),
            "s_county": pa.array(
                np.array(["Ziebach County", "Williamson County", "Walker County",
                          "Daviess County"], dtype=object)[np.arange(n) % 4]
            ),
            "s_city": pa.array(_CITIES[np.arange(n) % len(_CITIES)]),
            "s_company_name": pa.array(np.full(n, "Unknown", dtype=object)),
            "s_number_of_employees": (200 + np.arange(n) * 13 % 100).astype(np.int32),
        }
    )
    return _parts(t, root, 1)


def cd_rows(sf: float) -> int:
    """customer_demographics is fixed-size in TPC-DS; scaled down below
    SF1 (keeping full field-cycle coverage) so tiny test runs stay fast."""
    return CD_ROWS if sf >= 1 else max(int(CD_ROWS * sf), 11_200)


def gen_customer_demographics(root: Path, sf: float = 1.0) -> int:
    """The dsdgen cartesian: demographics fields CYCLE with fixed periods
    so any (gender, marital, education) combo is a fixed 1/70 of keys."""
    n = cd_rows(sf)
    i = np.arange(n, dtype=np.int64)
    t = pa.table(
        {
            "cd_demo_sk": i + 1,
            "cd_gender": pa.array(_GENDER[i % 2]),
            "cd_marital_status": pa.array(_MARITAL[(i // 2) % 5]),
            "cd_education_status": pa.array(_EDUCATION[(i // 10) % 7]),
            "cd_purchase_estimate": ((i // 70) % 20 * 500 + 500).astype(np.int32),
            "cd_credit_rating": pa.array(
                np.array(["Good", "High Risk", "Low Risk", "Unknown"], dtype=object)[
                    (i // 1400) % 4
                ]
            ),
            "cd_dep_count": ((i // 5600) % 7).astype(np.int32),
        }
    )
    return _parts(t, root, 2)


def gen_household_demographics(root: Path) -> int:
    n = HD_ROWS
    i = np.arange(n, dtype=np.int64)
    t = pa.table(
        {
            "hd_demo_sk": i + 1,
            "hd_income_band_sk": (i % 20 + 1).astype(np.int64),
            "hd_buy_potential": pa.array(_BUY_POTENTIAL[i % 6]),
            "hd_dep_count": ((i // 6) % 10).astype(np.int32),
            "hd_vehicle_count": ((i // 60) % 5).astype(np.int32),
        }
    )
    return _parts(t, root, 1)


def gen_time_dim(root: Path) -> int:
    i = np.arange(86_400, dtype=np.int64)
    hour = (i // 3600).astype(np.int32)
    meal = np.full(86_400, "", dtype=object)
    meal[(hour >= 6) & (hour < 9)] = "breakfast"
    meal[(hour >= 11) & (hour < 13)] = "lunch"
    meal[(hour >= 17) & (hour < 20)] = "dinner"
    t = pa.table(
        {
            "t_time_sk": i,
            "t_hour": hour,
            "t_minute": (i % 3600 // 60).astype(np.int32),
            "t_second": (i % 60).astype(np.int32),
            "t_am_pm": pa.array(np.where(hour < 12, "AM", "PM").astype(object)),
            # dsdgen leaves t_meal_time NULL outside meal windows.
            "t_meal_time": pa.array(meal, mask=meal == ""),
        }
    )
    return _parts(t, root, 1)


def ca_rows(sf: float) -> int:
    return max(int(CA_SF1_ROWS * max(sf, 0.02)), 100)


def gen_customer_address(root: Path, sf: float = 1.0, seed: int = 62) -> int:
    n = ca_rows(sf)
    rng = np.random.default_rng(seed)
    t = pa.table(
        {
            "ca_address_sk": np.arange(1, n + 1, dtype=np.int64),
            "ca_state": pa.array(_STATES[rng.integers(0, len(_STATES), n)]),
            "ca_zip": pa.array(rng.integers(10000, 99999, n).astype("U5").astype(object)),
            "ca_country": pa.array(np.full(n, "United States", dtype=object)),
            "ca_city": pa.array(_CITIES[rng.integers(0, len(_CITIES), n)]),
            "ca_county": pa.array(
                np.array(["Ziebach County", "Williamson County", "Walker County",
                          "Daviess County", "Luce County", "Fairfield County",
                          "Dona Ana County", "Barrow County"], dtype=object)[
                    rng.integers(0, 8, n)
                ]
            ),
            "ca_gmt_offset": np.where(rng.random(n) < 0.5, -5.0, -6.0),
        }
    )
    return _parts(t, root, 1)


def customer_rows(sf: float) -> int:
    return int(CUSTOMER_SF1_ROWS * max(sf, 0.02))


def gen_customer(root: Path, sf: float = 1.0, seed: int = 63) -> int:
    n = customer_rows(sf)
    rng = np.random.default_rng(seed)
    first = np.array(
        ["James", "Mary", "John", "Linda", "Robert", "Susan", "David", "Karen"],
        dtype=object,
    )
    last = np.array(
        ["Smith", "Jones", "Brown", "Davis", "Miller", "Wilson", "Moore", "Clark"],
        dtype=object,
    )
    countries = np.array(
        ["United States", "Canada", "Mexico", "Japan", "Germany",
         "Brazil", "India", "France"], dtype=object
    )
    t = pa.table(
        {
            "c_customer_sk": np.arange(1, n + 1, dtype=np.int64),
            "c_customer_id": pa.array(
                np.char.add("AAAAAAAA", np.arange(n).astype("U8")).astype(object)
            ),
            "c_current_addr_sk": rng.integers(1, ca_rows(sf) + 1, n).astype(np.int64),
            "c_current_cdemo_sk": rng.integers(1, cd_rows(sf) + 1, n).astype(np.int64),
            "c_current_hdemo_sk": rng.integers(1, HD_ROWS + 1, n).astype(np.int64),
            "c_first_name": pa.array(first[rng.integers(0, len(first), n)]),
            "c_last_name": pa.array(last[rng.integers(0, len(last), n)]),
            "c_salutation": pa.array(
                np.array(["Mr.", "Mrs.", "Ms.", "Dr."], dtype=object)[
                    rng.integers(0, 4, n)
                ]
            ),
            "c_preferred_cust_flag": pa.array(
                np.array(["N", "Y"], dtype=object)[(rng.random(n) < 0.5).astype(int)]
            ),
            "c_birth_year": rng.integers(1924, 1993, n).astype(np.int32),
            "c_birth_month": rng.integers(1, 13, n).astype(np.int32),
            "c_birth_day": rng.integers(1, 29, n).astype(np.int32),
            "c_birth_country": pa.array(countries[rng.integers(0, len(countries), n)]),
            "c_email_address": pa.array(
                np.char.add(np.arange(n).astype("U8"), "@example.com").astype(object)
            ),
        }
    )
    return _parts(t, root, 1)


def gen_promotion(root: Path, seed: int = 64) -> int:
    """promotion: 300 rows at SF1; channel flags mostly N with a Y
    sprinkle (q7/q26 filter p_channel_email = 'N' OR p_channel_event =
    'N')."""
    n = 300
    rng = np.random.default_rng(seed)
    yn = np.array(["N", "Y"], dtype=object)
    t = pa.table(
        {
            "p_promo_sk": np.arange(1, n + 1, dtype=np.int64),
            "p_channel_email": pa.array(yn[(rng.random(n) < 0.1).astype(int)]),
            "p_channel_event": pa.array(yn[(rng.random(n) < 0.1).astype(int)]),
            "p_channel_dmail": pa.array(yn[(rng.random(n) < 0.5).astype(int)]),
            "p_channel_tv": pa.array(yn[(rng.random(n) < 0.1).astype(int)]),
        }
    )
    return _parts(t, root, 1)


# Sales tables are memoized per (channel, sf) for the duration of one
# cached_tpcds() pass so the RETURNS channels can derive from the exact
# sold rows (dsdgen links returns to sales items the same way); cleared
# after datagen so SF10+ tables don't pin memory.
_SALES_TABLES: dict = {}

WAREHOUSE_ROWS = 5
CC_ROWS = 6
WEB_SITE_ROWS = 30
WEB_PAGE_ROWS = 60
CATALOG_PAGE_ROWS = 11_718
REASON_ROWS = 35
SHIP_MODE_ROWS = 20


def _null_frac(arr: np.ndarray, frac: float, rng) -> pa.Array:
    """Arrow column with a `frac` fraction of NULLs (dsdgen emits null
    FKs; q76 counts the rows whose channel FK IS NULL)."""
    return pa.array(arr, mask=rng.random(len(arr)) < frac)


def _money(rng, n, scale=200.0):
    return np.round(rng.random(n) * scale, 2)


def _ss_table(sf: float, seed: int = 60) -> pa.Table:
    """The store fact table. Sold dates concentrate in 1998-2002 (the
    years the published queries probe), store hours 08:00-21:00. Rows
    group into multi-item TICKETS (dsdgen's structure): all rows of one
    ss_ticket_number share customer / date / time / store / demographics
    / address — the grain q34/q46/q68/q73/q79 aggregate on. ss_addr_sk
    carries ~1% NULLs (q76's store-channel probe)."""
    key = ("ss", sf, seed)
    if key in _SALES_TABLES:
        return _SALES_TABLES[key]
    n = int(SS_SF1_ROWS * sf)
    rng = np.random.default_rng(seed)
    lo, hi = SOLD_DATE_LO, SOLD_DATE_HI
    n_items = item_rows(sf)
    n_ca = ca_rows(sf)
    # Ticket runs: ~9 items per ticket in expectation.
    start = rng.random(n) < (1.0 / 9.0)
    if n:
        start[0] = True
    tid = np.cumsum(start, dtype=np.int64) - 1  # 0-based ticket ordinal
    n_t = int(tid[-1]) + 1 if n else 0

    def per_ticket(vals: np.ndarray) -> np.ndarray:
        return vals[tid]

    quantity = rng.integers(1, 101, n).astype(np.int32)
    list_price = np.round(rng.random(n) * 190 + 10, 2)
    sales_price = np.round(list_price * (0.2 + rng.random(n) * 0.8), 2)
    wholesale = np.round(list_price * (0.3 + rng.random(n) * 0.4), 2)
    t = pa.table(
        {
            "ss_sold_date_sk": per_ticket(rng.integers(lo, hi + 1, n_t)).astype(np.int64),
            "ss_sold_time_sk": per_ticket(rng.integers(8 * 3600, 21 * 3600, n_t)).astype(np.int64),
            "ss_item_sk": rng.integers(1, n_items + 1, n).astype(np.int64),
            "ss_customer_sk": per_ticket(
                rng.integers(1, customer_rows(sf) + 1, n_t)
            ).astype(np.int64),
            "ss_cdemo_sk": per_ticket(rng.integers(1, cd_rows(sf) + 1, n_t)).astype(np.int64),
            "ss_hdemo_sk": per_ticket(rng.integers(1, HD_ROWS + 1, n_t)).astype(np.int64),
            "ss_addr_sk": _null_frac(
                per_ticket(rng.integers(1, n_ca + 1, n_t)).astype(np.int64), 0.01, rng
            ),
            "ss_store_sk": per_ticket(rng.integers(1, STORE_ROWS + 1, n_t)).astype(np.int64),
            "ss_promo_sk": rng.integers(1, 301, n).astype(np.int64),
            "ss_ticket_number": (tid + 1).astype(np.int64),
            "ss_quantity": quantity,
            "ss_list_price": list_price,
            "ss_sales_price": sales_price,
            "ss_ext_wholesale_cost": np.round(quantity * wholesale, 2),
            "ss_coupon_amt": np.round(np.where(rng.random(n) < 0.2, rng.random(n) * 50, 0.0), 2),
            "ss_ext_sales_price": np.round(quantity * sales_price, 2),
            "ss_net_profit": np.round(quantity * (sales_price - list_price * 0.5), 2),
        }
    )
    _SALES_TABLES[key] = t
    return t


def gen_store_sales(root: Path, sf: float = 1.0, seed: int = 60, files: int = 8) -> int:
    return _parts(_ss_table(sf, seed), root, files)


CS_SF1_ROWS = 1_441_548
WS_SF1_ROWS = 719_384


def _channel_table(prefix: str, sf: float, seed: int) -> pa.Table:
    """catalog_sales / web_sales at full query width: sold/ship dates and
    times, bill demographics/address, order numbers (~4-item orders),
    warehouse / page / site / call-center / ship-mode / promo links, and
    the quantity+price measure block. cs_ship_addr_sk and
    ws_ship_customer_sk carry ~2% NULLs (q76's channel probes)."""
    key = (prefix, sf, seed)
    if key in _SALES_TABLES:
        return _SALES_TABLES[key]
    n = int((CS_SF1_ROWS if prefix == "cs" else WS_SF1_ROWS) * sf)
    rng = np.random.default_rng(seed)
    n_items, n_cust, n_ca = item_rows(sf), customer_rows(sf), ca_rows(sf)
    start = rng.random(n) < (1.0 / 4.0)
    if n:
        start[0] = True
    oid = np.cumsum(start, dtype=np.int64) - 1
    n_o = int(oid[-1]) + 1 if n else 0

    def per_order(vals: np.ndarray) -> np.ndarray:
        return vals[oid]

    sold = per_order(rng.integers(SOLD_DATE_LO, SOLD_DATE_HI + 1, n_o)).astype(np.int64)
    # Cross-channel correlation (dsdgen ties catalog/web activity to the
    # store channel): ~15% of ORDERS belong to a store customer and open
    # with an item that customer actually bought in store_sales — the
    # buy-return-rebuy triangles (q17/q25/q29) and cross-channel
    # customer overlaps depend on this overlap. Order granularity keeps
    # the one-customer-per-order invariant intact.
    item_sk = rng.integers(1, n_items + 1, n).astype(np.int64)
    cust_o = rng.integers(1, n_cust + 1, n_o).astype(np.int64)
    ss_t = _ss_table(sf)
    if ss_t.num_rows and n_o:
        pick_o = rng.random(n_o) < 0.15
        src_o = rng.integers(0, ss_t.num_rows, n_o)
        ss_cust = ss_t.column("ss_customer_sk").to_numpy(zero_copy_only=False)
        ss_item = ss_t.column("ss_item_sk").to_numpy(zero_copy_only=False)
        cust_o[pick_o] = ss_cust[src_o[pick_o]]
        first_of_picked = start & pick_o[oid]
        item_sk[first_of_picked] = ss_item[src_o[oid[first_of_picked]]]
    bill_customer = cust_o[oid]
    quantity = rng.integers(1, 101, n).astype(np.int32)
    list_price = np.round(rng.random(n) * 190 + 10, 2)
    sales_price = np.round(list_price * (0.2 + rng.random(n) * 0.8), 2)
    ext_sales = np.round(quantity * sales_price, 2)
    cols = {
        "sold_date_sk": sold,
        "sold_time_sk": per_order(rng.integers(0, 86_400, n_o)).astype(np.int64),
        "ship_date_sk": sold + rng.integers(1, 31, n),
        "item_sk": item_sk,
        "bill_customer_sk": bill_customer,
        "bill_cdemo_sk": per_order(rng.integers(1, cd_rows(sf) + 1, n_o)).astype(np.int64),
        "bill_hdemo_sk": per_order(rng.integers(1, HD_ROWS + 1, n_o)).astype(np.int64),
        "bill_addr_sk": per_order(rng.integers(1, n_ca + 1, n_o)).astype(np.int64),
        "ship_addr_sk": per_order(rng.integers(1, n_ca + 1, n_o)).astype(np.int64),
        "warehouse_sk": rng.integers(1, WAREHOUSE_ROWS + 1, n).astype(np.int64),
        "ship_mode_sk": per_order(rng.integers(1, SHIP_MODE_ROWS + 1, n_o)).astype(np.int64),
        "promo_sk": rng.integers(1, 301, n).astype(np.int64),
        "order_number": (oid + 1).astype(np.int64),
        "quantity": quantity,
        "list_price": list_price,
        "sales_price": sales_price,
        "coupon_amt": np.round(
            np.where(rng.random(n) < 0.2, rng.random(n) * 50, 0.0), 2
        ),
        "ext_discount_amt": np.round(
            np.where(rng.random(n) < 0.3, rng.random(n) * quantity * 20, 0.0), 2
        ),
        "ext_sales_price": ext_sales,
        "ext_ship_cost": np.round(quantity * rng.random(n) * 10, 2),
        "ext_list_price": np.round(quantity * list_price, 2),
        "net_paid": ext_sales,
        "net_profit": np.round(quantity * (sales_price - list_price * 0.5), 2),
    }
    if prefix == "cs":
        cols["call_center_sk"] = per_order(rng.integers(1, CC_ROWS + 1, n_o)).astype(np.int64)
        cols["catalog_page_sk"] = rng.integers(1, CATALOG_PAGE_ROWS + 1, n).astype(np.int64)
        cols["ship_customer_sk"] = per_order(rng.integers(1, n_cust + 1, n_o)).astype(np.int64)
    else:
        cols["web_site_sk"] = per_order(rng.integers(1, WEB_SITE_ROWS + 1, n_o)).astype(np.int64)
        cols["web_page_sk"] = rng.integers(1, WEB_PAGE_ROWS + 1, n).astype(np.int64)
        cols["ship_hdemo_sk"] = per_order(rng.integers(1, HD_ROWS + 1, n_o)).astype(np.int64)
    named = {}
    for name, v in cols.items():
        named[f"{prefix}_{name}"] = v
    t_dict = dict(named)
    # q76's NULL-FK probes: cs_ship_addr_sk / ws_ship_customer_sk.
    if prefix == "cs":
        t_dict["cs_ship_addr_sk"] = _null_frac(np.asarray(cols["ship_addr_sk"]), 0.02, rng)
    else:
        t_dict[f"{prefix}_ship_customer_sk"] = _null_frac(
            per_order(rng.integers(1, n_cust + 1, n_o)).astype(np.int64), 0.02, rng
        )
    t = pa.table(t_dict)
    _SALES_TABLES[key] = t
    return t


def gen_catalog_sales(root: Path, sf: float = 1.0, seed: int = 65) -> int:
    return _parts(_channel_table("cs", sf, seed), root, 4)


def gen_web_sales(root: Path, sf: float = 1.0, seed: int = 66) -> int:
    return _parts(_channel_table("ws", sf, seed), root, 4)


def _derive_returns(sales: pa.Table, prefix: str, out_prefix: str, frac: float,
                    sf: float, seed: int, link_cols: dict, rng_extra=None) -> pa.Table:
    """Returns derive from a sample of the channel's sold rows (dsdgen's
    linkage): item + order/ticket keys copy from the sampled sale so
    sales⋈returns joins land, dates land 1..90 days after the sale, and
    the measure block scales off the sold quantity."""
    rng = np.random.default_rng(seed)
    n_s = sales.num_rows
    n = int(n_s * frac)
    idx = np.sort(rng.choice(n_s, size=n, replace=False))

    def take(name):
        return sales.column(name).take(pa.array(idx)).to_numpy(zero_copy_only=False)

    sold = take(f"{prefix}_sold_date_sk").astype(np.int64)
    qty = take(f"{prefix}_quantity").astype(np.int64) if f"{prefix}_quantity" in sales.column_names else rng.integers(1, 101, n)
    price = take(f"{prefix}_sales_price")
    rqty = np.minimum(rng.integers(1, 101, n), qty).astype(np.int32)
    ramt = np.round(rqty * price, 2)
    cols = {
        f"{out_prefix}_returned_date_sk": sold + rng.integers(1, 91, n),
        f"{out_prefix}_item_sk": take(f"{prefix}_item_sk").astype(np.int64),
        f"{out_prefix}_reason_sk": rng.integers(1, REASON_ROWS + 1, n).astype(np.int64),
        f"{out_prefix}_return_quantity": rqty,
        f"{out_prefix}_return_amt": ramt,
        f"{out_prefix}_fee": _money(rng, n, 100.0),
        f"{out_prefix}_net_loss": np.round(ramt * (0.3 + rng.random(n) * 0.5) + 50, 2),
    }
    for out_name, src_name in link_cols.items():
        cols[f"{out_prefix}_{out_name}"] = take(f"{prefix}_{src_name}").astype(np.int64)
    if rng_extra is not None:
        cols.update(rng_extra(rng, n, take))
    return pa.table(cols)


def gen_store_returns(root: Path, sf: float = 1.0, seed: int = 70) -> int:
    """~10% of store_sales rows return; linked by (ticket, item) —
    the q17/q25/q29/q50/q93 join grain."""
    t = _derive_returns(
        _ss_table(sf), "ss", "sr", 0.10, sf, seed,
        {
            "customer_sk": "customer_sk",
            "store_sk": "store_sk",
            "ticket_number": "ticket_number",
            "cdemo_sk": "cdemo_sk",
            "hdemo_sk": "hdemo_sk",
        },
        rng_extra=lambda rng, n, take: {
            "sr_addr_sk": rng.integers(1, ca_rows(sf) + 1, n).astype(np.int64),
        },
    )
    return _parts(t, root, 2)


def gen_catalog_returns(root: Path, sf: float = 1.0, seed: int = 71) -> int:
    t = _derive_returns(
        _channel_table("cs", sf, 65), "cs", "cr", 0.10, sf, seed,
        {
            "returning_customer_sk": "bill_customer_sk",
            "refunded_customer_sk": "bill_customer_sk",
            "returning_addr_sk": "bill_addr_sk",
            "refunded_cdemo_sk": "bill_cdemo_sk",
            "call_center_sk": "call_center_sk",
            "catalog_page_sk": "catalog_page_sk",
            "order_number": "order_number",
        },
    )
    return _parts(t, root, 2)


def gen_web_returns(root: Path, sf: float = 1.0, seed: int = 72) -> int:
    t = _derive_returns(
        _channel_table("ws", sf, 66), "ws", "wr", 0.08, sf, seed,
        {
            "returning_customer_sk": "bill_customer_sk",
            "refunded_customer_sk": "bill_customer_sk",
            "returning_addr_sk": "bill_addr_sk",
            "refunded_addr_sk": "bill_addr_sk",
            "refunded_cdemo_sk": "bill_cdemo_sk",
            "refunded_hdemo_sk": "bill_hdemo_sk",
            "web_page_sk": "web_page_sk",
            "order_number": "order_number",
        },
        rng_extra=lambda rng, n, take: {
            # The returner's demographics usually (80%) match the
            # buyer's (q85 equates cd1/cd2 attributes over these keys).
            "wr_returning_cdemo_sk": np.where(
                rng.random(n) < 0.8,
                take("ws_bill_cdemo_sk").astype(np.int64),
                rng.integers(1, cd_rows(sf) + 1, n),
            ).astype(np.int64),
        },
    )
    return _parts(t, root, 2)


def gen_inventory(root: Path, sf: float = 1.0, seed: int = 73) -> int:
    """Weekly on-hand quantity per (item, warehouse): Mondays across the
    1998-2002 probe window x a quarter of items x 3 warehouses — the
    dsdgen grain thinned to keep the SF1 table near store_sales size
    (the full cross product would be ~8x; queries probe narrow date
    bands either way)."""
    rng = np.random.default_rng(seed)
    days = np.arange(SOLD_DATE_LO, SOLD_DATE_HI + 1, dtype=np.int64)
    dows = (days - DD_SK0 + 4) % 7  # same numbering as gen_date_dim
    mondays = days[dows == 1]
    items = np.arange(1, item_rows(sf) + 1, 4, dtype=np.int64)
    whs = np.arange(1, 4, dtype=np.int64)
    d, i, w = np.meshgrid(mondays, items, whs, indexing="ij")
    n = d.size
    t = pa.table(
        {
            "inv_date_sk": d.ravel(),
            "inv_item_sk": i.ravel(),
            "inv_warehouse_sk": w.ravel(),
            "inv_quantity_on_hand": rng.integers(0, 1001, n).astype(np.int32),
        }
    )
    return _parts(t, root, 4)


def gen_warehouse(root: Path) -> int:
    n = WAREHOUSE_ROWS
    i = np.arange(n)
    t = pa.table(
        {
            "w_warehouse_sk": (i + 1).astype(np.int64),
            "w_warehouse_name": pa.array(
                np.array(["Conventional childr", "Important issues liv", "Doors canno",
                          "Bad cards must make.", "Rooms cook "], dtype=object)[:n]
            ),
            "w_warehouse_sq_ft": ((i + 1) * 97_312 % 900_000 + 50_000).astype(np.int32),
            "w_city": pa.array(_CITIES[i % len(_CITIES)]),
            "w_county": pa.array(
                np.array(["Ziebach County", "Williamson County", "Walker County",
                          "Daviess County"], dtype=object)[i % 4]
            ),
            "w_state": pa.array(_STATES[i % len(_STATES)]),
            "w_country": pa.array(np.full(n, "United States", dtype=object)),
        }
    )
    return _parts(t, root, 1)


def gen_reason(root: Path) -> int:
    i = np.arange(REASON_ROWS)
    t = pa.table(
        {
            "r_reason_sk": (i + 1).astype(np.int64),
            "r_reason_desc": pa.array(
                np.char.add("reason ", (i + 1).astype("U2")).astype(object)
            ),
        }
    )
    return _parts(t, root, 1)


def gen_ship_mode(root: Path) -> int:
    i = np.arange(SHIP_MODE_ROWS)
    types = np.array(["EXPRESS", "NEXT DAY", "OVERNIGHT", "REGULAR", "TWO DAY"], dtype=object)
    t = pa.table(
        {
            "sm_ship_mode_sk": (i + 1).astype(np.int64),
            "sm_type": pa.array(types[i % 5]),
            "sm_code": pa.array(
                np.array(["AIR", "SURFACE", "SEA"], dtype=object)[i % 3]
            ),
            "sm_carrier": pa.array(
                np.char.add("carrier", (i % 7).astype("U1")).astype(object)
            ),
        }
    )
    return _parts(t, root, 1)


def gen_call_center(root: Path) -> int:
    i = np.arange(CC_ROWS)
    t = pa.table(
        {
            "cc_call_center_sk": (i + 1).astype(np.int64),
            "cc_call_center_id": pa.array(
                np.char.add("AAAAAAAA", i.astype("U1")).astype(object)
            ),
            "cc_name": pa.array(
                np.array(["NY Metro", "Mid Atlantic", "Pacific NW", "North Midwest",
                          "California", "Hawaii/Alaska"], dtype=object)[:CC_ROWS]
            ),
            "cc_manager": pa.array(
                np.char.add("Manager ", (i + 1).astype("U1")).astype(object)
            ),
            "cc_county": pa.array(
                np.array(["Ziebach County", "Williamson County", "Walker County",
                          "Daviess County"], dtype=object)[i % 4]
            ),
        }
    )
    return _parts(t, root, 1)


def gen_web_site(root: Path) -> int:
    i = np.arange(WEB_SITE_ROWS)
    t = pa.table(
        {
            "web_site_sk": (i + 1).astype(np.int64),
            "web_site_id": pa.array(np.char.add("AAAAAAAA", i.astype("U2")).astype(object)),
            "web_name": pa.array(np.char.add("site_", (i % 10).astype("U1")).astype(object)),
            "web_company_name": pa.array(
                np.array(["pri", "able", "ought", "ese", "anti", "cally"], dtype=object)[i % 6]
            ),
        }
    )
    return _parts(t, root, 1)


def gen_web_page(root: Path) -> int:
    i = np.arange(WEB_PAGE_ROWS)
    t = pa.table(
        {
            "wp_web_page_sk": (i + 1).astype(np.int64),
            "wp_char_count": (i * 229 % 8000 + 100).astype(np.int32),
        }
    )
    return _parts(t, root, 1)


def gen_catalog_page(root: Path) -> int:
    i = np.arange(CATALOG_PAGE_ROWS)
    t = pa.table(
        {
            "cp_catalog_page_sk": (i + 1).astype(np.int64),
            "cp_catalog_page_id": pa.array(
                np.char.add("AAAAAAAA", i.astype("U6")).astype(object)
            ),
        }
    )
    return _parts(t, root, 1)


def gen_income_band(root: Path) -> int:
    i = np.arange(20)
    t = pa.table(
        {
            "ib_income_band_sk": (i + 1).astype(np.int64),
            "ib_lower_bound": (i * 10_000 + 1).astype(np.int32),
            "ib_upper_bound": ((i + 1) * 10_000).astype(np.int32),
        }
    )
    return _parts(t, root, 1)


_GENS = {
    "store_sales": gen_store_sales,
    "catalog_sales": gen_catalog_sales,
    "web_sales": gen_web_sales,
    "store_returns": gen_store_returns,
    "catalog_returns": gen_catalog_returns,
    "web_returns": gen_web_returns,
    "inventory": gen_inventory,
    "date_dim": lambda root, sf=1.0: gen_date_dim(root),
    "item": gen_item,
    "store": lambda root, sf=1.0: gen_store(root),
    "customer": gen_customer,
    "customer_demographics": gen_customer_demographics,
    "household_demographics": lambda root, sf=1.0: gen_household_demographics(root),
    "time_dim": lambda root, sf=1.0: gen_time_dim(root),
    "customer_address": gen_customer_address,
    "promotion": lambda root, sf=1.0: gen_promotion(root),
    "warehouse": lambda root, sf=1.0: gen_warehouse(root),
    "reason": lambda root, sf=1.0: gen_reason(root),
    "ship_mode": lambda root, sf=1.0: gen_ship_mode(root),
    "call_center": lambda root, sf=1.0: gen_call_center(root),
    "web_site": lambda root, sf=1.0: gen_web_site(root),
    "web_page": lambda root, sf=1.0: gen_web_page(root),
    "catalog_page": lambda root, sf=1.0: gen_catalog_page(root),
    "income_band": lambda root, sf=1.0: gen_income_band(root),
}

TABLES = tuple(_GENS)


def cached_tpcds(sf: float = 1.0, cache_root: Path | None = None) -> dict[str, Path]:
    import shutil
    import tempfile

    # v5: cross-channel (customer, item) correlation + returner-cdemo
    # agreement (bump the suffix whenever datagen changes, or stale /tmp
    # data is silently reused).
    base = cache_root or Path(tempfile.gettempdir()) / f"hs_tpcds_v5_sf{sf:g}"
    roots = {}
    try:
        for name, gen in _GENS.items():
            root = base / name
            if not (root / "_COMPLETE").exists():
                shutil.rmtree(root, ignore_errors=True)
                gen(root, sf=sf)
                (root / "_COMPLETE").touch()
            roots[name] = root
    finally:
        _SALES_TABLES.clear()  # don't pin SF10+ fact tables in memory
    return roots


# --------------------------------------------------------------------------
# The queries. Each takes the dict of registered scans and returns a
# LogicalPlan. The innermost join is the one the index rewrite aligns.
# Texts follow the published store-channel queries with qgen-style
# parameter substitutions for this dataset's domains; reformulations
# forced by the IR (scalar subqueries as explicit sub-plans, CASE NULL
# defaults as '', week-grain date join) are noted per query.

def tpcds_queries(t: dict) -> dict:
    from hyperspace_tpu import AggSpec, col, date_lit, lit, when
    from hyperspace_tpu.plan.nodes import Union

    ss, dd, item, store = t["store_sales"], t["date_dim"], t["item"], t["store"]
    cd, hd, td, ca = (
        t["customer_demographics"],
        t["household_demographics"],
        t["time_dim"],
        t["customer_address"],
    )
    cust, promo = t["customer"], t["promotion"]

    def brand_report(manufact_or_manager, months, years, manager=False, cat=False):
        """The q3/q42/q52/q55 family: ss x date_dim x item with an item
        attribute filter and a month/year window."""
        dpred = col("d_moy") == lit(months)
        if years is not None:
            dpred = dpred & (col("d_year") == lit(years))
        dim_filter = dd.select("d_date_sk", "d_year", "d_moy").filter(dpred)
        it = item.select(
            "i_item_sk", "i_brand_id", "i_brand", "i_category_id", "i_category",
            "i_manufact_id", "i_manager_id",
        ).filter(
            (col("i_manager_id") == lit(manufact_or_manager))
            if manager
            else (col("i_manufact_id") == lit(manufact_or_manager))
        )
        group = ["d_year", "i_category_id", "i_category"] if cat else ["d_year", "i_brand_id", "i_brand"]
        return (
            ss.select("ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price")
            .join(dim_filter, ["ss_sold_date_sk"], ["d_date_sk"])
            .join(it, ["ss_item_sk"], ["i_item_sk"])
            .aggregate(group, [AggSpec.of("sum", "ss_ext_sales_price", "sum_sales")])
            .sort([("d_year", True), ("sum_sales", False), (group[1], True)])
            .limit(100)
        )

    q3 = brand_report(128, 11, None)                      # i_manufact_id = 128, d_moy = 11
    q42 = brand_report(1, 11, 2000, manager=True, cat=True)
    q52 = brand_report(1, 11, 2000, manager=True)
    q55 = brand_report(28, 11, 1999, manager=True)

    # q7: average measures for single college-educated male shoppers under
    # a no-email-or-no-event promotion in 2000.
    q7 = (
        ss.select(
            "ss_cdemo_sk", "ss_sold_date_sk", "ss_item_sk", "ss_promo_sk",
            "ss_quantity", "ss_list_price", "ss_coupon_amt", "ss_sales_price",
        )
        .join(
            cd.select("cd_demo_sk", "cd_gender", "cd_marital_status", "cd_education_status")
            .filter(
                (col("cd_gender") == lit("M"))
                & (col("cd_marital_status") == lit("S"))
                & (col("cd_education_status") == lit("College"))
            ),
            ["ss_cdemo_sk"], ["cd_demo_sk"],
        )
        .join(
            dd.select("d_date_sk", "d_year").filter(col("d_year") == lit(2000)),
            ["ss_sold_date_sk"], ["d_date_sk"],
        )
        .join(item.select("i_item_sk", "i_item_id"), ["ss_item_sk"], ["i_item_sk"])
        .join(
            promo.select("p_promo_sk", "p_channel_email", "p_channel_event").filter(
                (col("p_channel_email") == lit("N")) | (col("p_channel_event") == lit("N"))
            ),
            ["ss_promo_sk"], ["p_promo_sk"],
        )
        .aggregate(
            ["i_item_id"],
            [
                AggSpec.of("mean", "ss_quantity", "agg1"),
                AggSpec.of("mean", "ss_list_price", "agg2"),
                AggSpec.of("mean", "ss_coupon_amt", "agg3"),
                AggSpec.of("mean", "ss_sales_price", "agg4"),
            ],
        )
        .sort(["i_item_id"])
        .limit(100)
    )

    # q27 (real ROLLUP form): averages by item and store state for
    # married primary-educated female shoppers in 2002, GROUP BY
    # ROLLUP(i_item_id, s_state) with the grouping(s_state) flag.
    q27 = (
        ss.select(
            "ss_cdemo_sk", "ss_sold_date_sk", "ss_item_sk", "ss_store_sk",
            "ss_quantity", "ss_list_price", "ss_coupon_amt", "ss_sales_price",
        )
        .join(
            cd.select("cd_demo_sk", "cd_gender", "cd_marital_status", "cd_education_status")
            .filter(
                (col("cd_gender") == lit("F"))
                & (col("cd_marital_status") == lit("M"))
                & (col("cd_education_status") == lit("Primary"))
            ),
            ["ss_cdemo_sk"], ["cd_demo_sk"],
        )
        .join(
            dd.select("d_date_sk", "d_year").filter(col("d_year") == lit(2002)),
            ["ss_sold_date_sk"], ["d_date_sk"],
        )
        .join(
            store.select("s_store_sk", "s_state").filter(
                col("s_state").isin(["TX", "OH", "OR", "CA", "WA", "NM"])
            ),
            ["ss_store_sk"], ["s_store_sk"],
        )
        .join(item.select("i_item_sk", "i_item_id"), ["ss_item_sk"], ["i_item_sk"])
        .rollup(
            ["i_item_id", "s_state"],
            [
                AggSpec.of("grouping", "s_state", "g_state"),
                AggSpec.of("mean", "ss_quantity", "agg1"),
                AggSpec.of("mean", "ss_list_price", "agg2"),
                AggSpec.of("mean", "ss_coupon_amt", "agg3"),
                AggSpec.of("mean", "ss_sales_price", "agg4"),
            ],
        )
        .sort(["i_item_id", "s_state"])
        .limit(100)
    )

    # q43: weekly store pivot — day-name CASE sums by store, one year.
    def day_sum(name, alias):
        return AggSpec.of(
            "sum",
            when(col("d_day_name") == lit(name), col("ss_sales_price")).otherwise(0.0),
            alias,
        )

    q43 = (
        ss.select("ss_sold_date_sk", "ss_store_sk", "ss_sales_price")
        .join(
            dd.select("d_date_sk", "d_year", "d_day_name").filter(col("d_year") == lit(2000)),
            ["ss_sold_date_sk"], ["d_date_sk"],
        )
        .join(store.select("s_store_sk", "s_store_id", "s_store_name"), ["ss_store_sk"], ["s_store_sk"])
        .aggregate(
            ["s_store_name", "s_store_id"],
            [
                day_sum("Sunday", "sun_sales"),
                day_sum("Monday", "mon_sales"),
                day_sum("Tuesday", "tue_sales"),
                day_sum("Wednesday", "wed_sales"),
                day_sum("Thursday", "thu_sales"),
                day_sum("Friday", "fri_sales"),
                day_sum("Saturday", "sat_sales"),
            ],
        )
        .sort(["s_store_name", "s_store_id"])
        .limit(100)
    )

    # q48: quantity sold under OR'd demographic/price and address/profit
    # band predicates (the cross-side OR stays a residual Kleene filter).
    q48 = (
        ss.select(
            "ss_cdemo_sk", "ss_sold_date_sk", "ss_addr_sk", "ss_store_sk",
            "ss_quantity", "ss_sales_price", "ss_net_profit",
        )
        .join(
            cd.select("cd_demo_sk", "cd_marital_status", "cd_education_status"),
            ["ss_cdemo_sk"], ["cd_demo_sk"],
        )
        .join(
            dd.select("d_date_sk", "d_year").filter(col("d_year") == lit(2000)),
            ["ss_sold_date_sk"], ["d_date_sk"],
        )
        .join(ca.select("ca_address_sk", "ca_country", "ca_state"), ["ss_addr_sk"], ["ca_address_sk"])
        .filter(
            (
                ((col("cd_marital_status") == lit("M")) & (col("cd_education_status") == lit("4 yr Degree")) & col("ss_sales_price").between(100.0, 150.0))
                | ((col("cd_marital_status") == lit("D")) & (col("cd_education_status") == lit("2 yr Degree")) & col("ss_sales_price").between(50.0, 100.0))
                | ((col("cd_marital_status") == lit("S")) & (col("cd_education_status") == lit("College")) & col("ss_sales_price").between(150.0, 200.0))
            )
            & (col("ca_country") == lit("United States"))
            & (
                (col("ca_state").isin(["CA", "OR", "WA"]) & col("ss_net_profit").between(0.0, 2000.0))
                | (col("ca_state").isin(["TX", "OH", "GA"]) & col("ss_net_profit").between(150.0, 3000.0))
                | (col("ca_state").isin(["FL", "NM", "KY"]) & col("ss_net_profit").between(50.0, 25000.0))
            )
        )
        .aggregate([], [AggSpec.of("sum", "ss_quantity", "quantity")])
    )

    # q96: count of evening shoppers with 7 dependents at store 'ese'.
    q96 = (
        ss.select("ss_hdemo_sk", "ss_sold_time_sk", "ss_store_sk")
        .join(
            hd.select("hd_demo_sk", "hd_dep_count").filter(col("hd_dep_count") == lit(7)),
            ["ss_hdemo_sk"], ["hd_demo_sk"],
        )
        .join(
            td.select("t_time_sk", "t_hour", "t_minute").filter(
                (col("t_hour") == lit(20)) & (col("t_minute") >= lit(30))
            ),
            ["ss_sold_time_sk"], ["t_time_sk"],
        )
        .join(
            store.select("s_store_sk", "s_store_name").filter(col("s_store_name") == lit("ese")),
            ["ss_store_sk"], ["s_store_sk"],
        )
        .aggregate([], [AggSpec.of("count", None, "cnt")])
    )

    # q6: states with >= 10 customers who bought items priced at least
    # 1.2x their category's average, in January 2001. The published
    # d_month_seq scalar subquery selects exactly the (d_year=2001,
    # d_moy=1) month, so the filter is expressed directly; the
    # correlated per-category average is the explicit aggregate joined
    # back to item.
    cat_avg = item.select("i_category", "i_current_price").aggregate(
        ["i_category"], [AggSpec.of("mean", "i_current_price", "cat_avg_price")]
    )
    pricey_items = (
        item.select("i_item_sk", "i_category", "i_current_price")
        .join(cat_avg, ["i_category"])
        .filter(col("i_current_price") > col("cat_avg_price") * lit(1.2))
        .select("i_item_sk")
    )
    q6 = (
        ss.select("ss_sold_date_sk", "ss_item_sk", "ss_customer_sk")
        .join(
            dd.select("d_date_sk", "d_year", "d_moy").filter(
                (col("d_year") == lit(2001)) & (col("d_moy") == lit(1))
            ),
            ["ss_sold_date_sk"], ["d_date_sk"],
        )
        .join(pricey_items, ["ss_item_sk"], ["i_item_sk"])
        .join(cust.select("c_customer_sk", "c_current_addr_sk"), ["ss_customer_sk"], ["c_customer_sk"])
        .join(ca.select("ca_address_sk", "ca_state"), ["c_current_addr_sk"], ["ca_address_sk"])
        .aggregate(["ca_state"], [AggSpec.of("count", None, "cnt")])
        .filter(col("cnt") >= lit(10))
        .sort([("cnt", True), ("ca_state", True)])
        .limit(100)
    )

    # q13: average quantity / prices and wholesale-cost sum under OR'd
    # demographic+price and address+profit bands in 2001.
    q13 = (
        ss.select(
            "ss_sold_date_sk", "ss_cdemo_sk", "ss_hdemo_sk", "ss_addr_sk", "ss_store_sk",
            "ss_quantity", "ss_ext_sales_price", "ss_ext_wholesale_cost",
            "ss_sales_price", "ss_net_profit",
        )
        .join(
            dd.select("d_date_sk", "d_year").filter(col("d_year") == lit(2001)),
            ["ss_sold_date_sk"], ["d_date_sk"],
        )
        .join(
            cd.select("cd_demo_sk", "cd_marital_status", "cd_education_status"),
            ["ss_cdemo_sk"], ["cd_demo_sk"],
        )
        .join(hd.select("hd_demo_sk", "hd_dep_count"), ["ss_hdemo_sk"], ["hd_demo_sk"])
        .join(ca.select("ca_address_sk", "ca_country", "ca_state"), ["ss_addr_sk"], ["ca_address_sk"])
        .join(store.select("s_store_sk"), ["ss_store_sk"], ["s_store_sk"])
        .filter(
            (
                ((col("cd_marital_status") == lit("M")) & (col("cd_education_status") == lit("Advanced Degree")) & col("ss_sales_price").between(100.0, 150.0) & (col("hd_dep_count") == lit(3)))
                | ((col("cd_marital_status") == lit("S")) & (col("cd_education_status") == lit("College")) & col("ss_sales_price").between(50.0, 100.0) & (col("hd_dep_count") == lit(1)))
                | ((col("cd_marital_status") == lit("W")) & (col("cd_education_status") == lit("2 yr Degree")) & col("ss_sales_price").between(150.0, 200.0) & (col("hd_dep_count") == lit(1)))
            )
            & (col("ca_country") == lit("United States"))
            & (
                (col("ca_state").isin(["TX", "OH", "VA"]) & col("ss_net_profit").between(100.0, 200.0))
                | (col("ca_state").isin(["OR", "NM", "KY"]) & col("ss_net_profit").between(150.0, 300.0))
                | (col("ca_state").isin(["FL", "GA", "MI"]) & col("ss_net_profit").between(50.0, 250.0))
            )
        )
        .aggregate(
            [],
            [
                AggSpec.of("mean", "ss_quantity", "avg_qty"),
                AggSpec.of("mean", "ss_ext_sales_price", "avg_esp"),
                AggSpec.of("mean", "ss_ext_wholesale_cost", "avg_ewc"),
                AggSpec.of("sum", "ss_ext_wholesale_cost", "sum_ewc"),
            ],
        )
    )

    # q34 / q73: ticket-size bands per customer (the dn subquery grain is
    # ss_ticket_number x customer). q34 keeps tickets of 15-20 items on
    # peak days; q73 keeps 1-5-item tickets.
    def ticket_counts(dom_pred, buy_pots, ratio_min, county_list):
        hdf = hd.select(
            "hd_demo_sk", "hd_buy_potential", "hd_dep_count", "hd_vehicle_count"
        ).filter(
            col("hd_buy_potential").isin(buy_pots)
            & (col("hd_vehicle_count") > lit(0))
            & ((col("hd_dep_count") / col("hd_vehicle_count")) > lit(ratio_min))
        )
        return (
            ss.select("ss_sold_date_sk", "ss_store_sk", "ss_hdemo_sk", "ss_customer_sk", "ss_ticket_number")
            .join(
                dd.select("d_date_sk", "d_dom", "d_year").filter(
                    dom_pred & col("d_year").isin([1999, 2000, 2001])
                ),
                ["ss_sold_date_sk"], ["d_date_sk"],
            )
            .join(hdf, ["ss_hdemo_sk"], ["hd_demo_sk"])
            .join(
                store.select("s_store_sk", "s_county").filter(col("s_county").isin(county_list)),
                ["ss_store_sk"], ["s_store_sk"],
            )
            .aggregate(
                ["ss_ticket_number", "ss_customer_sk"],
                [AggSpec.of("count", None, "cnt")],
            )
        )

    q34 = (
        ticket_counts(
            col("d_dom").between(1, 3) | col("d_dom").between(25, 28),
            [">10000", "1001-5000"], 1.2,
            ["Ziebach County", "Williamson County", "Walker County", "Daviess County"],
        )
        .filter(col("cnt").between(15, 20))
        .join(
            cust.select("c_customer_sk", "c_last_name", "c_first_name", "c_salutation"),
            ["ss_customer_sk"], ["c_customer_sk"],
        )
        .sort([("c_last_name", True), ("c_first_name", True), ("c_salutation", True), ("ss_ticket_number", False)])
        .limit(1000)
    )
    q73 = (
        ticket_counts(
            col("d_dom").between(1, 2),
            [">10000", "Unknown"], 1.0,
            ["Ziebach County", "Williamson County", "Walker County", "Daviess County"],
        )
        .filter(col("cnt").between(1, 5))
        .join(
            cust.select("c_customer_sk", "c_last_name", "c_first_name", "c_salutation"),
            ["ss_customer_sk"], ["c_customer_sk"],
        )
        .sort([("cnt", False), ("c_last_name", True)])
        .limit(1000)
    )

    # q36: gross-margin rollup over (i_category, i_class) with the
    # rank-within-parent window. lochierarchy and the masked parent key
    # are computed projections over the rollup (CASE NULL default is ''
    # — the IR's Case carries an explicit default).
    q36 = (
        ss.select("ss_sold_date_sk", "ss_item_sk", "ss_store_sk", "ss_net_profit", "ss_ext_sales_price")
        .join(
            dd.select("d_date_sk", "d_year").filter(col("d_year") == lit(2001)),
            ["ss_sold_date_sk"], ["d_date_sk"],
        )
        .join(
            store.select("s_store_sk", "s_state").filter(
                col("s_state").isin(["TX", "OH", "OR", "CA", "WA", "NM", "KY", "VA"])
            ),
            ["ss_store_sk"], ["s_store_sk"],
        )
        .join(item.select("i_item_sk", "i_category", "i_class"), ["ss_item_sk"], ["i_item_sk"])
        .rollup(
            ["i_category", "i_class"],
            [
                AggSpec.of("sum", "ss_net_profit", "sum_np"),
                AggSpec.of("sum", "ss_ext_sales_price", "sum_esp"),
                AggSpec.of("grouping", "i_category", "g_cat"),
                AggSpec.of("grouping", "i_class", "g_class"),
            ],
        )
        .select(
            "i_category", "i_class",
            ("gross_margin", col("sum_np") / col("sum_esp")),
            ("lochierarchy", col("g_cat") + col("g_class")),
            ("parent_cat", when(col("g_class") == lit(0), col("i_category")).otherwise(lit(""))),
        )
        .window(
            ["lochierarchy", "parent_cat"],
            order_by=[("gross_margin", True)],
            funcs=[("rank", None, "rank_within_parent")],
        )
        .select("gross_margin", "i_category", "i_class", "lochierarchy", "rank_within_parent")
        .sort([("lochierarchy", False), ("i_category", True), ("rank_within_parent", True)])
        .limit(100)
    )

    # q53 / q63 / q89: monthly manufacturer/manager/brand sums against
    # their all-months window average, keeping >10% deviations. abs() is
    # spelled as a CASE over the sign (the IR has no abs()).
    def deviation_filter(plan, sum_col, avg_col):
        dev = when(
            col(sum_col) >= col(avg_col),
            (col(sum_col) - col(avg_col)) / col(avg_col),
        ).otherwise((col(avg_col) - col(sum_col)) / col(avg_col))
        return plan.filter((col(avg_col) > lit(0.0)) & (dev > lit(0.1)))

    _q53_item = item.select("i_item_sk", "i_manufact_id", "i_category", "i_class", "i_brand").filter(
        (
            col("i_category").isin(["Books", "Children", "Electronics"])
            & col("i_class").isin(["class1", "class2", "class3", "class4"])
        )
        | (
            col("i_category").isin(["Women", "Music", "Men"])
            & col("i_class").isin(["class5", "class6", "class7", "class8"])
        )
    )
    q53 = deviation_filter(
        ss.select("ss_sold_date_sk", "ss_item_sk", "ss_store_sk", "ss_sales_price")
        .join(
            dd.select("d_date_sk", "d_month_seq", "d_qoy").filter(
                col("d_month_seq").isin(list(range(1200, 1212)))
            ),
            ["ss_sold_date_sk"], ["d_date_sk"],
        )
        .join(_q53_item, ["ss_item_sk"], ["i_item_sk"])
        .join(store.select("s_store_sk"), ["ss_store_sk"], ["s_store_sk"])
        .aggregate(["i_manufact_id", "d_qoy"], [AggSpec.of("sum", "ss_sales_price", "sum_sales")])
        .window(["i_manufact_id"], funcs=[("mean", "sum_sales", "avg_quarterly_sales")]),
        "sum_sales", "avg_quarterly_sales",
    ).select("i_manufact_id", "sum_sales", "avg_quarterly_sales").sort(
        [("avg_quarterly_sales", True), ("sum_sales", True), ("i_manufact_id", True)]
    ).limit(100)

    _q63_item = item.select("i_item_sk", "i_manager_id", "i_category", "i_class", "i_brand").filter(
        (
            col("i_category").isin(["Books", "Children", "Electronics"])
            & col("i_class").isin(["class1", "class2", "class3", "class4"])
        )
        | (
            col("i_category").isin(["Women", "Music", "Men"])
            & col("i_class").isin(["class5", "class6", "class7", "class8"])
        )
    )
    q63 = deviation_filter(
        ss.select("ss_sold_date_sk", "ss_item_sk", "ss_store_sk", "ss_sales_price")
        .join(
            dd.select("d_date_sk", "d_month_seq", "d_moy").filter(
                col("d_month_seq").isin(list(range(1176, 1188)))
            ),
            ["ss_sold_date_sk"], ["d_date_sk"],
        )
        .join(_q63_item, ["ss_item_sk"], ["i_item_sk"])
        .join(store.select("s_store_sk"), ["ss_store_sk"], ["s_store_sk"])
        .aggregate(["i_manager_id", "d_moy"], [AggSpec.of("sum", "ss_sales_price", "sum_sales")])
        .window(["i_manager_id"], funcs=[("mean", "sum_sales", "avg_monthly_sales")]),
        "sum_sales", "avg_monthly_sales",
    ).select("i_manager_id", "sum_sales", "avg_monthly_sales").sort(
        [("i_manager_id", True), ("avg_monthly_sales", True), ("sum_sales", True)]
    ).limit(100)

    q89 = deviation_filter(
        ss.select("ss_sold_date_sk", "ss_item_sk", "ss_store_sk", "ss_sales_price")
        .join(
            dd.select("d_date_sk", "d_year", "d_moy").filter(col("d_year") == lit(1999)),
            ["ss_sold_date_sk"], ["d_date_sk"],
        )
        .join(
            item.select("i_item_sk", "i_category", "i_class", "i_brand").filter(
                (
                    col("i_category").isin(["Books", "Electronics", "Sports"])
                    & col("i_class").isin(["class1", "class2", "class16"])
                )
                | (
                    col("i_category").isin(["Men", "Jewelry", "Women"])
                    & col("i_class").isin(["class3", "class9", "class11"])
                )
            ),
            ["ss_item_sk"], ["i_item_sk"],
        )
        .join(store.select("s_store_sk", "s_store_name", "s_company_name"), ["ss_store_sk"], ["s_store_sk"])
        .aggregate(
            ["i_category", "i_class", "i_brand", "s_store_name", "s_company_name", "d_moy"],
            [AggSpec.of("sum", "ss_sales_price", "sum_sales")],
        )
        .window(
            ["i_category", "i_brand", "s_store_name", "s_company_name"],
            funcs=[("mean", "sum_sales", "avg_monthly_sales")],
        ),
        "sum_sales", "avg_monthly_sales",
    ).select(
        "i_category", "i_class", "i_brand", "s_store_name", "s_company_name",
        "d_moy", "sum_sales", "avg_monthly_sales",
        ("sales_diff", col("sum_sales") - col("avg_monthly_sales")),
    ).sort([("sales_diff", True), ("s_store_name", True)]).limit(100)

    # q44: best vs worst performing items by average net profit at one
    # store, asc/desc ranks joined. The published having-threshold scalar
    # (0.9x the store's overall average) is recomposed from the window
    # totals of the per-item aggregate.
    v1 = (
        ss.select("ss_store_sk", "ss_item_sk", "ss_net_profit")
        .filter(col("ss_store_sk") == lit(4))
        .aggregate(
            ["ss_item_sk"],
            [AggSpec.of("sum", "ss_net_profit", "np_sum"), AggSpec.of("count", "ss_net_profit", "np_cnt")],
        )
        .window([], funcs=[("sum", "np_sum", "tot_sum"), ("sum", "np_cnt", "tot_cnt")])
        .select(
            "ss_item_sk",
            ("rank_col", col("np_sum") / col("np_cnt")),
            ("threshold", col("tot_sum") / col("tot_cnt") * lit(0.9)),
        )
        .filter(col("rank_col") > col("threshold"))
        .select("ss_item_sk", "rank_col")
    )
    asc = (
        v1.window([], order_by=[("rank_col", True)], funcs=[("rank", None, "rnk")])
        .filter(col("rnk") < lit(11))
        .select(("item_sk_a", col("ss_item_sk")), "rnk")
    )
    desc = (
        v1.window([], order_by=[("rank_col", False)], funcs=[("rank", None, "rnk")])
        .filter(col("rnk") < lit(11))
        .select(("item_sk_d", col("ss_item_sk")), ("rnk_d", col("rnk")))
    )
    q44 = (
        asc.join(desc, ["rnk"], ["rnk_d"])
        .join(
            item.select("i_item_sk", ("best_performing", col("i_item_id"))),
            ["item_sk_a"], ["i_item_sk"],
        )
        .join(
            item.select(("i_item_sk_2", col("i_item_sk")), ("worst_performing", col("i_item_id"))),
            ["item_sk_d"], ["i_item_sk_2"],
        )
        .select("rnk", "best_performing", "worst_performing")
        .sort([("rnk", True)])
        .limit(100)
    )

    # q59: week-over-year store sales ratios. The weekly pivot joins the
    # calendar at WEEK grain (an aggregate of date_dim — the published
    # text joins date_dim directly and multiplies rows 7x, which LIMIT
    # hides; the week-grain join preserves the result set).
    wss = (
        ss.select("ss_sold_date_sk", "ss_store_sk", "ss_sales_price")
        .join(dd.select("d_date_sk", "d_week_seq", "d_day_name"), ["ss_sold_date_sk"], ["d_date_sk"])
        .aggregate(
            ["d_week_seq", "ss_store_sk"],
            [
                day_sum("Sunday", "sun_sales"),
                day_sum("Monday", "mon_sales"),
                day_sum("Tuesday", "tue_sales"),
                day_sum("Wednesday", "wed_sales"),
                day_sum("Thursday", "thu_sales"),
                day_sum("Friday", "fri_sales"),
                day_sum("Saturday", "sat_sales"),
            ],
        )
    )
    dweeks = dd.select("d_week_seq", "d_month_seq").aggregate(
        ["d_week_seq"], [AggSpec.of("min", "d_month_seq", "mseq")]
    )

    def year_slice(lo, hi, suffix):
        renames = [
            ("d_week_seq" + suffix, col("d_week_seq")),
            ("sun" + suffix, col("sun_sales")), ("mon" + suffix, col("mon_sales")),
            ("tue" + suffix, col("tue_sales")), ("wed" + suffix, col("wed_sales")),
            ("thu" + suffix, col("thu_sales")), ("fri" + suffix, col("fri_sales")),
            ("sat" + suffix, col("sat_sales")),
        ]
        out = (
            wss.join(dweeks.filter(col("mseq").between(lo, hi)), ["d_week_seq"])
            .join(
                store.select("s_store_sk", "s_store_id", "s_store_name"),
                ["ss_store_sk"], ["s_store_sk"],
            )
        )
        if suffix == "1":
            return out.select("s_store_name", "s_store_id", *renames)
        return out.select(("s_store_id2", col("s_store_id")), *renames,
                          ("wk_join", col("d_week_seq") - lit(52)))

    y = year_slice(1176, 1187, "1")
    x = year_slice(1188, 1199, "2")
    q59 = (
        y.join(x, ["s_store_id", "d_week_seq1"], ["s_store_id2", "wk_join"])
        .select(
            "s_store_name", "s_store_id", "d_week_seq1",
            ("r_sun", col("sun1") / col("sun2")), ("r_mon", col("mon1") / col("mon2")),
            ("r_tue", col("tue1") / col("tue2")), ("r_wed", col("wed1") / col("wed2")),
            ("r_thu", col("thu1") / col("thu2")), ("r_fri", col("fri1") / col("fri2")),
            ("r_sat", col("sat1") / col("sat2")),
        )
        .sort([("s_store_name", True), ("s_store_id", True), ("d_week_seq1", True)])
        .limit(100)
    )

    # q65: items whose revenue is at most 10% of their store's average
    # item revenue (the sb/sc subqueries are explicit aggregates; the
    # cross-subquery comparison is the residual filter).
    sc = (
        ss.select("ss_sold_date_sk", "ss_store_sk", "ss_item_sk", "ss_sales_price")
        .join(
            dd.select("d_date_sk", "d_month_seq").filter(
                col("d_month_seq").between(1176, 1187)
            ),
            ["ss_sold_date_sk"], ["d_date_sk"],
        )
        .aggregate(["ss_store_sk", "ss_item_sk"], [AggSpec.of("sum", "ss_sales_price", "revenue")])
    )
    sb = sc.aggregate(["ss_store_sk"], [AggSpec.of("mean", "revenue", "ave")])
    q65 = (
        sc.join(sb, ["ss_store_sk"])
        .filter(col("revenue") <= col("ave") * lit(0.1))
        .join(store.select("s_store_sk", "s_store_name"), ["ss_store_sk"], ["s_store_sk"])
        .join(
            item.select("i_item_sk", "i_item_desc", "i_current_price", "i_brand"),
            ["ss_item_sk"], ["i_item_sk"],
        )
        .select("s_store_name", "i_item_desc", "revenue", "i_current_price", "i_brand")
        .sort([("s_store_name", True), ("i_item_desc", True)])
        .limit(100)
    )

    # q67: the 8-level rollup of quantity*price with a rank-within-
    # category window keeping the top 100 per category (i_product_name
    # is this dataset's i_item_id; the measures are non-null so the
    # published COALESCE is the identity).
    q67 = (
        ss.select("ss_sold_date_sk", "ss_item_sk", "ss_store_sk", "ss_quantity", "ss_sales_price")
        .join(
            dd.select("d_date_sk", "d_year", "d_qoy", "d_moy", "d_month_seq").filter(
                col("d_month_seq").between(1200, 1211)
            ),
            ["ss_sold_date_sk"], ["d_date_sk"],
        )
        .join(store.select("s_store_sk", "s_store_id"), ["ss_store_sk"], ["s_store_sk"])
        .join(
            item.select("i_item_sk", "i_category", "i_class", "i_brand", "i_item_id"),
            ["ss_item_sk"], ["i_item_sk"],
        )
        .rollup(
            ["i_category", "i_class", "i_brand", "i_item_id", "d_year", "d_qoy", "d_moy", "s_store_id"],
            [AggSpec.of("sum", col("ss_sales_price") * col("ss_quantity"), "sumsales")],
        )
        .window(["i_category"], order_by=[("sumsales", False)], funcs=[("rank", None, "rk")])
        .filter(col("rk") <= lit(100))
        .select("i_category", "i_class", "i_brand", "i_item_id", "d_year", "d_qoy", "d_moy", "s_store_id", "sumsales", "rk")
        .sort([("i_category", True), ("rk", True)])
        .limit(100)
    )

    # q70: net-profit rollup over (s_state, s_county) restricted to the
    # top-ranked states (the published inner ranking subquery — its
    # per-state partition makes every state rank 1, which the semi join
    # preserves faithfully), with the rank-within-parent window.
    top_states = (
        ss.select("ss_sold_date_sk", "ss_store_sk", "ss_net_profit")
        .join(
            dd.select("d_date_sk", "d_month_seq").filter(col("d_month_seq").between(1176, 1187)),
            ["ss_sold_date_sk"], ["d_date_sk"],
        )
        .join(store.select("s_store_sk", "s_state"), ["ss_store_sk"], ["s_store_sk"])
        .aggregate(["s_state"], [AggSpec.of("sum", "ss_net_profit", "state_np")])
        .window(["s_state"], order_by=[("state_np", False)], funcs=[("rank", None, "ranking")])
        .filter(col("ranking") <= lit(5))
        .select("s_state")
    )
    q70 = (
        ss.select("ss_sold_date_sk", "ss_store_sk", "ss_net_profit")
        .join(
            dd.select("d_date_sk", "d_month_seq").filter(col("d_month_seq").between(1176, 1187)),
            ["ss_sold_date_sk"], ["d_date_sk"],
        )
        .join(
            store.select("s_store_sk", "s_state", "s_county").join(
                top_states, ["s_state"], ["s_state"], how="semi"
            ),
            ["ss_store_sk"], ["s_store_sk"],
        )
        .rollup(
            ["s_state", "s_county"],
            [
                AggSpec.of("sum", "ss_net_profit", "total_sum"),
                AggSpec.of("grouping", "s_state", "g_state"),
                AggSpec.of("grouping", "s_county", "g_county"),
            ],
        )
        .select(
            "total_sum", "s_state", "s_county",
            ("lochierarchy", col("g_state") + col("g_county")),
            ("parent_state", when(col("g_county") == lit(0), col("s_state")).otherwise(lit(""))),
        )
        .window(
            ["lochierarchy", "parent_state"],
            order_by=[("total_sum", False)],
            funcs=[("rank", None, "rank_within_parent")],
        )
        .select("total_sum", "s_state", "s_county", "lochierarchy", "rank_within_parent")
        .sort([("lochierarchy", False), ("s_state", True), ("rank_within_parent", True)])
        .limit(100)
    )

    # q79: per-ticket coupon amount and profit for high-dependency /
    # multi-vehicle households on Mondays, joined to the customer.
    q79 = (
        ss.select(
            "ss_sold_date_sk", "ss_store_sk", "ss_hdemo_sk", "ss_customer_sk",
            "ss_addr_sk", "ss_ticket_number", "ss_coupon_amt", "ss_net_profit",
        )
        .join(
            dd.select("d_date_sk", "d_dow", "d_year").filter(
                (col("d_dow") == lit(1)) & col("d_year").isin([1999, 2000, 2001])
            ),
            ["ss_sold_date_sk"], ["d_date_sk"],
        )
        .join(
            hd.select("hd_demo_sk", "hd_dep_count", "hd_vehicle_count").filter(
                (col("hd_dep_count") == lit(6)) | (col("hd_vehicle_count") > lit(2))
            ),
            ["ss_hdemo_sk"], ["hd_demo_sk"],
        )
        .join(
            store.select("s_store_sk", "s_number_of_employees", "s_city").filter(
                col("s_number_of_employees").between(200, 295)
            ),
            ["ss_store_sk"], ["s_store_sk"],
        )
        .aggregate(
            ["ss_ticket_number", "ss_customer_sk", "ss_addr_sk", "s_city"],
            [
                AggSpec.of("sum", "ss_coupon_amt", "amt"),
                AggSpec.of("sum", "ss_net_profit", "profit"),
            ],
        )
        .join(
            cust.select("c_customer_sk", "c_last_name", "c_first_name"),
            ["ss_customer_sk"], ["c_customer_sk"],
        )
        .select(
            "c_last_name", "c_first_name",
            ("city_30", col("s_city").substr(1, 30)),
            "ss_ticket_number", "amt", "profit",
        )
        .sort([("c_last_name", True), ("c_first_name", True), ("city_30", True), ("profit", True)])
        .limit(100)
    )

    # q46 / q68: per-ticket amounts for weekend/high-dependency trips in
    # probe cities, joined to the customer and their CURRENT address,
    # keeping trips bought in a DIFFERENT city (string col<>col — the
    # two city columns carry different dictionaries and compare through
    # a merged domain). q46 filters weekends; q68 the published
    # month-start days, with this dataset's measures
    # (ss_ext_list_price/ss_ext_tax are not generated).
    def city_trips(hd_pred, date_pred, cities, measures):
        return (
            ss.select(
                "ss_sold_date_sk", "ss_store_sk", "ss_hdemo_sk", "ss_addr_sk",
                "ss_customer_sk", "ss_ticket_number", "ss_coupon_amt",
                "ss_net_profit", "ss_ext_sales_price",
            )
            .join(
                dd.select("d_date_sk", "d_dow", "d_dom", "d_year").filter(
                    date_pred & col("d_year").isin([1999, 2000, 2001])
                ),
                ["ss_sold_date_sk"], ["d_date_sk"],
            )
            .join(
                store.select("s_store_sk", "s_city").filter(col("s_city").isin(cities)),
                ["ss_store_sk"], ["s_store_sk"],
            )
            .join(hd.select("hd_demo_sk", "hd_dep_count", "hd_vehicle_count").filter(hd_pred),
                  ["ss_hdemo_sk"], ["hd_demo_sk"])
            .join(ca.select("ca_address_sk", ("bought_city", col("ca_city"))),
                  ["ss_addr_sk"], ["ca_address_sk"])
            .aggregate(
                ["ss_ticket_number", "ss_customer_sk", "ss_addr_sk", "bought_city"],
                measures,
            )
            .join(
                cust.select("c_customer_sk", "c_current_addr_sk", "c_last_name", "c_first_name"),
                ["ss_customer_sk"], ["c_customer_sk"],
            )
            .join(ca.select(("cur_addr_sk", col("ca_address_sk")), "ca_city"),
                  ["c_current_addr_sk"], ["cur_addr_sk"])
            .filter(col("ca_city") != col("bought_city"))
        )

    q46 = (
        city_trips(
            (col("hd_dep_count") == lit(4)) | (col("hd_vehicle_count") == lit(3)),
            col("d_dow").isin([6, 0]),  # weekend trips
            ["Midway", "Fairview", "Oak Grove", "Five Points", "Centerville"],
            [AggSpec.of("sum", "ss_coupon_amt", "amt"), AggSpec.of("sum", "ss_net_profit", "profit")],
        )
        .select("c_last_name", "c_first_name", "ca_city", "bought_city", "ss_ticket_number", "amt", "profit")
        .sort([("c_last_name", True), ("c_first_name", True), ("ca_city", True), ("bought_city", True), ("ss_ticket_number", True)])
        .limit(100)
    )
    q68 = (
        city_trips(
            (col("hd_dep_count") == lit(5)) | (col("hd_vehicle_count") == lit(3)),
            col("d_dom").between(1, 2),  # the published q68 month-start filter
            ["Midway", "Fairview"],
            [AggSpec.of("sum", "ss_ext_sales_price", "extended_price"),
             AggSpec.of("sum", "ss_coupon_amt", "amt")],
        )
        .select("c_last_name", "c_first_name", "ca_city", "bought_city", "ss_ticket_number", "extended_price", "amt")
        .sort([("c_last_name", True), ("ss_ticket_number", True)])
        .limit(100)
    )

    # q19: brand revenue from customers shopping OUTSIDE their home zip
    # prefix (SUBSTRING col <> SUBSTRING col across two dictionaries);
    # i_manufact (string) is this dataset's i_manufact_id.
    q19 = (
        ss.select("ss_sold_date_sk", "ss_item_sk", "ss_customer_sk", "ss_store_sk", "ss_ext_sales_price")
        .join(
            dd.select("d_date_sk", "d_moy", "d_year").filter(
                (col("d_moy") == lit(11)) & (col("d_year") == lit(1998))
            ),
            ["ss_sold_date_sk"], ["d_date_sk"],
        )
        .join(
            item.select("i_item_sk", "i_brand_id", "i_brand", "i_manufact_id", "i_manager_id")
            .filter(col("i_manager_id") == lit(8)),
            ["ss_item_sk"], ["i_item_sk"],
        )
        .join(cust.select("c_customer_sk", "c_current_addr_sk"), ["ss_customer_sk"], ["c_customer_sk"])
        .join(ca.select("ca_address_sk", "ca_zip"), ["c_current_addr_sk"], ["ca_address_sk"])
        .join(store.select("s_store_sk", "s_zip"), ["ss_store_sk"], ["s_store_sk"])
        .filter(col("ca_zip").substr(1, 5) != col("s_zip").substr(1, 5))
        .aggregate(
            ["i_brand", "i_brand_id", "i_manufact_id"],
            [AggSpec.of("sum", "ss_ext_sales_price", "ext_price")],
        )
        .sort([("ext_price", False), ("i_brand", True), ("i_brand_id", True), ("i_manufact_id", True)])
        .limit(100)
    )

    # q88: the 8 half-hour store-traffic counts 8:30-12:30 — the
    # published cross-join of 8 scalar subqueries computed in ONE pass
    # as conditional counts over the union of their time bands.
    def half_hour(h, first_half):
        cond = col("t_hour") == lit(h)
        band = (col("t_minute") < lit(30)) if first_half else (col("t_minute") >= lit(30))
        return cond & band

    bands = [
        ("h8_30_to_9", half_hour(8, False)), ("h9_to_9_30", half_hour(9, True)),
        ("h9_30_to_10", half_hour(9, False)), ("h10_to_10_30", half_hour(10, True)),
        ("h10_30_to_11", half_hour(10, False)), ("h11_to_11_30", half_hour(11, True)),
        ("h11_30_to_12", half_hour(11, False)), ("h12_to_12_30", half_hour(12, True)),
    ]
    q88 = (
        ss.select("ss_sold_time_sk", "ss_hdemo_sk", "ss_store_sk")
        .join(
            hd.select("hd_demo_sk", "hd_dep_count", "hd_vehicle_count").filter(
                ((col("hd_dep_count") == lit(4)) & (col("hd_vehicle_count") <= lit(6)))
                | ((col("hd_dep_count") == lit(2)) & (col("hd_vehicle_count") <= lit(4)))
                | ((col("hd_dep_count") == lit(0)) & (col("hd_vehicle_count") <= lit(2)))
            ),
            ["ss_hdemo_sk"], ["hd_demo_sk"],
        )
        .join(
            td.select("t_time_sk", "t_hour", "t_minute").filter(
                (col("t_hour") >= lit(8)) & ((col("t_hour") < lit(12)) | ((col("t_hour") == lit(12)) & (col("t_minute") < lit(30))))
                & ~((col("t_hour") == lit(8)) & (col("t_minute") < lit(30)))
            ),
            ["ss_sold_time_sk"], ["t_time_sk"],
        )
        .join(
            store.select("s_store_sk", "s_store_name").filter(col("s_store_name") == lit("ese")),
            ["ss_store_sk"], ["s_store_sk"],
        )
        .aggregate(
            [],
            [AggSpec.of("sum", when(cond, 1).otherwise(0), alias) for alias, cond in bands],
        )
    )

    # q98: item revenue share within class over a 30-day window.
    q98 = (
        ss.select("ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price")
        .join(
            dd.select("d_date_sk", "d_date").filter(
                (col("d_date") >= date_lit("1999-02-22")) & (col("d_date") <= date_lit("1999-03-24"))
            ),
            ["ss_sold_date_sk"], ["d_date_sk"],
        )
        .join(
            item.select(
                "i_item_sk", "i_item_id", "i_item_desc", "i_category", "i_class", "i_current_price"
            ).filter(col("i_category").isin(["Sports", "Books", "Home"])),
            ["ss_item_sk"], ["i_item_sk"],
        )
        .aggregate(
            ["i_item_id", "i_item_desc", "i_category", "i_class", "i_current_price"],
            [AggSpec.of("sum", "ss_ext_sales_price", "itemrevenue")],
        )
        .window(["i_class"], funcs=[("sum", "itemrevenue", "class_revenue")])
        .select(
            "i_item_id", "i_item_desc", "i_category", "i_class", "i_current_price",
            "itemrevenue",
            ("revenueratio", col("itemrevenue") * lit(100.0) / col("class_revenue")),
        )
        .sort([("i_category", True), ("i_class", True), ("i_item_id", True), ("i_item_desc", True), ("revenueratio", True)])
        .limit(100)
    )

    # q33 / q60: total extended sales price per manufacturer / item
    # across ALL THREE channels — each channel aggregates independently
    # (store / catalog / web facts, bill-or-store address in the -5 GMT
    # band, one month), the channel partials UNION, and an outer
    # aggregate folds them (the published UNION ALL + re-group shape).
    # The probed item sets come from semi joins against the
    # category-filtered ids, as the published subqueries do.
    cs, ws = t["catalog_sales"], t["web_sales"]

    def channel_sum(fact, dk, ik, ak, price, item_side, group_col):
        return (
            fact.select(dk, ik, ak, price)
            .join(
                dd.select("d_date_sk", "d_year", "d_moy").filter(
                    (col("d_year") == lit(2000)) & (col("d_moy") == lit(1))
                ),
                [dk], ["d_date_sk"],
            )
            .join(
                ca.select("ca_address_sk", "ca_gmt_offset").filter(
                    col("ca_gmt_offset") == lit(-5.0)
                ),
                [ak], ["ca_address_sk"],
            )
            .join(item_side, [ik], ["i_item_sk"])
            .aggregate([group_col], [AggSpec.of("sum", price, "total_sales")])
        )

    def three_channel(item_side, group_col, order_by):
        parts = [
            channel_sum(ss, "ss_sold_date_sk", "ss_item_sk", "ss_addr_sk",
                        "ss_ext_sales_price", item_side, group_col),
            channel_sum(cs, "cs_sold_date_sk", "cs_item_sk", "cs_bill_addr_sk",
                        "cs_ext_sales_price", item_side, group_col),
            channel_sum(ws, "ws_sold_date_sk", "ws_item_sk", "ws_bill_addr_sk",
                        "ws_ext_sales_price", item_side, group_col),
        ]
        return (
            Union(parts)
            .aggregate([group_col], [AggSpec.of("sum", "total_sales", "total_sales2")])
            .select(group_col, ("total_sales", col("total_sales2")))
            .sort(order_by)
            .limit(100)
        )

    electronics_mf = (
        item.select("i_manufact_id", "i_category")
        .filter(col("i_category") == lit("Electronics"))
        .select("i_manufact_id")
        .distinct()
    )
    q33 = three_channel(
        item.select("i_item_sk", "i_manufact_id").join(
            electronics_mf, ["i_manufact_id"], how="semi"
        ),
        "i_manufact_id",
        [("total_sales", True), ("i_manufact_id", True)],
    )
    music_ids = (
        item.select("i_item_id", "i_category")
        .filter(col("i_category") == lit("Music"))
        .select("i_item_id")
        .distinct()
    )
    q60 = three_channel(
        item.select("i_item_sk", "i_item_id").join(music_ids, ["i_item_id"], how="semi"),
        "i_item_id",
        # Published q60 orders by the item id FIRST, then total sales.
        [("i_item_id", True), ("total_sales", True)],
    )

    out = {
        "q3": q3, "q6": q6, "q7": q7, "q13": q13, "q19": q19, "q27": q27,
        "q34": q34, "q36": q36, "q42": q42, "q43": q43, "q44": q44,
        "q33": q33, "q46": q46, "q48": q48, "q52": q52, "q53": q53,
        "q55": q55, "q59": q59, "q60": q60, "q63": q63, "q65": q65,
        "q67": q67, "q68": q68, "q70": q70, "q73": q73, "q79": q79,
        "q88": q88, "q89": q89, "q96": q96, "q98": q98,
    }
    from benchmarks.tpcds_ext import tpcds_extra_queries
    from benchmarks.tpcds_ext2 import tpcds_extra_queries2

    out.update(tpcds_extra_queries(t))
    out.update(tpcds_extra_queries2(t))
    return out


def tpcds_indexes(hs, scans: dict) -> None:
    """The covering indexes a Hyperspace user would build for this slice:
    the fact table bucketed on each probing dimension key, plus the
    matching dimension-side indexes (equal bucket counts => the innermost
    join of every query runs zero-exchange)."""
    from hyperspace_tpu import IndexConfig

    ss, dd, cd, hd = scans["store_sales"], scans["date_dim"], scans["customer_demographics"], scans["household_demographics"]
    hs.create_index(ss, IndexConfig(
        "ss_by_date", ["ss_sold_date_sk"],
        ["ss_item_sk", "ss_store_sk", "ss_customer_sk", "ss_cdemo_sk", "ss_hdemo_sk",
         "ss_addr_sk", "ss_ticket_number", "ss_quantity", "ss_list_price",
         "ss_sales_price", "ss_ext_sales_price", "ss_ext_wholesale_cost",
         "ss_coupon_amt", "ss_net_profit"],
    ))
    hs.create_index(ss, IndexConfig(
        "ss_by_cdemo", ["ss_cdemo_sk"],
        ["ss_sold_date_sk", "ss_item_sk", "ss_store_sk", "ss_addr_sk", "ss_promo_sk",
         "ss_quantity", "ss_list_price", "ss_coupon_amt", "ss_sales_price", "ss_net_profit"],
    ))
    hs.create_index(ss, IndexConfig(
        "ss_by_hdemo", ["ss_hdemo_sk"], ["ss_sold_time_sk", "ss_store_sk"],
    ))
    hs.create_index(ss, IndexConfig(
        "ss_by_store", ["ss_store_sk"], ["ss_item_sk", "ss_net_profit"],
    ))
    hs.create_index(ss, IndexConfig(
        "ss_by_ticket_item", ["ss_ticket_number", "ss_item_sk"],
        ["ss_customer_sk", "ss_sold_date_sk", "ss_quantity", "ss_sales_price",
         "ss_store_sk", "ss_net_profit"],
    ))
    hs.create_index(scans["catalog_sales"], IndexConfig(
        "cs_by_date", ["cs_sold_date_sk"],
        ["cs_sold_time_sk", "cs_ship_date_sk", "cs_item_sk", "cs_bill_customer_sk",
         "cs_bill_cdemo_sk", "cs_bill_hdemo_sk", "cs_bill_addr_sk", "cs_warehouse_sk",
         "cs_call_center_sk", "cs_promo_sk", "cs_order_number", "cs_quantity",
         "cs_list_price", "cs_sales_price", "cs_coupon_amt", "cs_ext_discount_amt",
         "cs_ext_sales_price", "cs_net_profit"],
    ))
    hs.create_index(scans["catalog_sales"], IndexConfig(
        "cs_by_ship_date", ["cs_ship_date_sk"],
        ["cs_sold_date_sk", "cs_ship_addr_sk", "cs_order_number", "cs_warehouse_sk",
         "cs_ship_mode_sk", "cs_call_center_sk", "cs_ext_ship_cost", "cs_net_profit"],
    ))
    hs.create_index(scans["web_sales"], IndexConfig(
        "ws_by_date", ["ws_sold_date_sk"],
        ["ws_sold_time_sk", "ws_ship_date_sk", "ws_item_sk", "ws_bill_customer_sk",
         "ws_bill_addr_sk", "ws_ship_customer_sk", "ws_ship_hdemo_sk",
         "ws_web_page_sk", "ws_web_site_sk", "ws_quantity", "ws_sales_price",
         "ws_ext_discount_amt", "ws_ext_sales_price", "ws_net_paid", "ws_net_profit",
         "ws_order_number"],
    ))
    hs.create_index(scans["web_sales"], IndexConfig(
        "ws_by_ship_date", ["ws_ship_date_sk"],
        ["ws_sold_date_sk", "ws_ship_addr_sk", "ws_order_number", "ws_warehouse_sk",
         "ws_ship_mode_sk", "ws_web_site_sk", "ws_ext_ship_cost", "ws_net_profit"],
    ))
    hs.create_index(scans["store_returns"], IndexConfig(
        "sr_by_date", ["sr_returned_date_sk"],
        ["sr_item_sk", "sr_customer_sk", "sr_store_sk", "sr_ticket_number",
         "sr_cdemo_sk", "sr_reason_sk", "sr_return_quantity", "sr_return_amt",
         "sr_fee", "sr_net_loss"],
    ))
    hs.create_index(scans["store_returns"], IndexConfig(
        "sr_by_ticket_item", ["sr_ticket_number", "sr_item_sk"],
        ["sr_customer_sk", "sr_returned_date_sk", "sr_reason_sk",
         "sr_return_quantity", "sr_return_amt", "sr_net_loss"],
    ))
    hs.create_index(scans["catalog_returns"], IndexConfig(
        "cr_by_date", ["cr_returned_date_sk"],
        ["cr_item_sk", "cr_order_number", "cr_returning_customer_sk",
         "cr_returning_addr_sk", "cr_call_center_sk", "cr_reason_sk",
         "cr_return_quantity", "cr_return_amt", "cr_net_loss"],
    ))
    hs.create_index(scans["web_returns"], IndexConfig(
        "wr_by_date", ["wr_returned_date_sk"],
        ["wr_item_sk", "wr_order_number", "wr_returning_customer_sk",
         "wr_returning_addr_sk", "wr_refunded_cdemo_sk", "wr_returning_cdemo_sk",
         "wr_refunded_addr_sk", "wr_reason_sk", "wr_web_page_sk",
         "wr_return_quantity", "wr_return_amt", "wr_fee", "wr_net_loss"],
    ))
    hs.create_index(scans["inventory"], IndexConfig(
        "inv_by_date", ["inv_date_sk"],
        ["inv_item_sk", "inv_warehouse_sk", "inv_quantity_on_hand"],
    ))
    hs.create_index(scans["catalog_sales"], IndexConfig(
        "cs_by_cdemo", ["cs_bill_cdemo_sk"],
        ["cs_sold_date_sk", "cs_item_sk", "cs_bill_customer_sk", "cs_quantity",
         "cs_list_price", "cs_coupon_amt", "cs_sales_price", "cs_net_profit"],
    ))
    hs.create_index(scans["web_sales"], IndexConfig(
        "ws_by_hdemo", ["ws_ship_hdemo_sk"], ["ws_sold_time_sk", "ws_web_page_sk"],
    ))
    hs.create_index(scans["catalog_sales"], IndexConfig(
        "cs_by_order_item", ["cs_order_number", "cs_item_sk"],
        ["cs_sold_date_sk", "cs_ship_date_sk", "cs_warehouse_sk", "cs_quantity",
         "cs_sales_price", "cs_promo_sk", "cs_bill_cdemo_sk", "cs_bill_hdemo_sk"],
    ))
    hs.create_index(scans["catalog_returns"], IndexConfig(
        "cr_by_order_item", ["cr_order_number", "cr_item_sk"], ["cr_return_amt"],
    ))
    hs.create_index(scans["web_sales"], IndexConfig(
        "ws_by_order_item", ["ws_order_number", "ws_item_sk"],
        ["ws_web_page_sk", "ws_sold_date_sk", "ws_quantity", "ws_sales_price",
         "ws_net_profit"],
    ))
    hs.create_index(scans["web_returns"], IndexConfig(
        "wr_by_order_item", ["wr_order_number", "wr_item_sk"],
        ["wr_refunded_cdemo_sk", "wr_returning_cdemo_sk", "wr_reason_sk",
         "wr_refunded_addr_sk", "wr_return_amt", "wr_fee"],
    ))
    hs.create_index(dd, IndexConfig(
        "dd_by_sk", ["d_date_sk"],
        ["d_date", "d_year", "d_moy", "d_dom", "d_qoy", "d_day_name",
         "d_month_seq", "d_week_seq", "d_dow"],
    ))
    hs.create_index(cd, IndexConfig(
        "cd_by_sk", ["cd_demo_sk"],
        ["cd_gender", "cd_marital_status", "cd_education_status"],
    ))
    hs.create_index(hd, IndexConfig(
        "hd_by_sk", ["hd_demo_sk"],
        ["hd_buy_potential", "hd_dep_count", "hd_vehicle_count"],
    ))
