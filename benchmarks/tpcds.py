"""TPC-DS star-schema slice: datagen + nine real queries in the plan IR.

Tables follow the TPC-DS schema (store_sales fact + date_dim / item /
store / customer_demographics / household_demographics / time_dim /
customer_address dimensions) with dsdgen-style surrogate keys (date_dim
julian numbering, cd demographics as a cycling cartesian product) and
synthetic value distributions. SF1 store_sales = 2,879,987 rows.

The queries are TPC-DS q3, q7, q27 (flat group-by; no ROLLUP in the IR),
q42, q43, q48, q52, q55 and q96 — the star-join + filter + group-by +
ORDER/LIMIT subset the engine expresses today (windowed/correlated
queries are out of scope this round). Each is written with the most
selective dimension join innermost so the index rewrite turns it into a
bucket-aligned zero-exchange SMJ; remaining dimensions chain above it.
The reference claims serde coverage of all TPC-DS queries
(index/serde/package.scala:47-50); BASELINE config 3 is the SF1000
99-query geomean this slice builds toward.
"""

from __future__ import annotations

from pathlib import Path

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

SS_SF1_ROWS = 2_879_987
ITEM_SF1_ROWS = 18_000
CUSTOMER_SF1_ROWS = 100_000
CA_SF1_ROWS = 50_000
CD_ROWS = 1_920_800  # fixed cartesian size in TPC-DS
HD_ROWS = 7_200
DD_ROWS = 73_049  # 1900-01-02 .. 2100-01-01
DD_SK0 = 2_415_022  # julian day number of the first date_dim row
STORE_ROWS = 12

_CATEGORIES = np.array(
    ["Books", "Children", "Electronics", "Home", "Jewelry",
     "Men", "Music", "Shoes", "Sports", "Women"], dtype=object
)
_GENDER = np.array(["M", "F"], dtype=object)
_MARITAL = np.array(["M", "S", "D", "W", "U"], dtype=object)
_EDUCATION = np.array(
    ["Primary", "Secondary", "College", "2 yr Degree", "4 yr Degree",
     "Advanced Degree", "Unknown"], dtype=object
)
_BUY_POTENTIAL = np.array(
    [">10000", "5001-10000", "1001-5000", "501-1000", "0-500", "Unknown"], dtype=object
)
_STATES = np.array(
    ["TX", "OH", "OR", "CA", "WA", "NM", "KY", "VA", "FL", "GA", "MI", "IL"], dtype=object
)
_STORE_NAMES = np.array(
    ["ought", "able", "pri", "ese", "anti", "cally", "ation", "eing",
     "ought", "able", "ese", "bar"], dtype=object
)


def _parts(t: pa.Table, root: Path, files: int) -> int:
    from benchmarks.datagen import _write_parts

    _write_parts(t, root, files)
    return t.nbytes


def gen_date_dim(root: Path) -> int:
    """Deterministic calendar: one row per day 1900-01-02..2100-01-01,
    julian d_date_sk numbering as dsdgen emits."""
    days = np.arange(DD_ROWS, dtype=np.int64)
    d64 = np.datetime64("1900-01-02") + days
    years = d64.astype("datetime64[Y]").astype(np.int64) + 1970
    months0 = d64.astype("datetime64[M]").astype(np.int64)
    moy = months0 % 12 + 1
    dom = (d64 - d64.astype("datetime64[M]")).astype(np.int64) + 1
    dow = (d64.astype("datetime64[D]").astype(np.int64) + 4) % 7  # 0=Sunday
    names = np.array(
        ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday", "Friday", "Saturday"],
        dtype=object,
    )
    t = pa.table(
        {
            "d_date_sk": DD_SK0 + days,
            "d_date": pa.array(
                (d64 - np.datetime64("1970-01-01")).astype(np.int32), type=pa.date32()
            ),
            "d_year": years.astype(np.int32),
            "d_moy": moy.astype(np.int32),
            "d_dom": dom.astype(np.int32),
            "d_qoy": ((moy - 1) // 3 + 1).astype(np.int32),
            "d_day_name": pa.array(names[dow]),
        }
    )
    return _parts(t, root, 1)


def item_rows(sf: float) -> int:
    """item scales sublinearly in TPC-DS; pinned to the SF1 size above
    SF1 (good enough for this slice) and proportionally below."""
    return max(int(ITEM_SF1_ROWS * min(sf, 1.0)), 100)


def gen_item(root: Path, sf: float = 1.0, seed: int = 61) -> int:
    n = item_rows(sf)
    rng = np.random.default_rng(seed)
    manufact = rng.integers(1, 1001, n).astype(np.int32)
    brand_id = (manufact * 1000 + rng.integers(1, 1000, n)).astype(np.int32)
    cat_id = rng.integers(1, 11, n).astype(np.int32)
    t = pa.table(
        {
            "i_item_sk": np.arange(1, n + 1, dtype=np.int64),
            "i_item_id": pa.array(
                np.char.add("AAAAAAAA", np.arange(n).astype("U8")).astype(object)
            ),
            "i_brand_id": brand_id,
            "i_brand": pa.array(
                np.char.add("brandbrand#", brand_id.astype("U8")).astype(object)
            ),
            "i_manufact_id": manufact,
            "i_manager_id": rng.integers(1, 101, n).astype(np.int32),
            "i_category_id": cat_id,
            "i_category": pa.array(_CATEGORIES[cat_id - 1]),
            "i_class": pa.array(
                np.char.add("class", rng.integers(1, 17, n).astype("U2")).astype(object)
            ),
            "i_current_price": np.round(rng.random(n) * 99 + 1, 2),
        }
    )
    return _parts(t, root, 1)


def gen_store(root: Path) -> int:
    n = STORE_ROWS
    t = pa.table(
        {
            "s_store_sk": np.arange(1, n + 1, dtype=np.int64),
            "s_store_id": pa.array(
                np.char.add("AAAAAAAA", np.arange(n).astype("U2")).astype(object)
            ),
            "s_store_name": pa.array(_STORE_NAMES[:n]),
            "s_state": pa.array(_STATES[:n]),
            "s_zip": pa.array(
                np.char.add("55", (np.arange(n) * 137 % 1000).astype("U3")).astype(object)
            ),
            "s_gmt_offset": np.full(n, -5.0),
        }
    )
    return _parts(t, root, 1)


def cd_rows(sf: float) -> int:
    """customer_demographics is fixed-size in TPC-DS; scaled down below
    SF1 (keeping full field-cycle coverage) so tiny test runs stay fast."""
    return CD_ROWS if sf >= 1 else max(int(CD_ROWS * sf), 11_200)


def gen_customer_demographics(root: Path, sf: float = 1.0) -> int:
    """The dsdgen cartesian: demographics fields CYCLE with fixed periods
    so any (gender, marital, education) combo is a fixed 1/70 of keys."""
    n = cd_rows(sf)
    i = np.arange(n, dtype=np.int64)
    t = pa.table(
        {
            "cd_demo_sk": i + 1,
            "cd_gender": pa.array(_GENDER[i % 2]),
            "cd_marital_status": pa.array(_MARITAL[(i // 2) % 5]),
            "cd_education_status": pa.array(_EDUCATION[(i // 10) % 7]),
            "cd_purchase_estimate": ((i // 70) % 20 * 500 + 500).astype(np.int32),
            "cd_credit_rating": pa.array(
                np.array(["Good", "High Risk", "Low Risk", "Unknown"], dtype=object)[
                    (i // 1400) % 4
                ]
            ),
            "cd_dep_count": ((i // 5600) % 7).astype(np.int32),
        }
    )
    return _parts(t, root, 2)


def gen_household_demographics(root: Path) -> int:
    n = HD_ROWS
    i = np.arange(n, dtype=np.int64)
    t = pa.table(
        {
            "hd_demo_sk": i + 1,
            "hd_buy_potential": pa.array(_BUY_POTENTIAL[i % 6]),
            "hd_dep_count": ((i // 6) % 10).astype(np.int32),
            "hd_vehicle_count": ((i // 60) % 5).astype(np.int32),
        }
    )
    return _parts(t, root, 1)


def gen_time_dim(root: Path) -> int:
    i = np.arange(86_400, dtype=np.int64)
    t = pa.table(
        {
            "t_time_sk": i,
            "t_hour": (i // 3600).astype(np.int32),
            "t_minute": (i % 3600 // 60).astype(np.int32),
            "t_second": (i % 60).astype(np.int32),
        }
    )
    return _parts(t, root, 1)


def ca_rows(sf: float) -> int:
    return max(int(CA_SF1_ROWS * max(sf, 0.02)), 100)


def gen_customer_address(root: Path, sf: float = 1.0, seed: int = 62) -> int:
    n = ca_rows(sf)
    rng = np.random.default_rng(seed)
    t = pa.table(
        {
            "ca_address_sk": np.arange(1, n + 1, dtype=np.int64),
            "ca_state": pa.array(_STATES[rng.integers(0, len(_STATES), n)]),
            "ca_zip": pa.array(rng.integers(10000, 99999, n).astype("U5").astype(object)),
            "ca_country": pa.array(np.full(n, "United States", dtype=object)),
        }
    )
    return _parts(t, root, 1)


def gen_store_sales(root: Path, sf: float = 1.0, seed: int = 60, files: int = 8,
                    n_items: int | None = None, n_ca: int | None = None) -> int:
    """The fact table. Sold dates concentrate in 1998-2002 (the years the
    published queries probe), store hours 08:00-21:00."""
    n = int(SS_SF1_ROWS * sf)
    rng = np.random.default_rng(seed)
    # d_date_sk for 1998-01-01..2002-12-31 in julian numbering.
    lo = DD_SK0 + int((np.datetime64("1998-01-01") - np.datetime64("1900-01-02")) // np.timedelta64(1, "D"))
    hi = DD_SK0 + int((np.datetime64("2002-12-31") - np.datetime64("1900-01-02")) // np.timedelta64(1, "D"))
    n_items = n_items if n_items is not None else item_rows(sf)
    n_ca = n_ca if n_ca is not None else ca_rows(sf)
    quantity = rng.integers(1, 101, n).astype(np.int32)
    list_price = np.round(rng.random(n) * 190 + 10, 2)
    sales_price = np.round(list_price * (0.2 + rng.random(n) * 0.8), 2)
    t = pa.table(
        {
            "ss_sold_date_sk": rng.integers(lo, hi + 1, n).astype(np.int64),
            "ss_sold_time_sk": rng.integers(8 * 3600, 21 * 3600, n).astype(np.int64),
            "ss_item_sk": rng.integers(1, n_items + 1, n).astype(np.int64),
            "ss_customer_sk": rng.integers(1, int(CUSTOMER_SF1_ROWS * max(sf, 0.02)) + 1, n).astype(np.int64),
            "ss_cdemo_sk": rng.integers(1, cd_rows(sf) + 1, n).astype(np.int64),
            "ss_hdemo_sk": rng.integers(1, HD_ROWS + 1, n).astype(np.int64),
            "ss_addr_sk": rng.integers(1, n_ca + 1, n).astype(np.int64),
            "ss_store_sk": rng.integers(1, STORE_ROWS + 1, n).astype(np.int64),
            "ss_promo_sk": rng.integers(1, 301, n).astype(np.int64),
            "ss_quantity": quantity,
            "ss_list_price": list_price,
            "ss_sales_price": sales_price,
            "ss_coupon_amt": np.round(np.where(rng.random(n) < 0.2, rng.random(n) * 50, 0.0), 2),
            "ss_ext_sales_price": np.round(quantity * sales_price, 2),
            "ss_net_profit": np.round(quantity * (sales_price - list_price * 0.5), 2),
        }
    )
    return _parts(t, root, files)


_GENS = {
    "store_sales": gen_store_sales,
    "date_dim": lambda root, sf=1.0: gen_date_dim(root),
    "item": gen_item,
    "store": lambda root, sf=1.0: gen_store(root),
    "customer_demographics": gen_customer_demographics,
    "household_demographics": lambda root, sf=1.0: gen_household_demographics(root),
    "time_dim": lambda root, sf=1.0: gen_time_dim(root),
    "customer_address": gen_customer_address,
}

TABLES = tuple(_GENS)


def cached_tpcds(sf: float = 1.0, cache_root: Path | None = None) -> dict[str, Path]:
    import shutil
    import tempfile

    base = cache_root or Path(tempfile.gettempdir()) / f"hs_tpcds_sf{sf:g}"
    roots = {}
    for name, gen in _GENS.items():
        root = base / name
        if not (root / "_COMPLETE").exists():
            shutil.rmtree(root, ignore_errors=True)
            gen(root, sf=sf)
            (root / "_COMPLETE").touch()
        roots[name] = root
    return roots


# --------------------------------------------------------------------------
# The nine queries. Each takes the dict of registered scans and returns a
# LogicalPlan. The innermost join is the one the index rewrite aligns.

def tpcds_queries(t: dict) -> dict:
    from hyperspace_tpu import AggSpec, col, lit, when

    ss, dd, item, store = t["store_sales"], t["date_dim"], t["item"], t["store"]
    cd, hd, td, ca = (
        t["customer_demographics"],
        t["household_demographics"],
        t["time_dim"],
        t["customer_address"],
    )

    def brand_report(manufact_or_manager, months, years, manager=False, cat=False):
        """The q3/q42/q52/q55 family: ss x date_dim x item with an item
        attribute filter and a month/year window."""
        dpred = col("d_moy") == lit(months)
        if years is not None:
            dpred = dpred & (col("d_year") == lit(years))
        dim_filter = dd.select("d_date_sk", "d_year", "d_moy").filter(dpred)
        it = item.select(
            "i_item_sk", "i_brand_id", "i_brand", "i_category_id", "i_category",
            "i_manufact_id", "i_manager_id",
        ).filter(
            (col("i_manager_id") == lit(manufact_or_manager))
            if manager
            else (col("i_manufact_id") == lit(manufact_or_manager))
        )
        group = ["d_year", "i_category_id", "i_category"] if cat else ["d_year", "i_brand_id", "i_brand"]
        return (
            ss.select("ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price")
            .join(dim_filter, ["ss_sold_date_sk"], ["d_date_sk"])
            .join(it, ["ss_item_sk"], ["i_item_sk"])
            .aggregate(group, [AggSpec.of("sum", "ss_ext_sales_price", "sum_sales")])
            .sort([("d_year", True), ("sum_sales", False), (group[1], True)])
            .limit(100)
        )

    q3 = brand_report(128, 11, None)                      # i_manufact_id = 128, d_moy = 11
    q42 = brand_report(1, 11, 2000, manager=True, cat=True)
    q52 = brand_report(1, 11, 2000, manager=True)
    q55 = brand_report(28, 11, 1999, manager=True)

    # q7: average measures for single college-educated male shoppers under
    # a no-email-or-no-event promotion in 2000 (promotion flags are
    # modeled by promo-key parity).
    q7 = (
        ss.select(
            "ss_cdemo_sk", "ss_sold_date_sk", "ss_item_sk", "ss_promo_sk",
            "ss_quantity", "ss_list_price", "ss_coupon_amt", "ss_sales_price",
        )
        .join(
            cd.select("cd_demo_sk", "cd_gender", "cd_marital_status", "cd_education_status")
            .filter(
                (col("cd_gender") == lit("M"))
                & (col("cd_marital_status") == lit("S"))
                & (col("cd_education_status") == lit("College"))
            ),
            ["ss_cdemo_sk"], ["cd_demo_sk"],
        )
        .join(
            dd.select("d_date_sk", "d_year").filter(col("d_year") == lit(2000)),
            ["ss_sold_date_sk"], ["d_date_sk"],
        )
        .join(item.select("i_item_sk", "i_item_id"), ["ss_item_sk"], ["i_item_sk"])
        # promotion is modeled by promo_sk parity (channel flags cycle).
        .filter((col("ss_promo_sk") % lit(2)) == lit(0))
        .aggregate(
            ["i_item_id"],
            [
                AggSpec.of("mean", "ss_quantity", "agg1"),
                AggSpec.of("mean", "ss_list_price", "agg2"),
                AggSpec.of("mean", "ss_coupon_amt", "agg3"),
                AggSpec.of("mean", "ss_sales_price", "agg4"),
            ],
        )
        .sort(["i_item_id"])
        .limit(100)
    )

    # q27 (flat group-by form): averages by item and store state for
    # married primary-educated female shoppers in 2002.
    q27 = (
        ss.select(
            "ss_cdemo_sk", "ss_sold_date_sk", "ss_item_sk", "ss_store_sk",
            "ss_quantity", "ss_list_price", "ss_coupon_amt", "ss_sales_price",
        )
        .join(
            cd.select("cd_demo_sk", "cd_gender", "cd_marital_status", "cd_education_status")
            .filter(
                (col("cd_gender") == lit("F"))
                & (col("cd_marital_status") == lit("M"))
                & (col("cd_education_status") == lit("Primary"))
            ),
            ["ss_cdemo_sk"], ["cd_demo_sk"],
        )
        .join(
            dd.select("d_date_sk", "d_year").filter(col("d_year") == lit(2002)),
            ["ss_sold_date_sk"], ["d_date_sk"],
        )
        .join(store.select("s_store_sk", "s_state"), ["ss_store_sk"], ["s_store_sk"])
        .join(item.select("i_item_sk", "i_item_id"), ["ss_item_sk"], ["i_item_sk"])
        .aggregate(
            ["i_item_id", "s_state"],
            [
                AggSpec.of("mean", "ss_quantity", "agg1"),
                AggSpec.of("mean", "ss_list_price", "agg2"),
                AggSpec.of("mean", "ss_coupon_amt", "agg3"),
                AggSpec.of("mean", "ss_sales_price", "agg4"),
            ],
        )
        .sort(["i_item_id", "s_state"])
        .limit(100)
    )

    # q43: weekly store pivot — day-name CASE sums by store, one year.
    def day_sum(name, alias):
        return AggSpec.of(
            "sum",
            when(col("d_day_name") == lit(name), col("ss_sales_price")).otherwise(0.0),
            alias,
        )

    q43 = (
        ss.select("ss_sold_date_sk", "ss_store_sk", "ss_sales_price")
        .join(
            dd.select("d_date_sk", "d_year", "d_day_name").filter(col("d_year") == lit(2000)),
            ["ss_sold_date_sk"], ["d_date_sk"],
        )
        .join(store.select("s_store_sk", "s_store_id", "s_store_name"), ["ss_store_sk"], ["s_store_sk"])
        .aggregate(
            ["s_store_name", "s_store_id"],
            [
                day_sum("Sunday", "sun_sales"),
                day_sum("Monday", "mon_sales"),
                day_sum("Tuesday", "tue_sales"),
                day_sum("Wednesday", "wed_sales"),
                day_sum("Thursday", "thu_sales"),
                day_sum("Friday", "fri_sales"),
                day_sum("Saturday", "sat_sales"),
            ],
        )
        .sort(["s_store_name", "s_store_id"])
        .limit(100)
    )

    # q48: quantity sold under OR'd demographic/price and address/profit
    # band predicates (the cross-side OR stays a residual Kleene filter).
    q48 = (
        ss.select(
            "ss_cdemo_sk", "ss_sold_date_sk", "ss_addr_sk", "ss_store_sk",
            "ss_quantity", "ss_sales_price", "ss_net_profit",
        )
        .join(
            cd.select("cd_demo_sk", "cd_marital_status", "cd_education_status"),
            ["ss_cdemo_sk"], ["cd_demo_sk"],
        )
        .join(
            dd.select("d_date_sk", "d_year").filter(col("d_year") == lit(2000)),
            ["ss_sold_date_sk"], ["d_date_sk"],
        )
        .join(ca.select("ca_address_sk", "ca_country", "ca_state"), ["ss_addr_sk"], ["ca_address_sk"])
        .filter(
            (
                ((col("cd_marital_status") == lit("M")) & (col("cd_education_status") == lit("4 yr Degree")) & col("ss_sales_price").between(100.0, 150.0))
                | ((col("cd_marital_status") == lit("D")) & (col("cd_education_status") == lit("2 yr Degree")) & col("ss_sales_price").between(50.0, 100.0))
                | ((col("cd_marital_status") == lit("S")) & (col("cd_education_status") == lit("College")) & col("ss_sales_price").between(150.0, 200.0))
            )
            & (col("ca_country") == lit("United States"))
            & (
                (col("ca_state").isin(["CA", "OR", "WA"]) & col("ss_net_profit").between(0.0, 2000.0))
                | (col("ca_state").isin(["TX", "OH", "GA"]) & col("ss_net_profit").between(150.0, 3000.0))
                | (col("ca_state").isin(["FL", "NM", "KY"]) & col("ss_net_profit").between(50.0, 25000.0))
            )
        )
        .aggregate([], [AggSpec.of("sum", "ss_quantity", "quantity")])
    )

    # q96: count of evening shoppers with 7 dependents at store 'ese'.
    q96 = (
        ss.select("ss_hdemo_sk", "ss_sold_time_sk", "ss_store_sk")
        .join(
            hd.select("hd_demo_sk", "hd_dep_count").filter(col("hd_dep_count") == lit(7)),
            ["ss_hdemo_sk"], ["hd_demo_sk"],
        )
        .join(
            td.select("t_time_sk", "t_hour", "t_minute").filter(
                (col("t_hour") == lit(20)) & (col("t_minute") >= lit(30))
            ),
            ["ss_sold_time_sk"], ["t_time_sk"],
        )
        .join(
            store.select("s_store_sk", "s_store_name").filter(col("s_store_name") == lit("ese")),
            ["ss_store_sk"], ["s_store_sk"],
        )
        .aggregate([], [AggSpec.of("count", None, "cnt")])
    )

    return {
        "q3": q3, "q7": q7, "q27": q27, "q42": q42, "q43": q43,
        "q48": q48, "q52": q52, "q55": q55, "q96": q96,
    }


def tpcds_indexes(hs, scans: dict) -> None:
    """The covering indexes a Hyperspace user would build for this slice:
    the fact table bucketed on each probing dimension key, plus the
    matching dimension-side indexes (equal bucket counts => the innermost
    join of every query runs zero-exchange)."""
    from hyperspace_tpu import IndexConfig

    ss, dd, cd, hd = scans["store_sales"], scans["date_dim"], scans["customer_demographics"], scans["household_demographics"]
    hs.create_index(ss, IndexConfig(
        "ss_by_date", ["ss_sold_date_sk"],
        ["ss_item_sk", "ss_store_sk", "ss_ext_sales_price", "ss_sales_price"],
    ))
    hs.create_index(ss, IndexConfig(
        "ss_by_cdemo", ["ss_cdemo_sk"],
        ["ss_sold_date_sk", "ss_item_sk", "ss_store_sk", "ss_addr_sk", "ss_promo_sk",
         "ss_quantity", "ss_list_price", "ss_coupon_amt", "ss_sales_price", "ss_net_profit"],
    ))
    hs.create_index(ss, IndexConfig(
        "ss_by_hdemo", ["ss_hdemo_sk"], ["ss_sold_time_sk", "ss_store_sk"],
    ))
    hs.create_index(dd, IndexConfig(
        "dd_by_sk", ["d_date_sk"], ["d_year", "d_moy", "d_day_name"],
    ))
    hs.create_index(cd, IndexConfig(
        "cd_by_sk", ["cd_demo_sk"],
        ["cd_gender", "cd_marital_status", "cd_education_status"],
    ))
    hs.create_index(hd, IndexConfig(
        "hd_by_sk", ["hd_demo_sk"], ["hd_dep_count"],
    ))
