"""Continuous-ingestion service under live queries (docs/ingestion.md).

The IngestDaemon runs for real (thread worker, fast poll): a producer
appends trip batches AND tails a CDC changelog while a query thread
hammers the indexed gauge query and a reader pinned BEFORE the first
commit re-reads its snapshot on every round. Measures sustained ingest
throughput through the unchanged two-phase refresh path, per-batch
freshness lag (arrival -> first reflected serve), and completed-query
latency while micro-batches commit underneath.

Writes BENCH_INGEST.json; ``--smoke`` runs a small fixed workload (the
CI job). Gates are ALWAYS enforced — exit 1 on any failure:

- pinned reader repeatable across live commits (zero wrong-version
  serves: every pinned read returns the admission-time rows, live
  counts never regress, and the drained count is exactly the expected
  total);
- zero stale-past-lag serves (no query completing more than
  ``maxLagSeconds`` after a batch arrived misses that batch);
- zero untyped errors anywhere in the loop;
- completed-query p99 bounded during sustained ingest;
- ingest throughput >= BENCH_REFRESH's 0.11 GB/s (>=2-CPU hosts;
  same accounting: dataset bytes over the ingest window).
"""

from __future__ import annotations

import json
import shutil
import sys
import tempfile
import threading
import time
from pathlib import Path

import numpy as np
import pyarrow.parquet as pq

P99_BOUND_S = 5.0  # the bench_soak completed-p99 bound
THROUGHPUT_FLOOR_GBPS = 0.11  # BENCH_REFRESH's committed number
GAUGE_ZONE = 42


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main(smoke: bool = False, out_path: str = "BENCH_INGEST.json") -> int:
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    import os

    from benchmarks.datagen import gen_trips_batch
    from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig, col
    from hyperspace_tpu import stats
    from hyperspace_tpu.exceptions import HyperspaceError

    batch_rows = 120_000 if smoke else 500_000
    batches = 4 if smoke else 8  # appended on top of the seed batch 0
    cdc_rows = 2_000 if smoke else 10_000  # per CDC wave
    lag_bound_s = 30.0 if smoke else 60.0

    class TimingFacade:
        """Pass-through Hyperspace that clocks successful refreshes, so
        throughput uses BENCH_REFRESH's accounting (dataset bytes over
        refresh time; empty polls and failures excluded)."""

        def __init__(self, hs):
            self._hs = hs
            self._tlock = threading.Lock()
            self.commit_s = 0.0

        def refresh_index(self, name, mode="full"):
            t0 = time.perf_counter()
            out = self._hs.refresh_index(name, mode)  # raises on empty poll
            with self._tlock:
                self.commit_s += time.perf_counter() - t0
            return out

        def __getattr__(self, attr):
            return getattr(self._hs, attr)

    tmp = Path(tempfile.mkdtemp(prefix="hs_benchingest_"))
    t_bench = time.perf_counter()
    try:
        data = tmp / "trips"
        staging = tmp / "staging"  # batches build here, publish atomically
        total_bytes = gen_trips_batch(data, batch_rows, 0)
        session = HyperspaceSession(system_path=str(tmp / "indexes"), num_buckets=16)
        conf = session.conf
        conf.set("hyperspace.ingest.enabled", "true")
        conf.set("hyperspace.ingest.pollSeconds", "0.02")
        conf.set("hyperspace.ingest.maxLagSeconds", str(lag_bound_s))
        conf.set("hyperspace.ingest.cdcBatchRows", str(cdc_rows))
        hs = Hyperspace(session)
        df = session.parquet(data)
        hs.create_index(df, IndexConfig("trips_zone", ["zone"], ["fare", "distance"]))
        session.enable_hyperspace()
        gauge = df.filter(col("zone") == GAUGE_ZONE).select("zone", "fare")
        timed = TimingFacade(hs)

        def count_rows(snapshot=None) -> int:
            return len(session.run(gauge, snapshot=snapshot).decode()["zone"])

        changelog = tmp / "changes.jsonl"
        changelog.touch()
        from hyperspace_tpu.ingest.daemon import IngestDaemon

        daemon = IngestDaemon(timed).watch("trips_zone", changelog=changelog)

        # Pin BEFORE any commit: this reader must stay on the seed world
        # for the whole run, however many micro-batches land underneath.
        pinned = session.pin_snapshot()
        pinned_admission = count_rows(snapshot=pinned)
        seed_count = count_rows()

        # One entry per appended unit: arrival time, the cumulative
        # expected gauge rows once it is served, and when a serve first
        # reflected it (freshness lag = seen_at - arrived).
        floors: list[dict] = []
        floors_lock = threading.Lock()
        expected = seed_count
        errors_untyped: list[str] = []
        stop = threading.Event()

        def producer():
            nonlocal total_bytes, expected
            rng = np.random.default_rng(1234)
            cdc_next_id = 10_000_000
            for b in range(1, batches + 1):
                # Build in staging, publish atomically — the operator
                # contract for watched arrival roots (docs/ingestion.md).
                nb = gen_trips_batch(staging, batch_rows, b)
                fname = f"batch-{b:04d}.parquet"
                t = pq.read_table(staging / fname, columns=["zone"])
                n42 = int((np.asarray(t.column("zone")) == GAUGE_ZONE).sum())
                os.replace(staging / fname, data / fname)
                with floors_lock:
                    total_bytes += nb
                    expected += n42
                    floors.append({"arrived": time.perf_counter(),
                                   "cum": expected, "seen_at": None})
                # A CDC wave rides along with every file batch: appended
                # rows the tailer materializes and the same refresh
                # commits.
                zones = rng.integers(0, 265, cdc_rows)
                with open(changelog, "a", encoding="utf-8") as f:
                    for z in zones:
                        f.write(json.dumps({
                            "trip_id": cdc_next_id,
                            "zone": int(z),
                            "fare": round(float(rng.random() * 80), 3),
                            "distance": round(float(rng.random() * 30), 3),
                        }) + "\n")
                        cdc_next_id += 1
                with floors_lock:
                    expected += int((zones == GAUGE_ZONE).sum())
                    floors.append({"arrived": time.perf_counter(),
                                   "cum": expected, "seen_at": None})
                # Keep a standing backlog without racing ahead of the
                # committer by more than one wave.
                deadline = time.perf_counter() + 120
                while time.perf_counter() < deadline and not stop.is_set():
                    if daemon.snapshot()["commits"] >= b:
                        break
                    time.sleep(0.02)

        latencies: list[float] = []
        serves = {"total": 0, "wrong_version": 0, "stale_past_lag": 0}
        pinned_state = {"reads": 0, "violations": 0}
        high_water = [seed_count]

        def querier():
            while not stop.is_set():
                t0 = time.perf_counter()
                try:
                    n = count_rows()
                except HyperspaceError:
                    continue  # typed refusal: counted nowhere, retried
                except Exception as e:  # noqa: BLE001 — the gate
                    errors_untyped.append(f"{type(e).__name__}: {e}")
                    continue
                t1 = time.perf_counter()
                latencies.append(t1 - t0)
                serves["total"] += 1
                if n < high_water[0]:
                    serves["wrong_version"] += 1  # a serve went backwards
                high_water[0] = max(high_water[0], n)
                with floors_lock:
                    # Every unit that arrived more than lag_bound before
                    # this query STARTED must be visible in its answer;
                    # the first serve covering a unit stamps its lag.
                    floor = 0
                    for u in floors:
                        if t0 - u["arrived"] > lag_bound_s:
                            floor = max(floor, u["cum"])
                        if u["seen_at"] is None and n >= u["cum"]:
                            u["seen_at"] = t1
                if n < floor:
                    serves["stale_past_lag"] += 1
                try:
                    pinned_state["reads"] += 1
                    if count_rows(snapshot=pinned) != pinned_admission:
                        pinned_state["violations"] += 1
                except Exception as e:  # noqa: BLE001 — the gate
                    errors_untyped.append(f"pinned {type(e).__name__}: {e}")

        daemon.start()
        t_ingest0 = time.perf_counter()
        qt = threading.Thread(target=querier, name="bench-querier", daemon=True)
        pt = threading.Thread(target=producer, name="bench-producer", daemon=True)
        qt.start()
        pt.start()
        pt.join(timeout=600)
        drained = daemon.drain(timeout=300)
        t_ingest = time.perf_counter() - t_ingest0
        commits_while_pinned = daemon.snapshot()["commits"]
        stop.set()
        qt.join(timeout=30)
        daemon.stop()

        # Drained exactness: the final live count is exactly the expected
        # total — every appended row served once, none lost, none doubled.
        final = count_rows()
        pinned_final = count_rows(snapshot=pinned)
        pinned.release()
        if final != expected:
            serves["wrong_version"] += 1
        if pinned_final != pinned_admission:
            pinned_state["violations"] += 1

        # Freshness lag: arrival -> first serve that covered the unit
        # (units only covered by the final drain use the drain end).
        t_end = t_ingest0 + t_ingest
        with floors_lock:
            lags = [
                max((u["seen_at"] if u["seen_at"] is not None else t_end)
                    - u["arrived"], 0.0)
                for u in floors
            ]
        lat = sorted(latencies)
        p99 = float(np.percentile(lat, 99)) if lat else 0.0
        # BENCH_REFRESH accounting: dataset bytes over the time spent
        # inside successful refresh commits (the path under test).
        gbps = (total_bytes / 1e9) / timed.commit_s if timed.commit_s > 0 else 0.0

        cpus = os.cpu_count() or 1
        gates = {
            "pinned_reader_repeatable_across_live_commits": (
                pinned_state["violations"] == 0
                and pinned_state["reads"] >= 10
                and commits_while_pinned >= 2
            ),
            "zero_wrong_version_serves": serves["wrong_version"] == 0,
            "zero_stale_past_lag_serves": serves["stale_past_lag"] == 0,
            "zero_untyped_errors": not errors_untyped,
            "completed_p99_bounded": p99 < P99_BOUND_S,
            "drained_exactly_once": drained and final == expected,
            "ingest_throughput_floor": (
                gbps >= THROUGHPUT_FLOOR_GBPS if cpus >= 2 else True
            ),
        }
        doc = {
            "bench": "ingest",
            "smoke": smoke,
            "batch_rows": batch_rows,
            "batches": batches,
            "cdc_rows_per_wave": cdc_rows,
            "dataset_bytes": total_bytes,
            "ingest_window_s": round(t_ingest, 3),
            "refresh_commit_s": round(timed.commit_s, 3),
            "ingest_throughput_gbps": round(gbps, 4),
            "throughput_floor_gbps": THROUGHPUT_FLOOR_GBPS,
            "cpus": cpus,
            "throughput_gate_enforced": cpus >= 2,  # ISSUE: >=2-CPU hosts
            "commits": commits_while_pinned,
            "counters": {
                name: stats.get(name)
                for name in (
                    "ingest.ticks", "ingest.commits", "ingest.commit_failures",
                    "ingest.rows", "ingest.bytes", "ingest.snapshots",
                    "ingest.pinned_reads",
                )
            },
            "serves": serves,
            "pinned": {
                "admission_rows": pinned_admission,
                "reads": pinned_state["reads"],
                "violations": pinned_state["violations"],
                "commits_underneath": commits_while_pinned,
            },
            "freshness_lag_s": {
                "mean": round(float(np.mean(lags)), 3) if lags else None,
                "max": round(float(np.max(lags)), 3) if lags else None,
                "bound": lag_bound_s,
            },
            "completed_p99_s": round(p99, 4),
            "p99_bound_s": P99_BOUND_S,
            "errors_untyped": errors_untyped[:10],
            "gates": gates,
        }
        doc["elapsed_s"] = round(time.perf_counter() - t_bench, 1)
        Path(out_path).write_text(json.dumps(doc, indent=2) + "\n")
        log(f"[ingest] {gbps:.4f} GB/s over {t_ingest:.1f}s, "
            f"{serves['total']} serves p99 {p99 * 1000:.1f}ms, "
            f"{pinned_state['reads']} pinned reads across "
            f"{commits_while_pinned} commits -> {out_path}")
        for k, ok in gates.items():
            log(f"[ingest]   gate {k}: {'PASS' if ok else 'FAIL'}")
        return 0 if all(gates.values()) else 1
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main(smoke="--smoke" in sys.argv))
