"""Index-build throughput curve: GB/s/chip at SF0.1 / SF1 (and SF10 with
an argument), through BOTH build paths — in-memory (source fits the
budget) and streaming out-of-core (budget deliberately capped below the
source, so the row-group chunk pipeline with spill runs). Emits one JSON
line with the streaming GB/s at the largest scale and the full curve;
the gate is streaming staying within 2x of in-memory (the out-of-core
path must not fall off a cliff — CreateActionBase.scala:99-120 builds
from any-size sources via Spark's shuffle)."""

from __future__ import annotations

import json
import shutil
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
from benchmarks.harness import log  # noqa: E402

INDEXED = ["l_orderkey"]
INCLUDED = ["l_partkey", "l_quantity", "l_extendedprice", "l_discount"]


def _build(tmp: Path, data_root: Path, tag: str, budget: int | None):
    from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig
    from hyperspace_tpu.config import INDEX_BUILD_MEMORY_BUDGET
    from hyperspace_tpu.dataset import list_data_files
    from hyperspace_tpu.execution import io as hio

    session = HyperspaceSession(system_path=str(tmp / f"idx_{tag}"), num_buckets=64)
    if budget is not None:
        session.conf.set(INDEX_BUILD_MEMORY_BUDGET, budget)
    hs = Hyperspace(session)
    df = session.parquet(data_root)
    files = [fi.path for fi in list_data_files(data_root)]
    sel_bytes = hio.estimate_uncompressed_bytes(files, INDEXED + INCLUDED)
    t0 = time.perf_counter()
    hs.create_index(df, IndexConfig(f"bb_{tag}", INDEXED, INCLUDED))
    dt = time.perf_counter() - t0
    return sel_bytes, dt


def main(sfs=(0.1, 1.0)):
    from benchmarks.datagen import cached_tpch

    tmp = Path(tempfile.mkdtemp(prefix="hs_build_"))
    curve = []
    try:
        for sf in sfs:
            (li_root,) = cached_tpch(sf=sf, tables=("lineitem",))
            sel, t_mem = _build(tmp, li_root, f"mem{sf:g}", budget=None)
            # Streaming: cap the budget to ~1/8 of the source so the
            # chunked out-of-core path (spill + budget-bounded phase 2)
            # is what actually runs — the budget MUST be below the
            # source estimate or the in-memory path runs and the point
            # is mislabeled.
            budget = max(sel // 8, 8 << 20)
            assert budget < sel, (
                f"sf={sf}: source ({sel >> 20} MB) fits the streaming "
                f"budget ({budget >> 20} MB) — point would not stream"
            )
            _, t_stream = _build(tmp, li_root, f"str{sf:g}", budget=budget)
            point = {
                "sf": sf,
                "selected_gb": round(sel / 1e9, 3),
                "inmem_gbps": round(sel / 1e9 / t_mem, 4),
                "stream_gbps": round(sel / 1e9 / t_stream, 4),
                "stream_budget_mb": budget >> 20,
            }
            curve.append(point)
            log(f"sf={sf:g}: in-mem {t_mem:.2f}s ({point['inmem_gbps']} GB/s)  "
                f"streaming {t_stream:.2f}s ({point['stream_gbps']} GB/s, "
                f"budget {budget >> 20} MB)")
        last = curve[-1]
        if last["sf"] >= 1.0:
            # The docstring's gate: out-of-core must stay within 2x of
            # the in-memory throughput at the largest (real) scale.
            assert last["stream_gbps"] * 2 >= last["inmem_gbps"], last
        print(json.dumps({
            "metric": "index_build_streaming_gbps",
            "value": last["stream_gbps"],
            "unit": "GB/s/chip",
            "vs_baseline": round(last["stream_gbps"] / max(last["inmem_gbps"], 1e-9), 3),
            "curve": curve,
        }))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sfs = [float(a) for a in sys.argv[1:]] or [0.1, 1.0]
    main(tuple(sfs))
