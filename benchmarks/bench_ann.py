"""BASELINE config 5 analog: embedding-column ANN covering index.

Builds a vector index (k-means partitions, Pallas top-k probe) and
measures query throughput vs exact brute force, with recall@10 as the
quality gate. vs_baseline = speedup * recall (a fast-but-wrong index
scores low).
"""

from __future__ import annotations

import json
import shutil
import sys
import tempfile
import time
from pathlib import Path

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main(n: int = 500_000, dim: int = 128, partitions: int = 64, nprobe: int = 8):
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    from benchmarks.datagen import gen_embeddings
    from hyperspace_tpu import Hyperspace, HyperspaceSession, VectorIndexConfig

    tmp = Path(tempfile.mkdtemp(prefix="hs_benchann_"))
    try:
        emb = gen_embeddings(tmp / "emb", n, dim, clusters=partitions)
        session = HyperspaceSession(system_path=str(tmp / "indexes"))
        hs = Hyperspace(session)
        df = session.parquet(tmp / "emb")

        t0 = time.perf_counter()
        hs.create_vector_index(
            df, VectorIndexConfig("annidx", "emb", ["id"], num_partitions=partitions)
        )
        log(f"vector index build: {time.perf_counter() - t0:.2f}s for {n}x{dim}")

        rng = np.random.default_rng(9)
        queries = emb[rng.choice(n, 32, replace=False)] + 0.01

        session.enable_hyperspace()
        hs.ann_search(df, queries, k=10, nprobe=nprobe)  # warmup
        t0 = time.perf_counter()
        res = hs.ann_search(df, queries, k=10, nprobe=nprobe)
        t_idx = time.perf_counter() - t0

        session.disable_hyperspace()
        hs.ann_search(df, queries, k=10)  # warmup
        t0 = time.perf_counter()
        exact = hs.ann_search(df, queries, k=10)
        t_bf = time.perf_counter() - t0

        a = res.rows.columns["id"].reshape(len(queries), -1)
        e = exact.rows.columns["id"].reshape(len(queries), -1)
        recall = float(np.mean([len(set(a[i]) & set(e[i])) / e.shape[1] for i in range(len(queries))]))
        speedup = t_bf / t_idx
        log(f"indexed {t_idx*1000:.0f}ms  brute {t_bf*1000:.0f}ms  recall@10 {recall:.3f}")
        # MXU utilization evidence: the scoring matmul is ~2*q*m*d FLOPs
        # over the probed rows (round-1 weakness: wall clock only).
        probed_rows = n * nprobe / partitions
        flops = 2.0 * len(queries) * probed_rows * dim
        log(
            f"scoring matmul ~{flops / 1e9:.2f} GFLOP in {t_idx*1000:.0f}ms end-to-end "
            f"-> {flops / t_idx / 1e9:.2f} GFLOP/s achieved (query batches this small are "
            f"routing/transfer-latency-bound, not MXU-bound — the matmul itself is "
            f"microseconds at v5e peak)"
        )
        print(json.dumps({
            "metric": "ann_query_speedup_recall_weighted",
            "value": round(speedup * recall, 3),
            "unit": "x",
            "vs_baseline": round(speedup * recall, 3),
        }))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
