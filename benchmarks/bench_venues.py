"""Device-venue perf evidence: the same query classes on the host and
device venues, with the device venue measured COLD (first query after a
cache clear — pays staging) and WARM (repeat query — uploads served from
the HBM-resident cache). Emits one JSON line with the warm-over-cold
device speedup plus the per-class venue table, and writes a
jax.profiler trace of one warm device join for kernel inspection.

On tunneled deployments (device<->host link far below PCIe) the venue
chooser picks host for a reason; this artifact documents both sides of
that choice AND shows the repeat-query upload elimination the
HBM-resident container provides (SURVEY.md §2.3).
"""

from __future__ import annotations

import json
import shutil
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
from benchmarks.harness import log  # noqa: E402


def _run_timed(session, plan, reps=3):
    ts = []
    out = None
    for _ in range(reps):
        t0 = time.perf_counter()
        out = session.run(plan)
        ts.append(time.perf_counter() - t0)
    return min(ts), out


def main(n_rows: int = 4_000_000):
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from hyperspace_tpu import AggSpec, Hyperspace, HyperspaceSession, IndexConfig, col
    from hyperspace_tpu.config import AGG_VENUE, FILTER_VENUE, JOIN_VENUE
    from hyperspace_tpu.execution import device_cache as dc

    tmp = Path(tempfile.mkdtemp(prefix="hs_venues_"))
    try:
        rng = np.random.default_rng(77)
        fact = pa.table(
            {
                "k": rng.integers(0, 100_000, n_rows).astype(np.int32),
                "a": rng.random(n_rows, dtype=np.float32),
                "b": rng.normal(size=n_rows),
            }
        )
        dim = pa.table(
            {
                "k": np.arange(100_000, dtype=np.int32),
                "w": rng.normal(size=100_000),
            }
        )
        (tmp / "fact").mkdir(parents=True)
        (tmp / "dim").mkdir()
        pq.write_table(fact, tmp / "fact" / "p.parquet", row_group_size=1 << 20)
        pq.write_table(dim, tmp / "dim" / "p.parquet")

        session = HyperspaceSession(system_path=str(tmp / "idx"), num_buckets=16)
        hs = Hyperspace(session)
        f = session.parquet(tmp / "fact")
        d = session.parquet(tmp / "dim")
        t0 = time.perf_counter()
        hs.create_index(f, IndexConfig("vf_k", ["k"], ["a", "b"]))
        hs.create_index(d, IndexConfig("vd_k", ["k"], ["w"]))
        session.enable_hyperspace()
        log(f"venue bench index builds: {time.perf_counter() - t0:.2f}s ({n_rows} rows)")

        queries = {
            "filter": f.filter(((col("k") % 3) == 0) & (col("b") > 0.0))
                       .aggregate([], [AggSpec.of("count", None, "n")]),
            "join_agg": f.join(d, ["k"]).aggregate([], [AggSpec.of("sum", "w", "sw"),
                                                        AggSpec.of("count", None, "n")]),
            "group_agg": f.aggregate(["k"], [AggSpec.of("sum", "a", "sa"),
                                             AggSpec.of("count", None, "n")]),
            "point": f.filter(col("k") == 54_321),
        }
        # Logical input bytes each class must touch (the achieved-rate
        # denominators; these kernels are bandwidth-bound, so bytes/s is
        # the honest utilization figure — the ANN bench reports FLOP/s
        # where FLOPs dominate).
        n_dim = 100_000
        logical_bytes = {
            "filter": n_rows * (4 + 8),                 # k int32 + b f64
            "join_agg": n_rows * 4 + n_dim * (4 + 8),   # fact k + dim k,w
            "group_agg": n_rows * (4 + 4),              # k codes + a f32
        }

        table: dict[str, dict] = {}
        warm_speedups = []
        for name, plan in queries.items():
            row: dict = {}
            for venue in ("host", "device"):
                for key in (FILTER_VENUE, JOIN_VENUE, AGG_VENUE):
                    session.conf.set(key, venue)
                dc.clear_all()  # also zeroes hit/miss counters
                t_cold0 = time.perf_counter()
                out_cold = session.run(plan)
                t_cold = time.perf_counter() - t_cold0
                h_cold = dc.DEVICE_CACHE.stats()["hits"]
                t_warm, out_warm = _run_timed(session, plan)
                assert out_cold.num_rows == out_warm.num_rows
                row[f"{venue}_cold_s"] = round(t_cold, 4)
                row[f"{venue}_warm_s"] = round(t_warm, 4)
                if venue == "device":
                    st = dc.DEVICE_CACHE.stats()
                    # Hits attributable to THIS class's warm repeats only.
                    row["device_cache"] = {
                        "warm_hits": st["hits"] - h_cold,
                        "bytes": st["bytes"],
                    }
            sp = row["device_cold_s"] / max(row["device_warm_s"], 1e-9)
            row["device_warm_speedup"] = round(sp, 3)
            warm_speedups.append(sp)
            table[name] = row
            log(f"{name}: {row}")

        # Profiler trace of one warm device join (kernel evidence).
        trace_dir = tmp.parent / "hs_venue_trace"
        shutil.rmtree(trace_dir, ignore_errors=True)
        try:
            import jax

            for key in (FILTER_VENUE, JOIN_VENUE, AGG_VENUE):
                session.conf.set(key, "device")
            with jax.profiler.trace(str(trace_dir)):
                session.run(queries["join_agg"])
            log(f"profiler trace written to {trace_dir}")
        except Exception as e:  # tracing is evidence, not a gate
            log(f"profiler trace skipped: {e}")

        import numpy as np

        # Achieved bytes/s per flagship kernel, warm, both venues.
        kernel_rates = {}
        for name, nbytes in logical_bytes.items():
            row = table.get(name, {})
            for venue in ("device", "host"):
                t = row.get(f"{venue}_warm_s")
                if t:
                    kernel_rates[f"{name}_{venue}_warm_GBps"] = round(nbytes / 1e9 / t, 3)
        log(f"kernel_rates: {kernel_rates}")

        geo = float(np.exp(np.mean(np.log([max(s, 1e-9) for s in warm_speedups]))))
        print(json.dumps({
            "metric": "device_venue_warm_speedup",
            "value": round(geo, 3),
            "unit": "x",
            "vs_baseline": round(geo, 3),
            "classes": table,
            "kernel_rates": kernel_rates,
        }))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 4_000_000)
