"""Device-venue perf evidence: the same query classes on the host and
device venues, with the device venue measured COLD (first query after a
cache clear — pays staging) and WARM (repeat query — uploads served from
the HBM-resident cache). Emits one JSON document (pretty-printed) with the warm-over-cold
device speedup plus the per-class venue table, and writes a
jax.profiler trace of one warm device join for kernel inspection.

On tunneled deployments (device<->host link far below PCIe) the venue
chooser picks host for a reason; this artifact documents both sides of
that choice AND shows the repeat-query upload elimination the
HBM-resident container provides (SURVEY.md §2.3).

Hard gates (the BENCH_PIPELINE discipline, enforced on every run):

- **identical results** — every class's device-venue output must be
  BYTE-identical to the host reference (float payloads are dyadic
  rationals with bounded magnitude, so every partial sum is exactly
  representable and any reduction order must agree to the bit);
- **staged-bytes reduction** — with the Arrow→device zero-copy staging
  layer on, host-copied staging bytes across the classes must drop at
  least 2x vs the staging-off decode of the same reads
  (`device.stage.bytes_copied` / `bytes_zero_copy`);
- **group_agg warm speedup** — the device-venue warm repeat must beat
  its cold run by more than 1.2x (the staged channel/upload caches, not
  the cores, carry this — it binds on 1-CPU hosts too).
"""

from __future__ import annotations

import json
import shutil
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
from benchmarks.harness import log  # noqa: E402


def _canon_decoded(table):
    """Decoded columns in a deterministic row order for exact compare."""
    import numpy as np

    dec = table.decode()
    names = sorted(dec)
    if not names or table.num_rows == 0:
        return {k: np.asarray(v) for k, v in dec.items()}
    keys = []
    for n in reversed(names):
        v = np.asarray(dec[n])
        if v.dtype == object:
            v = v.astype("U64")
        elif v.dtype.kind == "f":
            v = np.nan_to_num(v.astype(np.float64), nan=-1e300)
        keys.append(v)
    order = np.lexsort(tuple(keys))
    return {k: np.asarray(v)[order] for k, v in dec.items()}


def assert_identical(host_table, device_table, label: str) -> None:
    """Byte-identical host-vs-device gate (bitwise on float payloads)."""
    import numpy as np

    ca, cb = _canon_decoded(host_table), _canon_decoded(device_table)
    assert set(ca) == set(cb), f"{label}: column sets differ"
    for name in ca:
        va, vb = ca[name], cb[name]
        assert len(va) == len(vb), f"{label}.{name}: row counts differ"
        if va.dtype.kind == "f" and vb.dtype.kind == "f":
            assert va.dtype == vb.dtype, f"{label}.{name}: dtypes differ"
            ints = f"i{va.dtype.itemsize}"
            assert np.array_equal(va.view(ints), vb.view(ints)), (
                f"{label}.{name}: device result not byte-identical to host"
            )
        else:
            assert np.array_equal(va, vb), f"{label}.{name}: values differ"


def _run_timed(session, plan, reps=3):
    ts = []
    out = None
    for _ in range(reps):
        t0 = time.perf_counter()
        out = session.run(plan)
        ts.append(time.perf_counter() - t0)
    return min(ts), out


def kernel_only(n_rows: int) -> dict:
    """Transfer-EXCLUDED device kernel timings: inputs pre-staged on the
    device (block_until_ready before the clock starts), outputs blocked
    on but never copied back — the achieved on-chip rate of the engine's
    flagship kernels, separated from the host<->device link cost that
    dominates the end-to-end venue table on tunneled deployments. The
    reference GB/s roof is the chip's HBM bandwidth (v5e ~819 GB/s;
    these kernels are bandwidth-bound)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from hyperspace_tpu.ops.aggregate import _segment_reduce_many
    from hyperspace_tpu.ops.join import join_counts

    rng = np.random.default_rng(5)
    # Staging rides the tunnel once; cap the resident set so a slow link
    # stages in seconds, not minutes (the timed kernels never touch it).
    n_rows = min(n_rows, 2_000_000)
    out: dict = {}

    def timed(fn, nbytes, reps=5):
        jax.block_until_ready(fn())  # compile + any residual staging
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(fn())
            ts.append(time.perf_counter() - t0)
        t = min(ts)
        return {"s": round(t, 5), "GBps": round(nbytes / 1e9 / t, 2),
                "spread_s": [round(x, 5) for x in ts]}

    # Bucketized sorted merge-join count kernel (the zero-exchange SMJ
    # probe): both key sides read once.
    B = 64
    L = max(n_rows // B, 1)
    lk = jnp.asarray(np.sort(rng.integers(0, 1 << 20, (B, L)).astype(np.int32), axis=1))
    rk = jnp.asarray(np.sort(rng.integers(0, 1 << 20, (B, L)).astype(np.int32), axis=1))
    jax.block_until_ready((lk, rk))
    out["join_counts"] = timed(lambda: join_counts(lk, rk), 2 * B * L * 4)

    # Grouped segment reduction (sum + sum-of-ones): values + gids read.
    n_pad = 1 << max((n_rows - 1).bit_length(), 1)
    vals = jnp.asarray(
        np.stack([rng.random(n_pad).astype(np.float32),
                  np.ones(n_pad, dtype=np.float32)])
    )
    gid = jnp.asarray(rng.integers(0, 100_000, n_pad).astype(np.int32))
    jax.block_until_ready((vals, gid))
    out["segment_reduce"] = timed(
        lambda: _segment_reduce_many(vals, gid, 131_072, ("sum", "sum")),
        n_pad * (2 * 4 + 4),
    )

    # Fused filter mask (one XLA elementwise program over two columns;
    # f32 staged explicitly — x64 is never enabled, so byte counts must
    # match the dtypes the device actually reads).
    k = jnp.asarray(rng.integers(0, 100_000, n_pad).astype(np.int32))
    b = jnp.asarray(rng.normal(size=n_pad).astype(np.float32))

    @jax.jit
    def mask_fn(kc, bc):
        return ((kc % 3) == 0) & (bc > 0.0)

    jax.block_until_ready((k, b))
    out["filter_mask"] = timed(lambda: mask_fn(k, b), int(k.nbytes) + int(b.nbytes))

    # The single-call timings above include one program DISPATCH, which
    # on a tunneled deployment is pure link latency (~0.1s RTT) and
    # swamps a microsecond kernel. Amortize it away: run the kernel K
    # times CHAINED inside one jitted fori_loop (iteration-dependent
    # constants keep XLA from hoisting the body), so kernel time is the
    # slope between a 1-iteration and a K-iteration program.
    from functools import partial

    K = 64

    @partial(jax.jit, static_argnames=("iters",))
    def filter_loop(kc, bc, iters: int):
        def body(i, acc):
            m = ((kc % 3) == 0) & (bc > i.astype(jnp.float32) * 1e-7)
            return acc + jnp.sum(m)

        return jax.lax.fori_loop(0, iters, body, jnp.int32(0))

    @partial(jax.jit, static_argnames=("iters",))
    def seg_loop(vals_, gid_, iters: int):
        def body(i, acc):
            # Shift the segment ids per iteration (fused elementwise — no
            # extra memory traffic): a scaled-values variant is LINEAR in
            # the scale and XLA hoists the whole reduce out of the loop;
            # a changing scatter pattern cannot fold.
            r = _segment_reduce_many(vals_, (gid_ + i) % 131_072, 131_072, ("sum", "sum"))
            return acc + r[0, 0]

        return jax.lax.fori_loop(0, iters, body, jnp.float32(0))

    @partial(jax.jit, static_argnames=("iters",))
    def join_loop(lk_, rk_, iters: int):
        def body(i, acc):
            c, _cum, tot = join_counts(lk_ + i, rk_)
            return acc + jnp.sum(tot)

        return jax.lax.fori_loop(0, iters, body, jnp.int32(0))

    def amortized(fn, nbytes, label):
        jax.block_until_ready(fn(1))
        jax.block_until_ready(fn(K))  # compile both variants
        t1s, tks = [], []
        for _ in range(3):
            t0 = time.perf_counter(); jax.block_until_ready(fn(1)); t1s.append(time.perf_counter() - t0)
            t0 = time.perf_counter(); jax.block_until_ready(fn(K)); tks.append(time.perf_counter() - t0)
        per_iter = (min(tks) - min(t1s)) / (K - 1)
        # Noise floor from run-to-run spread (NOT from t1 — t1 is the
        # dispatch latency the loop exists to amortize away).
        noise = (max(tks) - min(tks) + max(t1s) - min(t1s)) / (K - 1)
        row = {
            "t1_s": round(min(t1s), 5),
            "tK_s": round(min(tks), 5),
            "K": K,
        }
        if per_iter > max(2 * noise, 1e-7):
            # The K-iteration program scaled above noise: the slope is a
            # credible per-kernel time.
            row["s_per_iter"] = round(per_iter, 7)
            row["GBps"] = round(nbytes / 1e9 / per_iter, 2)
        else:
            # No scaling above noise: the kernel is below the measurable
            # floor (or the runtime elided the loop) — report the raw
            # walls rather than a fictional rate.
            row["note"] = "no scaling above noise; kernel below measurable floor on this backend"
        out[label] = row

    amortized(lambda it: filter_loop(k, b, it), int(k.nbytes) + int(b.nbytes),
              "filter_mask_amortized")
    amortized(lambda it: seg_loop(vals, gid, it), n_pad * (2 * 4 + 4),
              "segment_reduce_amortized")
    amortized(lambda it: join_loop(lk, rk, it), 2 * B * L * 4,
              "join_counts_amortized")
    out["hbm_roof_ref_GBps"] = 819  # v5e HBM roof for context
    return out


def main(n_rows: int = 4_000_000, out_path: str | None = None):
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from hyperspace_tpu import AggSpec, Hyperspace, HyperspaceSession, IndexConfig, col
    from hyperspace_tpu import stats
    from hyperspace_tpu.config import AGG_VENUE, FILTER_VENUE, JOIN_VENUE
    from hyperspace_tpu.execution import device_cache as dc
    from hyperspace_tpu.execution import io as hio
    from hyperspace_tpu.execution import staging

    tmp = Path(tempfile.mkdtemp(prefix="hs_venues_"))
    try:
        rng = np.random.default_rng(77)
        # Float payloads are DYADIC rationals (integer/2^10) of bounded
        # magnitude: every partial sum is exactly representable in
        # float64, so the identical-results gate can demand BITWISE
        # equality across venues and reduction orders — the same byte-
        # identity discipline BENCH_PIPELINE applies to build outputs.
        fact = pa.table(
            {
                "k": rng.integers(0, 100_000, n_rows).astype(np.int32),
                "a": (rng.integers(0, 1 << 20, n_rows) / 1024.0).astype(np.float32),
                "b": (rng.integers(-(1 << 20), 1 << 20, n_rows) / 1024.0).astype(np.float64),
            }
        )
        dim = pa.table(
            {
                "k": np.arange(100_000, dtype=np.int32),
                "w": (rng.integers(-(1 << 20), 1 << 20, 100_000) / 1024.0).astype(np.float64),
            }
        )
        (tmp / "fact").mkdir(parents=True)
        (tmp / "dim").mkdir()
        pq.write_table(fact, tmp / "fact" / "p.parquet", row_group_size=1 << 20)
        pq.write_table(dim, tmp / "dim" / "p.parquet")

        session = HyperspaceSession(system_path=str(tmp / "idx"), num_buckets=16)
        hs = Hyperspace(session)
        f = session.parquet(tmp / "fact")
        d = session.parquet(tmp / "dim")
        t0 = time.perf_counter()
        hs.create_index(f, IndexConfig("vf_k", ["k"], ["a", "b"]))
        hs.create_index(d, IndexConfig("vd_k", ["k"], ["w"]))
        session.enable_hyperspace()
        log(f"venue bench index builds: {time.perf_counter() - t0:.2f}s ({n_rows} rows)")

        queries = {
            "filter": f.filter(((col("k") % 3) == 0) & (col("b") > 0.0))
                       .aggregate([], [AggSpec.of("count", None, "n")]),
            "join_agg": f.join(d, ["k"]).aggregate([], [AggSpec.of("sum", "w", "sw"),
                                                        AggSpec.of("count", None, "n")]),
            "group_agg": f.aggregate(["k"], [AggSpec.of("sum", "a", "sa"),
                                             AggSpec.of("count", None, "n")]),
            "point": f.filter(col("k") == 54_321),
        }
        # Logical input bytes each class must touch (the achieved-rate
        # denominators; these kernels are bandwidth-bound, so bytes/s is
        # the honest utilization figure — the ANN bench reports FLOP/s
        # where FLOPs dominate).
        n_dim = 100_000
        logical_bytes = {
            "filter": n_rows * (4 + 8),                 # k int32 + b f64
            "join_agg": n_rows * 4 + n_dim * (4 + 8),   # fact k + dim k,w
            "group_agg": n_rows * (4 + 4),              # k codes + a f32
        }

        table: dict[str, dict] = {}
        warm_speedups = []
        gate_failures: list[str] = []
        for name, plan in queries.items():
            row: dict = {}
            outs: dict = {}
            for venue in ("host", "device"):
                for key in (FILTER_VENUE, JOIN_VENUE, AGG_VENUE):
                    session.conf.set(key, venue)
                dc.clear_all()  # also zeroes hit/miss counters
                t_cold0 = time.perf_counter()
                out_cold = session.run(plan)
                t_cold = time.perf_counter() - t_cold0
                h_cold = dc.DEVICE_CACHE.stats()["hits"]
                t_warm, out_warm = _run_timed(session, plan)
                assert out_cold.num_rows == out_warm.num_rows
                outs[venue] = out_warm
                row[f"{venue}_cold_s"] = round(t_cold, 4)
                row[f"{venue}_warm_s"] = round(t_warm, 4)
                if venue == "device":
                    st = dc.DEVICE_CACHE.stats()
                    # Hits attributable to THIS class's warm repeats only.
                    row["device_cache"] = {
                        "warm_hits": st["hits"] - h_cold,
                        "bytes": st["bytes"],
                    }
            # GATE (always enforced): device results byte-identical to
            # the host reference — dyadic payloads make bitwise equality
            # the correct bar, not a tolerance.
            assert_identical(outs["host"], outs["device"], name)
            row["identical_to_host"] = True
            sp = row["device_cold_s"] / max(row["device_warm_s"], 1e-9)
            row["device_warm_speedup"] = round(sp, 3)
            warm_speedups.append(sp)
            table[name] = row
            log(f"{name}: {row}")

        # GATE: group_agg warm repeats must beat cold by >1.2x — the
        # staged channel/stack/upload caches carry this (cache hits, not
        # cores), so it binds on single-CPU hosts too.
        ga_speedup = table["group_agg"]["device_warm_speedup"]
        if ga_speedup <= 1.2:
            gate_failures.append(
                f"group_agg device_warm_speedup {ga_speedup} <= 1.2"
            )

        # GATE: zero-copy staging must cut host-copied staging bytes at
        # least 2x vs the staging-off decode of the same reads.
        def staged_bytes(enabled: bool) -> dict:
            staging.set_enabled(enabled)
            hio.clear_table_cache()  # force a full re-decode (+ device caches)
            base = stats.snapshot()
            for cname in ("filter", "join_agg", "group_agg"):
                session.run(queries[cname])
            snap = stats.snapshot()
            return {
                "bytes_copied": snap["device.stage.bytes_copied"] - base["device.stage.bytes_copied"],
                "bytes_zero_copy": snap["device.stage.bytes_zero_copy"] - base["device.stage.bytes_zero_copy"],
            }

        for key in (FILTER_VENUE, JOIN_VENUE, AGG_VENUE):
            session.conf.set(key, "device")
        staging_row = {
            "disabled": staged_bytes(False),
            "enabled": staged_bytes(True),
        }
        staging.set_enabled(True)
        copied_off = staging_row["disabled"]["bytes_copied"]
        copied_on = max(staging_row["enabled"]["bytes_copied"], 1)
        staging_row["copied_reduction_x"] = round(copied_off / copied_on, 2)
        if copied_off < 2 * copied_on:
            gate_failures.append(
                f"staged copied-bytes reduction {staging_row['copied_reduction_x']}x < 2x"
            )
        staging_row["device_kernel"] = {
            "fused": stats.get("device.kernel.fused"),
            "fallbacks": stats.get("device.kernel.fallbacks"),
        }
        log(f"staging: {staging_row}")

        # Profiler trace of one warm device join (kernel evidence).
        trace_dir = tmp.parent / "hs_venue_trace"
        shutil.rmtree(trace_dir, ignore_errors=True)
        try:
            import jax

            for key in (FILTER_VENUE, JOIN_VENUE, AGG_VENUE):
                session.conf.set(key, "device")
            with jax.profiler.trace(str(trace_dir)):
                session.run(queries["join_agg"])
            log(f"profiler trace written to {trace_dir}")
        except Exception as e:  # tracing is evidence, not a gate
            log(f"profiler trace skipped: {e}")

        import numpy as np

        # Achieved bytes/s per flagship kernel, warm, both venues.
        kernel_rates = {}
        for name, nbytes in logical_bytes.items():
            row = table.get(name, {})
            for venue in ("device", "host"):
                t = row.get(f"{venue}_warm_s")
                if t:
                    kernel_rates[f"{name}_{venue}_warm_GBps"] = round(nbytes / 1e9 / t, 3)
        log(f"kernel_rates: {kernel_rates}")

        # Transfer-excluded device-resident kernel rates (the on-chip
        # story the end-to-end table cannot show through the tunnel).
        try:
            ko = kernel_only(n_rows)
            log(f"kernel_only (transfer-excluded): {ko}")
        except Exception as e:  # evidence, not a gate
            ko = {"error": str(e)}
            log(f"kernel_only skipped: {e}")

        geo = float(np.exp(np.mean(np.log([max(s, 1e-9) for s in warm_speedups]))))
        doc = {
            "metric": "device_venue_warm_speedup",
            "value": round(geo, 3),
            "unit": "x",
            "vs_baseline": round(geo, 3),
            "n_rows": n_rows,
            "classes": table,
            "staging": staging_row,
            "gates": {
                "identical_results": "enforced (bitwise, every class)",
                "group_agg_warm_speedup_min": 1.2,
                "staged_copied_reduction_min_x": 2.0,
                "failures": gate_failures,
            },
            "kernel_rates": kernel_rates,
            "kernel_only_device": ko,
        }
        rendered = json.dumps(doc, indent=1)
        print(rendered)
        if out_path:
            Path(out_path).write_text(rendered + "\n")
        if gate_failures:
            log(f"GATE FAILURES: {gate_failures}")
            return 1
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    args = [a for a in sys.argv[1:]]
    out = None
    n = 4_000_000
    rest = []
    it = iter(args)
    for a in it:
        if a == "--smoke":
            n = 400_000
        elif a == "--out":
            out = next(it)
        elif a.startswith("--out="):
            out = a.split("=", 1)[1]
        else:
            rest.append(a)
    if rest:
        n = int(rest[0])
    sys.exit(main(n, out_path=out))
