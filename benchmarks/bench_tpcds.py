"""TPC-DS slice benchmark: the 91 published queries of benchmarks/tpcds.py (+ tpcds_ext / tpcds_ext2)
with and without indexes, results REQUIRED identical both ways, timed
in storage-cold and warm regimes per side. Prints one JSON document
(pretty-printed) with the geomean speedups —
the artifact building toward BASELINE config 3 (SF1000 99-query
geomean)."""

from __future__ import annotations

import json
import shutil
import sys
import tempfile
import time
from pathlib import Path


sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
from benchmarks.harness import assert_same_results, log, timed as _timed


def main(sf: float = 1.0):
    import numpy as np

    from benchmarks.tpcds import cached_tpcds, tpcds_indexes, tpcds_queries
    from hyperspace_tpu import Hyperspace, HyperspaceSession

    tmp = Path(tempfile.mkdtemp(prefix="hs_tpcds_"))
    results = []
    try:
        roots = cached_tpcds(sf=sf)
        session = HyperspaceSession(system_path=str(tmp / "indexes"), num_buckets=64)
        hs = Hyperspace(session)
        scans = {name: session.parquet(root) for name, root in roots.items()}

        t0 = time.perf_counter()
        tpcds_indexes(hs, scans)
        log(f"tpcds index builds (sf={sf:g}): {time.perf_counter() - t0:.2f}s")

        queries = tpcds_queries(scans)
        speedups = []
        warm_speedups = []

        from hyperspace_tpu.execution import io as hio

        def drop_page_cache() -> bool:
            """Storage-cold: drop the OS page cache (root-only; standard
            cold-cache DB methodology). False when not permitted."""
            try:
                import os

                os.sync()
                with open("/proc/sys/vm/drop_caches", "w") as f:
                    f.write("3")
                return True
            except OSError:
                return False

        storage_cold = drop_page_cache()
        log(f"cold regime: page-cache drop {'ENABLED' if storage_cold else 'unavailable (engine caches only)'}")

        def best_of(fn, reps=2, cold=True):
            """One untimed warmup (compile caches only — code, not data),
            then the best of `reps` timed runs. `cold` clears the decoded
            table / device caches AND (when permitted) the OS page cache
            before EVERY timed run, so each rep pays real scan IO — the
            regime index pruning exists for, and the closest SF1 proxy of
            the SF1000 target where data cannot be RAM-resident. Warm
            repeats (cold=False) measure the steady-state serving path
            both sides' caches enable."""
            fn()
            times = []
            out = None
            for _ in range(reps):
                if cold:
                    hio.clear_table_cache()  # also drops the device caches
                    drop_page_cache()
                t, out = _timed(fn)
                times.append(t)
            return min(times), times, out

        for name, plan in queries.items():
            session.disable_hyperspace()
            t_raw, raw_times, r_raw = best_of(lambda p=plan: session.run(p))
            _, raw_warm, _ = best_of(lambda p=plan: session.run(p), cold=False)
            session.enable_hyperspace()
            t_idx, idx_times, r_idx = best_of(lambda p=plan: session.run(p))
            _, idx_warm, _ = best_of(lambda p=plan: session.run(p), cold=False)
            stats = dict(session.last_query_stats)

            assert_same_results(name, r_raw, r_idx)

            sp = t_raw / t_idx
            sp_warm = min(raw_warm) / min(idx_warm)
            speedups.append(sp)
            warm_speedups.append(sp_warm)
            log(
                f"{name}: raw {t_raw:.3f}s  indexed {t_idx:.3f}s  {sp:.2f}x  "
                f"(warm {sp_warm:.2f}x, rows={r_idx.num_rows}, join={stats['join_path']}, "
                f"agg={stats['agg_path']}, rows_pruned={stats.get('rows_pruned', 0)})"
            )
            results.append({
                "query": name,
                "speedup": round(sp, 3),
                "warm_speedup": round(sp_warm, 3),
                "raw_s": [round(t, 4) for t in raw_times],
                "indexed_s": [round(t, 4) for t in idx_times],
                "raw_warm_s": [round(t, 4) for t in raw_warm],
                "indexed_warm_s": [round(t, 4) for t in idx_warm],
            })

        geo = float(np.exp(np.mean(np.log(speedups))))
        geo_warm = float(np.exp(np.mean(np.log(warm_speedups))))
        print(json.dumps({
            "metric": "tpcds_slice_geomean_speedup",
            "value": round(geo, 3),
            "unit": "x",
            "vs_baseline": round(geo, 3),
            "warm_geomean_speedup": round(geo_warm, 3),
            "cold_regime": "storage-cold (page cache dropped per rep)" if storage_cold
                           else "engine-caches-cleared only",
            "queries": results,
        }, indent=1))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main(float(sys.argv[1]) if len(sys.argv) > 1 else 1.0)
