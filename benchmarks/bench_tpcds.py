"""TPC-DS slice benchmark: the 76 published queries of benchmarks/tpcds.py (+ tpcds_ext.py)
with and without indexes, results REQUIRED identical both ways, timed
warm best-of-2 per side. Prints one JSON line with the geomean speedup —
the artifact building toward BASELINE config 3 (SF1000 99-query
geomean)."""

from __future__ import annotations

import json
import shutil
import sys
import tempfile
import time
from pathlib import Path


sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
from benchmarks.harness import assert_same_results, log, timed as _timed


def main(sf: float = 1.0):
    import numpy as np

    from benchmarks.tpcds import cached_tpcds, tpcds_indexes, tpcds_queries
    from hyperspace_tpu import Hyperspace, HyperspaceSession

    tmp = Path(tempfile.mkdtemp(prefix="hs_tpcds_"))
    results = []
    try:
        roots = cached_tpcds(sf=sf)
        session = HyperspaceSession(system_path=str(tmp / "indexes"), num_buckets=64)
        hs = Hyperspace(session)
        scans = {name: session.parquet(root) for name, root in roots.items()}

        t0 = time.perf_counter()
        tpcds_indexes(hs, scans)
        log(f"tpcds index builds (sf={sf:g}): {time.perf_counter() - t0:.2f}s")

        queries = tpcds_queries(scans)
        speedups = []

        def best_of(fn, reps=2):
            """One untimed warmup (populates the decode/compile caches —
            the serving steady state BOTH sides enjoy), then the best of
            `reps` timed runs; the spread distinguishes contention noise
            from real regressions (single-core hosts)."""
            fn()
            times = []
            out = None
            for _ in range(reps):
                t, out = _timed(fn)
                times.append(t)
            return min(times), times, out

        for name, plan in queries.items():
            session.disable_hyperspace()
            t_raw, raw_times, r_raw = best_of(lambda p=plan: session.run(p))
            session.enable_hyperspace()
            t_idx, idx_times, r_idx = best_of(lambda p=plan: session.run(p))
            stats = dict(session.last_query_stats)

            assert_same_results(name, r_raw, r_idx)

            sp = t_raw / t_idx
            speedups.append(sp)
            log(
                f"{name}: raw {t_raw:.3f}s  indexed {t_idx:.3f}s  {sp:.2f}x  "
                f"(rows={r_idx.num_rows}, join={stats['join_path']}, "
                f"agg={stats['agg_path']}, rows_pruned={stats.get('rows_pruned', 0)})"
            )
            results.append({
                "query": name,
                "speedup": round(sp, 3),
                "raw_s": [round(t, 4) for t in raw_times],
                "indexed_s": [round(t, 4) for t in idx_times],
            })

        geo = float(np.exp(np.mean(np.log(speedups))))
        print(json.dumps({
            "metric": "tpcds_slice_geomean_speedup",
            "value": round(geo, 3),
            "unit": "x",
            "vs_baseline": round(geo, 3),
            "queries": results,
        }))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main(float(sys.argv[1]) if len(sys.argv) > 1 else 1.0)
