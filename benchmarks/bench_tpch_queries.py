"""A slice of real TPC-H queries through the full stack.

Q1 (pricing summary), Q6 (forecast revenue), a Q3-shaped join-aggregate
(top unshipped orders over orders x lineitem), Q12 with its REAL
predicates (l_shipmode IN ('MAIL','SHIP'), the commit/receipt date
comparisons), Q13 (customer LEFT JOIN orders with the NOT LIKE comment
exclusion, double aggregation), and Q14 (promo revenue share,
p_type LIKE 'PROMO%' inside the conditional aggregate) — expressed in
the plan IR with no CASE-WHEN workarounds, executed with and without
indexes, with results REQUIRED identical both ways. Prints one JSON line
per query plus the geomean.

Index design per query (what a Hyperspace user would build):
- Q1/Q6 filter on l_shipdate -> covering index keyed on l_shipdate
  (range pruning + searchsorted slicing serve the date window);
- Q3/Q12 join on the orderkey -> both sides bucketed on it with equal
  counts (zero-exchange SMJ; the aggregation fuses over it);
- Q13 join on custkey -> customer + orders bucketed on it (the LEFT
  join runs zero-exchange too);
- Q14 join on partkey -> lineitem + part bucketed on it.
"""

from __future__ import annotations

import datetime
import json
import shutil
import sys
import tempfile
import time
from pathlib import Path


sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
from benchmarks.harness import assert_same_results, log, timed as _timed  # noqa: E402


def days(iso: str) -> int:
    d = datetime.date.fromisoformat(iso)
    return (d - datetime.date(1970, 1, 1)).days


def main(sf: float = 1.0):
    import numpy as np

    from benchmarks.datagen import cached_tpch
    from hyperspace_tpu import AggSpec, Hyperspace, HyperspaceSession, IndexConfig, col, lit, when

    tmp = Path(tempfile.mkdtemp(prefix="hs_tpchq_"))
    results = []
    try:
        li_root, o_root, p_root, c_root = cached_tpch(
            sf=sf, tables=("lineitem", "orders", "part", "customer")
        )
        session = HyperspaceSession(system_path=str(tmp / "indexes"), num_buckets=64)
        hs = Hyperspace(session)
        li = session.parquet(li_root)
        orders = session.parquet(o_root)
        part = session.parquet(p_root)
        customer = session.parquet(c_root)

        t0 = time.perf_counter()
        hs.create_index(li, IndexConfig(
            "li_shipdate", ["l_shipdate"],
            ["l_returnflag", "l_linestatus", "l_quantity", "l_extendedprice",
             "l_discount", "l_tax"],
        ))
        hs.create_index(li, IndexConfig(
            "li_orderkey", ["l_orderkey"],
            ["l_extendedprice", "l_discount", "l_shipdate", "l_shipmode",
             "l_commitdate", "l_receiptdate"],
        ))
        hs.create_index(orders, IndexConfig(
            "o_orderkey", ["o_orderkey"], ["o_orderdate", "o_shippriority", "o_orderpriority"],
        ))
        hs.create_index(li, IndexConfig(
            "li_partkey", ["l_partkey"],
            ["l_shipdate", "l_extendedprice", "l_discount"],
        ))
        hs.create_index(part, IndexConfig("p_partkey", ["p_partkey"], ["p_type"]))
        hs.create_index(customer, IndexConfig("c_custkey", ["c_custkey"], []))
        hs.create_index(orders, IndexConfig(
            "o_custkey", ["o_custkey"], ["o_orderkey", "o_comment"],
        ))
        log(f"index builds (sf={sf:g}): {time.perf_counter() - t0:.2f}s")

        rev = col("l_extendedprice") * (lit(1.0) - col("l_discount"))
        queries = {
            # Q1: pricing summary report (shipdate <= 1998-09-02).
            "q1": li.filter(col("l_shipdate") <= lit(days("1998-09-02")))
                    .aggregate(
                        ["l_returnflag", "l_linestatus"],
                        [
                            AggSpec.of("sum", "l_quantity", "sum_qty"),
                            AggSpec.of("sum", "l_extendedprice", "sum_base_price"),
                            AggSpec.of("sum", rev, "sum_disc_price"),
                            AggSpec.of("sum", rev * (lit(1.0) + col("l_tax")), "sum_charge"),
                            AggSpec.of("mean", "l_quantity", "avg_qty"),
                            AggSpec.of("mean", "l_extendedprice", "avg_price"),
                            AggSpec.of("mean", "l_discount", "avg_disc"),
                            AggSpec.of("count", None, "count_order"),
                        ],
                    )
                    .sort(["l_returnflag", "l_linestatus"]),
            # Q6: forecast revenue change (one-year shipdate window).
            "q6": li.filter(
                        (col("l_shipdate") >= lit(days("1994-01-01")))
                        & (col("l_shipdate") < lit(days("1995-01-01")))
                        & (col("l_discount") >= lit(0.05))
                        & (col("l_discount") <= lit(0.07))
                        & (col("l_quantity") < lit(24.0))
                    )
                    .aggregate([], [AggSpec.of("sum", col("l_extendedprice") * col("l_discount"), "revenue")]),
            # Q3-shaped: top unshipped-order revenue (orders x lineitem).
            "q3": orders.select("o_orderkey", "o_orderdate", "o_shippriority")
                    .join(
                        li.select("l_orderkey", "l_extendedprice", "l_discount"),
                        ["o_orderkey"], ["l_orderkey"],
                    )
                    .aggregate(["o_orderkey"], [AggSpec.of("sum", rev, "revenue")])
                    .sort([("revenue", False), ("o_orderkey", True)])
                    .limit(10),
            # Q12: shipping-mode priority counts — the REAL predicate text:
            # l_shipmode IN ('MAIL','SHIP'), commit/receipt date column
            # comparisons, one receipt year; the conditional aggregate IS
            # the real query's CASE WHEN.
            "q12": orders.select("o_orderkey", "o_orderpriority")
                    .join(
                        li.select(
                            "l_orderkey", "l_shipmode", "l_shipdate",
                            "l_commitdate", "l_receiptdate",
                        ),
                        ["o_orderkey"], ["l_orderkey"],
                    )
                    .filter(
                        col("l_shipmode").isin(["MAIL", "SHIP"])
                        & (col("l_commitdate") < col("l_receiptdate"))
                        & (col("l_shipdate") < col("l_commitdate"))
                        & (col("l_receiptdate") >= lit(days("1994-01-01")))
                        & (col("l_receiptdate") < lit(days("1995-01-01")))
                    )
                    .aggregate(
                        ["l_shipmode"],
                        [
                            AggSpec.of(
                                "sum",
                                when(
                                    col("o_orderpriority").isin(["1-URGENT", "2-HIGH"]),
                                    1.0,
                                ).otherwise(0.0),
                                "high_line_count",
                            ),
                            AggSpec.of(
                                "sum",
                                when(
                                    col("o_orderpriority").isin(["1-URGENT", "2-HIGH"]),
                                    0.0,
                                ).otherwise(1.0),
                                "low_line_count",
                            ),
                        ],
                    )
                    .sort(["l_shipmode"]),
            # Q13: customer distribution — LEFT OUTER JOIN with the comment
            # exclusion in the join condition, then the count-of-counts.
            "q13": customer.select("c_custkey")
                    .join(
                        orders.select("o_custkey", "o_orderkey", "o_comment")
                              .filter(~col("o_comment").like("%special%requests%")),
                        ["c_custkey"], ["o_custkey"],
                        how="left",
                    )
                    .aggregate(["c_custkey"], [AggSpec.of("count", "o_orderkey", "c_count")])
                    .aggregate(["c_count"], [AggSpec.of("count", None, "custdist")])
                    .sort([("custdist", False), ("c_count", False)]),
            # Selective single-day revenue (a Q6-shaped point slice): the
            # equality on the bucket key prunes to ONE bucket file — the
            # file-pruning path must show up in the perf artifact, not
            # just unit tests (round-2 review ask #9).
            "q6s": li.filter(
                        (col("l_shipdate") == lit(days("1995-03-15")))
                        & (col("l_discount") >= lit(0.03))
                    )
                    .aggregate([], [AggSpec.of("sum", col("l_extendedprice") * col("l_discount"), "revenue"),
                                    AggSpec.of("count", None, "lines")]),
            # Q14: promo revenue share — p_type LIKE 'PROMO%' inside the
            # conditional aggregate, one shipdate month.
            "q14": li.select("l_partkey", "l_shipdate", "l_extendedprice", "l_discount")
                    .filter(
                        (col("l_shipdate") >= lit(days("1995-09-01")))
                        & (col("l_shipdate") < lit(days("1995-10-01")))
                    )
                    .join(part.select("p_partkey", "p_type"), ["l_partkey"], ["p_partkey"])
                    .aggregate(
                        [],
                        [
                            AggSpec.of(
                                "sum",
                                when(col("p_type").like("PROMO%"), rev).otherwise(0.0),
                                "promo_revenue",
                            ),
                            AggSpec.of("sum", rev, "total_revenue"),
                        ],
                    ),
        }

        speedups = []
        for name, plan in queries.items():
            session.disable_hyperspace()
            t_raw, r_raw = _timed(lambda p=plan: session.run(p))
            session.enable_hyperspace()
            t_idx, r_idx = _timed(lambda p=plan: session.run(p))
            stats = dict(session.last_query_stats)

            assert_same_results(name, r_raw, r_idx)

            if name == "q6s":
                # The selective query MUST exercise file pruning (the
                # point of including it in the artifact).
                assert stats["files_pruned"] > 0, ("q6s pruned no files", stats)
            sp = t_raw / t_idx
            speedups.append(sp)
            log(
                f"{name}: raw {t_raw:.3f}s  indexed {t_idx:.3f}s  {sp:.2f}x  "
                f"(rows={r_idx.num_rows}, files_pruned={stats['files_pruned']}, "
                f"rows_pruned={stats['rows_pruned']}, join={stats['join_path']}, "
                f"agg={stats['agg_path']})"
            )
            results.append({"query": name, "speedup": round(sp, 3)})

        geo = float(np.exp(np.mean(np.log(speedups))))
        print(json.dumps({
            "metric": "tpch_query_slice_geomean_speedup",
            "value": round(geo, 3),
            "unit": "x",
            "vs_baseline": round(geo, 3),
            "queries": results,
        }))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main(float(sys.argv[1]) if len(sys.argv) > 1 else 1.0)
