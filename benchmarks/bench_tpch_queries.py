"""A slice of real TPC-H queries through the full stack.

Q1 (pricing summary), Q6 (forecast revenue), a Q3-shaped join-aggregate
(top unshipped orders over orders x lineitem), and a Q12-shaped
join-count — expressed in the plan IR, executed with and without
indexes, with results REQUIRED identical both ways (and sanity-checked
against pandas). Prints one JSON line per query plus the geomean.

Index design per query (what a Hyperspace user would build):
- Q1/Q6 filter on l_shipdate -> covering index keyed on l_shipdate
  (range pruning + searchsorted slicing serve the date window);
- Q3/Q12 join on the orderkey -> both sides bucketed on it with equal
  counts (zero-exchange SMJ; the aggregation fuses over it).
"""

from __future__ import annotations

import datetime
import json
import shutil
import sys
import tempfile
import time
from pathlib import Path


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def days(iso: str) -> int:
    d = datetime.date.fromisoformat(iso)
    return (d - datetime.date(1970, 1, 1)).days


def _timed(fn, warmup=1, reps=2):
    for _ in range(warmup):
        out = fn()
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn()
    return (time.perf_counter() - t0) / reps, out


def main(sf: float = 1.0):
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    import numpy as np

    from benchmarks.datagen import cached_tpch
    from hyperspace_tpu import AggSpec, Hyperspace, HyperspaceSession, IndexConfig, col, lit, when

    tmp = Path(tempfile.mkdtemp(prefix="hs_tpchq_"))
    results = []
    try:
        li_root, o_root = cached_tpch(sf=sf)
        session = HyperspaceSession(system_path=str(tmp / "indexes"), num_buckets=64)
        hs = Hyperspace(session)
        li = session.parquet(li_root)
        orders = session.parquet(o_root)

        t0 = time.perf_counter()
        hs.create_index(li, IndexConfig(
            "li_shipdate", ["l_shipdate"],
            ["l_returnflag", "l_linestatus", "l_quantity", "l_extendedprice",
             "l_discount", "l_tax"],
        ))
        hs.create_index(li, IndexConfig(
            "li_orderkey", ["l_orderkey"],
            ["l_extendedprice", "l_discount", "l_shipdate", "l_shipmode", "l_receiptdate"],
        ))
        hs.create_index(orders, IndexConfig(
            "o_orderkey", ["o_orderkey"], ["o_orderdate", "o_shippriority", "o_orderpriority"],
        ))
        log(f"index builds (sf={sf:g}): {time.perf_counter() - t0:.2f}s")

        rev = col("l_extendedprice") * (lit(1.0) - col("l_discount"))
        queries = {
            # Q1: pricing summary report (shipdate <= 1998-09-02).
            "q1": li.filter(col("l_shipdate") <= lit(days("1998-09-02")))
                    .aggregate(
                        ["l_returnflag", "l_linestatus"],
                        [
                            AggSpec.of("sum", "l_quantity", "sum_qty"),
                            AggSpec.of("sum", "l_extendedprice", "sum_base_price"),
                            AggSpec.of("sum", rev, "sum_disc_price"),
                            AggSpec.of("sum", rev * (lit(1.0) + col("l_tax")), "sum_charge"),
                            AggSpec.of("mean", "l_quantity", "avg_qty"),
                            AggSpec.of("mean", "l_extendedprice", "avg_price"),
                            AggSpec.of("mean", "l_discount", "avg_disc"),
                            AggSpec.of("count", None, "count_order"),
                        ],
                    )
                    .sort(["l_returnflag", "l_linestatus"]),
            # Q6: forecast revenue change (one-year shipdate window).
            "q6": li.filter(
                        (col("l_shipdate") >= lit(days("1994-01-01")))
                        & (col("l_shipdate") < lit(days("1995-01-01")))
                        & (col("l_discount") >= lit(0.05))
                        & (col("l_discount") <= lit(0.07))
                        & (col("l_quantity") < lit(24.0))
                    )
                    .aggregate([], [AggSpec.of("sum", col("l_extendedprice") * col("l_discount"), "revenue")]),
            # Q3-shaped: top unshipped-order revenue (orders x lineitem).
            "q3": orders.select("o_orderkey", "o_orderdate", "o_shippriority")
                    .join(
                        li.select("l_orderkey", "l_extendedprice", "l_discount"),
                        ["o_orderkey"], ["l_orderkey"],
                    )
                    .aggregate(["o_orderkey"], [AggSpec.of("sum", rev, "revenue")])
                    .sort([("revenue", False), ("o_orderkey", True)])
                    .limit(10),
            # Q12: shipping-mode priority counts — conditional aggregates
            # (CASE WHEN o_orderpriority in high) over the join, filtered
            # to two ship modes and one receipt year.
            "q12": orders.select("o_orderkey", "o_orderpriority")
                    .join(
                        li.select("l_orderkey", "l_shipmode", "l_receiptdate"),
                        ["o_orderkey"], ["l_orderkey"],
                    )
                    .filter(
                        ((col("l_shipmode") == lit("MAIL")) | (col("l_shipmode") == lit("SHIP")))
                        & (col("l_receiptdate") >= lit(days("1994-01-01")))
                        & (col("l_receiptdate") < lit(days("1995-01-01")))
                    )
                    .aggregate(
                        ["l_shipmode"],
                        [
                            AggSpec.of(
                                "sum",
                                when(
                                    (col("o_orderpriority") == lit("1-URGENT"))
                                    | (col("o_orderpriority") == lit("2-HIGH")),
                                    1.0,
                                ).otherwise(0.0),
                                "high_line_count",
                            ),
                            AggSpec.of(
                                "sum",
                                when(
                                    (col("o_orderpriority") == lit("1-URGENT"))
                                    | (col("o_orderpriority") == lit("2-HIGH")),
                                    0.0,
                                ).otherwise(1.0),
                                "low_line_count",
                            ),
                        ],
                    )
                    .sort(["l_shipmode"]),
        }

        speedups = []
        for name, plan in queries.items():
            session.disable_hyperspace()
            t_raw, r_raw = _timed(lambda p=plan: session.run(p))
            session.enable_hyperspace()
            t_idx, r_idx = _timed(lambda p=plan: session.run(p))
            stats = dict(session.last_query_stats)

            a, b = r_raw.decode(), r_idx.decode()
            assert set(a) == set(b), (name, set(a), set(b))
            for c in a:
                av, bv = np.asarray(a[c]), np.asarray(b[c])
                assert len(av) == len(bv), (name, c, len(av), len(bv))
                if av.dtype.kind in "fc":
                    np.testing.assert_allclose(av, bv, rtol=1e-9, err_msg=f"{name}.{c}")
                else:
                    assert (av == bv).all(), (name, c)

            sp = t_raw / t_idx
            speedups.append(sp)
            log(
                f"{name}: raw {t_raw:.3f}s  indexed {t_idx:.3f}s  {sp:.2f}x  "
                f"(rows={r_idx.num_rows}, files_pruned={stats['files_pruned']}, "
                f"rows_pruned={stats['rows_pruned']}, join={stats['join_path']}, "
                f"agg={stats['agg_path']})"
            )
            results.append({"query": name, "speedup": round(sp, 3)})

        geo = float(np.exp(np.mean(np.log(speedups))))
        print(json.dumps({
            "metric": "tpch_query_slice_geomean_speedup",
            "value": round(geo, 3),
            "unit": "x",
            "vs_baseline": round(geo, 3),
            "queries": results,
        }))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main(float(sys.argv[1]) if len(sys.argv) > 1 else 1.0)
