"""BASELINE config 2 analog: orders ⋈ lineitem shuffle-free join.

Both sides carry a covering index bucketed on the join key with EQUAL
bucket counts, so the rewritten join runs per-bucket with zero exchange
(the reference's headline: ShuffleExchange count drops to 0,
JoinIndexRanker.scala:28-37). Prints one JSON line; vs_baseline normalizes
against 1x (parity with the un-indexed join) — higher is better.
"""

from __future__ import annotations

import json
import shutil
import sys
import tempfile
import time
from pathlib import Path


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main(sf: float = 1.0):
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    from benchmarks.datagen import cached_tpch
    from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig

    tmp = Path(tempfile.mkdtemp(prefix="hs_benchjoin_"))
    try:
        li_root, o_root = cached_tpch(sf=sf)
        session = HyperspaceSession(system_path=str(tmp / "indexes"), num_buckets=64)
        hs = Hyperspace(session)
        li = session.parquet(li_root)
        orders = session.parquet(o_root)

        t0 = time.perf_counter()
        hs.create_index(li, IndexConfig("li_ok", ["l_orderkey"], ["l_extendedprice", "l_discount"]))
        hs.create_index(orders, IndexConfig("o_ok", ["o_orderkey"], ["o_totalprice", "o_orderpriority"]))
        log(f"index builds (sf={sf:g}): {time.perf_counter() - t0:.2f}s")

        q = li.select("l_orderkey", "l_extendedprice").join(
            orders.select("o_orderkey", "o_totalprice", "o_orderpriority"),
            ["l_orderkey"], ["o_orderkey"],
        )

        session.enable_hyperspace()
        opt = session.optimized_plan(q)
        assert all(s.bucket_spec is not None for s in opt.leaves()), "join rewrite missed"
        n_idx = len(session.run(q).columns["l_orderkey"])  # warmup + count
        t0 = time.perf_counter()
        session.run(q)
        t_indexed = time.perf_counter() - t0
        assert session.last_query_stats["join_path"] == "zero-exchange-aligned"

        session.disable_hyperspace()
        n_no = len(session.run(q).columns["l_orderkey"])  # warmup + count
        t0 = time.perf_counter()
        session.run(q)
        t_noindex = time.perf_counter() - t0

        assert n_idx == n_no, f"result mismatch {n_idx} vs {n_no}"
        speedup = t_noindex / t_indexed
        log(f"indexed {t_indexed:.2f}s  no-index {t_noindex:.2f}s  rows={n_idx}")
        print(json.dumps({
            "metric": "tpch_join_shuffle_free_speedup",
            "value": round(speedup, 3),
            "unit": "x",
            "vs_baseline": round(speedup, 3),
        }))

        _bench_broadcast(session, sf, tmp)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _bench_broadcast(session, sf: float, tmp: Path):
    """Dimension join with NO index anywhere (the reference environment's
    BroadcastExchange fallback, PhysicalOperatorAnalyzer.scala:46-50):
    lineitem x part, small side probed vs both sides merge-sorted."""
    import time

    from benchmarks.datagen import cached_tpch
    from hyperspace_tpu.config import JOIN_BROADCAST_MAX_ROWS

    li_root, p_root = cached_tpch(sf=sf, tables=("lineitem", "part"))
    li = session.parquet(li_root)
    part = session.parquet(p_root)
    session.disable_hyperspace()
    q = li.select("l_partkey", "l_extendedprice").join(
        part.select("p_partkey", "p_brand"), ["l_partkey"], ["p_partkey"]
    )

    session.conf.set(JOIN_BROADCAST_MAX_ROWS, 0)
    n_merge = session.run(q).num_rows  # warmup
    t0 = time.perf_counter()
    session.run(q)
    t_merge = time.perf_counter() - t0
    assert session.last_query_stats["join_path"] == "single-partition"

    session.conf.set(JOIN_BROADCAST_MAX_ROWS, 4_000_000)
    n_bc = session.run(q).num_rows  # warmup
    t0 = time.perf_counter()
    session.run(q)
    t_bc = time.perf_counter() - t0
    assert session.last_query_stats["join_path"] == "broadcast-hash"
    assert n_bc == n_merge, (n_bc, n_merge)

    sp = t_merge / t_bc
    log(f"broadcast {t_bc:.2f}s  merge {t_merge:.2f}s  rows={n_bc}")
    print(json.dumps({
        "metric": "broadcast_dimension_join_speedup",
        "value": round(sp, 3),
        "unit": "x",
        "vs_baseline": round(sp, 3),
    }))


if __name__ == "__main__":
    main(float(sys.argv[1]) if len(sys.argv) > 1 else 1.0)
