"""BASELINE config 3 analog: Hybrid Scan — query freshness without refresh.

After appending files to an indexed dataset, hybrid scan lets the stale
index keep serving (index buckets ∪ raw appended files) until the next
incremental refresh. Measures the hybrid-scan query cost relative to the
fresh-index query AND asserts correctness against the full scan.
vs_baseline = full-scan time / hybrid time (how much of the index's value
survives staleness).
"""

from __future__ import annotations

import json
import shutil
import sys
import tempfile
import time
from pathlib import Path

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main(n: int = 8_000_000, append_n: int = 800_000):
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    from benchmarks.datagen import gen_lineitem
    from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig, col
    from hyperspace_tpu.config import INDEX_HYBRID_SCAN_ENABLED

    tmp = Path(tempfile.mkdtemp(prefix="hs_benchhybrid_"))
    try:
        data = tmp / "lineitem"
        gen_lineitem(data, n)
        session = HyperspaceSession(system_path=str(tmp / "indexes"), num_buckets=32)
        hs = Hyperspace(session)
        df = session.parquet(data)
        hs.create_index(df, IndexConfig("hidx", ["l_orderkey"], ["l_extendedprice"]))

        # Append ~10% new data WITHOUT refreshing.
        rng = np.random.default_rng(1)
        pq.write_table(
            pa.table(
                {
                    "l_orderkey": rng.integers(0, n // 4, append_n).astype(np.int64),
                    "l_partkey": rng.integers(0, 200_000, append_n).astype(np.int64),
                    "l_quantity": rng.integers(1, 51, append_n).astype(np.int64),
                    "l_extendedprice": (rng.random(append_n) * 100_000),
                    "l_discount": (rng.random(append_n) * 0.1),
                }
            ),
            data / "part-append.parquet",
        )
        session.conf.set(INDEX_HYBRID_SCAN_ENABLED, True)
        session.enable_hyperspace()

        keys = rng.integers(0, n // 4, 8)

        def run_queries():
            total = 0
            for kk in keys:
                q = df.filter(col("l_orderkey") == int(kk)).select(
                    "l_orderkey", "l_extendedprice"
                )
                total += len(session.run(q).columns["l_orderkey"])
            return total

        from hyperspace_tpu.execution import io as hio

        def cold() -> bool:
            """Storage-cold timed pass (the BENCH_TPCDS regime): engine
            caches cleared + page cache dropped, so the scan IO the
            hybrid index avoids is actually paid by the full scan.
            Returns False when the page-cache drop is not permitted."""
            hio.clear_table_cache()
            try:
                import os

                os.sync()
                with open("/proc/sys/vm/drop_caches", "w") as f:
                    f.write("3")
                return True
            except OSError:
                return False

        rows_hybrid = run_queries()  # warmup (compile)
        storage_cold = cold()
        t0 = time.perf_counter()
        rows_hybrid = run_queries()
        t_hybrid = time.perf_counter() - t0

        session.disable_hyperspace()
        rows_full = run_queries()  # warmup
        cold()
        t0 = time.perf_counter()
        rows_full = run_queries()
        t_full = time.perf_counter() - t0

        assert rows_hybrid == rows_full, f"hybrid results wrong: {rows_hybrid} vs {rows_full}"
        speedup = t_full / t_hybrid
        log(f"hybrid {t_hybrid:.2f}s  full-scan {t_full:.2f}s  rows={rows_hybrid}")
        print(json.dumps({
            "metric": "hybrid_scan_stale_index_speedup",
            "value": round(speedup, 3),
            "unit": "x",
            "vs_baseline": round(speedup, 3),
            "cold_regime": "storage-cold (page cache dropped)" if storage_cold
                           else "engine-caches-cleared only",
        }))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
