"""Synthetic data generators for the benchmark suite.

TPC-H-shaped tables (lineitem/orders with a shared orderkey domain), a
NYC-Taxi-shaped trips table for the incremental-refresh loop, and
clustered embeddings for the ANN config. Deterministic under a seed so
runs are comparable across rounds.
"""

from __future__ import annotations

from pathlib import Path

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq


def gen_lineitem(root: Path, n: int, seed: int = 42, orders: int | None = None) -> int:
    """lineitem-shaped parquet under root; returns byte size."""
    rng = np.random.default_rng(seed)
    orders = orders or n // 4
    t = pa.table(
        {
            "l_orderkey": rng.integers(0, orders, n).astype(np.int64),
            "l_partkey": rng.integers(0, 200_000, n).astype(np.int64),
            "l_quantity": rng.integers(1, 51, n).astype(np.int64),
            "l_extendedprice": (rng.random(n) * 100_000),
            "l_discount": (rng.random(n) * 0.1),
        }
    )
    root.mkdir(parents=True, exist_ok=True)
    pq.write_table(t, root / "part-0.parquet")
    return t.nbytes


def gen_orders(root: Path, n_orders: int, seed: int = 43) -> int:
    rng = np.random.default_rng(seed)
    t = pa.table(
        {
            "o_orderkey": np.arange(n_orders, dtype=np.int64),
            "o_custkey": rng.integers(0, n_orders // 10 + 1, n_orders).astype(np.int64),
            "o_totalprice": (rng.random(n_orders) * 500_000),
        }
    )
    root.mkdir(parents=True, exist_ok=True)
    pq.write_table(t, root / "part-0.parquet")
    return t.nbytes


def gen_trips_batch(root: Path, n: int, batch: int, seed: int = 50) -> int:
    """One append batch of taxi-trip-shaped rows (file per batch)."""
    rng = np.random.default_rng(seed + batch)
    t = pa.table(
        {
            "trip_id": (np.arange(n, dtype=np.int64) + batch * n),
            "zone": rng.integers(0, 265, n).astype(np.int64),
            "fare": (rng.random(n) * 80),
            "distance": (rng.random(n) * 30),
        }
    )
    root.mkdir(parents=True, exist_ok=True)
    pq.write_table(t, root / f"batch-{batch:04d}.parquet")
    return t.nbytes


def gen_embeddings(root: Path, n: int, dim: int, clusters: int, seed: int = 7) -> np.ndarray:
    """Clustered embedding table; returns the raw matrix for querying."""
    rng = np.random.default_rng(seed)
    centers = rng.standard_normal((clusters, dim)).astype(np.float32) * 4
    emb = centers[rng.integers(0, clusters, n)] + rng.standard_normal((n, dim)).astype(
        np.float32
    )
    t = pa.table(
        {
            "id": pa.array(np.arange(n, dtype=np.int64)),
            "emb": pa.FixedSizeListArray.from_arrays(
                pa.array(emb.reshape(-1), type=pa.float32()), dim
            ),
        }
    )
    root.mkdir(parents=True, exist_ok=True)
    pq.write_table(t, root / "part-0.parquet")
    return emb
