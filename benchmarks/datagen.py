"""Synthetic data generators for the benchmark suite.

TPC-H-shaped tables (lineitem/orders with a shared orderkey domain), a
NYC-Taxi-shaped trips table for the incremental-refresh loop, and
clustered embeddings for the ANN config. Deterministic under a seed so
runs are comparable across rounds.
"""

from __future__ import annotations

from pathlib import Path

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq


def gen_lineitem(root: Path, n: int, seed: int = 42, orders: int | None = None) -> int:
    """lineitem-shaped parquet under root; returns byte size."""
    rng = np.random.default_rng(seed)
    orders = orders or n // 4
    t = pa.table(
        {
            "l_orderkey": rng.integers(0, orders, n).astype(np.int64),
            "l_partkey": rng.integers(0, 200_000, n).astype(np.int64),
            "l_quantity": rng.integers(1, 51, n).astype(np.int64),
            "l_extendedprice": (rng.random(n) * 100_000),
            "l_discount": (rng.random(n) * 0.1),
        }
    )
    root.mkdir(parents=True, exist_ok=True)
    pq.write_table(t, root / "part-0.parquet")
    return t.nbytes


def gen_orders(root: Path, n_orders: int, seed: int = 43) -> int:
    rng = np.random.default_rng(seed)
    t = pa.table(
        {
            "o_orderkey": np.arange(n_orders, dtype=np.int64),
            "o_custkey": rng.integers(0, n_orders // 10 + 1, n_orders).astype(np.int64),
            "o_totalprice": (rng.random(n_orders) * 500_000),
        }
    )
    root.mkdir(parents=True, exist_ok=True)
    pq.write_table(t, root / "part-0.parquet")
    return t.nbytes


def gen_trips_batch(root: Path, n: int, batch: int, seed: int = 50) -> int:
    """One append batch of taxi-trip-shaped rows (file per batch)."""
    rng = np.random.default_rng(seed + batch)
    t = pa.table(
        {
            "trip_id": (np.arange(n, dtype=np.int64) + batch * n),
            "zone": rng.integers(0, 265, n).astype(np.int64),
            "fare": (rng.random(n) * 80),
            "distance": (rng.random(n) * 30),
        }
    )
    root.mkdir(parents=True, exist_ok=True)
    pq.write_table(t, root / f"batch-{batch:04d}.parquet")
    return t.nbytes


TPCH_SF1_LINEITEM_ROWS = 6_001_215
TPCH_SF1_ORDERS_ROWS = 1_500_000

_RETURNFLAGS = np.array(["A", "N", "R"], dtype=object)
_LINESTATUS = np.array(["F", "O"], dtype=object)
_SHIPINSTRUCT = np.array(
    ["COLLECT COD", "DELIVER IN PERSON", "NONE", "TAKE BACK RETURN"], dtype=object
)
_SHIPMODE = np.array(
    ["AIR", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP", "TRUCK"], dtype=object
)
_ORDERPRIORITY = np.array(
    ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"], dtype=object
)
_ORDERSTATUS = np.array(["F", "O", "P"], dtype=object)
_EPOCH_1992 = 8035  # days from 1970-01-01 to 1992-01-01
_DATE_SPAN = 2525  # order dates span 1992-01-01 .. 1998-12-01 (TPC-H 4.2.3)


def _write_parts(t: "pa.Table", root: Path, files: int) -> None:
    """Chunked parquet write shared by every TPC-H table generator."""
    root.mkdir(parents=True, exist_ok=True)
    per = (t.num_rows + files - 1) // files
    for i in range(files):
        part = t.slice(i * per, per)
        if part.num_rows:
            pq.write_table(part, root / f"part-{i}.parquet", row_group_size=262_144)


def gen_tpch_lineitem(
    root: Path, sf: float = 1.0, seed: int = 42, files: int | None = None
) -> int:
    """TPC-H-faithful lineitem: full 16-column schema (ints, decimals as
    float64, 1-char flags, dates, mode/instruction strings, comments),
    ~4 lines per order (SF1 ≈ 6.0M rows). Generated CHUNK BY CHUNK —
    each file covers a contiguous order range with its own derived seed
    — so peak memory stays one chunk regardless of scale factor (SF10+
    would not fit a full-table build). Deterministic under the seed;
    returns total in-memory byte size."""
    n_orders = int(TPCH_SF1_ORDERS_ROWS * sf)
    if files is None:
        files = max(8, int(round(8 * sf)))
    root.mkdir(parents=True, exist_ok=True)
    per_orders = (n_orders + files - 1) // files
    total = 0
    for i in range(files):
        o0, o1 = i * per_orders, min((i + 1) * per_orders, n_orders)
        if o0 >= o1:
            break
        rng = np.random.default_rng(seed + 7919 * i)
        # ~4 lines per order: repeat each orderkey a random 1-7 times.
        orderkey = np.repeat(
            np.arange(o0, o1, dtype=np.int64), rng.integers(1, 8, o1 - o0)
        )
        m = len(orderkey)
        shipdate = (
            _EPOCH_1992 + rng.integers(0, _DATE_SPAN, m) + rng.integers(1, 122, m)
        ).astype(np.int32)
        quantity = rng.integers(1, 51, m).astype(np.float64)
        extendedprice = np.round(quantity * (900 + rng.random(m) * 100_000) / 100, 2)
        comments = np.char.add(
            np.char.add(
                _SHIPMODE[rng.integers(0, len(_SHIPMODE), m)].astype(str), " carefully "
            ),
            _SHIPINSTRUCT[rng.integers(0, 4, m)].astype(str),
        )
        t = pa.table(
            {
                "l_orderkey": orderkey,
                "l_partkey": rng.integers(0, int(200_000 * max(sf, 0.01)), m).astype(np.int64),
                "l_suppkey": rng.integers(0, int(10_000 * max(sf, 0.01)), m).astype(np.int64),
                "l_linenumber": np.ones(m, dtype=np.int32),
                "l_quantity": quantity,
                "l_extendedprice": extendedprice,
                "l_discount": np.round(rng.integers(0, 11, m) / 100.0, 2),
                "l_tax": np.round(rng.integers(0, 9, m) / 100.0, 2),
                "l_returnflag": pa.array(_RETURNFLAGS[rng.integers(0, 3, m)]),
                "l_linestatus": pa.array(_LINESTATUS[(shipdate > _EPOCH_1992 + 1260).astype(int)]),
                "l_shipdate": pa.array(shipdate, type=pa.date32()),
                "l_commitdate": pa.array(shipdate + rng.integers(-30, 31, m).astype(np.int32), type=pa.date32()),
                "l_receiptdate": pa.array(shipdate + rng.integers(1, 31, m).astype(np.int32), type=pa.date32()),
                "l_shipinstruct": pa.array(_SHIPINSTRUCT[rng.integers(0, 4, m)]),
                "l_shipmode": pa.array(_SHIPMODE[rng.integers(0, 7, m)]),
                "l_comment": pa.array(comments.astype(object)),
            }
        )
        pq.write_table(t, root / f"part-{i}.parquet", row_group_size=262_144)
        total += t.nbytes
    return total


def gen_tpch_orders(root: Path, sf: float = 1.0, seed: int = 43, files: int | None = None) -> int:
    """TPC-H-faithful orders (9 columns, SF1 = 1.5M rows), generated
    chunk by chunk like lineitem."""
    n = int(TPCH_SF1_ORDERS_ROWS * sf)
    if files is None:
        files = max(4, int(round(4 * sf)))
    root.mkdir(parents=True, exist_ok=True)
    per = (n + files - 1) // files
    total = 0
    for i in range(files):
        k0, k1 = i * per, min((i + 1) * per, n)
        if k0 >= k1:
            break
        rng = np.random.default_rng(seed + 7919 * i)
        m = k1 - k0
        orderdate = (_EPOCH_1992 + rng.integers(0, _DATE_SPAN, m)).astype(np.int32)
        t = pa.table(
            {
                "o_orderkey": np.arange(k0, k1, dtype=np.int64),
                "o_custkey": rng.integers(0, n // 10 + 1, m).astype(np.int64),
                "o_orderstatus": pa.array(_ORDERSTATUS[rng.integers(0, 3, m)]),
                "o_totalprice": np.round(rng.random(m) * 500_000, 2),
                "o_orderdate": pa.array(orderdate, type=pa.date32()),
                "o_orderpriority": pa.array(_ORDERPRIORITY[rng.integers(0, 5, m)]),
                "o_clerk": pa.array(
                    np.char.add("Clerk#", rng.integers(1, 1001, m).astype("U6")).astype(object)
                ),
                "o_shippriority": np.zeros(m, dtype=np.int32),
                # ~1.2% of comments match Q13's '%special%requests%' exclusion.
                "o_comment": pa.array(
                    np.where(
                        rng.random(m) < 0.012,
                        "the special packages wake furiously among the requests",
                        np.char.add(
                            _ORDERPRIORITY[rng.integers(0, 5, m)].astype(str),
                            " instructions sleep quickly",
                        ).astype(object),
                    ).astype(object)
                ),
            }
        )
        pq.write_table(t, root / f"part-{i}.parquet", row_group_size=262_144)
        total += t.nbytes
    return total


TPCH_SF1_PART_ROWS = 200_000
TPCH_SF1_CUSTOMER_ROWS = 150_000

_P_TYPE_1 = np.array(["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"], dtype=object)
_P_TYPE_2 = np.array(["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"], dtype=object)
_P_TYPE_3 = np.array(["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"], dtype=object)
_SEGMENTS = np.array(
    ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"], dtype=object
)


def gen_tpch_part(root: Path, sf: float = 1.0, seed: int = 44, files: int = 2) -> int:
    """TPC-H part (SF1 = 200k rows): p_type is the three-word TPC-H shape
    ('PROMO BURNISHED COPPER'), so Q14's `like 'PROMO%'` is faithful."""
    n = int(TPCH_SF1_PART_ROWS * sf)
    rng = np.random.default_rng(seed)
    ptype = np.char.add(
        np.char.add(
            np.char.add(_P_TYPE_1[rng.integers(0, 6, n)].astype(str), " "),
            np.char.add(_P_TYPE_2[rng.integers(0, 5, n)].astype(str), " "),
        ),
        _P_TYPE_3[rng.integers(0, 5, n)].astype(str),
    )
    t = pa.table(
        {
            "p_partkey": np.arange(n, dtype=np.int64),
            "p_name": pa.array(
                np.char.add("part ", rng.integers(0, 100_000, n).astype("U6")).astype(object)
            ),
            "p_brand": pa.array(
                np.char.add("Brand#", rng.integers(11, 56, n).astype("U2")).astype(object)
            ),
            "p_type": pa.array(ptype.astype(object)),
            "p_size": rng.integers(1, 51, n).astype(np.int32),
            "p_container": pa.array(
                np.char.add("JUMBO ", _P_TYPE_3[rng.integers(0, 5, n)].astype(str)).astype(object)
            ),
            "p_retailprice": np.round(900 + rng.random(n) * 1000, 2),
        }
    )
    _write_parts(t, root, files)
    return t.nbytes


def gen_tpch_customer(root: Path, sf: float = 1.0, seed: int = 45, files: int = 2) -> int:
    """TPC-H customer (SF1 = 150k rows). c_custkey aligns with orders'
    o_custkey domain; ~1% of Q13-facing comments would match
    '%special%requests%' via the ORDERS comment (this table carries the
    phone/segment columns Q22-style queries read)."""
    n = int(TPCH_SF1_CUSTOMER_ROWS * sf)
    rng = np.random.default_rng(seed)
    t = pa.table(
        {
            "c_custkey": np.arange(n, dtype=np.int64),
            "c_name": pa.array(
                np.char.add("Customer#", np.arange(n).astype("U9")).astype(object)
            ),
            "c_phone": pa.array(
                np.char.add(
                    np.char.add(rng.integers(10, 35, n).astype("U2"), "-555-"),
                    rng.integers(1000, 10000, n).astype("U4"),
                ).astype(object)
            ),
            "c_acctbal": np.round(rng.random(n) * 10_000 - 1_000, 2),
            "c_mktsegment": pa.array(_SEGMENTS[rng.integers(0, 5, n)]),
            "c_nationkey": rng.integers(0, 25, n).astype(np.int32),
        }
    )
    _write_parts(t, root, files)
    return t.nbytes


_TPCH_GENS = {
    "lineitem": gen_tpch_lineitem,
    "orders": gen_tpch_orders,
    "part": gen_tpch_part,
    "customer": gen_tpch_customer,
}


def cached_tpch(
    sf: float = 1.0,
    cache_root: Path | None = None,
    tables: tuple[str, ...] = ("lineitem", "orders"),
) -> tuple[Path, ...]:
    """Generate (or reuse) the requested TPC-H tables under a cache dir
    keyed by scale factor; bench reruns skip the ~20s generation.
    Returns one root per requested table, in order."""
    import tempfile

    import shutil

    # v3: chunked (memory-bounded) lineitem/orders generation.
    base = cache_root or Path(tempfile.gettempdir()) / f"hs_tpch_v3_sf{sf:g}"
    roots = []
    # A _COMPLETE marker written AFTER generation guards against reusing a
    # partial dataset from an interrupted run.
    for name in tables:
        root = base / name
        if not (root / "_COMPLETE").exists():
            shutil.rmtree(root, ignore_errors=True)
            _TPCH_GENS[name](root, sf)
            (root / "_COMPLETE").touch()
        roots.append(root)
    return tuple(roots)


def gen_embeddings(root: Path, n: int, dim: int, clusters: int, seed: int = 7) -> np.ndarray:
    """Clustered embedding table; returns the raw matrix for querying."""
    rng = np.random.default_rng(seed)
    centers = rng.standard_normal((clusters, dim)).astype(np.float32) * 4
    emb = centers[rng.integers(0, clusters, n)] + rng.standard_normal((n, dim)).astype(
        np.float32
    )
    t = pa.table(
        {
            "id": pa.array(np.arange(n, dtype=np.int64)),
            "emb": pa.FixedSizeListArray.from_arrays(
                pa.array(emb.reshape(-1), type=pa.float32()), dim
            ),
        }
    )
    root.mkdir(parents=True, exist_ok=True)
    pq.write_table(t, root / "part-0.parquet")
    return emb
