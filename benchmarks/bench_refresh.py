"""BASELINE config 4 analog: NYC-Taxi incremental ingest loop.

Repeated cycle: append a batch of trip files → incremental refresh (delta
buckets only) → point query (hybrid multi-version read) → periodic
optimize (compaction). The metric is sustained ingest throughput through
the refresh path; vs_baseline compares incremental refresh against what
full rebuilds of the grown dataset would have cost.
"""

from __future__ import annotations

import json
import shutil
import sys
import tempfile
import time
from pathlib import Path


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main(batch_rows: int = 250_000, batches: int = 6):
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    from benchmarks.datagen import gen_trips_batch
    from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig, col

    tmp = Path(tempfile.mkdtemp(prefix="hs_benchrefresh_"))
    try:
        data = tmp / "trips"
        total_bytes = gen_trips_batch(data, batch_rows, 0)
        session = HyperspaceSession(system_path=str(tmp / "indexes"), num_buckets=32)
        hs = Hyperspace(session)
        df = session.parquet(data)
        hs.create_index(df, IndexConfig("trips_zone", ["zone"], ["fare", "distance"]))
        session.enable_hyperspace()

        t_inc_total = 0.0
        for b in range(1, batches):
            total_bytes += gen_trips_batch(data, batch_rows, b)
            t0 = time.perf_counter()
            hs.refresh_index("trips_zone", mode="incremental")
            t_inc = time.perf_counter() - t0
            t_inc_total += t_inc
            q = df.filter(col("zone") == 42).select("zone", "fare")
            rows = len(session.run(q).columns["zone"])
            log(f"batch {b}: incremental refresh {t_inc:.2f}s, query rows={rows}")
            if b == batches // 2:
                t0 = time.perf_counter()
                hs.optimize_index("trips_zone")
                log(f"  optimize (compaction): {time.perf_counter() - t0:.2f}s")

        # Reference cost: full rebuild per batch on the grown dataset.
        t0 = time.perf_counter()
        hs.refresh_index("trips_zone")  # one full rebuild at final size
        t_full = time.perf_counter() - t0
        est_full_total = t_full * (batches - 1)
        log(f"incremental total {t_inc_total:.2f}s vs est. full-rebuild total {est_full_total:.2f}s")

        ingest_gbps = (total_bytes / 1e9) / t_inc_total
        print(json.dumps({
            "metric": "taxi_incremental_ingest_throughput",
            "value": round(ingest_gbps, 4),
            "unit": "GB/s",
            "vs_baseline": round(est_full_total / t_inc_total, 3),
        }))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
