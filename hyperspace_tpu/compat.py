"""Version-compatibility shims for jax's fragile import surface.

jax renames and relocates public symbols across minor versions; the cost
of importing them directly is not a graceful degradation but a module
that fails to IMPORT — the seed shipped a bare ``from jax import
shard_map`` that produced 66 collection errors and ~200 cascading test
failures on jax 0.4.37. Every symbol jax has moved (or is likely to
move) is resolved HERE and nowhere else:

- ``shard_map``: ``jax.shard_map`` (new public API) falling back to
  ``jax.experimental.shard_map.shard_map`` (0.4.x). Callers always use
  the NEW kwarg spelling ``check_vma=``; the shim renames it to the
  older ``check_rep=`` when the resolved function predates the rename.
- Pallas: ``resolve_pallas()`` returns the ``pallas`` module from its
  current home (``jax.experimental.pallas`` today).
- ``jit``: the package's one jit entry point. Same surface as
  ``jax.jit``, plus a ``key=`` call-site identity used by the runtime
  health plane (``obs/runtime.py``) to count compiles per call site and
  detect recompile storms while the process runs — the dynamic mirror
  of lint rule HSL015, and the observable form of the XLA:CPU
  map-count segfault ``utils/jit_memory.py`` guards against.

The trace-safety linter (``analysis/lint.py``, rule HSL001) makes this
arrangement permanent: any ``from jax import shard_map`` or
``jax.experimental`` use outside this module is a lint error, and the CI
gate runs the linter over the package — so the seed's breakage class
cannot be reintroduced by a future PR.
"""

from __future__ import annotations

import functools
import inspect

#: The bounded signature-space registry (static-analysis rule HSL024,
#: analysis/tracedomain.py). Every value that reaches a jit static
#: argument must range over a declared bounded domain, or each new value
#: mints a fresh compile — the static dual of the runtime
#: ``jit.recompile_storm`` detector in obs/runtime.py. Keys are static
#: argument / enum parameter names; values describe the domain (a tuple
#: enumerates it exactly). AST-extracted by the analyzer like
#: ``faults.KNOWN_POINTS`` — keep it a plain literal of constants.
KNOWN_STATIC_DOMAINS = {
    # jit static argument names (bounded by construction at their sites)
    "cap": "pow2-rounded expansion capacity (join_expand)",
    "m_pad": "pow2-rounded pair-buffer length (join _compact_pairs)",
    "shift": "bit width from pack_shift — at most 64",
    "num_segments": "tile-rounded group count (aggregate/join_agg)",
    "channels": "per-spec channel count — bounded by the plan",
    "fns": "reduction-kind tuple drawn from the AggSpec vocabulary",
    "iters": "Lloyd iteration count — a config-bounded small int",
    # enum parameters that select a compiled variant
    "venue": ("auto", "device", "host"),
    "fused": ("auto", "off"),
    "impl": ("auto", "pallas", "lax"),
}


def _resolve_shard_map():
    import jax

    sm = getattr(jax, "shard_map", None)
    if sm is not None and not callable(sm):
        # Some versions expose jax.shard_map as a MODULE holding the fn.
        sm = getattr(sm, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm  # noqa: HSL001
    return sm


_SHARD_MAP = _resolve_shard_map()
try:
    _SHARD_MAP_PARAMS = frozenset(inspect.signature(_SHARD_MAP).parameters)
except (TypeError, ValueError):
    # No introspectable signature: assume the modern kwarg surface.
    _SHARD_MAP_PARAMS = frozenset()


def shard_map(f=None, **kwargs):
    """``jax.shard_map`` with the modern kwarg surface on every jax.

    Accepts the new-style ``check_vma=`` kwarg and rewrites it to the
    pre-rename ``check_rep=`` when the installed jax wants that. Usable
    directly or through ``functools.partial(shard_map, mesh=..., ...)``
    as a decorator (the call style ops/* use); calling with the keyword
    arguments alone returns a decorator, matching jax's own behavior.
    """
    if (
        "check_vma" in kwargs
        and _SHARD_MAP_PARAMS
        and "check_vma" not in _SHARD_MAP_PARAMS
    ):
        kwargs["check_rep"] = kwargs.pop("check_vma")
    if f is None:
        return functools.partial(shard_map, **kwargs)
    return _SHARD_MAP(f, **kwargs)


def jit(fn=None, *, key: "str | None" = None, **jit_kwargs):
    """``jax.jit`` with per-call-site compile accounting (obs/runtime.py).

    Usable exactly like ``jax.jit``: as a decorator, through
    ``functools.partial(jit, static_argnames=...)``, or called directly
    on a function. ``key`` names the call site in the runtime jit
    report and in recompile-storm events; it defaults to the wrapped
    function's module-qualified name — pass it explicitly when the
    function is a lambda or a local closure (whose qualnames collide).
    """
    if fn is None:
        return functools.partial(jit, key=key, **jit_kwargs)
    import jax

    from hyperspace_tpu.obs import runtime as obs_runtime

    if key is None:
        module = getattr(fn, "__module__", None) or "<unknown>"
        qual = getattr(fn, "__qualname__", None) or getattr(fn, "__name__", "<fn>")
        key = f"{module}.{qual}"
    return obs_runtime.instrument(jax.jit(fn, **jit_kwargs), key)


def enable_x64(new_val: bool = True):
    """Scoped-x64 context manager: ``jax.enable_x64`` (new public API)
    falling back to ``jax.experimental.enable_x64`` (0.4.x)."""
    import jax

    ctx = getattr(jax, "enable_x64", None)
    if ctx is None:
        from jax.experimental import enable_x64 as ctx  # noqa: HSL001
    return ctx(new_val)


def resolve_pallas():
    """The Pallas module, wherever this jax puts it. Kernel factories
    import it lazily through here (Pallas is optional at runtime — the
    topk kernel falls back to lax.top_k when lowering fails)."""
    from jax.experimental import pallas  # noqa: HSL001

    return pallas
