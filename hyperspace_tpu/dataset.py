"""Dataset registration: the analog of a Spark relation over data-lake files.

A Dataset is a parquet directory with a derived Schema; `scan()` yields the
plan leaf. File enumeration returns (path, size, mtime) triples — the
identity the signature provider fingerprints (reference collects
`PartitioningAwareFileIndex.allFiles` at actions/CreateActionBase.scala:89-97).
"""

from __future__ import annotations

import dataclasses
from pathlib import Path

from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.metadata.log_entry import FileInfo
from hyperspace_tpu.plan.nodes import Scan
from hyperspace_tpu.schema import Schema


def list_data_files(root: str | Path, suffix: str = ".parquet") -> list[FileInfo]:
    """Recursively list data files under `root`, sorted by path."""
    root = Path(root)
    if root.is_file():
        st = root.stat()
        return [FileInfo(str(root), st.st_size, st.st_mtime_ns)]
    out = []
    for p in sorted(root.rglob(f"*{suffix}")):
        if p.name.startswith((".", "_")):
            continue
        st = p.stat()
        out.append(FileInfo(str(p), st.st_size, st.st_mtime_ns))
    return out


@dataclasses.dataclass
class Dataset:
    root: str
    format: str
    schema: Schema

    @staticmethod
    def parquet(root: str | Path) -> "Dataset":
        """Register a parquet dataset, deriving the schema from the first
        footer (all files must share it)."""
        import pyarrow.parquet as pq

        files = list_data_files(root)
        if not files:
            raise HyperspaceError(f"no parquet files found under {root}")
        arrow_schema = pq.read_schema(files[0].path)
        return Dataset(str(root), "parquet", Schema.from_arrow(arrow_schema))

    def files(self) -> list[FileInfo]:
        return list_data_files(self.root)

    def scan(self) -> Scan:
        return Scan(self.root, self.format, self.schema)
