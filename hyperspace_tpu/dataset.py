"""Dataset registration: the analog of a Spark relation over data-lake files.

A Dataset is a parquet directory with a derived Schema; `scan()` yields the
plan leaf. File enumeration returns (path, size, mtime) triples — the
identity the signature provider fingerprints (reference collects
`PartitioningAwareFileIndex.allFiles` at actions/CreateActionBase.scala:89-97).
"""

from __future__ import annotations

import dataclasses
from pathlib import Path

from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.metadata.log_entry import FileInfo
from hyperspace_tpu.plan.nodes import Scan
from hyperspace_tpu.schema import Schema


_FORMAT_SUFFIX = {"parquet": ".parquet", "orc": ".orc", "csv": ".csv", "json": ".json"}


def format_suffix(fmt: str) -> str:
    try:
        return _FORMAT_SUFFIX[fmt]
    except KeyError:
        raise HyperspaceError(f"unsupported source format {fmt!r} (parquet|orc|csv|json)")


def list_data_files(root: str | Path, suffix: str = ".parquet") -> list[FileInfo]:
    """Recursively list data files under `root`, sorted by path."""
    root = Path(root)
    if root.is_file():
        st = root.stat()
        return [FileInfo(str(root), st.st_size, st.st_mtime_ns)]
    out = []
    for p in sorted(root.rglob(f"*{suffix}")):
        if p.name.startswith((".", "_")):
            continue
        st = p.stat()
        out.append(FileInfo(str(p), st.st_size, st.st_mtime_ns))
    return out


@dataclasses.dataclass
class Dataset:
    root: str
    format: str
    schema: Schema

    @staticmethod
    def parquet(root: str | Path) -> "Dataset":
        """Register a parquet dataset, deriving the schema from the first
        footer (all files must share it)."""
        import pyarrow.parquet as pq

        files = list_data_files(root)
        if not files:
            raise HyperspaceError(f"no parquet files found under {root}")
        arrow_schema = pq.read_schema(files[0].path)
        return Dataset(str(root), "parquet", Schema.from_arrow(arrow_schema))

    @staticmethod
    def of_format(root: str | Path, fmt: str) -> "Dataset":
        """Register a dataset of any supported format (parquet/orc/csv/
        json — the same four the reference gates sources to,
        index/serde/LogicalPlanSerDeUtils.scala:225-245), deriving the
        schema from the first file."""
        if fmt == "parquet":
            return Dataset.parquet(root)
        files = list_data_files(root, suffix=format_suffix(fmt))
        if not files:
            raise HyperspaceError(f"no {fmt} files found under {root}")
        first = files[0].path
        if fmt == "orc":
            from pyarrow import orc

            arrow_schema = orc.ORCFile(first).schema
        elif fmt == "csv":
            from pyarrow import csv as pcsv

            # Full-file read: block-sample inference can mis-type columns
            # whose early values look numeric. Reads at registration are
            # pinned to this schema afterwards (io._arrow_types_for).
            arrow_schema = pcsv.read_csv(first).schema
        else:  # json
            from pyarrow import json as pjson

            arrow_schema = pjson.read_json(first).schema
        return Dataset(str(root), fmt, Schema.from_arrow(arrow_schema))

    @staticmethod
    def orc(root: str | Path) -> "Dataset":
        return Dataset.of_format(root, "orc")

    @staticmethod
    def csv(root: str | Path) -> "Dataset":
        return Dataset.of_format(root, "csv")

    @staticmethod
    def json(root: str | Path) -> "Dataset":
        return Dataset.of_format(root, "json")

    def files(self) -> list[FileInfo]:
        return list_data_files(self.root, suffix=format_suffix(self.format))

    def scan(self) -> Scan:
        return Scan(self.root, self.format, self.schema)
