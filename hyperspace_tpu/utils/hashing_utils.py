"""Host-side hashing helpers.

Reference parity: util/HashingUtils.scala:186-197 (MD5 hex of a string).
Used by the file-based signature provider to fingerprint source data.
"""

from __future__ import annotations

import hashlib


def md5_hex(s: str | bytes) -> str:
    if isinstance(s, str):
        s = s.encode("utf-8")
    return hashlib.md5(s).hexdigest()
