"""Filesystem helpers for the metadata plane.

Reference parity: util/FileUtils.scala:28-117 (create/read/delete/byte IO).
The load-bearing primitive here is `atomic_write`: the operation log's
optimistic concurrency is "write temp file, atomically link to final name;
loser of the race gets False" (reference: index/IndexLogManager.scala:138-154,
which uses Hadoop's atomic rename). On POSIX we get compare-and-swap via
`os.link` (fails with EEXIST if the target already exists) which, unlike
`os.rename`, does not clobber.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
from pathlib import Path
from typing import Any

from hyperspace_tpu.faults import fault_point
from hyperspace_tpu.utils import retry


def ensure_dir(path: str | os.PathLike) -> None:
    Path(path).mkdir(parents=True, exist_ok=True)


def fsync_dir(path: str | os.PathLike) -> None:
    """fsync a directory: POSIX makes a rename/link durable only once the
    parent directory's entry is flushed separately — without this, the
    `latestStable` pointer (and any os.replace commit) can vanish on
    power loss even though the data file's bytes were fsynced."""
    try:
        fd = os.open(path, os.O_RDONLY | getattr(os, "O_DIRECTORY", 0))
    except OSError:
        return  # platform/filesystem without dir fds — best effort
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write(path: str | os.PathLike, data: bytes) -> bool:
    """Atomically create `path` with `data`.

    Returns True on success, False when the CAS is lost: `path` already
    exists (a concurrent writer won), or — on the degraded no-hardlink
    fallback only — a concurrent writer holds the lock lease (including a
    writer that crashed less than _LOCK_STALE_S ago; callers treat any
    False as contention and may retry). Never overwrites an existing file.
    """
    path = Path(path)
    ensure_dir(path.parent)
    fault_point("file.atomic_write", path)
    fd, tmp = tempfile.mkstemp(dir=path.parent, prefix=".tmp-", suffix=path.name)
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        try:
            os.link(tmp, path)  # CAS: fails iff path exists
            fsync_dir(path.parent)
            return True
        except FileExistsError:
            return False
        except OSError:
            # Filesystem without hard links (FUSE/SMB/some overlays). The
            # tmp file already holds the full fsynced payload; serialize
            # the visibility rename behind an O_EXCL lock file so two
            # writers can never both pass the existence check (content is
            # never torn either way — rename is atomic).
            return _locked_rename(tmp, path)
    finally:
        try:
            os.unlink(tmp)
        except OSError:
            pass


# Lease duration for the no-hardlink lock-file fallback. A crashed
# writer's lock older than this is presumed dead and reaped. Staleness is
# judged from an epoch the CREATOR wrote into the lock file (never from
# filesystem mtime — network filesystems stamp mtime with the SERVER's
# clock), so single-winner correctness assumes inter-writer clock skew
# below this bound — the standard lease-lock assumption.
_LOCK_STALE_S = 30.0


def _read_lock_text(p: Path) -> str | None:
    try:
        with open(p, "r") as f:
            return f.read()
    except OSError:
        return None


def _lock_epoch(text: str | None) -> float | None:
    """Creator epoch out of a lock token ('<epoch>:<uuid>'); None means
    unreadable/torn — treated as stale, which is safe because a mis-stolen
    live lock is detected by token mismatch and by the holder's pre-commit
    re-verification."""
    if not text or ":" not in text:
        return None
    try:
        return float(text.split(":", 1)[0])
    except ValueError:
        return None


def _try_reap(lock: Path, nonce: str) -> bool:
    """Clear `lock` if stale. True ⇒ cleared (caller may retry the
    acquire); False ⇒ a live contender holds it (CAS lost). The claim is
    atomic — rename to a unique name, exactly one reaper wins — and
    verified: stealing a lock instance OTHER than the one judged stale is
    detected by content mismatch and the stolen token is reinstalled."""
    import time

    text = _read_lock_text(lock)
    if text is None:
        return True  # vanished underneath us — retry the acquire
    ep = _lock_epoch(text)
    if ep is None:
        # Token missing/torn: the holder may be BETWEEN its O_EXCL create
        # and its token write — judge by file age instead (the only case
        # where mtime, with its server-clock caveat, is consulted), so a
        # live-but-not-yet-written lease is not reaped.
        try:
            # Wall clock on purpose (cross-process lease vs file mtime).
            if time.time() - os.stat(lock).st_mtime <= _LOCK_STALE_S:  # noqa: HSL007
                return False
        except OSError:
            return True  # vanished — retry the acquire
    elif time.time() - ep <= _LOCK_STALE_S:  # noqa: HSL007 — persisted epoch token
        return False
    reaped = lock.with_name(f"{lock.name}.reap-{nonce}")
    try:
        os.rename(lock, reaped)
    except OSError:
        return False  # another reaper won
    stolen = _read_lock_text(reaped)
    try:
        os.unlink(reaped)
    except OSError:
        pass
    if stolen != text:
        # Between our read and the rename the stale lock was replaced by a
        # NEW (live) instance — reinstall its token so later writers still
        # see a held lease; its holder aborts via pre-commit verification
        # only if this reinstall loses a further race.
        try:
            fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            try:
                os.write(fd, (stolen or "").encode())
            finally:
                os.close(fd)
        except OSError:
            pass
        return False
    return True


def _locked_rename(tmp: str, path: Path) -> bool:
    """Compare-and-swap via an O_EXCL lock file (the no-hardlink fallback
    for atomic_write): only the lock holder may check-and-rename. The
    holder re-reads its own token immediately before committing, so a
    writer whose lease was (wrongly) reaped aborts instead of producing a
    second winner. Residual lease-lock hazard (inherent to leases): a
    holder paused for longer than _LOCK_STALE_S between that check and
    its rename can still commit over a successor's write — bounded-pause
    is assumed alongside bounded clock skew."""
    import time
    import uuid

    lock = path.with_name(path.name + ".lock")
    token = f"{time.time():.6f}:{uuid.uuid4().hex}"
    for attempt in range(3):
        try:
            fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            # uuid nonce: concurrent reapers (even same-pid threads) must
            # never collide on the claim name.
            if not _try_reap(lock, f"{os.getpid()}-{uuid.uuid4().hex[:8]}-{attempt}"):
                return False
            continue
        except OSError:
            return False
        try:
            with os.fdopen(fd, "w") as f:
                f.write(token)
                f.flush()
                os.fsync(f.fileno())
        except OSError:  # noqa: HSL017 — not a retry: an unwritten token
            # simply fails the lease check below and the claim returns
            # False in-band
            pass
        try:
            if path.exists():
                return False
            if _read_lock_text(lock) != token:
                return False  # our lease was stolen — do not double-commit
            try:
                os.rename(tmp, path)
                fsync_dir(path.parent)
                return True
            except OSError:
                return False
        finally:
            if _read_lock_text(lock) == token:
                try:
                    os.unlink(lock)
                except OSError:  # noqa: HSL017 — lease-file cleanup only;
                    # a leftover lock is reaped by the next claimant
                    pass
    return False


def write_json(path: str | os.PathLike, obj: Any, *, overwrite: bool = True) -> bool:
    data = json.dumps(obj, indent=2, sort_keys=False).encode()
    if overwrite:
        path = Path(path)
        ensure_dir(path.parent)
        retry.retry_call(_overwrite_json, path, data)
        return True
    return retry.retry_call(atomic_write, path, data)


def _overwrite_json(path: Path, data: bytes) -> None:
    """Torn-write-proof overwrite: fsync the payload BEFORE the rename
    (an unfsynced os.replace can surface as an empty/partial file after
    power loss — the exact torn `latestStable` the backward scan exists
    to survive) and fsync the parent dir after, so the commit itself is
    durable."""
    fault_point("file.write_json", path)
    fd, tmp = tempfile.mkstemp(dir=path.parent, prefix=".tmp-")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    fsync_dir(path.parent)


def read_json(path: str | os.PathLike) -> Any:
    with open(path, "rb") as f:
        return json.loads(f.read())


def delete_recursively(path: str | os.PathLike) -> None:
    p = Path(path)
    if p.is_dir():
        shutil.rmtree(p, ignore_errors=True)
    elif p.exists():
        p.unlink(missing_ok=True)
