"""Filesystem helpers for the metadata plane.

Reference parity: util/FileUtils.scala:28-117 (create/read/delete/byte IO).
The load-bearing primitive here is `atomic_write`: the operation log's
optimistic concurrency is "write temp file, atomically link to final name;
loser of the race gets False" (reference: index/IndexLogManager.scala:138-154,
which uses Hadoop's atomic rename). On POSIX we get compare-and-swap via
`os.link` (fails with EEXIST if the target already exists) which, unlike
`os.rename`, does not clobber.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
from pathlib import Path
from typing import Any


def ensure_dir(path: str | os.PathLike) -> None:
    Path(path).mkdir(parents=True, exist_ok=True)


def atomic_write(path: str | os.PathLike, data: bytes) -> bool:
    """Atomically create `path` with `data`.

    Returns True on success, False if `path` already exists (i.e. a
    concurrent writer won the race). Never overwrites an existing file.
    """
    path = Path(path)
    ensure_dir(path.parent)
    fd, tmp = tempfile.mkstemp(dir=path.parent, prefix=".tmp-", suffix=path.name)
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        try:
            os.link(tmp, path)  # CAS: fails iff path exists
            return True
        except FileExistsError:
            return False
        except OSError:
            # Filesystem without hard links (FUSE/SMB/some overlays). The
            # tmp file already holds the full fsynced payload; make it
            # visible with rename guarded by an existence check. The
            # check→rename window is a narrow race on this degraded path,
            # but content is never torn (rename is atomic).
            if path.exists():
                return False
            try:
                os.rename(tmp, path)
                return True
            except OSError:
                return False
    finally:
        try:
            os.unlink(tmp)
        except OSError:
            pass


def write_json(path: str | os.PathLike, obj: Any, *, overwrite: bool = True) -> bool:
    data = json.dumps(obj, indent=2, sort_keys=False).encode()
    if overwrite:
        path = Path(path)
        ensure_dir(path.parent)
        fd, tmp = tempfile.mkstemp(dir=path.parent, prefix=".tmp-")
        with os.fdopen(fd, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
        return True
    return atomic_write(path, data)


def read_json(path: str | os.PathLike) -> Any:
    with open(path, "rb") as f:
        return json.loads(f.read())


def delete_recursively(path: str | os.PathLike) -> None:
    p = Path(path)
    if p.is_dir():
        shutil.rmtree(p, ignore_errors=True)
    elif p.exists():
        p.unlink(missing_ok=True)
