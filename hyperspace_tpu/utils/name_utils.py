"""Index-name normalization.

Reference parity: util/IndexNameUtils.scala:219-231 — trim and replace
whitespace runs with underscores so names are filesystem-safe.
"""

from __future__ import annotations

import re

_WS = re.compile(r"\s+")


def normalize_index_name(name: str) -> str:
    return _WS.sub("_", name.strip())
