"""Guard against kernel memory-map exhaustion from JIT accumulation.

Every XLA:CPU executable pins LLVM-JIT'd code/rodata/data mappings for
the life of jax's jit cache. A long-running process that compiles many
programs (a query engine serving varied plans does exactly that)
accumulates mappings until it hits the kernel's `vm.max_map_count`
(default 65530), after which mmap fails inside LLVM and the next
compilation SIGSEGVs — observed reproducibly on jaxlib 0.4.37 during
full TPC-DS sweeps. Dropping jax's caches releases the executables; the
occasional recompile is far cheaper than a dead process.

The check reads /proc/self/maps, so it is sampled (every
`_CHECK_EVERY` calls) and is a no-op on platforms without procfs.

Observability (docs/observability.md): the guard used to be a silent
save — the only evidence was the absence of a segfault. Every sampled
check now feeds the ``proc.map_count`` gauge, and every cache drop
counts (``jit_memory.cache_drops``) and emits a WARN ``jit.cache_drop``
event carrying the observed map count and the limit, so the /metrics
and /debug/events endpoints (obs/http.py) show the pressure building
*before* it becomes a dead process.
"""

from __future__ import annotations

import itertools
import threading

from hyperspace_tpu import stats
from hyperspace_tpu.obs import events as _events

_CHECK_EVERY = 16
_counter = itertools.count()
_limit_cache: list = []  # [int] once resolved
_limit_lock = threading.Lock()

_EVT_CACHE_DROP = _events.declare("jit.cache_drop")


def _map_limit() -> int:
    """70% of vm.max_map_count (0 where unknown: disables the guard)."""
    with _limit_lock:
        if not _limit_cache:
            try:
                with open("/proc/sys/vm/max_map_count", "rb") as f:
                    _limit_cache.append(int(f.read()) * 7 // 10)
            except (OSError, ValueError):
                _limit_cache.append(0)
        return _limit_cache[0]


def map_count() -> int:
    """Memory mappings of this process (0 where /proc is unreadable) —
    the resource the XLA:CPU jit cache exhausts."""
    try:
        with open("/proc/self/maps", "rb") as f:
            return sum(1 for _ in f)
    except OSError:
        return 0


def drop_caches(reason: str) -> None:
    """Unconditionally clear jax's compilation caches — the audited
    actuator the OpsController's recompile-storm response uses (the
    sampled guard above stays the autonomous pressure-relief path).
    Counted and WARN-announced like every other drop, with the `reason`
    on the event so the decision log says WHY the executables vanished."""
    import jax

    jax.clear_caches()
    stats.increment("jit_memory.cache_drops")
    _EVT_CACHE_DROP.emit(reason=reason, map_count=map_count(), limit=_map_limit())
    from hyperspace_tpu.obs import runtime as obs_runtime

    obs_runtime.refresh_process_gauges()


def maybe_relieve_jit_pressure() -> bool:
    """Sampled check; clears jax's compilation caches when the process
    nears the kernel mapping limit. Returns True when a clear ran."""
    if next(_counter) % _CHECK_EVERY:
        return False
    from hyperspace_tpu.obs import runtime as obs_runtime

    limit = _map_limit()
    maps = obs_runtime.refresh_process_gauges()["map_count"]
    if not limit or maps <= limit:
        return False
    import jax

    jax.clear_caches()
    stats.increment("jit_memory.cache_drops")
    _EVT_CACHE_DROP.emit(map_count=maps, limit=limit)
    # The drop emptied every instrumented jit cache — re-sample the
    # gauges so jit.live_executables reflects the post-drop state.
    obs_runtime.refresh_process_gauges()
    return True
