"""Retry with exponential backoff for transient-IO call sites.

Wraps the IO primitives the metadata plane depends on (write_json,
parquet/footer reads) so a flaky disk or a lease-contended rename is a
delay, not a failure. Policy knobs surface as `hyperspace.retry.*`
config keys (config.py routes them here); classification of what is
worth retrying lives in `exceptions.is_retryable` — corruption and
missing files surface immediately, only genuinely transient OS errors
(and injected `faults.FaultError`s, which carry errno EIO) retry.

Determinism: backoff is a pure function of the attempt number
(base * multiplier**attempt, capped). A `jitter` hook exists for
deployments that want decorrelation, but it must be injected explicitly
— nothing here draws from an RNG (HSL005 applies to this module too),
so tests replay byte-identically. The sleeper is injectable for the
same reason: unit tests pass a recording no-op and assert the schedule
instead of actually waiting.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable

from hyperspace_tpu import stats
from hyperspace_tpu.exceptions import is_retryable
from hyperspace_tpu.obs import trace as obs_trace


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Attempt budget + deterministic exponential backoff schedule."""

    max_attempts: int = 3
    backoff_base: float = 0.005  # seconds before the first retry
    backoff_multiplier: float = 2.0
    backoff_max: float = 0.25
    # Optional decorrelation hook: (attempt_index, computed_delay) -> delay.
    # None ⇒ fully deterministic schedule.
    jitter: Callable[[int, float], float] | None = None
    retryable: Callable[[BaseException], bool] = is_retryable

    def delay(self, attempt: int) -> float:
        """Seconds to sleep before retry number `attempt` (0-based)."""
        d = min(self.backoff_max, self.backoff_base * self.backoff_multiplier**attempt)
        if self.jitter is not None:
            d = self.jitter(attempt, d)
        return max(0.0, d)


_io_policy = RetryPolicy()
_cas_attempts = 1  # Action.run CAS-contention retries; 1 = abort on loss (reference behavior)
_sleeper: Callable[[float], None] = time.sleep


def configure(
    *,
    max_attempts: int | None = None,
    backoff_base: float | None = None,
    backoff_max: float | None = None,
    cas_attempts: int | None = None,
    sleeper: Callable[[float], None] | None = None,
) -> None:
    """Adjust the process-default policy (the `hyperspace.retry.*` keys
    route here from HyperspaceConf.set). `max_attempts=1` is the retry
    kill switch: every transient failure surfaces on first occurrence."""
    global _io_policy, _cas_attempts, _sleeper
    kwargs: dict[str, Any] = {}
    if max_attempts is not None:
        kwargs["max_attempts"] = max(1, int(max_attempts))
    if backoff_base is not None:
        kwargs["backoff_base"] = float(backoff_base)
    if backoff_max is not None:
        kwargs["backoff_max"] = float(backoff_max)
    if kwargs:
        _io_policy = dataclasses.replace(_io_policy, **kwargs)
    if cas_attempts is not None:
        _cas_attempts = max(1, int(cas_attempts))
    if sleeper is not None:
        _sleeper = sleeper


def io_policy() -> RetryPolicy:
    return _io_policy


def cas_attempts() -> int:
    """Whole-protocol retries Action.run() makes when its begin() CAS
    loses to a concurrent writer (re-reads the log and re-validates per
    attempt). Default 1 — single-writer optimistic concurrency aborts,
    matching the reference; opt in via `hyperspace.retry.casAttempts`."""
    return _cas_attempts


def retry_call(fn: Callable[..., Any], *args, policy: RetryPolicy | None = None, **kwargs) -> Any:
    """Run `fn(*args, **kwargs)`, retrying per `policy` on retryable
    exceptions. Exhaustion re-raises the last exception unchanged (so
    existing `except OSError` handling upstream keeps working). Only
    `Exception` subclasses are considered — a simulated crash
    (faults.CrashPoint, a BaseException) always propagates: a dead
    process does not retry."""
    p = policy if policy is not None else _io_policy
    attempt = 0
    while True:
        try:
            return fn(*args, **kwargs)
        except Exception as e:  # noqa: BLE001 — classified below
            if attempt >= p.max_attempts - 1 or not p.retryable(e):
                if attempt > 0:
                    stats.increment("retry.exhausted")
                    obs_trace.event("retry.exhausted", attempts=attempt + 1, error=str(e))
                raise
            stats.increment("retry.attempts")
            delay = p.delay(attempt)
            # Point event on the active span (if any): which call site
            # retried, why, and what the backoff cost.
            obs_trace.event("retry", attempt=attempt + 1, delay_s=delay, error=str(e))
            _sleeper(delay)
            attempt += 1


def retrying(policy: RetryPolicy | None = None):
    """Decorator form of retry_call for named transient-IO functions."""

    def deco(fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return retry_call(fn, *args, policy=policy, **kwargs)

        return wrapper

    return deco
