from hyperspace_tpu.utils.file_utils import (
    atomic_write,
    delete_recursively,
    read_json,
    write_json,
)
from hyperspace_tpu.utils.hashing_utils import md5_hex
from hyperspace_tpu.utils.name_utils import normalize_index_name

__all__ = [
    "atomic_write",
    "delete_recursively",
    "read_json",
    "write_json",
    "md5_hex",
    "normalize_index_name",
]
