"""hyperspace-tpu: a TPU-native indexing and query-acceleration framework.

Capabilities of Microsoft Hyperspace (the Spark indexing subsystem), rebuilt
TPU-first: bucketed sorted covering indexes over columnar datasets, a
filesystem-backed operation log with optimistic concurrency and a full
lifecycle state machine, and transparent query rewriting — filters become
index scans, equi-joins become shuffle-free bucket-aligned sort-merge joins.
The data plane is JAX/XLA (all_to_all bucketize under shard_map, per-shard
sort, gather/filter and merge-join kernels); the host plane is pure Python.
"""

from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.index.index_config import IndexConfig
from hyperspace_tpu.plan.expr import (
    abs_,
    col,
    date_lit,
    day,
    floor,
    lit,
    month,
    sqrt,
    when,
    year,
)
from hyperspace_tpu.plan.nodes import AggSpec, WindowSpec
from hyperspace_tpu.schema import Field, Schema

__version__ = "0.1.0"

__all__ = [
    "HyperspaceError",
    "IndexConfig",
    "col",
    "when",
    "sqrt",
    "abs_",
    "floor",
    "AggSpec",
    "WindowSpec",
    "lit",
    "Field",
    "Schema",
    "Hyperspace",
    "HyperspaceSession",
    "VectorIndexConfig",
]


def __getattr__(name):
    # Lazy imports so the metadata plane is importable without jax.
    if name in ("Hyperspace", "HyperspaceSession"):
        from hyperspace_tpu import hyperspace as _h

        return getattr(_h, name)
    if name == "Dataset":
        from hyperspace_tpu.dataset import Dataset

        return Dataset
    if name == "VectorIndexConfig":
        from hyperspace_tpu.vector.index import VectorIndexConfig

        return VectorIndexConfig
    if name in ("stats", "faults", "obs", "serve"):
        # Fault-tolerance counters (stats.snapshot()), the deterministic
        # fault-injection harness (docs/fault_tolerance.md), the
        # observability plane — tracer/metrics/profiles
        # (docs/observability.md) — and the concurrent query-serving
        # plane (docs/serving.md).
        import importlib

        return importlib.import_module(f"hyperspace_tpu.{name}")
    raise AttributeError(name)
