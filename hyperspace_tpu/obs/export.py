"""Metrics export: Prometheus text exposition + Chrome trace timelines.

Modes::

    python -m hyperspace_tpu.obs.export                      # live registry
    python -m hyperspace_tpu.obs.export --sink q.jsonl       # aggregate a sink
    python -m hyperspace_tpu.obs.export --format chrome \
        --sink q.jsonl --output trace.json                   # span timelines
    python -m hyperspace_tpu.obs.export --format chrome \
        --fleet /path/_obs --output fleet.json   # merged fleet journals

Prometheus: renders whatever the registry holds (the /metrics endpoint
in obs/http.py serves exactly this), or replays a JSON-lines trace sink
(`hyperspace.obs.sink`) into a fresh registry so offline trajectories
export the same way live processes do. Metric names are sanitized to
the Prometheus grammar (`hyperspace_` prefix, dots → underscores);
HELP text and label values are escaped per the text exposition format
(`\\` → `\\\\`, newline → `\\n`, and `"` → `\\"` inside label values) —
a hostile metric description can no longer tear the exposition apart.

Chrome: converts span trees (from a sink file, or the in-process
recent-root ring) to the Chrome Trace Event format — open the output in
`chrome://tracing` or https://ui.perfetto.dev. Spans carry their start
offset and OS thread id (obs/trace.py), so genuinely concurrent work —
the overlapped build-pipeline stages, pool-fanned IO — renders as
overlapping slices on separate thread lanes instead of a flattened
tree.
"""

from __future__ import annotations

import argparse
import json
import sys

from hyperspace_tpu.obs import metrics as m


def _prom_name(name: str) -> str:
    return "hyperspace_" + "".join(
        c if c.isalnum() or c == "_" else "_" for c in name
    )


def escape_help(text: str) -> str:
    """HELP/TYPE comment escaping per the Prometheus text exposition
    format: backslash and newline only."""
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def escape_label_value(value: str) -> str:
    """Label-value escaping: backslash, double-quote, newline."""
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    return repr(float(v)) if isinstance(v, float) and not float(v).is_integer() else str(int(v))


def render_prometheus(registry: "m.MetricsRegistry | None" = None) -> str:
    """The registry as Prometheus text exposition format."""
    reg = registry if registry is not None else m.REGISTRY
    out: list[str] = []
    for metric in reg.collect():
        name = _prom_name(metric.name)
        if metric.help:
            out.append(f"# HELP {name} {escape_help(metric.help)}")
        out.append(f"# TYPE {name} {metric.kind}")
        if metric.kind in ("counter", "gauge"):
            out.append(f"{name} {_fmt(metric.value)}")
        else:  # histogram
            for le, cum in metric.bucket_counts():
                le_s = "+Inf" if le == float("inf") else repr(float(le))
                out.append(f'{name}_bucket{{le="{escape_label_value(le_s)}"}} {cum}')
            out.append(f"{name}_sum {float(metric.sum)!r}")
            out.append(f"{name}_count {metric.count}")
    return "\n".join(out) + "\n"


def _walk_span(span: dict):
    yield span
    for c in span.get("children", ()):
        yield from _walk_span(c)


def registry_from_sink(path: str) -> "m.MetricsRegistry":
    """Replay a JSON-lines trace sink into a fresh registry. Unparseable
    lines are skipped (a crash mid-append can tear the final line)."""
    reg = m.MetricsRegistry()
    queries = reg.counter("query.count", "root traces in sink")
    q_s = reg.histogram("query.seconds", "root trace wall time", buckets=m.SECONDS_BUCKETS)
    op_s = reg.histogram("query.operator.seconds", "span wall time", buckets=m.SECONDS_BUCKETS)
    io_b = reg.histogram("query.bytes_scanned", "bytes per io span", buckets=m.BYTES_BUCKETS)
    errors = reg.counter("trace.errors", "spans closed with error=")
    with open(path) as f:
        for line in f:
            try:
                event = json.loads(line)
            except ValueError:
                continue
            root = event.get("trace") or {}
            queries.inc()
            if root.get("wall_s") is not None:
                q_s.observe(root["wall_s"])
            for span in _walk_span(root):
                if span.get("error"):
                    errors.inc()
                if span.get("wall_s") is None:
                    continue
                name = span.get("name", "")
                if name.startswith("execute."):
                    op_s.observe(span["wall_s"])
                attrs = span.get("attrs") or {}
                if name.startswith("io.") and "bytes" in attrs:
                    io_b.observe(float(attrs["bytes"]))
    return reg


# -- Chrome trace export ------------------------------------------------------

def roots_from_sink(path: str) -> list[dict]:
    """Every root-span dict in a JSON-lines sink file (torn lines
    skipped, same contract as registry_from_sink)."""
    roots: list[dict] = []
    with open(path) as f:
        for line in f:
            try:
                event = json.loads(line)
            except ValueError:
                continue
            root = event.get("trace")
            if root:
                roots.append(root)
    return roots


def live_roots() -> list[dict]:
    """The in-process recent-root ring as span dicts (the no-sink
    source: /debug/trace and programmatic export share it)."""
    from hyperspace_tpu.obs import trace as _trace

    return [r.to_json() for r in _trace.recent_roots()]


def roots_from_fleet(journal_root: str) -> list[dict]:
    """Root spans merged from every fleet member's durable journal under
    `journal_root` (the `_obs` dir: one `<pid>/` per member —
    obs/journal.py). Each root is stamped with its member's pid so
    `chrome_trace` lanes one track group per member even for spans whose
    trace ids predate adoption. Dead members' sealed segments read fine;
    torn tails are skipped by the journal reader."""
    from hyperspace_tpu.obs import journal as _journal

    roots = []
    for rec in _journal.merge_dir(journal_root):
        if rec.get("kind") != "span" or not isinstance(rec.get("trace"), dict):
            continue
        root = rec["trace"]
        if isinstance(rec.get("pid"), int):
            root = dict(root, pid=rec["pid"])
        roots.append(root)
    return roots


def chrome_trace(roots: "list[dict]") -> dict:
    """Span trees as a Chrome Trace Event document (Perfetto/
    chrome://tracing). Each span becomes one complete ("X") event laned
    by the OS thread it ran on; timestamps are normalized so the
    earliest span starts at 0. Spans from old sinks without timeline
    fields inherit their parent's start (rendering nested, zero-offset).
    """
    events: list[dict] = []
    starts = [
        s["t0_s"] for r in roots for s in _walk_span(r) if s.get("t0_s") is not None
    ]
    base = min(starts) if starts else 0.0
    # Lanes are qualified by (pid, os-thread): two fleet members whose
    # OS thread ids collide (they usually do — every member's main
    # thread) must not interleave on one track. Alias numbering restarts
    # per pid so each member's track group reads thread-1..N.
    tid_alias: dict = {}
    lanes_per_pid: dict = {}

    def lane(pid: int, raw_tid) -> int:
        key = (pid, raw_tid)
        if key not in tid_alias:
            lanes_per_pid[pid] = lanes_per_pid.get(pid, 0) + 1
            tid_alias[key] = lanes_per_pid[pid]
        return tid_alias[key]

    def emit(span: dict, pid: int, trace_id: "str | None", parent_ts: float) -> None:
        ts = (
            (span["t0_s"] - base) * 1e6 if span.get("t0_s") is not None else parent_ts
        )
        args = dict(span.get("attrs") or {})
        if span.get("error") is not None:
            args["error"] = span["error"]
        if trace_id is not None:
            args["trace_id"] = trace_id
        events.append(
            {
                "ph": "X",
                "name": span.get("name", "?"),
                "cat": "span",
                "ts": round(ts, 3),
                "dur": round((span.get("wall_s") or 0.0) * 1e6, 3),
                "pid": pid,
                "tid": lane(pid, span.get("tid", 0)),
                "args": args,
            }
        )
        for child in span.get("children", ()):
            emit(child, pid, trace_id, ts)

    for root in roots:
        trace_id = root.get("trace_id")
        # Root ids are "<pid>-<seq>" (obs/trace.py): keep sink lines from
        # several processes on separate pid tracks. Journal-merged roots
        # may also carry an explicit "pid" (obs/journal.py), preferred
        # over parsing.
        pid = 1
        if isinstance(root.get("pid"), int):
            pid = root["pid"]
        elif trace_id and "-" in str(trace_id):
            head = str(trace_id).split("-", 1)[0]
            if head.isdigit():
                pid = int(head)
        emit(root, pid, trace_id, 0.0)
    alias_of = {(pid, alias): raw for (pid, raw), alias in tid_alias.items()}
    meta = [
        {
            "ph": "M",
            "name": "thread_name",
            "pid": pid,
            "tid": alias,
            "args": {"name": f"thread-{alias} (os:{alias_of[(pid, alias)]})"},
        }
        for pid, alias in sorted({(e["pid"], e["tid"]) for e in events})
    ]
    meta += [
        {
            "ph": "M",
            "name": "process_name",
            "pid": pid,
            "tid": 0,
            "args": {"name": f"member pid {pid}"},
        }
        for pid in sorted({e["pid"] for e in events})
    ]
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m hyperspace_tpu.obs.export",
        description="Export hyperspace telemetry: Prometheus text or Chrome trace.",
    )
    ap.add_argument(
        "--sink", help="read a JSON-lines trace sink file instead of live process state"
    )
    ap.add_argument(
        "--fleet",
        help="merge every fleet member's durable journal under this _obs "
        "root (obs/journal.py) — one chrome track group per member pid; "
        "reads sealed segments only, so it works on a dead fleet",
    )
    ap.add_argument(
        "--format",
        choices=("prom", "chrome"),
        default="prom",
        help="prom = Prometheus text exposition; chrome = Chrome Trace Events "
        "(open in chrome://tracing or Perfetto)",
    )
    ap.add_argument("--output", help="write here instead of stdout")
    args = ap.parse_args(argv)
    if args.format == "chrome":
        if args.fleet:
            roots = roots_from_fleet(args.fleet)
            if args.sink:
                roots += roots_from_sink(args.sink)
        else:
            roots = roots_from_sink(args.sink) if args.sink else live_roots()
        text = json.dumps(chrome_trace(roots))
    elif args.sink:
        text = render_prometheus(registry_from_sink(args.sink))
    else:
        # Declare the core metric families so a fresh process exposes
        # the full schema (zeros) instead of an empty page.
        import hyperspace_tpu.obs.profile  # noqa: F401 — declares query.* metrics
        import hyperspace_tpu.obs.runtime  # noqa: F401 — declares jit./proc. gauges
        import hyperspace_tpu.obs.slo  # noqa: F401 — declares slo.* burn gauges
        import hyperspace_tpu.stats  # noqa: F401 — declares fault-plane counters

        text = render_prometheus()
    if args.output:
        with open(args.output, "w") as f:
            f.write(text if text.endswith("\n") else text + "\n")
    else:
        sys.stdout.write(text if text.endswith("\n") else text + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
