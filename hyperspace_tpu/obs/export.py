"""Metrics export: Prometheus-style text exposition.

Two modes::

    python -m hyperspace_tpu.obs.export            # live process registry
    python -m hyperspace_tpu.obs.export --sink q.jsonl   # aggregate a sink file

The first renders whatever this process's registry holds (useful from a
long-lived server REPL or an embedding application that execs it). The
second replays a JSON-lines trace sink (`hyperspace.obs.sink`) into a
fresh registry — every `execute.*` span becomes an operator wall-time
observation, every root a query observation — so offline trajectories
(bench runs, soak tests) export the same way live processes do.

Metric names are sanitized to the Prometheus grammar
(`hyperspace_` prefix, dots → underscores); histograms render classic
cumulative `_bucket{le="..."}` series plus `_sum`/`_count`.
"""

from __future__ import annotations

import argparse
import json
import sys

from hyperspace_tpu.obs import metrics as m


def _prom_name(name: str) -> str:
    return "hyperspace_" + "".join(
        c if c.isalnum() or c == "_" else "_" for c in name
    )


def _fmt(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    return repr(float(v)) if isinstance(v, float) and not float(v).is_integer() else str(int(v))


def render_prometheus(registry: "m.MetricsRegistry | None" = None) -> str:
    """The registry as Prometheus text exposition format."""
    reg = registry if registry is not None else m.REGISTRY
    out: list[str] = []
    for metric in reg.collect():
        name = _prom_name(metric.name)
        if metric.help:
            out.append(f"# HELP {name} {metric.help}")
        out.append(f"# TYPE {name} {metric.kind}")
        if metric.kind in ("counter", "gauge"):
            out.append(f"{name} {_fmt(metric.value)}")
        else:  # histogram
            for le, cum in metric.bucket_counts():
                le_s = "+Inf" if le == float("inf") else repr(float(le))
                out.append(f'{name}_bucket{{le="{le_s}"}} {cum}')
            out.append(f"{name}_sum {float(metric.sum)!r}")
            out.append(f"{name}_count {metric.count}")
    return "\n".join(out) + "\n"


def _walk_span(span: dict):
    yield span
    for c in span.get("children", ()):
        yield from _walk_span(c)


def registry_from_sink(path: str) -> "m.MetricsRegistry":
    """Replay a JSON-lines trace sink into a fresh registry. Unparseable
    lines are skipped (a crash mid-append can tear the final line)."""
    reg = m.MetricsRegistry()
    queries = reg.counter("query.count", "root traces in sink")
    q_s = reg.histogram("query.seconds", "root trace wall time", buckets=m.SECONDS_BUCKETS)
    op_s = reg.histogram("query.operator.seconds", "span wall time", buckets=m.SECONDS_BUCKETS)
    io_b = reg.histogram("query.bytes_scanned", "bytes per io span", buckets=m.BYTES_BUCKETS)
    errors = reg.counter("trace.errors", "spans closed with error=")
    with open(path) as f:
        for line in f:
            try:
                event = json.loads(line)
            except ValueError:
                continue
            root = event.get("trace") or {}
            queries.inc()
            if root.get("wall_s") is not None:
                q_s.observe(root["wall_s"])
            for span in _walk_span(root):
                if span.get("error"):
                    errors.inc()
                if span.get("wall_s") is None:
                    continue
                name = span.get("name", "")
                if name.startswith("execute."):
                    op_s.observe(span["wall_s"])
                attrs = span.get("attrs") or {}
                if name.startswith("io.") and "bytes" in attrs:
                    io_b.observe(float(attrs["bytes"]))
    return reg


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m hyperspace_tpu.obs.export",
        description="Prometheus-style text exposition of hyperspace metrics.",
    )
    ap.add_argument(
        "--sink", help="aggregate a JSON-lines trace sink file instead of the live registry"
    )
    args = ap.parse_args(argv)
    if args.sink:
        reg = registry_from_sink(args.sink)
    else:
        # Declare the core metric families so a fresh process exposes
        # the full schema (zeros) instead of an empty page.
        import hyperspace_tpu.obs.profile  # noqa: F401 — declares query.* metrics
        import hyperspace_tpu.stats  # noqa: F401 — declares fault-plane counters

        reg = None
    sys.stdout.write(render_prometheus(reg))
    return 0


if __name__ == "__main__":
    sys.exit(main())
