"""Per-query profiles: what one query did, operator by operator.

A :class:`QueryProfile` is assembled by ``session.run()`` after every
query from (a) the executed physical plan — each
:class:`~hyperspace_tpu.execution.physical.PhysicalNode` now carries its
measured wall time next to the rows/files/kernel evidence it already
recorded — and (b) the query's span tree when tracing is enabled
(``hyperspace.obs.enabled``). The physical side is always present (its
cost is two ``perf_counter`` calls per operator), so every query yields
a profile even with tracing off; the trace side adds IO/cache/rule/retry
depth and goes to the JSON-lines sink.

``session.last_profile()`` returns the most recent profile;
``explain(mode="analyze")`` renders it (explain/plan_analyzer.py);
completed profiles also feed the process metrics registry (operator
wall-time, bytes-scanned, and bucket-fan-out histograms).
"""

from __future__ import annotations

import dataclasses
from typing import Any

from hyperspace_tpu.obs import metrics

OPERATOR_SECONDS = metrics.histogram(
    "query.operator.seconds", "per-operator wall time", buckets=metrics.SECONDS_BUCKETS
)
QUERY_SECONDS = metrics.histogram(
    "query.seconds", "end-to-end session.run wall time", buckets=metrics.SECONDS_BUCKETS
)
BYTES_SCANNED = metrics.histogram(
    "query.bytes_scanned", "physical bytes decoded per query", buckets=metrics.BYTES_BUCKETS
)
BUCKET_FANOUT = metrics.histogram(
    "query.bucket_fanout", "files read per scan operator", buckets=metrics.COUNT_BUCKETS
)
QUERY_COUNT = metrics.counter("query.count", "queries executed via session.run")


@dataclasses.dataclass
class OperatorProfile:
    """One executed operator: identity + measured cost. `detail` carries
    the operator-specific evidence the executor recorded (files, bytes,
    kernel, venue, prune counts, ...)."""

    op: str
    wall_s: float
    rows_out: int | None
    detail: dict
    children: list["OperatorProfile"]

    @property
    def rows_in(self) -> int | None:
        """Rows flowing in from child operators (None for leaves)."""
        if not self.children:
            return None
        return sum(c.rows_out or 0 for c in self.children)

    def self_s(self) -> float:
        return max(0.0, self.wall_s - sum(c.wall_s for c in self.children))

    def walk(self):
        yield self
        for c in self.children:
            yield from c.walk()

    def to_json(self) -> dict:
        return {
            "op": self.op,
            "wall_s": self.wall_s,
            "rows_out": self.rows_out,
            "rows_in": self.rows_in,
            "detail": dict(self.detail),
            "children": [c.to_json() for c in self.children],
        }


@dataclasses.dataclass
class QueryProfile:
    """Everything one query did: operator tree with measured wall times,
    executor totals, venue/device placement, cache and fallback
    outcomes, and (when tracing was on) the full span tree."""

    total_s: float
    root: OperatorProfile | None
    stats: dict  # executor.stats copy (files read/pruned, paths, kernels)
    venue: dict  # platform/devices + per-family path choices
    cache: dict  # decoded-table + device-cache hit/miss deltas for THIS query
    fallback: dict  # replan attempts + degraded indexes
    trace: dict | None = None  # span tree (None when obs disabled)

    def operators(self) -> list[OperatorProfile]:
        return list(self.root.walk()) if self.root is not None else []

    def operator_total_s(self) -> float:
        """Sum of per-operator SELF times ≈ root wall time (the invariant
        tests pin: attribution loses nothing)."""
        return sum(op.self_s() for op in self.operators())

    def to_json(self) -> dict:
        return {
            "total_s": self.total_s,
            "operators": self.root.to_json() if self.root is not None else None,
            "stats": dict(self.stats),
            "venue": dict(self.venue),
            "cache": dict(self.cache),
            "fallback": dict(self.fallback),
            "trace": self.trace,
        }


def _from_physical(node) -> OperatorProfile:
    return OperatorProfile(
        op=node.op,
        wall_s=float(getattr(node, "wall_s", None) or 0.0),
        rows_out=node.rows_out,
        detail=dict(node.detail),
        children=[_from_physical(c) for c in node.children],
    )


def build_profile(
    *,
    total_s: float,
    physical_plan,
    stats: dict,
    venue: dict,
    cache: dict,
    fallback: dict,
    trace_root=None,
) -> QueryProfile:
    """Assemble the profile and feed the completed query's numbers into
    the process metrics registry."""
    root = _from_physical(physical_plan) if physical_plan is not None else None
    profile = QueryProfile(
        total_s=total_s,
        root=root,
        stats=dict(stats),
        venue=dict(venue),
        cache=dict(cache),
        fallback=dict(fallback),
        trace=trace_root.to_json() if trace_root is not None else None,
    )
    QUERY_COUNT.inc()
    QUERY_SECONDS.observe(total_s)
    BYTES_SCANNED.observe(float(stats.get("bytes_scanned", 0) or 0))
    for op in profile.operators():
        OPERATOR_SECONDS.observe(op.self_s())
        if "files" in op.detail and op.op.startswith(("IndexScan", "TableScan", "Index")):
            BUCKET_FANOUT.observe(float(op.detail["files"]))
    return profile


def render(profile: QueryProfile) -> str:
    """Text rendering for ``explain(mode="analyze")``: the operator tree
    annotated with measured wall time / rows / bytes, then the totals,
    venue, cache, and fallback sections."""
    out = ["=" * 64, "EXPLAIN ANALYZE", "=" * 64]
    total = max(profile.total_s, 1e-12)

    def fmt_bytes(n: float) -> str:
        for unit in ("B", "KiB", "MiB", "GiB"):
            if n < 1024 or unit == "GiB":
                return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
            n /= 1024
        return f"{n:.1f}GiB"

    def walk(op: OperatorProfile, indent: int) -> None:
        parts = [f"{'  ' * indent}{op.op}"]
        parts.append(f"time={op.wall_s * 1e3:.2f}ms ({100 * op.wall_s / total:.1f}%)")
        if op.rows_out is not None:
            rin = op.rows_in
            parts.append(f"rows={rin if rin is not None else '-'}→{op.rows_out}")
        if "bytes" in op.detail:
            parts.append(f"bytes={fmt_bytes(op.detail['bytes'])}")
        for k in sorted(op.detail):
            if k == "bytes":
                continue
            parts.append(f"{k}={op.detail[k]}")
        out.append("  ".join(str(p) for p in parts))
        for c in op.children:
            walk(c, indent + 1)

    if profile.root is not None:
        walk(profile.root, 0)
    out.append("-" * 64)
    out.append(
        f"total: {profile.total_s * 1e3:.2f}ms  "
        f"(operator self-time {profile.operator_total_s() * 1e3:.2f}ms)"
    )
    st = profile.stats
    out.append(
        f"io: files read {st.get('files_read', 0)}, pruned {st.get('files_pruned', 0)}; "
        f"rows pruned {st.get('rows_pruned', 0)}; "
        f"bytes scanned {fmt_bytes(st.get('bytes_scanned', 0) or 0)}"
    )
    v = profile.venue
    vparts = [f"platform={v.get('platform')}"]
    for fam in ("join_path", "join_kernel", "agg_path"):
        if st.get(fam):
            vparts.append(f"{fam}={st[fam]}")
    if st.get("join_devices"):
        vparts.append(f"devices={st['join_devices']}")
    out.append("venue: " + "  ".join(vparts))
    c = profile.cache
    out.append(
        "cache: table {t_hits}h/{t_miss}m  device {d_hits}h/{d_miss}m  derived {h_hits}h/{h_miss}m".format(
            t_hits=c.get("table_hits", 0), t_miss=c.get("table_misses", 0),
            d_hits=c.get("device_hits", 0), d_miss=c.get("device_misses", 0),
            h_hits=c.get("derived_hits", 0), h_miss=c.get("derived_misses", 0),
        )
    )
    fb = profile.fallback
    if fb.get("replans") or fb.get("degraded_indexes"):
        out.append(
            f"fallback: replans={fb.get('replans', 0)} "
            f"degraded={fb.get('degraded_indexes', [])}"
        )
    routing = st.get("advisor_routing")
    if routing:
        # Adaptive routing verdict (docs/advisor.md): which path the
        # ledger sent this query down, and whether that was a demotion.
        out.append(
            f"routing: {routing.get('decision')}"
            + (" (demoted by measured history)" if routing.get("demoted") else "")
        )
    if profile.trace is None:
        out.append("(tracing disabled — set hyperspace.obs.enabled for span detail)")
    return "\n".join(out)
