"""Zero-dependency tracer: nestable spans over a contextvar.

A :class:`Span` measures one unit of work (``perf_counter`` wall time),
carries free-form attributes and point-in-time events, and nests: the
span active when another opens becomes its parent. The active span lives
in a :data:`contextvars.ContextVar`, so nesting follows the call stack —
including across ``await``-free thread hops when the submitted task is
wrapped with :func:`wrap` (worker threads start with an empty context;
the wrapper re-plants the caller's active span for the task's duration).

Design constraints:

- **Near-zero overhead when disabled.** ``span()``/``trace()`` check one
  module global and return shared no-op singletons — no Span object, no
  attrs dict, no contextvar write. ``hyperspace.obs.enabled`` routes
  here (config.py).
- **Spans always close.** ``__exit__`` runs on ``BaseException`` too, so
  a simulated crash (faults.CrashPoint) or an injected FaultError still
  records ``error=`` and the duration before propagating — the fault
  plane is *more* visible under tracing, never less.
- **Recording needs an active trace.** ``span()`` is a no-op unless some
  enclosing :func:`trace` established a root (``session.run`` and
  ``Action.run`` do). Instrumented library code can therefore call
  ``span()`` unconditionally; outside a traced request nothing records.

Finished root traces go to the JSON-lines sink when one is configured
(``hyperspace.obs.sink``), and the last root is kept in-process for
``session.last_profile()`` / tests (:func:`last_trace`).
"""

from __future__ import annotations

import collections
import contextvars
import itertools
import json
import os
import threading
import time
from typing import Any, Callable

# Span names a spawned WORKER process may emit — the coordinator's lane
# vocabulary for traces that ship back across the process boundary as
# to_json() dicts and are adopted into the recent-root ring
# (adopt_root; parallel/procpool.py ships them). Declared for the same
# reason stats.KNOWN_COUNTERS is: an undeclared worker span name is a
# typo'd (or unreviewed) lane the chrome exporter and /debug/trace
# would silently grow. Statically enforced over the inferred spawn
# domain by analysis rule HSL022 (docs/static_analysis.md); keep it a
# plain literal of string constants — the analyzer reads it by AST.
KNOWN_WORKER_SPANS = (
    "build.p1.worker",
    "build.p1.decode",
    "build.p1.spill",
    "build.p2.worker",
    "build.p2.read",
    "build.p2.sort",
    "build.p2.write",
    "io.read",
    "io.footers",
    "device.stage",
)

_current: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "hyperspace_obs_span", default=None
)
# Root-trace id of the active trace (None outside one). Distinct from
# _current so events/children anywhere in the tree can cite the ROOT id
# without a parent-pointer walk (spans only link downward).
_trace_id: contextvars.ContextVar["str | None"] = contextvars.ContextVar(
    "hyperspace_obs_trace_id", default=None
)

_enabled = True  # hyperspace.obs.enabled; module-global fast path
_sink_path: str | None = None  # hyperspace.obs.sink; None = no export
_sink_lock = threading.Lock()
_last_trace: "Span | None" = None  # most recently finished ROOT span
# Bounded ring of recently finished root spans — the live feed behind
# /debug/trace and the chrome exporter (docs/observability.md). Kept
# small: a root span tree is a few KB; 32 of them is bounded memory.
RECENT_ROOTS_MAX = 32
_recent_lock = threading.Lock()
_recent_roots: collections.deque = collections.deque(maxlen=RECENT_ROOTS_MAX)
_trace_seq = itertools.count(1)  # itertools.count is GIL-atomic


class Span:
    """One timed unit of work. Use as a context manager; attributes via
    ``set(k=v)`` (chainable), point events via ``add_event``."""

    __slots__ = (
        "name", "attrs", "children", "events", "start_s", "wall_s",
        "error", "tid", "trace_id", "_token",
    )

    def __init__(self, name: str, attrs: dict):
        self.name = name
        self.attrs = attrs
        self.children: list[Span] = []
        self.events: list[dict] = []
        self.start_s: float | None = None
        self.wall_s: float | None = None
        self.error: str | None = None
        self.tid: int | None = None  # OS thread the span ran on
        self.trace_id: str | None = None  # set on ROOT spans only
        self._token = None

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def rename(self, name: str) -> "Span":
        self.name = name
        return self

    def add_event(self, name: str, **attrs) -> None:
        self.events.append({"name": name, **attrs})

    def __enter__(self) -> "Span":
        parent = _current.get()
        if parent is not None:
            # list.append is atomic under the GIL — worker threads
            # re-planted on this parent via wrap() attach children
            # concurrently without a lock.
            parent.children.append(self)
        self._token = _current.set(self)
        self.tid = threading.get_ident()
        self.start_s = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        # BaseException included: a CrashPoint flying through still
        # closes (and error-tags) every open span on its way out.
        self.wall_s = time.perf_counter() - (self.start_s or 0.0)
        if exc is not None and self.error is None:
            self.error = f"{exc_type.__name__}: {exc}"
        _current.reset(self._token)
        return False

    def self_s(self) -> float:
        """Wall time NOT attributed to child spans."""
        own = self.wall_s or 0.0
        return max(0.0, own - sum(c.wall_s or 0.0 for c in self.children))

    def walk(self):
        yield self
        for c in self.children:
            yield from c.walk()

    def to_json(self) -> dict:
        out: dict[str, Any] = {"name": self.name, "wall_s": self.wall_s}
        # Timeline fields for the chrome exporter (obs/export.py):
        # start_s is this process's perf_counter clock (comparable across
        # spans of one process; the exporter normalizes), tid lanes the
        # span onto the OS thread it ran on.
        if self.start_s is not None:
            out["t0_s"] = self.start_s
        if self.tid is not None:
            out["tid"] = self.tid
        if self.trace_id is not None:
            out["trace_id"] = self.trace_id
        if self.attrs:
            out["attrs"] = dict(self.attrs)
        if self.error is not None:
            out["error"] = self.error
        if self.events:
            out["events"] = list(self.events)
        if self.children:
            out["children"] = [c.to_json() for c in self.children]
        return out


class _NoopSpan:
    """Shared do-nothing span: the disabled/untraced fast path. One
    module-level instance; every method is a cheap no-op so call sites
    never branch."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set(self, **attrs) -> "_NoopSpan":
        return self

    def rename(self, name: str) -> "_NoopSpan":
        return self

    def add_event(self, name: str, **attrs) -> None:
        pass


NOOP = _NoopSpan()


class _TraceHandle:
    """Context manager establishing (or joining) a trace. Entering yields
    the root span; exiting a true root records it as the last trace and
    emits one JSON line to the sink."""

    __slots__ = ("_span", "_is_root", "_id_token")

    def __init__(self, span: Span):
        self._span = span
        self._is_root = False
        self._id_token = None

    def __enter__(self) -> Span:
        self._is_root = _current.get() is None
        if self._is_root:
            # Root id: pid-qualified so sink lines from several processes
            # stay distinguishable after aggregation.
            self._span.trace_id = f"{os.getpid()}-{next(_trace_seq)}"
            self._id_token = _trace_id.set(self._span.trace_id)
        return self._span.__enter__()

    def __exit__(self, exc_type, exc, tb) -> bool:
        self._span.__exit__(exc_type, exc, tb)
        if self._is_root:
            global _last_trace
            _last_trace = self._span
            _trace_id.reset(self._id_token)
            with _recent_lock:
                _recent_roots.append(self._span)
            _emit(self._span)
            _journal_root(self._span)
        return False


class _NoopTrace:
    __slots__ = ()

    def __enter__(self) -> _NoopSpan:
        return NOOP

    def __exit__(self, *exc) -> bool:
        return False


_NOOP_TRACE = _NoopTrace()


def enabled() -> bool:
    return _enabled


def set_enabled(on: bool) -> None:
    """`hyperspace.obs.enabled` (config.py routes here). Process-global,
    like the metrics it feeds."""
    global _enabled
    _enabled = bool(on)


def configure(sink: str | None = ...) -> None:
    """Adjust module-global tracer config (`hyperspace.obs.*` keys).
    `sink` is a JSON-lines path receiving one event per finished root
    trace; None disables export."""
    global _sink_path
    if sink is not ...:
        _sink_path = str(sink) if sink else None


def sink_path() -> str | None:
    return _sink_path


def trace(name: str, **attrs):
    """Open a ROOT span (or a plain child span when a trace is already
    active — nested requests don't double-root). No-op when disabled."""
    if not _enabled:
        return _NOOP_TRACE
    return _TraceHandle(Span(name, attrs))


def span(name: str, **attrs):
    """Open a child span under the active trace. Returns the shared
    no-op singleton when disabled or untraced — nothing is allocated."""
    if not _enabled or _current.get() is None:
        return NOOP
    return Span(name, attrs)


def current_span() -> "Span | None":
    return _current.get()


def annotate(**attrs) -> None:
    """Attach attributes to the active span, if any (used by code that
    has evidence but did not open the span — e.g. a rule recording why
    it failed)."""
    cur = _current.get()
    if cur is not None:
        cur.attrs.update(attrs)


def event(name: str, **attrs) -> None:
    """Record a point-in-time event on the active span (retry attempts,
    evictions). No-op when untraced."""
    if not _enabled:
        return
    cur = _current.get()
    if cur is not None:
        cur.add_event(name, **attrs)


def wrap(fn: Callable) -> Callable:
    """Propagate the caller's active span into a worker-thread task.

    ThreadPoolExecutor workers start with an empty context, so spans
    opened inside them would silently detach; wrapping the submitted
    callable re-plants the submitting thread's active span for the
    task's duration (each task sets/resets its own thread's context —
    safe under arbitrary pool fan-out)."""
    if not _enabled:
        return fn
    parent = _current.get()
    if parent is None:
        return fn

    def run(*args, **kwargs):
        token = _current.set(parent)
        try:
            return fn(*args, **kwargs)
        finally:
            _current.reset(token)

    return run


def span_from_json(d: dict) -> Span:
    """Rebuild a Span tree from its :meth:`Span.to_json` dict — the
    inverse used to adopt a worker PROCESS's finished trace into this
    process (spans only ship across process boundaries as dicts)."""
    s = Span(str(d.get("name", "?")), dict(d.get("attrs") or {}))
    s.wall_s = d.get("wall_s")
    s.start_s = d.get("t0_s")
    s.tid = d.get("tid")
    s.trace_id = d.get("trace_id")
    s.error = d.get("error")
    s.events = list(d.get("events") or [])
    s.children = [span_from_json(c) for c in d.get("children") or ()]
    return s


def adopt_root(root: "dict | None") -> None:
    """Adopt a FOREIGN root span — a worker process's finished trace,
    shipped back as its ``to_json()`` dict — into this process's
    recent-root ring and sink. The root keeps its own pid-qualified
    trace_id, so the chrome exporter lanes it on the worker's pid track
    (one lane per worker process). Never raises on a malformed dict
    (adoption is telemetry, not control flow)."""
    if not _enabled or not root:
        return
    try:
        span = span_from_json(root)
    except (TypeError, ValueError, AttributeError):
        return
    with _recent_lock:
        _recent_roots.append(span)
    _emit(span)
    _journal_root(span)


def _journal_root(span: "Span") -> None:
    """Durable tap: completed root spans (local and adopted) also land
    in the telemetry journal (obs/journal.py). The journal is advisory
    and off by default; `to_json` is only paid when it is on."""
    from hyperspace_tpu.obs import journal as _journal

    if _journal.enabled():
        _journal.record_span(span.to_json())


def last_trace() -> "Span | None":
    """The most recently finished root span (None before the first)."""
    return _last_trace


def current_trace_id() -> "str | None":
    """The active root trace's id (None outside a trace) — the
    correlation key structured events carry (obs/events.py)."""
    return _trace_id.get()


def recent_roots(limit: int | None = None) -> "list[Span]":
    """The most recently finished root spans, oldest first (bounded at
    RECENT_ROOTS_MAX). Feeds /debug/trace and the chrome exporter."""
    with _recent_lock:
        roots = list(_recent_roots)
    return roots if limit is None else roots[-int(limit):]


def reset() -> None:
    """Drop the last trace, recent roots, and sink config (test
    isolation)."""
    global _last_trace, _sink_path
    _last_trace = None
    _sink_path = None
    with _recent_lock:
        _recent_roots.clear()


def _emit(root: Span) -> None:
    """Append one JSON line per finished root trace to the sink. Export
    must never fail a query: errors are swallowed."""
    if _sink_path is None:
        return
    # Wall-clock stamp (not a duration): sink lines are correlated with
    # external logs, which speak wall time.
    line = json.dumps({"ts": time.time(), "trace": root.to_json()}, default=str)
    try:
        with _sink_lock, open(_sink_path, "a") as f:
            f.write(line + "\n")
    except OSError:
        pass
