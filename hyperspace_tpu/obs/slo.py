"""Declared SLO objectives with multi-window burn-rate tracking.

The serving tier's ROADMAP contract — "bounded p99, typed rejections,
never collapse" — is only checkable at runtime if the process itself
computes how fast it is consuming its error budget. This module does
the standard SRE multi-window burn-rate math over the *already
declared* serve metrics (obs/metrics.py):

- **availability** — of the queries the scheduler admitted, the
  fraction that completed (failures, timeouts, and shutdown
  cancellations spend budget);
- **p99 latency** — the fraction of served queries finishing under the
  configured threshold must stay ≥ 0.99 (the threshold maps onto the
  latency histogram's bucket bounds, so "good" counts come straight
  from the cumulative bucket counts).

Objectives are **declared** in :data:`KNOWN_OBJECTIVES`, exactly like
``stats.KNOWN_COUNTERS``: asking the tracker about an undeclared
objective raises, so a typo'd dashboard query dies loudly instead of
silently reporting a healthy nothing.

Burn rate = (bad fraction over a window) / (1 - target). 1.0 means
"spending budget exactly as fast as the SLO allows"; the classic page
condition is a *pair* of windows burning fast simultaneously (the long
window proves it is real, the short window proves it is still
happening). The tracker keeps a bounded ring of cumulative-counter
samples and differences windows out of it; scrapes (obs/http.py) drive
sampling, so a process that nobody watches spends nothing.
"""

from __future__ import annotations

import bisect
import collections
import dataclasses
import threading
import time

from hyperspace_tpu.obs import events as _events
from hyperspace_tpu.obs import journal as _journal
from hyperspace_tpu.obs import metrics as _metrics

# Default objective targets (`hyperspace.obs.slo.*` keys override).
DEFAULT_AVAILABILITY_TARGET = 0.999
DEFAULT_LATENCY_P99_SECONDS = 1.0
LATENCY_TARGET_RATIO = 0.99  # "p99 under threshold" as a good-ratio SLO

# Multi-window verdict pairs (seconds, burn threshold): page when BOTH
# windows of the page pair burn above 14.4 (i.e. a 99.9% budget gone in
# ~2 days), warn when both warn windows burn above 6. Windows clamp to
# the observed sample span — a young process judges on what it has.
PAGE_WINDOWS = ((60.0, 14.4), (600.0, 14.4))
WARN_WINDOWS = ((300.0, 6.0), (3600.0, 6.0))

KNOWN_OBJECTIVES: dict[str, str] = {
    "serve.availability": "admitted queries that completed (vs failed/timed out/cancelled)",
    "serve.latency_p99": "served queries finishing under the configured latency threshold",
}

_EVT_BURN = _events.declare("slo.burn")


@dataclasses.dataclass(frozen=True)
class _Sample:
    t: float
    good: float
    total: float


class BurnRate:
    """Per-objective sample ring + window math."""

    def __init__(self, name: str, target: float, max_samples: int = 512):
        self.name = name
        self.target = float(target)
        self._lock = threading.Lock()
        self._samples: collections.deque = collections.deque(maxlen=int(max_samples))

    def add(self, good: float, total: float, now: float) -> None:
        with self._lock:
            self._samples.append(_Sample(float(now), float(good), float(total)))

    def window_burn(self, window_s: float, now: float | None = None) -> float | None:
        """Burn rate over the trailing window (None with <2 samples or
        no traffic in the window). Windows clamp to the observed span."""
        with self._lock:
            samples = list(self._samples)
        if len(samples) < 2:
            return None
        if now is None:
            now = samples[-1].t
        # Oldest sample still inside the window (clamped to what we
        # have): cumulative counters difference out to window deltas.
        times = [s.t for s in samples]
        i = bisect.bisect_left(times, now - window_s)
        base, head = samples[min(i, len(samples) - 2)], samples[-1]
        total = head.total - base.total
        if total <= 0:
            return None
        good = head.good - base.good
        bad_fraction = max(0.0, 1.0 - good / total)
        budget = 1.0 - self.target
        if budget <= 0:
            return float("inf") if bad_fraction > 0 else 0.0
        return bad_fraction / budget

    def verdict(self, now: float | None = None) -> dict:
        """{"verdict": ok|warn|page, "windows": {label: burn|None}}."""
        windows: dict[str, float | None] = {}

        def burns(pairs) -> list:
            out = []
            for w, threshold in pairs:
                b = self.window_burn(w, now=now)
                windows[f"{int(w)}s"] = b
                out.append((b, threshold))
            return out

        page = burns(PAGE_WINDOWS)
        warn = burns(WARN_WINDOWS)
        verdict = "ok"
        if all(b is not None and b >= t for b, t in warn):
            verdict = "warn"
        if all(b is not None and b >= t for b, t in page):
            verdict = "page"
        return {"verdict": verdict, "target": self.target, "windows": windows}

    def reset(self) -> None:
        with self._lock:
            self._samples.clear()


class SLOTracker:
    """The process-global tracker over the declared objectives."""

    def __init__(self):
        self._lock = threading.Lock()
        self.availability_target = DEFAULT_AVAILABILITY_TARGET
        self.latency_threshold_s = DEFAULT_LATENCY_P99_SECONDS
        self._rates = {
            "serve.availability": BurnRate("serve.availability", DEFAULT_AVAILABILITY_TARGET),
            "serve.latency_p99": BurnRate("serve.latency_p99", LATENCY_TARGET_RATIO),
        }
        # Burn gauges so a plain /metrics scrape carries the computed
        # short-window burn rate per objective (-1 = not yet computable).
        self._gauges = {
            name: _metrics.gauge(
                f"slo.{name}.burn_rate",
                f"short-window error-budget burn rate: {doc} (-1 until computable)",
            )
            for name, doc in KNOWN_OBJECTIVES.items()
        }
        self._paged: set[str] = set()
        # Last verdict per objective — the journal records verdict
        # TRANSITIONS only (ok→page, page→ok, …), not every evaluate.
        self._last_verdict: dict[str, str] = {}

    def objective(self, name: str) -> BurnRate:
        """The tracker for a DECLARED objective; undeclared names raise
        (the registry contract KNOWN_COUNTERS established)."""
        rate = self._rates.get(name)
        if rate is None:
            raise KeyError(
                f"undeclared SLO objective {name!r} — declare it in "
                f"obs.slo.KNOWN_OBJECTIVES (undeclared objectives reporting "
                f"healthy nothings is the failure mode this registry removes)"
            )
        return rate

    def configure(
        self,
        availability_target: float | None = None,
        latency_threshold_s: float | None = None,
    ) -> None:
        with self._lock:
            if availability_target is not None:
                self.availability_target = float(availability_target)
                self._rates["serve.availability"].target = float(availability_target)
            if latency_threshold_s is not None:
                self.latency_threshold_s = float(latency_threshold_s)

    def sample(self, now: float | None = None) -> None:
        """Record one cumulative sample per objective from the live
        serve metrics. Driven by /metrics and /healthz scrapes; cheap
        enough to run per scrape."""
        if now is None:
            now = time.monotonic()
        reg = _metrics.REGISTRY
        completed = _counter_value(reg, "serve.completed")
        failed = _counter_value(reg, "serve.failed")
        timeouts = _counter_value(reg, "serve.timeouts")
        cancelled = _counter_value(reg, "serve.cancelled")
        total = completed + failed + timeouts + cancelled
        self._rates["serve.availability"].add(completed, total, now)
        good, count = self._latency_good(reg)
        self._rates["serve.latency_p99"].add(good, count, now)

    def _latency_good(self, reg) -> tuple[float, float]:
        """(queries under the threshold, all queries) from the latency
        histogram's cumulative bucket counts. The threshold maps to the
        largest bucket bound at or below it — conservative: a query
        counts as "good" only when its bucket proves it finished under
        the threshold."""
        hist = reg.get("serve.latency.seconds")
        if hist is None or hist.kind != "histogram":
            return 0.0, 0.0
        with self._lock:
            threshold = self.latency_threshold_s
        good = 0
        for le, cum in hist.bucket_counts():
            if le > threshold:
                break
            good = cum
        return float(good), float(hist.count)

    def evaluate(self, now: float | None = None) -> dict:
        """Verdicts for every declared objective; updates the burn
        gauges and emits one ``slo.burn`` event per fresh page verdict
        (re-armed when the objective recovers)."""
        out: dict[str, dict] = {}
        for name in sorted(KNOWN_OBJECTIVES):
            rate = self._rates[name]
            v = rate.verdict(now=now)
            short = next(iter(v["windows"].values()))
            self._gauges[name].set(short if short is not None else -1.0)
            with self._lock:
                fresh_page = v["verdict"] == "page" and name not in self._paged
                if v["verdict"] == "page":
                    self._paged.add(name)
                else:
                    self._paged.discard(name)
                previous = self._last_verdict.get(name, "ok")
                self._last_verdict[name] = v["verdict"]
            if fresh_page:
                _EVT_BURN.emit(objective=name, **{k: w for k, w in v["windows"].items()})
            if previous != v["verdict"]:
                # Durable tap: the page AND the recovery land in the
                # telemetry journal (obs/journal.py) — the incident
                # bundle's evidence that the burn happened and ended.
                _journal.record_slo(name, v["verdict"], previous,
                                    detail={"windows": v["windows"]})
            out[name] = v
        return out

    def reset(self) -> None:
        with self._lock:
            self.availability_target = DEFAULT_AVAILABILITY_TARGET
            self.latency_threshold_s = DEFAULT_LATENCY_P99_SECONDS
            self._paged.clear()
            self._last_verdict.clear()
        for name, rate in self._rates.items():
            rate.reset()
            rate.target = (
                DEFAULT_AVAILABILITY_TARGET
                if name == "serve.availability"
                else LATENCY_TARGET_RATIO
            )


def _counter_value(reg, name: str) -> float:
    m = reg.get(name)
    return float(m.value) if m is not None else 0.0


TRACKER = SLOTracker()


def objective(name: str) -> BurnRate:
    return TRACKER.objective(name)


def sample(now: float | None = None) -> None:
    TRACKER.sample(now=now)


def evaluate(now: float | None = None) -> dict:
    return TRACKER.evaluate(now=now)


def configure(**kwargs) -> None:
    TRACKER.configure(**kwargs)


def reset() -> None:
    """Restore targets and drop sample history (test isolation)."""
    TRACKER.reset()
