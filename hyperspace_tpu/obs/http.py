"""Zero-dependency runtime health endpoints: /metrics, /healthz, /debug.

A stdlib :class:`~http.server.ThreadingHTTPServer` exposing the whole
observability plane of a *running* process — until now every signal
(spans, metrics, profiles) was only visible post-hoc through the sink
file or `explain(analyze)`:

- ``GET /metrics`` — Prometheus text exposition of the live registry
  (process gauges and SLO burn gauges refreshed per scrape);
- ``GET /healthz`` — one JSON verdict an operator or load balancer can
  act on: index health map, scheduler saturation, SLO burn verdicts,
  jit/compile pressure, event counts. 200 while serving is viable
  (``ok``/``degraded``), 503 once an SLO page verdict fires
  (``critical``);
- ``GET /debug/events[?level=warn&limit=100]`` — the structured event
  ring (obs/events.py);
- ``GET /debug/trace[?limit=8]`` — recent root span trees
  (obs/trace.py), the live counterpart of the JSON-lines sink;
- ``GET /debug/incidents[?name=<bundle>]`` — read-only index of the
  controller's incident bundles (serve/controller.py,
  docs/fault_tolerance.md "incident bundles"): the list, or one
  bundle's manifest + file inventory.

Lifecycle: a :class:`HealthServer` can be constructed standalone, but
the normal path is ``hyperspace.obs.http.enabled=true`` + a
``QueryServer`` (serve/scheduler.py), which acquires the process-global
refcounted instance on construction and releases it on shutdown — N
QueryServers share one port, and the last shutdown closes the socket.
When the key is false (the default) nothing here is imported, no thread
starts, and no socket exists — the zero-overhead contract the tracer's
disabled mode established.

Health *providers* (sessions, query servers) register weakly: the
endpoint never keeps a dead session alive, and a GC'd provider simply
drops out of /healthz.
"""

from __future__ import annotations

import json
import threading
import weakref
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from hyperspace_tpu.obs import events as _events
from hyperspace_tpu.obs import metrics as _metrics
from hyperspace_tpu.obs import runtime as _runtime
from hyperspace_tpu.obs import slo as _slo
from hyperspace_tpu.obs import trace as _trace

DEFAULT_HOST = "127.0.0.1"
DEFAULT_PORT = 0  # ephemeral: the bound port is on HealthServer.port

_REQUESTS = _metrics.counter("obs.http.requests", "health-plane HTTP requests served")
_ERRORS = _metrics.counter("obs.http.errors", "health-plane requests that failed (500)")

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class HealthServer:
    """One process's health plane: a bound socket + daemon serve thread.

    Usable standalone::

        hs = HealthServer(host="0.0.0.0", port=9464)
        hs.attach_session(session)
        hs.start()
        ... # scrape http://host:port/metrics
        hs.stop()
    """

    def __init__(self, host: str = DEFAULT_HOST, port: int = DEFAULT_PORT):
        self.host = host
        self._requested_port = int(port)
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()
        # Weak provider sets: a dead session/server drops out of healthz.
        self._sessions: weakref.WeakSet = weakref.WeakSet()
        self._servers: weakref.WeakSet = weakref.WeakSet()
        self._controllers: weakref.WeakSet = weakref.WeakSet()
        self._supervisors: weakref.WeakSet = weakref.WeakSet()
        self._ingests: weakref.WeakSet = weakref.WeakSet()

    # -- providers --------------------------------------------------------
    def attach_session(self, session) -> None:
        with self._lock:
            self._sessions.add(session)

    def attach_server(self, query_server) -> None:
        with self._lock:
            self._servers.add(query_server)

    def detach_server(self, query_server) -> None:
        with self._lock:
            self._servers.discard(query_server)

    def attach_controller(self, controller) -> None:
        """Surface an ops controller's live verdict in /healthz
        (serve/controller.py registers itself on start())."""
        with self._lock:
            self._controllers.add(controller)

    def attach_supervisor(self, supervisor) -> None:
        """Surface a fleet supervisor's member list in /healthz: pids,
        ports, per-member last-heartbeat age — WITHOUT scraping members
        (FleetSupervisor.fleet_summary), so a silently dead member is
        visible between supervisor poll ticks."""
        with self._lock:
            self._supervisors.add(supervisor)

    def attach_ingest(self, daemon) -> None:
        """Surface a continuous-ingestion daemon's live state in
        /healthz — mode, pause flag, per-index freshness lag, last
        committed log ids (ingest/daemon.py registers on start())."""
        with self._lock:
            self._ingests.add(daemon)

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "HealthServer":
        with self._lock:
            if self._httpd is not None:
                return self
            plane = self
            handler = type("_Handler", (_Handler,), {"plane": plane})
            self._httpd = ThreadingHTTPServer((self.host, self._requested_port), handler)
            self._httpd.daemon_threads = True
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                kwargs={"poll_interval": 0.1},
                name="hs-obs-http",
                daemon=True,
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        with self._lock:
            httpd, thread = self._httpd, self._thread
            self._httpd = self._thread = None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if thread is not None:
            thread.join(timeout=5.0)

    @property
    def running(self) -> bool:
        with self._lock:
            return self._httpd is not None

    @property
    def port(self) -> int | None:
        """The actually bound port (resolves port=0), None when stopped."""
        with self._lock:
            httpd = self._httpd
            return httpd.server_address[1] if httpd is not None else None

    def url(self, path: str = "") -> str:
        return f"http://{self.host}:{self.port}{path}"

    # -- views ------------------------------------------------------------
    def healthz(self) -> dict:
        """The health verdict document (also the /healthz body). Carries
        the ACTUALLY-BOUND endpoint address: with ``port=0`` (the
        default — N fleet processes on one host must not fight over one
        configured port) the ephemeral port the kernel picked is
        reported here and via :attr:`port`/:func:`shared`, so a
        supervisor or service discovery can address this member."""
        with self._lock:
            sessions = list(self._sessions)
            servers = list(self._servers)
            controllers = list(self._controllers)
            supervisors = list(self._supervisors)
            ingests = list(self._ingests)
        indexes: dict[str, dict] = {}
        for s in sessions:
            with s._state_lock:
                indexes.update({root: dict(rec) for root, rec in s.index_health.items()})
        scheduler = [srv.saturation() for srv in servers]
        _slo.sample()
        slo_verdicts = _slo.evaluate()
        proc = _runtime.refresh_process_gauges()
        _events.refresh_gauges()
        status = "ok"
        if indexes or any(v["verdict"] == "warn" for v in slo_verdicts.values()):
            status = "degraded"
        if any(v["verdict"] == "page" for v in slo_verdicts.values()):
            status = "critical"
        return {
            "status": status,
            "endpoint": {"host": self.host, "port": self.port},
            "indexes": indexes,
            "scheduler": scheduler,
            # Self-driving operations (serve/controller.py): each
            # attached controller's live verdict — mode, engaged
            # overrides, remaining actuation budget, recent decisions.
            "controller": [c.snapshot() for c in controllers],
            "slo": slo_verdicts,
            "jit": {**proc, "sites": _runtime.jit_report()},
            "events": _events.counts_by_severity(),
            # Fleet topology (serve/fleet/supervisor.py): member
            # pids/ports and per-member last-heartbeat ages, read from
            # registrations — no member scrape on the /healthz path.
            "fleet": [s.fleet_summary() for s in supervisors],
            # Continuous ingestion (ingest/daemon.py): each attached
            # daemon's mode, pause flag, freshness lag, and last
            # committed log ids.
            "ingest": [d.snapshot() for d in ingests],
        }

    def metrics_text(self) -> str:
        from hyperspace_tpu.obs.export import render_prometheus

        _runtime.refresh_process_gauges()
        _slo.sample()
        _slo.evaluate()
        _events.refresh_gauges()
        return render_prometheus()


class _Handler(BaseHTTPRequestHandler):
    plane: HealthServer  # injected per-server subclass (start())

    # Health scrapes are high-frequency; stdlib default logs every
    # request to stderr — route to logging at debug instead.
    def log_message(self, fmt: str, *args) -> None:
        import logging

        logging.getLogger("hyperspace_tpu.obs.http").debug(fmt, *args)

    def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        _REQUESTS.inc()
        try:
            url = urlparse(self.path)
            q = parse_qs(url.query)
            if url.path == "/metrics":
                self._send(200, self.plane.metrics_text(), PROMETHEUS_CONTENT_TYPE)
            elif url.path == "/healthz":
                doc = self.plane.healthz()
                self._send_json(503 if doc["status"] == "critical" else 200, doc)
            elif url.path == "/debug/events":
                level = (q.get("level") or [None])[0]
                limit = int((q.get("limit") or [256])[0])
                self._send_json(200, {"events": _events.recent(level=level, limit=limit)})
            elif url.path == "/debug/trace":
                limit = int((q.get("limit") or [8])[0])
                roots = _trace.recent_roots(limit=limit)
                self._send_json(200, {"traces": [r.to_json() for r in roots]})
            elif url.path == "/debug/incidents":
                # Read-only: list every attached controller's incident
                # bundles, or one bundle's manifest + file inventory via
                # ?name=<bundle dir name> (serve/controller.py).
                name = (q.get("name") or [None])[0]
                with self.plane._lock:
                    controllers = list(self.plane._controllers)
                if name is None:
                    bundles = []
                    for c in controllers:
                        bundles.extend(c.list_incidents())
                    self._send_json(200, {"incidents": bundles})
                else:
                    doc = None
                    for c in controllers:
                        doc = c.read_incident(name)
                        if doc is not None:
                            break
                    if doc is None:
                        self._send_json(404, {"error": f"unknown incident {name!r}"})
                    else:
                        self._send_json(200, doc)
            else:
                self._send_json(404, {"error": f"unknown path {url.path!r}"})
        except (ValueError, KeyError) as e:
            # Bad query params / unknown severity levels: client error.
            self._send_json(400, {"error": str(e)})
        except Exception as e:
            _ERRORS.inc()
            self._send_json(500, {"error": f"{type(e).__name__}: {e}"})

    def _send(self, code: int, body: str, content_type: str) -> None:
        payload = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _send_json(self, code: int, doc: dict) -> None:
        self._send(code, json.dumps(doc, default=str), "application/json")


# -- process-global refcounted instance (QueryServer lifecycle) -----------

_shared_lock = threading.Lock()
_shared: "HealthServer | None" = None
_shared_refs = 0


def acquire(host: str = DEFAULT_HOST, port: int = DEFAULT_PORT) -> HealthServer:
    """The process-shared HealthServer, started on first acquire. Later
    acquirers share the first binding (one port per process — the
    refcounted in-process sharing); every acquire must be paired with a
    :func:`release`. With ``port=0`` the kernel picks an ephemeral port:
    read it back from the returned instance's ``.port`` (or
    ``shared().port``, or the /healthz ``endpoint`` section) — the fleet
    default, so N worker processes on one host never collide."""
    global _shared, _shared_refs
    with _shared_lock:
        if _shared is None:
            _shared = HealthServer(host=host, port=port).start()
        _shared_refs += 1
        return _shared


def release() -> None:
    """Drop one reference; the last release stops the shared server."""
    global _shared, _shared_refs
    with _shared_lock:
        if _shared is None:
            return
        _shared_refs -= 1
        if _shared_refs > 0:
            return
        server, _shared, _shared_refs = _shared, None, 0
    server.stop()


def shared() -> "HealthServer | None":
    """The live shared instance, if any (tests / standalone tools)."""
    with _shared_lock:
        return _shared
