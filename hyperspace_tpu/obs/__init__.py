"""Query-plane observability: tracing spans, metrics, per-query profiles.

The system's value proposition — "the optimizer transparently picked the
index" — is invisible unless every query can explain what it did and
what it cost. This package is that explanation, in three layers
(docs/observability.md):

- **trace** — a zero-dependency tracer with nestable spans
  (``span("execute.join", rows=...)``) threaded through ``session.run``,
  rule application, the executor's operator dispatch, parquet IO, the
  device cache, the retry layer, and the action lifecycle. Contextvar
  based, so worker threads inherit the active trace via
  :func:`trace.wrap`; near-zero overhead when disabled
  (``hyperspace.obs.enabled=false`` ⇒ ``span()`` returns a shared no-op
  singleton, nothing is allocated).
- **metrics** — a declared process-wide registry of counters, gauges,
  and bounded histograms (p50/p95/p99 of operator wall time, bytes
  scanned, bucket fan-out). ``hyperspace_tpu.stats`` is now a compat
  shim over it; undeclared counter names raise instead of silently
  creating new counters (lint rule HSL007 enforces call sites too).
- **profile** — a per-query :class:`~hyperspace_tpu.obs.profile.QueryProfile`
  assembled from the executed physical plan and the span tree: operator
  tree with wall time, rows in/out, bytes, venue, cache and fallback
  outcomes. ``session.last_profile()`` returns it;
  ``explain(mode="analyze")`` renders it.

Export: a JSON-lines event sink (``hyperspace.obs.sink``) receives one
line per finished root trace, and ``python -m hyperspace_tpu.obs.export``
renders Prometheus-style text exposition (of the live registry, or
aggregated from a sink file) or — ``--format chrome`` — a Chrome Trace
Event timeline of the span trees (Perfetto/chrome://tracing).

The **runtime health plane** layers live visibility on top
(docs/observability.md "live endpoints"):

- **events** — a bounded, severity-leveled structured event ring
  (fallback taken, index quarantined, recompile storm, ...), each
  record carrying the active trace id;
- **runtime** — JIT/compile introspection: per-call-site compile
  counts via the ``compat.jit`` entry point, recompile-storm detection
  (the dynamic mirror of lint rule HSL015), and the
  ``jit.live_executables`` / ``proc.map_count`` / RSS gauges behind the
  XLA:CPU map-count segfault guard;
- **slo** — declared objectives (availability, p99 latency) with
  multi-window error-budget burn rates;
- **journal** — a durable, bounded, crash-safe JSONL journal of
  events, root spans, SLO transitions, and metrics snapshots, one
  ``<root>/<pid>/`` dir per process; the fleet merge and the
  controller's incident bundles read it (docs/observability.md
  "telemetry journal");
- **http** — ``/metrics``, ``/healthz``, ``/debug/events``, and
  ``/debug/trace`` over a zero-dependency stdlib server riding the
  QueryServer lifecycle (``hyperspace.obs.http.*``).
"""

from hyperspace_tpu.obs import events, journal, metrics, runtime, slo, trace
from hyperspace_tpu.obs.trace import annotate, current_span, event, set_enabled, span

__all__ = [
    "annotate",
    "current_span",
    "event",
    "events",
    "journal",
    "metrics",
    "runtime",
    "set_enabled",
    "slo",
    "span",
    "trace",
]
