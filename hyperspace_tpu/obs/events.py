"""Bounded, severity-leveled structured event ring.

Counters say *how often*; spans say *how long*; this module records
*that something notable happened* — a fallback taken, an index
quarantined, a recompile storm — as one structured record an operator
can read live from ``/debug/events`` (obs/http.py) while the system is
running, instead of reconstructing it from counter deltas after the
fact. Each event carries the active root-trace id (obs/trace.py) so an
anomaly links straight to the query that caused it.

Event names are **declared** in :data:`KNOWN_EVENTS`, the event analog
of ``stats.KNOWN_COUNTERS``: instrumented modules obtain a handle at
import time via :func:`declare`, which raises immediately for an
undeclared name — the typo dies at import, and the handle's ``emit``
itself can never raise (several call sites sit inside narrow declared
error contracts, e.g. ``QueryServer.submit``; emitting telemetry must
not widen them).

The ring is process-global and bounded (``hyperspace.obs.events
.maxEvents``): old events age out, ``obs.events.dropped`` counts how
many did, and memory stays O(max) forever — the same constant-memory
contract the bounded histograms make.
"""

from __future__ import annotations

import collections
import itertools
import threading
import time

from hyperspace_tpu.obs import journal as _journal
from hyperspace_tpu.obs import metrics as _metrics
from hyperspace_tpu.obs import trace as _trace

# Severity order, least to most severe (filter threshold semantics).
SEVERITIES = ("debug", "info", "warn", "error")

# The declared event set: name -> default severity. Keep this a plain
# dict literal of string constants (house style for declared
# registries — config.KNOWN_KEYS, stats.KNOWN_COUNTERS); new events are
# added by extending it.
KNOWN_EVENTS: dict[str, str] = {
    # Query plane (docs/fault_tolerance.md): a query hit unreadable
    # index data and re-planned; the index that served it got
    # quarantined for the session.
    "fallback.replan": "warn",
    "index.quarantined": "warn",
    # Advisor plane (docs/advisor.md): adaptive routing demoted a plan
    # signature to a raw source scan.
    "advisor.routing.demoted": "info",
    # Serving plane (docs/serving.md): admission control refused a
    # submit; the result cache evicted a burst of entries for one put.
    "serve.admission_rejected": "warn",
    "serve.result_cache.eviction_storm": "warn",
    # Fleet plane (docs/serving.md "fleet topology"): a tenant bounced
    # off its token-bucket quota; queue-depth shedding refused a
    # non-priority submit before the queue filled; a crashed
    # single-flight holder's lease was reaped by another process; the
    # supervisor respawned a dead worker.
    "serve.quota_rejected": "warn",
    "serve.shed": "warn",
    "fleet.singleflight.takeover": "warn",
    "fleet.worker.restarted": "warn",
    # A fleet member is crash-looping: the supervisor engaged exponential
    # backoff before its next respawn, so the restart budget cannot be
    # burned in milliseconds (serve/fleet/supervisor.py).
    "fleet.worker.crash_loop": "warn",
    # The supervisor's member count moved (set_target_workers — manual
    # or controller-actuated scale up/down); carries from/to counts.
    "fleet.worker.scaled": "info",
    # Self-driving operations controller (serve/controller.py,
    # docs/fault_tolerance.md "self-driving operations"): every decision
    # is an auditable record. `controller.actuation` carries
    # action/trigger/outcome for each decision (executed, deferred, or
    # observed); `controller.actuation_failed` records a mutation that
    # raised (its own Action already rolled back); `controller.backoff`
    # records background work (heal rebuild / advisor sweep) held while
    # serve SLOs burn; `controller.observe_only` fires ONCE when the
    # global actuation budget is exhausted and the controller degrades
    # to computing-but-not-acting.
    "controller.actuation": "info",
    "controller.actuation_failed": "error",
    "controller.backoff": "info",
    "controller.observe_only": "error",
    # The controller answered a jit.recompile_storm: the storming key's
    # signature was pinned to the raw-scan route and the jit caches
    # dropped once (serve/controller.py "storm response").
    "controller.storm_response": "warn",
    # The controller opened or closed an incident bundle — a durable
    # forensic snapshot under <fleet>/incidents/<ts>-<trigger>/
    # (docs/fault_tolerance.md "incident bundles"); carries
    # trigger/phase/dir.
    "controller.incident": "warn",
    # JIT plane (docs/observability.md): a call-site key is compiling on
    # most calls (the runtime mirror of lint rule HSL015), or the
    # map-count guard dropped jax's caches to stay under
    # vm.max_map_count (utils/jit_memory.py).
    "jit.recompile_storm": "warn",
    "jit.cache_drop": "warn",
    # SLO plane (obs/slo.py): an objective's multi-window burn rate
    # crossed its page threshold.
    "slo.burn": "error",
    # Continuous-ingestion daemon (hyperspace_tpu/ingest/,
    # docs/ingestion.md): lifecycle transitions (started/stopped),
    # every micro-batch landed (`ingest.committed` carries index/rows/
    # bytes/log id), commits that raised (`ingest.commit_failed` — the
    # Action already rolled back), compactions triggered through the
    # gated optimize action, controller-driven pause/resume of the
    # daemon, and the advisory freshness objective being missed
    # (`ingest.lagging`, hyperspace.ingest.maxLagSeconds).
    "ingest.started": "info",
    "ingest.stopped": "info",
    "ingest.committed": "info",
    "ingest.commit_failed": "error",
    "ingest.compacted": "info",
    "ingest.paused": "warn",
    "ingest.resumed": "info",
    "ingest.lagging": "warn",
}

DEFAULT_MAX_EVENTS = 256

_EMITTED = _metrics.counter("obs.events.emitted", "structured events recorded")
_DROPPED = _metrics.counter("obs.events.dropped", "events aged out of the bounded ring")
_UTILIZATION = _metrics.gauge(
    "obs.events.ring_utilization",
    "resident events / ring capacity — saturation visible before drops start",
)

_seq = itertools.count(1)  # itertools.count is GIL-atomic


class _Ring:
    """The bounded ring itself; one process-global instance."""

    def __init__(self, max_events: int = DEFAULT_MAX_EVENTS):
        self._lock = threading.Lock()
        self._events: collections.deque = collections.deque(maxlen=int(max_events))

    def resize(self, max_events: int) -> None:
        with self._lock:
            self._events = collections.deque(self._events, maxlen=int(max_events))

    def append(self, event: dict) -> None:
        with self._lock:
            if len(self._events) == self._events.maxlen:
                _DROPPED.inc()
            self._events.append(event)
        _EMITTED.inc()

    def recent(self, level: str | None = None, limit: int | None = None) -> list[dict]:
        with self._lock:
            out = list(self._events)
        if level is not None:
            floor = SEVERITIES.index(level)  # unknown level -> ValueError
            out = [e for e in out if SEVERITIES.index(e["severity"]) >= floor]
        if limit is not None:
            out = out[-int(limit):]
        return out

    def counts_by_severity(self) -> dict[str, int]:
        with self._lock:
            out = dict.fromkeys(SEVERITIES, 0)
            for e in self._events:
                out[e["severity"]] += 1
        return out

    def max_events(self) -> int:
        with self._lock:
            return int(self._events.maxlen or 0)

    def utilization(self) -> float:
        with self._lock:
            cap = self._events.maxlen or 0
            return len(self._events) / cap if cap else 0.0

    def clear(self) -> None:
        with self._lock:
            self._events.clear()


RING = _Ring()


class Event:
    """A declared event's emit handle (obtained via :func:`declare` at
    module import). ``emit`` never raises — validation happened at
    declaration — so it is safe inside narrow error contracts."""

    __slots__ = ("name", "severity")

    def __init__(self, name: str, severity: str):
        self.name = name
        self.severity = severity

    def emit(self, severity: str | None = None, **fields) -> dict:
        record = {
            "seq": next(_seq),
            "ts": time.time(),  # wall clock: correlated with external logs
            "name": self.name,
            "severity": severity or self.severity,
            "trace_id": _trace.current_trace_id(),
            "fields": fields,
        }
        RING.append(record)
        _journal.record_event(record)  # durable tap; advisory, never raises
        return record


def declare(name: str) -> Event:
    """The emit handle for a declared event name; an undeclared name
    raises here — at the instrumented module's import — not at the
    (possibly contract-constrained) emit site."""
    severity = KNOWN_EVENTS.get(name)
    if severity is None:
        raise KeyError(
            f"undeclared event {name!r} — declare it in obs.events.KNOWN_EVENTS "
            f"(declared registries are how silent-typo telemetry dies here)"
        )
    return Event(name, severity)


def recent(level: str | None = None, limit: int | None = None) -> list[dict]:
    """Recorded events, oldest first; `level` keeps events at or above
    that severity, `limit` keeps the newest N."""
    return RING.recent(level=level, limit=limit)


def counts_by_severity() -> dict[str, int]:
    """How many resident ring events sit at each severity (healthz)."""
    return RING.counts_by_severity()


def max_events() -> int:
    """The ring's current bound (config get path)."""
    return RING.max_events()


def refresh_gauges() -> float:
    """Refresh `obs.events.ring_utilization` from the live ring (called
    per /metrics scrape and /healthz read — drops only say saturation
    happened; this gauge shows it coming). Returns the utilization."""
    u = RING.utilization()
    _UTILIZATION.set(u)
    return u


def configure(max_events: int | None = None) -> None:
    """Adjust the process-global ring (`hyperspace.obs.events.maxEvents`
    routes here). Shrinking keeps the newest events."""
    if max_events is not None:
        RING.resize(max_events)


def reset() -> None:
    """Drop every recorded event and restore the default bound (test
    isolation)."""
    RING.clear()
    RING.resize(DEFAULT_MAX_EVENTS)
