"""Durable telemetry journal — the flight recorder under the obs plane.

The event ring (`obs/events.py`), trace ring (`obs/trace.py`) and the
metrics registry are in-memory and per-process: the moment a process
dies (or an `OpsController` actuation fires), the evidence that
justified it is already evaporating. This module gives runtime
telemetry the same durability story the paper gives index metadata — an
append-only, replayable journal on disk — without giving up the
advisory contract the obs plane promises (observability never fails a
query).

Shape
-----
One journal per process, under ``<root>/<pid>/`` (``root`` defaults to
``<system_path>/_obs``). Records are JSONL, one dict per line, each
with ``ts`` (wall clock — the only clock that correlates across
processes), ``pid`` and a ``kind``:

- ``event``    every structured event emitted through `obs.events`
- ``span``     every completed *root* span (workers' roots included)
- ``metrics``  periodic counter/gauge snapshots (at most one per
  ``snapshotSeconds``, taken opportunistically on the write path — no
  background thread)
- ``slo``      SLO verdict *transitions* (ok→page, page→ok, …)
- ``process``  a process-start marker written when a pooled/fleet
  worker installs shipped journal state

Records accumulate in an *active* segment: a ``.tmp-seg-*`` file
created with ``tempfile.mkstemp`` in the journal directory. When the
active segment reaches ``segmentBytes`` it is *sealed*: flush + fsync +
``os.replace`` to ``segment-<n>.jsonl`` + directory fsync — the same
atomic-publish idiom as ``file_utils._overwrite_json``, so readers
(the merge API, incident bundles) only ever see whole segments and a
crashed process leaves at most one torn ``.tmp-seg-*`` tail, which
merge skips and :func:`sweep` removes (the `recover()` analogue).

Retention is byte-budgeted per process: sealed segments beyond
``maxBytes`` are evicted oldest-first.

Contract
--------
Advisory, always: IO failures increment ``obs.journal.errors`` and are
swallowed; nothing here ever raises into a query or an actuation.
Disabled (the default) the tap is one boolean read — no IO, no locks
taken by callers.

Workers journal too: :func:`export_state` / :func:`install_state`
follow the `faults` cross-process pattern and ride the same ``env``
dict through `TaskPool.submit` and `FleetSupervisor._spawn`, so build
workers and serve fleet members write their own per-pid journals under
the shared root, ready for the fleet merge
(``python -m hyperspace_tpu.obs.export --format chrome --fleet <dir>``).

Config: ``hyperspace.obs.journal.*`` (docs/observability.md).
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from pathlib import Path

from hyperspace_tpu import faults
from hyperspace_tpu.obs import metrics as _metrics

# Import-time counter handles (the scheduler idiom): `.inc()` never
# raises, so the taps stay safe inside narrow error contracts
# (Event.emit rides QueryServer.submit's `AdmissionRejected`-only
# surface — HSL016).
_RECORDS = _metrics.counter("obs.journal.records", "journal records appended")
_ERRORS = _metrics.counter("obs.journal.errors", "journal IO failures (advisory)")
_SEALED = _metrics.counter("obs.journal.segments_sealed", "segments published")
_EVICTIONS = _metrics.counter("obs.journal.evictions", "segments evicted for the byte budget")

DEFAULT_SEGMENT_BYTES = 64 * 1024
DEFAULT_MAX_BYTES = 4 * 1024 * 1024
DEFAULT_SNAPSHOT_SECONDS = 5.0

SEGMENT_PREFIX = "segment-"
SEGMENT_SUFFIX = ".jsonl"
TMP_PREFIX = ".tmp-seg-"

_lock = threading.Lock()
_enabled = False
_root: str | None = None
_segment_bytes = DEFAULT_SEGMENT_BYTES
_max_bytes = DEFAULT_MAX_BYTES
_snapshot_s = DEFAULT_SNAPSHOT_SECONDS

_fh = None  # open file object for the active (tmp) segment
_fh_path: Path | None = None
_fh_bytes = 0
_fh_pid: int | None = None  # fork/spawn guard: never write an inherited handle
_next_seg: int | None = None
_last_snapshot = 0.0


# -- configuration --------------------------------------------------------
def configure(
    enabled: bool | None = None,
    root: str | None = None,
    segment_bytes: int | None = None,
    max_bytes: int | None = None,
    snapshot_s: float | None = None,
) -> None:
    """Reconfigure the process-global journal (config.py routes the
    ``hyperspace.obs.journal.*`` keys here). Any open active segment is
    sealed first so no records are stranded across a reconfigure."""
    global _enabled, _root, _segment_bytes, _max_bytes, _snapshot_s
    with _lock:
        _seal_locked()
        if enabled is not None:
            _enabled = bool(enabled)
        if root is not None:
            _root = str(root) if root else None
        if segment_bytes is not None:
            _segment_bytes = max(1024, int(segment_bytes))
        if max_bytes is not None:
            _max_bytes = max(4096, int(max_bytes))
        if snapshot_s is not None:
            _snapshot_s = max(0.1, float(snapshot_s))


def ensure_root(path: str | os.PathLike) -> None:
    """Fill in the journal root if none was configured explicitly —
    session/server wiring derives ``<system_path>/_obs`` through here
    without clobbering a user-set ``hyperspace.obs.journal.dir``."""
    global _root
    with _lock:
        if _root is None:
            _root = str(path)


def enabled() -> bool:
    # The disabled-tap fast path: one racy boolean read, no lock. Both
    # names are init-only publication (config writes, then taps read);
    # the worst interleaving skips or double-gates one record around a
    # reconfigure, which the advisory contract already tolerates.
    return _enabled and _root is not None  # noqa: HSL013


def configured_enabled() -> bool:
    """The enabled flag alone (config `get` surface; `enabled()` also
    requires a root)."""
    with _lock:
        return _enabled


def root() -> str | None:
    with _lock:
        return _root


def segment_bytes() -> int:
    with _lock:
        return _segment_bytes


def max_bytes() -> int:
    with _lock:
        return _max_bytes


def snapshot_seconds() -> float:
    with _lock:
        return _snapshot_s


# -- cross-process shipping (the `faults.export_state` pattern) ----------
def export_state() -> dict:
    """Picklable journal config for worker env dicts. The worker derives
    its own ``<root>/<pid>/`` directory — nothing per-process ships."""
    with _lock:
        return {
            "enabled": _enabled,
            "root": _root,
            "segment_bytes": _segment_bytes,
            "max_bytes": _max_bytes,
            "snapshot_s": _snapshot_s,
            "parent_pid": os.getpid(),
        }


def install_state(state: dict) -> None:
    """Install shipped journal config in a worker process and stamp a
    ``process`` record so merged timelines show when each member
    (re)started — supervisor-respawned members keep continuity."""
    if not isinstance(state, dict):
        return
    configure(
        enabled=state.get("enabled"),
        root=state.get("root"),
        segment_bytes=state.get("segment_bytes"),
        max_bytes=state.get("max_bytes"),
        snapshot_s=state.get("snapshot_s"),
    )
    if enabled():
        record_process(
            parent_pid=state.get("parent_pid"), worker_id=state.get("worker_id")
        )


# -- record taps ---------------------------------------------------------
def record(kind: str, **payload) -> None:
    """Append one record. Advisory: errors are counted, never raised."""
    if not enabled():
        return
    doc = {"ts": time.time(), "pid": os.getpid(), "kind": kind}
    doc.update(payload)
    with _lock:
        _append_locked(doc)


def record_event(event_record: dict) -> None:
    """Tap for `obs.events.Event.emit` — the full ring record."""
    if not enabled():
        return
    record("event", event=event_record)


def record_span(root_json: dict) -> None:
    """Tap for completed root spans (`obs.trace` close/adopt)."""
    if not enabled():
        return
    record("span", trace=root_json)


def record_slo(objective: str, verdict: str, previous: str, detail: dict | None = None) -> None:
    """Tap for SLO verdict transitions (`obs.slo.SLOTracker.evaluate`)."""
    if not enabled():
        return
    record("slo", objective=objective, verdict=verdict, previous=previous,
           detail=detail or {})


def record_process(**fields) -> None:
    """Process-start marker (worker install, controller open)."""
    if not enabled():
        return
    record("process", **fields)


def seal() -> None:
    """Seal the active segment now (incident-bundle capture, tests).
    No-op when there is nothing buffered."""
    with _lock:
        _seal_locked()


# -- write path (all advisory) -------------------------------------------
def _proc_dir() -> Path:
    return Path(_root) / str(os.getpid())


def _append_locked(doc: dict) -> None:
    global _fh_bytes, _last_snapshot
    try:
        if _fh is None or _fh_pid != os.getpid():
            _open_active_locked()
        line = json.dumps(doc, default=str, separators=(",", ":")) + "\n"
        _fh.write(line)
        _fh.flush()
        _fh_bytes += len(line)
        _RECORDS.inc()
        now = doc.get("ts") or time.time()
        if doc.get("kind") != "metrics" and now - _last_snapshot >= _snapshot_s:
            # Opportunistic counter/gauge snapshot on the write path —
            # no background thread, at most one per snapshotSeconds.
            _last_snapshot = now
            snap = {
                "ts": now,
                "pid": os.getpid(),
                "kind": "metrics",
                "metrics": _metrics.REGISTRY.snapshot(),
            }
            sline = json.dumps(snap, default=str, separators=(",", ":")) + "\n"
            _fh.write(sline)
            _fh.flush()
            _fh_bytes += len(sline)
            _RECORDS.inc()
        if _fh_bytes >= _segment_bytes:
            _seal_locked()
    except (OSError, ValueError):
        # Advisory: a full disk or unwritable root must never fail the
        # query/actuation being observed — count and move on.
        _ERRORS.inc()


def _open_active_locked() -> None:
    global _fh, _fh_path, _fh_bytes, _fh_pid, _next_seg
    if _fh is not None and _fh_pid != os.getpid():
        # Inherited across fork/spawn: the handle (and the tmp file it
        # points at) belongs to the parent — drop it without touching.
        try:
            _fh.close()
        except OSError:
            pass
        _fh = None
        _fh_path = None
        _next_seg = None
    d = _proc_dir()
    d.mkdir(parents=True, exist_ok=True)
    if _next_seg is None:
        _next_seg = _scan_next_segment(d)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=TMP_PREFIX)
    _fh = os.fdopen(fd, "w", encoding="utf-8")
    _fh_path = Path(tmp)
    _fh_bytes = 0
    _fh_pid = os.getpid()


def _scan_next_segment(d: Path) -> int:
    top = 0
    try:
        for p in d.iterdir():
            n = _segment_number(p.name)
            if n is not None:
                top = max(top, n + 1)
    except OSError:
        pass
    return top


def _segment_number(name: str) -> int | None:
    if not (name.startswith(SEGMENT_PREFIX) and name.endswith(SEGMENT_SUFFIX)):
        return None
    try:
        return int(name[len(SEGMENT_PREFIX):-len(SEGMENT_SUFFIX)])
    except ValueError:
        return None


def _seal_locked() -> None:
    """Atomically publish the active segment: flush + fsync +
    os.replace + directory fsync (file_utils._overwrite_json idiom), so
    a sealed ``segment-<n>.jsonl`` appears whole or not at all."""
    global _fh, _fh_path, _fh_bytes, _next_seg
    if _fh is None:
        return
    if _fh_pid != os.getpid():  # inherited handle — not ours to seal
        _fh = None
        _fh_path = None
        _fh_bytes = 0
        return
    try:
        if _fh_bytes == 0:
            _fh.close()
            _fh_path.unlink(missing_ok=True)
            return
        _fh.flush()
        os.fsync(_fh.fileno())
        _fh.close()
        d = _fh_path.parent
        final = d / f"{SEGMENT_PREFIX}{_next_seg:08d}{SEGMENT_SUFFIX}"
        os.replace(_fh_path, final)
        _fsync_dir(d)
        # Torn window: segment sealed, eviction index not yet run. A
        # crash here leaves an extra sealed segment on disk; the next
        # seal's sweep re-lists and evicts it (CrashPoint is a
        # BaseException, so the except OSError below never eats it).
        faults.fault_point("journal.seal", final)
        _next_seg += 1
        _SEALED.inc()
        _evict_locked(d)
    except OSError:
        _ERRORS.inc()
    finally:
        _fh = None
        _fh_path = None
        _fh_bytes = 0


def _evict_locked(d: Path) -> None:
    """Drop oldest sealed segments until the per-process byte budget
    holds (always keeps the newest one)."""
    try:
        sealed = sorted(
            (p for p in d.iterdir() if _segment_number(p.name) is not None),
            key=lambda p: _segment_number(p.name),
        )
        total = sum(p.stat().st_size for p in sealed)
        while sealed[:-1] and total > _max_bytes:
            victim = sealed.pop(0)
            total -= victim.stat().st_size
            victim.unlink(missing_ok=True)
            _EVICTIONS.inc()
    except OSError:
        _ERRORS.inc()


def _fsync_dir(d: Path) -> None:
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


# -- merge / sweep (the reader side) -------------------------------------
def segment_paths(proc_dir: str | os.PathLike) -> list[Path]:
    """Sealed segments of one process dir, oldest first. The active
    ``.tmp-seg-*`` tail is deliberately invisible here — it may be torn."""
    d = Path(proc_dir)
    try:
        sealed = [p for p in d.iterdir() if _segment_number(p.name) is not None]
    except OSError:
        return []
    return sorted(sealed, key=lambda p: _segment_number(p.name))


def read_segment(path: str | os.PathLike) -> list[dict]:
    """Records of one sealed segment; torn or alien lines are skipped
    (a crashed writer can leave at most one, at the very end)."""
    out = []
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    doc = json.loads(line)
                except ValueError:
                    continue
                if isinstance(doc, dict):
                    out.append(doc)
    except OSError:
        pass
    return out


def merge_dir(root_dir: str | os.PathLike) -> list[dict]:
    """Merge every member's sealed segments under ``root_dir`` (the
    ``_obs`` root: one ``<pid>/`` dir per process) into one record list,
    ordered by wall-clock ``ts``. Tolerates dead members, torn tails and
    alien files — this is the post-incident reader and must never
    require the fleet to be alive."""
    records: list[dict] = []
    rd = Path(root_dir)
    try:
        pid_dirs = [p for p in rd.iterdir() if p.is_dir() and p.name.isdigit()]
    except OSError:
        return []
    for d in sorted(pid_dirs, key=lambda p: int(p.name)):
        for seg in segment_paths(d):
            for doc in read_segment(seg):
                doc.setdefault("pid", int(d.name))
                records.append(doc)
    records.sort(key=lambda r: (r.get("ts") or 0.0, r.get("pid") or 0))
    return records


def spans_from_journal(root_dir: str | os.PathLike) -> list[dict]:
    """Root-span JSON docs from a merged journal — feed for
    `obs.export.chrome_trace` (``--fleet`` mode)."""
    return [r["trace"] for r in merge_dir(root_dir)
            if r.get("kind") == "span" and isinstance(r.get("trace"), dict)]


def sweep(root_dir: str | os.PathLike) -> list[str]:
    """Remove torn ``.tmp-seg-*`` tails left by crashed writers — the
    `recover()` analogue for the journal. The calling process's own live
    active segment is left alone. Returns the removed paths."""
    removed: list[str] = []
    rd = Path(root_dir)
    with _lock:
        live = str(_fh_path) if _fh is not None and _fh_pid == os.getpid() else None
    try:
        pid_dirs = [p for p in rd.iterdir() if p.is_dir() and p.name.isdigit()]
    except OSError:
        return removed
    for d in pid_dirs:
        try:
            for p in d.iterdir():
                if p.name.startswith(TMP_PREFIX) and str(p) != live:
                    p.unlink(missing_ok=True)
                    removed.append(str(p))
        except OSError:
            _ERRORS.inc()
    return removed


def reset() -> None:
    """Back to defaults, discarding any buffered records (tests)."""
    global _enabled, _root, _segment_bytes, _max_bytes, _snapshot_s
    global _fh, _fh_path, _fh_bytes, _fh_pid, _next_seg, _last_snapshot
    with _lock:
        if _fh is not None and _fh_pid == os.getpid():
            try:
                _fh.close()
                if _fh_path is not None:
                    _fh_path.unlink(missing_ok=True)
            except OSError:
                pass
        _fh = None
        _fh_path = None
        _fh_bytes = 0
        _fh_pid = None
        _next_seg = None
        _last_snapshot = 0.0
        _enabled = False
        _root = None
        _segment_bytes = DEFAULT_SEGMENT_BYTES
        _max_bytes = DEFAULT_MAX_BYTES
        _snapshot_s = DEFAULT_SNAPSHOT_SECONDS
