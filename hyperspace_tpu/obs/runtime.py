"""JIT/compile introspection and process-health gauges.

Every XLA executable a long-lived process compiles pins code mappings
for the life of jax's jit cache — the invisible signal behind the
XLA:CPU ``vm.max_map_count`` segfault that ``utils/jit_memory.py``
guards against and lint rule HSL015 forbids statically. This module
makes that signal *observable at runtime*:

- **Per-call-site compile accounting.** ``compat.jit`` (the one jit
  entry point the package uses) routes every jitted callable through
  :func:`instrument`, keyed by its call site. Each call samples the
  underlying jit cache size (``_cache_size()``, ~0.1 µs); growth means
  a compile happened, attributed to that key.
- **Recompile-storm detection** — the dynamic mirror of HSL015: a key
  whose compile count reaches :data:`STORM_THRESHOLD` while at least
  half its calls compiled is pathological (fresh-callable-per-call or
  unstable static args), and emits a structured ``jit.recompile_storm``
  event *naming the key*, plus a counter. Legitimate warm-up (a handful
  of shapes over thousands of calls) never trips it.
- **Process gauges**: ``jit.live_executables`` (sum of live jit cache
  sizes across instrumented sites), ``proc.map_count`` (memory mappings
  — the resource the segfault exhausts), and ``proc.rss_watermark
  .bytes`` (peak RSS). ``utils/jit_memory.py`` refreshes them on its
  sampled checks; the /metrics endpoint refreshes them per scrape.

Stdlib-only: jax is never imported here — the instrumented callables
close over it, and cache-size introspection is a duck-typed getattr.
"""

from __future__ import annotations

import threading
import weakref

from hyperspace_tpu.obs import events as _events
from hyperspace_tpu.obs import metrics as _metrics

# A key storms once its compiles reach the threshold AND at least this
# fraction of its calls compiled (so many-calls/few-compiles warm-up
# never qualifies). Deterministic — no clocks, no windows to flake.
STORM_THRESHOLD = 8
STORM_MIN_COMPILE_RATIO = 0.5

_COMPILES = _metrics.counter("jit.compiles", "XLA compiles observed at instrumented jit sites")
_STORMS = _metrics.counter("jit.recompile_storms", "recompile-storm events emitted")
_LIVE = _metrics.gauge("jit.live_executables", "live executables across instrumented jit caches")
_MAP_COUNT = _metrics.gauge("proc.map_count", "memory mappings of this process (/proc/self/maps)")
_RSS_WATERMARK = _metrics.gauge("proc.rss_watermark.bytes", "peak resident set size")

_EVT_STORM = _events.declare("jit.recompile_storm")


def _cache_size(jitted) -> int:
    """The jitted callable's executable-cache population; 0 where the
    installed jax does not expose it (the accounting degrades to
    call counting, never to an error)."""
    probe = getattr(jitted, "_cache_size", None)
    if probe is None:
        return 0
    try:
        return int(probe())
    except Exception:
        return 0


class _SiteStats:
    """Aggregated per-call-site-key accounting. Several jitted objects
    can share one key (a factory re-jitting inside an lru_cache miss is
    still ONE call site), so the registry aggregates by key, not by
    callable identity."""

    __slots__ = ("key", "calls", "compiles", "storms")

    def __init__(self, key: str):
        self.key = key
        self.calls = 0
        self.compiles = 0
        self.storms = 0


class _Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._sites: dict[str, _SiteStats] = {}
        # Live jitted callables (weak: a dropped factory product must
        # not be pinned by its own telemetry) for the executable gauge.
        self._live: list = []

    def note_call(self, key: str, compiled: int) -> None:
        storm = None
        with self._lock:
            site = self._sites.get(key)
            if site is None:
                site = self._sites[key] = _SiteStats(key)
            site.calls += 1
            if compiled > 0:
                site.compiles += compiled
                if (
                    site.compiles >= STORM_THRESHOLD * (site.storms + 1)
                    and site.compiles >= site.calls * STORM_MIN_COMPILE_RATIO
                ):
                    # Re-arm at the next threshold multiple so a
                    # persisting storm re-reports instead of spamming
                    # one event per compile.
                    site.storms += 1
                    storm = (site.calls, site.compiles)
        if compiled > 0:
            _COMPILES.inc(compiled)
        if storm is not None:
            _STORMS.inc()
            _EVT_STORM.emit(key=key, calls=storm[0], compiles=storm[1])

    def track(self, jitted) -> None:
        with self._lock:
            self._live.append(weakref.ref(jitted))

    def live_executables(self) -> int:
        with self._lock:
            refs = list(self._live)
        alive, total = [], 0
        for r in refs:
            fn = r()
            if fn is not None:
                alive.append(r)
                total += _cache_size(fn)
        with self._lock:
            self._live = alive
        return total

    def report(self) -> dict:
        with self._lock:
            return {
                s.key: {"calls": s.calls, "compiles": s.compiles, "storms": s.storms}
                for s in sorted(self._sites.values(), key=lambda s: s.key)
            }

    def reset(self) -> None:
        with self._lock:
            self._sites.clear()
            self._live = []


REGISTRY = _Registry()


class _InstrumentedJit:
    """A jitted callable plus per-call compile accounting. Transparent:
    unknown attributes (``lower``, ``clear_cache``, ``_cache_size``)
    forward to the wrapped callable."""

    __slots__ = ("_jitted", "_key", "_last_size", "__weakref__")

    def __init__(self, jitted, key: str):
        self._jitted = jitted
        self._key = key
        self._last_size = _cache_size(jitted)
        REGISTRY.track(jitted)

    def __call__(self, *args, **kwargs):
        out = self._jitted(*args, **kwargs)
        size = _cache_size(self._jitted)
        # A cache drop (jit_memory relieving map pressure) shrinks the
        # cache; only growth counts as compiles.
        compiled = max(0, size - self._last_size)
        self._last_size = size
        REGISTRY.note_call(self._key, compiled)
        return out

    @property
    def jit_key(self) -> str:
        return self._key

    def __getattr__(self, name):
        return getattr(self._jitted, name)


def instrument(jitted, key: str):
    """Wrap one jitted callable with per-call-site compile accounting
    (compat.jit routes every jit through here)."""
    return _InstrumentedJit(jitted, key)


def jit_report() -> dict:
    """Per-call-site-key {calls, compiles, storms} (healthz / tests)."""
    return REGISTRY.report()


def refresh_process_gauges() -> dict:
    """Re-sample the process-health gauges (map count, RSS watermark,
    live executables) and return their values. Called by the /metrics
    scrape path and by jit_memory's sampled pressure checks."""
    from hyperspace_tpu.utils.jit_memory import map_count

    maps = map_count()
    rss = _rss_watermark_bytes()
    live = REGISTRY.live_executables()
    _MAP_COUNT.set(maps)
    if rss:
        _RSS_WATERMARK.set(rss)
    _LIVE.set(live)
    return {"map_count": maps, "rss_watermark_bytes": rss, "live_executables": live}


def _rss_watermark_bytes() -> int:
    """Peak RSS in bytes (ru_maxrss is KiB on Linux); 0 where
    unavailable."""
    try:
        import resource

        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except (ImportError, ValueError, OSError):
        return 0


def reset() -> None:
    """Drop per-site accounting and tracked callables (test isolation)."""
    REGISTRY.reset()
