"""Declared process-wide metrics registry: counters, gauges, histograms.

Replaces the ad-hoc counter dict that `hyperspace_tpu/stats.py` grew in
the fault-tolerance PR. Metrics are **declared** before use — a typo'd
name raises instead of silently creating a new counter (lint rule HSL007
additionally flags undeclared constant names at `stats.increment` call
sites). The registry is process-global and thread-safe, matching the
process-global filesystem/device state it describes.

Histograms are **bounded**: fixed bucket boundaries chosen at
declaration, constant memory regardless of observation count, with
p50/p95/p99 estimated by linear interpolation inside the owning bucket
(the Prometheus classic-histogram model — exact enough for operator
wall-time / bytes-scanned distributions, and exportable as cumulative
``_bucket{le=...}`` lines by obs/export.py).

Stdlib-only on purpose: `stats.py` (imported by the fault plane before
jax is ever touched) shims onto this module, so it must stay importable
with no third-party dependencies.
"""

from __future__ import annotations

import bisect
import threading

# Shared bucket presets (upper bounds; +Inf is implicit).
SECONDS_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 120.0,
)
BYTES_BUCKETS = tuple(float(1 << s) for s in range(10, 37, 2))  # 1 KiB .. 64 GiB
COUNT_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0, 1024.0, 4096.0)


class Counter:
    """Monotonic counter."""

    __slots__ = ("name", "help", "_value", "_lock")

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value

    def _reset(self) -> None:
        with self._lock:
            self._value = 0

    def snapshot(self):
        return self._value


class Gauge:
    """Point-in-time value (cache bytes, live entries)."""

    __slots__ = ("name", "help", "_value", "_lock")

    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = v

    def add(self, v: float) -> None:
        with self._lock:
            self._value += v

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _reset(self) -> None:
        with self._lock:
            self._value = 0.0

    def snapshot(self):
        with self._lock:
            return self._value


class Histogram:
    """Bounded histogram over fixed bucket upper bounds (+Inf implicit).

    Memory is O(len(bounds)) forever. Quantiles interpolate linearly
    within the owning bucket, using the observed min/max to tighten the
    first and last buckets (so a distribution narrower than its bucket
    does not smear across it)."""

    __slots__ = ("name", "help", "bounds", "_counts", "_sum", "_count", "_min", "_max", "_lock")

    kind = "histogram"

    def __init__(self, name: str, help: str = "", buckets: tuple = SECONDS_BUCKETS):
        self.name = name
        self.help = help
        self.bounds = tuple(sorted(float(b) for b in buckets))
        self._counts = [0] * (len(self.bounds) + 1)  # last = +Inf
        self._sum = 0.0
        self._count = 0
        self._min: float | None = None
        self._max: float | None = None
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            if self._min is None or v < self._min:
                self._min = v
            if self._max is None or v > self._max:
                self._max = v

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def quantile(self, q: float) -> float | None:
        """Estimated q-quantile (0..1); None when empty."""
        with self._lock:
            if self._count == 0:
                return None
            target = q * self._count
            seen = 0.0
            for i, c in enumerate(self._counts):
                if c == 0:
                    continue
                lo = self.bounds[i - 1] if i > 0 else (self._min or 0.0)
                hi = self.bounds[i] if i < len(self.bounds) else (self._max or lo)
                lo = max(lo, self._min or lo)
                hi = min(hi, self._max or hi) if self._max is not None else hi
                if seen + c >= target:
                    frac = (target - seen) / c
                    return lo + (hi - lo) * max(0.0, min(1.0, frac))
                seen += c
            return self._max

    def percentiles(self) -> dict:
        return {
            "p50": self.quantile(0.50),
            "p95": self.quantile(0.95),
            "p99": self.quantile(0.99),
        }

    def _reset(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self.bounds) + 1)
            self._sum = 0.0
            self._count = 0
            self._min = None
            self._max = None

    def snapshot(self):
        with self._lock:
            out = {"count": self._count, "sum": self._sum}
        out.update(self.percentiles())
        return out

    def bucket_counts(self) -> list[tuple[float, int]]:
        """Cumulative (le, count) pairs, Prometheus classic style."""
        out = []
        cum = 0
        with self._lock:
            for b, c in zip(self.bounds, self._counts):
                cum += c
                out.append((b, cum))
            out.append((float("inf"), cum + self._counts[-1]))
        return out


class MetricsRegistry:
    """Name → metric map with declare-or-get semantics. Re-declaring a
    name with a different kind is a bug and raises."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, Counter | Gauge | Histogram] = {}

    def _declare(self, cls, name: str, help: str, **kwargs):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise ValueError(
                        f"metric {name!r} already declared as {m.kind}, not {cls.kind}"
                    )
                return m
            m = cls(name, help, **kwargs)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._declare(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._declare(Gauge, name, help)

    def histogram(self, name: str, help: str = "", buckets: tuple = SECONDS_BUCKETS) -> Histogram:
        return self._declare(Histogram, name, help, buckets=buckets)

    def get(self, name: str):
        """The declared metric, or None."""
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._metrics)

    def collect(self) -> list:
        """Stable-ordered list of all declared metrics (export API)."""
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def snapshot(self) -> dict:
        """Point-in-time {name: value | histogram summary}."""
        return {m.name: m.snapshot() for m in self.collect()}

    def reset(self) -> None:
        """Zero every metric, keeping declarations (test isolation)."""
        for m in self.collect():
            m._reset()


REGISTRY = MetricsRegistry()


def counter(name: str, help: str = "") -> Counter:
    return REGISTRY.counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    return REGISTRY.gauge(name, help)


def histogram(name: str, help: str = "", buckets: tuple = SECONDS_BUCKETS) -> Histogram:
    return REGISTRY.histogram(name, help, buckets=buckets)
