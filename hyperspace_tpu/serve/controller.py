"""Self-driving operations: the SLO→advisor reconciliation control loop.

The paper's Hyperspace is "an index you manage": a human watches query
regressions and calls refresh/optimize/recover by hand. Every control
signal and actuator that a self-managing installation needs already
exists in this repo in isolation — `obs/slo.py` computes multi-window
burn verdicts, `obs/events.py` records quarantines and routing
demotions, `faults.py` injects failures deterministically, and the
advisor/Action protocols make every mutation crash-safe. This module
composes them into one closed loop (docs/fault_tolerance.md
"self-driving operations"):

========================  ==========================================
signal                    actuation (existing crash-safe protocol)
========================  ==========================================
`serve.availability` or   **shed load + tighten quotas**:
`serve.latency_p99`       `QueryServer.set_shed_depth` drops the
pages (multi-window       graceful-saturation threshold to
burn verdict)             `controller.shedRatio` x maxQueueDepth and
                          `TenantQuotas.set_throttle` scales every
                          tenant's refill rate by
                          `controller.quotaFactor`; both restored
                          when the burn recovers.
index quarantined         **heal**: `Hyperspace.recover(name)` (log
(`session.index_health`)  repair + quarantine lift) then — gated by
                          `controller.heal.rebuild` — a full
                          `refresh_index` rebuild through the normal
                          two-phase Action, so the corrupt bytes are
                          actually replaced.
`advisor.routing.demoted` **advisor sweep**:
events cluster            `LifecyclePolicy.sweep()` — still gated by
                          the `hyperspace.advisor.lifecycle.*`
                          opt-ins; the controller only decides WHEN.
serve SLOs burning        **back off background work**: heals and
                          sweeps (rebuild/optimize-class work) are
                          deferred with a `controller.backoff` event
                          until the burn clears.
========================  ==========================================

Control discipline — the loop must never become its own incident:

- **Kill switch.** `hyperspace.controller.enabled` defaults OFF. A
  running controller that sees it flip releases whatever overrides it
  holds and stands down mid-loop.
- **Hysteresis.** The overload response needs `hysteresisTicks`
  consecutive page verdicts to engage and `recoveryTicks` consecutive
  non-page verdicts to release — a verdict flicker never flaps the
  actuators.
- **Cooldown.** Each actuation (per healed index, per sweep, per
  engage) is rate-limited by `cooldownSeconds` on the controller's own
  injectable clock.
- **Actuation budget.** `actuationBudget` bounds total mutations per
  controller lifetime. Exhaustion degrades to observe-only — decisions
  are still computed and audited, nothing mutates — announced once by
  an ERROR `controller.observe_only` event. Releases stay free, so the
  system is always left as found.
- **Audit.** Every decision is a structured `controller.*` event
  carrying action/trigger/outcome; `/healthz` surfaces the live
  controller snapshot next to the SLO verdicts.
- **Crash safety.** The `controller.actuate` fault point fires
  immediately BEFORE each mutation: an injected CrashPoint there
  proves a dying controller leaves no partial actuation behind
  (nothing has mutated yet), and every mutation it does make goes
  through APIs that are individually crash-safe (Action two-phase
  protocol / locked scheduler state). An actuation that fails with an
  ordinary Exception is recorded (`controller.actuation_failed`) and
  reconciliation continues — one broken actuator must not stop the
  loop — while CrashPoint propagates like the process death it
  simulates.

Proven end to end by the chaos soak harness (`benchmarks/bench_soak.py`
→ BENCH_SOAK.json): under a deterministic fault schedule the SLOs
recover without a human, and the identical run with the controller
disabled shows the degraded counterfactual.
"""

from __future__ import annotations

import collections
import threading
import time
from pathlib import Path

from hyperspace_tpu import faults, stats
from hyperspace_tpu.obs import events as obs_events
from hyperspace_tpu.obs import metrics as obs_metrics
from hyperspace_tpu.obs import slo as obs_slo
from hyperspace_tpu.obs import trace as obs_trace

# Declared at import (obs/events.py): emit never raises, so audit
# records cannot widen the controller's narrow typed surface.
_EVT_ACTUATION = obs_events.declare("controller.actuation")
_EVT_FAILED = obs_events.declare("controller.actuation_failed")
_EVT_BACKOFF = obs_events.declare("controller.backoff")
_EVT_OBSERVE_ONLY = obs_events.declare("controller.observe_only")

_ENGAGED = obs_metrics.gauge(
    "controller.engaged", "1 while the controller's overload response holds overrides"
)
_BUDGET_REMAINING = obs_metrics.gauge(
    "controller.budget_remaining", "actuations left before observe-only degradation"
)

# The serve objectives whose page verdicts drive the overload response.
SERVE_OBJECTIVES = ("serve.availability", "serve.latency_p99")


class OpsController:
    """The reconciliation loop over one session (+ optional QueryServer).

    Construct via ``Hyperspace.controller(server=...)``; `step()` is one
    reconciliation pass (the unit tests drive it with an injectable
    clock), `start()`/`stop()` run it as a daemon loop at
    `hyperspace.controller.intervalSeconds`.
    """

    def __init__(self, hyperspace, server=None, clock=time.monotonic):
        # `hyperspace` is the user-facing API facade: like the advisor's
        # LifecyclePolicy, the controller has exactly the powers an
        # operator has — recover/refresh/lifecycle — no private side
        # doors into the log.
        self.hyperspace = hyperspace
        self.session = hyperspace.session
        self.server = server
        self._clock = clock
        self._lock = threading.RLock()
        self._budget = int(self.session.conf.controller_actuation_budget)
        self._observe_only_announced = False
        self._page_ticks = 0
        self._ok_ticks = 0
        self._engaged = False
        self._saved: dict = {}
        self._cooldowns: dict[str, float] = {}
        self._last_seq = 0
        self._demotions: collections.deque = collections.deque()
        self._last_verdicts: dict[str, str] = {}
        self._recent_actions: collections.deque = collections.deque(maxlen=16)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        _BUDGET_REMAINING.set(self._budget)

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "OpsController":
        """Run the loop as a daemon thread; idempotent. Also registers
        this controller with the process-shared health endpoint (if one
        is live) so /healthz carries the controller verdict."""
        with self._lock:
            if self._thread is None:
                self._stop.clear()
                self._thread = threading.Thread(
                    target=self._run, name="hs-ops-controller", daemon=True
                )
                self._thread.start()
        from hyperspace_tpu.obs import http as obs_http

        shared = obs_http.shared()
        if shared is not None:
            shared.attach_controller(self)
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        with self._lock:
            t = self._thread
        if t is not None:
            t.join(timeout=timeout)
        with self._lock:
            self._thread = None

    def __enter__(self) -> "OpsController":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.stop()
        return False

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.step()
            except Exception as e:
                # One failed reconciliation pass must not kill the loop:
                # record it and keep reconciling. CrashPoint is a
                # BaseException and propagates — a dying process does
                # not keep actuating.
                stats.increment("controller.actuation_failures")
                _EVT_FAILED.emit(action="step", error=f"{type(e).__name__}: {e}")
            self._stop.wait(self.session.conf.controller_interval_seconds)

    # -- one reconciliation pass ------------------------------------------
    def step(self, now: float | None = None) -> dict:
        """One reconciliation pass: sample SLOs, drain new events,
        decide, actuate. Returns the post-step snapshot (the /healthz
        document's `controller` section). `now` overrides the injected
        clock for deterministic tests."""
        conf = self.session.conf
        if now is None:
            now = self._clock()
        now = float(now)
        with self._lock:
            if not conf.controller_enabled:
                # Kill switch mid-loop: release anything we hold, then
                # stand down without observing or deciding anything.
                if self._engaged:
                    self._release_overload(now, trigger="kill_switch")
                return self.snapshot()
            stats.increment("controller.ticks")
            obs_slo.sample(now)
            verdicts = obs_slo.evaluate(now)
            self._last_verdicts = {k: v["verdict"] for k, v in verdicts.items()}
            burning = any(
                self._last_verdicts.get(o) == "page" for o in SERVE_OBJECTIVES
            )
            if burning:
                self._page_ticks += 1
                self._ok_ticks = 0
            else:
                self._ok_ticks += 1
                self._page_ticks = 0
            demotion_cluster = self._drain_events(conf, now)

            # 1. Overload response: shed + tighten quotas while pages
            # persist (hysteresis), restore once the burn clears.
            if (
                burning
                and not self._engaged
                and self._page_ticks >= int(conf.controller_hysteresis_ticks)
            ):
                self._actuate(
                    "shed.engage", trigger="slo.page", now=now,
                    fn=lambda: self._engage_overload(conf),
                    verdicts=dict(self._last_verdicts),
                )
            elif (
                not burning
                and self._engaged
                and self._ok_ticks >= int(conf.controller_recovery_ticks)
            ):
                self._release_overload(now, trigger="slo.recovered")

            # 2. Heal quarantined indexes — rebuild-class work, deferred
            # while serve SLOs burn (backing off background work is
            # itself the actuation that protects the serve plane).
            with self.session._state_lock:
                quarantined = sorted(self.session.index_health)
            for root in quarantined:
                name = Path(root).name
                if burning:
                    self._defer_background(
                        conf, "heal", now, index=name, reason="slo.burning"
                    )
                    continue
                self._actuate(
                    f"heal.{name}", trigger="index.quarantined", now=now,
                    fn=lambda n=name: self._heal(conf, n), index=name,
                )

            # 3. Routing demotions clustering means the index layout no
            # longer fits the workload: hand the evidence to the advisor.
            if demotion_cluster:
                if burning:
                    self._defer_background(
                        conf, "advisor.sweep", now, reason="slo.burning"
                    )
                elif self._actuate(
                    "advisor.sweep", trigger="routing.demotion_cluster", now=now,
                    fn=self._sweep, demotions=demotion_cluster,
                ):
                    self._demotions.clear()  # evidence consumed; re-arm
            return self.snapshot()

    # -- signal plumbing --------------------------------------------------
    def _drain_events(self, conf, now: float) -> int:
        """Fold new ring events into the controller's trailing state;
        returns the demotion count when it constitutes a cluster."""
        fresh = [e for e in obs_events.recent() if e["seq"] > self._last_seq]
        if fresh:
            self._last_seq = max(e["seq"] for e in fresh)
        n = sum(1 for e in fresh if e["name"] == "advisor.routing.demoted")
        if n:
            self._demotions.append((now, n))
        cutoff = now - float(conf.controller_demotion_window_seconds)
        while self._demotions and self._demotions[0][0] < cutoff:
            self._demotions.popleft()
        total = sum(c for _, c in self._demotions)
        return total if total >= int(conf.controller_demotion_cluster_size) else 0

    # -- actuators --------------------------------------------------------
    def _actuate(self, action: str, trigger: str, now: float, fn, **details) -> bool:
        """Run one mutation under the full control discipline: cooldown,
        budget, fault point, audit. Returns True when it executed."""
        conf = self.session.conf
        if self._cooldowns.get(action, float("-inf")) > now:
            stats.increment("controller.deferred")
            return False
        if self._budget <= 0:
            # Observe-only: the decision is still computed and audited,
            # nothing mutates.
            self._announce_observe_only()
            stats.increment("controller.deferred")
            _EVT_ACTUATION.emit(
                action=action, trigger=trigger, outcome="observe_only", **details
            )
            return False
        # The fault point fires BEFORE any mutation: a CrashPoint here
        # unwinds out of step() with zero partial state (tested), and a
        # transient FaultError surfaces through the declared contract.
        faults.fault_point("controller.actuate")
        try:
            with obs_trace.span("controller.actuate", action=action, trigger=trigger):
                fn()
        except Exception as e:
            # The failed subsystem's own Action already rolled back;
            # record, cool down, keep reconciling. CrashPoint propagates.
            stats.increment("controller.actuation_failures")
            _EVT_FAILED.emit(
                action=action, trigger=trigger, error=f"{type(e).__name__}: {e}"
            )
            self._cooldowns[action] = now + float(conf.controller_cooldown_seconds)
            return False
        self._budget -= 1
        _BUDGET_REMAINING.set(self._budget)
        stats.increment("controller.actuations")
        self._cooldowns[action] = now + float(conf.controller_cooldown_seconds)
        record = _EVT_ACTUATION.emit(
            action=action, trigger=trigger, outcome="executed",
            budget_remaining=self._budget, **details,
        )
        self._recent_actions.append(
            {"action": action, "trigger": trigger, "at": now, "seq": record["seq"]}
        )
        return True

    def _engage_overload(self, conf) -> None:
        # Re-entered under the step() RLock; restated here because this
        # runs through the _actuate(fn=...) indirection, which hides the
        # entry-lock guarantee from direct call-site analysis.
        with self._lock:
            saved: dict = {}
            if self.server is not None:
                saved["shed_depth"] = self.server.get_shed_depth()
                self.server.set_shed_depth(
                    int(self.server.max_queue_depth * float(conf.controller_shed_ratio))
                )
                quotas = getattr(self.server, "quotas", None)
                if quotas is not None:
                    saved["throttle"] = quotas.throttle()
                    quotas.set_throttle(float(conf.controller_quota_factor))
            self._saved = saved
            self._engaged = True
            _ENGAGED.set(1)

    def _release_overload(self, now: float, trigger: str) -> None:
        """Restore the pre-engage shed depth and quota throttle. Free of
        budget by design — the controller must always be able to leave
        the system as it found it (kill switch, budget exhaustion)."""
        faults.fault_point("controller.actuate")
        try:
            if self.server is not None:
                if "shed_depth" in self._saved:
                    self.server.set_shed_depth(self._saved["shed_depth"])
                quotas = getattr(self.server, "quotas", None)
                if quotas is not None and "throttle" in self._saved:
                    quotas.set_throttle(self._saved["throttle"])
        except Exception as e:
            stats.increment("controller.actuation_failures")
            _EVT_FAILED.emit(
                action="shed.release", trigger=trigger,
                error=f"{type(e).__name__}: {e}",
            )
            return
        self._engaged = False
        self._saved = {}
        _ENGAGED.set(0)
        record = _EVT_ACTUATION.emit(
            action="shed.release", trigger=trigger, outcome="executed",
            budget_remaining=self._budget,
        )
        self._recent_actions.append(
            {"action": "shed.release", "trigger": trigger, "at": now,
             "seq": record["seq"]}
        )

    def _heal(self, conf, name: str) -> None:
        """recover() repairs the log and lifts the quarantine; the gated
        full refresh rebuilds the data files through the crash-safe
        Action protocol so the corruption is actually gone (not merely
        re-served until the next quarantine)."""
        self.hyperspace.recover(name)
        if conf.controller_heal_rebuild:
            self.hyperspace.refresh_index(name, "full")
        stats.increment("controller.heals")

    def _sweep(self) -> None:
        # The lifecycle policy's own gates (autoCreate/autoVacuum/
        # autoOptimize, confidence and benefit floors) still decide WHAT
        # may mutate; the controller only decided WHEN to look.
        self.hyperspace.lifecycle().sweep()

    def _defer_background(self, conf, action: str, now: float, **details) -> None:
        stats.increment("controller.deferred")
        key = f"backoff.{action}"
        if self._cooldowns.get(key, float("-inf")) <= now:
            # Rate-limit the audit record, not the deferral itself.
            self._cooldowns[key] = now + float(conf.controller_cooldown_seconds)
            _EVT_BACKOFF.emit(action=action, **details)

    def _announce_observe_only(self) -> None:
        if not self._observe_only_announced:
            self._observe_only_announced = True
            _EVT_OBSERVE_ONLY.emit(budget_remaining=0)

    # -- views ------------------------------------------------------------
    def snapshot(self) -> dict:
        """Point-in-time controller state — the /healthz `controller`
        section (docs/observability.md)."""
        with self._lock:
            enabled = bool(self.session.conf.controller_enabled)
            if not enabled:
                mode = "disabled"
            elif self._budget <= 0:
                mode = "observe_only"
            else:
                mode = "actuate"
            return {
                "enabled": enabled,
                "mode": mode,
                "engaged": self._engaged,
                "budget_remaining": self._budget,
                "verdicts": dict(self._last_verdicts),
                "page_ticks": self._page_ticks,
                "ok_ticks": self._ok_ticks,
                "pending_demotions": sum(c for _, c in self._demotions),
                "recent_actions": list(self._recent_actions),
            }
