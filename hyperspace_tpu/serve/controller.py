"""Self-driving operations: the SLO→advisor reconciliation control loop.

The paper's Hyperspace is "an index you manage": a human watches query
regressions and calls refresh/optimize/recover by hand. Every control
signal and actuator that a self-managing installation needs already
exists in this repo in isolation — `obs/slo.py` computes multi-window
burn verdicts, `obs/events.py` records quarantines and routing
demotions, `faults.py` injects failures deterministically, and the
advisor/Action protocols make every mutation crash-safe. This module
composes them into one closed loop (docs/fault_tolerance.md
"self-driving operations"):

========================  ==========================================
signal                    actuation (existing crash-safe protocol)
========================  ==========================================
`serve.availability` or   **shed load + tighten quotas**:
`serve.latency_p99`       `QueryServer.set_shed_depth` drops the
pages (multi-window       graceful-saturation threshold to
burn verdict)             `controller.shedRatio` x maxQueueDepth and
                          `TenantQuotas.set_throttle` scales every
                          tenant's refill rate by
                          `controller.quotaFactor`; both restored
                          when the burn recovers.
index quarantined         **heal**: `Hyperspace.recover(name)` (log
(`session.index_health`)  repair + quarantine lift) then — gated by
                          `controller.heal.rebuild` — a full
                          `refresh_index` rebuild through the normal
                          two-phase Action, so the corrupt bytes are
                          actually replaced.
`advisor.routing.demoted` **advisor sweep**:
events cluster            `LifecyclePolicy.sweep()` — still gated by
                          the `hyperspace.advisor.lifecycle.*`
                          opt-ins; the controller only decides WHEN.
sustained fleet/serve     **scale the fleet**:
saturation                `FleetSupervisor.set_target_workers` grows
(`fleet_health` queue     the member count by `controller.scale.step`
ratio over               (up to `controller.scale.maxWorkers`) after
`controller.scale.`       `hysteresisTicks` saturated ticks, and
`saturation`)             restores the pre-episode count after
                          `recoveryTicks` calm ticks (the scale-down,
                          like every release, is budget-free).
`jit.recompile_storm`     **storm response**: pin the storming key's
event in the window       signature to the raw-scan route
                          (`RoutingLedger.pin`) and drop the jit
                          caches once (`jit_memory.drop_caches`) —
                          the signature stops feeding the cache it is
                          churning. Gated by
                          `controller.stormResponse`.
serve SLOs burning        **back off background work**: heals and
                          sweeps (rebuild/optimize-class work) are
                          deferred with a `controller.backoff` event
                          until the burn clears.
========================  ==========================================

Fleet coordination (docs/fault_tolerance.md "fleet coordination"): N
controllers over ONE store must not race their heals — a quarantined
index would be rebuilt N times (N full refreshes of the same bytes).
Heal actuations therefore route through the fleet's O_EXCL single-
flight lease (serve/fleet/singleflight.py) keyed per index, with a
generation-stamped marker file as the published artifact: exactly one
member (the lease leader) runs recover+rebuild and bumps the marker
generation; every other member observes the fresh marker, lifts its
LOCAL quarantine via the idempotent `recover()`, and spends neither
budget nor a `controller.heals` count (audited as outcome="observed").
A SIGKILLed healer's lease goes stale after the TTL and the next
member reaps it and takes over (`fleet.singleflight.takeovers`). Every
audit event carries this controller's `member` id so the fleet-wide
decision log is reconstructible from any member's event ring.
Coordination is gated by `hyperspace.controller.heal.coordinate` and
engages only when a fleet directory is discoverable (explicit
`hyperspace.fleet.cache.dir`, or an existing store to derive
`<system.path>/_fleet` under); otherwise heals stay process-local.

Control discipline — the loop must never become its own incident:

- **Kill switch.** `hyperspace.controller.enabled` defaults OFF. A
  running controller that sees it flip releases whatever overrides it
  holds and stands down mid-loop.
- **Hysteresis.** The overload response needs `hysteresisTicks`
  consecutive page verdicts to engage and `recoveryTicks` consecutive
  non-page verdicts to release — a verdict flicker never flaps the
  actuators.
- **Cooldown.** Each actuation (per healed index, per sweep, per
  engage) is rate-limited by `cooldownSeconds` on the controller's own
  injectable clock.
- **Actuation budget.** `actuationBudget` bounds total mutations per
  controller lifetime. Exhaustion degrades to observe-only — decisions
  are still computed and audited, nothing mutates — announced once by
  an ERROR `controller.observe_only` event. Releases stay free, so the
  system is always left as found.
- **Audit.** Every decision is a structured `controller.*` event
  carrying action/trigger/outcome; `/healthz` surfaces the live
  controller snapshot next to the SLO verdicts.
- **Crash safety.** The `controller.actuate` fault point fires
  immediately BEFORE each mutation: an injected CrashPoint there
  proves a dying controller leaves no partial actuation behind
  (nothing has mutated yet), and every mutation it does make goes
  through APIs that are individually crash-safe (Action two-phase
  protocol / locked scheduler state). An actuation that fails with an
  ordinary Exception is recorded (`controller.actuation_failed`) and
  reconciliation continues — one broken actuator must not stop the
  loop — while CrashPoint propagates like the process death it
  simulates.

Incident flight recorder (docs/observability.md "incident bundles"):
the controller is the one component that already knows WHEN something
went wrong — so it snapshots a content-complete bundle under
`<fleet>/incidents/<ts>-<trigger>/` the moment an episode opens (SLO
page engage, a fresh quarantine, observe-only degradation) and
finalizes it when the episode resolves: event-ring dump, config
snapshot, jit report, routing ledger, the actuation audit trail, and
the last N durable journal segments (obs/journal.py) from every
reachable fleet member. At most ONE bundle is open at a time — later
triggers annotate it — and the recorder runs under the same cooldown
discipline as every actuator, with retention capped at
`controller.incident.maxBundles`. The whole path is advisory: any IO
failure is counted (`controller.incident_errors`), never raised — the
flight recorder must never become the incident. Bundles are served
read-only at `/debug/incidents` (obs/http.py).

Proven end to end by the chaos soak harness (`benchmarks/bench_soak.py`
→ BENCH_SOAK.json): under a deterministic fault schedule the SLOs
recover without a human, and the identical run with the controller
disabled shows the degraded counterfactual — and every injected
episode leaves exactly one incident bundle behind (zero with the
controller disabled), which the soak gates enforce.
"""

from __future__ import annotations

import collections
import json
import os
import shutil
import tempfile
import threading
import time
from pathlib import Path

from hyperspace_tpu import faults, stats
from hyperspace_tpu.utils import file_utils
from hyperspace_tpu.obs import events as obs_events
from hyperspace_tpu.obs import metrics as obs_metrics
from hyperspace_tpu.obs import slo as obs_slo
from hyperspace_tpu.obs import trace as obs_trace

# Declared at import (obs/events.py): emit never raises, so audit
# records cannot widen the controller's narrow typed surface.
_EVT_ACTUATION = obs_events.declare("controller.actuation")
_EVT_FAILED = obs_events.declare("controller.actuation_failed")
_EVT_BACKOFF = obs_events.declare("controller.backoff")
_EVT_OBSERVE_ONLY = obs_events.declare("controller.observe_only")
_EVT_STORM = obs_events.declare("controller.storm_response")
_EVT_INCIDENT = obs_events.declare("controller.incident")

_ENGAGED = obs_metrics.gauge(
    "controller.engaged", "1 while the controller's overload response holds overrides"
)
_BUDGET_REMAINING = obs_metrics.gauge(
    "controller.budget_remaining", "actuations left before observe-only degradation"
)

# The serve objectives whose page verdicts drive the overload response.
SERVE_OBJECTIVES = ("serve.availability", "serve.latency_p99")


class OpsController:
    """The reconciliation loop over one session (+ optional QueryServer).

    Construct via ``Hyperspace.controller(server=...)``; `step()` is one
    reconciliation pass (the unit tests drive it with an injectable
    clock), `start()`/`stop()` run it as a daemon loop at
    `hyperspace.controller.intervalSeconds`.
    """

    def __init__(self, hyperspace, server=None, clock=time.monotonic,
                 member_id: str | None = None, supervisor=None, ingest=None):
        # `hyperspace` is the user-facing API facade: like the advisor's
        # LifecyclePolicy, the controller has exactly the powers an
        # operator has — recover/refresh/lifecycle — no private side
        # doors into the log.
        self.hyperspace = hyperspace
        self.session = hyperspace.session
        self.server = server
        # Fleet identity on every audit event (defaults to the pid —
        # unique per fleet member since members are processes) and the
        # optional supervisor handle the scale actuator drives.
        self.member_id = str(member_id) if member_id else f"pid-{os.getpid()}"
        self.supervisor = supervisor
        # Continuous-ingestion daemon handle (ingest/daemon.py): the
        # controller throttles it while serve SLOs burn and resumes it
        # on recovery — background commit/compact IO is exactly the
        # load class the backoff discipline exists for.
        self.ingest = ingest
        self._ingest_paused = False
        self._clock = clock
        self._lock = threading.RLock()
        self._budget = int(self.session.conf.controller_actuation_budget)
        self._observe_only_announced = False
        self._page_ticks = 0
        self._ok_ticks = 0
        self._engaged = False
        self._saved: dict = {}
        self._cooldowns: dict[str, float] = {}
        self._last_seq = 0
        self._demotions: collections.deque = collections.deque()
        self._last_verdicts: dict[str, str] = {}
        self._recent_actions: collections.deque = collections.deque(maxlen=16)
        # Fleet-heal bookkeeping: marker generation last observed per
        # index (fresh generation = another member healed since we
        # looked), and the single-flight lease an in-flight heal holds
        # (its own small lock: stop() must reach it while step() is
        # blocked inside an actuation holding the main lock).
        self._seen_heal_gen: dict[str, int] = {}
        self._lease_lock = threading.Lock()
        self._held_lease: tuple | None = None
        # Incident flight recorder: at most ONE open bundle at a time
        # (later triggers annotate it rather than opening a second);
        # `_seen_quarantine` makes "fresh quarantine" detectable across
        # ticks so re-quarantine after a heal opens a NEW incident.
        self._incident_dir: Path | None = None
        self._incident_trigger: str | None = None
        self._incident_opened_at: float | None = None
        self._incident_notes: list[dict] = []
        self._seen_quarantine: set[str] = set()
        # Scale hysteresis state (mirrors page/ok ticks for saturation).
        self._sat_ticks = 0
        self._calm_ticks = 0
        self._scale_baseline: int | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        _BUDGET_REMAINING.set(self._budget)

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "OpsController":
        """Run the loop as a daemon thread; idempotent. Also registers
        this controller with the process-shared health endpoint (if one
        is live) so /healthz carries the controller verdict."""
        with self._lock:
            if self._thread is None:
                self._stop.clear()
                self._thread = threading.Thread(
                    target=self._run, name="hs-ops-controller", daemon=True
                )
                self._thread.start()
        from hyperspace_tpu.obs import http as obs_http

        shared = obs_http.shared()
        if shared is not None:
            shared.attach_controller(self)
            if self.supervisor is not None:
                # /healthz "fleet" section: member pids/ports and
                # per-member heartbeat ages without a member scrape.
                shared.attach_supervisor(self.supervisor)
        return self

    def stop(self, timeout: float = 5.0) -> None:
        """Stand the loop down. A heal actuation in flight may hold the
        fleet single-flight lease — release it BEFORE joining, so a
        controller stopped mid-heal (disarm, shutdown) never leaves a
        live lease blocking the fleet for TTL seconds. FileLease.release
        is token-checked and idempotent, so the actuation's own
        `finally` re-release is harmless."""
        self._stop.set()
        with self._lease_lock:
            held = self._held_lease
            self._held_lease = None
        if held is not None:
            lease, token = held
            try:
                lease.release(token)
            except OSError:
                pass  # reaped/expired already — nothing left to free
        with self._lock:
            t = self._thread
        if t is not None:
            t.join(timeout=timeout)
        with self._lock:
            self._thread = None

    def __enter__(self) -> "OpsController":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.stop()
        return False

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.step()
            except Exception as e:
                # One failed reconciliation pass must not kill the loop:
                # record it and keep reconciling. CrashPoint is a
                # BaseException and propagates — a dying process does
                # not keep actuating.
                stats.increment("controller.actuation_failures")
                _EVT_FAILED.emit(action="step", error=f"{type(e).__name__}: {e}")
            self._stop.wait(self.session.conf.controller_interval_seconds)

    # -- one reconciliation pass ------------------------------------------
    def step(self, now: float | None = None) -> dict:
        """One reconciliation pass: sample SLOs, drain new events,
        decide, actuate. Returns the post-step snapshot (the /healthz
        document's `controller` section). `now` overrides the injected
        clock for deterministic tests."""
        conf = self.session.conf
        if now is None:
            now = self._clock()
        now = float(now)
        with self._lock:
            if not conf.controller_enabled:
                # Kill switch mid-loop: release anything we hold, then
                # stand down without observing or deciding anything.
                if self._engaged:
                    self._release_overload(now, trigger="kill_switch")
                if self._ingest_paused:
                    self._resume_ingest(now, trigger="kill_switch")
                self._close_incident(now, resolution="kill_switch")
                return self.snapshot()
            stats.increment("controller.ticks")
            obs_slo.sample(now)
            verdicts = obs_slo.evaluate(now)
            self._last_verdicts = {k: v["verdict"] for k, v in verdicts.items()}
            burning = any(
                self._last_verdicts.get(o) == "page" for o in SERVE_OBJECTIVES
            )
            if burning:
                self._page_ticks += 1
                self._ok_ticks = 0
            else:
                self._ok_ticks += 1
                self._page_ticks = 0
            demotion_cluster, storm_keys = self._drain_events(conf, now)

            # 1. Overload response: shed + tighten quotas while pages
            # persist (hysteresis), restore once the burn clears.
            if (
                burning
                and not self._engaged
                and self._page_ticks >= int(conf.controller_hysteresis_ticks)
            ):
                if self._actuate(
                    "shed.engage", trigger="slo.page", now=now,
                    fn=lambda: self._engage_overload(conf),
                    verdicts=dict(self._last_verdicts),
                ):
                    # The overload response engaging IS the incident
                    # opening: snapshot the system state at the moment
                    # the controller started mutating it.
                    self._open_incident(
                        "slo.page", now, verdicts=dict(self._last_verdicts)
                    )
            elif (
                not burning
                and self._engaged
                and self._ok_ticks >= int(conf.controller_recovery_ticks)
            ):
                self._release_overload(now, trigger="slo.recovered")

            # 1b. Fleet scaling: sustained saturation grows the member
            # count (same hysteresis discipline as the overload
            # response); sustained calm restores the pre-episode count
            # (budget-free, like every release).
            if self.supervisor is not None:
                self._reconcile_scale(conf, now)

            # 1c. Ingest backoff: the continuous-ingestion daemon is
            # rebuild-class background IO on the serve plane — pause it
            # (durably: an atomically-written control file its every
            # tick polls, so it works across process boundaries) while
            # pages persist, resume once the burn clears. Pausing is a
            # budgeted, cooldown-disciplined actuation; resuming is
            # budget-free like every release.
            if self.ingest is not None:
                if (
                    burning
                    and not self._ingest_paused
                    and self._page_ticks >= int(conf.controller_hysteresis_ticks)
                ):
                    if self._actuate(
                        "ingest.pause", trigger="slo.page", now=now,
                        fn=lambda: self.ingest.pause(reason="controller.slo_burn"),
                        verdicts=dict(self._last_verdicts),
                    ):
                        self._ingest_paused = True
                elif (
                    not burning
                    and self._ingest_paused
                    and self._ok_ticks >= int(conf.controller_recovery_ticks)
                ):
                    self._resume_ingest(now, trigger="slo.recovered")

            # 2. Heal quarantined indexes — rebuild-class work, deferred
            # while serve SLOs burn (backing off background work is
            # itself the actuation that protects the serve plane).
            with self.session._state_lock:
                quarantined = sorted(self.session.index_health)
            # A FRESH quarantine (not seen last tick) opens an incident
            # bundle — re-quarantine after a successful heal is a new
            # episode and records as one.
            current_q = {Path(r).name for r in quarantined}
            for q_name in sorted(current_q - self._seen_quarantine):
                self._open_incident(f"quarantine.{q_name}", now, index=q_name)
            self._seen_quarantine = current_q
            for root in quarantined:
                name = Path(root).name
                if burning:
                    self._defer_background(
                        conf, "heal", now, index=name, reason="slo.burning"
                    )
                    continue
                self._actuate(
                    f"heal.{name}", trigger="index.quarantined", now=now,
                    fn=lambda n=name: self._heal(conf, n), index=name,
                )

            # 3. Routing demotions clustering means the index layout no
            # longer fits the workload: hand the evidence to the advisor.
            if demotion_cluster:
                if burning:
                    self._defer_background(
                        conf, "advisor.sweep", now, reason="slo.burning"
                    )
                elif self._actuate(
                    "advisor.sweep", trigger="routing.demotion_cluster", now=now,
                    fn=self._sweep, demotions=demotion_cluster,
                ):
                    self._demotions.clear()  # evidence consumed; re-arm

            # 4. Recompile-storm response: pin the storming signature to
            # the raw-scan route and drop the jit caches once. NOT
            # deferred while burning — a storm is itself a serve-plane
            # pressure source, and the response is cheap.
            if getattr(conf, "controller_storm_response", True):
                for key in storm_keys:
                    self._actuate(
                        f"storm.response.{key}", trigger="jit.recompile_storm",
                        now=now, fn=lambda k=key: self._storm_response(k),
                        key=key,
                    )

            # 5. Incident close: the episode is over once nothing is
            # burning, no override is engaged, and no index remains
            # quarantined — finalize the open bundle (journal segments
            # from every member, manifest with the audit trail). The
            # quarantine state is re-read: a heal that just executed
            # above empties it THIS tick, and recovery should close the
            # bundle in the same reconciliation pass it happened in.
            if self._incident_dir is not None and not burning and not self._engaged:
                with self.session._state_lock:
                    still_quarantined = bool(self.session.index_health)
                if not still_quarantined:
                    self._close_incident(
                        now, resolution=self._incident_resolution()
                    )
            return self.snapshot()

    # -- signal plumbing --------------------------------------------------
    def _drain_events(self, conf, now: float) -> tuple[int, list[str]]:
        """Fold new ring events into the controller's trailing state;
        returns (demotion count when it constitutes a cluster, the keys
        of fresh `jit.recompile_storm` events, deduplicated in order)."""
        fresh = [e for e in obs_events.recent() if e["seq"] > self._last_seq]
        if fresh:
            self._last_seq = max(e["seq"] for e in fresh)
        storms: list[str] = []
        for e in fresh:
            if e["name"] == "jit.recompile_storm":
                key = str(e.get("fields", {}).get("key", ""))
                if key and key not in storms:
                    storms.append(key)
        n = sum(1 for e in fresh if e["name"] == "advisor.routing.demoted")
        if n:
            self._demotions.append((now, n))
        cutoff = now - float(conf.controller_demotion_window_seconds)
        while self._demotions and self._demotions[0][0] < cutoff:
            self._demotions.popleft()
        total = sum(c for _, c in self._demotions)
        cluster = total if total >= int(conf.controller_demotion_cluster_size) else 0
        return cluster, storms

    # -- actuators --------------------------------------------------------
    def _actuate(self, action: str, trigger: str, now: float, fn, **details) -> bool:
        """Run one mutation under the full control discipline: cooldown,
        budget, fault point, audit. Returns True when it executed."""
        conf = self.session.conf
        if self._cooldowns.get(action, float("-inf")) > now:
            stats.increment("controller.deferred")
            return False
        if self._budget <= 0:
            # Observe-only: the decision is still computed and audited,
            # nothing mutates.
            self._announce_observe_only(now)
            stats.increment("controller.deferred")
            _EVT_ACTUATION.emit(
                action=action, trigger=trigger, outcome="observe_only",
                member=self.member_id, **details,
            )
            return False
        # The fault point fires BEFORE any mutation: a CrashPoint here
        # unwinds out of step() with zero partial state (tested), and a
        # transient FaultError surfaces through the declared contract.
        faults.fault_point("controller.actuate")
        try:
            with obs_trace.span("controller.actuate", action=action, trigger=trigger):
                result = fn()
        except Exception as e:
            # The failed subsystem's own Action already rolled back;
            # record, cool down, keep reconciling. CrashPoint propagates.
            stats.increment("controller.actuation_failures")
            _EVT_FAILED.emit(
                action=action, trigger=trigger, member=self.member_id,
                error=f"{type(e).__name__}: {e}",
            )
            self._cooldowns[action] = now + float(conf.controller_cooldown_seconds)
            return False
        if result == "observed":
            # Fleet-coordinated decision resolved by ANOTHER member (a
            # heal follower): nothing mutated here, so no budget spent
            # and no actuation counted — "exactly one fleet-wide" stays
            # exact — but the decision is audited and cooled down like
            # any other.
            self._cooldowns[action] = now + float(conf.controller_cooldown_seconds)
            record = _EVT_ACTUATION.emit(
                action=action, trigger=trigger, outcome="observed",
                member=self.member_id, budget_remaining=self._budget, **details,
            )
            self._recent_actions.append(
                {"action": action, "trigger": trigger, "at": now,
                 "seq": record["seq"]}
            )
            return True
        self._budget -= 1
        _BUDGET_REMAINING.set(self._budget)
        stats.increment("controller.actuations")
        self._cooldowns[action] = now + float(conf.controller_cooldown_seconds)
        record = _EVT_ACTUATION.emit(
            action=action, trigger=trigger, outcome="executed",
            member=self.member_id, budget_remaining=self._budget, **details,
        )
        self._recent_actions.append(
            {"action": action, "trigger": trigger, "at": now, "seq": record["seq"]}
        )
        return True

    def _engage_overload(self, conf) -> None:
        # Re-entered under the step() RLock; restated here because this
        # runs through the _actuate(fn=...) indirection, which hides the
        # entry-lock guarantee from direct call-site analysis.
        with self._lock:
            saved: dict = {}
            if self.server is not None:
                saved["shed_depth"] = self.server.get_shed_depth()
                self.server.set_shed_depth(
                    int(self.server.max_queue_depth * float(conf.controller_shed_ratio))
                )
                quotas = getattr(self.server, "quotas", None)
                if quotas is not None:
                    saved["throttle"] = quotas.throttle()
                    quotas.set_throttle(float(conf.controller_quota_factor))
            self._saved = saved
            self._engaged = True
            _ENGAGED.set(1)

    def _release_overload(self, now: float, trigger: str) -> None:
        """Restore the pre-engage shed depth and quota throttle. Free of
        budget by design — the controller must always be able to leave
        the system as it found it (kill switch, budget exhaustion)."""
        faults.fault_point("controller.actuate")
        try:
            if self.server is not None:
                if "shed_depth" in self._saved:
                    self.server.set_shed_depth(self._saved["shed_depth"])
                quotas = getattr(self.server, "quotas", None)
                if quotas is not None and "throttle" in self._saved:
                    quotas.set_throttle(self._saved["throttle"])
        except Exception as e:
            stats.increment("controller.actuation_failures")
            _EVT_FAILED.emit(
                action="shed.release", trigger=trigger,
                error=f"{type(e).__name__}: {e}",
            )
            return
        self._engaged = False
        self._saved = {}
        _ENGAGED.set(0)
        record = _EVT_ACTUATION.emit(
            action="shed.release", trigger=trigger, outcome="executed",
            member=self.member_id, budget_remaining=self._budget,
        )
        self._recent_actions.append(
            {"action": "shed.release", "trigger": trigger, "at": now,
             "seq": record["seq"]}
        )

    def _resume_ingest(self, now: float, trigger: str) -> None:
        """Un-pause the ingest daemon we paused. Budget-free by design,
        exactly like `_release_overload`: the controller must always be
        able to hand back what it took (kill switch, budget
        exhaustion), and a resume that fails stays paused-by-us so the
        next tick retries."""
        faults.fault_point("controller.actuate")
        try:
            self.ingest.resume()
        except Exception as e:
            stats.increment("controller.actuation_failures")
            _EVT_FAILED.emit(
                action="ingest.resume", trigger=trigger,
                error=f"{type(e).__name__}: {e}",
            )
            return
        self._ingest_paused = False
        record = _EVT_ACTUATION.emit(
            action="ingest.resume", trigger=trigger, outcome="executed",
            member=self.member_id, budget_remaining=self._budget,
        )
        self._recent_actions.append(
            {"action": "ingest.resume", "trigger": trigger, "at": now,
             "seq": record["seq"]}
        )

    def _heal(self, conf, name: str):
        """Heal one quarantined index — fleet-coordinated when a fleet
        directory is discoverable, process-local otherwise.

        Coordinated path: the heal routes through the single-flight
        lease keyed per index. The lease LEADER runs the local heal
        (recover + gated rebuild) and publishes a generation-stamped
        marker; every FOLLOWER observes the fresh marker, lifts its own
        quarantine with the idempotent `recover()` (the leader already
        repaired the shared bytes), and returns ``"observed"`` so
        `_actuate` spends no budget and counts no heal — exactly one
        `controller.heals` fleet-wide. Generations (not wall-clock
        timestamps) mark freshness: a member that restarts observes one
        stale marker at most, then heals normally next tick."""
        root = self._fleet_root(conf)
        if root is None:
            self._heal_local(conf, name)
            return None
        from hyperspace_tpu.serve.fleet.singleflight import SingleFlight

        heal_dir = root / "heal"
        heal_dir.mkdir(parents=True, exist_ok=True)
        marker = heal_dir / f"{name}.json"
        sf = SingleFlight(
            heal_dir,
            lease_ttl_s=float(conf.fleet_lease_seconds),
            wait_s=float(conf.fleet_singleflight_wait_seconds),
        )

        def check():
            doc = self._read_marker(marker)
            if doc is None:
                return None
            gen = int(doc.get("generation", 0))
            if gen <= self._seen_heal_gen.get(name, 0):
                return None  # our own past observation, not a fresh heal
            return doc

        def build():
            self._heal_local(conf, name)
            # Torn window: shared bytes healed, marker not yet
            # published. A crash here leaves followers quarantined for
            # one tick; the next leader re-heals idempotently.
            faults.fault_point("controller.heal.marker", marker)
            prior = self._read_marker(marker) or {}
            gen = int(prior.get("generation", 0)) + 1
            self._write_marker(marker, {
                "index": name, "member": self.member_id, "generation": gen,
            })
            self._seen_heal_gen[name] = gen
            return {"led": True, "generation": gen}

        doc = sf.run(f"heal.{name}", build, check=check,
                     on_lease=self._note_lease)
        if isinstance(doc, dict) and not doc.get("led"):
            # Follower: another member rebuilt the shared bytes; lift
            # the LOCAL quarantine (recover is idempotent) and record
            # the generation we acted on.
            self._seen_heal_gen[name] = int(doc.get("generation", 0))
            self.hyperspace.recover(name)
            return "observed"
        return None

    def _heal_local(self, conf, name: str) -> None:
        """recover() repairs the log and lifts the quarantine; the gated
        full refresh rebuilds the data files through the crash-safe
        Action protocol so the corruption is actually gone (not merely
        re-served until the next quarantine)."""
        self.hyperspace.recover(name)
        if conf.controller_heal_rebuild:
            self.hyperspace.refresh_index(name, "full")
        stats.increment("controller.heals")

    def _fleet_root(self, conf) -> Path | None:
        """The shared fleet directory heals coordinate under, or None
        when coordination is off / no fleet root is discoverable (then
        heals stay process-local — the pre-fleet behavior)."""
        if not getattr(conf, "controller_heal_coordinate", True):
            return None
        if getattr(conf, "fleet_cache_dir", ""):
            return Path(conf.fleet_cache_dir)
        sp = Path(conf.system_path)
        if sp.is_dir():
            return sp / "_fleet"
        return None

    def _note_lease(self, lease, token) -> None:
        """SingleFlight's on_lease hook: remember the lease an in-flight
        heal holds so stop() can release it before joining."""
        with self._lease_lock:
            self._held_lease = (lease, token) if lease is not None else None

    @staticmethod
    def _read_marker(path: Path) -> dict | None:
        try:
            return json.loads(path.read_text())
        except (OSError, ValueError):
            return None  # absent or torn: treated as no published heal

    @staticmethod
    def _write_marker(path: Path, doc: dict) -> None:
        # mkstemp + fsync + rename so a follower's read never sees a
        # torn document AND a crash never publishes an empty marker (the
        # rename is durable before the data without the fsync barrier);
        # writer races are excluded by the single-flight lease.
        fd, tmp = tempfile.mkstemp(dir=str(path.parent), prefix=".heal-",
                                   suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as f:
                f.write(json.dumps(doc))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            file_utils.fsync_dir(path.parent)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)

    def _reconcile_scale(self, conf, now: float) -> None:
        """Fleet-scale hysteresis: count saturated vs calm ticks from
        the worst of the fleet-aggregate and local queue ratios, grow
        the member count after `hysteresisTicks` saturated ticks, and
        restore the pre-episode baseline after `recoveryTicks` calm
        ticks (budget-free — the controller always leaves the fleet as
        found)."""
        sat = self._saturation_ratio()
        if sat >= float(getattr(conf, "controller_scale_saturation", 0.75)):
            self._sat_ticks += 1
            self._calm_ticks = 0
        else:
            self._calm_ticks += 1
            self._sat_ticks = 0
        current = int(self.supervisor.n)
        max_workers = int(getattr(conf, "controller_scale_max_workers", 8))
        if (
            self._sat_ticks >= int(conf.controller_hysteresis_ticks)
            and current < max_workers
        ):
            step = max(1, int(getattr(conf, "controller_scale_step", 1)))
            target = min(current + step, max_workers)
            baseline = self._scale_baseline if self._scale_baseline is not None else current
            if self._actuate(
                "fleet.scale.up", trigger="fleet.saturation", now=now,
                fn=lambda t=target: self._scale_to(t, conf),
                workers=target, saturation=round(sat, 3),
            ):
                self._scale_baseline = baseline
                self._sat_ticks = 0
        elif (
            self._calm_ticks >= int(conf.controller_recovery_ticks)
            and self._scale_baseline is not None
            and current > self._scale_baseline
        ):
            self._scale_release(conf, now)

    def _saturation_ratio(self) -> float:
        """Worst queue-fullness ratio across the fleet aggregate and the
        local server (either one saturating is a real capacity signal)."""
        ratios = [0.0]
        try:
            agg = self.supervisor.fleet_health().get("saturation", {})
            ratios.append(
                float(agg.get("queue_depth", 0)) / max(1.0, float(agg.get("max_queue_depth", 0)))
            )
        except Exception:
            # Unreachable members count as zero load for this tick, but
            # the failed probe itself is still a signal.
            stats.increment("controller.health_probe_errors")
        if self.server is not None:
            try:
                local = self.server.saturation()
                ratios.append(
                    float(local.get("queue_depth", 0))
                    / max(1.0, float(local.get("max_queue_depth", 0)))
                )
            except Exception:
                stats.increment("controller.health_probe_errors")
        return max(ratios)

    def _scale_to(self, target: int, conf) -> None:
        min_workers = max(1, int(getattr(conf, "fleet_min_workers", 1)))
        self.supervisor.set_target_workers(target, min_workers=min_workers)
        stats.increment("controller.scale")

    def _scale_release(self, conf, now: float) -> None:
        """Restore the pre-episode member count. Budget-free like
        `_release_overload`: the scale-down is the controller leaving
        the fleet as it found it."""
        baseline = self._scale_baseline
        if baseline is None:
            return
        faults.fault_point("controller.actuate")
        try:
            min_workers = max(1, int(getattr(conf, "fleet_min_workers", 1)))
            self.supervisor.set_target_workers(baseline, min_workers=min_workers)
        except Exception as e:
            stats.increment("controller.actuation_failures")
            _EVT_FAILED.emit(
                action="fleet.scale.down", trigger="fleet.recovered",
                member=self.member_id, error=f"{type(e).__name__}: {e}",
            )
            return
        self._scale_baseline = None
        self._calm_ticks = 0
        stats.increment("controller.scale")
        record = _EVT_ACTUATION.emit(
            action="fleet.scale.down", trigger="fleet.recovered",
            outcome="executed", member=self.member_id,
            budget_remaining=self._budget, workers=baseline,
        )
        self._recent_actions.append(
            {"action": "fleet.scale.down", "trigger": "fleet.recovered",
             "at": now, "seq": record["seq"]}
        )

    def _storm_response(self, key: str) -> None:
        """One recompile storm, one response: pin the storming key's
        signature to the raw-scan route (so it stops feeding the jit
        cache — versioned like every routing entry, any index mutation
        re-promotes it) and drop the jit caches once to evict the
        churned executables."""
        from hyperspace_tpu.utils import jit_memory

        self.session.routing_ledger().pin(key, "raw")
        jit_memory.drop_caches(reason="controller.storm_response")
        _EVT_STORM.emit(key=key, route="raw", member=self.member_id)

    def _sweep(self) -> None:
        # The lifecycle policy's own gates (autoCreate/autoVacuum/
        # autoOptimize, confidence and benefit floors) still decide WHAT
        # may mutate; the controller only decided WHEN to look.
        self.hyperspace.lifecycle().sweep()

    def _defer_background(self, conf, action: str, now: float, **details) -> None:
        stats.increment("controller.deferred")
        key = f"backoff.{action}"
        if self._cooldowns.get(key, float("-inf")) <= now:
            # Rate-limit the audit record, not the deferral itself.
            self._cooldowns[key] = now + float(conf.controller_cooldown_seconds)
            _EVT_BACKOFF.emit(action=action, **details)

    def _announce_observe_only(self, now: float) -> None:
        if not self._observe_only_announced:
            self._observe_only_announced = True
            _EVT_OBSERVE_ONLY.emit(budget_remaining=0)
            # Budget exhaustion is itself an incident: snapshot the
            # moment the controller degraded (open + close in one
            # motion — there is no "recovery" to wait for). An already-
            # open episode is annotated instead, not closed early.
            if self._incident_dir is None:
                self._open_incident("observe_only", now, budget_remaining=0)
                self._close_incident(now, resolution="observe_only")
            else:
                self._incident_notes.append(
                    {"trigger": "observe_only", "at": now, "budget_remaining": 0}
                )

    # -- incident flight recorder -----------------------------------------
    def _incident_root(self, conf) -> Path | None:
        """Where bundles land, or None when the recorder is disabled /
        no root is derivable. NOT gated by `heal.coordinate` — a
        single-process controller still records its incidents."""
        if not getattr(conf, "controller_incident_enabled", True):
            return None
        explicit = getattr(conf, "controller_incident_dir", "")
        if explicit:
            return Path(explicit)
        if getattr(conf, "fleet_cache_dir", ""):
            return Path(conf.fleet_cache_dir) / "incidents"
        sp = Path(conf.system_path)
        if sp.is_dir():
            return sp / "_fleet" / "incidents"
        return None

    def _open_incident(self, trigger: str, now: float, **annotations) -> None:
        """Open ONE incident bundle: `<root>/<ts>-<trigger>/` with the
        state an operator needs at page time — event-ring dump, config
        snapshot, jit report, routing ledger. Rate-limited per trigger
        by the controller cooldown; retention pruned to
        `controller.incident.maxBundles`. Advisory end to end: IO
        failures are counted, never raised."""
        if self._incident_dir is not None:
            # One open bundle at a time: later triggers annotate it.
            self._incident_notes.append(
                {"trigger": trigger, "at": now, **annotations}
            )
            return
        conf = self.session.conf
        root = self._incident_root(conf)
        if root is None:
            return
        key = f"incident.{trigger}"
        if self._cooldowns.get(key, float("-inf")) > now:
            stats.increment("controller.deferred")
            return
        self._cooldowns[key] = now + float(conf.controller_cooldown_seconds)
        try:
            wall = time.time()  # noqa: HSL007 — bundle names + manifest
            # timestamps are operator-facing artifacts, not control flow.
            stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime(wall))
            base = f"{stamp}-{trigger}"
            bundle = root / base
            n = 2
            while bundle.exists():
                bundle = root / f"{base}-{n}"
                n += 1
            bundle.mkdir(parents=True)
            self._write_bundle_state(bundle, trigger, now, wall, annotations)
            self._incident_dir = bundle
            self._incident_trigger = trigger
            self._incident_opened_at = now
            self._incident_notes = []
            stats.increment("controller.incidents")
            _EVT_INCIDENT.emit(
                phase="open", trigger=trigger, bundle=bundle.name,
                member=self.member_id,
            )
            self._prune_incidents(root, int(conf.controller_incident_max_bundles))
        except (OSError, ValueError):
            # Advisory: the flight recorder must never become the
            # incident — the failed write is the count, reconciliation
            # continues untouched.
            stats.increment("controller.incident_errors")

    def _write_bundle_state(
        self, bundle: Path, trigger: str, now: float, wall: float, annotations: dict
    ) -> None:
        from hyperspace_tpu import config as _config
        from hyperspace_tpu.obs import runtime as obs_runtime
        from hyperspace_tpu.utils import file_utils

        conf = self.session.conf
        file_utils.write_json(bundle / "open.json", {
            "trigger": trigger, "member": self.member_id,
            "at": wall, "clock": now,
            "verdicts": dict(self._last_verdicts),
            "annotations": dict(annotations),
        })
        file_utils.write_json(
            bundle / "events.json", {"events": obs_events.recent(limit=1024)}
        )
        file_utils.write_json(
            bundle / "config.json",
            {k: conf.get(k) for k in sorted(_config.KNOWN_KEYS)},
        )
        file_utils.write_json(bundle / "jit.json", obs_runtime.jit_report())
        routing: dict = {}
        ledger = getattr(self.session, "routing_ledger", None)
        if callable(ledger):
            snap = getattr(ledger(), "snapshot", None)
            if callable(snap):
                routing = snap()
        file_utils.write_json(bundle / "routing.json", routing)

    def _close_incident(self, now: float, resolution: str) -> None:
        """Finalize the open bundle: seal the local journal, copy the
        last N sealed segments from every reachable member's journal
        dir, refresh the event-ring dump (it now holds the whole
        episode), and write the manifest — resolution plus the
        actuation audit trail. No-op when nothing is open; advisory
        like `_open_incident`."""
        bundle = self._incident_dir
        if bundle is None:
            return
        trigger = self._incident_trigger
        opened_at = self._incident_opened_at
        notes = list(self._incident_notes)
        self._incident_dir = None
        self._incident_trigger = None
        self._incident_opened_at = None
        self._incident_notes = []
        conf = self.session.conf
        try:
            from hyperspace_tpu.utils import file_utils

            copied = self._copy_journal_segments(
                bundle, int(conf.controller_incident_segments)
            )
            file_utils.write_json(
                bundle / "events.json", {"events": obs_events.recent(limit=1024)}
            )
            wall = time.time()  # noqa: HSL007 — manifest timestamps are
            # operator-facing artifacts, not control flow.
            file_utils.write_json(bundle / "manifest.json", {
                "trigger": trigger, "resolution": resolution,
                "member": self.member_id,
                "opened_clock": opened_at, "closed_clock": now,
                "closed_at": wall,
                "verdicts": dict(self._last_verdicts),
                "annotations": notes,
                "actions": list(self._recent_actions),
                "journal_segments": copied,
            })
            _EVT_INCIDENT.emit(
                phase="closed", trigger=trigger, resolution=resolution,
                bundle=bundle.name, member=self.member_id,
            )
        except (OSError, ValueError):
            # Advisory: a bundle without a manifest reads as still-open
            # in /debug/incidents, which is the truthful rendering of a
            # close that could not complete.
            stats.increment("controller.incident_errors")

    def _copy_journal_segments(self, bundle: Path, keep: int) -> int:
        """Copy the last `keep` SEALED journal segments from every
        member's `<_obs>/<pid>/` dir into `bundle/journal/<pid>/`;
        returns the copy count. Sealing the local journal first makes
        this member's in-flight tail durable before the snapshot."""
        from hyperspace_tpu.obs import journal as obs_journal

        obs_journal.seal()
        jroot = obs_journal.root()
        if jroot is None:
            return 0
        jroot = Path(jroot)
        if not jroot.is_dir():
            return 0
        copied = 0
        for proc_dir in sorted(jroot.iterdir()):
            if not (proc_dir.is_dir() and proc_dir.name.isdigit()):
                continue
            segs = obs_journal.segment_paths(proc_dir)[-max(1, keep):]
            if not segs:
                continue
            dest = bundle / "journal" / proc_dir.name
            dest.mkdir(parents=True, exist_ok=True)
            for seg in segs:
                try:
                    shutil.copy2(seg, dest / Path(seg).name)
                    copied += 1
                except OSError:
                    # A live member may evict the segment between the
                    # listing and the copy — count it, keep copying.
                    stats.increment("controller.incident_errors")
        return copied

    def _incident_resolution(self) -> str:
        t = self._incident_trigger or ""
        if t.startswith("slo"):
            return "slo.recovered"
        if t.startswith("quarantine"):
            return "healed"
        return "recovered"

    @staticmethod
    def _prune_incidents(root: Path, keep: int) -> None:
        """Drop the oldest bundle dirs beyond `keep` (names are
        timestamp-prefixed, so lexical order is chronological)."""
        keep = max(1, keep)
        dirs = sorted(d for d in root.iterdir() if d.is_dir())
        for d in dirs[:-keep]:
            shutil.rmtree(d, ignore_errors=True)

    def list_incidents(self) -> list[dict]:
        """Read-only bundle index (the /debug/incidents list): name,
        trigger, open/closed, resolution — newest last."""
        root = self._incident_root(self.session.conf)
        if root is None or not root.is_dir():
            return []
        out = []
        for d in sorted(root.iterdir()):
            if not d.is_dir():
                continue
            doc: dict = {"name": d.name}
            opened = self._read_marker(d / "open.json")
            if opened:
                doc["trigger"] = opened.get("trigger")
                doc["member"] = opened.get("member")
                doc["at"] = opened.get("at")
            manifest = self._read_marker(d / "manifest.json")
            doc["open"] = manifest is None
            if manifest:
                doc["resolution"] = manifest.get("resolution")
            out.append(doc)
        return out

    def read_incident(self, name: str) -> dict | None:
        """One bundle's manifest + open record + file inventory, or
        None for unknown names (the /debug/incidents?name= detail)."""
        if not name or "/" in name or "\\" in name or ".." in name:
            return None  # bundle names never contain path separators
        root = self._incident_root(self.session.conf)
        if root is None:
            return None
        d = root / name
        if not d.is_dir():
            return None
        files = sorted(
            str(p.relative_to(d)) for p in d.rglob("*") if p.is_file()
        )
        doc: dict = {"name": name, "files": files}
        opened = self._read_marker(d / "open.json")
        if opened:
            doc["open"] = opened
        manifest = self._read_marker(d / "manifest.json")
        if manifest:
            doc["manifest"] = manifest
        return doc

    # -- views ------------------------------------------------------------
    def snapshot(self) -> dict:
        """Point-in-time controller state — the /healthz `controller`
        section (docs/observability.md)."""
        with self._lock:
            enabled = bool(self.session.conf.controller_enabled)
            if not enabled:
                mode = "disabled"
            elif self._budget <= 0:
                mode = "observe_only"
            else:
                mode = "actuate"
            return {
                "enabled": enabled,
                "mode": mode,
                "member": self.member_id,
                "engaged": self._engaged,
                "ingest_paused": self._ingest_paused,
                "budget_remaining": self._budget,
                "verdicts": dict(self._last_verdicts),
                "page_ticks": self._page_ticks,
                "ok_ticks": self._ok_ticks,
                "sat_ticks": self._sat_ticks,
                "scale_baseline": self._scale_baseline,
                "pending_demotions": sum(c for _, c in self._demotions),
                "open_incident": (
                    self._incident_dir.name if self._incident_dir else None
                ),
                "recent_actions": list(self._recent_actions),
            }
