"""Optimized-plan cache: repeat queries skip `optimized_plan()` entirely.

Plan optimization is pure host work — rule matching, index-log reads,
pushdown/prune rewrites — but under serving traffic it runs once per
query, and for a point-lookup workload it can dominate the (cached,
device-resident) execution. This cache memoizes the *output* of
`HyperspaceSession.optimized_plan` under a **versioned key**, so
invalidation is structural rather than event-driven:

    (plan signature,            # canonical-JSON MD5 of the logical plan
     data fingerprint,          # (size, mtime, path) fold of source files
     index log versions,        # (index dir, latest log id) per index
     quarantine set,            # session.index_health snapshot
     hyperspace enabled?)

Every mutating index API — create/refresh/optimize/delete/restore/vacuum
— commits by writing a NEW log entry, so the latest log id bumps and old
keys simply never hit again; appended/rewritten source files change the
data fingerprint the same way. There is no invalidation hook to forget
and no stale-entry window: a key either describes the current world or
is unreachable. The LRU bound only caps memory.

Thread-safe; hits/misses/evictions land in the exportable metrics
registry (`serve.plan_cache.*`, docs/observability.md).
"""

from __future__ import annotations

import threading

from hyperspace_tpu.obs import metrics as obs_metrics
from hyperspace_tpu.signature import FileBasedSignatureProvider, plan_signature


def collection_log_versions(session) -> tuple:
    """(index dir name, latest log id) per index under the system path —
    the cheap metadata-plane stamp every versioned serve key embeds. Any
    committed index mutation writes a new log entry and bumps it."""
    mgr = session.manager
    out = []
    for d in mgr.path_resolver.list_index_paths():
        out.append((d.name, mgr.log_manager_factory(d).get_latest_id()))
    return tuple(out)


def versioned_plan_key(session, plan, snapshot=None) -> tuple:
    """The full serve-cache key for `plan` under `session`'s current
    world state (module docstring). Stat-ing the source files costs one
    os.stat per file — orders of magnitude cheaper than re-optimizing,
    and it is exactly what makes a post-append/post-refresh hit
    impossible. A pinned `snapshot` (ingest/snapshot.py) substitutes its
    admission-time stamp for the live version vector: the pinned world
    never moves, so pinned reads keep hitting while micro-batches bump
    the live ids underneath."""
    fp = FileBasedSignatureProvider().signature(plan)
    with session._state_lock:
        quarantined = tuple(sorted(session.index_health))
    return (
        plan_signature(plan),
        fp.value if fp is not None else None,
        snapshot.stamp if snapshot is not None else collection_log_versions(session),
        quarantined,
        session.is_hyperspace_enabled(),
    )


class PlanCache:
    """Bounded LRU of optimized logical plans keyed by versioned plan
    key. Cached plans are shared across threads — plan nodes are
    immutable after construction (the optimizer builds new trees, the
    executor only reads them)."""

    def __init__(self, max_entries: int = 128):
        self.max_entries = int(max_entries)
        self._lock = threading.Lock()
        self._entries: dict[tuple, object] = {}
        self._hits = obs_metrics.counter("serve.plan_cache.hits", "optimized-plan cache hits")
        self._misses = obs_metrics.counter("serve.plan_cache.misses", "optimized-plan cache misses")
        self._evictions = obs_metrics.counter("serve.plan_cache.evictions", "LRU evictions")

    def get_or_optimize(self, session, plan, snapshot=None):
        """The optimized plan for `plan`, from cache when the versioned
        key matches, else freshly via `session.optimized_plan` (outside
        the lock — optimization reads the index log and stats files)."""
        key = versioned_plan_key(session, plan, snapshot=snapshot)
        with self._lock:
            hit = self._entries.get(key)
            if hit is not None:
                self._entries[key] = self._entries.pop(key)  # LRU touch
                self._hits.inc()
                return hit
        self._misses.inc()
        optimized = session.optimized_plan(plan, snapshot=snapshot)
        with self._lock:
            if key not in self._entries:
                self._entries[key] = optimized
                while len(self._entries) > self.max_entries:
                    self._entries.pop(next(iter(self._entries)))
                    self._evictions.inc()
        return optimized

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "hits": self._hits.value,
                "misses": self._misses.value,
                "evictions": self._evictions.value,
            }
