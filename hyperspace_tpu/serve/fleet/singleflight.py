"""Cross-process single-flight: N cold processes, ONE build.

`device_cache.RefCache` already dedups concurrent in-process misses on
one cold key (one thread builds, the rest wait on its event —
docs/serving.md). A fleet of processes has the same thundering-herd
problem one level up: N freshly started workers all miss the shared
plan/result cache on the same hot key and would each pay the same
optimize/execute/stage cost. This module extends the dedup across
process boundaries with a lease-file protocol (fleet/lease.py):

- the first claimant wins the lease and becomes the **leader** — it
  runs ``build()`` (which normally publishes its artifact into the
  shared cache) and releases the lease;
- every other process is a **follower**: it polls ``check()`` (the
  shared-cache read) and returns as soon as the leader's artifact
  appears;
- a follower whose wait expires (``wait_s``) falls back to a **local
  build** — correctness never depends on the leader, the wait only
  dedups work;
- a leader that is SIGKILLed mid-build leaves a lease whose epoch goes
  stale after the TTL; the next claimant **reaps** it and takes over
  (`fleet.singleflight.takeovers`) — a crashed holder can never wedge
  the fleet.

Every outcome is counted (`fleet.singleflight.*`, stats.KNOWN_COUNTERS)
and a takeover additionally emits a WARN ``fleet.singleflight.takeover``
event naming the key — reaping a dead process's lease is worth an
operator's attention even though the fleet healed itself.
"""

from __future__ import annotations

import hashlib
import time
from pathlib import Path
from typing import Callable

from hyperspace_tpu import stats
from hyperspace_tpu.obs import events as obs_events
from hyperspace_tpu.obs import trace as obs_trace
from hyperspace_tpu.serve.fleet.lease import FileLease

_EVT_TAKEOVER = obs_events.declare("fleet.singleflight.takeover")

# Follower poll cadence: cheap (one stat / small read per lap) and fast
# enough that a follower observes the leader's publish promptly.
_POLL_S = 0.02


def key_name(key: object) -> str:
    """Filesystem-safe digest of an arbitrary (reprable) key."""
    return hashlib.md5(repr(key).encode()).hexdigest()


class SingleFlight:
    """Lease-backed cross-process build dedup rooted at one directory
    (every fleet member must point at the same dir — the factory in
    serve/fleet/__init__.py derives it from the shared store path)."""

    def __init__(self, root: str | Path, lease_ttl_s: float = 10.0, wait_s: float = 15.0):
        self.root = Path(root)
        self.lease_ttl_s = float(lease_ttl_s)
        self.wait_s = float(wait_s)

    def run(self, name: str, build: Callable, check: Callable | None = None,
            on_lease: Callable | None = None):
        """Run `build()` at most once across the fleet for `name`,
        returning its value. `check() -> value | None` observes the
        leader's published artifact (e.g. a shared-cache read); without
        it every claimant that loses the lease waits for the lease to
        clear and then builds (pure serialization, no artifact reuse).
        Exceptions from `build` propagate to the caller that ran it;
        the lease is always released.

        `on_lease(lease, token)` (optional) is told when this process
        WINS the lease, and `on_lease(None, None)` when it is released —
        a shutdown path (OpsController.stop) uses it to release a lease
        its in-flight actuation still holds instead of leaving it live
        for TTL seconds. FileLease.release is token-checked and
        idempotent, so the `finally` re-release is harmless."""
        lease = FileLease(self.root / f"{key_name(name)}.lease", self.lease_ttl_s)
        deadline = time.monotonic() + self.wait_s
        # Follower wait span: opened lazily on the first lap that
        # actually waits, linked to the leader's root trace id read from
        # the lease token's note field — the cross-process edge a merged
        # fleet trace needs (docs/observability.md "cross-process query
        # traces"). NOOP outside a trace; closed on every exit path.
        wait_span = None
        leader_id = None
        try:
            while True:
                # Check BEFORE claiming: once the leader releases, every
                # waiter's next acquire would succeed — without this order a
                # waiter that raced past its last check would win the freed
                # lease and redo the build it was waiting for.
                if check is not None:
                    value = check()
                    if value is not None:
                        stats.increment("fleet.singleflight.follower_hits")
                        if wait_span is not None:
                            wait_span.set(outcome="follower_hit")
                        return value
                claim = lease.try_acquire(note=obs_trace.current_trace_id())
                if claim is not None:
                    token, reaped = claim
                    if on_lease is not None:
                        on_lease(lease, token)
                    try:
                        if check is not None:
                            # Double-check after winning: the previous
                            # leader may have published between our check
                            # and the claim.
                            value = check()
                            if value is not None:
                                stats.increment("fleet.singleflight.follower_hits")
                                if wait_span is not None:
                                    wait_span.set(outcome="follower_hit")
                                return value
                        if reaped:
                            stats.increment("fleet.singleflight.takeovers")
                            _EVT_TAKEOVER.emit(key=str(name))
                        stats.increment("fleet.singleflight.leader")
                        if wait_span is not None:
                            wait_span.set(outcome="became_leader")
                        return build()
                    finally:
                        lease.release(token)
                        if on_lease is not None:
                            on_lease(None, None)
                if time.monotonic() >= deadline:
                    # The leader is slow (or its artifact is uncacheable):
                    # build locally. Same cost as a world without dedup.
                    stats.increment("fleet.singleflight.local_fallbacks")
                    if wait_span is not None:
                        wait_span.set(outcome="local_fallback")
                    return build()
                if wait_span is None:
                    wait_span = obs_trace.span(
                        "fleet.singleflight.wait", key=str(name)
                    ).__enter__()
                if leader_id is None:
                    leader_id = lease.holder_note()
                    if leader_id:
                        wait_span.set(leader_trace_id=leader_id)
                time.sleep(_POLL_S)
        finally:
            if wait_span is not None:
                wait_span.__exit__(None, None, None)
