"""Disk-backed shared plan/result cache: one cache, N serving processes.

The in-process serve caches (serve/plan_cache.py, serve/result_cache.py)
already solved invalidation the only way that scales to a fleet:
**versioned keys** — plan signature × source-file fingerprint ×
per-index latest-log-id stamp × quarantine snapshot × enablement. This
module reuses those exact keys for entries that live on SHARED DISK, so
the guarantee crosses process boundaries for free: when any process
commits an index mutation (refresh/optimize/create/...), the log id it
bumps is part of every other process's lookup key — the pre-mutation
entries are not flushed, they become *unreachable* in every process at
once. There is no invalidation message to broadcast and no window in
which process B can serve what process A made stale.

Mechanics (PAPER.md L3's `CachingIndexCollectionManager` is the
single-host ancestor of this: many sessions, one catalog):

- **Entries are content-addressed files**: ``md5(repr(key))`` names the
  entry, results as Arrow IPC files (read back zero-copy via
  ``pa.memory_map`` — N processes share one page-cache copy), optimized
  plans as canonical JSON (`plan_from_json` round-trips them).
- **Atomic publication**: write to a same-directory temp file, fsync,
  ``os.replace`` — a reader sees a whole entry or no entry, never a torn
  one (the metadata plane's write_json discipline).
- **Byte-budgeted eviction under a cross-process file lease**
  (fleet/lease.py): whichever process notices the budget exceeded takes
  the eviction lease and removes oldest-mtime entries; the lease keeps
  two processes from racing the scan, and a crashed evictor's lease is
  reaped after its TTL.
- **Advisory by contract**: every IO failure is counted
  (`fleet.shared_cache.errors`) and answered with a miss — a broken
  shared cache degrades the fleet to per-process work, never to a
  failed query.
"""

from __future__ import annotations

import json
import os
import tempfile
import time
from pathlib import Path

from hyperspace_tpu import stats
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.faults import fault_point
from hyperspace_tpu.serve.fleet.lease import FileLease
from hyperspace_tpu.serve.fleet.singleflight import SingleFlight, key_name
from hyperspace_tpu.serve.plan_cache import versioned_plan_key

EVICT_LEASE_NAME = "evict.lease"


class _SharedCacheBase:
    """Directory + budget + lease-held eviction, shared by both caches."""

    suffix = ".bin"

    def __init__(self, root: str | Path, max_bytes: int, lease_ttl_s: float = 10.0):
        self.root = Path(root)
        self.max_bytes = int(max_bytes)
        self.lease_ttl_s = float(lease_ttl_s)
        self.root.mkdir(parents=True, exist_ok=True)

    def entry_path(self, key: tuple) -> Path:
        return self.root / f"{key_name(key)}{self.suffix}"

    def _publish(self, path: Path, data: bytes) -> None:
        """Atomic same-directory publish; the entry appears whole or not
        at all. Raises OSError to the (advisory) caller."""
        fault_point("fleet.cache.write", path)
        fd, tmp = tempfile.mkstemp(dir=path.parent, prefix=".tmp-")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def _entries(self) -> list[tuple[float, int, Path]]:
        """(mtime, size, path) of every resident entry, oldest first."""
        out = []
        try:
            for p in self.root.iterdir():
                if p.suffix != self.suffix:
                    continue
                st = p.stat()
                out.append((st.st_mtime, st.st_size, p))
        except OSError:
            return []
        out.sort()
        return out

    def _maybe_evict(self) -> int:
        """Evict oldest entries past the byte budget, under the
        cross-process eviction lease. Advisory: lease contention or IO
        failure just leaves eviction to the next put. Returns the number
        of entries removed."""
        entries = self._entries()
        total = sum(size for _, size, _ in entries)
        if total <= self.max_bytes:
            return 0
        lease = FileLease(self.root / EVICT_LEASE_NAME, self.lease_ttl_s)
        claim = lease.try_acquire()
        if claim is None:
            return 0  # another process is already evicting
        token, _ = claim
        evicted = 0
        try:
            for _, size, path in entries:
                if total <= self.max_bytes:
                    break
                try:
                    fault_point("fleet.cache.evict", path)
                    os.unlink(path)
                except OSError:
                    stats.increment("fleet.shared_cache.errors")
                    continue
                total -= size
                evicted += 1
        finally:
            lease.release(token)
        if evicted:
            stats.increment("fleet.shared_cache.evictions", evicted)
        return evicted

    def stats(self) -> dict:
        entries = self._entries()
        return {"entries": len(entries), "bytes": sum(s for _, s, _ in entries)}

    def clear(self) -> None:
        for _, _, p in self._entries():
            try:
                os.unlink(p)
            except OSError:
                pass


class SharedResultCache(_SharedCacheBase):
    """Whole-result cache on shared disk: ColumnTables as Arrow IPC
    files under versioned plan keys. Drop-in for the in-process
    `ResultCache` in `QueryServer` (same key/get/put surface); with a
    `SingleFlight`, a fleet-wide cold miss executes ONCE (the scheduler
    wires `single_flight` through `_execute`)."""

    suffix = ".arrow"

    def __init__(
        self,
        root: str | Path,
        max_bytes: int = 1 << 30,
        lease_ttl_s: float = 10.0,
        single_flight: SingleFlight | None = None,
    ):
        super().__init__(root, max_bytes, lease_ttl_s)
        self.single_flight = single_flight

    def key(self, session, plan) -> tuple:
        return versioned_plan_key(session, plan)

    def get(self, key: tuple, count_miss: bool = True):
        """The cached ColumnTable for `key`, or None. mmap-backed read:
        the IPC payload stays in the shared page cache, so N processes
        hitting one entry share one resident copy."""
        import pyarrow as pa

        from hyperspace_tpu.execution.table import ColumnTable

        path = self.entry_path(key)
        try:
            fault_point("fleet.cache.read", path)
            if not path.exists():
                if count_miss:
                    stats.increment("fleet.shared_cache.misses")
                return None
            with pa.memory_map(str(path), "r") as source:
                arrow = pa.ipc.open_file(source).read_all()
            out = ColumnTable.from_arrow(arrow)
            os.utime(path)  # LRU touch for the mtime-ordered eviction
        except (OSError, pa.ArrowException, HyperspaceError, ValueError, KeyError):
            # Advisory: a torn/alien/unreadable entry is a miss, never a
            # failed query — the caller recomputes (and re-publishes).
            stats.increment("fleet.shared_cache.errors")
            return None
        stats.increment("fleet.shared_cache.hits")
        return out

    def peek(self, key: tuple):
        """`get` without miss accounting — the single-flight follower's
        poll (one poll loop would otherwise record hundreds of misses
        for one logical lookup)."""
        return self.get(key, count_miss=False)

    def put(self, key: tuple, table) -> bool:
        """Publish `table` under `key`; False when it was too large
        (over a quarter of the budget), already present, or the publish
        failed (advisory)."""
        import pyarrow as pa

        path = self.entry_path(key)
        try:
            arrow = table.to_arrow()
            if int(arrow.nbytes) > self.max_bytes // 4:
                return False
            if path.exists():
                return False  # same versioned key ⇒ same content
            import io as _io

            buf = _io.BytesIO()
            with pa.ipc.new_file(buf, arrow.schema) as writer:
                writer.write(arrow)
            self._publish(path, buf.getvalue())
        except (OSError, pa.ArrowException):
            stats.increment("fleet.shared_cache.errors")
            return False
        self._maybe_evict()
        return True


class SharedPlanCache(_SharedCacheBase):
    """Optimized-plan cache on shared disk: canonical plan JSON under
    versioned plan keys. Drop-in for the in-process `PlanCache` (same
    `get_or_optimize` surface); cold optimizes are single-flighted
    across the fleet when a `SingleFlight` is attached."""

    suffix = ".json"

    def __init__(
        self,
        root: str | Path,
        max_bytes: int = 64 << 20,
        lease_ttl_s: float = 10.0,
        single_flight: SingleFlight | None = None,
    ):
        super().__init__(root, max_bytes, lease_ttl_s)
        self.single_flight = single_flight

    def get_or_optimize(self, session, plan, snapshot=None):
        key = versioned_plan_key(session, plan, snapshot=snapshot)
        path = self.entry_path(key)
        cached = self._read(path)
        if cached is not None:
            stats.increment("fleet.shared_cache.hits")
            return cached
        stats.increment("fleet.shared_cache.misses")
        if self.single_flight is not None:
            return self.single_flight.run(
                f"plan-{key_name(key)}",
                build=lambda: self._optimize_and_publish(session, plan, path, snapshot),
                check=lambda: self._read(path),
            )
        return self._optimize_and_publish(session, plan, path, snapshot)

    def _read(self, path: Path):
        from hyperspace_tpu.plan.nodes import plan_from_json

        try:
            fault_point("fleet.cache.read", path)
            if not path.exists():
                return None
            with open(path, "rb") as f:
                doc = json.loads(f.read())
            out = plan_from_json(doc)
            os.utime(path)
        except (OSError, ValueError, KeyError):
            stats.increment("fleet.shared_cache.errors")
            return None
        return out

    def _optimize_and_publish(self, session, plan, path: Path, snapshot=None):
        optimized = session.optimized_plan(plan, snapshot=snapshot)
        try:
            self._publish(path, json.dumps(optimized.to_json(), sort_keys=True).encode())
        except OSError:
            stats.increment("fleet.shared_cache.errors")
        else:
            self._maybe_evict()
        return optimized


def warm_age_s(path: Path) -> float:
    """Seconds since an entry was last touched (tests/tools)."""
    return time.time() - path.stat().st_mtime  # noqa: HSL007 — cross-process mtime age
