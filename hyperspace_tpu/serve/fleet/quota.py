"""Per-tenant admission quotas: token buckets in front of the scheduler.

The admission queue (serve/scheduler.py) bounds TOTAL work; it cannot
stop one noisy tenant from filling the whole queue and starving
everyone else. This module adds the per-tenant dimension: each tenant
id gets a token bucket (`ratePerSecond` refill, `burst` capacity), and
a submit whose bucket is dry is refused with a typed
:class:`~hyperspace_tpu.exceptions.QuotaExceeded` — an
`AdmissionRejected` subclass carrying `retry_after_s`, the earliest
moment a token will exist again — BEFORE the query costs a queue slot
or worker time. Layered under the scheduler's priority lane: quota
admission runs first, then depth shedding, then the hard depth limit
(docs/serving.md "fleet topology").

Deterministic by construction: the bucket math uses an injectable
monotonic clock, so tests drive time explicitly. Buckets are created
lazily per tenant and the map is bounded (LRU past `max_tenants` — a
tenant idle long enough to be evicted restarts with a full bucket,
which only ever errs in the tenant's favor).
"""

from __future__ import annotations

import threading
import time

from hyperspace_tpu.exceptions import QuotaExceeded
from hyperspace_tpu.obs import events as obs_events
from hyperspace_tpu.obs import metrics as obs_metrics

_EVT_QUOTA = obs_events.declare("serve.quota_rejected")
_QUOTA_REJECTED = obs_metrics.counter(
    "serve.quota.rejected", "submits refused by a tenant's token bucket"
)


class TokenBucket:
    """One tenant's bucket: `rate` tokens/second refill up to `burst`.
    Not self-locking — the owning :class:`TenantQuotas` serializes."""

    __slots__ = ("rate", "burst", "tokens", "_t_last")

    def __init__(self, rate: float, burst: float, now: float):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self._t_last = now

    def try_take(self, now: float, scale: float = 1.0) -> float:
        """Take one token. Returns 0.0 on success, else the seconds
        until one will be available (the retry-after hint). `scale`
        multiplies the refill rate for this refill window — the ops
        controller's fleet-wide throttle (serve/controller.py)."""
        rate = self.rate * scale
        self.tokens = min(self.burst, self.tokens + (now - self._t_last) * rate)
        self._t_last = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return 0.0
        if rate <= 0:
            return float("inf")
        return (1.0 - self.tokens) / rate


class TenantQuotas:
    """Tenant id -> token bucket, with per-tenant limit overrides."""

    def __init__(
        self,
        rate: float = 100.0,
        burst: float = 200.0,
        clock=time.monotonic,
        max_tenants: int = 4096,
    ):
        self.default_rate = float(rate)
        self.default_burst = float(burst)
        self.max_tenants = int(max_tenants)
        self._clock = clock
        self._lock = threading.Lock()
        self._buckets: dict[str, TokenBucket] = {}
        self._limits: dict[str, tuple[float, float]] = {}
        # Multiplier on every bucket's refill rate (1.0 = rated). The
        # ops controller tightens it while serve SLOs page and restores
        # it on recovery (docs/fault_tolerance.md "self-driving
        # operations"); per-tenant limits and burst stay untouched.
        self._throttle = 1.0

    def set_throttle(self, factor: float) -> None:
        """Scale every tenant's refill rate by `factor` (0 < factor;
        1.0 restores the rated quotas)."""
        with self._lock:
            self._throttle = max(0.0, float(factor))

    def throttle(self) -> float:
        with self._lock:
            return self._throttle

    def set_limit(self, tenant: str, rate: float, burst: float | None = None) -> None:
        """Override one tenant's rate/burst; takes effect on its next
        bucket refill (an existing bucket is rebuilt)."""
        with self._lock:
            self._limits[tenant] = (float(rate), float(burst if burst is not None else rate * 2))
            self._buckets.pop(tenant, None)

    def admit(self, tenant: str) -> None:
        """Take one token for `tenant` or raise :class:`QuotaExceeded`
        (with `retry_after_s`). Tenants are strings — opaque ids minted
        by whatever fronts the fleet."""
        tenant = str(tenant)
        with self._lock:
            bucket = self._buckets.get(tenant)
            if bucket is None:
                rate, burst = self._limits.get(
                    tenant, (self.default_rate, self.default_burst)
                )
                bucket = TokenBucket(rate, burst, self._clock())
                self._buckets[tenant] = bucket
                while len(self._buckets) > self.max_tenants:
                    self._buckets.pop(next(iter(self._buckets)))
            else:
                self._buckets[tenant] = self._buckets.pop(tenant)  # LRU touch
            wait_s = bucket.try_take(self._clock(), scale=self._throttle)
        if wait_s > 0.0:
            _QUOTA_REJECTED.inc()
            _EVT_QUOTA.emit(tenant=tenant, retry_after_s=wait_s)
            raise QuotaExceeded(
                f"tenant {tenant!r} admission quota exhausted "
                f"(retry after {wait_s:.3f}s)",
                tenant=tenant,
                retry_after_s=wait_s,
            )

    def snapshot(self) -> dict:
        """Point-in-time {tenant: remaining tokens} (healthz/tests)."""
        with self._lock:
            return {t: b.tokens for t, b in self._buckets.items()}
