"""Cross-process file leases: the fleet's only coordination primitive.

A lease is one file whose content is ``<epoch>:<pid>:<uuid>`` — created
with ``O_CREAT|O_EXCL`` (exactly one winner per claim, POSIX-atomic on
every filesystem the metadata plane already trusts) and judged stale by
the CREATOR-written epoch, never by filesystem mtime (network
filesystems stamp mtime with the server's clock). This generalizes the
lock-file fallback `utils/file_utils.py` grew for no-hardlink
filesystems into a reusable primitive for the fleet's single-flight and
eviction protocols (serve/fleet/).

The load-bearing property is **crash safety**: a holder that is
SIGKILLed mid-build leaves its lease file behind, and the next claimant
reaps it once the epoch is older than the TTL — so a dead process can
never wedge the fleet; at worst it delays one build by the TTL. The
reap itself is atomic (rename to a unique claim name, exactly one
reaper wins) and verified: if the content under the rename turns out to
belong to a NEWER (live) lease, its token is reinstalled and the reap
reports failure. Single-winner correctness therefore assumes the
standard lease-lock bounds: inter-process clock skew and holder pauses
below the TTL.
"""

from __future__ import annotations

import os
import time
import uuid
from pathlib import Path

from hyperspace_tpu.faults import fault_point


def _read_text(p: Path) -> str | None:
    try:
        with open(p, "r") as f:
            return f.read()
    except OSError:
        return None


def _token_epoch(text: str | None) -> float | None:
    if not text or ":" not in text:
        return None
    try:
        return float(text.split(":", 1)[0])
    except ValueError:
        return None


class FileLease:
    """One named lease file with a TTL. `try_acquire` returns the token
    on success (pass it back to `release`), None when a live contender
    holds the lease. Stateless between calls — any process (including a
    freshly restarted one) can operate on the same path."""

    def __init__(self, path: str | os.PathLike, ttl_s: float):
        self.path = Path(path)
        self.ttl_s = float(ttl_s)

    def holder(self) -> str | None:
        """The current lease token, or None when unheld/unreadable."""
        return _read_text(self.path)

    def holder_note(self) -> str | None:
        """The holder's optional annotation (fourth token field) — the
        single-flight leader stamps its root trace id here so followers
        can link their wait span to the leader's trace. None on legacy
        three-field tokens or when unheld."""
        text = _read_text(self.path)
        if not text:
            return None
        parts = text.split(":", 3)
        return parts[3] or None if len(parts) == 4 else None

    def try_acquire(self, note: str | None = None) -> tuple[str, bool] | None:
        """Claim the lease. Returns ``(token, reaped)`` on success —
        `reaped` is True when the claim displaced a stale (crashed)
        holder — or None while a live contender holds it. `note` is an
        optional annotation carried as a fourth token field (readable
        via `holder_note`); epoch parsing ignores it, so three- and
        four-field tokens coexist on one lease path."""
        fault_point("fleet.lease.acquire", self.path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        # Wall clock on purpose: the epoch must be comparable across
        # processes and survive the writer (monotonic clocks are
        # per-boot, not per-file).
        token = f"{time.time():.6f}:{os.getpid()}:{uuid.uuid4().hex}"  # noqa: HSL007
        if note:
            # One line, colon-delimited: strip both from the annotation.
            token += ":" + "".join(c for c in str(note) if c not in ":\n\r")[:128]
        reaped = False
        for attempt in range(3):
            try:
                fd = os.open(self.path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                if not self._reap(f"{os.getpid()}-{uuid.uuid4().hex[:8]}-{attempt}"):
                    return None
                reaped = True
                continue
            except OSError:
                return None
            try:
                with os.fdopen(fd, "w") as f:
                    f.write(token)
                    f.flush()
                    os.fsync(f.fileno())
            except OSError:  # noqa: HSL017 — not a retry: an unwritten
                # token fails the verification below and the claim is
                # reported lost in-band
                pass
            if _read_text(self.path) != token:
                return None  # torn write / concurrent steal — claim lost
            return token, reaped
        return None

    def release(self, token: str) -> None:
        """Drop the lease if (and only if) this token still holds it — a
        lease that was reaped from us while we were paused belongs to
        its new holder and must not be unlinked."""
        if _read_text(self.path) == token:
            try:
                os.unlink(self.path)
            except OSError:
                pass  # leftover lease is reaped by the next claimant

    def _reap(self, nonce: str) -> bool:
        """Clear the lease if its epoch is stale. True ⇒ cleared (retry
        the acquire); False ⇒ a live holder keeps it."""
        text = _read_text(self.path)
        if text is None:
            return True  # vanished underneath us — retry the acquire
        ep = _token_epoch(text)
        if ep is None:
            # Token missing/torn: the holder may sit BETWEEN its O_EXCL
            # create and its token write — judge by file age (the one
            # case where mtime is consulted) so a live-but-unwritten
            # lease is not reaped.
            try:
                if time.time() - os.stat(self.path).st_mtime <= self.ttl_s:  # noqa: HSL007
                    return False
            except OSError:
                return True
        elif time.time() - ep <= self.ttl_s:  # noqa: HSL007 — persisted epoch token
            return False
        claimed = self.path.with_name(f"{self.path.name}.reap-{nonce}")
        try:
            os.rename(self.path, claimed)
        except OSError:
            return False  # another reaper won
        stolen = _read_text(claimed)
        try:
            os.unlink(claimed)
        except OSError:
            pass
        if stolen != text:
            # Between our read and the rename the stale lease was
            # replaced by a NEW (live) instance — reinstall its token so
            # later claimants still see a held lease.
            try:
                fd = os.open(self.path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                try:
                    os.write(fd, (stolen or "").encode())
                finally:
                    os.close(fd)
            except OSError:
                pass
            return False
        return True
