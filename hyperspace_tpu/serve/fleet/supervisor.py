"""Fleet supervisor: spawn, monitor, restart, and drain worker processes.

One :class:`FleetSupervisor` owns N worker *processes* (the "millions of
users" topology, ROADMAP item 1): each worker runs its own
`HyperspaceSession` + `QueryServer` over the SAME index store, shares
the fleet's on-disk plan/result cache (fleet/shared_cache.py), and —
with ``hyperspace.obs.http.enabled`` — binds its own ephemeral health
port (obs/http.py `port=0`) and registers it in the fleet directory so
the supervisor (or a load balancer's service discovery) can find every
member's `/metrics` and `/healthz`.

The supervisor's contract:

- **spawn**: workers start via the shared spawn-context lifecycle in
  `parallel/procpool.py` (:class:`~hyperspace_tpu.parallel.procpool.ProcessHost`
  — a fork of a jax-initialized parent is never safe; the scale-out
  build's TaskPool rides the same primitive); the target is called as
  ``target(ctx, *args)`` with a :class:`WorkerContext` carrying the
  worker id, the fleet directory, and the shared stop event.
- **monitor/restart**: a daemon thread watches liveness; a worker that
  dies with a non-zero exit (including a SIGKILL's negative exitcode)
  is respawned until its restart budget (``hyperspace.fleet.maxRestarts``)
  is spent — each respawn counted in `fleet.supervisor.restarts` and
  announced as a WARN ``fleet.worker.restarted`` event. Workers that
  exit 0 are considered done and stay down. The FIRST respawn of a
  member is immediate; repeat crashes of the SAME member back off
  exponentially (``hyperspace.fleet.restartBackoffSeconds`` base,
  deterministic per-member jitter, capped) so a crash-looping worker
  cannot burn its whole budget in milliseconds — the moment backoff
  engages, a WARN ``fleet.worker.crash_loop`` event names the member.
- **scale**: `set_target_workers(n)` grows or shrinks the member count
  live — the OpsController's fleet actuator (scale up on sustained
  fleet-health saturation, back down on recovery). New members spawn
  through the same env-shipping path as start(); drained members are
  terminated, deregistered, and never respawned. Every change counts
  (`fleet.worker.scaled`), emits an INFO ``fleet.worker.scaled`` event,
  and moves the ``fleet.target_workers`` gauge.
- **drain/stop**: `stop()` sets the shared stop event (workers exit
  their serve loops, QueryServers drain) and joins with a timeout;
  stragglers are terminated. The supervisor is a context manager.
- **fleet health**: `fleet_health()` scrapes every registered member's
  `/healthz` and aggregates scheduler saturation (summed workers /
  inflight / queue depth) plus the worst member status — the fleet-wide
  overload signal a balancer consumes.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from pathlib import Path

from hyperspace_tpu import faults, stats
from hyperspace_tpu.obs import events as obs_events
from hyperspace_tpu.obs import metrics as obs_metrics
from hyperspace_tpu.obs import trace as obs_trace
from hyperspace_tpu.parallel.procpool import ProcessHost
from hyperspace_tpu.utils import file_utils

_EVT_RESTARTED = obs_events.declare("fleet.worker.restarted")
_EVT_CRASH_LOOP = obs_events.declare("fleet.worker.crash_loop")
_EVT_SCALED = obs_events.declare("fleet.worker.scaled")

_TARGET_WORKERS = obs_metrics.gauge(
    "fleet.target_workers", "the supervisor's current target member count"
)

_MONITOR_POLL_S = 0.1
_HEALTH_TIMEOUT_S = 5.0
_BACKOFF_CAP_S = 30.0


def _restart_jitter(worker_id: int, attempt: int) -> float:
    """Deterministic jitter fraction in [0, 0.25): spreads simultaneous
    crash-loop respawns without RNG (the faults-harness determinism
    contract extends to the supervisor's timing decisions)."""
    return ((worker_id * 2654435761 + attempt * 40503) % 1000) / 4000.0

WORKERS_DIRNAME = "workers"


@dataclasses.dataclass
class WorkerContext:
    """What a worker target receives: its identity, the fleet's shared
    directory, and the supervisor's stop event (a multiprocessing.Event
    — poll `ctx.stop_event.is_set()` in the serve loop)."""

    worker_id: int
    fleet_dir: str
    stop_event: object


def register_worker(
    fleet_dir: str | os.PathLike, worker_id: int, port: int | None, host: str = "127.0.0.1"
) -> None:
    """Publish this worker's pid + bound health port into the fleet dir
    (atomic write_json) — how ephemeral `port=0` bindings become
    discoverable (obs/http.py)."""
    path = Path(fleet_dir) / WORKERS_DIRNAME / f"{int(worker_id)}.json"
    # Wall clock on purpose: the heartbeat must be comparable from OTHER
    # processes (the supervisor's last-heartbeat ages in /healthz).
    file_utils.write_json(
        path,
        {"pid": os.getpid(), "port": port, "host": host, "ts": time.time()},  # noqa: HSL007
    )


def read_workers(fleet_dir: str | os.PathLike) -> dict[int, dict]:
    """Every registered worker's {pid, port, host}, by worker id."""
    root = Path(fleet_dir) / WORKERS_DIRNAME
    out: dict[int, dict] = {}
    try:
        entries = sorted(root.glob("*.json"))
    except OSError:
        return out
    for p in entries:
        try:
            out[int(p.stem)] = file_utils.read_json(p)
        except (OSError, ValueError):
            continue  # torn registration: the worker re-publishes
    return out


def _worker_entry(target, worker_id: int, fleet_dir: str, stop_event, args: tuple,
                  env: dict | None = None) -> None:
    """Module-level shim (spawn needs a picklable top-level callable).

    Cross-boundary continuity (HSL022, the TaskPool `_task_entry`
    contract): the coordinator's registered fault rules and tracer
    enablement ship in via `env` and are installed before the worker
    main runs, so a deterministic fault schedule reaches long-lived
    fleet members exactly like pooled build workers. Service workers
    have no result envelope to merge observations back through — their
    telemetry flows out via the per-worker health plane (/metrics,
    /healthz) instead.
    """
    env = env or {}
    fstate = env.get("faults")
    if fstate is not None:
        faults.install_state(fstate)
    obs_trace.set_enabled(bool(env.get("obs_enabled", True)))
    jstate = env.get("journal")
    if jstate is not None:
        from hyperspace_tpu.obs import journal as obs_journal

        obs_journal.install_state(dict(jstate, worker_id=worker_id))
    target(WorkerContext(worker_id, fleet_dir, stop_event), *args)


def _scrape_json(host: str, port: int, path: str, timeout: float = _HEALTH_TIMEOUT_S) -> dict | None:
    """GET a JSON document from a member's health endpoint; None when
    unreachable. A 503 (SLO page) still carries the healthz body —
    read it from the HTTPError."""
    import urllib.error
    import urllib.request

    url = f"http://{host}:{port}{path}"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        try:
            return json.loads(e.read().decode())
        except (OSError, ValueError):
            return None
    except (OSError, ValueError):
        return None


def _scrape_text(host: str, port: int, path: str, timeout: float = _HEALTH_TIMEOUT_S) -> str | None:
    import urllib.request

    try:
        with urllib.request.urlopen(f"http://{host}:{port}{path}", timeout=timeout) as r:
            return r.read().decode()
    except (OSError, ValueError):
        return None


class FleetSupervisor:
    """Spawn and babysit N worker processes over one index store."""

    def __init__(
        self,
        target,
        fleet_dir: str | os.PathLike,
        n: int | None = None,
        args: tuple = (),
        max_restarts: int | None = None,
        restart_backoff: float | None = None,
        conf=None,
    ):
        n = int(n if n is not None else getattr(conf, "fleet_workers", 2))
        self._target = target
        self.fleet_dir = str(fleet_dir)
        self.n = n
        self._args = tuple(args)
        self.max_restarts = int(
            max_restarts if max_restarts is not None else getattr(conf, "fleet_max_restarts", 3)
        )
        self.restart_backoff = float(
            restart_backoff if restart_backoff is not None
            else getattr(conf, "fleet_restart_backoff_seconds", 0.5)
        )
        # The shared spawn-context worker lifecycle (parallel/procpool.py):
        # the host owns the spawn context, the stop event, and the keyed
        # process registry; the supervisor layers fleet policy (restart
        # budgets, health aggregation) on top.
        self._host = ProcessHost(name="hs-fleet")
        self._stop = self._host.stop_event
        self._lock = threading.Lock()
        self._restarts: dict[int, int] = {}
        # Per-member earliest-next-respawn deadlines (monotonic clock):
        # the crash-loop backoff state, entries live only while a
        # delayed respawn is pending.
        self._restart_at: dict[int, float] = {}
        self._monitor_thread: threading.Thread | None = None
        self._stopping = False
        # Last wall-clock instant each member proved life: a successful
        # /healthz scrape, or its registration heartbeat — whichever is
        # newer. Read (not scraped) by `fleet_summary` so /healthz can
        # show a silently dead member's age between poll ticks.
        self._last_seen: dict[int, float] = {}

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "FleetSupervisor":
        Path(self.fleet_dir, WORKERS_DIRNAME).mkdir(parents=True, exist_ok=True)
        with self._lock:
            for wid in range(self.n):
                self._spawn(wid)
            _TARGET_WORKERS.set(self.n)
            self._monitor_thread = threading.Thread(
                target=self._monitor, name="hs-fleet-monitor", daemon=True
            )
            self._monitor_thread.start()
        return self

    def set_target_workers(self, n: int, min_workers: int = 1) -> int:
        """Scale the fleet to `n` members (the OpsController's fleet
        actuator). Up: fresh ids spawn through the same env-shipping
        path as start(), so the coordinator's fault rules and tracer
        state reach the new members. Down: the highest ids are
        terminated, their registration JSON and restart state dropped,
        so `fleet_health` stops counting them. Clamped to at least
        `min_workers`; returns the applied target. Idempotent — a no-op
        change emits nothing."""
        n = max(int(min_workers), int(n))
        with self._lock:
            if self._stopping:
                return self.n
            old = self.n
            if n == old:
                return old
            to_drain = list(range(n, old))
            for wid in range(old, n):
                # A re-grown slot starts with a fresh restart budget —
                # its crash history belonged to the drained member.
                self._restarts.pop(wid, None)
                self._restart_at.pop(wid, None)
                self._spawn(wid)
            # Publish the new target BEFORE draining: the monitor skips
            # wid >= self.n, so a drained member that exits non-zero in
            # the termination window cannot be respawned.
            self.n = n
        for wid in to_drain:
            self._host.terminate(wid, grace=5.0)
            with self._lock:
                self._restarts.pop(wid, None)
                self._restart_at.pop(wid, None)
            try:
                (Path(self.fleet_dir) / WORKERS_DIRNAME / f"{wid}.json").unlink()
            except OSError:
                pass
        stats.increment("fleet.worker.scaled", abs(n - old))
        _EVT_SCALED.emit(from_workers=old, to_workers=n)
        _TARGET_WORKERS.set(n)
        return n

    def _spawn(self, worker_id: int):
        from hyperspace_tpu.obs import journal as obs_journal

        env = {
            "faults": faults.export_state(),
            "obs_enabled": obs_trace.enabled(),
            "journal": obs_journal.export_state(),
        }
        return self._host.spawn(
            worker_id,
            _worker_entry,
            args=(self._target, worker_id, self.fleet_dir, self._stop, self._args, env),
            name=f"hs-fleet-{worker_id}",
        )

    def _monitor(self) -> None:
        """Respawn crashed members until their restart budget is spent.
        exit 0 = completed (left down); any other exit, including a
        SIGKILL's negative code, = crash. A member crashing AGAIN backs
        off exponentially before its next respawn (first respawn is
        immediate), so a crash-looping worker spends its budget over
        seconds — observable, WARN-announced — not milliseconds."""
        while True:
            with self._lock:
                if self._stopping:
                    return
                now = time.monotonic()
                dead = [
                    (wid, p) for wid, p in self._host.processes().items()
                    if not p.is_alive() and p.exitcode not in (0, None)
                ]
                for wid, p in dead:
                    if isinstance(wid, int) and wid >= self.n:
                        continue  # scaled-down slot: stays down by design
                    used = self._restarts.get(wid, 0)
                    if used >= self.max_restarts:
                        continue
                    if used > 0 and self.restart_backoff > 0:
                        deadline = self._restart_at.get(wid)
                        if deadline is None:
                            delay = min(
                                self.restart_backoff * (2 ** (used - 1)),
                                _BACKOFF_CAP_S,
                            ) * (1.0 + _restart_jitter(wid, used))
                            self._restart_at[wid] = now + delay
                            _EVT_CRASH_LOOP.emit(
                                worker_id=wid, exitcode=p.exitcode,
                                restarts_used=used, delay_s=round(delay, 3),
                            )
                            continue
                        if now < deadline:
                            continue
                    self._restart_at.pop(wid, None)
                    self._restarts[wid] = used + 1
                    self._spawn(wid)
                    stats.increment("fleet.supervisor.restarts")
                    _EVT_RESTARTED.emit(
                        worker_id=wid, exitcode=p.exitcode, restarts=used + 1
                    )
            time.sleep(_MONITOR_POLL_S)

    def stop(self, timeout: float = 30.0) -> None:
        """Graceful drain: signal every worker's stop event, join, then
        terminate stragglers (ProcessHost.stop). Idempotent."""
        with self._lock:
            self._stopping = True
            t = self._monitor_thread
        self._host.stop(timeout=timeout, grace=5.0)
        if t is not None:
            t.join(timeout=5.0)

    def __enter__(self) -> "FleetSupervisor":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.stop()
        return False

    # -- views ------------------------------------------------------------
    def alive_count(self) -> int:
        return self._host.alive_count()

    def pids(self) -> dict[int, int | None]:
        return {wid: p.pid for wid, p in self._host.processes().items()}

    def restarts(self) -> dict[int, int]:
        with self._lock:
            return dict(self._restarts)

    def fleet_health(self) -> dict:
        """Aggregate fleet view: every registered member's /healthz plus
        summed scheduler saturation and the worst member status — what a
        load balancer reads to decide where (and whether) to send
        traffic."""
        members: dict[int, dict] = {}
        agg = {"workers": 0, "inflight": 0, "queue_depth": 0, "max_queue_depth": 0}
        rank = {"ok": 0, "degraded": 1, "critical": 2, "unreachable": 2}
        worst = "ok"
        procs = list(self._host.processes().values())
        alive_pids = {p.pid for p in procs if p.is_alive()}
        now = time.time()  # noqa: HSL007 — cross-process heartbeat ages
        for wid, reg in read_workers(self.fleet_dir).items():
            port = reg.get("port")
            doc = None
            if port and reg.get("pid") in alive_pids:
                doc = _scrape_json(reg.get("host", "127.0.0.1"), port, "/healthz")
            status = doc["status"] if doc else "unreachable"
            with self._lock:
                seen = self._last_seen.get(wid)
                reg_ts = reg.get("ts")
                if isinstance(reg_ts, (int, float)):
                    seen = max(seen or 0.0, float(reg_ts))
                if doc is not None:
                    seen = max(seen or 0.0, now)
                if seen is not None:
                    self._last_seen[wid] = seen
            members[wid] = {"pid": reg.get("pid"), "port": port, "status": status,
                            "last_heartbeat_age_s":
                                round(now - seen, 3) if seen else None,
                            "healthz": doc}
            if rank.get(status, 2) > rank.get(worst, 0):
                worst = status
            for sched in (doc or {}).get("scheduler", []):
                for k in agg:
                    agg[k] += int(sched.get(k, 0))
        with self._lock:
            spawned = self.n
        return {"status": worst, "saturation": agg, "members": members,
                "alive": self.alive_count(), "spawned": spawned}

    def fleet_summary(self) -> dict:
        """Cheap fleet view for /healthz: member pids/ports and per-member
        last-heartbeat age WITHOUT scraping anyone (reads the fleet dir's
        registrations and the liveness the supervisor already tracks) —
        a silently dead member shows a growing age here between
        `fleet_health` poll ticks instead of disappearing."""
        now = time.time()  # noqa: HSL007 — cross-process heartbeat ages
        procs = dict(self._host.processes())
        members: dict[int, dict] = {}
        for wid, reg in read_workers(self.fleet_dir).items():
            with self._lock:
                seen = self._last_seen.get(wid)
            reg_ts = reg.get("ts")
            if isinstance(reg_ts, (int, float)):
                seen = max(seen or 0.0, float(reg_ts))
            p = procs.get(wid)
            members[wid] = {
                "pid": reg.get("pid"),
                "port": reg.get("port"),
                "alive": bool(p.is_alive()) if p is not None else None,
                "last_heartbeat_age_s": round(now - seen, 3) if seen else None,
            }
        with self._lock:
            spawned = self.n
        return {"members": members, "alive": self.alive_count(), "spawned": spawned}

    def aggregate_metrics(self) -> dict[int, str]:
        """Raw Prometheus text per registered live member (a scrape
        federation shim; each page is already namespaced per process by
        its scrape origin)."""
        out: dict[int, str] = {}
        procs = list(self._host.processes().values())
        alive_pids = {p.pid for p in procs if p.is_alive()}
        for wid, reg in read_workers(self.fleet_dir).items():
            port = reg.get("port")
            if not port or reg.get("pid") not in alive_pids:
                continue
            text = _scrape_text(reg.get("host", "127.0.0.1"), port, "/metrics")
            if text is not None:
                out[wid] = text
        return out
