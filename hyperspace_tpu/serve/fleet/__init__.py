"""Multi-process serving fleet (docs/serving.md "fleet topology").

N `QueryServer` processes over ONE index store:

- `shared_cache.py` — disk-backed plan/result cache under the serving
  plane's versioned keys, so any process's index mutation structurally
  invalidates every process's entries;
- `singleflight.py` — lease-file cross-process build dedup (N cold
  processes, one optimize/execute; crashed holders reaped by TTL);
- `quota.py` — per-tenant token-bucket admission + the scheduler's
  queue-depth shedding = graceful saturation (bounded p99, typed
  rejections, never collapse);
- `supervisor.py` — spawn/monitor/restart/drain the worker processes
  and aggregate their `/metrics` + `/healthz`;
- `lease.py` — the crash-safe file-lease primitive under all of it.

The normal wiring is :func:`shared_caches`: build the fleet caches from
a session's config and hand them to ``session.serve(plan_cache=...,
result_cache=...)`` in every worker process.
"""

from __future__ import annotations

from pathlib import Path

from hyperspace_tpu.serve.fleet.lease import FileLease
from hyperspace_tpu.serve.fleet.quota import TenantQuotas, TokenBucket
from hyperspace_tpu.serve.fleet.shared_cache import SharedPlanCache, SharedResultCache
from hyperspace_tpu.serve.fleet.singleflight import SingleFlight
from hyperspace_tpu.serve.fleet.supervisor import (
    FleetSupervisor,
    WorkerContext,
    read_workers,
    register_worker,
)


def fleet_dir(conf) -> Path:
    """The fleet's shared on-disk root for a session config:
    `hyperspace.fleet.cache.dir`, defaulting to `<system.path>/_fleet`
    (underscore-prefixed ⇒ invisible to index listing)."""
    return Path(conf.fleet_cache_dir or Path(conf.system_path) / "_fleet")


def shared_caches(session) -> tuple[SharedPlanCache, SharedResultCache]:
    """The fleet cache pair for `session`, rooted at its fleet dir and
    wired through one SingleFlight — pass straight into
    ``session.serve(plan_cache=..., result_cache=...)``. Every process
    pointing at the same store derives the same paths, which is the
    whole trick."""
    conf = session.conf
    root = fleet_dir(conf)
    sf = SingleFlight(
        root / "sf",
        lease_ttl_s=conf.fleet_lease_seconds,
        wait_s=conf.fleet_singleflight_wait_seconds,
    )
    plans = SharedPlanCache(
        root / "cache" / "plans",
        max_bytes=max(1, conf.fleet_cache_max_bytes // 16),
        lease_ttl_s=conf.fleet_lease_seconds,
        single_flight=sf,
    )
    results = SharedResultCache(
        root / "cache" / "results",
        max_bytes=conf.fleet_cache_max_bytes,
        lease_ttl_s=conf.fleet_lease_seconds,
        single_flight=sf,
    )
    return plans, results


__all__ = [
    "FileLease",
    "FleetSupervisor",
    "SharedPlanCache",
    "SharedResultCache",
    "SingleFlight",
    "TenantQuotas",
    "TokenBucket",
    "WorkerContext",
    "fleet_dir",
    "read_workers",
    "register_worker",
    "shared_caches",
]
