"""Opt-in result cache: identical repeat queries skip execution outright.

Values are whole :class:`~hyperspace_tpu.execution.table.ColumnTable`
results under the same versioned keys as the plan cache
(serve/plan_cache.py): plan signature + source-data fingerprint + index
log versions + quarantine set + enablement. The version stamping is what
makes the "never serve pre-refresh rows" guarantee structural: a refresh
(or any index mutation, or a source append) bumps the stamp, so every
entry cached before it becomes unreachable — there is no epoch counter
to bump and no window where a stale row can hit. tests/test_serve.py
drives a refresh mid-flight to prove it.

Opt-in (`hyperspace.serve.resultCache.enabled`, default false) because
caching results pins host memory per distinct query and only pays off
for workloads with literal repeats. Byte accounting is explicit: entries
are LRU-evicted past `maxBytes`, and a single result larger than a
quarter of the budget is never admitted (it would flush the whole cache
for one query's benefit).

Cached tables are returned by reference to every hit — treat results as
read-only (the decode path already does).
"""

from __future__ import annotations

import threading

from hyperspace_tpu.obs import events as obs_events
from hyperspace_tpu.obs import metrics as obs_metrics
from hyperspace_tpu.serve.plan_cache import versioned_plan_key

# One admission flushing this many resident entries is a storm: either
# the budget is far too small for the workload or one huge result is
# churning the whole cache — worth a structured WARN, not just a
# counter tick (obs/events.py).
EVICTION_STORM_THRESHOLD = 8
_EVT_EVICTION_STORM = obs_events.declare("serve.result_cache.eviction_storm")


def table_nbytes(table) -> int:
    """Resident byte estimate of a ColumnTable — the canonical
    (codes + dictionary payload) accounting from
    execution/device_cache.py. The previous local estimate added a
    ``<U``-dtype dictionary's UTF-32-padded ``.nbytes`` on top of its
    character payload, over-counting dict-coded columns and evicting
    them too eagerly."""
    from hyperspace_tpu.execution.device_cache import table_footprint_bytes

    return table_footprint_bytes(table)


class ResultCache:
    """Bounded LRU of query results keyed by versioned plan key."""

    def __init__(self, max_bytes: int = 256 << 20):
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._entries: dict[tuple, tuple[int, object]] = {}
        self._bytes = 0
        self._hits = obs_metrics.counter("serve.result_cache.hits", "result cache hits")
        self._misses = obs_metrics.counter("serve.result_cache.misses", "result cache misses")
        self._evictions = obs_metrics.counter("serve.result_cache.evictions", "LRU evictions")
        self._gauge_bytes = obs_metrics.gauge("serve.result_cache.bytes", "resident result bytes")

    def key(self, session, plan) -> tuple:
        return versioned_plan_key(session, plan)

    def get(self, key: tuple):
        """The cached result for `key`, or None (counted as hit/miss)."""
        with self._lock:
            hit = self._entries.get(key)
            if hit is not None:
                self._entries[key] = self._entries.pop(key)  # LRU touch
                self._hits.inc()
                return hit[1]
            self._misses.inc()
            return None

    def put(self, key: tuple, table) -> bool:
        """Admit `table` under `key`; False when it is too large to cache
        (more than a quarter of the byte budget) or already present."""
        nb = table_nbytes(table)
        if nb > self.max_bytes // 4:
            return False
        evicted = 0
        with self._lock:
            if key in self._entries:
                return False
            self._entries[key] = (nb, table)
            self._bytes += nb
            while self._bytes > self.max_bytes and len(self._entries) > 1:
                k = next(iter(self._entries))  # oldest = least recently used
                old_nb, _ = self._entries.pop(k)
                self._bytes -= old_nb
                evicted += 1
            self._gauge_bytes.set(self._bytes)
        if evicted:
            self._evictions.inc(evicted)
            if evicted >= EVICTION_STORM_THRESHOLD:
                _EVT_EVICTION_STORM.emit(evicted=evicted, admitted_bytes=nb)
        return True

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0
            self._gauge_bytes.set(0)

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "hits": self._hits.value,
                "misses": self._misses.value,
                "evictions": self._evictions.value,
            }
