"""Concurrent query server: bounded workers + admission control.

The serving plane in front of `HyperspaceSession` (docs/serving.md),
in the spirit of Hyperspace's split between a cheap metadata/serving
plane and the heavy scan plane (PAPER.md §0): N clients submit logical
plans; a fixed worker pool executes them against ONE session; an
admission queue bounds how much work can pile up in front of the
executors. Design points:

- **Admission control at the door.** `submit` rejects with a typed
  :class:`AdmissionRejected` the moment the queue is at
  `hyperspace.serve.maxQueueDepth` — load sheds before it costs queue
  slots or worker time, and the exception carries the observed depth so
  clients can back off.
- **Deterministic FIFO + one priority lane.** Two deques under one
  condition variable: priority tickets always dequeue first, each lane
  strictly in submit order. No timestamps, no heap — dequeue order is a
  pure function of submit order.
- **Per-query timeout.** A ticket whose deadline passes while still
  queued is discarded un-executed (its handle raises
  :class:`QueryTimeout`); `QueryHandle.result()` bounds its wait the
  same way. Running queries are never killed — Python threads can't be —
  so a result()-side timeout means "gave up waiting", not "cancelled".
- **Per-query handles.** Each submit returns a :class:`QueryHandle`
  owning that query's result/error/profile/stats — the serving analog of
  `session.last_profile()`, minus the shared-global race.
- **Graceful drain/shutdown.** `drain()` pauses admission until the
  queue and in-flight work hit zero; `shutdown(wait=False)` cancels
  queued tickets; the server is a context manager.
- **Observability.** Queue-depth/in-flight gauges, admission counters,
  queue-wait and end-to-end latency histograms (`serve.*`,
  docs/observability.md). The submitter's active span is re-planted into
  the worker thread via the existing `trace.wrap`, so a `serve.run` span
  nests under whatever trace submitted the query; a bare submit gets its
  own root trace.

Off by default: nothing constructs a QueryServer unless the caller does
(`session.serve()`), and plain `session.run()` is untouched.
"""

from __future__ import annotations

import collections
import threading
import time

from hyperspace_tpu.exceptions import AdmissionRejected, QueryTimeout
from hyperspace_tpu.obs import events as obs_events
from hyperspace_tpu.obs import metrics as obs_metrics
from hyperspace_tpu.obs import trace as obs_trace
from hyperspace_tpu.serve.plan_cache import PlanCache
from hyperspace_tpu.serve.result_cache import ResultCache

# Declared at import so submit's narrow error contract (AdmissionRejected
# only) stays narrow: Event.emit never raises (obs/events.py).
_EVT_REJECTED = obs_events.declare("serve.admission_rejected")
_EVT_SHED = obs_events.declare("serve.shed")

_ADMITTED = obs_metrics.counter("serve.admitted", "queries accepted into the queue")
_REJECTED = obs_metrics.counter("serve.rejected", "submits refused by admission control")
_SHED = obs_metrics.counter(
    "serve.shed.rejected", "non-priority submits shed at the saturation threshold"
)
_TIMEOUTS = obs_metrics.counter("serve.timeouts", "queries expired before/while executing")
_COMPLETED = obs_metrics.counter("serve.completed", "queries finished successfully")
_FAILED = obs_metrics.counter("serve.failed", "queries finished with an error")
_CANCELLED = obs_metrics.counter("serve.cancelled", "queued queries dropped by shutdown")
_QUEUE_DEPTH = obs_metrics.gauge("serve.queue.depth", "tickets waiting for a worker")
_INFLIGHT = obs_metrics.gauge("serve.inflight", "queries currently executing")
_QUEUE_WAIT = obs_metrics.histogram(
    "serve.queue.seconds", "submit -> dequeue wait", buckets=obs_metrics.SECONDS_BUCKETS
)
_LATENCY = obs_metrics.histogram(
    "serve.latency.seconds", "submit -> completion end-to-end", buckets=obs_metrics.SECONDS_BUCKETS
)


class QueryHandle:
    """One submitted query's state: wait on it, then read the result (or
    the typed error), the per-query profile, and the executor stats —
    no shared session globals involved."""

    __slots__ = (
        "_done", "_result", "error", "profile", "stats",
        "timeout_s", "submitted_s", "timed_out", "cancelled", "cache_hit",
    )

    def __init__(self, timeout_s: float):
        self._done = threading.Event()
        self._result = None
        self.error: BaseException | None = None
        self.profile = None
        self.stats: dict | None = None
        self.timeout_s = float(timeout_s)
        self.submitted_s = time.perf_counter()
        self.timed_out = False
        self.cancelled = False
        self.cache_hit = False

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: float | None = None):
        """Block for the result. `timeout` (seconds) overrides the
        query's own timeout; with neither, waits forever. Raises
        :class:`QueryTimeout` when the wait expires (the query may still
        complete later — inspect `done()`), or the query's stored error."""
        budget = timeout if timeout is not None else (self.timeout_s or None)
        if not self._done.wait(budget):
            elapsed = time.perf_counter() - self.submitted_s
            raise QueryTimeout(
                f"query still running after {elapsed:.3f}s (wait budget {budget:.3f}s)",
                elapsed_s=elapsed,
            )
        if self.error is not None:
            raise self.error
        return self._result


class _Ticket:
    __slots__ = ("plan", "handle", "run", "priority", "enqueued_s", "deadline_s")

    def __init__(self, plan, handle: QueryHandle, priority: bool):
        self.plan = plan
        self.handle = handle
        self.run = None  # set at submit: trace.wrap'd execution body
        self.priority = bool(priority)
        self.enqueued_s = time.perf_counter()
        self.deadline_s = (
            self.enqueued_s + handle.timeout_s if handle.timeout_s > 0 else None
        )


class QueryServer:
    """Bounded concurrent query execution over one HyperspaceSession."""

    def __init__(
        self,
        session,
        workers: int | None = None,
        max_queue_depth: int | None = None,
        timeout_seconds: float | None = None,
        plan_cache: "PlanCache | bool | None" = None,
        result_cache: "ResultCache | bool | None" = None,
        run_fn=None,
        quotas=None,
        shed_depth_ratio: float | None = None,
    ):
        conf = session.conf
        self.session = session
        self.workers = int(workers if workers is not None else conf.serve_workers)
        self.max_queue_depth = int(
            max_queue_depth if max_queue_depth is not None else conf.serve_max_queue_depth
        )
        self.timeout_seconds = float(
            timeout_seconds if timeout_seconds is not None else conf.serve_query_timeout_seconds
        )
        # Graceful saturation (docs/serving.md "fleet topology"): shed
        # NON-priority submits once the queue reaches ratio x maxDepth,
        # so the priority lane keeps a bounded p99 while the server
        # saturates instead of queueing toward collapse. ratio >= 1
        # leaves only the hard depth limit.
        ratio = float(
            shed_depth_ratio if shed_depth_ratio is not None else conf.serve_shed_depth_ratio
        )
        self.shed_depth = (
            self.max_queue_depth if ratio >= 1.0
            else max(1, int(self.max_queue_depth * ratio))
        )
        # Per-tenant token-bucket admission (serve/fleet/quota.py). An
        # explicit TenantQuotas instance is shareable across servers;
        # True/None follow `hyperspace.serve.tenant.quota.enabled`.
        if quotas is None:
            quotas = conf.serve_tenant_quota_enabled
        if quotas is True:
            from hyperspace_tpu.serve.fleet.quota import TenantQuotas

            quotas = TenantQuotas(
                rate=conf.serve_tenant_quota_rate, burst=conf.serve_tenant_quota_burst
            )
        self.quotas = quotas or None
        # True/False force the caches on/off; None follows config; an
        # instance is used as-is (shareable across servers).
        if plan_cache is None:
            plan_cache = conf.serve_plan_cache_enabled
        if plan_cache is True:
            plan_cache = PlanCache(conf.serve_plan_cache_max_entries)
        self._plan_cache: PlanCache | None = plan_cache or None
        if result_cache is None:
            result_cache = conf.serve_result_cache_enabled
        if result_cache is True:
            result_cache = ResultCache(conf.serve_result_cache_max_bytes)
        self._result_cache: ResultCache | None = result_cache or None
        # DI seam for scheduler tests: replaces the whole execute step
        # (plan -> result), keeping admission/timeout logic identical.
        self._run_fn = run_fn
        self._cv = threading.Condition()
        self._prio: collections.deque[_Ticket] = collections.deque()
        self._fifo: collections.deque[_Ticket] = collections.deque()
        self._inflight = 0
        self._accepting = True
        self._stopping = False
        self._threads = [
            threading.Thread(target=self._worker, name=f"hs-serve-{i}", daemon=True)
            for i in range(self.workers)
        ]
        for t in self._threads:
            t.start()
        # Runtime health plane (docs/observability.md): opt-in /metrics +
        # /healthz endpoints sharing this server's lifecycle. Zero
        # overhead when disabled — one conf read, no import, no thread,
        # no socket.
        self._http = None
        if getattr(conf, "obs_http_enabled", False):
            from hyperspace_tpu.obs import http as obs_http

            self._http = obs_http.acquire(
                host=conf.obs_http_host, port=conf.obs_http_port
            )
            self._http.attach_session(session)
            self._http.attach_server(self)

    # -- client API -------------------------------------------------------
    def submit(
        self,
        plan,
        priority: bool = False,
        timeout: float | None = None,
        tenant: str | None = None,
    ) -> QueryHandle:
        """Enqueue a plan; returns its :class:`QueryHandle` immediately.
        Raises :class:`AdmissionRejected` when the queue is full, the
        saturation threshold sheds a non-priority submit, or the server
        is draining/shut down — and its :class:`QuotaExceeded` subclass
        when `tenant`'s token bucket is dry (tenant-less submits are
        unmetered)."""
        if self.quotas is not None and tenant is not None:
            # Quota admission runs FIRST: a throttled tenant never costs
            # a queue slot, a lock acquisition, or a trace span.
            self.quotas.admit(tenant)
        timeout_s = self.timeout_seconds if timeout is None else float(timeout)
        handle = QueryHandle(timeout_s)
        with obs_trace.span("serve.enqueue", priority=bool(priority)):
            ticket = _Ticket(plan, handle, priority)
            # Built while the submitter's span is active: trace.wrap
            # re-plants it in whichever worker thread runs the body.
            ticket.run = obs_trace.wrap(self._body(ticket))
            with self._cv:
                if not self._accepting:
                    _REJECTED.inc()
                    _EVT_REJECTED.emit(reason="not_accepting")
                    raise AdmissionRejected("server is not accepting queries (draining or shut down)")
                depth = len(self._prio) + len(self._fifo)
                if depth >= self.max_queue_depth:
                    _REJECTED.inc()
                    _EVT_REJECTED.emit(
                        reason="queue_full", depth=depth, max_depth=self.max_queue_depth
                    )
                    raise AdmissionRejected(
                        f"admission queue full ({depth} >= max depth {self.max_queue_depth})",
                        depth=depth, max_depth=self.max_queue_depth,
                    )
                if depth >= self.shed_depth and not priority:
                    # Graceful saturation: the queue is past its shed
                    # threshold — refuse ordinary traffic (typed, with
                    # the observed depth for backoff) while the priority
                    # lane keeps admitting. p99 stays bounded; the
                    # server never queues toward collapse.
                    _REJECTED.inc()
                    _SHED.inc()
                    _EVT_SHED.emit(depth=depth, shed_depth=self.shed_depth)
                    raise AdmissionRejected(
                        f"load shed: queue depth {depth} >= shed threshold "
                        f"{self.shed_depth} (max {self.max_queue_depth})",
                        depth=depth, max_depth=self.max_queue_depth,
                    )
                (self._prio if priority else self._fifo).append(ticket)
                _ADMITTED.inc()
                _QUEUE_DEPTH.set(depth + 1)
                self._cv.notify()
        return handle

    def run(self, plan, priority: bool = False, timeout: float | None = None,
            tenant: str | None = None):
        """Submit and block for the result — the one-call client path."""
        return self.submit(
            plan, priority=priority, timeout=timeout, tenant=tenant
        ).result(timeout=timeout)

    @property
    def plan_cache(self) -> PlanCache | None:
        return self._plan_cache

    @property
    def result_cache(self) -> ResultCache | None:
        return self._result_cache

    def queue_depth(self) -> int:
        with self._cv:
            return len(self._prio) + len(self._fifo)

    def set_shed_depth(self, depth: int) -> int:
        """Move the graceful-saturation shed threshold at runtime —
        the ops controller's load-shedding actuator (serve/controller.py):
        while serve SLOs page, non-priority traffic is refused earlier
        (typed, with the observed depth) so the queue drains instead of
        feeding the burn. Clamped to [1, maxQueueDepth]; returns the
        applied value."""
        with self._cv:
            self.shed_depth = max(1, min(int(depth), self.max_queue_depth))
            return self.shed_depth

    def get_shed_depth(self) -> int:
        with self._cv:
            return self.shed_depth

    def saturation(self) -> dict:
        """Point-in-time scheduler load — the /healthz overload signal
        (docs/serving.md): how full the admission queue is and how many
        workers are busy tells a balancer to back off BEFORE submits
        start bouncing off AdmissionRejected."""
        with self._cv:
            return {
                "workers": self.workers,
                "inflight": self._inflight,
                "queue_depth": len(self._prio) + len(self._fifo),
                "max_queue_depth": self.max_queue_depth,
                "shed_depth": self.shed_depth,
                "accepting": self._accepting,
            }

    @property
    def health_endpoint(self):
        """The attached HealthServer (None unless
        `hyperspace.obs.http.enabled` was true at construction)."""
        with self._cv:
            return self._http

    # -- lifecycle --------------------------------------------------------
    def drain(self, timeout: float | None = None) -> bool:
        """Pause admission and wait until the queue and in-flight work
        are empty; admission resumes afterwards (unless shut down).
        Returns False if `timeout` expired first."""
        with self._cv:
            self._accepting = False
            ok = self._cv.wait_for(
                lambda: not self._prio and not self._fifo and self._inflight == 0,
                timeout,
            )
            if not self._stopping:
                self._accepting = True
        return ok

    def shutdown(self, wait: bool = True, timeout: float | None = None) -> None:
        """Stop the server. With `wait`, queued and in-flight queries
        finish first (graceful); without, queued tickets are cancelled
        (their handles raise AdmissionRejected) and only in-flight
        queries complete. Idempotent."""
        with self._cv:
            self._accepting = False
            self._stopping = True
            if not wait:
                for t in (*self._prio, *self._fifo):
                    t.handle.cancelled = True
                    t.handle.error = AdmissionRejected("server shut down before execution")
                    _CANCELLED.inc()
                    t.handle._done.set()
                self._prio.clear()
                self._fifo.clear()
                _QUEUE_DEPTH.set(0)
            self._cv.notify_all()
        if wait:
            with self._cv:
                self._cv.wait_for(
                    lambda: not self._prio and not self._fifo and self._inflight == 0,
                    timeout,
                )
                self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=5.0)
        # Health plane rides the server lifecycle: drop this server from
        # /healthz and release the shared endpoint (the last QueryServer
        # out closes the socket). Claimed exactly once across repeated
        # shutdown() calls.
        with self._cv:
            http, self._http = self._http, None
        if http is not None:
            from hyperspace_tpu.obs import http as obs_http

            http.detach_server(self)
            obs_http.release()

    def __enter__(self) -> "QueryServer":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.shutdown(wait=exc_type is None)
        return False

    # -- worker plane -----------------------------------------------------
    def _worker(self) -> None:
        while True:
            with self._cv:
                while True:
                    if self._prio:
                        ticket = self._prio.popleft()
                        break
                    if self._fifo:
                        ticket = self._fifo.popleft()
                        break
                    if self._stopping:
                        return
                    self._cv.wait()
                _QUEUE_DEPTH.set(len(self._prio) + len(self._fifo))
                self._inflight += 1
                _INFLIGHT.set(self._inflight)
            try:
                waited = time.perf_counter() - ticket.enqueued_s
                _QUEUE_WAIT.observe(waited)
                if ticket.deadline_s is not None and time.perf_counter() > ticket.deadline_s:
                    # Expired while queued: the client has (or will have)
                    # timed out — executing it would burn worker time on
                    # an answer nobody is waiting for.
                    ticket.handle.timed_out = True
                    ticket.handle.error = QueryTimeout(
                        f"query expired in queue after {waited:.3f}s "
                        f"(timeout {ticket.handle.timeout_s:.3f}s)",
                        elapsed_s=waited,
                    )
                    _TIMEOUTS.inc()
                    ticket.handle._done.set()
                else:
                    ticket.run()
            finally:
                with self._cv:
                    self._inflight -= 1
                    _INFLIGHT.set(self._inflight)
                    self._cv.notify_all()

    def _body(self, ticket: _Ticket):
        """The execution closure for one ticket, run on a worker thread
        under the submitter's re-planted span (see submit)."""

        def body() -> None:
            handle = ticket.handle
            try:
                with obs_trace.trace("serve.run", priority=ticket.priority):
                    handle._result = self._execute(ticket.plan, handle)
                _COMPLETED.inc()
            except BaseException as e:  # noqa: HSL017 — worker isolation:
                # a fault-injected CrashPoint must not take the worker
                # thread down; the exception object (traceback included)
                # is stored on the handle and re-raised, original frames
                # intact, by QueryHandle.result() — preserved, not
                # swallowed.
                handle.error = e
                _FAILED.inc()
            finally:
                _LATENCY.observe(time.perf_counter() - handle.submitted_s)
                handle._done.set()

        return body

    def _execute(self, plan, handle: QueryHandle):
        if self._run_fn is not None:
            return self._run_fn(plan)
        session = self.session
        rc = self._result_cache
        if rc is None:
            return self._run_and_cache(plan, handle, None, None)
        key = rc.key(session, plan)

        def observe(hit):
            if hit is not None:
                handle.cache_hit = True
                handle.stats = {"result_cache": "hit"}
            return hit

        first = observe(rc.get(key))
        if first is not None:
            return first
        sf = getattr(rc, "single_flight", None)
        if sf is not None:
            # Fleet-wide cold miss (docs/serving.md "fleet topology"):
            # one process across the fleet executes and publishes the
            # shared entry; the rest observe it via the poll — or fall
            # back to a local run when the wait budget expires.
            from hyperspace_tpu.serve.fleet.singleflight import key_name

            peek = getattr(rc, "peek", rc.get)
            return sf.run(
                f"result-{key_name(key)}",
                build=lambda: self._run_and_cache(plan, handle, rc, key),
                check=lambda: observe(peek(key)),
            )
        return self._run_and_cache(plan, handle, rc, key)

    def _run_and_cache(self, plan, handle: QueryHandle, rc, key):
        session = self.session
        outcome = session.run_query(plan, plan_cache=self._plan_cache)
        handle.profile = outcome.profile
        handle.stats = outcome.stats
        # Keep the session view current so last_profile()/explain keep
        # working for interactive pokes at a serving session.
        session._publish(outcome)
        if rc is not None and outcome.replans == 0:
            # A replanned (corruption-fallback) result is correct but its
            # key predates the quarantine it triggered — don't cache it.
            rc.put(key, outcome.result)
        return outcome.result

    def metrics_snapshot(self) -> dict:
        """Point-in-time serve.* metrics (tests / ops)."""
        reg = obs_metrics.REGISTRY
        return {
            name: m.snapshot()
            for name in reg.names()
            if name.startswith("serve.")
            for m in [reg.get(name)]
        }
