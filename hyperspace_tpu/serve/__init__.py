"""Concurrent query-serving plane (docs/serving.md).

`QueryServer` puts a bounded worker pool + admission-control queue in
front of one `HyperspaceSession`; `PlanCache`/`ResultCache` memoize
optimized plans and whole results under versioned keys that index
mutations and source appends invalidate structurally. Off by default —
construct it explicitly (`session.serve()`); plain `session.run()` is
unchanged.
"""

from hyperspace_tpu.serve.controller import OpsController
from hyperspace_tpu.serve.plan_cache import (
    PlanCache,
    collection_log_versions,
    versioned_plan_key,
)
from hyperspace_tpu.serve.result_cache import ResultCache, table_nbytes
from hyperspace_tpu.serve.scheduler import QueryHandle, QueryServer

__all__ = [
    "QueryServer",
    "QueryHandle",
    "OpsController",
    "PlanCache",
    "ResultCache",
    "collection_log_versions",
    "versioned_plan_key",
    "table_nbytes",
]

# The multi-process fleet layer (shared disk caches, cross-process
# single-flight, tenant quotas, supervisor) lives in
# `hyperspace_tpu.serve.fleet` — imported explicitly by fleet deployments
# (docs/serving.md "fleet topology"), never on the single-process path.
