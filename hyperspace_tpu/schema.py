"""Columnar schema model.

The reference stores a Spark `schemaString` (JSON StructType) inside the
index log entry (index/IndexLogEntry.scala:39-47). Here the schema is a
first-class dataclass that serializes to/from plain JSON, and additionally
knows how each logical type maps onto a TPU-resident physical type:

- fixed-width numerics map 1:1 onto jax dtypes;
- strings are dictionary-encoded on the host feed (int32 codes on device,
  dictionary kept host-side) because variable-length data has no efficient
  TPU representation (SURVEY.md §7 step 1, "hard part").
"""

from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np

_SUPPORTED = {
    "int32": np.int32,
    "int64": np.int64,
    "float32": np.float32,
    "float64": np.float64,
    "bool": np.bool_,
    "string": np.int32,  # dictionary codes on device
    "date": np.int32,  # days since epoch
    "timestamp": np.int64,  # microseconds since epoch
    "vector": np.float32,  # fixed-dim embedding, [n, dim] float32 on device
}


@dataclasses.dataclass(frozen=True)
class Field:
    name: str
    dtype: str  # logical type name, one of _SUPPORTED
    nullable: bool = False
    # Embedding dimensionality; required iff dtype == "vector".
    dim: int | None = None

    def __post_init__(self):
        if self.dtype not in _SUPPORTED:
            raise ValueError(f"unsupported dtype {self.dtype!r} for field {self.name!r}")
        if (self.dtype == "vector") != (self.dim is not None):
            raise ValueError(f"field {self.name!r}: dim is required iff dtype is 'vector'")

    @property
    def device_dtype(self) -> np.dtype:
        """Physical dtype of the device-resident column."""
        return np.dtype(_SUPPORTED[self.dtype])

    @property
    def is_string(self) -> bool:
        return self.dtype == "string"

    @property
    def is_vector(self) -> bool:
        return self.dtype == "vector"

    def to_json(self) -> dict[str, Any]:
        d = {"name": self.name, "dtype": self.dtype, "nullable": self.nullable}
        if self.dim is not None:
            d["dim"] = self.dim
        return d

    @staticmethod
    def from_json(d: dict[str, Any]) -> "Field":
        return Field(d["name"], d["dtype"], d.get("nullable", False), d.get("dim"))


@dataclasses.dataclass(frozen=True)
class Schema:
    fields: tuple[Field, ...]

    def __post_init__(self):
        names = [f.name.lower() for f in self.fields]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate column names in schema: {names}")

    @staticmethod
    def of(*fields: Field) -> "Schema":
        return Schema(tuple(fields))

    @property
    def names(self) -> list[str]:
        return [f.name for f in self.fields]

    def field(self, name: str) -> Field:
        """Case-insensitive field lookup (reference resolves columns
        case-insensitively, index/IndexConfig.scala:40-53)."""
        low = name.lower()
        for f in self.fields:
            if f.name.lower() == low:
                return f
        raise KeyError(name)

    def __contains__(self, name: str) -> bool:
        try:
            self.field(name)
            return True
        except KeyError:
            return False

    def select(self, names: list[str]) -> "Schema":
        return Schema(tuple(self.field(n) for n in names))

    def to_json(self) -> list[dict[str, Any]]:
        return [f.to_json() for f in self.fields]

    @staticmethod
    def from_json(items: list[dict[str, Any]]) -> "Schema":
        return Schema(tuple(Field.from_json(d) for d in items))

    @staticmethod
    def from_arrow(arrow_schema) -> "Schema":
        """Derive a Schema from a pyarrow schema."""
        import pyarrow as pa

        fields = []
        for f in arrow_schema:
            t = f.type
            if pa.types.is_int32(t):
                dt = "int32"
            elif pa.types.is_int64(t):
                dt = "int64"
            elif pa.types.is_float32(t):
                dt = "float32"
            elif pa.types.is_float64(t):
                dt = "float64"
            elif pa.types.is_boolean(t):
                dt = "bool"
            elif pa.types.is_string(t) or pa.types.is_large_string(t) or pa.types.is_dictionary(t):
                dt = "string"
            elif pa.types.is_date32(t):
                dt = "date"
            elif pa.types.is_timestamp(t):
                dt = "timestamp"
            elif pa.types.is_fixed_size_list(t) and pa.types.is_floating(t.value_type):
                fields.append(Field(f.name, "vector", f.nullable, dim=t.list_size))
                continue
            else:
                raise ValueError(f"unsupported arrow type {t} for column {f.name!r}")
            fields.append(Field(f.name, dt, f.nullable))
        return Schema(tuple(fields))
