"""Deterministic fault injection for the metadata and execution planes.

The production claim this repo rides on — the operation log tolerates
writers that die mid-`op()` (actions/base.py two-phase protocol) — is
untestable without a way to make IO fail *on purpose, at a chosen call,
deterministically*. This module provides that: named **fault points**
threaded through `utils/file_utils.py`, `metadata/log_manager.py` and
`execution/io.py` call :func:`fault_point` with a semantic name and the
path being acted on; tests register :class:`FaultRule`\\ s that make a
specific call raise a transient :class:`FaultError`, simulate a hard
process death via :class:`CrashPoint`, or truncate/corrupt the bytes on
disk — on a schedule (fail the first N calls, fail exactly call K).

Design constraints:

- **Zero overhead when disabled.** `fault_point` is a single module-global
  check (`if not _armed: return`) on the hot IO paths; nothing else runs
  unless a test armed the harness.
- **Crash ≠ error.** :class:`CrashPoint` derives from ``BaseException``,
  so the `except Exception` failure handling in `Action.run()` cannot
  "survive" it — exactly like a real `kill -9`, the dying writer gets no
  chance to clean up, and recovery must happen in a later process
  (`Hyperspace.recover`). Transient :class:`FaultError` is an ``OSError``
  with errno EIO, so `exceptions.is_retryable` classifies it and the
  retry layer (utils/retry.py) handles it like any flaky disk.
- **Deterministic.** Schedules count calls, never wall time or RNG.
  Brownout jitter derives from the rule's own call counter through a
  Knuth multiplicative hash — the same schedule every run.

Brownout (slow-path) injection: a rule with ``delay_s`` makes the point
*slow* instead of failed — the arriving call sleeps ``delay_s`` plus a
deterministic per-call jitter in ``[0, jitter_s)`` before continuing (or
before the rule's error/crash action, so "slow then fail" composes).
The sleep happens OUTSIDE the harness lock (HSL013), in small slices
that re-check the armed gate, so the ``hyperspace.faults.enabled`` kill
switch disarms a delay already in flight. Tests that must not spend
wall time install a virtual sleeper via :func:`set_sleeper` (the same
virtual-clock idiom as the soak harness); delays are clamped to
``hyperspace.faults.maxDelaySeconds`` so a typo'd rule cannot wedge a
deadline-carrying path past its typed timeout budget.

Kill switch: ``hyperspace.faults.enabled`` (config.py) — when set False,
`fault_point` is inert even with rules registered, so a production
config can never be one stray rule away from injected failures.

Fault point names in use (see each call site):

====================  =====================================================
``file.write_json``   file_utils.write_json overwrite (temp + replace) path
``file.atomic_write`` file_utils.atomic_write CAS-create path
``log.write``         log_manager.write_log, before the entry CAS
``log.written``       after a log entry commits (truncate ⇒ torn entry)
``log.stable.write``  before the latestStable pointer rewrite
``manifest.write``    io.write_manifest, before the atomic write
``manifest.written``  after the manifest commits (truncate ⇒ torn manifest)
``manifest.read``     io.read_manifest, before the JSON parse
``bucket.write``      io.write_bucket, before the parquet encode
``bucket.written``    after a bucket file lands (truncate ⇒ corrupt bucket)
``bucket.read``       io._read_one_file / io.read_chunk, before a data decode
``footer.read``       io.read_footers, before a footer parse
``spill.read``        builder p2 pipeline, before a bucket's spill read
``pipeline.put``      builder, before a read bucket enters the sort queue
``pipeline.get``      builder, before the sort stage dequeues a bucket
``prefetch.issue``    execution/prefetch.py, before an async prefetch job
``advisor.recommend`` advisor/whatif.py, at the head of a recommendation pass
``advisor.apply``     advisor/lifecycle.py, before each policy mutation
``fleet.lease.acquire`` fleet/lease.py, before a cross-process lease claim
``fleet.cache.read``  fleet/shared_cache.py, before a shared-entry read
``fleet.cache.write`` fleet/shared_cache.py, before a shared-entry publish
``fleet.cache.evict`` fleet/shared_cache.py, before each lease-held eviction
``build.worker.spawn`` builder coordinator, before each pooled worker spawn
``build.exchange.write`` build_exchange p1 shard, before a spill file finalizes
``build.exchange.read`` build_exchange p2 owner, before a bucket's spill read
``build.manifest.merge`` builder coordinator, before the per-owner stats merge
``device.stage``      execution/staging.py, before each zero-copy column view
                      (transient ⇒ that column degrades to the copied host
                      path; crash ⇒ the query dies like any hard death)
``controller.actuate`` serve/controller.py, immediately BEFORE each ops-
                      controller mutation (shed engage/release, heal,
                      sweep): a crash there proves the reconciliation
                      step leaves no partial actuation behind
``ingest.tail``       ingest/tailer.py, after a CDC batch file lands but
                      BEFORE the cursor persists: a crash there leaves an
                      orphan batch the deterministic naming makes the
                      retry idempotent over
``ingest.commit``     ingest/writer.py, before a micro-batch's incremental
                      refresh action runs (a crash mid-commit leaves at
                      most the Action protocol's transient log)
``ingest.compact``    ingest/writer.py, before the gated optimize action
                      compacts delta buckets
``ingest.stamp``      ingest/daemon.py, after a micro-batch commits but
                      BEFORE the daemon stamps its lag/commit bookkeeping
                      (the commit-before-stamp torn window HSL028 proves)
``journal.seal``      obs/journal.py, after a sealed segment publishes but
                      BEFORE the eviction index runs (the
                      seal-before-index torn window HSL028 proves)
``controller.heal.marker`` serve/controller.py, after the leader heals the
                      shared bytes but BEFORE the generation marker
                      publishes (the marker-after-heal torn window
                      HSL028 proves)
====================  =====================================================

Cross-process injection: the pooled build's workers are SPAWNED
processes with fresh module state, so the coordinator's registered
rules would never fire inside them. `parallel/procpool.py` ships
:func:`export_state` into each worker (installed via
:func:`install_state` — fresh per-process call/fire schedules) and
merges the worker's observed points back on join, so the deterministic
crash sweep sees through the process boundary.
"""

from __future__ import annotations

import dataclasses
import errno as _errno
import os
import threading
from contextlib import contextmanager
from typing import Iterator

from hyperspace_tpu import stats

KNOWN_POINTS = (
    "file.write_json",
    "file.atomic_write",
    "log.write",
    "log.written",
    "log.stable.write",
    "manifest.write",
    "manifest.written",
    "manifest.read",
    "bucket.write",
    "bucket.written",
    "bucket.read",
    "footer.read",
    "spill.read",
    "pipeline.put",
    "pipeline.get",
    "prefetch.issue",
    "advisor.recommend",
    "advisor.apply",
    "fleet.lease.acquire",
    "fleet.cache.read",
    "fleet.cache.write",
    "fleet.cache.evict",
    "build.worker.spawn",
    "build.exchange.write",
    "build.exchange.read",
    "build.manifest.merge",
    "device.stage",
    "controller.actuate",
    "ingest.tail",
    "ingest.commit",
    "ingest.compact",
    "ingest.stamp",
    "journal.seal",
    "controller.heal.marker",
)


class FaultError(OSError):
    """Injected transient IO failure. errno EIO ⇒ retryable
    (exceptions.is_retryable), so the retry layer treats it exactly like
    a real flaky disk."""

    def __init__(self, msg: str):
        super().__init__(_errno.EIO, msg)


class CrashPoint(BaseException):
    """Simulated hard process death at a fault point.

    BaseException on purpose: recovery code that catches ``Exception``
    must not be able to run in the "dying" process — the test harness
    catches this at its outermost level and then plays the next process
    (recover / re-open), which is the only honest way to test crash
    consistency.
    """

    def __init__(self, point: str, path: str | None = None):
        super().__init__(f"simulated crash at fault point {point!r}")
        self.point = point
        self.path = path


@dataclasses.dataclass
class FaultRule:
    """One registered fault: where, what, and on which calls.

    `at_call` fires on exactly the K-th arrival at the point (1-based);
    `times` caps how many times the rule fires (fail-N-then-succeed);
    both unset ⇒ fires on every arrival. Actions compose in order:
    `delay_s` sleeps first (brownout), then truncate/corrupt mutate the
    file, then `error`/`crash` raise — so a single rule can model "the
    disk went slow, wrote garbage AND the process died". A pure-delay
    rule (delay_s set, no other action) slows the call and lets it
    proceed; `jitter_s` adds a deterministic per-call extra in
    ``[0, jitter_s)`` derived from the rule's call counter (no RNG)."""

    point: str
    error: BaseException | type | None = None
    crash: bool = False
    truncate: int | None = None  # keep only the first N bytes of `path`
    corrupt: bytes | None = None  # overwrite the head of `path` with these bytes
    delay_s: float = 0.0  # brownout: sleep this long before any other action
    jitter_s: float = 0.0  # deterministic per-call extra delay in [0, jitter_s)
    at_call: int | None = None  # 1-based call index this rule fires at
    times: int | None = None  # max number of firings (None = unlimited)
    calls: int = 0
    fired: int = 0


_lock = threading.Lock()
_rules: list[FaultRule] = []
_observed: set[str] = set()
_armed = False  # fast-path gate: False ⇒ fault_point returns immediately
_enabled = True  # hyperspace.faults.enabled kill switch

# Brownout machinery. The sleeper is a hook (default: real time.sleep)
# so virtual-clock harnesses account delay without spending wall time;
# sleeps run in _DELAY_SLICE_S slices re-checking the armed gate, so
# the kill switch disarms a delay already in flight. _max_delay_s caps
# any single injected delay (hyperspace.faults.maxDelaySeconds).
_DELAY_SLICE_S = 0.05
_KNUTH = 2654435761  # multiplicative-hash constant (deterministic jitter)
_sleeper = None  # None ⇒ time.sleep; swapped by set_sleeper()
_max_delay_s = 30.0


def set_enabled(enabled: bool) -> None:
    """Config kill switch (`hyperspace.faults.enabled`). False disarms
    the harness even with rules registered."""
    global _enabled, _armed
    with _lock:
        _enabled = bool(enabled)
        _armed = _enabled and bool(_rules)


def set_sleeper(sleeper) -> None:
    """Install the brownout sleep hook: ``sleeper(seconds)`` is called
    (possibly in slices) for every injected delay. Pass a virtual-clock
    advance to keep delay accounting wall-clock-free (the soak harness
    does), or None to restore real ``time.sleep``."""
    global _sleeper
    with _lock:
        _sleeper = sleeper


def set_max_delay(seconds: float) -> None:
    """Config clamp (`hyperspace.faults.maxDelaySeconds`) on any single
    injected delay (base + jitter)."""
    global _max_delay_s
    with _lock:
        _max_delay_s = max(0.0, float(seconds))


def inject(
    point: str,
    *,
    error: BaseException | type | None = None,
    crash: bool = False,
    truncate: int | None = None,
    corrupt: bytes | None = None,
    delay_s: float = 0.0,
    jitter_s: float = 0.0,
    at_call: int | None = None,
    times: int | None = None,
) -> FaultRule:
    """Register a fault at `point`. With no explicit action, the rule
    raises a transient :class:`FaultError` (the common retry-test case);
    a bare ``delay_s`` makes a brownout rule — the call slows down and
    then proceeds normally."""
    if point not in KNOWN_POINTS:
        raise ValueError(f"unknown fault point {point!r} (see faults.KNOWN_POINTS)")
    if (error is None and not crash and truncate is None and corrupt is None
            and not delay_s):
        error = FaultError
    rule = FaultRule(
        point=point, error=error, crash=crash, truncate=truncate,
        corrupt=corrupt, delay_s=delay_s, jitter_s=jitter_s,
        at_call=at_call, times=times,
    )
    global _armed
    with _lock:
        _rules.append(rule)
        _armed = _enabled
    return rule


def reset() -> None:
    """Clear every rule and observation; disarm the fast path. The
    brownout sleeper hook is restored to real ``time.sleep`` so a
    virtual clock can never leak across tests."""
    global _armed, _sleeper
    with _lock:
        _rules.clear()
        _observed.clear()
        _armed = False
        _sleeper = None


@contextmanager
def injected(point: str, **kwargs) -> Iterator[FaultRule]:
    """`with faults.injected("log.write", crash=True): ...` — register one
    rule for the block, always reset after."""
    rule = inject(point, **kwargs)
    try:
        yield rule
    finally:
        reset()


@contextmanager
def recording() -> Iterator[set]:
    """Arm the harness with no rules, purely to record which fault points
    a block of code passes through — the discovery pass the crash sweep
    uses to enumerate the points each action actually exercises. The
    yielded set keeps its contents after the block exits."""
    global _armed
    out: set[str] = set()
    with _lock:
        _observed.clear()
        _armed = _enabled
    try:
        yield out
    finally:
        with _lock:
            out |= _observed
        reset()


def observed_points() -> set[str]:
    """Fault points hit while the harness was armed (recording or rules)."""
    with _lock:
        return set(_observed)


def export_state() -> dict:
    """Picklable snapshot of the harness (rules with FRESH call/fire
    schedules, the kill switch, and whether the fast path is armed) for
    shipping into a spawned worker process. Schedules count per process:
    `at_call=1` fires at each worker's first arrival."""
    with _lock:
        return {
            "enabled": _enabled,
            "armed": _armed,
            "max_delay_s": _max_delay_s,
            "rules": [dataclasses.replace(r, calls=0, fired=0) for r in _rules],
        }


def install_state(state: dict) -> None:
    """Install a coordinator's :func:`export_state` snapshot into this
    (worker) process. `armed` is honored even with zero rules so a
    coordinator-side `recording()` pass observes worker-side points
    too."""
    global _armed, _enabled, _max_delay_s
    with _lock:
        _rules.clear()
        _rules.extend(state.get("rules") or ())
        _enabled = bool(state.get("enabled", True))
        _max_delay_s = float(state.get("max_delay_s", _max_delay_s))
        _armed = _enabled and (bool(_rules) or bool(state.get("armed")))


def merge_observed(points) -> None:
    """Fold a worker's observed points back into this process's set (the
    return leg of the cross-process recording contract)."""
    if not points:
        return
    with _lock:
        _observed.update(points)


def fault_point(name: str, path: str | os.PathLike | None = None) -> None:
    """Declare a named fault point. Call sites sprinkle this on the IO
    paths; it is a no-op unless a test armed the harness."""
    # Benign racy read BY DESIGN: _armed is a monotonic bool gate flipped
    # under _lock; a stale False skips at most one injection during the
    # arming instant, and the disarmed fast path must stay lock-free
    # (every metadata/IO call site runs through here).
    if not _armed:  # noqa: HSL013
        return
    _hit(name, path)


def _hit(name: str, path: str | os.PathLike | None) -> None:
    to_fire: list[tuple[FaultRule, int]] = []
    with _lock:
        _observed.add(name)
        for rule in _rules:
            if rule.point != name:
                continue
            rule.calls += 1
            if rule.at_call is not None and rule.calls != rule.at_call:
                continue
            if rule.times is not None and rule.fired >= rule.times:
                continue
            rule.fired += 1
            to_fire.append((rule, rule.calls))
    for rule, call_no in to_fire:
        stats.increment("faults.injected")
        # Brownout first — "went slow, THEN failed" is the composition a
        # real degraded disk exhibits. Runs outside _lock (HSL013).
        if rule.delay_s > 0.0 or rule.jitter_s > 0.0:
            _apply_delay(rule, call_no)
        if path is not None and (rule.truncate is not None or rule.corrupt is not None):
            _mangle_file(path, rule)
        if rule.crash:
            raise CrashPoint(name, str(path) if path is not None else None)
        if rule.error is not None:
            if isinstance(rule.error, type):
                raise rule.error(f"injected fault at {name!r}" + (f" ({path})" if path else ""))
            raise rule.error


def _apply_delay(rule: FaultRule, call_no: int) -> None:
    """Sleep the rule's brownout schedule for its `call_no`-th arrival:
    base delay plus a deterministic jitter in ``[0, jitter_s)`` hashed
    from the call counter (same schedule every run, no RNG), clamped to
    the configured max. Sliced so the kill switch (or reset) disarms a
    delay already in flight."""
    jitter = rule.jitter_s * ((call_no * _KNUTH) % 1000) / 1000.0
    with _lock:
        total = min(rule.delay_s + jitter, _max_delay_s)
        sleeper = _sleeper
    if total <= 0.0:
        return
    stats.increment("faults.delays_injected")
    import time

    if sleeper is None:
        sleeper = time.sleep
    remaining = total
    while remaining > 0.0:
        with _lock:  # kill switch flipped mid-delay ⇒ stop browning out
            armed = _armed
        if not armed:
            return
        step = min(remaining, _DELAY_SLICE_S)
        sleeper(step)
        remaining -= step


def _mangle_file(path: str | os.PathLike, rule: FaultRule) -> None:
    """Apply a truncate/corrupt schedule to the file at `path` (missing
    file ⇒ no-op: the point fired before the bytes landed)."""
    try:
        if rule.truncate is not None:
            with open(path, "r+b") as f:
                f.truncate(rule.truncate)
        if rule.corrupt is not None:
            with open(path, "r+b") as f:
                f.write(rule.corrupt)
    except OSError:
        pass
