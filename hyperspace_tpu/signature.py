"""Plan signature providers: fingerprint the *data* a plan reads.

Reference parity: index/FileBasedSignatureProvider.scala:30-75 — fold an MD5
over (size, mtime, path) of every file in each scan leaf; an index matches a
plan iff the stored fingerprint equals the recomputed one. Providers are
pluggable by name (reference uses reflection by class name,
index/LogicalPlanSignatureProvider.scala:55-62; we use a registry).
"""

from __future__ import annotations

import hashlib

from hyperspace_tpu.dataset import format_suffix, list_data_files
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.metadata.log_entry import Fingerprint
from hyperspace_tpu.plan.nodes import LogicalPlan, Scan


def collect_leaf_files(leaf: Scan) -> list:
    """Enumerate a scan leaf's files as FileInfo, honoring pinned subsets."""
    import os

    from hyperspace_tpu.metadata.log_entry import FileInfo

    if leaf.files is not None:
        out = []
        for path in sorted(leaf.files):
            st = os.stat(path)
            out.append(FileInfo(path, st.st_size, st.st_mtime_ns))
        return out
    return list_data_files(leaf.root, suffix=format_suffix(leaf.format))


def fingerprint_files(files) -> str:
    """Delimited MD5 fold over (size, mtime, path) identities — the same
    contract as FileBasedSignatureProvider.scala:48-74, with explicit field
    separators so distinct (size, mtime) pairs cannot collide."""
    h = hashlib.md5()
    for fi in files:
        h.update(f"{fi.size},{fi.mtime_ns},{fi.path}\0".encode())
    return h.hexdigest()


def diff_source_files(entry, plan, current=None):
    """(appended, deleted_or_modified) file diff between the live listing of
    `plan`'s leaves and the files logged in `entry.source.files`. Identity
    is (path, size, mtime) — a rewritten-in-place file shows up as deleted.
    Basis of incremental refresh and hybrid-scan applicability. Pass
    `current` (a FileInfo list) to reuse one listing across many entries."""
    if current is None:
        current = []
        for leaf in plan.leaves():
            current.extend(collect_leaf_files(leaf))
    logged = {(f.path, f.size, f.mtime_ns) for f in entry.source.files}
    live = {(f.path, f.size, f.mtime_ns) for f in current}
    appended = [f for f in current if (f.path, f.size, f.mtime_ns) not in logged]
    deleted = [f for f in entry.source.files if (f.path, f.size, f.mtime_ns) not in live]
    return appended, deleted


def plan_signature(plan: LogicalPlan) -> str:
    """Structural fingerprint of a logical plan: an MD5 over its canonical
    JSON serialization (sorted keys, so dict ordering cannot perturb it).
    Two plans with the same signature ask the same question of the same
    sources — the serving plane's plan/result caches key on this plus the
    data fingerprint and the index-collection log versions
    (serve/plan_cache.py), so a repeat query skips re-optimization."""
    import json

    payload = json.dumps(plan.to_json(), sort_keys=True, default=str)
    return hashlib.md5(payload.encode()).hexdigest()


class SignatureProvider:
    name: str = "base"

    def signature(self, plan: LogicalPlan) -> Fingerprint | None:
        """Return the plan's data fingerprint, or None if this provider
        cannot fingerprint the plan (e.g. a leaf kind it doesn't know)."""
        raise NotImplementedError


class FileBasedSignatureProvider(SignatureProvider):
    name = "fileBased"

    def signature(self, plan: LogicalPlan) -> Fingerprint | None:
        leaves = plan.leaves()
        if not leaves:
            return None
        files = []
        for leaf in leaves:
            if not isinstance(leaf, Scan):
                return None
            files.extend(collect_leaf_files(leaf))
        return Fingerprint(kind=self.name, value=fingerprint_files(files))


import threading

_REGISTRY: dict[str, type[SignatureProvider]] = {
    FileBasedSignatureProvider.name: FileBasedSignatureProvider,
}
_REGISTRY_LOCK = threading.Lock()


def register_signature_provider(cls: type[SignatureProvider]) -> None:
    with _REGISTRY_LOCK:
        _REGISTRY[cls.name] = cls


def create_signature_provider(name: str = "fileBased") -> SignatureProvider:
    with _REGISTRY_LOCK:
        provider = _REGISTRY.get(name)
    if provider is None:
        raise HyperspaceError(f"unknown signature provider {name!r}")
    return provider()
