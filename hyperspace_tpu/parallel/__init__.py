from hyperspace_tpu.parallel.mesh import default_mesh, make_mesh

__all__ = ["default_mesh", "make_mesh"]
