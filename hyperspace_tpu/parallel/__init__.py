"""Parallel execution utilities: device meshes, bandwidth-aware venue
choice, x64 worker pools, and the spawn-context worker-process
lifecycle.

This ``__init__`` must stay **jax-free at module load**: the pooled
build's spawned workers import ``hyperspace_tpu.parallel.procpool``,
which executes THIS file first — an eager ``from .mesh import ...``
re-export here made every worker pay the full jax import before its
task ran (caught by the HSL019 runtime-mirror test; the static proof is
analysis rule HSL019, docs/static_analysis.md). The mesh re-exports are
therefore lazy.
"""

__all__ = ["default_mesh", "make_mesh"]


def __getattr__(name):
    if name in ("default_mesh", "make_mesh"):
        from hyperspace_tpu.parallel import mesh

        return getattr(mesh, name)
    raise AttributeError(name)
