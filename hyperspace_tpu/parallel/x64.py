"""Scoped 64-bit device compute without global flag flips.

Aggregation sums need 53-bit accumulation, but `jax_enable_x64` is
process-wide poison (round 1 weakness #8) and re-entering the
`jax.enable_x64(True)` context around every call invalidates the jit
executable cache — each query would re-lower a multi-second program.

JAX config contexts are THREAD-LOCAL, so all f64 device work runs on one
dedicated worker thread that enters the context once and never leaves it.
Every other thread keeps 32-bit-native semantics; the executable cache
stays warm across queries.
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor

_pool: ThreadPoolExecutor | None = None
_pool_lock = threading.Lock()
# The entered context object must stay referenced: on jax versions where
# enable_x64 is a generator-based contextmanager, dropping it lets GC
# close the generator and silently REVERT x64 on the worker thread.
_x64_ctx = None


def _enter_x64() -> None:
    global _x64_ctx
    from hyperspace_tpu.compat import enable_x64

    _x64_ctx = enable_x64(True)
    _x64_ctx.__enter__()  # intentionally never exited: thread-local scope


def run_x64(fn, /, *args, **kwargs):
    """Run `fn` on the persistent x64 worker thread and return its result."""
    global _pool
    # Double-checked init (HSL013-allowlisted): the unguarded read is
    # the lock-free hot path; a stale None only sends the loser into the
    # locked block, where the re-check under _pool_lock decides. Once
    # published, _pool is never reassigned.
    if _pool is None:
        with _pool_lock:
            if _pool is None:
                # XLA:CPU compiles on the calling thread, and LLVM's
                # recursive passes can exhaust the default 8 MB pthread
                # stack on very large fused programs (observed as a
                # SIGSEGV inside backend_compile) — give the worker a
                # deep stack before it is spawned.
                prev = threading.stack_size()
                try:
                    threading.stack_size(256 << 20)
                except (ValueError, RuntimeError):
                    prev = None
                try:
                    _pool = ThreadPoolExecutor(max_workers=1, initializer=_enter_x64)
                    # Spawn the worker NOW, while the stack size is set
                    # (threads are created lazily on first submit).
                    _pool.submit(lambda: None).result()
                finally:
                    if prev is not None:
                        threading.stack_size(prev)
    # The worker thread starts with an empty contextvar context —
    # re-plant the caller's active trace span so f64 device work
    # attributes to the operator that requested it.
    from hyperspace_tpu.obs import trace as obs_trace

    return _pool.submit(obs_trace.wrap(fn), *args, **kwargs).result()
