"""Scoped 64-bit device compute without global flag flips.

Aggregation sums need 53-bit accumulation, but `jax_enable_x64` is
process-wide poison (round 1 weakness #8) and re-entering the
`jax.enable_x64(True)` context around every call invalidates the jit
executable cache — each query would re-lower a multi-second program.

JAX config contexts are THREAD-LOCAL, so all f64 device work runs on one
dedicated worker thread that enters the context once and never leaves it.
Every other thread keeps 32-bit-native semantics; the executable cache
stays warm across queries.
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor

_pool: ThreadPoolExecutor | None = None
_pool_lock = threading.Lock()


def _enter_x64() -> None:
    import jax

    ctx = jax.enable_x64(True)
    ctx.__enter__()  # intentionally never exited: thread-local scope


def run_x64(fn, /, *args, **kwargs):
    """Run `fn` on the persistent x64 worker thread and return its result."""
    global _pool
    if _pool is None:
        with _pool_lock:
            if _pool is None:
                _pool = ThreadPoolExecutor(max_workers=1, initializer=_enter_x64)
    return _pool.submit(fn, *args, **kwargs).result()
