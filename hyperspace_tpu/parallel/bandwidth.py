"""Device→host transfer probe.

On a directly-attached TPU, PCIe readback runs at GB/s; through a
tunneled/remote device it can be tens of MB/s with ~100ms per-transfer
latency — 100x slower than host memory. Operators whose OUTPUT must land
on host (a materialized join's match pairs) pick their execution venue
by this number: below the threshold, computing on host beats shipping
results off the device. Probed once per process with a 4 MB transfer.
"""

from __future__ import annotations

import functools
import time


def pick_venue(
    requested: str,
    floor_mbps: float,
    prefer_device: bool,
    what: str,
    needs_native: bool = True,
) -> str:
    """Shared auto/device/host venue selection (join merge, build sort,
    aggregation reduce).

    `requested` other than auto forces the venue — forcing "host" without
    the native library (when the host path needs it) is an error, not a
    silent device fallback. `prefer_device` wins the auto case (e.g. a
    real multi-device mesh, where the distributed kernel is the point).
    `needs_native=False` marks host paths implemented in pure numpy.

    The HYPERSPACE_VENUE env var overrides every auto decision at once
    (explicit per-operator conf still wins) — the testing/ops escape
    hatch for exercising one venue across a whole run."""
    import os

    from hyperspace_tpu import native
    from hyperspace_tpu.exceptions import HyperspaceError

    forced_by_env = False
    if requested == "auto":
        env = os.environ.get("HYPERSPACE_VENUE", "")
        if env:
            if env not in ("device", "host"):
                raise HyperspaceError(
                    f"unknown HYPERSPACE_VENUE={env!r} (device|host)"
                )
            requested = env
            forced_by_env = True

    if requested == "host":
        if needs_native and not native.available():
            origin = "HYPERSPACE_VENUE" if forced_by_env else what
            raise HyperspaceError(
                f"{origin}=host requires the native library (g++ build failed "
                "or unavailable); use auto or device"
            )
        return "host"
    if requested == "device":
        return "device"
    if requested != "auto":
        raise HyperspaceError(f"unknown {what}={requested!r} (auto|device|host)")
    if prefer_device or (needs_native and not native.available()):
        return "device"
    return "host" if d2h_mb_per_s() < floor_mbps else "device"


# A measured link speed is a property of the deployment, not the
# process: persist it so short-lived runs (the point-lookup CLI shape)
# skip the ~0.3-1s probe entirely.
_PROBE_TTL_S = 24 * 3600.0


def _probe_cache_path():
    import os
    from pathlib import Path

    d = os.environ.get("HYPERSPACE_CACHE_DIR") or os.path.expanduser("~/.cache/hyperspace_tpu")
    return Path(d) / "bandwidth.json"


def _device_key() -> str:
    try:
        import jax

        dev = jax.devices()[0]
        return f"{dev.platform}:{getattr(dev, 'device_kind', '?')}"
    except Exception:
        return "unknown"


@functools.lru_cache(maxsize=1)
def d2h_mb_per_s() -> float:
    """Measured device→host bandwidth (MB/s), probed once per deployment
    (persisted with a TTL) rather than once per process."""
    import json

    import jax
    import jax.numpy as jnp
    import numpy as np

    key = _device_key()
    path = _probe_cache_path()
    try:
        data = json.loads(path.read_text())
        if not isinstance(data, dict):
            data = {}
    except Exception:
        data = {}
    try:
        # Missing/expired entry for THIS device must not discard other
        # devices' cached entries on the rewrite below. The persisted
        # stamp must be wall clock (monotonic() restarts per boot, and
        # the file outlives the process) — so guard the clock-step
        # hazard instead: a NEGATIVE age means the clock stepped
        # backwards past the stamp, and the entry is treated as expired
        # rather than living arbitrarily long.
        ts, mbps = data[key]
        age = time.time() - ts  # noqa: HSL007 — cross-process TTL, see above
        if 0.0 <= age < _PROBE_TTL_S:
            return float(mbps)
    except (KeyError, ValueError, TypeError):
        pass  # missing/corrupt cache entry: fall through to a fresh probe

    try:
        x = jnp.arange(1 << 20, dtype=jnp.uint32)  # 4 MB
        x.block_until_ready()
        t0 = time.perf_counter()
        np.asarray(jax.device_get(x))
        dt = time.perf_counter() - t0
        mbps = 4.0 / max(dt, 1e-9)
    except Exception:
        return float("inf")  # probe failure: assume fast, keep device path
    try:
        path.parent.mkdir(parents=True, exist_ok=True)
        data[key] = [time.time(), mbps]
        path.write_text(json.dumps(data))
    except OSError:
        pass  # unwritable cache dir: the probe result still returns
    return mbps
