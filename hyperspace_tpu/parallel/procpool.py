"""Spawn-context worker-process lifecycle, shared by the serving fleet
and the scale-out build.

Two layers:

- :class:`ProcessHost` — the primitive both planes ride: one ``spawn``
  multiprocessing context (a **fork of a jax-initialized parent is never
  safe** — XLA's runtime threads and locked allocator state do not
  survive fork, so every worker process in this package starts from a
  fresh interpreter), a shared stop event for cooperative drain, a
  keyed registry of named processes, and a stop() that signals, joins,
  and terminates stragglers. `serve/fleet/supervisor.py` layers its
  restart-budget monitor on top; the build layers :class:`TaskPool`.

- :class:`TaskPool` — one process per submitted task with a shared
  result queue, for the pooled index build (execution/builder.py). The
  coordinator's :meth:`TaskPool.join` is a **bounded join with a
  liveness check**: it polls the result queue, and when a worker is
  found dead without having posted its result (a real ``kill -9``, an
  OOM kill, or an injected :class:`~hyperspace_tpu.faults.CrashPoint`
  flying out of the worker), it raises a typed
  :class:`~hyperspace_tpu.exceptions.WorkerCrashed` instead of blocking
  forever on a queue that will never fill. A worker whose body raised
  an ``Exception`` posts the error (type, message, traceback text) and
  join re-raises it as :class:`~hyperspace_tpu.exceptions.WorkerFailed`.

Cross-process plumbing the build relies on:

- **fault injection** — the coordinator's registered
  :mod:`~hyperspace_tpu.faults` rules are shipped into each worker
  (fresh call/fire schedules, counted per process) and the worker's
  observed fault points are merged back on join, so the deterministic
  crash sweep sees through the process boundary;
- **tracing** — each worker's finished root span is shipped back as its
  ``to_json()`` dict and adopted into this process's recent-root ring
  and sink (:func:`~hyperspace_tpu.obs.trace.adopt_root`), so the
  chrome-trace export renders one lane per worker process.
"""

from __future__ import annotations

import threading
import time
import traceback

from hyperspace_tpu import faults, stats
from hyperspace_tpu.exceptions import WorkerCrashed, WorkerFailed
from hyperspace_tpu.obs import trace as obs_trace

_DEFAULT_POLL_S = 0.2
# How long a dead-without-result worker is given for an already-posted
# result to drain out of the queue's feeder pipe before the crash is
# declared (the post-then-exit race).
_CRASH_GRACE_S = 2.0


def spawn_context():
    """The one multiprocessing context this package spawns workers with.
    Always ``spawn``: forking a jax-initialized parent duplicates XLA
    runtime threads and locked allocator state into a child that then
    deadlocks or corrupts — every worker starts from a fresh
    interpreter instead."""
    import multiprocessing as mp

    return mp.get_context("spawn")


class ProcessHost:
    """Owns a spawn context, a shared stop event, and a keyed registry
    of worker processes (the lifecycle extracted from the fleet
    supervisor so the build pool and the fleet share one
    implementation)."""

    def __init__(self, name: str = "hs-procs"):
        self.name = name
        self._ctx = spawn_context()
        self.stop_event = self._ctx.Event()
        self._lock = threading.Lock()
        self._procs: dict = {}

    @property
    def ctx(self):
        return self._ctx

    def spawn(self, key, target, args: tuple = (), name: str | None = None):
        """Start (or replace) the worker registered under `key`."""
        p = self._ctx.Process(
            target=target, args=args, name=name or f"{self.name}-{key}"
        )
        p.start()
        with self._lock:
            self._procs[key] = p
        return p

    def get(self, key):
        with self._lock:
            return self._procs.get(key)

    def processes(self) -> dict:
        """Snapshot of the registry (key -> Process)."""
        with self._lock:
            return dict(self._procs)

    def alive_count(self) -> int:
        with self._lock:
            return sum(1 for p in self._procs.values() if p.is_alive())

    def stop(self, timeout: float = 30.0, grace: float = 5.0) -> None:
        """Cooperative drain: set the stop event, join with `timeout`,
        terminate stragglers (and join those with `grace`). Idempotent."""
        self.stop_event.set()
        procs = list(self.processes().values())
        for p in procs:
            p.join(timeout=timeout)
        for p in procs:
            if p.is_alive():
                p.terminate()
                p.join(timeout=grace)

    def terminate(self, key, grace: float = 5.0) -> bool:
        """Stop and deregister ONE worker (the supervisor's scale-down
        path): drop it from the registry first — so a concurrent monitor
        pass cannot respawn it — then terminate if still alive and join
        with `grace`. Returns True when a process was registered under
        `key`."""
        with self._lock:
            p = self._procs.pop(key, None)
        if p is None:
            return False
        if p.is_alive():
            p.terminate()
        p.join(timeout=grace)
        return True


def _task_entry(result_q, task_id, fn, args, env) -> None:
    """Module-level worker entry (spawn needs a picklable top-level
    callable): install the coordinator's shipped fault rules, run the
    task body, and post exactly one (task_id, ok, envelope) record. A
    CrashPoint (BaseException) deliberately falls through — the process
    dies without posting, exactly like a real ``kill -9``, and the
    coordinator's liveness check converts that into a typed abort."""
    fstate = env.get("faults")
    if fstate is not None:
        faults.install_state(fstate)
    obs_trace.set_enabled(bool(env.get("obs_enabled", True)))
    jstate = env.get("journal")
    if jstate is not None:
        from hyperspace_tpu.obs import journal as obs_journal

        obs_journal.install_state(jstate)
    try:
        result = fn(*args)
        root = obs_trace.last_trace()
        result_q.put((task_id, True, {
            "result": result,
            "observed": sorted(faults.observed_points()),
            "trace": root.to_json() if root is not None else None,
        }))
    except Exception as e:  # noqa: HSL017 — process-boundary error shipping:
        # the exception (injected FaultError included) is not absorbed, it
        # is posted with its full traceback and re-raised in the
        # coordinator as a typed WorkerFailed (TaskPool.join) — proven by
        # tests/test_procpool.py::test_posted_error_reraises_typed.
        result_q.put((task_id, False, {
            "type": type(e).__name__,
            "message": str(e),
            "traceback": traceback.format_exc(),
            "observed": sorted(faults.observed_points()),
        }))


class TaskPool:
    """One spawn-context process per submitted task, joined with a
    liveness check. Use as a context manager: exit terminates any
    still-running workers (the error path's cleanup)."""

    def __init__(self, name: str = "hs-build", poll_s: float = _DEFAULT_POLL_S,
                 crash_grace_s: float = _CRASH_GRACE_S):
        self._host = ProcessHost(name)
        self._q = self._host.ctx.Queue()
        self._poll_s = float(poll_s)
        self._crash_grace_s = float(crash_grace_s)
        self._pending: dict = {}

    @property
    def host(self) -> ProcessHost:
        return self._host

    def submit(self, task_id, fn, *args) -> None:
        """Spawn one worker running ``fn(*args)``; its return value comes
        back from :meth:`join`. The coordinator's fault-injection state
        and tracer enablement ship along."""
        from hyperspace_tpu.obs import journal as obs_journal

        env = {
            "faults": faults.export_state(),
            "obs_enabled": obs_trace.enabled(),
            "journal": obs_journal.export_state(),
        }
        p = self._host.spawn(task_id, _task_entry, (self._q, task_id, fn, args, env))
        self._pending[task_id] = p

    def join(self, timeout: float | None = None) -> dict:
        """Collect every submitted task's result (task_id -> result).

        Bounded: polls the result queue and, between polls, checks every
        outstanding worker's liveness — a worker dead without a posted
        result raises :class:`WorkerCrashed` (after a short grace for
        the post-then-exit race) instead of hanging the coordinator; a
        posted worker error re-raises as :class:`WorkerFailed` with the
        worker's traceback. `timeout` additionally bounds the whole
        join."""
        import queue as _qmod

        results: dict = {}
        deadline = (time.monotonic() + timeout) if timeout is not None else None
        dead_since: dict = {}
        while self._pending:
            try:
                task_id, ok, envelope = self._q.get(timeout=self._poll_s)
            except _qmod.Empty:
                now = time.monotonic()
                for tid, p in list(self._pending.items()):
                    if p.is_alive():
                        dead_since.pop(tid, None)
                        continue
                    first = dead_since.setdefault(tid, now)
                    if now - first >= self._crash_grace_s:
                        stats.increment("build.worker.crashes")
                        raise WorkerCrashed(
                            f"worker {tid!r} died (exitcode {p.exitcode}) without "
                            f"posting a result — build aborted",
                            task_id=tid, exitcode=p.exitcode,
                        )
                if deadline is not None and now > deadline:
                    raise WorkerCrashed(
                        f"worker pool join timed out after {timeout}s with "
                        f"{len(self._pending)} task(s) outstanding: "
                        f"{sorted(self._pending)}"
                    )
                continue
            self._pending.pop(task_id, None)
            dead_since.pop(task_id, None)
            faults.merge_observed(envelope.get("observed") or ())
            root = envelope.get("trace")
            if ok and root:
                obs_trace.adopt_root(root)
            if not ok:
                raise WorkerFailed(
                    f"worker {task_id!r} failed with {envelope.get('type')}: "
                    f"{envelope.get('message')}\n--- worker traceback ---\n"
                    f"{envelope.get('traceback')}",
                    task_id=task_id, error_type=envelope.get("type"),
                )
            results[task_id] = envelope.get("result")
        for p in self._host.processes().values():
            p.join(timeout=5.0)
        return results

    def __enter__(self) -> "TaskPool":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        # Error-path cleanup: workers still running after a crash abort
        # are torn down so the build's finally (exchange-dir sweep) never
        # races live writers.
        self._host.stop(timeout=0.5, grace=2.0)
        self._q.close()
        return False
