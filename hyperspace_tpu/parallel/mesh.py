"""Device mesh/topology layer.

SURVEY.md §2.3 names this a first-class component for the TPU build: the
analog of "N shuffle partitions over a Spark cluster" is "N buckets sharded
over a device mesh". The inner axis ("x") spans the chips of one slice —
build-time bucketize rides ICI via all_to_all over it; query-time
bucket-aligned ops need no collective at all. Multi-slice deployments add
an outer "dcn" axis (make_multislice_mesh): the exchange then runs over
the combined (dcn, x) axes and XLA routes the inter-slice portion over
DCN. Bucket ownership stays contiguous in flattened mesh order either way,
so the carve/query planes are mesh-shape agnostic.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

AXIS = "x"
DCN_AXIS = "dcn"


def mesh_axes(mesh: Mesh) -> tuple:
    """The mesh's data axes, innermost last ((x,) or (dcn, x))."""
    return tuple(mesh.axis_names)


def mesh_size(mesh: Mesh) -> int:
    out = 1
    for name in mesh.axis_names:
        out *= mesh.shape[name]
    return out

_cache_enabled = False


def enable_compile_cache() -> None:
    """Turn on XLA's persistent compilation cache. The build pipeline's
    exchange+sort program takes tens of seconds to compile on TPU; caching
    it on disk makes every process after the first start hot."""
    global _cache_enabled
    if _cache_enabled:
        return
    import os

    cache_dir = os.environ.get(
        "HYPERSPACE_TPU_COMPILE_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "hyperspace_tpu", "xla"),
    )
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # noqa: BLE001,HSL017 — cache is an optimization, never fatal; nothing to repair or surface
        pass
    _cache_enabled = True


def make_mesh(devices=None, n: int | None = None) -> Mesh:
    devices = list(jax.devices()) if devices is None else list(devices)
    if n is not None:
        devices = devices[:n]
    return Mesh(np.array(devices), (AXIS,))


def make_multislice_mesh(num_slices: int, devices=None) -> Mesh:
    """2-D (dcn, x) mesh: outer axis spans slices (DCN), inner axis the
    chips within a slice (ICI)."""
    devices = list(jax.devices()) if devices is None else list(devices)
    if num_slices < 1 or len(devices) % num_slices != 0:
        raise ValueError(
            f"{len(devices)} devices do not split into {num_slices} equal slices"
        )
    per = len(devices) // num_slices
    return Mesh(np.array(devices).reshape(num_slices, per), (DCN_AXIS, AXIS))


def default_mesh() -> Mesh:
    return make_mesh()


def mesh_for_parallelism(mesh: Mesh | None, n_units: int) -> Mesh:
    """The largest prefix of `mesh` (flattened order) whose size divides
    `n_units`, so contiguous ownership of units (buckets) is exact. Used by
    both the build and the distributed query plane."""
    mesh = mesh if mesh is not None else make_mesh()
    d = mesh_size(mesh)
    if n_units % d == 0:
        return mesh
    while n_units % d != 0:
        d -= 1
    return make_mesh(list(mesh.devices.flat), n=d)
