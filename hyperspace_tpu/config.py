"""Framework configuration.

Reference parity: index/IndexConstants.scala:21-49 — all tunables live under
string keys with defaults, resolved at use-sites. Here they are a typed
dataclass attached to the session (there is no SparkSession / SQLConf to
piggyback on), plus the same string-keyed override map so tests and callers
can set individual knobs.
"""

from __future__ import annotations

import dataclasses
import difflib
import os
from typing import Any

from hyperspace_tpu.exceptions import UnknownConfigKeyError

# String keys (kept spiritually compatible with spark.hyperspace.* keys,
# reference index/IndexConstants.scala:21-49).
INDEX_SYSTEM_PATH = "hyperspace.system.path"
INDEX_NUM_BUCKETS = "hyperspace.index.num.buckets"
INDEX_CACHE_EXPIRY_SECONDS = "hyperspace.index.cache.expiryDurationInSeconds"
INDEX_HYBRID_SCAN_ENABLED = "hyperspace.index.hybridscan.enabled"
# Hybrid scan only applies while appended bytes stay below this fraction of
# the indexed source (past it, scanning deltas unindexed beats the index).
INDEX_HYBRID_SCAN_MAX_APPENDED_RATIO = "hyperspace.index.hybridscan.maxAppendedRatio"
# Out-of-core build: sources whose uncompressed estimate exceeds the memory
# budget stream through row-group chunks of at most chunkBytes (0 = derive
# from the budget).
INDEX_BUILD_MEMORY_BUDGET = "hyperspace.index.build.memoryBudgetBytes"
INDEX_BUILD_CHUNK_BYTES = "hyperspace.index.build.chunkBytes"
# Materialized-join execution venue: "auto" picks the host-native merge
# kernel when measured device->host bandwidth is below joinVenueMinMbps
# (the match pairs land on host either way; on tunneled devices the
# readback dominates), else the device kernel. "device"/"host" force it.
JOIN_VENUE = "hyperspace.join.venue"
JOIN_VENUE_MIN_MBPS = "hyperspace.join.venueMinMbps"
# Build sort venue: same auto/device/host scheme for the bucketize+sort
# permutation (its only output lands on host).
BUILD_VENUE = "hyperspace.build.venue"
# Streaming-build pipeline (docs/architecture.md "build pipeline"): when
# enabled, p1 overlaps decode/hash with pooled spill encode and spilled
# buckets flow through a 3-stage p2 pipeline (spill read ‖ key sort ‖
# final write) behind a bounded bucket-completion queue, instead of the
# serial two-phase build. maxInflightBytes bounds the decoded bucket
# bytes resident across the p2 stages (0 = derive 4x chunkBytes).
BUILD_PIPELINE_ENABLED = "hyperspace.build.pipeline.enabled"
BUILD_PIPELINE_MAX_INFLIGHT_BYTES = "hyperspace.build.pipeline.maxInflightBytes"
# Scale-out pooled build (docs/architecture.md "scale-out build"): N
# spawn-context worker PROCESSES split the build by bucket id → owner,
# exchanging rows through per-owner spill files. 0 (the default) keeps
# the in-process build paths exactly as they are.
BUILD_WORKERS = "hyperspace.build.workers"
BUILD_EXCHANGE_DIR = "hyperspace.build.exchange.dir"
# Query-tail prefetch: while the optimizer still runs, footers (and the
# first row-group chunk) of the index bucket files the pruner keeps are
# fetched on a background pool, so scan-bound queries stop paying serial
# cold reads. Purely advisory — prefetch failures never fail a query.
SCAN_PREFETCH_ENABLED = "hyperspace.scan.prefetch.enabled"
AGG_VENUE = "hyperspace.agg.venue"
SORT_VENUE = "hyperspace.sort.venue"
FILTER_VENUE = "hyperspace.filter.venue"
# Device data path (docs/architecture.md "device data path").
# staging.enabled gates the Arrow→device zero-copy staging layer
# (execution/staging.py): eligible fixed-width columns stay read-only
# views over the Arrow buffers on the cache-destined read path instead
# of owned host copies (process-global, like the faults/obs switches —
# the decode path has no session handle). fusedKernels gates the Pallas
# fused kernels (segment reduce, join-agg run bounds): "auto" engages
# them on the device venue when the shape is eligible AND exactness is
# provable, with the jitted lax path as the always-available fallback;
# "off" keeps the lax path everywhere.
DEVICE_STAGING_ENABLED = "hyperspace.device.staging.enabled"
DEVICE_FUSED_KERNELS = "hyperspace.device.fusedKernels"
# Broadcast hash join: a non-aligned join whose smaller side has at most
# this many rows (and is at least 4x smaller than the other) probes the
# large side against the sorted small side instead of sorting both for a
# merge (the analog of Spark's BroadcastExchange fallback the reference
# environment counts, PhysicalOperatorAnalyzer.scala:46-50). 0 disables.
JOIN_BROADCAST_MAX_ROWS = "hyperspace.join.broadcast.maxRows"
# Query-time re-bucketing exchange: when exactly one join side is an index
# bucketed on its join keys, the OTHER side can re-bucketize on the fly
# (hash + counting sort / device sort) so the merge stays bucket-parallel.
# "auto" engages it when the broadcast probe does not apply; "force"
# always re-bucketizes (bucket-aligned evidence for chained star joins);
# "off" keeps the single-partition fallback.
JOIN_REBUCKETIZE = "hyperspace.join.rebucketize"
# Pre-execution plan validation (analysis/validator.py): reject malformed
# plans with structured diagnostics before any device work. On by default;
# the switch exists for benchmarking the (small) walk cost away.
ANALYSIS_VALIDATE = "hyperspace.analysis.validate"
# Fault-tolerance plane (docs/fault_tolerance.md). faults.enabled is the
# injection-harness kill switch (False ⇒ fault_point is inert even with
# rules registered — a production config can never inject). retry.* tune
# the transient-IO retry layer (utils/retry.py; maxAttempts=1 disables).
# fallback.enabled gates the query plane's corruption fallback: a query
# whose index data turns out unreadable re-plans against the source
# instead of failing. recover.onAccess makes index listing lazily repair
# a crashed writer's transient log (after graceSeconds of staleness).
FAULTS_ENABLED = "hyperspace.faults.enabled"
FAULTS_MAX_DELAY_SECONDS = "hyperspace.faults.maxDelaySeconds"
# Observability plane (docs/observability.md). obs.enabled gates the
# tracer: False makes span()/trace() return shared no-op singletons (no
# allocation on the query hot path); per-query profiles remain available
# either way (they ride the executed physical plan). obs.sink is a
# JSON-lines path receiving one event per finished root trace — the
# export feed (`python -m hyperspace_tpu.obs.export --sink <path>`).
OBS_ENABLED = "hyperspace.obs.enabled"
OBS_SINK = "hyperspace.obs.sink"
# Runtime health plane (docs/observability.md "live endpoints"): an
# opt-in stdlib HTTP server exposing /metrics (Prometheus text),
# /healthz (index health + scheduler saturation + SLO burn verdict),
# /debug/events, and /debug/trace. Started/stopped with the QueryServer
# lifecycle; port 0 binds an ephemeral port (read it back from
# `server.health_endpoint.port`). Off by default: no thread, no socket.
OBS_HTTP_ENABLED = "hyperspace.obs.http.enabled"
OBS_HTTP_HOST = "hyperspace.obs.http.host"
OBS_HTTP_PORT = "hyperspace.obs.http.port"
# Bounded structured-event ring (obs/events.py) — process-global, like
# the metrics registry it complements.
OBS_EVENTS_MAX = "hyperspace.obs.events.maxEvents"
# Declared SLO objectives (obs/slo.py): availability target of admitted
# queries, and the latency threshold the p99 objective holds serves to.
OBS_SLO_AVAILABILITY_TARGET = "hyperspace.obs.slo.availabilityTarget"
OBS_SLO_LATENCY_P99_SECONDS = "hyperspace.obs.slo.latencyP99Seconds"
# Durable telemetry journal (obs/journal.py, docs/observability.md
# "telemetry journal"): a bounded, segment-rotated JSONL journal per
# process under `<dir>/<pid>/` (dir defaults to `<system.path>/_obs`),
# fed by the event ring, completed root spans, periodic metric
# snapshots and SLO verdict transitions. Advisory and off by default —
# one boolean read per tap when disabled.
OBS_JOURNAL_ENABLED = "hyperspace.obs.journal.enabled"
OBS_JOURNAL_DIR = "hyperspace.obs.journal.dir"
OBS_JOURNAL_SEGMENT_BYTES = "hyperspace.obs.journal.segmentBytes"
OBS_JOURNAL_MAX_BYTES = "hyperspace.obs.journal.maxBytes"
OBS_JOURNAL_SNAPSHOT_SECONDS = "hyperspace.obs.journal.snapshotSeconds"
# Concurrent query-serving plane (docs/serving.md). The subsystem is OFF
# by default: nothing changes for direct `session.run()` callers; a
# QueryServer is constructed explicitly (or via `session.serve()`) and
# reads these knobs as its defaults. workers bounds the executor pool;
# maxQueueDepth is the admission-control limit (submits beyond it raise
# AdmissionRejected); queryTimeoutSeconds (0 = none) expires queries
# still queued (and bounds result() waits). The plan cache memoizes
# optimized plans per (plan signature, data fingerprint, index log
# versions); the result cache is opt-in and byte-bounded.
SERVE_WORKERS = "hyperspace.serve.workers"
SERVE_MAX_QUEUE_DEPTH = "hyperspace.serve.maxQueueDepth"
SERVE_QUERY_TIMEOUT_SECONDS = "hyperspace.serve.queryTimeoutSeconds"
SERVE_PLAN_CACHE_ENABLED = "hyperspace.serve.planCache.enabled"
SERVE_PLAN_CACHE_MAX_ENTRIES = "hyperspace.serve.planCache.maxEntries"
SERVE_RESULT_CACHE_ENABLED = "hyperspace.serve.resultCache.enabled"
SERVE_RESULT_CACHE_MAX_BYTES = "hyperspace.serve.resultCache.maxBytes"
# Per-tenant admission quotas + graceful saturation (serve/fleet/quota.py,
# docs/serving.md "fleet topology"). Token-bucket admission per tenant id
# (submits carrying a tenant bounce with QuotaExceeded once the bucket is
# dry); shedDepthRatio sheds NON-priority submits once the queue reaches
# that fraction of maxQueueDepth, so the priority lane keeps a bounded
# p99 while the server saturates instead of collapsing.
SERVE_TENANT_QUOTA_ENABLED = "hyperspace.serve.tenant.quota.enabled"
SERVE_TENANT_QUOTA_RATE = "hyperspace.serve.tenant.quota.ratePerSecond"
SERVE_TENANT_QUOTA_BURST = "hyperspace.serve.tenant.quota.burst"
SERVE_SHED_DEPTH_RATIO = "hyperspace.serve.shedDepthRatio"
# Multi-process serving fleet (serve/fleet/, docs/serving.md "fleet
# topology"): N QueryServer processes over one index store share a
# disk-backed plan/result cache under the SAME versioned keys the
# in-process caches use (any process's index mutation structurally
# invalidates every process's entries), dedup cold builds through a
# lease-file single-flight protocol, and are spawned/monitored/restarted
# by a FleetSupervisor.
FLEET_CACHE_DIR = "hyperspace.fleet.cache.dir"
FLEET_CACHE_MAX_BYTES = "hyperspace.fleet.cache.maxBytes"
FLEET_LEASE_SECONDS = "hyperspace.fleet.lease.seconds"
FLEET_SINGLEFLIGHT_WAIT_SECONDS = "hyperspace.fleet.singleflight.waitSeconds"
FLEET_WORKERS = "hyperspace.fleet.workers"
FLEET_MIN_WORKERS = "hyperspace.fleet.minWorkers"
FLEET_MAX_RESTARTS = "hyperspace.fleet.maxRestarts"
FLEET_RESTART_BACKOFF_SECONDS = "hyperspace.fleet.restartBackoffSeconds"
# Self-driving operations controller (serve/controller.py,
# docs/fault_tolerance.md "self-driving operations"): a reconciliation
# loop consuming SLO burn verdicts + the structured event ring and
# actuating ONLY through the existing crash-safe protocols — shed
# load / tighten tenant quotas while serve SLOs page, heal quarantined
# indexes via recover() + rebuild, trigger an advisor sweep when
# routing demotions cluster, and back off background work while SLOs
# burn. Kill switch `hyperspace.controller.enabled` defaults OFF: the
# controller observes nothing and touches nothing unless an operator
# opts in. hysteresisTicks/recoveryTicks + cooldownSeconds prevent
# actuation flapping across verdict flicker; actuationBudget bounds
# total mutations per controller lifetime (exhaustion degrades to
# observe-only + ERROR event, releases stay free so the system is
# always left as found).
CONTROLLER_ENABLED = "hyperspace.controller.enabled"
CONTROLLER_INTERVAL_SECONDS = "hyperspace.controller.intervalSeconds"
CONTROLLER_COOLDOWN_SECONDS = "hyperspace.controller.cooldownSeconds"
CONTROLLER_HYSTERESIS_TICKS = "hyperspace.controller.hysteresisTicks"
CONTROLLER_RECOVERY_TICKS = "hyperspace.controller.recoveryTicks"
CONTROLLER_ACTUATION_BUDGET = "hyperspace.controller.actuationBudget"
CONTROLLER_SHED_RATIO = "hyperspace.controller.shedRatio"
CONTROLLER_QUOTA_FACTOR = "hyperspace.controller.quotaFactor"
CONTROLLER_HEAL_REBUILD = "hyperspace.controller.heal.rebuild"
CONTROLLER_DEMOTION_CLUSTER_SIZE = "hyperspace.controller.demotionClusterSize"
CONTROLLER_DEMOTION_WINDOW_SECONDS = "hyperspace.controller.demotionWindowSeconds"
# Fleet-coordinated operations (docs/fault_tolerance.md "fleet
# coordination"): heal.coordinate routes heal actuations through the
# fleet single-flight lease so exactly one member rebuilds a quarantined
# index fleet-wide; scale.* drive the supervisor's member count up on
# sustained fleet-health saturation (and back to the pre-episode
# baseline on recovery); stormResponse turns jit.recompile_storm events
# into an actuated response (raw-route pin + one audited cache drop)
# instead of observed-only telemetry.
CONTROLLER_HEAL_COORDINATE = "hyperspace.controller.heal.coordinate"
CONTROLLER_SCALE_SATURATION = "hyperspace.controller.scale.saturation"
CONTROLLER_SCALE_MAX_WORKERS = "hyperspace.controller.scale.maxWorkers"
CONTROLLER_SCALE_STEP = "hyperspace.controller.scale.step"
CONTROLLER_STORM_RESPONSE = "hyperspace.controller.stormResponse"
# Incident bundles (docs/fault_tolerance.md "incident bundles"): on an
# SLO page engage, a fresh quarantine, or observe-only entry the
# controller snapshots a content-complete forensic bundle under
# `<dir>/<ts>-<trigger>/` (dir defaults to `<fleet root>/incidents`) —
# journal segments from every reachable member, event ring dump, jit
# report, config snapshot, routing ledger, and the actuation audit
# trail. Advisory (capture failures never compound the incident),
# rate-limited by the controller cooldown, retained newest-first up to
# maxBundles.
CONTROLLER_INCIDENT_ENABLED = "hyperspace.controller.incident.enabled"
CONTROLLER_INCIDENT_DIR = "hyperspace.controller.incident.dir"
CONTROLLER_INCIDENT_MAX_BUNDLES = "hyperspace.controller.incident.maxBundles"
CONTROLLER_INCIDENT_SEGMENTS = "hyperspace.controller.incident.segments"
RETRY_MAX_ATTEMPTS = "hyperspace.retry.maxAttempts"
RETRY_BACKOFF_BASE = "hyperspace.retry.backoffBaseSeconds"
RETRY_CAS_ATTEMPTS = "hyperspace.retry.casAttempts"
FALLBACK_ENABLED = "hyperspace.fallback.enabled"
RECOVER_ON_ACCESS = "hyperspace.recover.onAccess"
RECOVER_GRACE_SECONDS = "hyperspace.recover.graceSeconds"
# Workload-driven index advisor (docs/advisor.md). routing.* gate the
# adaptive query router: a per-plan-signature ledger of measured indexed
# vs raw wall times that demotes rewrites which measured slower
# (advisor/routing.py) — off by default because it changes plan choice.
# workload.maxRecords bounds the in-memory workload ring the what-if
# analyzer learns from. lifecycle.* gate the autonomous policy engine
# (advisor/lifecycle.py): all three default off — the advisor observes
# by default and acts only on explicit opt-in; minConfidence /
# minBenefitSeconds are the evidence floor any auto-applied
# recommendation must clear; lifecycle.maxDeltas is the fragmentation
# threshold past which an optimize recommendation fires.
ADVISOR_ROUTING_ENABLED = "hyperspace.advisor.routing.enabled"
ADVISOR_ROUTING_DEMOTE_RATIO = "hyperspace.advisor.routing.demoteRatio"
ADVISOR_ROUTING_ALPHA = "hyperspace.advisor.routing.alpha"
ADVISOR_ROUTING_MIN_SAMPLES = "hyperspace.advisor.routing.minSamples"
ADVISOR_WORKLOAD_MAX_RECORDS = "hyperspace.advisor.workload.maxRecords"
ADVISOR_AUTO_CREATE = "hyperspace.advisor.lifecycle.autoCreate"
ADVISOR_AUTO_VACUUM = "hyperspace.advisor.lifecycle.autoVacuum"
ADVISOR_AUTO_OPTIMIZE = "hyperspace.advisor.lifecycle.autoOptimize"
ADVISOR_LIFECYCLE_MAX_DELTAS = "hyperspace.advisor.lifecycle.maxDeltas"
ADVISOR_MIN_CONFIDENCE = "hyperspace.advisor.minConfidence"
ADVISOR_MIN_BENEFIT_SECONDS = "hyperspace.advisor.minBenefitSeconds"
# Explain rendering (explain/display_mode.py re-exports these; declared
# here so every hyperspace.* key lives in ONE registry — HSL010).
EXPLAIN_DISPLAY_MODE = "hyperspace.explain.displayMode"
EXPLAIN_HIGHLIGHT_BEGIN = "hyperspace.explain.displayMode.highlight.beginTag"
EXPLAIN_HIGHLIGHT_END = "hyperspace.explain.displayMode.highlight.endTag"
# Continuous-ingestion daemon (hyperspace_tpu/ingest/, docs/ingestion.md):
# a background service that turns refresh from an operator action into a
# poll loop — source watchers (new-file arrival + appended-row CDC
# batches) feed micro-batch incremental refreshes through the unchanged
# two-phase Action protocol, with advisor-gated compaction once delta
# fragmentation passes `hyperspace.advisor.lifecycle.maxDeltas`.
# enabled defaults OFF (nothing polls, nothing mutates without opt-in);
# pollSeconds is the tailer cadence; cdcBatchRows bounds the rows one
# materialized CDC batch file carries; autoCompact gates the compaction
# step (the advisor lifecycle gates still apply on top); processWorker
# moves the loop into a spawn-context worker process
# (parallel/procpool.py) instead of the default in-process thread;
# maxLagSeconds is the advisory freshness objective past which the
# daemon emits `ingest.lagging`.
INGEST_ENABLED = "hyperspace.ingest.enabled"
INGEST_POLL_SECONDS = "hyperspace.ingest.pollSeconds"
INGEST_CDC_BATCH_ROWS = "hyperspace.ingest.cdcBatchRows"
INGEST_AUTO_COMPACT = "hyperspace.ingest.autoCompact"
INGEST_PROCESS_WORKER = "hyperspace.ingest.processWorker"
INGEST_MAX_LAG_SECONDS = "hyperspace.ingest.maxLagSeconds"

# Directory-layout constants (reference index/IndexConstants.scala:38-39).
HYPERSPACE_LOG_DIR = "_hyperspace_log"
DATA_VERSION_PREFIX = "v__="
LATEST_STABLE_LOG_NAME = "latestStable"

DEFAULT_NUM_BUCKETS = 8
DEFAULT_CACHE_EXPIRY_SECONDS = 300.0
DEFAULT_HYBRID_SCAN_MAX_APPENDED_RATIO = 0.3
DEFAULT_BUILD_MEMORY_BUDGET = 4 << 30
DEFAULT_JOIN_VENUE = "auto"
DEFAULT_JOIN_VENUE_MIN_MBPS = 200.0
DEFAULT_JOIN_BROADCAST_MAX_ROWS = 4_000_000
DEFAULT_JOIN_REBUCKETIZE = "auto"
# Lazy recovery leaves a transient log alone until it is at least this
# stale (entry timestamp), so listing indexes cannot cancel a LIVE
# concurrent writer's in-flight action. Explicit recover() ignores it.
DEFAULT_RECOVER_GRACE_SECONDS = 300.0
DEFAULT_SERVE_WORKERS = 4
DEFAULT_SERVE_MAX_QUEUE_DEPTH = 32
DEFAULT_SERVE_PLAN_CACHE_MAX_ENTRIES = 128
DEFAULT_SERVE_RESULT_CACHE_MAX_BYTES = 256 << 20
DEFAULT_ADVISOR_ROUTING_DEMOTE_RATIO = 1.0
DEFAULT_ADVISOR_ROUTING_ALPHA = 0.5
DEFAULT_ADVISOR_ROUTING_MIN_SAMPLES = 1
DEFAULT_ADVISOR_WORKLOAD_MAX_RECORDS = 512
DEFAULT_ADVISOR_LIFECYCLE_MAX_DELTAS = 4
DEFAULT_ADVISOR_MIN_CONFIDENCE = 0.5
DEFAULT_SERVE_TENANT_QUOTA_RATE = 100.0
DEFAULT_SERVE_TENANT_QUOTA_BURST = 200
DEFAULT_SERVE_SHED_DEPTH_RATIO = 1.0
DEFAULT_FLEET_CACHE_MAX_BYTES = 1 << 30
DEFAULT_FLEET_LEASE_SECONDS = 10.0
DEFAULT_FLEET_SINGLEFLIGHT_WAIT_SECONDS = 15.0
DEFAULT_FLEET_WORKERS = 2
DEFAULT_FLEET_MIN_WORKERS = 1
DEFAULT_FLEET_MAX_RESTARTS = 3
DEFAULT_FLEET_RESTART_BACKOFF_SECONDS = 0.5
DEFAULT_FAULTS_MAX_DELAY_SECONDS = 30.0
DEFAULT_CONTROLLER_INTERVAL_SECONDS = 1.0
DEFAULT_CONTROLLER_COOLDOWN_SECONDS = 30.0
DEFAULT_CONTROLLER_HYSTERESIS_TICKS = 2
DEFAULT_CONTROLLER_RECOVERY_TICKS = 2
DEFAULT_CONTROLLER_ACTUATION_BUDGET = 32
DEFAULT_CONTROLLER_SHED_RATIO = 0.5
DEFAULT_CONTROLLER_QUOTA_FACTOR = 0.5
DEFAULT_CONTROLLER_DEMOTION_CLUSTER_SIZE = 3
DEFAULT_CONTROLLER_DEMOTION_WINDOW_SECONDS = 300.0
DEFAULT_CONTROLLER_SCALE_SATURATION = 0.75
DEFAULT_CONTROLLER_SCALE_MAX_WORKERS = 8
DEFAULT_CONTROLLER_SCALE_STEP = 1
DEFAULT_OBS_JOURNAL_SEGMENT_BYTES = 64 << 10
DEFAULT_OBS_JOURNAL_MAX_BYTES = 4 << 20
DEFAULT_OBS_JOURNAL_SNAPSHOT_SECONDS = 5.0
DEFAULT_CONTROLLER_INCIDENT_MAX_BUNDLES = 16
DEFAULT_CONTROLLER_INCIDENT_SEGMENTS = 4
DEFAULT_INGEST_POLL_SECONDS = 1.0
DEFAULT_INGEST_CDC_BATCH_ROWS = 65536
DEFAULT_INGEST_MAX_LAG_SECONDS = 30.0


@dataclasses.dataclass(frozen=True)
class ConfKey:
    """One declared config key: its rendered default and its one-line
    doc. docs/configuration.md's key table is GENERATED from this
    registry (analysis/check.py verifies it; --write-config-docs
    rewrites it), so the docs cannot drift from the code."""

    default: str
    doc: str


# The declared-key registry — the config analog of stats.KNOWN_COUNTERS
# and faults.KNOWN_POINTS. `HyperspaceConf.get/set` REJECT any
# hyperspace.* key not declared here (UnknownConfigKeyError, with a
# did-you-mean suggestion), and static rule HSL010 checks every call
# site against it before runtime. Keep this a plain dict literal keyed
# by the constants above: the analysis engine reads it by AST parse, no
# imports (the CI check job runs dependency-free).
KNOWN_KEYS: dict[str, ConfKey] = {
    INDEX_SYSTEM_PATH: ConfKey(
        "`<cwd>/spark-warehouse/indexes`",
        "Root directory holding every index (log + data versions)."),
    INDEX_NUM_BUCKETS: ConfKey(
        "8",
        "Bucket count for new covering indexes (= build/query parallelism; the "
        "analog of `spark.hyperspace.index.num.buckets`)."),
    INDEX_CACHE_EXPIRY_SECONDS: ConfKey(
        "300",
        "TTL of the read-path metadata cache; every mutating API clears it."),
    INDEX_HYBRID_SCAN_ENABLED: ConfKey(
        "false",
        "Serve stale indexes by unioning the index scan with a pinned scan of "
        "appended files."),
    INDEX_HYBRID_SCAN_MAX_APPENDED_RATIO: ConfKey(
        "0.3",
        "Hybrid scan applies only while appended bytes stay below this fraction "
        "of the indexed source."),
    INDEX_BUILD_MEMORY_BUDGET: ConfKey(
        "4 GiB",
        "Sources whose uncompressed footer estimate exceeds this stream through "
        "the out-of-core build."),
    INDEX_BUILD_CHUNK_BYTES: ConfKey(
        "0 (derived)",
        "Row-group chunk size of the streaming build; 0 derives it from the "
        "budget."),
    JOIN_VENUE: ConfKey(
        "`auto`",
        "Where the materialized join's merge runs: `auto` probes device→host "
        "bandwidth once and picks `host` (threaded C++ kernel) below the floor, "
        "else `device`; `host`/`device` force it (unknown values raise)."),
    JOIN_VENUE_MIN_MBPS: ConfKey(
        "200",
        "The link-speed floor shared by every `auto` venue choice (join, build, "
        "aggregation, sort): below it, host paths win."),
    BUILD_VENUE: ConfKey(
        "`auto`",
        "Where the build's bucketize+sort permutation is computed: threaded C++ "
        "counting/key sort on host vs the device all_to_all exchange (a real "
        "multi-device mesh keeps device in `auto`)."),
    BUILD_PIPELINE_ENABLED: ConfKey(
        "true",
        "Streaming-build pipeline: overlap p1 decode/hash with pooled spill "
        "encode, and run p2 as a 3-stage spill-read ‖ key-sort ‖ final-write "
        "pipeline behind a bounded bucket-completion queue. `false` restores "
        "the serial two-phase build (the byte-for-byte reference path)."),
    BUILD_PIPELINE_MAX_INFLIGHT_BYTES: ConfKey(
        "0 (derived)",
        "Byte budget of decoded spill buckets resident across the p2 pipeline "
        "stages (the memory bound on small hosts); 0 derives 4x "
        "`hyperspace.index.build.chunkBytes`. A single bucket above the budget "
        "is still admitted alone. The pooled build derives each p2 owner's "
        "one-ahead spill-read window from the same budget."),
    BUILD_WORKERS: ConfKey(
        "0 (in-process)",
        "Scale-out pooled build: split the build across this many spawn-context "
        "worker processes — p1 shards each decode a contiguous file slice and "
        "spill per destination bucket-owner, p2 owners sort/encode/write their "
        "buckets in parallel (bucket id → owner is the shard key, the analogue "
        "of Spark's hash shuffle), byte-identical to the in-process streaming "
        "build. 0 keeps the in-process paths."),
    BUILD_EXCHANGE_DIR: ConfKey(
        "`` (derived)",
        "Root of the pooled build's cross-process spill exchange; empty derives "
        "`<dest>.exchange` next to the index version dir (same filesystem as "
        "the output). Always swept when the build ends, success or abort."),
    SCAN_PREFETCH_ENABLED: ConfKey(
        "true",
        "Async index bucket-file prefetch at plan-optimize time: footers (and "
        "the first row-group chunk) of the files the pruner keeps are read on "
        "a background pool so the executor's cold reads start warm. Advisory "
        "— prefetch failures are counted, never surfaced."),
    AGG_VENUE: ConfKey(
        "`auto`",
        "Where the grouped segment-reduce runs: numpy bincount/reduceat on host "
        "vs the device (mesh-sharded with psum/pmin/pmax collectives) segment "
        "reduce."),
    SORT_VENUE: ConfKey(
        "`auto`",
        "Where ORDER BY runs: numpy lexsort on host vs one device lax.sort over "
        "32-bit lanes."),
    FILTER_VENUE: ConfKey(
        "`auto`",
        "Where predicate masks evaluate: exact numpy on host vs the fused XLA "
        "computation (mesh-sharded rows on device)."),
    DEVICE_STAGING_ENABLED: ConfKey(
        "true",
        "Arrow→device zero-copy staging (execution/staging.py): fixed-width "
        "null-free columns on the cache-destined read path stay read-only "
        "views over the Arrow buffers instead of owned host copies, counted "
        "in `device.stage.bytes_zero_copy` vs `device.stage.bytes_copied`. "
        "Process-global; `false` restores the always-copy decode."),
    DEVICE_FUSED_KERNELS: ConfKey(
        "`auto`",
        "Fused Pallas kernels on the device venue (segment reduce, join-agg "
        "run bounds): `auto` engages them when the shape is eligible and "
        "byte-identical results are provable, falling back to the jitted lax "
        "path otherwise (`device.kernel.fused`/`device.kernel.fallbacks` "
        "count the split); `off` keeps the lax path everywhere."),
    JOIN_BROADCAST_MAX_ROWS: ConfKey(
        "4,000,000",
        "A non-aligned join whose smaller side is under this row count (and ≥4x "
        "smaller than the other) takes the broadcast hash path — dense code "
        "table from the small side, vectorized gather probe, large side never "
        "sorted. 0 disables."),
    JOIN_REBUCKETIZE: ConfKey(
        "`auto`",
        "Query-time re-bucketing exchange when exactly one join side is an index "
        "bucketed on its join keys: the other side re-groups into the index's "
        "bucket layout (native counting sort on host / one device sort on the "
        "device venue). `auto` engages it when the broadcast probe does not "
        "apply; `force` always; `off` keeps the single-partition fallback."),
    EXPLAIN_DISPLAY_MODE: ConfKey(
        "`plaintext`",
        "Explain rendering: `plaintext`, `console` (ANSI), or `html`."),
    EXPLAIN_HIGHLIGHT_BEGIN: ConfKey(
        "`<b>`",
        "Custom highlight tag opening replaced subtrees in html explain output "
        "(notebook use)."),
    EXPLAIN_HIGHLIGHT_END: ConfKey(
        "`</b>`",
        "Custom highlight tag closing replaced subtrees in html explain output "
        "(notebook use)."),
    ANALYSIS_VALIDATE: ConfKey(
        "true",
        "Pre-execution plan validation (analysis/validator.py): reject malformed "
        "plans with structured diagnostics before any device work."),
    FAULTS_ENABLED: ConfKey(
        "true",
        "Kill switch for the fault-injection harness (`faults.py`): false makes "
        "every `fault_point` inert even with rules registered. See "
        "[fault_tolerance.md](fault_tolerance.md)."),
    FAULTS_MAX_DELAY_SECONDS: ConfKey(
        "30",
        "Clamp on any single injected brownout delay (base + jitter of a "
        "`delay_s` fault rule): a typo'd rule slows a call by at most this "
        "long, so deadline-carrying paths surface their typed timeouts "
        "instead of wedging."),
    RETRY_MAX_ATTEMPTS: ConfKey(
        "3",
        "Attempts per transient-IO call site (log/pointer/manifest writes, "
        "parquet data/footer reads); 1 disables retry."),
    RETRY_BACKOFF_BASE: ConfKey(
        "0.005",
        "First-retry delay; doubles per attempt (capped, deterministic — jitter "
        "is an explicit hook)."),
    RETRY_CAS_ATTEMPTS: ConfKey(
        "1",
        "Whole-protocol retries when `Action.begin()` loses its CAS to a "
        "concurrent writer; 1 = abort (the reference's single-writer behavior)."),
    FALLBACK_ENABLED: ConfKey(
        "true",
        "Query-plane corruption fallback: an index scan over unreadable data "
        "quarantines the index (`session.index_health`) and re-plans the query "
        "against healthy indexes / the source instead of failing."),
    OBS_ENABLED: ConfKey(
        "true",
        "Tracer gate (process-global, [observability.md](observability.md)): "
        "false makes `span()`/`trace()` shared no-ops (nothing allocated on the "
        "query hot path); per-query profiles (`session.last_profile()`, "
        "`explain(mode=\"analyze\")`) remain available either way."),
    OBS_SINK: ConfKey(
        "unset",
        "JSON-lines path receiving one event per finished root trace (query or "
        "action) — the export feed for `python -m hyperspace_tpu.obs.export "
        "--sink <path>`."),
    OBS_HTTP_ENABLED: ConfKey(
        "false",
        "Runtime health plane ([observability.md](observability.md)): serve "
        "`/metrics`, `/healthz`, `/debug/events`, and `/debug/trace` over a "
        "zero-dependency HTTP server that starts/stops with the QueryServer "
        "lifecycle. Off ⇒ no thread, no socket, nothing imported."),
    OBS_HTTP_HOST: ConfKey(
        "`127.0.0.1`",
        "Bind address of the health endpoints (loopback by default — expose "
        "deliberately, not accidentally)."),
    OBS_HTTP_PORT: ConfKey(
        "0 (ephemeral)",
        "Port of the health endpoints; 0 binds an ephemeral port, read back "
        "from `QueryServer.health_endpoint.port`."),
    OBS_EVENTS_MAX: ConfKey(
        "256",
        "Bound of the structured event ring (`/debug/events`): old events age "
        "out (counted in `obs.events.dropped`), memory stays constant."),
    OBS_SLO_AVAILABILITY_TARGET: ConfKey(
        "0.999",
        "Availability objective over admitted queries (completed vs "
        "failed/timed-out/cancelled); burn rates are computed against "
        "1 - target (obs/slo.py)."),
    OBS_SLO_LATENCY_P99_SECONDS: ConfKey(
        "1.0",
        "Latency threshold of the `serve.latency_p99` objective: 99% of served "
        "queries must finish under it (measured from the latency histogram's "
        "bucket bounds)."),
    OBS_JOURNAL_ENABLED: ConfKey(
        "false",
        "Durable telemetry journal (process-global, [observability.md]"
        "(observability.md) \"telemetry journal\"): append events, completed "
        "root spans, periodic metric snapshots, and SLO verdict transitions "
        "to a segment-rotated JSONL journal under `<dir>/<pid>/`. Advisory — "
        "IO failures are counted (`obs.journal.errors`), never raised. "
        "Pooled/fleet workers inherit it and journal under their own pid."),
    OBS_JOURNAL_DIR: ConfKey(
        "unset (`<system.path>/_obs`)",
        "Root of the telemetry journal; one `<pid>/` subdirectory per "
        "journaling process. The fleet merge reads this root "
        "(`python -m hyperspace_tpu.obs.export --format chrome --fleet "
        "<dir>`)."),
    OBS_JOURNAL_SEGMENT_BYTES: ConfKey(
        "65536",
        "Active-segment size at which the journal seals: flush + fsync + "
        "atomic rename to `segment-<n>.jsonl` (readers only ever see whole "
        "segments; a crash tears at most the unsealed tail)."),
    OBS_JOURNAL_MAX_BYTES: ConfKey(
        "4194304",
        "Per-process byte budget over sealed segments; exceeded ⇒ "
        "oldest-first eviction (`obs.journal.evictions`). The journal is a "
        "flight recorder, not an archive."),
    OBS_JOURNAL_SNAPSHOT_SECONDS: ConfKey(
        "5.0",
        "Minimum spacing of periodic counter/gauge snapshot records — taken "
        "opportunistically on the journal write path, no background "
        "thread."),
    RECOVER_ON_ACCESS: ConfKey(
        "true",
        "Index listing lazily repairs a crashed writer's log (torn entries "
        "immediately, transient tails after the grace)."),
    RECOVER_GRACE_SECONDS: ConfKey(
        "300",
        "Minimum staleness of a transient entry before lazy recovery touches it "
        "— keeps a listing from cancelling a LIVE writer's in-flight action. "
        "Explicit `recover()` ignores it."),
    SERVE_WORKERS: ConfKey(
        "4",
        "Worker threads of the concurrent query server ([serving.md](serving.md)); "
        "the subsystem is off unless a `QueryServer` is constructed "
        "(`session.serve()`)."),
    SERVE_MAX_QUEUE_DEPTH: ConfKey(
        "32",
        "Admission-control limit: submits beyond it raise `AdmissionRejected`."),
    SERVE_QUERY_TIMEOUT_SECONDS: ConfKey(
        "0 (off)",
        "Per-query deadline — expires queries still waiting in the queue and "
        "bounds `QueryHandle.result()` waits (`QueryTimeout`)."),
    SERVE_PLAN_CACHE_ENABLED: ConfKey(
        "true",
        "Serving-plane plan cache: memoize `optimized_plan()` under versioned "
        "keys that index mutations / source appends invalidate structurally."),
    SERVE_PLAN_CACHE_MAX_ENTRIES: ConfKey(
        "128",
        "Plan-cache LRU bound."),
    SERVE_RESULT_CACHE_ENABLED: ConfKey(
        "false",
        "Opt-in whole-result cache under the same versioned keys (never serves "
        "pre-refresh rows)."),
    SERVE_RESULT_CACHE_MAX_BYTES: ConfKey(
        "256 MiB",
        "Result-cache byte budget; LRU eviction past it, no single entry above "
        "a quarter of it."),
    SERVE_TENANT_QUOTA_ENABLED: ConfKey(
        "false",
        "Per-tenant token-bucket admission ([serving.md](serving.md) \"fleet "
        "topology\"): a `submit(..., tenant=id)` whose bucket is dry raises "
        "`QuotaExceeded` (an `AdmissionRejected` carrying `retry_after_s`) "
        "before costing a queue slot. Tenant-less submits are unmetered."),
    SERVE_TENANT_QUOTA_RATE: ConfKey(
        "100",
        "Default refill rate (queries/second) of each tenant's token bucket; "
        "override per tenant via `TenantQuotas.set_limit`."),
    SERVE_TENANT_QUOTA_BURST: ConfKey(
        "200",
        "Default bucket capacity: how many queries a tenant may burst above "
        "its sustained rate."),
    SERVE_SHED_DEPTH_RATIO: ConfKey(
        "1.0 (off)",
        "Graceful saturation: non-priority submits are shed (typed "
        "`AdmissionRejected`) once the queue reaches this fraction of "
        "`hyperspace.serve.maxQueueDepth`, keeping a bounded p99 for the "
        "priority lane instead of collapsing under overload. 1.0 disables "
        "early shedding (only the hard depth limit applies)."),
    FLEET_CACHE_DIR: ConfKey(
        "`<system.path>/_fleet`",
        "Root of the fleet's shared on-disk state (plan/result cache entries, "
        "single-flight leases, worker registrations). Underscore-prefixed, so "
        "index listing never mistakes it for an index."),
    FLEET_CACHE_MAX_BYTES: ConfKey(
        "1 GiB",
        "Byte budget of the shared result cache; past it the oldest entries "
        "are evicted under a cross-process file lease (plans get 1/16 of the "
        "budget). No single result above a quarter of the budget is admitted."),
    FLEET_LEASE_SECONDS: ConfKey(
        "10",
        "TTL of cross-process lease files (single-flight claims, eviction "
        "lease): a holder that dies is presumed dead after this long and its "
        "lease is reaped by the next claimant — a crashed process can never "
        "wedge the fleet."),
    FLEET_SINGLEFLIGHT_WAIT_SECONDS: ConfKey(
        "15",
        "How long a cold process waits for another process's in-flight build "
        "before giving up and building locally (correct either way — the "
        "wait only dedups work)."),
    FLEET_WORKERS: ConfKey(
        "2",
        "Default worker-process count of a `FleetSupervisor` "
        "(serve/fleet/supervisor.py)."),
    FLEET_MIN_WORKERS: ConfKey(
        "1",
        "Floor of `FleetSupervisor.set_target_workers`: no scale-down (manual "
        "or controller-actuated) drops the fleet below this many members."),
    FLEET_MAX_RESTARTS: ConfKey(
        "3",
        "How many times the supervisor respawns a crashed worker before "
        "leaving its slot down (counted in `fleet.supervisor.restarts`)."),
    FLEET_RESTART_BACKOFF_SECONDS: ConfKey(
        "0.5",
        "Base of the exponential backoff between restarts of the SAME fleet "
        "member (delay = base x 2^(restarts-1), deterministic jitter, capped): "
        "a crash-looping worker cannot burn its whole "
        "`hyperspace.fleet.maxRestarts` budget in milliseconds. The first "
        "respawn is immediate; when backoff engages a WARN "
        "`fleet.worker.crash_loop` event names the member."),
    CONTROLLER_ENABLED: ConfKey(
        "false",
        "Kill switch of the self-driving operations controller "
        "([fault_tolerance.md](fault_tolerance.md) \"self-driving "
        "operations\"): false (the default) means the reconciliation loop "
        "observes nothing and actuates nothing; disarming a RUNNING "
        "controller mid-loop releases any overrides it holds (shed depth, "
        "quota throttle) and stands down."),
    CONTROLLER_INTERVAL_SECONDS: ConfKey(
        "1.0",
        "Reconciliation-loop tick interval of `OpsController.start()`; each "
        "tick samples the SLO tracker, drains new structured events, and "
        "runs one `step()`."),
    CONTROLLER_COOLDOWN_SECONDS: ConfKey(
        "30",
        "Minimum controller-clock seconds between two firings of the SAME "
        "actuation (per healed index, per sweep, per shed engage) — the "
        "anti-flap floor on top of the verdict hysteresis."),
    CONTROLLER_HYSTERESIS_TICKS: ConfKey(
        "2",
        "Consecutive page-verdict ticks required before the overload "
        "response engages: a single verdict flicker never actuates."),
    CONTROLLER_RECOVERY_TICKS: ConfKey(
        "2",
        "Consecutive non-page ticks required before an engaged overload "
        "response releases (restoring the original shed depth and quota "
        "rates)."),
    CONTROLLER_ACTUATION_BUDGET: ConfKey(
        "32",
        "Global mutation budget of one controller lifetime. Exhaustion "
        "degrades the controller to observe-only — decisions are still "
        "computed and audited, nothing mutates — announced once by an ERROR "
        "`controller.observe_only` event. Releases of held overrides stay "
        "free, so the system is always left as found."),
    CONTROLLER_SHED_RATIO: ConfKey(
        "0.5",
        "Shed-depth tightening applied while serve SLOs page: the queue's "
        "shed threshold drops to this fraction of `hyperspace.serve."
        "maxQueueDepth` (non-priority submits refused earlier, typed), "
        "restored on recovery."),
    CONTROLLER_QUOTA_FACTOR: ConfKey(
        "0.5",
        "Tenant-quota tightening applied while serve SLOs page: every "
        "tenant's token-bucket refill rate is scaled by this factor "
        "(`TenantQuotas.set_throttle`), restored on recovery."),
    CONTROLLER_HEAL_REBUILD: ConfKey(
        "true",
        "After healing a quarantined index via `recover()`, also rebuild it "
        "(`refresh_index(mode=\"full\")` — the crash-safe Action protocol) "
        "so on-disk corruption is actually repaired, not just re-served "
        "until the next quarantine. false limits healing to log recovery."),
    CONTROLLER_DEMOTION_CLUSTER_SIZE: ConfKey(
        "3",
        "How many `advisor.routing.demoted` events must cluster inside "
        "`demotionWindowSeconds` before the controller triggers an advisor "
        "lifecycle sweep (the sweep itself stays gated by the "
        "`hyperspace.advisor.lifecycle.*` opt-ins)."),
    CONTROLLER_DEMOTION_WINDOW_SECONDS: ConfKey(
        "300",
        "Trailing controller-clock window over which routing-demotion "
        "events are counted toward the sweep-trigger cluster."),
    CONTROLLER_HEAL_COORDINATE: ConfKey(
        "true",
        "Route heal actuations through the fleet single-flight lease "
        "(serve/fleet/singleflight.py) so exactly ONE member rebuilds a "
        "quarantined index fleet-wide; followers observe the published "
        "heal marker and only lift their local quarantine. Engages only "
        "when a fleet directory is discoverable; false keeps every heal "
        "process-local."),
    CONTROLLER_SCALE_SATURATION: ConfKey(
        "0.75",
        "Queue-fullness ratio (worst of the fleet-health aggregate and the "
        "local server) at or above which a controller tick counts toward "
        "the scale-up hysteresis."),
    CONTROLLER_SCALE_MAX_WORKERS: ConfKey(
        "8",
        "Ceiling of controller-actuated fleet scale-up "
        "(`FleetSupervisor.set_target_workers`); recovery restores the "
        "pre-episode member count."),
    CONTROLLER_SCALE_STEP: ConfKey(
        "1",
        "How many members each scale-up actuation adds (each addition is a "
        "separate audited, budgeted, cooled-down actuation)."),
    CONTROLLER_STORM_RESPONSE: ConfKey(
        "true",
        "Actuate on `jit.recompile_storm` events: pin the storming key's "
        "signature to the raw-scan route (`RoutingLedger.pin`) and drop the "
        "jit caches once (`jit_memory.drop_caches`). false keeps storms "
        "observe-only telemetry."),
    CONTROLLER_INCIDENT_ENABLED: ConfKey(
        "true",
        "Incident bundles ([fault_tolerance.md](fault_tolerance.md) "
        "\"incident bundles\"): on an SLO page engage, a fresh quarantine, "
        "or observe-only entry the controller opens a forensic bundle under "
        "`<dir>/<ts>-<trigger>/` (event ring dump, jit report, config "
        "snapshot, routing ledger, actuation audit trail) and closes it on "
        "recovery with every reachable member's journal segments. Advisory: "
        "capture failures count `controller.incident_errors`, never raise."),
    CONTROLLER_INCIDENT_DIR: ConfKey(
        "unset (`<fleet root>/incidents`)",
        "Where incident bundles land; defaults next to the fleet "
        "coordination root (`hyperspace.fleet.cacheDir` or "
        "`<system.path>/_fleet`). Served read-only at `/debug/incidents`."),
    CONTROLLER_INCIDENT_MAX_BUNDLES: ConfKey(
        "16",
        "On-disk bundle retention: opening a bundle beyond this count "
        "evicts the oldest bundle directory first."),
    CONTROLLER_INCIDENT_SEGMENTS: ConfKey(
        "4",
        "How many of each reachable member's newest sealed journal "
        "segments the closing bundle copies in — the cross-process evidence "
        "window."),
    ADVISOR_ROUTING_ENABLED: ConfKey(
        "false",
        "Adaptive query routing ([advisor.md](advisor.md)): a per-plan-"
        "signature ledger of measured indexed vs raw wall times demotes "
        "rewrites that measured slower to source scans. Changes plan choice, "
        "so explicit opt-in; the ledger invalidates structurally on any index "
        "mutation."),
    ADVISOR_ROUTING_DEMOTE_RATIO: ConfKey(
        "1.0",
        "Demotion threshold: a signature routes raw once its indexed EMA "
        "exceeds ratio x its raw EMA (both sides sampled)."),
    ADVISOR_ROUTING_ALPHA: ConfKey(
        "0.5",
        "EMA smoothing of the routing ledger's wall-time estimates (higher = "
        "newer samples dominate)."),
    ADVISOR_ROUTING_MIN_SAMPLES: ConfKey(
        "1",
        "Evidence floor: both the indexed and raw path need at least this "
        "many samples before a signature can be demoted."),
    ADVISOR_WORKLOAD_MAX_RECORDS: ConfKey(
        "512",
        "Bound of the in-memory per-session workload ring the what-if "
        "analyzer learns from; old traffic ages out."),
    ADVISOR_AUTO_CREATE: ConfKey(
        "false",
        "Lifecycle gate: let `LifecyclePolicy.sweep()` build recommended "
        "indexes autonomously (crash-safe through the normal create action)."),
    ADVISOR_AUTO_VACUUM: ConfKey(
        "false",
        "Lifecycle gate: let the sweep delete+vacuum indexes the observed "
        "workload never touched."),
    ADVISOR_AUTO_OPTIMIZE: ConfKey(
        "false",
        "Lifecycle gate: let the sweep compact indexes fragmented past "
        "`hyperspace.advisor.lifecycle.maxDeltas`."),
    ADVISOR_LIFECYCLE_MAX_DELTAS: ConfKey(
        "4",
        "Fragmentation threshold: an index spanning more version dirs than "
        "this earns an optimize recommendation."),
    ADVISOR_MIN_CONFIDENCE: ConfKey(
        "0.5",
        "Policy floor: recommendations below this confidence are reported "
        "but never auto-applied."),
    ADVISOR_MIN_BENEFIT_SECONDS: ConfKey(
        "0",
        "Policy floor: recommendations whose estimated benefit is below this "
        "many seconds are reported but never auto-applied."),
    INGEST_ENABLED: ConfKey(
        "false",
        "Continuous-ingestion daemon ([ingestion.md](ingestion.md)): source "
        "watchers feed micro-batch incremental refreshes through the "
        "two-phase Action protocol as a background service. Off by default — "
        "nothing polls or mutates without opt-in; `Hyperspace.ingest()` "
        "constructs the daemon either way."),
    INGEST_POLL_SECONDS: ConfKey(
        "1.0",
        "Tailer cadence: how often the daemon polls its sources for new "
        "files / appended CDC rows (and re-reads its pause control file)."),
    INGEST_CDC_BATCH_ROWS: ConfKey(
        "65536",
        "Row bound of one materialized CDC batch file: a changelog tail "
        "longer than this is split into multiple deterministic batch files "
        "(each commits through its own micro-batch)."),
    INGEST_AUTO_COMPACT: ConfKey(
        "true",
        "Gate the daemon's background compaction: once an index spans more "
        "delta version dirs than `hyperspace.advisor.lifecycle.maxDeltas`, "
        "trigger the optimize action (deferred while serve SLOs burn; the "
        "advisor lifecycle gates still bound WHAT may compact)."),
    INGEST_PROCESS_WORKER: ConfKey(
        "false",
        "Run the ingest loop in a spawn-context worker PROCESS "
        "(parallel/procpool.py) instead of the default in-process daemon "
        "thread — the crash-isolation deployment shape (a SIGKILLed worker "
        "leaves only a transient log the next recover() converges)."),
    INGEST_MAX_LAG_SECONDS: ConfKey(
        "30.0",
        "Advisory freshness objective: when data observed by the tailer has "
        "waited longer than this without reaching a committed index version, "
        "the daemon emits a WARN `ingest.lagging` event (never blocks)."),
}


def check_known_key(key: str) -> None:
    """Reject an undeclared ``hyperspace.*`` key with a did-you-mean
    suggestion (the runtime counterpart of static rule HSL010). Keys
    outside the hyperspace namespace pass through — the overrides map
    doubles as a scratch space for tests and embedding apps."""
    if not key.startswith("hyperspace.") or key in KNOWN_KEYS:
        return
    close = difflib.get_close_matches(key, KNOWN_KEYS, n=1, cutoff=0.6)
    raise UnknownConfigKeyError(key, close[0] if close else None)


def docs_table() -> str:
    """The markdown key table docs/configuration.md embeds between its
    `<!-- KNOWN_KEYS:begin -->` / `end` markers. Generated so a key can
    never exist in code without a documented default and meaning."""
    lines = ["| Key | Default | Meaning |", "|---|---|---|"]
    for key, spec in KNOWN_KEYS.items():
        lines.append(f"| `{key}` | {spec.default} | {spec.doc} |")
    return "\n".join(lines)


def _as_bool(value: Any) -> bool:
    return bool(value) if not isinstance(value, str) else value.lower() == "true"


@dataclasses.dataclass
class HyperspaceConf:
    """Per-session configuration with string-key overrides."""

    system_path: str = ""
    num_buckets: int = DEFAULT_NUM_BUCKETS
    cache_expiry_seconds: float = DEFAULT_CACHE_EXPIRY_SECONDS
    hybrid_scan_enabled: bool = False
    hybrid_scan_max_appended_ratio: float = DEFAULT_HYBRID_SCAN_MAX_APPENDED_RATIO
    build_memory_budget_bytes: int = DEFAULT_BUILD_MEMORY_BUDGET
    build_chunk_bytes: int = 0  # 0 = derived from the budget
    join_venue: str = DEFAULT_JOIN_VENUE
    join_venue_min_mbps: float = DEFAULT_JOIN_VENUE_MIN_MBPS
    build_venue: str = DEFAULT_JOIN_VENUE
    build_pipeline_enabled: bool = True
    build_pipeline_max_inflight_bytes: int = 0  # 0 = derived from chunkBytes
    build_workers: int = 0  # 0 = in-process build (no worker pool)
    build_exchange_dir: str = ""  # "" = <dest>.exchange next to the version dir
    scan_prefetch_enabled: bool = True
    agg_venue: str = DEFAULT_JOIN_VENUE
    sort_venue: str = DEFAULT_JOIN_VENUE
    filter_venue: str = DEFAULT_JOIN_VENUE
    device_fused_kernels: str = "auto"
    join_broadcast_max_rows: int = DEFAULT_JOIN_BROADCAST_MAX_ROWS
    join_rebucketize: str = DEFAULT_JOIN_REBUCKETIZE
    validate_plans: bool = True
    fallback_enabled: bool = True
    recover_on_access: bool = True
    recover_grace_seconds: float = DEFAULT_RECOVER_GRACE_SECONDS
    serve_workers: int = DEFAULT_SERVE_WORKERS
    serve_max_queue_depth: int = DEFAULT_SERVE_MAX_QUEUE_DEPTH
    serve_query_timeout_seconds: float = 0.0  # 0 = no per-query timeout
    serve_plan_cache_enabled: bool = True
    serve_plan_cache_max_entries: int = DEFAULT_SERVE_PLAN_CACHE_MAX_ENTRIES
    serve_result_cache_enabled: bool = False  # opt-in: results pin host memory
    serve_result_cache_max_bytes: int = DEFAULT_SERVE_RESULT_CACHE_MAX_BYTES
    serve_tenant_quota_enabled: bool = False  # opt-in: meters tenant-keyed submits
    serve_tenant_quota_rate: float = DEFAULT_SERVE_TENANT_QUOTA_RATE
    serve_tenant_quota_burst: int = DEFAULT_SERVE_TENANT_QUOTA_BURST
    serve_shed_depth_ratio: float = DEFAULT_SERVE_SHED_DEPTH_RATIO
    fleet_cache_dir: str = ""  # "" = <system_path>/_fleet
    fleet_cache_max_bytes: int = DEFAULT_FLEET_CACHE_MAX_BYTES
    fleet_lease_seconds: float = DEFAULT_FLEET_LEASE_SECONDS
    fleet_singleflight_wait_seconds: float = DEFAULT_FLEET_SINGLEFLIGHT_WAIT_SECONDS
    fleet_workers: int = DEFAULT_FLEET_WORKERS
    fleet_min_workers: int = DEFAULT_FLEET_MIN_WORKERS
    fleet_max_restarts: int = DEFAULT_FLEET_MAX_RESTARTS
    fleet_restart_backoff_seconds: float = DEFAULT_FLEET_RESTART_BACKOFF_SECONDS
    controller_enabled: bool = False  # opt-in: the controller mutates serving state
    controller_interval_seconds: float = DEFAULT_CONTROLLER_INTERVAL_SECONDS
    controller_cooldown_seconds: float = DEFAULT_CONTROLLER_COOLDOWN_SECONDS
    controller_hysteresis_ticks: int = DEFAULT_CONTROLLER_HYSTERESIS_TICKS
    controller_recovery_ticks: int = DEFAULT_CONTROLLER_RECOVERY_TICKS
    controller_actuation_budget: int = DEFAULT_CONTROLLER_ACTUATION_BUDGET
    controller_shed_ratio: float = DEFAULT_CONTROLLER_SHED_RATIO
    controller_quota_factor: float = DEFAULT_CONTROLLER_QUOTA_FACTOR
    controller_heal_rebuild: bool = True
    controller_demotion_cluster_size: int = DEFAULT_CONTROLLER_DEMOTION_CLUSTER_SIZE
    controller_demotion_window_seconds: float = DEFAULT_CONTROLLER_DEMOTION_WINDOW_SECONDS
    controller_heal_coordinate: bool = True
    controller_scale_saturation: float = DEFAULT_CONTROLLER_SCALE_SATURATION
    controller_scale_max_workers: int = DEFAULT_CONTROLLER_SCALE_MAX_WORKERS
    controller_scale_step: int = DEFAULT_CONTROLLER_SCALE_STEP
    controller_storm_response: bool = True
    controller_incident_enabled: bool = True
    controller_incident_dir: str = ""  # "" = <fleet root>/incidents
    controller_incident_max_bundles: int = DEFAULT_CONTROLLER_INCIDENT_MAX_BUNDLES
    controller_incident_segments: int = DEFAULT_CONTROLLER_INCIDENT_SEGMENTS
    advisor_routing_enabled: bool = False  # opt-in: routing changes plan choice
    advisor_routing_demote_ratio: float = DEFAULT_ADVISOR_ROUTING_DEMOTE_RATIO
    advisor_routing_alpha: float = DEFAULT_ADVISOR_ROUTING_ALPHA
    advisor_routing_min_samples: int = DEFAULT_ADVISOR_ROUTING_MIN_SAMPLES
    advisor_workload_max_records: int = DEFAULT_ADVISOR_WORKLOAD_MAX_RECORDS
    advisor_auto_create: bool = False
    advisor_auto_vacuum: bool = False
    advisor_auto_optimize: bool = False
    advisor_lifecycle_max_deltas: int = DEFAULT_ADVISOR_LIFECYCLE_MAX_DELTAS
    advisor_min_confidence: float = DEFAULT_ADVISOR_MIN_CONFIDENCE
    advisor_min_benefit_seconds: float = 0.0
    obs_http_enabled: bool = False  # opt-in: binds a socket
    obs_http_host: str = "127.0.0.1"
    obs_http_port: int = 0  # 0 = ephemeral
    ingest_enabled: bool = False  # opt-in: the daemon mutates index state
    ingest_poll_seconds: float = DEFAULT_INGEST_POLL_SECONDS
    ingest_cdc_batch_rows: int = DEFAULT_INGEST_CDC_BATCH_ROWS
    ingest_auto_compact: bool = True
    ingest_process_worker: bool = False  # opt-in: spawns a worker process
    ingest_max_lag_seconds: float = DEFAULT_INGEST_MAX_LAG_SECONDS
    overrides: dict[str, Any] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        if not self.system_path:
            self.system_path = os.path.join(os.getcwd(), "spark-warehouse", "indexes")

    def set(self, key: str, value: Any) -> None:
        check_known_key(key)
        self.overrides[key] = value
        if key == INDEX_SYSTEM_PATH:
            self.system_path = str(value)
        elif key == INDEX_NUM_BUCKETS:
            self.num_buckets = int(value)
        elif key == INDEX_CACHE_EXPIRY_SECONDS:
            self.cache_expiry_seconds = float(value)
        elif key == INDEX_HYBRID_SCAN_ENABLED:
            self.hybrid_scan_enabled = bool(value) if not isinstance(value, str) else value.lower() == "true"
        elif key == INDEX_HYBRID_SCAN_MAX_APPENDED_RATIO:
            self.hybrid_scan_max_appended_ratio = float(value)
        elif key == INDEX_BUILD_MEMORY_BUDGET:
            self.build_memory_budget_bytes = int(value)
        elif key == INDEX_BUILD_CHUNK_BYTES:
            self.build_chunk_bytes = int(value)
        elif key == JOIN_VENUE:
            self.join_venue = str(value)
        elif key == JOIN_VENUE_MIN_MBPS:
            self.join_venue_min_mbps = float(value)
        elif key == BUILD_VENUE:
            self.build_venue = str(value)
        elif key == BUILD_PIPELINE_ENABLED:
            self.build_pipeline_enabled = _as_bool(value)
        elif key == BUILD_PIPELINE_MAX_INFLIGHT_BYTES:
            self.build_pipeline_max_inflight_bytes = int(value)
        elif key == BUILD_WORKERS:
            self.build_workers = int(value)
        elif key == BUILD_EXCHANGE_DIR:
            self.build_exchange_dir = str(value)
        elif key == SCAN_PREFETCH_ENABLED:
            self.scan_prefetch_enabled = _as_bool(value)
        elif key == AGG_VENUE:
            self.agg_venue = str(value)
        elif key == SORT_VENUE:
            self.sort_venue = str(value)
        elif key == FILTER_VENUE:
            self.filter_venue = str(value)
        elif key == DEVICE_FUSED_KERNELS:
            self.device_fused_kernels = str(value)
        elif key == DEVICE_STAGING_ENABLED:
            # Process-global like the faults/obs switches: the decode
            # path (ColumnTable.from_arrow) has no session handle.
            from hyperspace_tpu.execution import staging

            staging.set_enabled(_as_bool(value))
        elif key == JOIN_BROADCAST_MAX_ROWS:
            self.join_broadcast_max_rows = int(value)
        elif key == JOIN_REBUCKETIZE:
            self.join_rebucketize = str(value)
        elif key == ANALYSIS_VALIDATE:
            self.validate_plans = _as_bool(value)
        elif key == FALLBACK_ENABLED:
            self.fallback_enabled = _as_bool(value)
        elif key == RECOVER_ON_ACCESS:
            self.recover_on_access = _as_bool(value)
        elif key == RECOVER_GRACE_SECONDS:
            self.recover_grace_seconds = float(value)
        elif key == SERVE_WORKERS:
            self.serve_workers = int(value)
        elif key == SERVE_MAX_QUEUE_DEPTH:
            self.serve_max_queue_depth = int(value)
        elif key == SERVE_QUERY_TIMEOUT_SECONDS:
            self.serve_query_timeout_seconds = float(value)
        elif key == SERVE_PLAN_CACHE_ENABLED:
            self.serve_plan_cache_enabled = _as_bool(value)
        elif key == SERVE_PLAN_CACHE_MAX_ENTRIES:
            self.serve_plan_cache_max_entries = int(value)
        elif key == SERVE_RESULT_CACHE_ENABLED:
            self.serve_result_cache_enabled = _as_bool(value)
        elif key == SERVE_RESULT_CACHE_MAX_BYTES:
            self.serve_result_cache_max_bytes = int(value)
        elif key == SERVE_TENANT_QUOTA_ENABLED:
            self.serve_tenant_quota_enabled = _as_bool(value)
        elif key == SERVE_TENANT_QUOTA_RATE:
            self.serve_tenant_quota_rate = float(value)
        elif key == SERVE_TENANT_QUOTA_BURST:
            self.serve_tenant_quota_burst = int(value)
        elif key == SERVE_SHED_DEPTH_RATIO:
            self.serve_shed_depth_ratio = float(value)
        elif key == FLEET_CACHE_DIR:
            self.fleet_cache_dir = str(value)
        elif key == FLEET_CACHE_MAX_BYTES:
            self.fleet_cache_max_bytes = int(value)
        elif key == FLEET_LEASE_SECONDS:
            self.fleet_lease_seconds = float(value)
        elif key == FLEET_SINGLEFLIGHT_WAIT_SECONDS:
            self.fleet_singleflight_wait_seconds = float(value)
        elif key == FLEET_WORKERS:
            self.fleet_workers = int(value)
        elif key == FLEET_MIN_WORKERS:
            self.fleet_min_workers = int(value)
        elif key == FLEET_MAX_RESTARTS:
            self.fleet_max_restarts = int(value)
        elif key == FLEET_RESTART_BACKOFF_SECONDS:
            self.fleet_restart_backoff_seconds = float(value)
        elif key == CONTROLLER_ENABLED:
            self.controller_enabled = _as_bool(value)
        elif key == CONTROLLER_INTERVAL_SECONDS:
            self.controller_interval_seconds = float(value)
        elif key == CONTROLLER_COOLDOWN_SECONDS:
            self.controller_cooldown_seconds = float(value)
        elif key == CONTROLLER_HYSTERESIS_TICKS:
            self.controller_hysteresis_ticks = int(value)
        elif key == CONTROLLER_RECOVERY_TICKS:
            self.controller_recovery_ticks = int(value)
        elif key == CONTROLLER_ACTUATION_BUDGET:
            self.controller_actuation_budget = int(value)
        elif key == CONTROLLER_SHED_RATIO:
            self.controller_shed_ratio = float(value)
        elif key == CONTROLLER_QUOTA_FACTOR:
            self.controller_quota_factor = float(value)
        elif key == CONTROLLER_HEAL_REBUILD:
            self.controller_heal_rebuild = _as_bool(value)
        elif key == CONTROLLER_DEMOTION_CLUSTER_SIZE:
            self.controller_demotion_cluster_size = int(value)
        elif key == CONTROLLER_DEMOTION_WINDOW_SECONDS:
            self.controller_demotion_window_seconds = float(value)
        elif key == CONTROLLER_HEAL_COORDINATE:
            self.controller_heal_coordinate = _as_bool(value)
        elif key == CONTROLLER_SCALE_SATURATION:
            self.controller_scale_saturation = float(value)
        elif key == CONTROLLER_SCALE_MAX_WORKERS:
            self.controller_scale_max_workers = int(value)
        elif key == CONTROLLER_SCALE_STEP:
            self.controller_scale_step = int(value)
        elif key == CONTROLLER_STORM_RESPONSE:
            self.controller_storm_response = _as_bool(value)
        elif key == CONTROLLER_INCIDENT_ENABLED:
            self.controller_incident_enabled = _as_bool(value)
        elif key == CONTROLLER_INCIDENT_DIR:
            self.controller_incident_dir = str(value)
        elif key == CONTROLLER_INCIDENT_MAX_BUNDLES:
            self.controller_incident_max_bundles = int(value)
        elif key == CONTROLLER_INCIDENT_SEGMENTS:
            self.controller_incident_segments = int(value)
        elif key == ADVISOR_ROUTING_ENABLED:
            self.advisor_routing_enabled = _as_bool(value)
        elif key == ADVISOR_ROUTING_DEMOTE_RATIO:
            self.advisor_routing_demote_ratio = float(value)
        elif key == ADVISOR_ROUTING_ALPHA:
            self.advisor_routing_alpha = float(value)
        elif key == ADVISOR_ROUTING_MIN_SAMPLES:
            self.advisor_routing_min_samples = int(value)
        elif key == ADVISOR_WORKLOAD_MAX_RECORDS:
            self.advisor_workload_max_records = int(value)
        elif key == ADVISOR_AUTO_CREATE:
            self.advisor_auto_create = _as_bool(value)
        elif key == ADVISOR_AUTO_VACUUM:
            self.advisor_auto_vacuum = _as_bool(value)
        elif key == ADVISOR_AUTO_OPTIMIZE:
            self.advisor_auto_optimize = _as_bool(value)
        elif key == ADVISOR_LIFECYCLE_MAX_DELTAS:
            self.advisor_lifecycle_max_deltas = int(value)
        elif key == ADVISOR_MIN_CONFIDENCE:
            self.advisor_min_confidence = float(value)
        elif key == ADVISOR_MIN_BENEFIT_SECONDS:
            self.advisor_min_benefit_seconds = float(value)
        elif key == FAULTS_ENABLED:
            # Process-global kill switch for the injection harness —
            # matches the process-global filesystem state it guards.
            from hyperspace_tpu import faults

            faults.set_enabled(_as_bool(value))
        elif key == FAULTS_MAX_DELAY_SECONDS:
            # Process-global like the harness it clamps.
            from hyperspace_tpu import faults

            faults.set_max_delay(float(value))
        elif key == OBS_ENABLED:
            # Process-global like the metrics/sink it feeds (obs/trace.py).
            from hyperspace_tpu.obs import trace as _obs_trace

            _obs_trace.set_enabled(_as_bool(value))
        elif key == OBS_SINK:
            from hyperspace_tpu.obs import trace as _obs_trace

            _obs_trace.configure(sink=str(value) if value else None)
        elif key == OBS_HTTP_ENABLED:
            self.obs_http_enabled = _as_bool(value)
        elif key == OBS_HTTP_HOST:
            self.obs_http_host = str(value)
        elif key == OBS_HTTP_PORT:
            self.obs_http_port = int(value)
        elif key == OBS_EVENTS_MAX:
            # Process-global ring, like the metrics registry it joins.
            from hyperspace_tpu.obs import events as _obs_events

            _obs_events.configure(max_events=int(value))
        elif key == OBS_SLO_AVAILABILITY_TARGET:
            from hyperspace_tpu.obs import slo as _obs_slo

            _obs_slo.configure(availability_target=float(value))
        elif key == OBS_SLO_LATENCY_P99_SECONDS:
            from hyperspace_tpu.obs import slo as _obs_slo

            _obs_slo.configure(latency_threshold_s=float(value))
        elif key == OBS_JOURNAL_ENABLED:
            # Process-global like the rings it taps (obs/journal.py);
            # enabling without an explicit dir derives the default root
            # from this conf's system path.
            from hyperspace_tpu.obs import journal as _obs_journal

            _obs_journal.configure(enabled=_as_bool(value))
            if _as_bool(value):
                _obs_journal.ensure_root(os.path.join(self.system_path, "_obs"))
        elif key == OBS_JOURNAL_DIR:
            from hyperspace_tpu.obs import journal as _obs_journal

            _obs_journal.configure(root=str(value) if value else "")
        elif key == OBS_JOURNAL_SEGMENT_BYTES:
            from hyperspace_tpu.obs import journal as _obs_journal

            _obs_journal.configure(segment_bytes=int(value))
        elif key == OBS_JOURNAL_MAX_BYTES:
            from hyperspace_tpu.obs import journal as _obs_journal

            _obs_journal.configure(max_bytes=int(value))
        elif key == OBS_JOURNAL_SNAPSHOT_SECONDS:
            from hyperspace_tpu.obs import journal as _obs_journal

            _obs_journal.configure(snapshot_s=float(value))
        elif key == RETRY_MAX_ATTEMPTS:
            from hyperspace_tpu.utils import retry

            retry.configure(max_attempts=int(value))
        elif key == RETRY_BACKOFF_BASE:
            from hyperspace_tpu.utils import retry

            retry.configure(backoff_base=float(value))
        elif key == RETRY_CAS_ATTEMPTS:
            from hyperspace_tpu.utils import retry

            retry.configure(cas_attempts=int(value))
        elif key == INGEST_ENABLED:
            self.ingest_enabled = _as_bool(value)
        elif key == INGEST_POLL_SECONDS:
            self.ingest_poll_seconds = float(value)
        elif key == INGEST_CDC_BATCH_ROWS:
            self.ingest_cdc_batch_rows = int(value)
        elif key == INGEST_AUTO_COMPACT:
            self.ingest_auto_compact = _as_bool(value)
        elif key == INGEST_PROCESS_WORKER:
            self.ingest_process_worker = _as_bool(value)
        elif key == INGEST_MAX_LAG_SECONDS:
            self.ingest_max_lag_seconds = float(value)

    def get(self, key: str, default: Any = None) -> Any:
        check_known_key(key)
        if key in self.overrides:
            return self.overrides[key]
        if key == INDEX_SYSTEM_PATH:
            return self.system_path
        if key == INDEX_NUM_BUCKETS:
            return self.num_buckets
        if key == INDEX_CACHE_EXPIRY_SECONDS:
            return self.cache_expiry_seconds
        if key == INDEX_HYBRID_SCAN_ENABLED:
            return self.hybrid_scan_enabled
        if key == INDEX_HYBRID_SCAN_MAX_APPENDED_RATIO:
            return self.hybrid_scan_max_appended_ratio
        if key == INDEX_BUILD_MEMORY_BUDGET:
            return self.build_memory_budget_bytes
        if key == INDEX_BUILD_CHUNK_BYTES:
            return self.build_chunk_bytes
        if key == JOIN_VENUE:
            return self.join_venue
        if key == JOIN_VENUE_MIN_MBPS:
            return self.join_venue_min_mbps
        if key == BUILD_VENUE:
            return self.build_venue
        if key == BUILD_PIPELINE_ENABLED:
            return self.build_pipeline_enabled
        if key == BUILD_PIPELINE_MAX_INFLIGHT_BYTES:
            return self.build_pipeline_max_inflight_bytes
        if key == BUILD_WORKERS:
            return self.build_workers
        if key == BUILD_EXCHANGE_DIR:
            return self.build_exchange_dir
        if key == SCAN_PREFETCH_ENABLED:
            return self.scan_prefetch_enabled
        if key == AGG_VENUE:
            return self.agg_venue
        if key == SORT_VENUE:
            return self.sort_venue
        if key == FILTER_VENUE:
            return self.filter_venue
        if key == DEVICE_FUSED_KERNELS:
            return self.device_fused_kernels
        if key == DEVICE_STAGING_ENABLED:
            from hyperspace_tpu.execution import staging

            return staging.enabled()
        if key == JOIN_BROADCAST_MAX_ROWS:
            return self.join_broadcast_max_rows
        if key == JOIN_REBUCKETIZE:
            return self.join_rebucketize
        if key == ANALYSIS_VALIDATE:
            return self.validate_plans
        if key == FALLBACK_ENABLED:
            return self.fallback_enabled
        if key == RECOVER_ON_ACCESS:
            return self.recover_on_access
        if key == RECOVER_GRACE_SECONDS:
            return self.recover_grace_seconds
        if key == SERVE_WORKERS:
            return self.serve_workers
        if key == SERVE_MAX_QUEUE_DEPTH:
            return self.serve_max_queue_depth
        if key == SERVE_QUERY_TIMEOUT_SECONDS:
            return self.serve_query_timeout_seconds
        if key == SERVE_PLAN_CACHE_ENABLED:
            return self.serve_plan_cache_enabled
        if key == SERVE_PLAN_CACHE_MAX_ENTRIES:
            return self.serve_plan_cache_max_entries
        if key == SERVE_RESULT_CACHE_ENABLED:
            return self.serve_result_cache_enabled
        if key == SERVE_RESULT_CACHE_MAX_BYTES:
            return self.serve_result_cache_max_bytes
        if key == SERVE_TENANT_QUOTA_ENABLED:
            return self.serve_tenant_quota_enabled
        if key == SERVE_TENANT_QUOTA_RATE:
            return self.serve_tenant_quota_rate
        if key == SERVE_TENANT_QUOTA_BURST:
            return self.serve_tenant_quota_burst
        if key == SERVE_SHED_DEPTH_RATIO:
            return self.serve_shed_depth_ratio
        if key == FLEET_CACHE_DIR:
            return self.fleet_cache_dir
        if key == FLEET_CACHE_MAX_BYTES:
            return self.fleet_cache_max_bytes
        if key == FLEET_LEASE_SECONDS:
            return self.fleet_lease_seconds
        if key == FLEET_SINGLEFLIGHT_WAIT_SECONDS:
            return self.fleet_singleflight_wait_seconds
        if key == FLEET_WORKERS:
            return self.fleet_workers
        if key == FLEET_MIN_WORKERS:
            return self.fleet_min_workers
        if key == FLEET_MAX_RESTARTS:
            return self.fleet_max_restarts
        if key == FLEET_RESTART_BACKOFF_SECONDS:
            return self.fleet_restart_backoff_seconds
        if key == CONTROLLER_ENABLED:
            return self.controller_enabled
        if key == CONTROLLER_INTERVAL_SECONDS:
            return self.controller_interval_seconds
        if key == CONTROLLER_COOLDOWN_SECONDS:
            return self.controller_cooldown_seconds
        if key == CONTROLLER_HYSTERESIS_TICKS:
            return self.controller_hysteresis_ticks
        if key == CONTROLLER_RECOVERY_TICKS:
            return self.controller_recovery_ticks
        if key == CONTROLLER_ACTUATION_BUDGET:
            return self.controller_actuation_budget
        if key == CONTROLLER_SHED_RATIO:
            return self.controller_shed_ratio
        if key == CONTROLLER_QUOTA_FACTOR:
            return self.controller_quota_factor
        if key == CONTROLLER_HEAL_REBUILD:
            return self.controller_heal_rebuild
        if key == CONTROLLER_DEMOTION_CLUSTER_SIZE:
            return self.controller_demotion_cluster_size
        if key == CONTROLLER_DEMOTION_WINDOW_SECONDS:
            return self.controller_demotion_window_seconds
        if key == CONTROLLER_HEAL_COORDINATE:
            return self.controller_heal_coordinate
        if key == CONTROLLER_SCALE_SATURATION:
            return self.controller_scale_saturation
        if key == CONTROLLER_SCALE_MAX_WORKERS:
            return self.controller_scale_max_workers
        if key == CONTROLLER_SCALE_STEP:
            return self.controller_scale_step
        if key == CONTROLLER_STORM_RESPONSE:
            return self.controller_storm_response
        if key == CONTROLLER_INCIDENT_ENABLED:
            return self.controller_incident_enabled
        if key == CONTROLLER_INCIDENT_DIR:
            return self.controller_incident_dir
        if key == CONTROLLER_INCIDENT_MAX_BUNDLES:
            return self.controller_incident_max_bundles
        if key == CONTROLLER_INCIDENT_SEGMENTS:
            return self.controller_incident_segments
        if key == ADVISOR_ROUTING_ENABLED:
            return self.advisor_routing_enabled
        if key == ADVISOR_ROUTING_DEMOTE_RATIO:
            return self.advisor_routing_demote_ratio
        if key == ADVISOR_ROUTING_ALPHA:
            return self.advisor_routing_alpha
        if key == ADVISOR_ROUTING_MIN_SAMPLES:
            return self.advisor_routing_min_samples
        if key == ADVISOR_WORKLOAD_MAX_RECORDS:
            return self.advisor_workload_max_records
        if key == ADVISOR_AUTO_CREATE:
            return self.advisor_auto_create
        if key == ADVISOR_AUTO_VACUUM:
            return self.advisor_auto_vacuum
        if key == ADVISOR_AUTO_OPTIMIZE:
            return self.advisor_auto_optimize
        if key == ADVISOR_LIFECYCLE_MAX_DELTAS:
            return self.advisor_lifecycle_max_deltas
        if key == ADVISOR_MIN_CONFIDENCE:
            return self.advisor_min_confidence
        if key == ADVISOR_MIN_BENEFIT_SECONDS:
            return self.advisor_min_benefit_seconds
        if key == OBS_ENABLED:
            from hyperspace_tpu.obs import trace as _obs_trace

            return _obs_trace.enabled()
        if key == OBS_SINK:
            from hyperspace_tpu.obs import trace as _obs_trace

            return _obs_trace.sink_path()
        if key == OBS_HTTP_ENABLED:
            return self.obs_http_enabled
        if key == OBS_HTTP_HOST:
            return self.obs_http_host
        if key == OBS_HTTP_PORT:
            return self.obs_http_port
        if key == OBS_EVENTS_MAX:
            from hyperspace_tpu.obs import events as _obs_events

            return _obs_events.max_events()
        if key == OBS_SLO_AVAILABILITY_TARGET:
            from hyperspace_tpu.obs import slo as _obs_slo

            return _obs_slo.TRACKER.availability_target
        if key == OBS_SLO_LATENCY_P99_SECONDS:
            from hyperspace_tpu.obs import slo as _obs_slo

            return _obs_slo.TRACKER.latency_threshold_s
        if key == OBS_JOURNAL_ENABLED:
            from hyperspace_tpu.obs import journal as _obs_journal

            return _obs_journal.configured_enabled()
        if key == OBS_JOURNAL_DIR:
            from hyperspace_tpu.obs import journal as _obs_journal

            return _obs_journal.root()
        if key == OBS_JOURNAL_SEGMENT_BYTES:
            from hyperspace_tpu.obs import journal as _obs_journal

            return _obs_journal.segment_bytes()
        if key == OBS_JOURNAL_MAX_BYTES:
            from hyperspace_tpu.obs import journal as _obs_journal

            return _obs_journal.max_bytes()
        if key == OBS_JOURNAL_SNAPSHOT_SECONDS:
            from hyperspace_tpu.obs import journal as _obs_journal

            return _obs_journal.snapshot_seconds()
        if key == INGEST_ENABLED:
            return self.ingest_enabled
        if key == INGEST_POLL_SECONDS:
            return self.ingest_poll_seconds
        if key == INGEST_CDC_BATCH_ROWS:
            return self.ingest_cdc_batch_rows
        if key == INGEST_AUTO_COMPACT:
            return self.ingest_auto_compact
        if key == INGEST_PROCESS_WORKER:
            return self.ingest_process_worker
        if key == INGEST_MAX_LAG_SECONDS:
            return self.ingest_max_lag_seconds
        return default
