"""Framework configuration.

Reference parity: index/IndexConstants.scala:21-49 — all tunables live under
string keys with defaults, resolved at use-sites. Here they are a typed
dataclass attached to the session (there is no SparkSession / SQLConf to
piggyback on), plus the same string-keyed override map so tests and callers
can set individual knobs.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any

# String keys (kept spiritually compatible with spark.hyperspace.* keys,
# reference index/IndexConstants.scala:21-49).
INDEX_SYSTEM_PATH = "hyperspace.system.path"
INDEX_NUM_BUCKETS = "hyperspace.index.num.buckets"
INDEX_CACHE_EXPIRY_SECONDS = "hyperspace.index.cache.expiryDurationInSeconds"
INDEX_HYBRID_SCAN_ENABLED = "hyperspace.index.hybridscan.enabled"
# Hybrid scan only applies while appended bytes stay below this fraction of
# the indexed source (past it, scanning deltas unindexed beats the index).
INDEX_HYBRID_SCAN_MAX_APPENDED_RATIO = "hyperspace.index.hybridscan.maxAppendedRatio"
# Out-of-core build: sources whose uncompressed estimate exceeds the memory
# budget stream through row-group chunks of at most chunkBytes (0 = derive
# from the budget).
INDEX_BUILD_MEMORY_BUDGET = "hyperspace.index.build.memoryBudgetBytes"
INDEX_BUILD_CHUNK_BYTES = "hyperspace.index.build.chunkBytes"
# Materialized-join execution venue: "auto" picks the host-native merge
# kernel when measured device->host bandwidth is below joinVenueMinMbps
# (the match pairs land on host either way; on tunneled devices the
# readback dominates), else the device kernel. "device"/"host" force it.
JOIN_VENUE = "hyperspace.join.venue"
JOIN_VENUE_MIN_MBPS = "hyperspace.join.venueMinMbps"
# Build sort venue: same auto/device/host scheme for the bucketize+sort
# permutation (its only output lands on host).
BUILD_VENUE = "hyperspace.build.venue"
AGG_VENUE = "hyperspace.agg.venue"
SORT_VENUE = "hyperspace.sort.venue"
FILTER_VENUE = "hyperspace.filter.venue"
# Broadcast hash join: a non-aligned join whose smaller side has at most
# this many rows (and is at least 4x smaller than the other) probes the
# large side against the sorted small side instead of sorting both for a
# merge (the analog of Spark's BroadcastExchange fallback the reference
# environment counts, PhysicalOperatorAnalyzer.scala:46-50). 0 disables.
JOIN_BROADCAST_MAX_ROWS = "hyperspace.join.broadcast.maxRows"
# Query-time re-bucketing exchange: when exactly one join side is an index
# bucketed on its join keys, the OTHER side can re-bucketize on the fly
# (hash + counting sort / device sort) so the merge stays bucket-parallel.
# "auto" engages it when the broadcast probe does not apply; "force"
# always re-bucketizes (bucket-aligned evidence for chained star joins);
# "off" keeps the single-partition fallback.
JOIN_REBUCKETIZE = "hyperspace.join.rebucketize"
# Pre-execution plan validation (analysis/validator.py): reject malformed
# plans with structured diagnostics before any device work. On by default;
# the switch exists for benchmarking the (small) walk cost away.
ANALYSIS_VALIDATE = "hyperspace.analysis.validate"
# Fault-tolerance plane (docs/fault_tolerance.md). faults.enabled is the
# injection-harness kill switch (False ⇒ fault_point is inert even with
# rules registered — a production config can never inject). retry.* tune
# the transient-IO retry layer (utils/retry.py; maxAttempts=1 disables).
# fallback.enabled gates the query plane's corruption fallback: a query
# whose index data turns out unreadable re-plans against the source
# instead of failing. recover.onAccess makes index listing lazily repair
# a crashed writer's transient log (after graceSeconds of staleness).
FAULTS_ENABLED = "hyperspace.faults.enabled"
# Observability plane (docs/observability.md). obs.enabled gates the
# tracer: False makes span()/trace() return shared no-op singletons (no
# allocation on the query hot path); per-query profiles remain available
# either way (they ride the executed physical plan). obs.sink is a
# JSON-lines path receiving one event per finished root trace — the
# export feed (`python -m hyperspace_tpu.obs.export --sink <path>`).
OBS_ENABLED = "hyperspace.obs.enabled"
OBS_SINK = "hyperspace.obs.sink"
# Concurrent query-serving plane (docs/serving.md). The subsystem is OFF
# by default: nothing changes for direct `session.run()` callers; a
# QueryServer is constructed explicitly (or via `session.serve()`) and
# reads these knobs as its defaults. workers bounds the executor pool;
# maxQueueDepth is the admission-control limit (submits beyond it raise
# AdmissionRejected); queryTimeoutSeconds (0 = none) expires queries
# still queued (and bounds result() waits). The plan cache memoizes
# optimized plans per (plan signature, data fingerprint, index log
# versions); the result cache is opt-in and byte-bounded.
SERVE_WORKERS = "hyperspace.serve.workers"
SERVE_MAX_QUEUE_DEPTH = "hyperspace.serve.maxQueueDepth"
SERVE_QUERY_TIMEOUT_SECONDS = "hyperspace.serve.queryTimeoutSeconds"
SERVE_PLAN_CACHE_ENABLED = "hyperspace.serve.planCache.enabled"
SERVE_PLAN_CACHE_MAX_ENTRIES = "hyperspace.serve.planCache.maxEntries"
SERVE_RESULT_CACHE_ENABLED = "hyperspace.serve.resultCache.enabled"
SERVE_RESULT_CACHE_MAX_BYTES = "hyperspace.serve.resultCache.maxBytes"
RETRY_MAX_ATTEMPTS = "hyperspace.retry.maxAttempts"
RETRY_BACKOFF_BASE = "hyperspace.retry.backoffBaseSeconds"
RETRY_CAS_ATTEMPTS = "hyperspace.retry.casAttempts"
FALLBACK_ENABLED = "hyperspace.fallback.enabled"
RECOVER_ON_ACCESS = "hyperspace.recover.onAccess"
RECOVER_GRACE_SECONDS = "hyperspace.recover.graceSeconds"

# Directory-layout constants (reference index/IndexConstants.scala:38-39).
HYPERSPACE_LOG_DIR = "_hyperspace_log"
DATA_VERSION_PREFIX = "v__="
LATEST_STABLE_LOG_NAME = "latestStable"

DEFAULT_NUM_BUCKETS = 8
DEFAULT_CACHE_EXPIRY_SECONDS = 300.0
DEFAULT_HYBRID_SCAN_MAX_APPENDED_RATIO = 0.3
DEFAULT_BUILD_MEMORY_BUDGET = 4 << 30
DEFAULT_JOIN_VENUE = "auto"
DEFAULT_JOIN_VENUE_MIN_MBPS = 200.0
DEFAULT_JOIN_BROADCAST_MAX_ROWS = 4_000_000
DEFAULT_JOIN_REBUCKETIZE = "auto"
# Lazy recovery leaves a transient log alone until it is at least this
# stale (entry timestamp), so listing indexes cannot cancel a LIVE
# concurrent writer's in-flight action. Explicit recover() ignores it.
DEFAULT_RECOVER_GRACE_SECONDS = 300.0
DEFAULT_SERVE_WORKERS = 4
DEFAULT_SERVE_MAX_QUEUE_DEPTH = 32
DEFAULT_SERVE_PLAN_CACHE_MAX_ENTRIES = 128
DEFAULT_SERVE_RESULT_CACHE_MAX_BYTES = 256 << 20


def _as_bool(value: Any) -> bool:
    return bool(value) if not isinstance(value, str) else value.lower() == "true"


@dataclasses.dataclass
class HyperspaceConf:
    """Per-session configuration with string-key overrides."""

    system_path: str = ""
    num_buckets: int = DEFAULT_NUM_BUCKETS
    cache_expiry_seconds: float = DEFAULT_CACHE_EXPIRY_SECONDS
    hybrid_scan_enabled: bool = False
    hybrid_scan_max_appended_ratio: float = DEFAULT_HYBRID_SCAN_MAX_APPENDED_RATIO
    build_memory_budget_bytes: int = DEFAULT_BUILD_MEMORY_BUDGET
    build_chunk_bytes: int = 0  # 0 = derived from the budget
    join_venue: str = DEFAULT_JOIN_VENUE
    join_venue_min_mbps: float = DEFAULT_JOIN_VENUE_MIN_MBPS
    build_venue: str = DEFAULT_JOIN_VENUE
    agg_venue: str = DEFAULT_JOIN_VENUE
    sort_venue: str = DEFAULT_JOIN_VENUE
    filter_venue: str = DEFAULT_JOIN_VENUE
    join_broadcast_max_rows: int = DEFAULT_JOIN_BROADCAST_MAX_ROWS
    join_rebucketize: str = DEFAULT_JOIN_REBUCKETIZE
    validate_plans: bool = True
    fallback_enabled: bool = True
    recover_on_access: bool = True
    recover_grace_seconds: float = DEFAULT_RECOVER_GRACE_SECONDS
    serve_workers: int = DEFAULT_SERVE_WORKERS
    serve_max_queue_depth: int = DEFAULT_SERVE_MAX_QUEUE_DEPTH
    serve_query_timeout_seconds: float = 0.0  # 0 = no per-query timeout
    serve_plan_cache_enabled: bool = True
    serve_plan_cache_max_entries: int = DEFAULT_SERVE_PLAN_CACHE_MAX_ENTRIES
    serve_result_cache_enabled: bool = False  # opt-in: results pin host memory
    serve_result_cache_max_bytes: int = DEFAULT_SERVE_RESULT_CACHE_MAX_BYTES
    overrides: dict[str, Any] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        if not self.system_path:
            self.system_path = os.path.join(os.getcwd(), "spark-warehouse", "indexes")

    def set(self, key: str, value: Any) -> None:
        self.overrides[key] = value
        if key == INDEX_SYSTEM_PATH:
            self.system_path = str(value)
        elif key == INDEX_NUM_BUCKETS:
            self.num_buckets = int(value)
        elif key == INDEX_CACHE_EXPIRY_SECONDS:
            self.cache_expiry_seconds = float(value)
        elif key == INDEX_HYBRID_SCAN_ENABLED:
            self.hybrid_scan_enabled = bool(value) if not isinstance(value, str) else value.lower() == "true"
        elif key == INDEX_HYBRID_SCAN_MAX_APPENDED_RATIO:
            self.hybrid_scan_max_appended_ratio = float(value)
        elif key == INDEX_BUILD_MEMORY_BUDGET:
            self.build_memory_budget_bytes = int(value)
        elif key == INDEX_BUILD_CHUNK_BYTES:
            self.build_chunk_bytes = int(value)
        elif key == JOIN_VENUE:
            self.join_venue = str(value)
        elif key == JOIN_VENUE_MIN_MBPS:
            self.join_venue_min_mbps = float(value)
        elif key == BUILD_VENUE:
            self.build_venue = str(value)
        elif key == AGG_VENUE:
            self.agg_venue = str(value)
        elif key == SORT_VENUE:
            self.sort_venue = str(value)
        elif key == FILTER_VENUE:
            self.filter_venue = str(value)
        elif key == JOIN_BROADCAST_MAX_ROWS:
            self.join_broadcast_max_rows = int(value)
        elif key == JOIN_REBUCKETIZE:
            self.join_rebucketize = str(value)
        elif key == ANALYSIS_VALIDATE:
            self.validate_plans = _as_bool(value)
        elif key == FALLBACK_ENABLED:
            self.fallback_enabled = _as_bool(value)
        elif key == RECOVER_ON_ACCESS:
            self.recover_on_access = _as_bool(value)
        elif key == RECOVER_GRACE_SECONDS:
            self.recover_grace_seconds = float(value)
        elif key == SERVE_WORKERS:
            self.serve_workers = int(value)
        elif key == SERVE_MAX_QUEUE_DEPTH:
            self.serve_max_queue_depth = int(value)
        elif key == SERVE_QUERY_TIMEOUT_SECONDS:
            self.serve_query_timeout_seconds = float(value)
        elif key == SERVE_PLAN_CACHE_ENABLED:
            self.serve_plan_cache_enabled = _as_bool(value)
        elif key == SERVE_PLAN_CACHE_MAX_ENTRIES:
            self.serve_plan_cache_max_entries = int(value)
        elif key == SERVE_RESULT_CACHE_ENABLED:
            self.serve_result_cache_enabled = _as_bool(value)
        elif key == SERVE_RESULT_CACHE_MAX_BYTES:
            self.serve_result_cache_max_bytes = int(value)
        elif key == FAULTS_ENABLED:
            # Process-global kill switch for the injection harness —
            # matches the process-global filesystem state it guards.
            from hyperspace_tpu import faults

            faults.set_enabled(_as_bool(value))
        elif key == OBS_ENABLED:
            # Process-global like the metrics/sink it feeds (obs/trace.py).
            from hyperspace_tpu.obs import trace as _obs_trace

            _obs_trace.set_enabled(_as_bool(value))
        elif key == OBS_SINK:
            from hyperspace_tpu.obs import trace as _obs_trace

            _obs_trace.configure(sink=str(value) if value else None)
        elif key == RETRY_MAX_ATTEMPTS:
            from hyperspace_tpu.utils import retry

            retry.configure(max_attempts=int(value))
        elif key == RETRY_BACKOFF_BASE:
            from hyperspace_tpu.utils import retry

            retry.configure(backoff_base=float(value))
        elif key == RETRY_CAS_ATTEMPTS:
            from hyperspace_tpu.utils import retry

            retry.configure(cas_attempts=int(value))

    def get(self, key: str, default: Any = None) -> Any:
        if key in self.overrides:
            return self.overrides[key]
        if key == INDEX_SYSTEM_PATH:
            return self.system_path
        if key == INDEX_NUM_BUCKETS:
            return self.num_buckets
        if key == INDEX_CACHE_EXPIRY_SECONDS:
            return self.cache_expiry_seconds
        if key == INDEX_HYBRID_SCAN_ENABLED:
            return self.hybrid_scan_enabled
        if key == INDEX_HYBRID_SCAN_MAX_APPENDED_RATIO:
            return self.hybrid_scan_max_appended_ratio
        if key == INDEX_BUILD_MEMORY_BUDGET:
            return self.build_memory_budget_bytes
        if key == INDEX_BUILD_CHUNK_BYTES:
            return self.build_chunk_bytes
        if key == JOIN_VENUE:
            return self.join_venue
        if key == JOIN_VENUE_MIN_MBPS:
            return self.join_venue_min_mbps
        if key == BUILD_VENUE:
            return self.build_venue
        if key == AGG_VENUE:
            return self.agg_venue
        if key == SORT_VENUE:
            return self.sort_venue
        if key == FILTER_VENUE:
            return self.filter_venue
        if key == JOIN_BROADCAST_MAX_ROWS:
            return self.join_broadcast_max_rows
        if key == JOIN_REBUCKETIZE:
            return self.join_rebucketize
        if key == ANALYSIS_VALIDATE:
            return self.validate_plans
        if key == FALLBACK_ENABLED:
            return self.fallback_enabled
        if key == RECOVER_ON_ACCESS:
            return self.recover_on_access
        if key == RECOVER_GRACE_SECONDS:
            return self.recover_grace_seconds
        if key == SERVE_WORKERS:
            return self.serve_workers
        if key == SERVE_MAX_QUEUE_DEPTH:
            return self.serve_max_queue_depth
        if key == SERVE_QUERY_TIMEOUT_SECONDS:
            return self.serve_query_timeout_seconds
        if key == SERVE_PLAN_CACHE_ENABLED:
            return self.serve_plan_cache_enabled
        if key == SERVE_PLAN_CACHE_MAX_ENTRIES:
            return self.serve_plan_cache_max_entries
        if key == SERVE_RESULT_CACHE_ENABLED:
            return self.serve_result_cache_enabled
        if key == SERVE_RESULT_CACHE_MAX_BYTES:
            return self.serve_result_cache_max_bytes
        if key == OBS_ENABLED:
            from hyperspace_tpu.obs import trace as _obs_trace

            return _obs_trace.enabled()
        if key == OBS_SINK:
            from hyperspace_tpu.obs import trace as _obs_trace

            return _obs_trace.sink_path()
        return default
