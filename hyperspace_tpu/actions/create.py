"""CreateAction: build a new covering index (CREATING → ACTIVE).

Reference parity: actions/CreateAction.scala:27-75 +
actions/CreateActionBase.scala:30-121. Validation requires a scan-only source
plan (CreateAction.scala:42-48), schema containment (:64-70) and a free index
name (:54-61). `build_log_entry` assembles the full IndexLogEntry — selected
schema, numBuckets from conf, the JSON plan (vs. the reference's Kryo blob),
the file-based signature and the source file list
(CreateActionBase.scala:38-97). `op` runs the device build pipeline — the
hot path: select columns → hash-bucketize (all_to_all over the mesh) →
per-bucket sort → persist buckets (CreateActionBase.scala:99-120).

The pipeline is injected via the `IndexWriter` protocol — the DI seam the
tests use (analog of index/factories.scala).
"""

from __future__ import annotations

from pathlib import Path
from typing import Protocol

from hyperspace_tpu import stats as _stats
from hyperspace_tpu.actions import states
from hyperspace_tpu.actions.base import Action
from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.index.index_config import IndexConfig
from hyperspace_tpu.metadata.data_manager import IndexDataManager
from hyperspace_tpu.metadata.log_entry import (
    Content,
    CoveringIndex,
    IndexLogEntry,
    Source,
)
from hyperspace_tpu.metadata.log_manager import IndexLogManager
from hyperspace_tpu.plan.nodes import LogicalPlan, Scan
from hyperspace_tpu.signature import create_signature_provider


class IndexWriter(Protocol):
    """The device build pipeline seam."""

    def write(
        self,
        plan: LogicalPlan,
        columns: list[str],
        indexed_columns: list[str],
        num_buckets: int,
        dest_path: Path,
    ) -> None: ...


class CreateActionBase(Action):
    transient_state = states.CREATING
    final_state = states.ACTIVE

    def __init__(
        self,
        plan: LogicalPlan,
        index_config: IndexConfig,
        log_manager: IndexLogManager,
        data_manager: IndexDataManager,
        index_path: Path,
        conf: HyperspaceConf,
        writer: IndexWriter,
    ):
        super().__init__(log_manager)
        self.plan = plan
        self.index_config = index_config
        self.data_manager = data_manager
        self.index_path = Path(index_path)
        self.conf = conf
        self.writer = writer
        self._version: int | None = None

    @property
    def _version_id(self) -> int:
        """Next data version dir (CreateActionBase.scala:31-36). Memoized
        on first access: once op() starts creating the directory, a
        recomputation would see it and skip ahead — the log entry, the
        build destination, and the failure cleanup must all name the SAME
        version."""
        if self._version is None:
            latest = self.data_manager.get_latest_version_id()
            self._version = 0 if latest is None else latest + 1
        return self._version

    def cleanup_failed_op(self) -> None:
        """A failed build leaves a partial `v__=N`; quarantine it so it
        can never be listed as index data (and never collides with the
        next attempt's version numbering)."""
        try:
            self.data_manager.quarantine(self._version_id)
        except Exception:
            # Must-not-raise path, but never silent: recover()'s orphan
            # GC owns whatever this leaves behind.
            _stats.increment("action.cleanup_failed")

    def _num_buckets(self) -> int:
        return int(self.conf.num_buckets)

    def _source_files(self) -> list:
        """The file snapshot this build indexes. Base: one live listing of
        the plan's leaves. Subclasses that index a pre-computed snapshot
        (incremental refresh) override this so the entry can never claim
        files the build didn't see."""
        from hyperspace_tpu.signature import collect_leaf_files

        files = []
        for leaf in self.plan.leaves():
            files.extend(collect_leaf_files(leaf))
        return files

    def build_log_entry(self) -> IndexLogEntry:
        from hyperspace_tpu.metadata.log_entry import Fingerprint
        from hyperspace_tpu.signature import fingerprint_files

        cfg = self.index_config
        plan_schema = self.plan.schema
        selected = plan_schema.select(cfg.all_columns)
        num_buckets = self._num_buckets()
        # Single listing pass: the fingerprint and the recorded file list are
        # derived from the same snapshot so they can never diverge.
        files = self._source_files()
        provider = create_signature_provider()
        fp = Fingerprint(kind=provider.name, value=fingerprint_files(files))
        version = self._version_id
        return IndexLogEntry(
            name=cfg.index_name,
            derived_dataset=CoveringIndex(
                indexed_columns=[plan_schema.field(c).name for c in cfg.indexed_columns],
                included_columns=[plan_schema.field(c).name for c in cfg.included_columns],
                schema=selected.to_json(),
                num_buckets=num_buckets,
            ),
            content=Content(root=str(self.index_path), directories=[f"v__={version}"]),
            source=Source(plan=self.plan.to_json(), fingerprint=fp, files=files),
        )

    def op(self) -> None:
        entry = self.log_entry
        dest = self.data_manager.get_path(self._version_id)
        self.writer.write(
            self.plan,
            entry.derived_dataset.all_columns,
            entry.derived_dataset.indexed_columns,
            entry.derived_dataset.num_buckets,
            dest,
        )


class CreateAction(CreateActionBase):
    def validate(self) -> None:
        # Scan-only source plans (CreateAction.scala:42-48).
        if not isinstance(self.plan, Scan):
            raise HyperspaceError(
                "only scan-only (single relation) plans are supported for createIndex"
            )
        # Schema containment (CreateAction.scala:64-70).
        schema = self.plan.schema
        for c in self.index_config.all_columns:
            if c not in schema:
                raise HyperspaceError(f"column {c!r} not found in source schema {schema.names}")
        # Name non-collision (CreateAction.scala:54-61).
        latest = self.log_manager.get_latest_log()
        if latest is not None and latest.state != states.DOESNOTEXIST:
            raise HyperspaceError(
                f"another index with name {self.index_config.index_name!r} already exists "
                f"(state={latest.state})"
            )
