"""Re-export of the lifecycle state constants.

The constants live in `hyperspace_tpu.states` (a leaf module) so that the
metadata plane can import them without pulling in the actions package —
mirrors actions/Constants.scala:115-129 in the reference.
"""

from hyperspace_tpu.states import (  # noqa: F401
    ACTIVE,
    ALL_STATES,
    CREATING,
    DELETED,
    DELETING,
    DOESNOTEXIST,
    OPTIMIZING,
    REFRESHING,
    RESTORING,
    STABLE_STATES,
    VACUUMING,
)
