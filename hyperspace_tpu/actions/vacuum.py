"""VacuumAction: hard delete (VACUUMING → DOESNOTEXIST).

Reference parity: actions/VacuumAction.scala:23-52 — valid from DELETED; op
deletes every data version directory newest → 0 (VacuumAction.scala:45-51).
The log itself stays so the name's history survives.
"""

from __future__ import annotations

import dataclasses

from hyperspace_tpu.actions import states
from hyperspace_tpu.actions.base import Action
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.metadata.data_manager import IndexDataManager
from hyperspace_tpu.metadata.log_entry import IndexLogEntry
from hyperspace_tpu.metadata.log_manager import IndexLogManager


class VacuumAction(Action):
    transient_state = states.VACUUMING
    final_state = states.DOESNOTEXIST

    def __init__(self, log_manager: IndexLogManager, data_manager: IndexDataManager):
        super().__init__(log_manager)
        self.data_manager = data_manager
        self.previous_entry = log_manager.get_latest_log()
        if self.previous_entry is None:
            raise HyperspaceError("no index to vacuum")

    def validate(self) -> None:
        if self.previous_entry.state != states.DELETED:
            raise HyperspaceError(
                f"vacuum is only supported in {states.DELETED} state "
                f"(found {self.previous_entry.state})"
            )

    def op(self) -> None:
        for vid in reversed(self.data_manager.get_version_ids()):
            self.data_manager.delete(vid)

    def build_log_entry(self) -> IndexLogEntry:
        return dataclasses.replace(self.previous_entry)
