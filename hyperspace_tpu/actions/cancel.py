"""CancelAction: crash recovery for actions that died mid-flight.

Reference parity: actions/CancelAction.scala:34-66 — from any transient
state, roll *forward* to the state of the last stable log entry (or
DOESNOTEXIST if none; a dying VACUUMING cancels forward to DOESNOTEXIST);
rejected when the index is already in a stable state
(CancelAction.scala:54-60). Partial data files from the failed job are left
behind (same acknowledged limitation as the reference).
"""

from __future__ import annotations

import dataclasses

from hyperspace_tpu.actions import states
from hyperspace_tpu.actions.base import Action
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.metadata.log_entry import IndexLogEntry
from hyperspace_tpu.metadata.log_manager import IndexLogManager


class CancelAction(Action):
    transient_state = states.DOESNOTEXIST  # overridden below; begin() skipped

    def __init__(self, log_manager: IndexLogManager):
        super().__init__(log_manager)
        self.previous_entry = log_manager.get_latest_log()
        if self.previous_entry is None:
            raise HyperspaceError("no index to cancel")

    @property
    def final_state(self) -> str:  # type: ignore[override]
        if self.previous_entry.state == states.VACUUMING:
            return states.DOESNOTEXIST
        stable = self.log_manager.get_latest_stable_log()
        return stable.state if stable is not None else states.DOESNOTEXIST

    def validate(self) -> None:
        if self.previous_entry.state in states.STABLE_STATES:
            raise HyperspaceError(
                f"cancel is not supported in stable state {self.previous_entry.state}"
            )

    def begin(self) -> None:
        # Cancel is a single forward transition — no transient phase.
        pass

    def end(self) -> None:
        entry = self.log_entry.with_state(self.final_state)
        final_id = self.base_id + 1
        self._save_entry(final_id, entry)
        # Atomic pointer overwrite — same no-delete rule as Action.end().
        self.log_manager.create_latest_stable_log(final_id)

    def build_log_entry(self) -> IndexLogEntry:
        stable = self.log_manager.get_latest_stable_log()
        base = stable if stable is not None else self.previous_entry
        return dataclasses.replace(base)
