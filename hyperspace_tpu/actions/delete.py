"""DeleteAction: soft delete (DELETING → DELETED).

Reference parity: actions/DeleteAction.scala:24-44 — op is a no-op; only the
log transitions, so the index data stays on disk for `restore`. Valid from
ACTIVE.
"""

from __future__ import annotations

import dataclasses

from hyperspace_tpu.actions import states
from hyperspace_tpu.actions.base import Action
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.metadata.log_entry import IndexLogEntry
from hyperspace_tpu.metadata.log_manager import IndexLogManager


class DeleteAction(Action):
    transient_state = states.DELETING
    final_state = states.DELETED

    def __init__(self, log_manager: IndexLogManager):
        super().__init__(log_manager)
        self.previous_entry = log_manager.get_latest_log()
        if self.previous_entry is None:
            raise HyperspaceError("no index to delete")

    def validate(self) -> None:
        if self.previous_entry.state != states.ACTIVE:
            raise HyperspaceError(
                f"delete is only supported in {states.ACTIVE} state "
                f"(found {self.previous_entry.state})"
            )

    def build_log_entry(self) -> IndexLogEntry:
        return dataclasses.replace(self.previous_entry)
