"""The Action protocol: a two-phase commit over the operation log.

Reference parity: actions/Action.scala:33-96. Every lifecycle operation runs

    run() = validate(); begin(); op(); end()

where `begin` CAS-writes log id `base_id + 1` in the transient state and
`end` CAS-writes `base_id + 2` in the final state, then swaps the
`latestStable` pointer (Action.scala:47-73). If either CAS write loses to a
concurrent writer, the action aborts with "Could not acquire proper state"
(Action.scala:75-80) — single-writer optimistic concurrency.

An action that dies between begin and end leaves the index in the transient
state; `cancel` rolls it forward to the last stable state (see cancel.py).
"""

from __future__ import annotations

from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.metadata.log_entry import IndexLogEntry
from hyperspace_tpu.metadata.log_manager import IndexLogManager


class Action:
    transient_state: str
    final_state: str

    def __init__(self, log_manager: IndexLogManager):
        self.log_manager = log_manager
        self._base_id: int | None = None
        self._log_entry: IndexLogEntry | None = None

    # -- extension points -------------------------------------------------
    def validate(self) -> None:
        """Raise HyperspaceError if this action is not permitted now."""

    def op(self) -> None:
        """Do the work (data plane). Default: metadata-only transition."""

    def build_log_entry(self) -> IndexLogEntry:
        """Construct the entry this action commits (lazily, once)."""
        raise NotImplementedError

    # -- protocol ---------------------------------------------------------
    @property
    def base_id(self) -> int:
        if self._base_id is None:
            latest = self.log_manager.get_latest_id()
            self._base_id = -1 if latest is None else latest
        return self._base_id

    @property
    def log_entry(self) -> IndexLogEntry:
        if self._log_entry is None:
            self._log_entry = self.build_log_entry()
        return self._log_entry

    def _save_entry(self, id: int, entry: IndexLogEntry) -> None:
        if not self.log_manager.write_log(id, entry):
            raise HyperspaceError(
                "Could not acquire proper state: concurrent writer committed "
                f"log id {id} first"
            )

    def begin(self) -> None:
        entry = self.log_entry.with_state(self.transient_state)
        self._save_entry(self.base_id + 1, entry)

    def end(self) -> None:
        entry = self.log_entry.with_state(self.final_state)
        final_id = self.base_id + 2
        self._save_entry(final_id, entry)
        self.log_manager.delete_latest_stable_log()
        self.log_manager.create_latest_stable_log(final_id)

    def run(self) -> None:
        self.validate()
        self.begin()
        self.op()
        self.end()
