"""The Action protocol: a two-phase commit over the operation log.

Reference parity: actions/Action.scala:33-96. Every lifecycle operation runs

    run() = validate(); begin(); op(); end()

where `begin` CAS-writes log id `base_id + 1` in the transient state and
`end` CAS-writes `base_id + 2` in the final state, then swaps the
`latestStable` pointer (Action.scala:47-73). If either CAS write loses to a
concurrent writer, the action aborts with "Could not acquire proper state"
(Action.scala:75-80) — single-writer optimistic concurrency.

Failure semantics (docs/fault_tolerance.md):

- An `op()` that raises an ordinary Exception is ROLLED BACK in-process:
  a roll-back entry restoring the last stable state is CAS-written at
  `base_id + 2`, the `latestStable` pointer is repointed, and the
  action's partial data (`cleanup_failed_op`) is quarantined. The log
  never stays transient because of a mere software failure.
- A hard crash (process death, simulated by faults.CrashPoint — a
  BaseException this handler deliberately does not catch) leaves the
  transient entry behind; `Hyperspace.recover()` repairs it from the
  next process, rolling forward/back exactly like `cancel` (cancel.py).
- `end()` keeps the `latestStable` pointer present at all times: the
  pointer file is atomically REPLACED (write_json's temp + os.replace),
  never deleted first, so a concurrent reader can no longer catch the
  window where the pointer is absent and fall into the backward scan.
"""

from __future__ import annotations

import dataclasses

from hyperspace_tpu import stats as _stats
from hyperspace_tpu import states
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.metadata.log_entry import IndexLogEntry
from hyperspace_tpu.metadata.log_manager import IndexLogManager
from hyperspace_tpu.obs import trace as obs_trace
from hyperspace_tpu.utils import retry


class Action:
    transient_state: str
    final_state: str

    def __init__(self, log_manager: IndexLogManager):
        self.log_manager = log_manager
        self._base_id: int | None = None
        self._log_entry: IndexLogEntry | None = None

    # -- extension points -------------------------------------------------
    def validate(self) -> None:
        """Raise HyperspaceError if this action is not permitted now."""

    def op(self) -> None:
        """Do the work (data plane). Default: metadata-only transition."""

    def build_log_entry(self) -> IndexLogEntry:
        """Construct the entry this action commits (lazily, once)."""
        raise NotImplementedError

    def cleanup_failed_op(self) -> None:
        """Quarantine/remove partial data a failed `op()` left behind.
        Default: nothing (metadata-only actions have no data plane).
        Must never raise."""

    # -- protocol ---------------------------------------------------------
    @property
    def base_id(self) -> int:
        if self._base_id is None:
            latest = self.log_manager.get_latest_id()
            self._base_id = -1 if latest is None else latest
        return self._base_id

    @property
    def log_entry(self) -> IndexLogEntry:
        if self._log_entry is None:
            self._log_entry = self.build_log_entry()
        return self._log_entry

    def _save_entry(self, id: int, entry: IndexLogEntry) -> None:
        if not self.log_manager.write_log(id, entry):
            raise HyperspaceError(
                "Could not acquire proper state: concurrent writer committed "
                f"log id {id} first"
            )

    def begin(self) -> None:
        entry = self.log_entry.with_state(self.transient_state)
        self._save_entry(self.base_id + 1, entry)

    def end(self) -> None:
        entry = self.log_entry.with_state(self.final_state)
        final_id = self.base_id + 2
        self._save_entry(final_id, entry)
        # Atomic overwrite of the pointer (temp file + os.replace inside
        # create_latest_stable_log): a delete-then-recreate here would
        # reopen the race where a reader finds no pointer and pays the
        # backward scan — or, crashing between the two calls, leaves no
        # pointer at all.
        self.log_manager.create_latest_stable_log(final_id)

    def run(self) -> None:
        """Execute the two-phase protocol, with rollback on op() failure.

        CAS contention at begin() aborts by default (single-writer
        optimistic concurrency, Action.scala:75-80); when
        `hyperspace.retry.casAttempts` > 1 the whole protocol re-reads
        the log and retries — useful for workloads where independent
        writers race on DIFFERENT indexes through a shared log id space.
        """
        # A root trace when called bare (create/refresh/... from user
        # code), a child span when the session is already tracing. Spans
        # close on BaseException too, so a simulated crash (CrashPoint)
        # still records which phase died before propagating.
        with obs_trace.trace(f"action.{type(self).__name__}"):
            attempts = retry.cas_attempts()
            for attempt in range(attempts):
                with obs_trace.span("action.validate"):
                    self.validate()
                try:
                    with obs_trace.span("action.begin", attempt=attempt + 1):
                        self.begin()
                except HyperspaceError:
                    if attempt + 1 >= attempts:
                        raise
                    # Concurrent writer won this id: re-read the world and
                    # re-validate from scratch.
                    self._base_id = None
                    self._log_entry = None
                    continue
                break
            try:
                with obs_trace.span("action.op"):
                    self.op()
            except Exception:
                # Software failure mid-op (NOT a crash: CrashPoint is a
                # BaseException and skips this handler by design). Roll the
                # log back to the last stable state and quarantine partial
                # data, then surface the original error.
                with obs_trace.span("action.rollback"):
                    self._rollback_failed_op()
                raise
            try:
                with obs_trace.span("action.end"):
                    self.end()
            except HyperspaceError:
                # Lost the final CAS: a concurrent writer committed over us
                # while op() ran. The winner's entry stands — only our
                # partial data needs quarantining.
                self.cleanup_failed_op()
                raise

    def _rollback_failed_op(self) -> None:
        """Best-effort in-process recovery for a failed op(): CAS-write a
        roll-back entry at `base_id + 2` restoring the last stable state
        (DOESNOTEXIST when there is none, or for a dying vacuum — same
        rules as cancel.py), repoint `latestStable`, quarantine partial
        data. Every step tolerates failure: whatever this leaves undone,
        `recover()` finishes from the next process."""
        try:
            stable = self.log_manager.get_latest_stable_log()
            if self.transient_state == states.VACUUMING:
                state = states.DOESNOTEXIST
            else:
                state = stable.state if stable is not None else states.DOESNOTEXIST
            base = stable if stable is not None else self.log_entry
            rollback = dataclasses.replace(base).with_state(state)
            rollback_id = self.base_id + 2
            if self.log_manager.write_log(rollback_id, rollback):
                self.log_manager.create_latest_stable_log(rollback_id)
                _stats.increment("action.rolled_back")
        except Exception as rb_err:
            # Must-not-raise path, but never a SILENT one: a failed
            # rollback means recover() owns the repair — say so.
            _stats.increment("action.rollback_failed")
            obs_trace.event("action.rollback_failed", error=str(rb_err))
        try:
            self.cleanup_failed_op()
        except Exception as cl_err:
            _stats.increment("action.cleanup_failed")
            obs_trace.event("action.cleanup_failed", error=str(cl_err))
