from hyperspace_tpu.actions import states
from hyperspace_tpu.actions.base import Action
from hyperspace_tpu.actions.create import CreateAction, IndexWriter
from hyperspace_tpu.actions.refresh import RefreshAction, RefreshIncrementalAction
from hyperspace_tpu.actions.delete import DeleteAction
from hyperspace_tpu.actions.restore import RestoreAction
from hyperspace_tpu.actions.vacuum import VacuumAction
from hyperspace_tpu.actions.cancel import CancelAction
from hyperspace_tpu.actions.optimize import OptimizeAction

__all__ = [
    "states",
    "Action",
    "CreateAction",
    "IndexWriter",
    "RefreshAction",
    "RefreshIncrementalAction",
    "DeleteAction",
    "RestoreAction",
    "VacuumAction",
    "CancelAction",
    "OptimizeAction",
]
