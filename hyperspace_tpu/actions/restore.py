"""RestoreAction: undo a soft delete (RESTORING → ACTIVE).

Reference parity: actions/RestoreAction.scala:27-47 — op is a no-op; valid
from DELETED.
"""

from __future__ import annotations

import dataclasses

from hyperspace_tpu.actions import states
from hyperspace_tpu.actions.base import Action
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.metadata.log_entry import IndexLogEntry
from hyperspace_tpu.metadata.log_manager import IndexLogManager


class RestoreAction(Action):
    transient_state = states.RESTORING
    final_state = states.ACTIVE

    def __init__(self, log_manager: IndexLogManager):
        super().__init__(log_manager)
        self.previous_entry = log_manager.get_latest_log()
        if self.previous_entry is None:
            raise HyperspaceError("no index to restore")

    def validate(self) -> None:
        if self.previous_entry.state != states.DELETED:
            raise HyperspaceError(
                f"restore is only supported in {states.DELETED} state "
                f"(found {self.previous_entry.state})"
            )

    def build_log_entry(self) -> IndexLogEntry:
        return dataclasses.replace(self.previous_entry)
