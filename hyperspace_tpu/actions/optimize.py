"""OptimizeAction: compact index data files (OPTIMIZING → ACTIVE).

The v0.2 reference does not yet ship optimizeIndex (it arrives in later
Hyperspace releases), but the BASELINE configs require an
incremental-refresh + compaction loop (NYC-Taxi), so it is first-class here:
valid from ACTIVE, op merges the per-bucket delta files produced by
incremental refreshes into one sorted file per bucket, written to the next
`v__=` version; the log swap makes the compacted version live.

The compaction itself is injected via the same writer seam as create
(actions/create.py) — an `IndexCompactor` with a `compact` method.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Protocol

from hyperspace_tpu import stats as _stats
from hyperspace_tpu.actions import states
from hyperspace_tpu.actions.base import Action
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.metadata.data_manager import IndexDataManager
from hyperspace_tpu.metadata.log_entry import IndexLogEntry
from hyperspace_tpu.metadata.log_manager import IndexLogManager


class IndexCompactor(Protocol):
    def compact(self, entry: IndexLogEntry, src_paths: list[Path], dest_path: Path) -> None: ...


class OptimizeAction(Action):
    transient_state = states.OPTIMIZING
    final_state = states.ACTIVE

    def __init__(
        self,
        log_manager: IndexLogManager,
        data_manager: IndexDataManager,
        compactor: IndexCompactor,
    ):
        super().__init__(log_manager)
        self.data_manager = data_manager
        self.compactor = compactor
        self._version: int | None = None
        self.previous_entry = log_manager.get_latest_log()
        if self.previous_entry is None:
            raise HyperspaceError("no index to optimize")
        dd = self.previous_entry.derived_dataset
        if dd is not None and dd.kind != "CoveringIndex":
            raise HyperspaceError(
                f"optimize of {dd.kind} indexes is not supported yet"
            )

    def validate(self) -> None:
        if self.previous_entry.state != states.ACTIVE:
            raise HyperspaceError(
                f"optimize is only supported in {states.ACTIVE} state "
                f"(found {self.previous_entry.state})"
            )

    @property
    def _version_id(self) -> int:
        # Memoized for the same reason as CreateActionBase: entry, dest,
        # and failure cleanup must agree on one version.
        if self._version is None:
            latest = self.data_manager.get_latest_version_id()
            self._version = 0 if latest is None else latest + 1
        return self._version

    def cleanup_failed_op(self) -> None:
        try:
            self.data_manager.quarantine(self._version_id)
        except Exception:
            # Must-not-raise path, but never silent: recover()'s orphan
            # GC owns whatever this leaves behind.
            _stats.increment("action.cleanup_failed")

    def build_log_entry(self) -> IndexLogEntry:
        entry = dataclasses.replace(self.previous_entry)
        entry.content = dataclasses.replace(entry.content, directories=[f"v__={self._version_id}"])
        return entry

    def op(self) -> None:
        prev_version = self.data_manager.get_latest_version_id()
        if prev_version is None:
            raise HyperspaceError("index has no data to optimize")
        # Compact EVERY live version dir (base + incremental-refresh deltas)
        # into one sorted file per bucket in the next version.
        root = Path(self.previous_entry.content.root)
        srcs = [root / d for d in self.previous_entry.content.directories]
        dest = self.data_manager.get_path(self._version_id)
        self.compactor.compact(self.previous_entry, srcs, dest)
