"""RefreshAction: full rebuild from logged lineage (REFRESHING → ACTIVE).

Reference parity: actions/RefreshAction.scala:30-78 — deserialize the stored
source plan (picking up new source files because the scan re-lists the live
filesystem), re-derive the IndexConfig from the previous entry
(RefreshAction.scala:52-55), re-run the build into the next `v__=` version.
Valid only from ACTIVE (RefreshAction.scala:64-70).
"""

from __future__ import annotations

from pathlib import Path

from hyperspace_tpu.actions import states
from hyperspace_tpu.actions.create import CreateActionBase, IndexWriter
from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.index.index_config import IndexConfig
from hyperspace_tpu.metadata.data_manager import IndexDataManager
from hyperspace_tpu.metadata.log_manager import IndexLogManager
from hyperspace_tpu.plan.nodes import plan_from_json


class RefreshAction(CreateActionBase):
    transient_state = states.REFRESHING
    final_state = states.ACTIVE

    def __init__(
        self,
        log_manager: IndexLogManager,
        data_manager: IndexDataManager,
        index_path: Path,
        conf: HyperspaceConf,
        writer: IndexWriter,
    ):
        prev = log_manager.get_latest_log()
        if prev is None:
            raise HyperspaceError("no index to refresh")
        self.previous_entry = prev
        plan = plan_from_json(prev.source.plan)
        cfg = IndexConfig(
            prev.name,
            prev.derived_dataset.indexed_columns,
            prev.derived_dataset.included_columns,
        )
        super().__init__(plan, cfg, log_manager, data_manager, index_path, conf, writer)

    def _num_buckets(self) -> int:
        # Keep the previous bucket count stable across refreshes.
        return self.previous_entry.derived_dataset.num_buckets

    def validate(self) -> None:
        if self.previous_entry.state != states.ACTIVE:
            raise HyperspaceError(
                f"refresh is only supported in {states.ACTIVE} state "
                f"(found {self.previous_entry.state})"
            )
