"""RefreshAction: full rebuild from logged lineage (REFRESHING → ACTIVE).

Reference parity: actions/RefreshAction.scala:30-78 — deserialize the stored
source plan (picking up new source files because the scan re-lists the live
filesystem), re-derive the IndexConfig from the previous entry
(RefreshAction.scala:52-55), re-run the build into the next `v__=` version.
Valid only from ACTIVE (RefreshAction.scala:64-70).
"""

from __future__ import annotations

import dataclasses
from pathlib import Path

from hyperspace_tpu.actions import states
from hyperspace_tpu.actions.create import CreateActionBase, IndexWriter
from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.index.index_config import IndexConfig
from hyperspace_tpu.metadata.data_manager import IndexDataManager
from hyperspace_tpu.metadata.log_manager import IndexLogManager
from hyperspace_tpu.plan.nodes import plan_from_json


class RefreshAction(CreateActionBase):
    transient_state = states.REFRESHING
    final_state = states.ACTIVE

    def __init__(
        self,
        log_manager: IndexLogManager,
        data_manager: IndexDataManager,
        index_path: Path,
        conf: HyperspaceConf,
        writer: IndexWriter,
    ):
        prev = log_manager.get_latest_log()
        if prev is None:
            raise HyperspaceError("no index to refresh")
        if prev.derived_dataset is not None and prev.derived_dataset.kind != "CoveringIndex":
            raise HyperspaceError(
                f"refresh of {prev.derived_dataset.kind} indexes is not supported yet; "
                "drop and re-create the index"
            )
        self.previous_entry = prev
        plan = plan_from_json(prev.source.plan)
        cfg = IndexConfig(
            prev.name,
            prev.derived_dataset.indexed_columns,
            prev.derived_dataset.included_columns,
        )
        super().__init__(plan, cfg, log_manager, data_manager, index_path, conf, writer)

    def _num_buckets(self) -> int:
        # Keep the previous bucket count stable across refreshes.
        return self.previous_entry.derived_dataset.num_buckets

    def validate(self) -> None:
        if self.previous_entry.state != states.ACTIVE:
            raise HyperspaceError(
                f"refresh is only supported in {states.ACTIVE} state "
                f"(found {self.previous_entry.state})"
            )


class RefreshIncrementalAction(RefreshAction):
    """Incremental refresh: index ONLY the source files appended since the
    last build, writing per-bucket delta files into the next `v__=` version.

    The v0.2 reference only has full-rebuild refresh
    (actions/RefreshAction.scala); incremental refresh + query-time hybrid
    scan arrive in later Hyperspace releases and are required by the
    BASELINE configs (TPC-DS Hybrid Scan, NYC-Taxi refresh loop). Design:

    - diff the live file listing against the logged `source.files`;
    - appended files are bucketized with the SAME bucket count and row-hash
      function as the base build, so bucket b's data is the union of bucket
      b's files across all version dirs — query plans need no re-shuffle;
    - the new log entry lists ALL version dirs in `content.directories` and
      records EXACTLY the indexed snapshot (previous files + the diff —
      never a second live listing, which could claim files written after
      the diff that op() will not index);
    - deleted/modified source files require a full refresh (round-1 scope;
      the reference's lineage-based delete handling is a later feature).
    """

    def __init__(
        self,
        log_manager: IndexLogManager,
        data_manager: IndexDataManager,
        index_path: Path,
        conf: HyperspaceConf,
        writer: IndexWriter,
    ):
        super().__init__(log_manager, data_manager, index_path, conf, writer)
        from hyperspace_tpu.signature import diff_source_files

        self._appended, self._deleted = diff_source_files(self.previous_entry, self.plan)

    def validate(self) -> None:
        super().validate()
        if self._deleted:
            raise HyperspaceError(
                "incremental refresh cannot handle deleted or modified source "
                f"files ({[f.path for f in self._deleted][:3]}...); run a full "
                "refresh instead"
            )
        if not self._appended:
            raise HyperspaceError(
                "refresh aborted: no appended source data files found"
            )

    def _source_files(self) -> list:
        return sorted(
            list(self.previous_entry.source.files) + list(self._appended),
            key=lambda f: f.path,
        )

    def build_log_entry(self) -> IndexLogEntry:
        entry = super().build_log_entry()
        # Keep every prior version dir live: bucket b = union over dirs.
        prev_dirs = list(self.previous_entry.content.directories)
        entry.content = dataclasses.replace(
            entry.content, directories=prev_dirs + [f"v__={self._version_id}"]
        )
        return entry

    def op(self) -> None:
        entry = self.log_entry
        dest = self.data_manager.get_path(self._version_id)
        delta_plan = dataclasses.replace(self.plan, files=[f.path for f in self._appended])
        self.writer.write(
            delta_plan,
            entry.derived_dataset.all_columns,
            entry.derived_dataset.indexed_columns,
            entry.derived_dataset.num_buckets,
            dest,
        )

