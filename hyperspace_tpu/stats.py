"""Process-wide fault-tolerance counters — compat shim over the metrics
registry.

Historically this module held its own ad-hoc ``dict[str, int]``; it is
now a thin facade over the declared registry in
`hyperspace_tpu/obs/metrics.py`, keeping the call-site API
(``increment``/``get``/``snapshot``/``reset``) stable for the fault
plane while everything lands in one exportable place
(docs/observability.md).

Counter names are **declared** in :data:`KNOWN_COUNTERS`; incrementing
an undeclared name raises immediately instead of silently creating a new
counter (the ``increment("retyr.attempts")`` typo class). Lint rule
HSL007 flags undeclared constant names at call sites too, so the typo
never survives to runtime. New counters are added by extending the
tuple below (and its docstring row).

Counter names in use:

- ``retry.attempts``       extra attempts made after a transient failure
- ``retry.exhausted``      retry loops that gave up and re-raised
- ``faults.injected``      faults the injection harness actually fired
- ``index.corruption``     typed corruption detections (bucket/manifest)
- ``fallback.queries``     queries re-planned against source data
- ``action.rolled_back``   op() failures rolled back to the last stable state
- ``recover.rolled``       recover() roll-forwards of a transient log
- ``recover.quarantined_entries``  torn log entries quarantined by recover()
- ``recover.orphans_removed``      unreferenced version dirs GC'd by recover()
- ``metadata.cache.hits``    TTL index-entry cache hits (metadata/cache.py)
- ``metadata.cache.misses``  TTL index-entry cache misses (empty or expired)
- ``action.rollback_failed``  in-process rollback attempts that themselves
  failed (recover() finishes the repair from the next process)
- ``action.cleanup_failed``   partial-data quarantines that failed (the
  orphan GC in recover() sweeps what they left)
- ``recover.on_access_failed``  lazy recover-on-access attempts that
  failed during listing (the entry stays unlisted; explicit recover()
  still applies)
- ``io.footer_cache.hits``    parquet footer parses skipped by the
  mtime-validated footer cache (execution/io.py)
- ``io.footer_cache.misses``  footer parses that actually opened the file
- ``jit_memory.cache_drops``  jax cache drops by the map-count guard
  (utils/jit_memory.py) — each one is a narrowly avoided XLA:CPU
  map-exhaustion segfault, paired with a WARN ``jit.cache_drop`` event
- ``fleet.shared_cache.hits``    disk-backed shared plan/result cache hits
  (serve/fleet/shared_cache.py)
- ``fleet.shared_cache.misses``  shared-cache lookups that found no entry
- ``fleet.shared_cache.evictions``  entries removed by the lease-held
  byte-budget eviction
- ``fleet.shared_cache.errors``  advisory shared-cache IO failures
  (unreadable/unwritable entries — the caller recomputes locally)
- ``fleet.singleflight.leader``  cross-process single-flight claims won
  (this process did the build)
- ``fleet.singleflight.follower_hits``  waits that ended by observing the
  leader's published artifact
- ``fleet.singleflight.takeovers``  stale leases reaped from a crashed
  holder (the fleet un-wedged itself)
- ``fleet.singleflight.local_fallbacks``  waits that expired and fell
  back to a local build (no dedup, full correctness)
- ``fleet.supervisor.restarts``  crashed fleet workers respawned by the
  supervisor (serve/fleet/supervisor.py)
- ``build.exchange.bytes``  decoded bytes exchanged through spill files
  between the pooled build's p1 shards and p2 owners (the cross-process
  ledger, execution/build_exchange.py)
- ``build.worker.crashes``  pooled-build workers found dead without a
  posted result — each one became a typed WorkerCrashed abort instead
  of a hung coordinator (parallel/procpool.py)
- ``device.stage.bytes_zero_copy``  column bytes that crossed the
  Arrow→device staging boundary as read-only buffer VIEWS — no host
  materialization (execution/staging.py, docs/architecture.md "device
  data path")
- ``device.stage.bytes_copied``  column bytes host-materialized during
  staging (nulls, casts, multi-chunk concat, unaligned offset views,
  staging disabled, or the un-cached downgrade path)
- ``device.kernel.fused``  fused Pallas kernel launches on the device
  venue (segment reduce / join-agg run bounds) — each one replaced a
  multi-dispatch lax composition
- ``device.kernel.fallbacks``  device-venue reduces that took the
  always-available jitted lax path while fused kernels were enabled
  (ineligible shape, unprovable exactness, or a failed Pallas lowering)
- ``controller.ticks``  reconciliation steps the self-driving operations
  controller ran while armed (serve/controller.py,
  docs/fault_tolerance.md "self-driving operations")
- ``controller.actuations``  mutations the controller executed through
  the crash-safe protocols (shed engage, quota tighten, heal, sweep)
- ``controller.actuation_failures``  actuations that raised an ordinary
  Exception — recorded (ERROR ``controller.actuation_failed`` event) and
  the reconciliation continued; the failed subsystem's own Action
  rollback already ran
- ``controller.deferred``  actuations the controller decided on but
  held back — per-actuation cooldown still running, background work
  backed off while serve SLOs burn, or observe-only after budget
  exhaustion
- ``controller.heals``  quarantined indexes the controller healed
  (recover() + gated rebuild) without a human in the loop
- ``controller.scale``  fleet scale actuations the controller executed
  (set_target_workers up on sustained saturation, back down on
  recovery)
- ``controller.health_probe_errors``  saturation probes (fleet-health
  aggregate or local server) that raised — the member counts as zero
  load for that tick, but the operator still gets the signal
- ``fleet.worker.scaled``  fleet members added or drained by
  ``FleetSupervisor.set_target_workers`` (counted per member moved,
  paired with an INFO ``fleet.worker.scaled`` event)
- ``faults.delays_injected``  brownout delays the injection harness
  applied (a `delay_s` fault rule firing — the slow-path counterpart
  of ``faults.injected``)
- ``obs.journal.records``  telemetry records appended to this process's
  durable journal (obs/journal.py — events, root spans, metrics
  snapshots, SLO transitions, process markers)
- ``obs.journal.errors``  advisory journal IO failures swallowed by the
  never-raise contract (full disk, unwritable root — the query or
  actuation being observed proceeds untouched)
- ``obs.journal.segments_sealed``  active journal segments atomically
  published as ``segment-<n>.jsonl`` (mkstemp + os.replace)
- ``obs.journal.evictions``  sealed journal segments dropped oldest-first
  by the per-process byte budget (``hyperspace.obs.journal.maxBytes``)
- ``controller.incidents``  incident bundles the controller opened on an
  SLO page, quarantine, or observe-only entry
  (docs/fault_tolerance.md "incident bundles")
- ``controller.incident_errors``  advisory incident-bundle capture
  failures (forensics must never compound the incident)
- ``ingest.ticks``  poll passes the continuous-ingestion daemon ran
  (hyperspace_tpu/ingest/, docs/ingestion.md)
- ``ingest.commits``  micro-batches committed through the incremental
  refresh action (each one is a new crash-safe index version)
- ``ingest.commit_failures``  micro-batch commits that raised an ordinary
  Exception — the Action's own rollback ran; the daemon keeps polling
- ``ingest.rows``  source rows the tailer materialized from CDC
  changelogs into batch files
- ``ingest.bytes``  source bytes the daemon observed arriving (new files
  + materialized CDC batches) — the ingest-throughput ledger
- ``ingest.compactions``  delta-bucket compactions the daemon triggered
  through the gated optimize action
- ``ingest.compact_failures``  compactions that raised an ordinary
  Exception (rolled back by the optimize action itself)
- ``ingest.deferred``  daemon work held back — paused by the controller,
  or compaction deferred behind its gates
- ``ingest.snapshots``  MVCC pinned snapshots taken (ingest/snapshot.py)
- ``ingest.pinned_reads``  queries executed against a pinned snapshot's
  stamp instead of the live latest-stable versions
"""

from __future__ import annotations

from hyperspace_tpu.obs import metrics as _metrics

# The declared counter set. analysis/lint.py parses this tuple (by AST,
# not import — the lint CI job runs dependency-free) to validate
# stats.increment call sites; keep it a plain literal of string
# constants.
KNOWN_COUNTERS = (
    "retry.attempts",
    "retry.exhausted",
    "faults.injected",
    "index.corruption",
    "fallback.queries",
    "action.rolled_back",
    "recover.rolled",
    "recover.quarantined_entries",
    "recover.orphans_removed",
    "metadata.cache.hits",
    "metadata.cache.misses",
    "action.rollback_failed",
    "action.cleanup_failed",
    "recover.on_access_failed",
    "io.footer_cache.hits",
    "io.footer_cache.misses",
    "jit_memory.cache_drops",
    "fleet.shared_cache.hits",
    "fleet.shared_cache.misses",
    "fleet.shared_cache.evictions",
    "fleet.shared_cache.errors",
    "fleet.singleflight.leader",
    "fleet.singleflight.follower_hits",
    "fleet.singleflight.takeovers",
    "fleet.singleflight.local_fallbacks",
    "fleet.supervisor.restarts",
    "build.exchange.bytes",
    "build.worker.crashes",
    "device.stage.bytes_zero_copy",
    "device.stage.bytes_copied",
    "device.kernel.fused",
    "device.kernel.fallbacks",
    "controller.ticks",
    "controller.actuations",
    "controller.actuation_failures",
    "controller.deferred",
    "controller.heals",
    "controller.scale",
    "controller.health_probe_errors",
    "fleet.worker.scaled",
    "faults.delays_injected",
    "obs.journal.records",
    "obs.journal.errors",
    "obs.journal.segments_sealed",
    "obs.journal.evictions",
    "controller.incidents",
    "controller.incident_errors",
    "ingest.ticks",
    "ingest.commits",
    "ingest.commit_failures",
    "ingest.rows",
    "ingest.bytes",
    "ingest.compactions",
    "ingest.compact_failures",
    "ingest.deferred",
    "ingest.snapshots",
    "ingest.pinned_reads",
)

_counters = {name: _metrics.counter(name) for name in KNOWN_COUNTERS}


def increment(name: str, n: int = 1) -> None:
    c = _counters.get(name)
    if c is None:
        raise KeyError(
            f"undeclared counter {name!r} — declare it in stats.KNOWN_COUNTERS "
            f"(silent typo counters are exactly what the declared registry removes)"
        )
    c.inc(n)


def get(name: str) -> int:
    c = _counters.get(name)
    if c is None:
        raise KeyError(f"undeclared counter {name!r} (see stats.KNOWN_COUNTERS)")
    return c.value


def snapshot() -> dict[str, int]:
    """Point-in-time copy of every declared counter."""
    return {name: c.value for name, c in _counters.items()}


def reset() -> None:
    for c in _counters.values():
        c._reset()
