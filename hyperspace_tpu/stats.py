"""Process-wide fault-tolerance counters.

The observability half of the fault-tolerance layer
(docs/fault_tolerance.md): retries, injected faults, detected
corruption, recovery transitions, and query fallbacks all tick a named
counter here, so degradation is measurable instead of silent. Counters
are process-global (matching the filesystem state they describe) and
thread-safe; `snapshot()` is the read API surfaced as
`hyperspace_tpu.stats`.

Counter names in use:

- ``retry.attempts``       extra attempts made after a transient failure
- ``retry.exhausted``      retry loops that gave up and re-raised
- ``faults.injected``      faults the injection harness actually fired
- ``index.corruption``     typed corruption detections (bucket/manifest)
- ``fallback.queries``     queries re-planned against source data
- ``action.rolled_back``   op() failures rolled back to the last stable state
- ``recover.rolled``       recover() roll-forwards of a transient log
- ``recover.quarantined_entries``  torn log entries quarantined by recover()
- ``recover.orphans_removed``      unreferenced version dirs GC'd by recover()
"""

from __future__ import annotations

import threading

_lock = threading.Lock()
_counters: dict[str, int] = {}


def increment(name: str, n: int = 1) -> None:
    with _lock:
        _counters[name] = _counters.get(name, 0) + n


def get(name: str) -> int:
    with _lock:
        return _counters.get(name, 0)


def snapshot() -> dict[str, int]:
    """Point-in-time copy of every counter."""
    with _lock:
        return dict(_counters)


def reset() -> None:
    with _lock:
        _counters.clear()
