"""Filesystem-backed operation log with optimistic concurrency.

Reference parity: index/IndexLogManager.scala:33-155. Layout:

    <index_path>/_hyperspace_log/<id>        immutable JSON entries, id = 0..n
    <index_path>/_hyperspace_log/latestStable  copy of the latest stable entry

Concurrency contract (IndexLogManager.scala:138-154): `write_log` creates the
entry file with compare-and-swap semantics — if a concurrent writer already
created the same id, the call returns False and the caller must abort
("Could not acquire proper state", actions/Action.scala:75-80).

`get_latest_stable_log` prefers the `latestStable` pointer file and falls
back to a backward scan for an entry in a stable state
(IndexLogManager.scala:92-122).
"""

from __future__ import annotations

import os
from pathlib import Path

from hyperspace_tpu.config import HYPERSPACE_LOG_DIR, LATEST_STABLE_LOG_NAME
from hyperspace_tpu.faults import fault_point
from hyperspace_tpu.metadata.log_entry import IndexLogEntry, entry_from_json
from hyperspace_tpu.utils.file_utils import read_json, write_json
from hyperspace_tpu.states import STABLE_STATES


class IndexLogManager:
    def __init__(self, index_path: str | os.PathLike):
        self.index_path = Path(index_path)
        self.log_dir = self.index_path / HYPERSPACE_LOG_DIR

    # -- reads -----------------------------------------------------------
    def get_log(self, id: int) -> IndexLogEntry | None:
        p = self.log_dir / str(id)
        if not p.exists():
            return None
        return entry_from_json(read_json(p))

    def get_latest_id(self) -> int | None:
        if not self.log_dir.is_dir():
            return None
        ids = [int(f.name) for f in self.log_dir.iterdir() if f.name.isdigit()]
        return max(ids) if ids else None

    def get_latest_log(self) -> IndexLogEntry | None:
        latest = self.get_latest_id()
        return None if latest is None else self.get_log(latest)

    def get_latest_stable_log(self) -> IndexLogEntry | None:
        pointer = self.log_dir / LATEST_STABLE_LOG_NAME
        try:
            entry = entry_from_json(read_json(pointer))
            if entry.state in STABLE_STATES:
                return entry
        except (FileNotFoundError, ValueError):
            # Pointer absent, or caught mid delete/recreate by a concurrent
            # Action.end(): fall back to the backward scan.
            pass
        # Backward scan fallback (IndexLogManager.scala:113-122). A torn
        # or garbage entry (crashed writer on a filesystem without atomic
        # create, injected truncation) is skipped, not fatal: the scan's
        # contract is "the last stable state still resolves".
        latest = self.get_latest_id()
        if latest is None:
            return None
        for id in range(latest, -1, -1):
            try:
                entry = self.get_log(id)
            except (ValueError, KeyError, TypeError, OSError):  # noqa: HSL017
                # Not a retry of one entry — the scan's documented
                # contract: a torn entry is skipped, the last STABLE
                # entry still resolves.
                continue
            if entry is not None and entry.state in STABLE_STATES:
                return entry
        return None

    # -- writes ----------------------------------------------------------
    def write_log(self, id: int, entry: IndexLogEntry) -> bool:
        """CAS-create log entry `id`. False ⇒ a concurrent writer won."""
        entry.id = id
        p = self.log_dir / str(id)
        fault_point("log.write", p)
        ok = write_json(p, entry.to_json(), overwrite=False)
        if ok:
            fault_point("log.written", p)
        return ok

    def create_latest_stable_log(self, id: int) -> bool:
        """Copy entry `id` to the latestStable pointer
        (IndexLogManager.scala:92-111)."""
        entry = self.get_log(id)
        if entry is None or entry.state not in STABLE_STATES:
            return False
        p = self.log_dir / LATEST_STABLE_LOG_NAME
        fault_point("log.stable.write", p)
        write_json(p, entry.to_json(), overwrite=True)
        return True

    def delete_latest_stable_log(self) -> bool:
        p = self.log_dir / LATEST_STABLE_LOG_NAME
        try:
            p.unlink(missing_ok=True)
            return True
        except OSError:
            return False

    def quarantine_log(self, id: int) -> bool:
        """Move a torn/garbage log entry aside (recover()'s repair for a
        truncated trailing entry). The renamed file no longer counts for
        `get_latest_id` (non-digit name), so the id becomes writable
        again; the bytes stay on disk for post-mortems."""
        p = self.log_dir / str(id)
        for attempt in range(10):
            suffix = ".corrupt" if attempt == 0 else f".corrupt-{attempt}"
            try:
                os.rename(p, p.with_name(p.name + suffix))
                return True
            except FileExistsError:
                continue
            except OSError:
                return False
        return False
