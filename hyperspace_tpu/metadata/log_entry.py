"""The versioned index log entry model.

Reference parity: index/IndexLogEntry.scala:27-131 and index/LogEntry.scala:22-47.
A log entry is a versioned JSON document:

- mutable envelope: id / state / timestamp / enabled (LogEntry.scala:22-29);
- `name`: index name;
- `derivedDataset`: the CoveringIndex spec — indexed columns, included
  columns, schema, numBuckets (IndexLogEntry.scala:39-47);
- `content`: root of the index data (versioned bucket dirs live below it);
- `source`: lineage — the serialized logical plan, its data fingerprint, and
  the list of source files (IndexLogEntry.scala:61-74). Unlike the
  reference's Base64-Kryo blob (the fragile subsystem flagged in SURVEY.md
  §7), the plan here is our own JSON-native plan IR, so `source.plan` is a
  plain JSON object.

Decoding is keyed on `version` (LogEntry.scala:33-46) so future layouts can
coexist in one log directory.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any

LOG_ENTRY_VERSION = "0.1"


@dataclasses.dataclass
class FileInfo:
    """One source data file: identity for fingerprinting."""

    path: str
    size: int
    mtime_ns: int

    def to_json(self) -> dict[str, Any]:
        return {"path": self.path, "size": self.size, "mtimeNs": self.mtime_ns}

    @staticmethod
    def from_json(d: dict[str, Any]) -> "FileInfo":
        return FileInfo(d["path"], d["size"], d["mtimeNs"])


@dataclasses.dataclass
class Fingerprint:
    """Signature of the source plan's data (kind + opaque value).

    Reference: LogicalPlanFingerprint / NoOpFingerprint
    (index/IndexLogEntry.scala:96-118)."""

    kind: str
    value: str

    def to_json(self) -> dict[str, Any]:
        return {"kind": self.kind, "value": self.value}

    @staticmethod
    def from_json(d: dict[str, Any]) -> "Fingerprint":
        return Fingerprint(d["kind"], d["value"])


NOOP_FINGERPRINT = Fingerprint(kind="noOp", value="")


@dataclasses.dataclass
class CoveringIndex:
    """The derived dataset spec (index/IndexLogEntry.scala:39-47)."""

    indexed_columns: list[str]
    included_columns: list[str]
    schema: list[dict[str, Any]]  # Schema.to_json() output
    num_buckets: int

    kind = "CoveringIndex"

    def to_json(self) -> dict[str, Any]:
        return {
            "kind": "CoveringIndex",
            "properties": {
                "indexedColumns": self.indexed_columns,
                "includedColumns": self.included_columns,
                "schema": self.schema,
                "numBuckets": self.num_buckets,
            },
        }

    @staticmethod
    def from_json(d: dict[str, Any]) -> "CoveringIndex":
        p = d["properties"]
        return CoveringIndex(
            list(p["indexedColumns"]),
            list(p["includedColumns"]),
            list(p["schema"]),
            int(p["numBuckets"]),
        )

    @property
    def all_columns(self) -> list[str]:
        return list(self.indexed_columns) + list(self.included_columns)


@dataclasses.dataclass
class VectorIndex:
    """Derived dataset for the ANN/embedding covering index (no analog in
    the v0.2 reference; required by BASELINE config 5). Rows are
    partitioned by nearest k-means centroid; a query probes the nprobe
    closest partitions with a matmul + top-k."""

    embedding_column: str
    included_columns: list[str]
    schema: list[dict[str, Any]]  # Schema.to_json() output
    num_partitions: int
    dim: int
    metric: str = "l2"  # l2 | ip | cos

    kind = "VectorIndex"

    def to_json(self) -> dict[str, Any]:
        return {
            "kind": "VectorIndex",
            "properties": {
                "embeddingColumn": self.embedding_column,
                "includedColumns": self.included_columns,
                "schema": self.schema,
                "numPartitions": self.num_partitions,
                "dim": self.dim,
                "metric": self.metric,
            },
        }

    @staticmethod
    def from_json(d: dict[str, Any]) -> "VectorIndex":
        p = d["properties"]
        return VectorIndex(
            p["embeddingColumn"],
            list(p["includedColumns"]),
            list(p["schema"]),
            int(p["numPartitions"]),
            int(p["dim"]),
            p.get("metric", "l2"),
        )

    @property
    def all_columns(self) -> list[str]:
        return [self.embedding_column] + list(self.included_columns)

    # Shared bucket-count vocabulary with CoveringIndex (partition == bucket).
    @property
    def num_buckets(self) -> int:
        return self.num_partitions


@dataclasses.dataclass
class Content:
    """Root of the index data tree (index/IndexLogEntry.scala:49-59)."""

    root: str
    directories: list[str] = dataclasses.field(default_factory=list)

    def to_json(self) -> dict[str, Any]:
        return {"root": self.root, "directories": self.directories}

    @staticmethod
    def from_json(d: dict[str, Any]) -> "Content":
        return Content(d["root"], list(d.get("directories", [])))


@dataclasses.dataclass
class Source:
    """Lineage of the index (index/IndexLogEntry.scala:61-74)."""

    plan: dict[str, Any]  # plan IR JSON (plan/nodes.py serde)
    fingerprint: Fingerprint
    files: list[FileInfo]

    def to_json(self) -> dict[str, Any]:
        return {
            "plan": self.plan,
            "fingerprint": self.fingerprint.to_json(),
            "files": [f.to_json() for f in self.files],
        }

    @staticmethod
    def from_json(d: dict[str, Any]) -> "Source":
        return Source(
            d["plan"],
            Fingerprint.from_json(d["fingerprint"]),
            [FileInfo.from_json(f) for f in d.get("files", [])],
        )


@dataclasses.dataclass
class LogEntry:
    """Mutable envelope common to all log entries (LogEntry.scala:22-29)."""

    id: int = 0
    state: str = ""
    timestamp: float = 0.0
    enabled: bool = True

    def with_state(self, state: str) -> "LogEntry":
        out = dataclasses.replace(self)
        out.state = state
        out.timestamp = time.time()
        return out


@dataclasses.dataclass
class IndexLogEntry(LogEntry):
    """The concrete v0.1 entry for a covering index."""

    name: str = ""
    derived_dataset: CoveringIndex | None = None
    content: Content | None = None
    source: Source | None = None
    extra: dict[str, Any] = dataclasses.field(default_factory=dict)

    # -- convenience accessors -------------------------------------------
    @property
    def indexed_columns(self) -> list[str]:
        return self.derived_dataset.indexed_columns

    @property
    def included_columns(self) -> list[str]:
        return self.derived_dataset.included_columns

    @property
    def num_buckets(self) -> int:
        return self.derived_dataset.num_buckets

    @property
    def signature(self) -> Fingerprint:
        return self.source.fingerprint

    # -- serde -----------------------------------------------------------
    def to_json(self) -> dict[str, Any]:
        return {
            "version": LOG_ENTRY_VERSION,
            "id": self.id,
            "state": self.state,
            "timestamp": self.timestamp,
            "enabled": self.enabled,
            "name": self.name,
            "derivedDataset": self.derived_dataset.to_json() if self.derived_dataset else None,
            "content": self.content.to_json() if self.content else None,
            "source": self.source.to_json() if self.source else None,
            "extra": self.extra,
        }

    @staticmethod
    def from_json(d: dict[str, Any]) -> "IndexLogEntry":
        version = d.get("version")
        if version != LOG_ENTRY_VERSION:
            # Polymorphic decode keyed on version (LogEntry.scala:33-46).
            raise ValueError(f"unsupported log entry version: {version!r}")
        return IndexLogEntry(
            id=int(d["id"]),
            state=d["state"],
            timestamp=float(d["timestamp"]),
            enabled=bool(d.get("enabled", True)),
            name=d["name"],
            derived_dataset=(
                _derived_dataset_from_json(d["derivedDataset"]) if d.get("derivedDataset") else None
            ),
            content=Content.from_json(d["content"]) if d.get("content") else None,
            source=Source.from_json(d["source"]) if d.get("source") else None,
            extra=dict(d.get("extra", {})),
        )


_DERIVED_KINDS = {"CoveringIndex": CoveringIndex, "VectorIndex": VectorIndex}


def _derived_dataset_from_json(d: dict[str, Any]):
    """Polymorphic decode keyed on `kind` (the reference keys decoding on
    the envelope version, LogEntry.scala:33-46; kinds compose with it)."""
    kind = d.get("kind", "CoveringIndex")
    if kind not in _DERIVED_KINDS:
        raise ValueError(f"unknown derived dataset kind {kind!r}")
    return _DERIVED_KINDS[kind].from_json(d)


def entry_from_json(d: dict[str, Any]) -> IndexLogEntry:
    return IndexLogEntry.from_json(d)
