"""Metadata cache SPI + TTL implementation.

Reference parity: index/Cache.scala:23-41 (get/set/clear SPI) and
index/CachingIndexCollectionManager.scala:117-160
(CreationTimeBasedIndexCache: entries expire `expiry_seconds` after they
were set; every mutating API clears the cache).
"""

from __future__ import annotations

import time
from typing import Generic, TypeVar

T = TypeVar("T")


class Cache(Generic[T]):
    def get(self) -> T | None:
        raise NotImplementedError

    def set(self, entry: T) -> None:
        raise NotImplementedError

    def clear(self) -> None:
        raise NotImplementedError


class CreationTimeBasedCache(Cache[T]):
    def __init__(self, expiry_seconds: float):
        self.expiry_seconds = expiry_seconds
        self._entry: T | None = None
        self._set_at: float = 0.0

    def get(self) -> T | None:
        if self._entry is None:
            return None
        # monotonic, not wall clock: an NTP step backwards must not make
        # a stale entry immortal (nor a forward step expire a fresh one).
        if time.monotonic() - self._set_at > self.expiry_seconds:
            self.clear()
            return None
        return self._entry

    def set(self, entry: T) -> None:
        self._entry = entry
        self._set_at = time.monotonic()

    def clear(self) -> None:
        self._entry = None
        self._set_at = 0.0
