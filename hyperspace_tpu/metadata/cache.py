"""Metadata cache SPI + TTL implementation.

Reference parity: index/Cache.scala:23-41 (get/set/clear SPI) and
index/CachingIndexCollectionManager.scala:117-160
(CreationTimeBasedIndexCache: entries expire `expiry_seconds` after they
were set; every mutating API clears the cache).

Thread-safe: the serving plane (docs/serving.md) reads this cache from N
worker threads while mutating APIs clear it. One lock covers the whole
get/set/clear surface — in particular the stamp check and the expiry
eviction in ``get`` are a single critical section, so a concurrent
``set`` can never interleave between "entry is stale" and "drop it" and
have its fresh entry evicted (the torn read the single-threaded version
tolerated). Hits and misses land in the declared counter registry
(``stats.KNOWN_COUNTERS``: ``metadata.cache.hits`` / ``.misses``).
"""

from __future__ import annotations

import threading
import time
from typing import Generic, TypeVar

from hyperspace_tpu import stats

T = TypeVar("T")


class Cache(Generic[T]):
    def get(self) -> T | None:
        raise NotImplementedError

    def set(self, entry: T) -> None:
        raise NotImplementedError

    def clear(self) -> None:
        raise NotImplementedError


class CreationTimeBasedCache(Cache[T]):
    def __init__(self, expiry_seconds: float):
        self.expiry_seconds = expiry_seconds
        self._lock = threading.Lock()
        self._entry: T | None = None
        self._set_at: float = 0.0

    def get(self) -> T | None:
        with self._lock:
            if self._entry is None:
                stats.increment("metadata.cache.misses")
                return None
            # monotonic, not wall clock: an NTP step backwards must not make
            # a stale entry immortal (nor a forward step expire a fresh one).
            if time.monotonic() - self._set_at > self.expiry_seconds:
                self._entry = None
                self._set_at = 0.0
                stats.increment("metadata.cache.misses")
                return None
            stats.increment("metadata.cache.hits")
            return self._entry

    def set(self, entry: T) -> None:
        with self._lock:
            self._entry = entry
            self._set_at = time.monotonic()

    def clear(self) -> None:
        with self._lock:
            self._entry = None
            self._set_at = 0.0
