"""Index path resolution.

Reference parity: index/PathResolver.scala:30-101. The system root comes from
configuration (default `<cwd>/spark-warehouse/indexes`,
PathResolver.scala:65-71); index names resolve case-insensitively by listing
the system directory (PathResolver.scala:39-60) so `MyIdx` and `myidx` are
the same index.
"""

from __future__ import annotations

from pathlib import Path

from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.utils.name_utils import normalize_index_name


class PathResolver:
    def __init__(self, conf: HyperspaceConf):
        self.conf = conf

    @property
    def system_path(self) -> Path:
        return Path(self.conf.system_path)

    def get_index_path(self, name: str) -> Path:
        """Resolve an index name to its directory, matching an existing
        directory case-insensitively, else the normalized name."""
        name = normalize_index_name(name)
        root = self.system_path
        if root.is_dir():
            low = name.lower()
            for d in root.iterdir():
                if d.is_dir() and d.name.lower() == low:
                    return d
        return root / name

    def list_index_paths(self) -> list[Path]:
        """Every index directory under the system path. Underscore-
        prefixed directories are metadata-plane state, not indexes
        (`_hyperspace_log` inside an index dir set the convention; the
        advisor's `_advisor/` ledger dir lives at THIS level), so they
        are excluded — listing one as an index would make lazy recovery
        try to "repair" it on every catalog scan."""
        root = self.system_path
        if not root.is_dir():
            return []
        return sorted(
            d for d in root.iterdir() if d.is_dir() and not d.name.startswith("_")
        )
