"""Versioned index data directories.

Reference parity: index/IndexDataManager.scala:38-73. Index data for version
`n` lives at `<index_path>/v__=<n>/` (Hive-partition naming so engines that
understand partition columns see `v__` as one). Refresh writes into
`v__=<latest+1>` and the log swap makes it live; vacuum deletes all versions.
"""

from __future__ import annotations

import os
import re
from pathlib import Path

from hyperspace_tpu.config import DATA_VERSION_PREFIX
from hyperspace_tpu.utils.file_utils import delete_recursively

_VERSION_RE = re.compile(re.escape(DATA_VERSION_PREFIX) + r"(\d+)$")


class IndexDataManager:
    def __init__(self, index_path: str | os.PathLike):
        self.index_path = Path(index_path)

    def get_version_ids(self) -> list[int]:
        if not self.index_path.is_dir():
            return []
        ids = []
        for f in self.index_path.iterdir():
            m = _VERSION_RE.match(f.name)
            if m and f.is_dir():
                ids.append(int(m.group(1)))
        return sorted(ids)

    def get_latest_version_id(self) -> int | None:
        ids = self.get_version_ids()
        return ids[-1] if ids else None

    def get_path(self, id: int) -> Path:
        return self.index_path / f"{DATA_VERSION_PREFIX}{id}"

    def delete(self, id: int) -> None:
        delete_recursively(self.get_path(id))

    def quarantine(self, id: int) -> bool:
        """Move a partial/orphaned version dir aside (failure path of
        Action.run, recover()'s orphan GC). The dotted name no longer
        matches the `v__=N` pattern, so the version id is immediately
        reusable and index listings can never pick the partial data up;
        the bytes stay for post-mortems. No-op (False) when absent."""
        src = self.get_path(id)
        if not src.exists():
            return False
        for attempt in range(10):
            suffix = "" if attempt == 0 else f"-{attempt}"
            dest = self.index_path / f".quarantine-{DATA_VERSION_PREFIX}{id}{suffix}"
            if dest.exists():
                continue
            try:
                os.rename(src, dest)
                return True
            except OSError:
                return False
        return False
