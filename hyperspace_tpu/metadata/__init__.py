from hyperspace_tpu.metadata.log_entry import (
    Content,
    CoveringIndex,
    FileInfo,
    IndexLogEntry,
    LogEntry,
    Fingerprint,
    Source,
)
from hyperspace_tpu.metadata.log_manager import IndexLogManager
from hyperspace_tpu.metadata.data_manager import IndexDataManager
from hyperspace_tpu.metadata.path_resolver import PathResolver
from hyperspace_tpu.metadata.cache import Cache, CreationTimeBasedCache

__all__ = [
    "Content",
    "CoveringIndex",
    "FileInfo",
    "IndexLogEntry",
    "LogEntry",
    "Fingerprint",
    "Source",
    "IndexLogManager",
    "IndexDataManager",
    "PathResolver",
    "Cache",
    "CreationTimeBasedCache",
]
