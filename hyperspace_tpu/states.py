"""Index lifecycle states.

Reference parity: actions/Constants.scala:115-129 — 9 states, of which
ACTIVE / DELETED / DOESNOTEXIST are stable; everything else is transient and
blocks further operations until completed or cancelled.
"""

ACTIVE = "ACTIVE"
CREATING = "CREATING"
DELETING = "DELETING"
DELETED = "DELETED"
REFRESHING = "REFRESHING"
VACUUMING = "VACUUMING"
RESTORING = "RESTORING"
DOESNOTEXIST = "DOESNOTEXIST"
OPTIMIZING = "OPTIMIZING"

ALL_STATES = frozenset(
    {ACTIVE, CREATING, DELETING, DELETED, REFRESHING, VACUUMING, RESTORING, DOESNOTEXIST, OPTIMIZING}
)

STABLE_STATES = frozenset({ACTIVE, DELETED, DOESNOTEXIST})
