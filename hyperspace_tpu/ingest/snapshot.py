"""MVCC snapshot isolation over the index log's version vector.

The serve plan cache already keys every cached plan on the collection's
per-index latest-log-id vector (serve/plan_cache.py) — that vector IS a
version stamp. A :class:`PinnedSnapshot` captures, at admission time,
the latest STABLE log entry of every ACTIVE index, and from then on a
query that carries the snapshot reads **only** that world:

- `pin_plan` rewrites every raw source-leaf ``Scan`` to the exact file
  list the pinned entry indexed (``Scan.files`` pinned subsets — the
  same mechanism hybrid scan uses, signature.collect_leaf_files).
  Because arrivals are append-only (new files; committed files are
  never touched), the pinned leaf's recomputed fingerprint equals the
  pinned entry's stored signature, so the rewrite rules exact-match the
  PINNED entry — not whatever newer version a concurrent micro-batch
  just committed — and the executor reads only its version
  directories. No torn reads, no refresh downtime.
- `optimized_plan`/`run_query` take the candidate entries from
  :meth:`entries` instead of re-listing the live log, so an index that
  goes ACTIVE (or grows a new version) after admission is invisible.
- Sources no index covers are pinned on FIRST TOUCH: one live listing,
  memoized, so repeated reads repeat there too.

Bounds of the guarantee (docs/ingestion.md "snapshot semantics"):
repeatability holds as long as the pinned version directories exist.
``optimize`` keeps superseded directories on disk (vacuum-later
design), so compaction under a live snapshot is safe; an explicit
``vacuum``/``recover`` orphan GC that deletes them ends the snapshot's
useful life — reads then fail like any deleted source. In-place
REWRITES of source files are outside the contract (CDC is append-only
by construction; that is the documented operator contract).
"""

from __future__ import annotations

import dataclasses
import threading
from pathlib import Path

from hyperspace_tpu import stats, states
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.plan.nodes import LogicalPlan, Scan


def _scan_leaves(plan_json: dict) -> list[dict]:
    """Every ``{"type": "scan", ...}`` dict in a serialized plan."""
    out: list[dict] = []

    def walk(node):
        if isinstance(node, dict):
            if node.get("type") == "scan":
                out.append(node)
            else:
                for v in node.values():
                    walk(v)
        elif isinstance(node, list):
            for v in node:
                walk(v)

    walk(plan_json)
    return out


class PinnedSnapshot:
    """A repeatable-read view of the collection, pinned at construction.

    Use as a context manager (``with session.pin_snapshot() as snap:``)
    or call :meth:`release` explicitly; a released snapshot refuses
    further pinning so a stale handle fails loudly instead of silently
    reading the live world.
    """

    def __init__(self, session):
        self._lock = threading.Lock()
        self._released = False
        # name -> pinned IndexLogEntry (latest STABLE, ACTIVE only)
        self._pinned: dict[str, object] = {}
        # normalized source root -> pinned entry (freshest wins when two
        # indexes cover the same root)
        self._by_root: dict[str, object] = {}
        # (root, format) -> file list for sources no index covers,
        # memoized on first touch
        self._unindexed: dict[tuple[str, str], list[str]] = {}
        mgr = session.manager
        stamp = []
        for d in mgr.path_resolver.list_index_paths():
            entry = mgr.log_manager_factory(d).get_latest_stable_log()
            if entry is None:
                stamp.append((d.name, None))
                continue
            stamp.append((d.name, entry.id))
            if entry.state != states.ACTIVE:
                continue
            self._pinned[d.name] = entry
            for leaf in _scan_leaves(entry.source.plan):
                root = str(Path(leaf["root"]))
                held = self._by_root.get(root)
                if held is None or entry.id > held.id:
                    self._by_root[root] = entry
        self.stamp: tuple = tuple(stamp)
        stats.increment("ingest.snapshots")

    @property
    def released(self) -> bool:
        return self._released

    def entries(self) -> list:
        """The pinned index entries — the candidate set the rewrite
        rules match against instead of the live listing."""
        return list(self._pinned.values())

    def pin_plan(self, plan: LogicalPlan) -> LogicalPlan:
        """Rewrite every un-pinned source leaf to the snapshot's file
        list, so both the fingerprint match and any raw-scan fallback
        read exactly the admitted world."""
        if self._released:
            raise HyperspaceError(
                "snapshot released: pin_snapshot() handles are single-use views; "
                "take a new snapshot for a new read point"
            )

        def rewrite(node: LogicalPlan) -> LogicalPlan:
            if isinstance(node, Scan):
                if node.files is not None or node.bucket_spec is not None:
                    return node  # already pinned (hybrid/exchange leaves)
                root = str(Path(node.root))
                entry = self._by_root.get(root)
                if entry is not None:
                    files = [f.path for f in entry.source.files]
                else:
                    files = self._pin_unindexed(root, node.format)
                return dataclasses.replace(node, files=files)
            changes = {}
            for f in dataclasses.fields(node):
                v = getattr(node, f.name)
                if isinstance(v, LogicalPlan):
                    nv = rewrite(v)
                    if nv is not v:
                        changes[f.name] = nv
                elif isinstance(v, list) and v and isinstance(v[0], LogicalPlan):
                    nv = [rewrite(c) for c in v]
                    if any(a is not b for a, b in zip(nv, v)):
                        changes[f.name] = nv
            return dataclasses.replace(node, **changes) if changes else node

        return rewrite(plan)

    def _pin_unindexed(self, root: str, fmt: str) -> list[str]:
        key = (root, fmt)
        with self._lock:
            files = self._unindexed.get(key)
            if files is None:
                from hyperspace_tpu.dataset import format_suffix, list_data_files

                files = [f.path for f in list_data_files(root, suffix=format_suffix(fmt))]
                self._unindexed[key] = files
            return files

    def release(self) -> None:
        self._released = True

    def __enter__(self) -> "PinnedSnapshot":
        return self

    def __exit__(self, *exc) -> None:
        self.release()
