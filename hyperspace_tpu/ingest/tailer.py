"""Poll-based source watchers with atomically-persisted cursors.

Two change-data-capture shapes, both cheap enough to run every poll
tick (docs/ingestion.md "tailing"):

- :class:`FileArrivalWatcher` — new-file arrival: list the source root
  (the same ``dataset.list_data_files`` walk the signature provider
  uses) and diff against the cursor's known set. Arrived bytes are
  metered into ``ingest.bytes`` — the ingest-throughput ledger.
- :class:`CdcTailer` — appended-row CDC: tail a JSONL changelog from a
  persisted byte offset and materialize complete new lines into
  ``cdc-<seq>.parquet`` batch files inside the indexed source root,
  where the next incremental refresh picks them up as appended data.

Crash discipline mirrors the advisor ledger (advisor/routing.py): the
cursor is one JSON document written via ``file_utils.write_json``
(mkstemp + fsync + rename), loaded leniently (unreadable -> start
empty). The ``ingest.tail`` fault point fires after a batch file lands
but BEFORE the cursor persists, so a crash there leaves an orphan
batch; batch names derive deterministically from the cursor sequence
and the retry re-materializes the SAME bytes to the SAME name from the
SAME offset — idempotent, and safe because a batch is only ever
rewritten before the commit that would freeze its mtime into an index
signature. Batch files are published atomically (temp + ``os.replace``)
so a concurrent query never lists a torn parquet file.
"""

from __future__ import annotations

import json
import os
import tempfile
from pathlib import Path

from hyperspace_tpu import faults, stats
from hyperspace_tpu.utils import file_utils


class Cursor:
    """One index's poll position, persisted atomically as a single JSON
    document (``<system_path>/_ingest/cursors/<name>.json``)."""

    def __init__(self, path: str | Path):
        self.path = Path(path)
        self._doc: dict | None = None

    def load(self) -> dict:
        if self._doc is None:
            try:
                self._doc = file_utils.read_json(self.path)
            except (OSError, ValueError):
                self._doc = {}
            if not isinstance(self._doc, dict):
                self._doc = {}
        return self._doc

    def save(self) -> None:
        file_utils.write_json(self.path, self.load())


class FileArrivalWatcher:
    """Detect files arriving (or growing) under one source root."""

    def __init__(self, root: str | Path, fmt: str, cursor: Cursor):
        self.root = str(root)
        self.format = fmt
        self.cursor = cursor

    def poll(self) -> int:
        """Number of new-or-grown files observed this tick; arrived
        bytes are metered into ``ingest.bytes``."""
        from hyperspace_tpu.dataset import format_suffix, list_data_files

        files = list_data_files(self.root, suffix=format_suffix(self.format))
        doc = self.cursor.load()
        known = doc.setdefault("known", {})
        fresh = 0
        new_bytes = 0
        for fi in files:
            seen = known.get(fi.path)
            if seen == fi.size:
                continue
            fresh += 1
            new_bytes += int(fi.size) - int(seen or 0)
            known[fi.path] = fi.size
        if fresh:
            stats.increment("ingest.bytes", max(new_bytes, 0))
            self.cursor.save()
        return fresh


class CdcTailer:
    """Tail a JSONL changelog into deterministic parquet batch files."""

    def __init__(self, changelog: str | Path, dest_root: str | Path, cursor: Cursor):
        self.changelog = str(changelog)
        self.dest_root = Path(dest_root)
        self.cursor = cursor

    def poll(self, batch_rows: int) -> int:
        """Materialize complete appended changelog lines into at most
        ``batch_rows``-row parquet batches; returns rows materialized
        (also metered into ``ingest.rows``)."""
        doc = self.cursor.load()
        st = doc.setdefault("cdc", {"offset": 0, "seq": 0})
        offset = int(st.get("offset", 0))
        try:
            size = os.path.getsize(self.changelog)
        except OSError:
            return 0  # changelog not created yet
        if size <= offset:
            return 0
        with open(self.changelog, "rb") as f:
            f.seek(offset)
            data = f.read()
        end = data.rfind(b"\n")
        if end < 0:
            return 0  # a partial trailing line: wait for the writer
        chunk = data[: end + 1]
        rows = [json.loads(line) for line in chunk.splitlines() if line.strip()]
        seq = int(st.get("seq", 0))
        total = 0
        for i in range(0, len(rows), max(int(batch_rows), 1)):
            batch = rows[i : i + max(int(batch_rows), 1)]
            path = self.dest_root / f"cdc-{seq:06d}.parquet"
            self._write_batch(path, batch)
            # Crash here -> cursor below never advances; the retry
            # rewrites the SAME file from the SAME offset (idempotent).
            faults.fault_point("ingest.tail", path)
            seq += 1
            total += len(batch)
        if total:
            stats.increment("ingest.rows", total)
        st["offset"] = offset + len(chunk)
        st["seq"] = seq
        self.cursor.save()
        return total

    @staticmethod
    def _write_batch(path: Path, rows: list[dict]) -> None:
        import pyarrow as pa
        import pyarrow.parquet as pq

        cols = sorted({k for r in rows for k in r})
        table = pa.table({c: [r.get(c) for r in rows] for c in cols})
        path.parent.mkdir(parents=True, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=str(path.parent), prefix=".cdc-", suffix=".tmp")
        os.close(fd)
        try:
            pq.write_table(table, tmp)
            # fsync BEFORE the rename: os.replace makes the NAME durable
            # independently of the data, so without the barrier a crash
            # can surface a zero-length cdc- file the planner then lists.
            rfd = os.open(tmp, os.O_RDONLY)
            try:
                os.fsync(rfd)
            finally:
                os.close(rfd)
            os.replace(tmp, path)  # atomic publish: no torn file is ever listed
            file_utils.fsync_dir(path.parent)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
