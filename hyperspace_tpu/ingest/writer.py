"""Streaming delta writer: micro-batch commits and gated compaction.

Each micro-batch commits through the UNCHANGED two-phase Action
protocol — one ``refresh(name, "incremental")`` per poll tick that saw
appended data, which appends exactly one delta bucket and swaps
``latestStable`` atomically (actions/refresh.py). The daemon adds no
new commit machinery: a SIGKILL mid-commit leaves at most the
protocol's transient log entry, and ``recover()`` converges it exactly
as it would an operator-run refresh. An empty poll is a no-op, not an
error (the refresh action's "no appended source data files" abort is
absorbed here).

Compaction is advisor-gated (docs/ingestion.md "compaction"): it fires
only when BOTH ``hyperspace.ingest.autoCompact`` and the advisor's
lifecycle gate ``hyperspace.advisor.lifecycle.autoOptimize`` are on,
only past ``hyperspace.advisor.lifecycle.maxDeltas`` delta buckets, and
is deferred (``ingest.deferred``) while serve SLOs burn — rebuild-class
background IO must not compound a latency incident.
"""

from __future__ import annotations

from hyperspace_tpu import faults, stats
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.obs import events as obs_events
from hyperspace_tpu.obs import trace as obs_trace

_EVT_COMMITTED = obs_events.declare("ingest.committed")
_EVT_COMPACTED = obs_events.declare("ingest.compacted")

# The refresh action's typed empty-poll abort (actions/refresh.py
# validate()); matching it by message keeps the action's contract
# unchanged while the daemon treats it as "nothing to do".
_EMPTY_POLL = "no appended source data files found"


def _latest_id(session, name: str):
    mgr = session.manager
    return mgr.log_manager_factory(mgr.path_resolver.get_index_path(name)).get_latest_id()


def delta_count(session, name: str) -> int:
    """Delta buckets in the latest stable entry (compaction pressure)."""
    mgr = session.manager
    entry = mgr.log_manager_factory(mgr.path_resolver.get_index_path(name)).get_latest_stable_log()
    if entry is None or entry.content is None:
        return 0
    return len(entry.content.directories)


def commit_micro_batch(hyperspace, name: str) -> int | None:
    """Commit appended source data as one delta bucket; returns the new
    latest log id, or None when the poll saw nothing new."""
    faults.fault_point("ingest.commit")
    try:
        with obs_trace.span("ingest.commit", index=name):
            hyperspace.refresh_index(name, "incremental")
    except HyperspaceError as e:
        if _EMPTY_POLL in str(e):
            return None
        raise
    stats.increment("ingest.commits")
    new_id = _latest_id(hyperspace.session, name)
    _EVT_COMMITTED.emit(index=name, log_id=new_id)
    return new_id


def maybe_compact(hyperspace, name: str, burning: bool = False) -> bool:
    """Compact delta buckets through the gated optimize action; returns
    True only when a compaction actually ran."""
    conf = hyperspace.session.conf
    if not (conf.ingest_auto_compact and conf.advisor_auto_optimize):
        return False
    if delta_count(hyperspace.session, name) <= int(conf.advisor_lifecycle_max_deltas):
        return False
    if burning:
        # Same discipline as the controller's _defer_background: hold
        # rebuild-class IO while serve SLOs burn.
        stats.increment("ingest.deferred")
        return False
    faults.fault_point("ingest.compact")
    with obs_trace.span("ingest.compact", index=name):
        hyperspace.optimize_index(name)
    stats.increment("ingest.compactions")
    _EVT_COMPACTED.emit(index=name, log_id=_latest_id(hyperspace.session, name))
    return True
