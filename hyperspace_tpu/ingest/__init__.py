"""Continuous ingestion: CDC tailing, streaming delta commits, MVCC
snapshot-isolated reads (docs/ingestion.md).

The package turns the batch-era refresh workflow into a service:

- `tailer`    — poll-based source watchers: new-file arrival detection
  plus a CDC changelog tailer that materializes appended rows into
  deterministic batch files, with an atomically-persisted cursor.
- `writer`    — micro-batch commits through the UNCHANGED two-phase
  Action protocol (one incremental refresh per batch = one crash-safe
  delta bucket), and advisor-gated background compaction.
- `snapshot`  — `PinnedSnapshot`: a query pins the per-index version
  stamp it was admitted under and re-reads repeatably against it while
  micro-batches keep committing underneath.
- `daemon`    — the `IngestDaemon` service loop tying them together:
  thread-hosted by default, optionally a spawned worker process
  (`hyperspace.ingest.processWorker`), controller-pausable through an
  atomically-written control file, registered on `/healthz`.
"""

from hyperspace_tpu.ingest.daemon import IngestDaemon
from hyperspace_tpu.ingest.snapshot import PinnedSnapshot
from hyperspace_tpu.ingest.tailer import CdcTailer, FileArrivalWatcher

__all__ = ["IngestDaemon", "PinnedSnapshot", "CdcTailer", "FileArrivalWatcher"]
