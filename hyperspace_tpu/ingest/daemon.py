"""The continuous-ingestion service loop (docs/ingestion.md).

One :class:`IngestDaemon` watches a set of indexes: each tick it tails
their CDC changelogs, detects arrived files, commits a micro-batch
through the two-phase refresh action when anything appended, and
triggers advisor-gated compaction when delta pressure crosses the
lifecycle threshold. The loop is the controller's shape
(serve/controller.py `_run`): ordinary Exceptions are absorbed
per-index (``ingest.commit_failures`` / ``ingest.compact_failures``;
the failed subsystem's own Action rollback already ran), CrashPoint
propagates — a dying daemon does not keep committing.

Hosting: a thread by default; ``hyperspace.ingest.processWorker``
spawns :func:`_service_entry` through ``parallel/procpool.ProcessHost``
instead (declared in analysis/procdomain.SPAWN_ENTRY_POINTS), shipping
fault/journal/obs state exactly like `_task_entry`. Control state
(pause/resume) is an atomically-written JSON file under
``<system_path>/_ingest/`` polled every tick — so the controller's
backoff works across process boundaries and survives SIGKILL.

The daemon registers with the shared ``/healthz`` endpoint
(obs/http.attach_ingest) and journals through its events; ``drain()``
blocks until the watched indexes' log ids stop advancing with no
pending observed data — the streaming analog of "flush".
"""

from __future__ import annotations

import threading
import time
from pathlib import Path

from hyperspace_tpu import faults, stats
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.ingest import writer as ingest_writer
from hyperspace_tpu.ingest.tailer import CdcTailer, Cursor, FileArrivalWatcher
from hyperspace_tpu.obs import events as obs_events
from hyperspace_tpu.obs import slo as obs_slo
from hyperspace_tpu.obs import trace as obs_trace
from hyperspace_tpu.utils import file_utils

_EVT_STARTED = obs_events.declare("ingest.started")
_EVT_STOPPED = obs_events.declare("ingest.stopped")
_EVT_COMMIT_FAILED = obs_events.declare("ingest.commit_failed")
_EVT_PAUSED = obs_events.declare("ingest.paused")
_EVT_RESUMED = obs_events.declare("ingest.resumed")
_EVT_LAGGING = obs_events.declare("ingest.lagging")

# Metadata-plane state dir under the system path; underscore-prefixed so
# PathResolver.list_index_paths never mistakes it for an index.
INGEST_DIR = "_ingest"
CONTROL_FILE = "control.json"

# Rate limit for the advisory ingest.lagging event (one per window per
# index, not one per tick while behind).
_LAG_EMIT_INTERVAL_S = 5.0


class IngestDaemon:
    """Poll-commit-compact service over a set of watched indexes."""

    def __init__(self, hyperspace, clock=time.monotonic):
        self.hyperspace = hyperspace
        self.session = hyperspace.session
        self._clock = clock
        self._lock = threading.RLock()
        self._watches: dict[str, dict] = {}
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._host = None  # ProcessHost in processWorker mode
        self._pending_since: dict[str, float] = {}
        self._last_commit_id: dict[str, int] = {}
        self._last_lag_s: float | None = None
        self._lag_last_emit: dict[str, float] = {}
        self._commits = 0

    # -- wiring ---------------------------------------------------------

    @property
    def _state_dir(self) -> Path:
        return Path(self.session.conf.system_path) / INGEST_DIR

    @property
    def control_path(self) -> Path:
        return self._state_dir / CONTROL_FILE

    def watch(self, name: str, changelog: str | Path | None = None) -> "IngestDaemon":
        """Register an index: its source roots get arrival watchers; an
        optional JSONL `changelog` gets a CDC tailer materializing into
        the index's (first) source root."""
        mgr = self.session.manager
        lm = mgr.log_manager_factory(mgr.path_resolver.get_index_path(name))
        entry = lm.get_latest_stable_log()
        if entry is None or entry.source is None:
            raise HyperspaceError(
                f"cannot watch {name!r}: no stable index log entry — create the index first"
            )
        from hyperspace_tpu.ingest.snapshot import _scan_leaves

        leaves = _scan_leaves(entry.source.plan)
        if not leaves:
            raise HyperspaceError(f"cannot watch {name!r}: its source plan has no scan leaves")
        cursor = Cursor(self._state_dir / "cursors" / f"{name}.json")
        watchers = [FileArrivalWatcher(leaf["root"], leaf["format"], cursor) for leaf in leaves]
        tailer = CdcTailer(changelog, leaves[0]["root"], cursor) if changelog else None
        with self._lock:
            self._watches[name] = {"watchers": watchers, "tailer": tailer, "changelog": changelog}
        return self

    # -- control plane --------------------------------------------------

    def pause(self, reason: str = "") -> None:
        """Throttle the daemon: ticks become deferred no-ops until
        resume(). Written atomically so a process-mode worker (or a
        daemon restarted after SIGKILL) observes it too."""
        file_utils.write_json(self.control_path, {"paused": True, "reason": reason})
        _EVT_PAUSED.emit(reason=reason)

    def resume(self) -> None:
        file_utils.write_json(self.control_path, {"paused": False, "reason": ""})
        _EVT_RESUMED.emit()

    def paused(self) -> bool:
        try:
            doc = file_utils.read_json(self.control_path)
        except (OSError, ValueError):
            return False
        return bool(isinstance(doc, dict) and doc.get("paused"))

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "IngestDaemon":
        conf = self.session.conf
        with self._lock:
            if self._thread is not None or self._host is not None:
                return self
            self._stop.clear()
            if conf.ingest_process_worker:
                self._start_process(conf)
            else:
                self._thread = threading.Thread(
                    target=self._run, name="hs-ingest", daemon=True
                )
                self._thread.start()
            watched = sorted(self._watches)
            mode = "process" if self._host is not None else "thread"
        from hyperspace_tpu.obs import http as obs_http  # deferred: optional plane

        shared = obs_http.shared()
        if shared is not None:
            shared.attach_ingest(self)
        _EVT_STARTED.emit(watched=watched, mode=mode)
        return self

    def _start_process(self, conf) -> None:
        from hyperspace_tpu.obs import journal as obs_journal
        from hyperspace_tpu.parallel.procpool import ProcessHost

        host = ProcessHost("hs-ingest")
        env = {
            "faults": faults.export_state(),
            "obs_enabled": obs_trace.enabled(),
            "journal": obs_journal.export_state(),
            "overrides": dict(getattr(conf, "overrides", {}) or {}),
        }
        watches = [(n, str(w["changelog"]) if w["changelog"] else None)
                   for n, w in sorted(self._watches.items())]
        host.spawn(
            "ingest",
            _service_entry,
            (str(conf.system_path), watches, env, host.stop_event),
            name="hs-ingest-0",
        )
        self._host = host

    def stop(self, timeout: float = 30.0) -> None:
        with self._lock:
            thread, host = self._thread, self._host
            self._thread = None
            self._host = None
        self._stop.set()
        if thread is not None:
            thread.join(timeout)
        if host is not None:
            host.stop(timeout=timeout)
        _EVT_STOPPED.emit()

    def worker_pid(self) -> int | None:
        """The spawned worker's pid (processWorker mode; tests SIGKILL it)."""
        with self._lock:
            if self._host is None:
                return None
            procs = self._host.processes()
            return next(iter(procs.values())).pid if procs else None

    def drain(self, timeout: float = 30.0) -> bool:
        """Block until the watched log ids stop advancing and nothing
        observed is pending commit. When the daemon is not running, the
        drain drives tick() itself (manual mode)."""
        deadline = time.monotonic() + timeout
        poll = max(float(self.session.conf.ingest_poll_seconds), 0.05)
        stable = 0
        last = None
        while time.monotonic() < deadline:
            with self._lock:
                in_process = self._host is not None
                running = self._thread is not None or in_process
                # Parent-side pending state is meaningless in process
                # mode (the worker owns it over there); log ids below
                # are the cross-process progress signal either way.
                pending = bool(self._pending_since) and not in_process
            if not running:
                self.tick()
                with self._lock:
                    pending = bool(self._pending_since)
            ids = tuple(sorted(self._log_ids().items()))
            if ids == last and not pending:
                stable += 1
                if stable >= 2:
                    return True
            else:
                stable = 0
                last = ids
            if running:
                time.sleep(poll)
        return False

    def _log_ids(self) -> dict[str, int | None]:
        mgr = self.session.manager
        with self._lock:
            names = sorted(self._watches)
        out = {}
        for name in names:
            lm = mgr.log_manager_factory(mgr.path_resolver.get_index_path(name))
            out[name] = lm.get_latest_id()
        return out

    def _run(self) -> None:
        """Service loop: absorbs ordinary Exceptions (tick already
        records them per-index; anything escaping tick is counted
        here), propagates CrashPoint."""
        conf = self.session.conf
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — service loop survives
                stats.increment("ingest.commit_failures")
                _EVT_COMMIT_FAILED.emit(error=f"{type(e).__name__}: {e}")
            self._stop.wait(float(conf.ingest_poll_seconds))

    # -- the tick -------------------------------------------------------

    def tick(self, now: float | None = None) -> dict:
        """One poll pass over every watched index; returns snapshot()."""
        conf = self.session.conf
        if now is None:
            now = self._clock()
        with self._lock:
            if not conf.ingest_enabled:
                # Kill-switch (hyperspace.ingest.enabled, default off) —
                # same live-config discipline as the controller: flipping
                # it makes every tick a no-op without restarting anything.
                return self.snapshot()
            stats.increment("ingest.ticks")
            if self.paused():
                stats.increment("ingest.deferred")
                return self.snapshot()
            burning = self._slo_burning()
            for name, w in sorted(self._watches.items()):
                try:
                    self._tick_index(conf, name, w, now, burning)
                except Exception as e:  # noqa: BLE001 — one index's failure
                    # must not starve the others; its Action already
                    # rolled back. CrashPoint propagates.
                    stats.increment("ingest.commit_failures")
                    _EVT_COMMIT_FAILED.emit(index=name, error=f"{type(e).__name__}: {e}")
            return self.snapshot()

    def _tick_index(self, conf, name: str, w: dict, now: float, burning: bool) -> None:
        with obs_trace.trace("ingest.tick", index=name):
            observed = 0
            if w["tailer"] is not None:
                observed += w["tailer"].poll(int(conf.ingest_cdc_batch_rows))
            for watcher in w["watchers"]:
                observed += watcher.poll()
            if observed and name not in self._pending_since:
                self._pending_since[name] = now
            # Lag is checked BEFORE the commit attempt: a failing commit
            # (the case where lag actually matters) must still warn.
            self._check_lag(conf, name, now)
            new_id = ingest_writer.commit_micro_batch(self.hyperspace, name)
            if new_id is not None:
                # Torn window: the micro-batch committed (log entry is
                # durable) but the daemon's lag/commit bookkeeping is
                # not yet stamped. A crash here is converged by
                # recover(); the next tick restamps from the log.
                faults.fault_point("ingest.stamp", name)
                self._commits += 1
                self._last_commit_id[name] = new_id
                since = self._pending_since.pop(name, now)
                self._last_lag_s = max(now - since, 0.0)
            else:
                # Empty poll: nothing appended at the source level, so
                # nothing is pending either — observed data that a crashed
                # commit (converged by recover()) already landed must not
                # wedge drain() on a stale pending flag.
                self._pending_since.pop(name, None)
            try:
                ingest_writer.maybe_compact(self.hyperspace, name, burning=burning)
            except Exception as e:  # noqa: BLE001 — compaction is optional work
                stats.increment("ingest.compact_failures")
                _EVT_COMMIT_FAILED.emit(
                    index=name, phase="compact", error=f"{type(e).__name__}: {e}"
                )

    def _check_lag(self, conf, name: str, now: float) -> None:
        since = self._pending_since.get(name)
        if since is None:
            return
        lag = now - since
        if lag <= float(conf.ingest_max_lag_seconds):
            return
        last = self._lag_last_emit.get(name)
        if last is not None and now - last < _LAG_EMIT_INTERVAL_S:
            return
        self._lag_last_emit[name] = now
        _EVT_LAGGING.emit(index=name, lag_s=round(lag, 3),
                          max_lag_s=float(conf.ingest_max_lag_seconds))

    def _slo_burning(self) -> bool:
        """Is any serve objective paging? Compaction (rebuild-class IO)
        defers behind this, same as the controller's backoff."""
        try:
            from hyperspace_tpu.serve.controller import SERVE_OBJECTIVES

            verdicts = obs_slo.evaluate()
            return any(
                verdicts.get(o, {}).get("verdict") == "page" for o in SERVE_OBJECTIVES
            )
        except Exception:  # noqa: BLE001 — advisory signal, never blocks ingest
            return False

    def snapshot(self) -> dict:
        """Healthz section (obs/http.py) — cheap, lock-consistent."""
        with self._lock:
            now = self._clock()
            return {
                "enabled": bool(self.session.conf.ingest_enabled),
                "running": self._thread is not None or self._host is not None,
                "mode": "process" if self._host is not None else "thread",
                "paused": self.paused(),
                "watched": sorted(self._watches),
                "commits": self._commits,
                "last_commit_ids": dict(self._last_commit_id),
                "pending_lag_seconds": {
                    n: round(now - t, 3) for n, t in self._pending_since.items()
                },
                "last_commit_lag_seconds": self._last_lag_s,
            }


def _service_entry(system_path, watches, env, stop_event):
    """Worker-process service shim (processWorker mode; declared in
    analysis/procdomain.SPAWN_ENTRY_POINTS). Installs shipped
    fault/obs/journal state, rebuilds a session over `system_path`, and
    runs the same tick loop in-process — commits go through the same
    two-phase Action protocol, so a SIGKILL here converges via
    recover() exactly like a crashed operator process."""
    fault_state = env.get("faults")
    if fault_state is not None:
        faults.install_state(fault_state)
    obs_trace.set_enabled(bool(env.get("obs_enabled", True)))
    journal_state = env.get("journal")
    if journal_state is not None:
        from hyperspace_tpu.obs import journal as obs_journal

        obs_journal.install_state(journal_state)
    # Deferred import: HSL019 — jax must not be reachable at worker
    # start; the session only pulls execution machinery when a commit
    # actually builds.
    from hyperspace_tpu.hyperspace import Hyperspace, HyperspaceSession

    session = HyperspaceSession(system_path=system_path)
    for key, value in (env.get("overrides") or {}).items():
        session.conf.set(key, value)
    daemon = IngestDaemon(Hyperspace(session))
    for name, changelog in watches:
        daemon.watch(name, changelog=changelog)
    poll = float(session.conf.ingest_poll_seconds)
    while not stop_event.is_set():
        try:
            daemon.tick()
        except Exception as e:  # noqa: BLE001 — service loop survives
            stats.increment("ingest.commit_failures")
            _EVT_COMMIT_FAILED.emit(error=f"{type(e).__name__}: {e}")
        stop_event.wait(poll)
