"""Vector-index lifecycle: refresh (full + incremental) and optimize.

Round-1 verdict called out that the ANN index silently rotted on append
(refresh/optimize raised for VectorIndex). Design mirrors the covering
index's lifecycle:

- full refresh re-lists the logged source, RETRAINS the coarse quantizer
  and rebuilds every partition into the next `v__=` version;
- incremental refresh assigns ONLY the appended rows to the EXISTING
  centroids and writes per-partition delta files into the next version,
  keeping all prior version dirs live (partition p = union of p's files
  across dirs — exactly the covering index's hybrid layout);
- optimize re-reads all live rows, retrains the centroids over the full
  set, and compacts everything back into one file per partition.

All three run inside the standard 2-phase op-log commit (REFRESHING /
OPTIMIZING transient states), so crash recovery and `cancel` apply
unchanged.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path

import numpy as np

from hyperspace_tpu import stats as _stats
from hyperspace_tpu.actions import states
from hyperspace_tpu.actions.base import Action
from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.execution import io as hio
from hyperspace_tpu.metadata.data_manager import IndexDataManager
from hyperspace_tpu.metadata.log_entry import IndexLogEntry
from hyperspace_tpu.metadata.log_manager import IndexLogManager
from hyperspace_tpu.ops.kmeans import assign_partitions, train_centroids
from hyperspace_tpu.plan.nodes import plan_from_json
from hyperspace_tpu.vector.index import (
    CENTROIDS_NAME,
    VectorCreateAction,
    VectorIndexConfig,
)


def _live_dirs(entry: IndexLogEntry) -> list[Path]:
    return [Path(entry.content.root) / d for d in entry.content.directories]


def load_centroids(entry: IndexLogEntry) -> np.ndarray:
    """Centroids of the newest live version (every version dir carries a
    copy so vacuuming old dirs can never orphan the quantizer)."""
    for d in reversed(_live_dirs(entry)):
        p = d / CENTROIDS_NAME
        if p.exists():
            return np.load(p)
    raise HyperspaceError(f"index {entry.name!r} has no {CENTROIDS_NAME}")


class VectorRefreshAction(VectorCreateAction):
    """Full rebuild from logged lineage (REFRESHING → ACTIVE): the scan
    re-lists the live filesystem, the quantizer is retrained, every
    partition is rewritten into the next version."""

    transient_state = states.REFRESHING
    final_state = states.ACTIVE

    def __init__(
        self,
        log_manager: IndexLogManager,
        data_manager: IndexDataManager,
        index_path: Path,
        conf: HyperspaceConf,
        builder=None,
    ):
        prev = log_manager.get_latest_log()
        if prev is None:
            raise HyperspaceError("no index to refresh")
        dd = prev.derived_dataset
        if dd is None or dd.kind != "VectorIndex":
            raise HyperspaceError(f"index {prev.name!r} is not a vector index")
        plan = plan_from_json(prev.source.plan)
        cfg = VectorIndexConfig(
            prev.name,
            dd.embedding_column,
            list(dd.included_columns),
            dd.num_partitions,
            dd.metric,
        )
        super().__init__(plan, cfg, log_manager, data_manager, index_path, conf, builder)
        self.previous_entry = prev

    def validate(self) -> None:
        if self.previous_entry.state != states.ACTIVE:
            raise HyperspaceError(
                f"refresh is only supported in {states.ACTIVE} state "
                f"(found {self.previous_entry.state})"
            )


class VectorRefreshIncrementalAction(VectorRefreshAction):
    """Index ONLY the appended source files: assign their rows to the
    existing centroids and write per-partition delta files into the next
    version; prior version dirs stay live."""

    def __init__(self, log_manager, data_manager, index_path, conf, builder=None):
        super().__init__(log_manager, data_manager, index_path, conf, builder)
        from hyperspace_tpu.signature import diff_source_files

        self._appended, self._deleted = diff_source_files(self.previous_entry, self.plan)

    def validate(self) -> None:
        super().validate()
        if self._deleted:
            raise HyperspaceError(
                "incremental refresh cannot handle deleted or modified source "
                f"files ({[f.path for f in self._deleted][:3]}...); run a full "
                "refresh instead"
            )
        if not self._appended:
            raise HyperspaceError("refresh aborted: no appended source data files found")

    def _source_files(self) -> list:
        # EXACTLY the indexed snapshot: previous files + the diff (never a
        # second live listing).
        return sorted(
            list(self.previous_entry.source.files) + list(self._appended),
            key=lambda f: f.path,
        )

    def build_log_entry(self) -> IndexLogEntry:
        entry = super().build_log_entry()
        prev_dirs = list(self.previous_entry.content.directories)
        entry.content = dataclasses.replace(
            entry.content, directories=prev_dirs + [f"v__={self._version_id}"]
        )
        return entry

    def op(self) -> None:
        entry = self.log_entry
        dest = self.data_manager.get_path(self._version_id)
        delta_plan = dataclasses.replace(
            self.plan, files=[f.path for f in self._appended]
        )
        centroids = load_centroids(self.previous_entry)
        write_partitions(
            delta_plan,
            entry.derived_dataset,
            centroids,
            dest,
            schema=self.plan.schema,
        )


class VectorOptimizeAction(Action):
    """Retrain + compact (OPTIMIZING → ACTIVE): all live rows are re-read,
    the quantizer is retrained on the full embedding set (appended data
    shifted the distribution the original centroids were fit to), and one
    file per partition is written to the next version."""

    transient_state = states.OPTIMIZING
    final_state = states.ACTIVE

    def __init__(
        self,
        log_manager: IndexLogManager,
        data_manager: IndexDataManager,
        kmeans_iters: int = 8,
        seed: int = 0,
    ):
        super().__init__(log_manager)
        self.data_manager = data_manager
        self.kmeans_iters = kmeans_iters
        self.seed = seed
        self._version: int | None = None
        self.previous_entry = log_manager.get_latest_log()
        if self.previous_entry is None:
            raise HyperspaceError("no index to optimize")
        dd = self.previous_entry.derived_dataset
        if dd is None or dd.kind != "VectorIndex":
            raise HyperspaceError(f"index {self.previous_entry.name!r} is not a vector index")

    def validate(self) -> None:
        if self.previous_entry.state != states.ACTIVE:
            raise HyperspaceError(
                f"optimize is only supported in {states.ACTIVE} state "
                f"(found {self.previous_entry.state})"
            )

    @property
    def _version_id(self) -> int:
        # Memoized (see actions/create.py): entry, dest, and failure
        # cleanup must agree on one version once op() starts writing.
        if self._version is None:
            latest = self.data_manager.get_latest_version_id()
            self._version = 0 if latest is None else latest + 1
        return self._version

    def cleanup_failed_op(self) -> None:
        try:
            self.data_manager.quarantine(self._version_id)
        except Exception:
            # Must-not-raise path, but never silent: recover()'s orphan
            # GC owns whatever this leaves behind.
            _stats.increment("action.cleanup_failed")

    def build_log_entry(self) -> IndexLogEntry:
        entry = dataclasses.replace(self.previous_entry)
        entry.content = dataclasses.replace(
            entry.content, directories=[f"v__={self._version_id}"]
        )
        return entry

    def op(self) -> None:
        from hyperspace_tpu.schema import Schema

        dd = self.previous_entry.derived_dataset
        schema = Schema.from_json(dd.schema)
        files = []
        for d in _live_dirs(self.previous_entry):
            files.extend(
                str(d / hio.bucket_file_name(p)) for p in range(dd.num_partitions)
                if (d / hio.bucket_file_name(p)).exists()
            )
        table = hio.read_parquet(files, columns=schema.names, schema=schema)
        if table.num_rows == 0:
            raise HyperspaceError("index has no data to optimize")
        emb = table.columns[schema.field(dd.embedding_column).name]
        if dd.metric == "cos":
            emb = emb / np.maximum(np.linalg.norm(emb, axis=1, keepdims=True), 1e-12)
        centroids = train_centroids(
            emb, dd.num_partitions, iters=self.kmeans_iters, seed=self.seed
        )
        part = assign_partitions(emb, centroids)
        order = np.argsort(part, kind="stable")
        dest = Path(self.data_manager.get_path(self._version_id))
        hio.carve_and_write(
            dest, table, part[order], dd.num_partitions, [dd.embedding_column], order=order
        )
        np.save(dest / CENTROIDS_NAME, centroids)


def write_partitions(plan, dd, centroids: np.ndarray, dest: Path, schema) -> None:
    """Assign `plan`'s rows to EXISTING centroids and carve one parquet
    per partition into `dest` (+ a centroids copy)."""
    from hyperspace_tpu.dataset import format_suffix, list_data_files

    files = plan.files if plan.files is not None else [
        fi.path for fi in list_data_files(plan.root, suffix=format_suffix(plan.format))
    ]
    table = hio.read_table_files(files, plan.format, columns=dd.all_columns, schema=schema)
    emb = table.columns[table.schema.field(dd.embedding_column).name]
    if dd.metric == "cos":
        emb = emb / np.maximum(np.linalg.norm(emb, axis=1, keepdims=True), 1e-12)
    part = assign_partitions(emb, centroids)
    order = np.argsort(part, kind="stable")
    dest = Path(dest)
    hio.carve_and_write(
        dest, table, part[order], dd.num_partitions, [dd.embedding_column], order=order
    )
    np.save(dest / CENTROIDS_NAME, centroids)
