"""ANN query path: probe the vector index (or brute-force the source).

Query flow for `ann_search`:

1. find an ACTIVE VectorIndex over the scanned dataset whose stored
   signature matches the live data (same contract as the rewrite rules —
   a stale index silently falls back to brute force, mirroring how the
   covering-index rules downgrade to the raw scan);
2. score queries against the centroids and pick each query's `nprobe`
   nearest partitions (matmul + top-k);
3. load the union of probed partitions, score candidates in one batched
   MXU matmul, select top-k per query with the Pallas kernel (ops/topk.py);
4. per query, mask candidates from partitions it did not probe.

With nprobe == num_partitions the result is EXACTLY brute force — the
equality gate the tests pin.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path

import numpy as np

from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.execution import io as hio
from hyperspace_tpu.execution.table import ColumnTable
from hyperspace_tpu.metadata.log_entry import IndexLogEntry
from hyperspace_tpu.ops.topk import topk
from hyperspace_tpu.plan.nodes import LogicalPlan, Scan
from hyperspace_tpu.schema import Schema


@dataclasses.dataclass
class AnnResult:
    """Top-k matches for one query batch. Row-major: query i's matches are
    `indices[i]`/`scores[i]`; `rows` holds the matched payload rows as a
    ColumnTable with a leading `__query__` column."""

    scores: np.ndarray  # [q, k] (higher is better; l2 scores are negated distances)
    rows: ColumnTable


def _device_scores(metric: str, queries, cand):
    """[q, m] score matrix, higher = better, computed AND LEFT on device.

    The tunneled-TPU lesson baked into this module: device→host bandwidth
    is ~30x worse than host→device here, so the [q, m] score matrix must
    never be materialized on host — only the [q, k] top-k result comes
    back."""
    import jax.numpy as jnp

    q = jnp.asarray(queries, dtype=jnp.float32)
    x = jnp.asarray(cand, dtype=jnp.float32)
    if metric == "cos":
        q = q / jnp.maximum(jnp.linalg.norm(q, axis=1, keepdims=True), 1e-12)
        x = x / jnp.maximum(jnp.linalg.norm(x, axis=1, keepdims=True), 1e-12)
    dots = q @ x.T  # [q, m] — the MXU hot op
    if metric == "l2":
        qsq = jnp.sum(q * q, axis=1, keepdims=True)
        xsq = jnp.sum(x * x, axis=1)[None, :]
        return -(qsq - 2.0 * dots + xsq)  # negated squared distance
    return dots


def brute_force_search(
    table: ColumnTable, embedding_column: str, queries: np.ndarray, k: int, metric: str = "l2"
) -> AnnResult:
    """Exact search over a materialized table (the no-index fallback)."""
    emb_name = table.schema.field(embedding_column).name
    scores = _device_scores(metric, queries, table.columns[emb_name])
    vals, idx = topk(scores, k)
    return _gather_result(table, vals, idx)


def _result_with_query_ids(rows: ColumnTable, vals: np.ndarray) -> AnnResult:
    """Attach the leading __query__ column; `rows` is query-major [q*k].
    Slots whose score is -inf (query matched fewer than k candidates) are
    dropped from `rows`; `scores` keeps the -inf markers."""
    from hyperspace_tpu.schema import Field

    q, k = vals.shape
    qcol = np.repeat(np.arange(q, dtype=np.int64), k)
    schema = Schema((Field("__query__", "int64"),) + rows.schema.fields)
    cols = {"__query__": qcol, **rows.columns}
    out = ColumnTable(schema, cols, dict(rows.dictionaries), dict(rows.validity))
    valid = np.isfinite(vals.reshape(-1))
    if not valid.all():
        out = out.filter_mask(valid)
    return AnnResult(scores=vals, rows=out)


def _gather_result(table: ColumnTable, vals: np.ndarray, idx: np.ndarray) -> AnnResult:
    return _result_with_query_ids(table.take(idx.reshape(-1)), vals)


def find_vector_index(
    session, plan: Scan, embedding_column: str | None = None
) -> IndexLogEntry | None:
    """ACTIVE VectorIndex over this scan with a live signature match."""
    from hyperspace_tpu.rules.base import SignatureMatcher

    matcher = SignatureMatcher()
    for entry in session.manager.get_indexes():
        if entry.derived_dataset.kind != "VectorIndex":
            continue
        if (
            embedding_column is not None
            and entry.derived_dataset.embedding_column.lower() != embedding_column.lower()
        ):
            continue
        m = matcher.match(entry, plan)
        if m is not None and m.is_exact:
            return entry
    return None


def ann_search(
    session,
    plan: LogicalPlan,
    queries,
    k: int,
    nprobe: int | None = None,
    embedding_column: str | None = None,
    metric: str | None = None,
) -> AnnResult:
    """Approximate nearest neighbours of `queries` [q, d] over the scanned
    dataset. Uses a matching vector index when hyperspace is enabled and
    one exists (scoring with the INDEX's metric; an explicitly different
    `metric` raises instead of being silently ignored); otherwise
    brute-forces the source exactly, scoring with `metric` (default l2)."""
    queries = np.asarray(queries, dtype=np.float32)
    if queries.ndim == 1:
        queries = queries[None, :]
    if not isinstance(plan, Scan):
        raise HyperspaceError("ann_search operates on a scanned dataset (Scan plan)")

    entry = None
    if session.is_hyperspace_enabled():
        entry = find_vector_index(session, plan, embedding_column)

    if entry is None:
        # Exact fallback over the raw source.
        if embedding_column is None:
            vec_fields = [f for f in plan.schema.fields if f.is_vector]
            if len(vec_fields) != 1:
                raise HyperspaceError(
                    "embedding_column is required when the schema does not have "
                    "exactly one vector column"
                )
            embedding_column = vec_fields[0].name
        from hyperspace_tpu.execution.executor import Executor

        table = Executor().execute(plan)
        return brute_force_search(table, embedding_column, queries, k, metric or "l2")

    dd = entry.derived_dataset
    if metric is not None and metric != dd.metric:
        raise HyperspaceError(
            f"metric {metric!r} conflicts with index {entry.name!r} built with "
            f"metric {dd.metric!r}; omit metric or disable hyperspace for an "
            "exact search with the requested metric"
        )
    # Incremental refresh keeps several version dirs live: partition p is
    # the union of p's files across dirs (the covering index's hybrid
    # layout). Centroids come from the newest dir carrying a copy.
    from hyperspace_tpu.vector.lifecycle import load_centroids

    dirs = [Path(entry.content.root) / d for d in entry.content.directories]
    centroids = load_centroids(entry)
    num_partitions = dd.num_partitions
    nprobe = num_partitions if nprobe is None else min(nprobe, num_partitions)

    qv = queries
    if dd.metric == "cos":
        qv = qv / np.maximum(np.linalg.norm(qv, axis=1, keepdims=True), 1e-12)

    # Stage 1: route queries to their nprobe nearest partitions.
    cscores = _device_scores(dd.metric, qv, centroids)
    _, probe = topk(cscores, nprobe)  # [q, nprobe]

    # Stage 2: candidate geometry from the manifests — no payload IO yet.
    # One rows[(dir, p)] map per query batch; stages 3 and 4 reuse it so
    # the stat/manifest lookups run once per (dir, partition).
    needed = sorted(set(int(p) for p in probe.reshape(-1)))
    schema = Schema.from_json(dd.schema)
    rows_map = {(d, p): _partition_rows(d, p) for p in needed for d in dirs}
    sizes = np.array(
        [sum(rows_map[(d, p)] for d in dirs) for p in needed], dtype=np.int64
    )
    offsets = np.concatenate([[0], np.cumsum(sizes)])
    cand_part = np.repeat(np.array(needed, dtype=np.int32), sizes)

    # Stage 3: one batched scoring matmul + top-k, all on device. Each
    # partition's embedding matrix is cached device-resident (only the
    # embedding column is read from parquet for it), so a query batch
    # uploads just the queries and the probed-partition mask; no score
    # matrix is ever downloaded.
    import jax.numpy as jnp

    emb_name = schema.field(dd.embedding_column).name
    emb_parts = [
        _partition_device_emb(d, p, schema, emb_name)
        for p in needed
        for d in dirs
        if rows_map[(d, p)] > 0
    ]
    emb_dev = jnp.concatenate(emb_parts) if emb_parts else jnp.zeros((0, dd.dim), jnp.float32)
    scores = _device_scores(dd.metric, qv, emb_dev)  # [q, m] on device
    probed_mask = np.zeros((len(qv), num_partitions), dtype=bool)
    probed_mask[np.arange(len(qv))[:, None], probe] = True
    scores = jnp.where(jnp.asarray(probed_mask[:, cand_part]), scores, -np.inf)
    m = int(offsets[-1])
    vals, idx = topk(scores, min(k, m))

    # Stage 4: payload gather — read ONLY the partitions owning winning
    # rows, one batched take per owner, reassembled into slot order.
    flat = np.asarray(idx).reshape(-1)
    # Pad-lane top-k partials can carry indices >= m with -inf scores; if
    # one survives the merge, its index would fall past the last offset.
    # Point those slots at row 0 — callers drop them via the -inf score.
    flat = np.where(np.isfinite(np.asarray(vals).reshape(-1)), flat, 0)
    owner = np.searchsorted(offsets, flat, side="right") - 1
    local = flat - offsets[owner]
    group_order = np.argsort(owner, kind="stable")
    grouped: list[ColumnTable] = []
    for o in np.unique(owner):
        part_table = _read_partition_multi(dirs, needed[int(o)], schema, rows_map)
        grouped.append(part_table.take(local[owner == o]))
    regrouped = ColumnTable.concat(grouped)
    inverse = np.empty(len(flat), dtype=np.int64)
    inverse[group_order] = np.arange(len(flat))
    rows = regrouped.take(inverse)
    return _result_with_query_ids(rows, vals)


def _partition_rows(version_dir: Path, p: int) -> int:
    """Row count of partition p in one version dir (0 when the dir has no
    file for it), from the dir's manifest or the parquet footer."""
    path = version_dir / hio.bucket_file_name(p)
    if not path.exists():
        return 0
    manifest = hio.read_manifest_cached(version_dir)
    if manifest is not None and p < len(manifest.get("bucketRows", [])):
        return int(manifest["bucketRows"][p])
    import pyarrow.parquet as pq

    return int(pq.read_metadata(path).num_rows)


def _read_partition_multi(dirs: list[Path], p: int, schema: Schema, rows_map: dict) -> ColumnTable:
    """Partition p's payload rows concatenated across version dirs, in the
    SAME dir order the embedding concat uses (offsets stay aligned)."""
    parts = [
        _read_partition(d, p, schema) for d in dirs if rows_map[(d, p)] > 0
    ]
    if not parts:
        return ColumnTable.empty(schema)
    return ColumnTable.concat(parts) if len(parts) > 1 else parts[0]


# Per-process partition read cache: (path, mtime_ns) → ColumnTable. The
# probed working set is re-read on every query batch otherwise; bounded by
# total cached bytes with FIFO eviction. One lock covers both caches —
# the byte-budget eviction is a read-modify-write that concurrent serve
# workers must not interleave.
import threading

_VEC_CACHE_LOCK = threading.Lock()
_PARTITION_CACHE: dict = {}
_PARTITION_CACHE_BYTES = 2 * 1024**3


def _table_bytes(t: ColumnTable) -> int:
    return sum(v.nbytes for v in t.columns.values())


# Device-resident embedding matrices per partition file, so repeated query
# batches skip the host→device upload of candidate embeddings entirely.
_DEVICE_EMB_CACHE: dict = {}
_DEVICE_EMB_CACHE_BYTES = 4 * 1024**3


def _partition_device_emb(version_dir: Path, p: int, schema: Schema, emb_name: str):
    import os

    import jax.numpy as jnp

    path = str(version_dir / hio.bucket_file_name(p))
    key = (path, os.stat(path).st_mtime_ns, emb_name)
    with _VEC_CACHE_LOCK:
        hit = _DEVICE_EMB_CACHE.get(key)
    if hit is not None:
        return hit
    # Read ONLY the embedding column — payload columns are read lazily by
    # _read_partition when a winning row actually lands in this partition.
    t = hio.read_parquet([path], columns=[emb_name], schema=schema)
    arr = jnp.asarray(t.columns[emb_name], dtype=jnp.float32)
    with _VEC_CACHE_LOCK:
        _DEVICE_EMB_CACHE[key] = arr
        total = sum(a.nbytes for a in _DEVICE_EMB_CACHE.values())
        while total > _DEVICE_EMB_CACHE_BYTES and len(_DEVICE_EMB_CACHE) > 1:
            oldest = next(iter(_DEVICE_EMB_CACHE))
            total -= _DEVICE_EMB_CACHE.pop(oldest).nbytes
    return arr


def _read_partition(version_dir: Path, p: int, schema: Schema) -> ColumnTable:
    import os

    path = str(version_dir / hio.bucket_file_name(p))
    key = (path, os.stat(path).st_mtime_ns)
    with _VEC_CACHE_LOCK:
        hit = _PARTITION_CACHE.get(key)
    if hit is not None:
        return hit
    t = hio.read_parquet([path], columns=schema.names, schema=schema)
    with _VEC_CACHE_LOCK:
        _PARTITION_CACHE[key] = t
        # FIFO-evict oldest entries past the byte budget (dict preserves
        # insertion order).
        total = sum(_table_bytes(tab) for tab in _PARTITION_CACHE.values())
        while total > _PARTITION_CACHE_BYTES and len(_PARTITION_CACHE) > 1:
            oldest = next(iter(_PARTITION_CACHE))
            total -= _table_bytes(_PARTITION_CACHE.pop(oldest))
    return t
