from hyperspace_tpu.vector.index import (
    VectorCreateAction,
    VectorIndexBuilder,
    VectorIndexConfig,
)
from hyperspace_tpu.vector.search import ann_search, brute_force_search

__all__ = [
    "VectorCreateAction",
    "VectorIndexBuilder",
    "VectorIndexConfig",
    "ann_search",
    "brute_force_search",
]
