"""Vector (ANN) covering index: config, build pipeline, create action.

No analog exists in the v0.2 reference (its covering index is relational
only); BASELINE config 5 requires an embedding-column ANN index. The design
follows the same two-plane split as the covering index:

- metadata: a `VectorIndex` derived dataset inside the standard
  IndexLogEntry, so the whole lifecycle machinery (op-log CAS, states,
  delete/restore/vacuum/cancel) applies unchanged;
- device: build = k-means coarse quantizer (ops/kmeans.py, pure MXU
  matmuls) + partition carve; query = matmul scoring + Pallas top-k
  (ops/topk.py) over the probed partitions.

On-disk layout mirrors the covering index: one parquet file per partition
(`bucket-XXXXX.parquet`, embedding + included columns) in a `v__=n` dir,
plus the manifest and a `_centroids.npy`.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path

import numpy as np

from hyperspace_tpu.actions.create import CreateActionBase
from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.execution import io as hio
from hyperspace_tpu.execution.table import ColumnTable
from hyperspace_tpu.metadata.data_manager import IndexDataManager
from hyperspace_tpu.metadata.log_entry import (
    Content,
    Fingerprint,
    IndexLogEntry,
    Source,
    VectorIndex,
)
from hyperspace_tpu.metadata.log_manager import IndexLogManager
from hyperspace_tpu.ops.kmeans import assign_partitions, train_centroids
from hyperspace_tpu.plan.nodes import LogicalPlan, Scan
from hyperspace_tpu.signature import create_signature_provider, fingerprint_files
from hyperspace_tpu.utils.name_utils import normalize_index_name

CENTROIDS_NAME = "_centroids.npy"

_METRICS = ("l2", "ip", "cos")


@dataclasses.dataclass
class VectorIndexConfig:
    """User spec for a vector index (the IndexConfig analog)."""

    index_name: str
    embedding_column: str
    included_columns: list[str] = dataclasses.field(default_factory=list)
    num_partitions: int | None = None  # default: conf.num_buckets
    metric: str = "l2"

    def __post_init__(self):
        self.index_name = normalize_index_name(self.index_name)
        if not self.index_name:
            raise HyperspaceError("index name cannot be empty")
        if self.metric not in _METRICS:
            raise HyperspaceError(f"unknown metric {self.metric!r}; one of {_METRICS}")
        low = [self.embedding_column.lower()] + [c.lower() for c in self.included_columns]
        if len(set(low)) != len(low):
            raise HyperspaceError("duplicate columns in vector index config")

    @property
    def all_columns(self) -> list[str]:
        return [self.embedding_column] + list(self.included_columns)


class VectorIndexBuilder:
    """The build pipeline (IndexWriter-shaped seam for VectorCreateAction)."""

    def __init__(self, kmeans_iters: int = 8, seed: int = 0):
        from hyperspace_tpu.parallel.mesh import enable_compile_cache

        enable_compile_cache()
        self.kmeans_iters = kmeans_iters
        self.seed = seed

    def write(
        self,
        plan: LogicalPlan,
        columns: list[str],
        embedding_column: str,
        num_partitions: int,
        dest_path: Path,
        metric: str,
    ) -> np.ndarray:
        """Build partitions under dest_path; returns the centroids."""
        from hyperspace_tpu.dataset import format_suffix, list_data_files

        if not isinstance(plan, Scan):
            raise HyperspaceError("vector index builds materialize scan-only plans")
        files = plan.files if plan.files is not None else [
            fi.path for fi in list_data_files(plan.root, suffix=format_suffix(plan.format))
        ]
        table = hio.read_table_files(files, plan.format, columns=columns, schema=plan.schema)
        if table.num_rows == 0:
            raise HyperspaceError("cannot build a vector index over an empty source")
        emb_field = table.schema.field(embedding_column)
        emb = table.columns[emb_field.name]
        if metric == "cos":
            norms = np.linalg.norm(emb, axis=1, keepdims=True)
            emb = emb / np.maximum(norms, 1e-12)

        centroids = train_centroids(
            emb, num_partitions, iters=self.kmeans_iters, seed=self.seed
        )
        part = assign_partitions(emb, centroids)

        order = np.argsort(part, kind="stable")
        dest = Path(dest_path)
        hio.carve_and_write(
            dest, table, part[order], num_partitions, [embedding_column], order=order
        )
        np.save(dest / CENTROIDS_NAME, centroids)
        return centroids


class VectorCreateAction(CreateActionBase):
    """CREATING → ACTIVE for a vector index; same 2-phase op-log commit."""

    def __init__(
        self,
        plan: LogicalPlan,
        config: VectorIndexConfig,
        log_manager: IndexLogManager,
        data_manager: IndexDataManager,
        index_path: Path,
        conf: HyperspaceConf,
        builder: VectorIndexBuilder | None = None,
    ):
        from hyperspace_tpu.index.index_config import IndexConfig

        # The base class wants an IndexConfig; give it the column view.
        base_cfg = IndexConfig(config.index_name, [config.embedding_column], config.included_columns)
        super().__init__(plan, base_cfg, log_manager, data_manager, index_path, conf, None)
        self.vconfig = config
        self._builder = builder

    @property
    def builder(self) -> VectorIndexBuilder:
        # Lazy: actions that never build (incremental refresh assigns to
        # existing centroids via write_partitions) skip construction.
        if self._builder is None:
            self._builder = VectorIndexBuilder()
        return self._builder

    def _num_partitions(self) -> int:
        if self.vconfig.num_partitions is not None:
            return int(self.vconfig.num_partitions)
        return int(self.conf.num_buckets)

    def validate(self) -> None:
        if not isinstance(self.plan, Scan):
            raise HyperspaceError("only scan-only plans are supported for vector indexes")
        schema = self.plan.schema
        for c in self.vconfig.all_columns:
            if c not in schema:
                raise HyperspaceError(f"column {c!r} not found in source schema {schema.names}")
        emb = schema.field(self.vconfig.embedding_column)
        if not emb.is_vector:
            raise HyperspaceError(
                f"embedding column {emb.name!r} must have vector dtype (got {emb.dtype!r})"
            )
        latest = self.log_manager.get_latest_log()
        from hyperspace_tpu.actions import states

        if latest is not None and latest.state != states.DOESNOTEXIST:
            raise HyperspaceError(
                f"another index with name {self.vconfig.index_name!r} already exists "
                f"(state={latest.state})"
            )

    def build_log_entry(self) -> IndexLogEntry:
        schema = self.plan.schema
        selected = schema.select(self.vconfig.all_columns)
        emb = schema.field(self.vconfig.embedding_column)
        files = self._source_files()
        provider = create_signature_provider()
        version = self._version_id
        return IndexLogEntry(
            name=self.vconfig.index_name,
            derived_dataset=VectorIndex(
                embedding_column=emb.name,
                included_columns=[schema.field(c).name for c in self.vconfig.included_columns],
                schema=selected.to_json(),
                num_partitions=self._num_partitions(),
                dim=int(emb.dim),
                metric=self.vconfig.metric,
            ),
            content=Content(root=str(self.index_path), directories=[f"v__={version}"]),
            source=Source(
                plan=self.plan.to_json(),
                fingerprint=Fingerprint(
                    kind=provider.name, value=fingerprint_files(files)
                ),
                files=files,
            ),
        )

    def op(self) -> None:
        entry = self.log_entry
        dest = self.data_manager.get_path(self._version_id)
        self.builder.write(
            self.plan,
            entry.derived_dataset.all_columns,
            entry.derived_dataset.embedding_column,
            entry.derived_dataset.num_partitions,
            dest,
            entry.derived_dataset.metric,
        )
