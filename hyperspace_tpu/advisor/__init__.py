"""Workload-driven index advisor (docs/advisor.md).

Three cooperating layers close the self-tuning loop over the evidence
the observability and serving planes already record:

- **What-if analyzer** (`advisor/whatif.py` + `advisor/cost.py`): replay
  the observed workload (per-query :class:`WorkloadRecord`\\ s carrying
  plan + measured profile) through the real rewrite rules and plan
  validator against *hypothetical* index specs mined from the filter /
  join predicates, cost them with a model calibrated from measured
  per-operator wall/bytes, and emit ranked create / drop / re-bucket /
  optimize recommendations with estimated benefit and confidence.
- **Adaptive query routing** (`advisor/routing.py`): a per-plan-
  signature outcome ledger (indexed vs raw wall, EMA-smoothed, persisted
  under ``<system_path>/_advisor/``, versioned-key invalidated on index
  mutation like the serve caches) demotes a rewrite to source scan when
  the indexed path has MEASURED slower — the structural fix for the
  sub-1x rewrite tail.
- **Autonomous lifecycle** (`advisor/lifecycle.py`): an opt-in policy
  engine that executes recommendations — auto-create hot indexes,
  auto-vacuum cold ones, auto-optimize fragmented ones — every mutation
  crash-safe through the existing `Action` state machine, with the
  ``advisor.recommend`` / ``advisor.apply`` fault points wired into the
  injection harness.
"""

from hyperspace_tpu.advisor.cost import CostModel
from hyperspace_tpu.advisor.lifecycle import LifecyclePolicy
from hyperspace_tpu.advisor.routing import RoutingLedger
from hyperspace_tpu.advisor.whatif import Recommendation, WhatIfAnalyzer
from hyperspace_tpu.advisor.workload import WorkloadLog, WorkloadRecord

__all__ = [
    "CostModel",
    "LifecyclePolicy",
    "Recommendation",
    "RoutingLedger",
    "WhatIfAnalyzer",
    "WorkloadLog",
    "WorkloadRecord",
]
