"""Autonomous index lifecycle: the opt-in engine that ACTS on advice.

``LifecyclePolicy.sweep()`` asks the what-if analyzer for ranked
recommendations and executes the ones the policy gates allow:

- ``create``   — when ``hyperspace.advisor.lifecycle.autoCreate`` is on:
  build the recommended covering index (hot predicates get their index
  without an operator in the loop);
- ``drop``     — when ``autoVacuum`` is on: delete THEN vacuum the cold
  index (both through the normal two-phase actions);
- ``optimize`` — when ``autoOptimize`` is on: compact a fragmented
  index's delta dirs;
- ``rebucket`` — always report-only: changing a bucket count rebuilds
  the index under a different layout, a capacity decision the policy
  surfaces but does not take autonomously.

Every mutation goes through the existing ``Hyperspace`` API and
therefore the crash-safe ``Action`` two-phase protocol — a process dying
mid-apply leaves a transient log entry that ``recover()`` repairs, same
as any human-initiated action. The ``advisor.apply`` fault point fires
in ``sweep()`` IMMEDIATELY BEFORE each mutation: an injected
``CrashPoint`` there proves the sweep itself never leaves partial state
(nothing has mutated yet), and an injected transient ``FaultError``
surfaces through the declared error contract. A mutation that fails with
an ordinary ``Exception`` is recorded (``advisor.apply_failed`` counter
+ trace event) and the sweep continues — one broken recommendation must
not starve the rest — while a ``CrashPoint`` propagates like the process
death it simulates.

All three gates default OFF: the advisor observes by default and acts
only by explicit opt-in.
"""

from __future__ import annotations

from hyperspace_tpu import faults
from hyperspace_tpu.advisor.whatif import Recommendation, WhatIfAnalyzer
from hyperspace_tpu.obs import metrics as obs_metrics
from hyperspace_tpu.obs import trace as obs_trace

_APPLIED = obs_metrics.counter(
    "advisor.applied", "lifecycle mutations executed from recommendations"
)
_APPLY_FAILED = obs_metrics.counter(
    "advisor.apply_failed", "lifecycle mutations that raised and were recorded"
)
_SKIPPED = obs_metrics.counter(
    "advisor.skipped", "recommendations below the policy gates"
)


class LifecyclePolicy:
    """Policy gates + executor over advisor recommendations."""

    def __init__(self, hyperspace, analyzer: WhatIfAnalyzer | None = None):
        # `hyperspace` is the user-facing API facade (hyperspace.Hyperspace):
        # every mutation below goes through its 8-method surface, so the
        # advisor has exactly the powers an operator has — no private
        # side doors into the log.
        self.hyperspace = hyperspace
        self.session = hyperspace.session
        self.analyzer = analyzer or WhatIfAnalyzer(self.session)

    def _allowed(self, rec: Recommendation) -> bool:
        conf = self.session.conf
        if rec.kind == "create":
            allowed = conf.advisor_auto_create
        elif rec.kind == "drop":
            allowed = conf.advisor_auto_vacuum
        elif rec.kind == "optimize":
            allowed = conf.advisor_auto_optimize
        else:  # rebucket: report-only by design (module docstring)
            return False
        if not allowed:
            return False
        if rec.confidence < float(conf.advisor_min_confidence):
            return False
        return rec.estimated_benefit_s >= float(conf.advisor_min_benefit_seconds)

    def sweep(self, recommendations: list[Recommendation] | None = None) -> dict:
        """One policy pass: recommend (unless given), gate, apply.
        Returns a report of applied / skipped / failed entries; every
        applied mutation is individually crash-safe (module docstring)."""
        with obs_trace.span("advisor.sweep"):
            if recommendations is None:
                recommendations = self.analyzer.recommend()
            report: dict = {"applied": [], "skipped": [], "failed": []}
            for rec in recommendations:
                if not self._allowed(rec):
                    _SKIPPED.inc()
                    report["skipped"].append(rec.to_json())
                    continue
                faults.fault_point("advisor.apply")
                try:
                    with obs_trace.span(
                        "advisor.apply", kind=rec.kind, index=rec.index_name
                    ):
                        self._apply(rec)
                except Exception as e:
                    # One failed mutation (its own Action already rolled
                    # back / quarantined) must not starve the remaining
                    # recommendations — record and continue. CrashPoint
                    # is a BaseException and propagates: a dying process
                    # does not keep sweeping.
                    _APPLY_FAILED.inc()
                    obs_trace.event(
                        "advisor.apply_failed", kind=rec.kind, error=str(e)
                    )
                    failed = rec.to_json()
                    failed["error"] = f"{type(e).__name__}: {e}"
                    report["failed"].append(failed)
                    continue
                _APPLIED.inc()
                report["applied"].append(rec.to_json())
            return report

    def _apply(self, rec: Recommendation) -> None:
        if rec.kind == "create":
            self.hyperspace.create_index(rec.source_plan, rec.index_config)
        elif rec.kind == "drop":
            # Cold index: delete (reversible via restore) then vacuum
            # (physical removal) — the two-step the manual API requires,
            # each its own crash-safe action.
            self.hyperspace.delete_index(rec.index_name)
            self.hyperspace.vacuum_index(rec.index_name)
        elif rec.kind == "optimize":
            self.hyperspace.optimize_index(rec.index_name)
        else:
            raise ValueError(f"unapplicable recommendation kind {rec.kind!r}")
