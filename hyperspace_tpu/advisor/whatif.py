"""What-if analyzer: hypothetical indexes replayed through the REAL rules.

The honest way to answer "would an index on (root, cols) help this
workload?" is to construct a hypothetical :class:`IndexLogEntry` for it
and push the observed plans through the *production* rewrite machinery —
``rules/base.apply_rules`` (JoinIndexRule + FilterIndexRule, including
:class:`~hyperspace_tpu.rules.ranker.JoinIndexRanker`) and the plan
validator — exactly as the optimizer would at query time. A candidate
only survives if the real rules actually rewrite the plan with it and
the rewritten plan validates; the calibrated cost model (cost.py) then
prices the rewrite. No parallel "would it match" reimplementation exists
to drift from the rules.

Recommendation kinds:

- ``create``  — a hot filter/join predicate over a raw scan, uncovered
  by any ACTIVE index, whose replay rewrote and whose estimated benefit
  is positive;
- ``drop``    — an ACTIVE index no observed query touched (paying
  refresh/storage rent for nothing);
- ``rebucket``— two ACTIVE indexes joined by the workload whose bucket
  counts differ, so the ranker can never give the join its zero-exchange
  pair (JoinIndexRanker.score ranks equal counts first);
- ``optimize``— an ACTIVE index fragmented past
  ``hyperspace.advisor.lifecycle.maxDeltas`` delta directories.

Entry point contract: :meth:`WhatIfAnalyzer.recommend` is a declared
error-contract entry (`exceptions.ERROR_CONTRACTS`) and hosts the
``advisor.recommend`` fault point — the injection harness can kill a
recommendation pass at its head and the crash sweeps prove nothing
downstream is left half-applied (recommendation is pure analysis; only
lifecycle.py mutates, behind its own fault point).
"""

from __future__ import annotations

import dataclasses
import tempfile
from collections import defaultdict
from pathlib import Path

from hyperspace_tpu import faults
from hyperspace_tpu import states
from hyperspace_tpu.advisor.cost import CostModel
from hyperspace_tpu.advisor.workload import (
    WorkloadRecord,
    mine_predicate_shapes,
)
from hyperspace_tpu.index.index_config import IndexConfig
from hyperspace_tpu.metadata.log_entry import (
    Content,
    CoveringIndex,
    IndexLogEntry,
    Source,
)
from hyperspace_tpu.obs import metrics as obs_metrics
from hyperspace_tpu.obs import trace as obs_trace
from hyperspace_tpu.plan.nodes import Join, LogicalPlan, Scan
from hyperspace_tpu.plan.prune import prune_columns
from hyperspace_tpu.plan.pushdown import push_down_filters
from hyperspace_tpu.rules.base import apply_rules
from hyperspace_tpu.rules.join_index_rule import _side_required_columns, _side_scan
from hyperspace_tpu.rules.ranker import JoinIndexRanker
from hyperspace_tpu.signature import FileBasedSignatureProvider, collect_leaf_files

_RECOMMENDATIONS = obs_metrics.counter(
    "advisor.recommendations", "recommendations emitted by the what-if analyzer"
)
_REPLAYS = obs_metrics.counter(
    "advisor.replays", "hypothetical-index rule replays executed"
)


@dataclasses.dataclass
class Recommendation:
    """One ranked advisor verdict. `estimated_benefit_s` is the summed
    per-workload-replay saving the cost model predicts; `confidence`
    folds evidence volume (queries matched, calibration samples) into
    [0, 1] so the lifecycle policy can gate on it."""

    kind: str  # create | drop | rebucket | optimize
    estimated_benefit_s: float
    confidence: float
    reason: str
    index_name: str | None = None  # drop/rebucket/optimize target
    index_config: IndexConfig | None = None  # create spec
    source_root: str | None = None
    source_plan: LogicalPlan | None = None  # create lineage (in-memory)
    num_buckets: int | None = None  # rebucket target
    queries_matched: int = 0

    def to_json(self) -> dict:
        return {
            "kind": self.kind,
            "estimated_benefit_s": round(self.estimated_benefit_s, 6),
            "confidence": round(self.confidence, 3),
            "reason": self.reason,
            "index_name": self.index_name,
            "index_config": (
                {
                    "name": self.index_config.index_name,
                    "indexedColumns": list(self.index_config.indexed_columns),
                    "includedColumns": list(self.index_config.included_columns),
                }
                if self.index_config is not None
                else None
            ),
            "source_root": self.source_root,
            "num_buckets": self.num_buckets,
            "queries_matched": self.queries_matched,
        }


def hypothetical_entry(
    scan: Scan, indexed: list[str], included: list[str], num_buckets: int,
    content_root: str, name: str = "__whatif__",
) -> IndexLogEntry | None:
    """A log entry for an index that does not exist: real signature
    (computed live over the scan's files — the rules' match test), real
    schema, but content rooted at an empty scratch dir. The rules can
    match and rewrite with it; nothing can (or does) execute it. Returns
    None when the source cannot be fingerprinted."""
    fp = FileBasedSignatureProvider().signature(scan)
    if fp is None:
        return None
    cols = [scan.scan_schema.field(c).name for c in [*indexed, *included]]
    schema = scan.scan_schema.select(cols)
    vdir = Path(content_root) / "v__=0"
    vdir.mkdir(parents=True, exist_ok=True)
    return IndexLogEntry(
        id=0,
        state=states.ACTIVE,
        name=name,
        derived_dataset=CoveringIndex(
            indexed_columns=[scan.scan_schema.field(c).name for c in indexed],
            included_columns=[scan.scan_schema.field(c).name for c in included],
            schema=schema.to_json(),
            num_buckets=int(num_buckets),
        ),
        content=Content(root=str(content_root), directories=["v__=0"]),
        source=Source(
            plan=scan.to_json(),
            fingerprint=fp,
            files=collect_leaf_files(scan),
        ),
    )


def _validates(optimized: LogicalPlan) -> bool:
    from hyperspace_tpu.analysis.validator import validate_plan

    try:
        return not any(d.severity == "error" for d in validate_plan(optimized))
    except Exception:
        return False


@dataclasses.dataclass(frozen=True)
class _CreateKey:
    root: str
    indexed: tuple[str, ...]  # lowercased
    included: tuple[str, ...]  # lowercased


class WhatIfAnalyzer:
    """Replay-based recommendation engine over a session's workload."""

    def __init__(self, session, cost_model: CostModel | None = None):
        self.session = session
        self._cost = cost_model

    # -- entry point ------------------------------------------------------
    def recommend(self, records: list[WorkloadRecord] | None = None) -> list[Recommendation]:
        """Ranked recommendations for the observed workload (most
        beneficial first). With no `records`, the session's own workload
        log is used. Pure analysis: no index is touched."""
        faults.fault_point("advisor.recommend")
        with obs_trace.span("advisor.recommend"):
            if records is None:
                records = self.session.workload.snapshot()
            cost = self._cost or CostModel.fit(r.profile for r in records)
            existing = self.session.manager.get_indexes()
            recs: list[Recommendation] = []
            recs += self._create_recs(records, existing, cost)
            recs += self._drop_recs(records, existing, cost)
            recs += self._rebucket_recs(records, existing, cost)
            recs += self._optimize_recs(existing, cost)
            recs.sort(key=lambda r: -r.estimated_benefit_s)
            _RECOMMENDATIONS.inc(len(recs))
            obs_trace.annotate(
                recommendations=len(recs), workload_records=len(records)
            )
            return recs

    # -- create -----------------------------------------------------------
    def _create_recs(self, records, existing, cost: CostModel) -> list[Recommendation]:
        """Hot filter shapes over raw scans → replay a hypothetical
        covering index through the real rules; keep candidates that
        rewrote, validated, and priced positive."""
        groups: dict[_CreateKey, dict] = defaultdict(
            lambda: {"records": [], "scan": None, "bytes": 0.0}
        )
        for rec in records:
            optimizable = prune_columns(push_down_filters(rec.plan))
            for shape, scan in mine_predicate_shapes(optimizable):
                key = _CreateKey(
                    shape.root,
                    shape.filter_columns,
                    tuple(c for c in shape.required_columns if c not in shape.filter_columns),
                )
                g = groups[key]
                g["records"].append(rec)
                g["scan"] = scan
                # MAX observed bytes, not the mean: repeat queries served
                # from the decoded-table cache record 0 bytes scanned,
                # but the index exists precisely for the cold case the
                # first run measured (production working sets do not fit
                # the cache).
                g["bytes"] = max(g["bytes"], float(rec.bytes_scanned))
        num_buckets = int(self.session.conf.num_buckets)
        out: list[Recommendation] = []
        for i, (key, g) in enumerate(sorted(groups.items(), key=lambda kv: repr(kv[0]))):
            scan: Scan = g["scan"]
            n = len(g["records"])
            benefit_per_query = cost.indexed_benefit_s(g["bytes"], num_buckets)
            if benefit_per_query <= 0.0:
                continue
            replay_ok = self._replay_filter(
                scan, list(key.indexed), list(key.included),
                num_buckets, [r.plan for r in g["records"]], existing, i,
            )
            if not replay_ok:
                continue
            name = f"adv_{Path(key.root).name}_{'_'.join(key.indexed)}"[:64]
            config = IndexConfig(
                name,
                [scan.scan_schema.field(c).name for c in key.indexed],
                [scan.scan_schema.field(c).name for c in key.included],
            )
            out.append(Recommendation(
                kind="create",
                estimated_benefit_s=benefit_per_query * n,
                confidence=self._confidence(n, cost),
                reason=(
                    f"{n} observed queries filter {key.indexed} on "
                    f"{key.root} with no covering index; replay through "
                    f"the rewrite rules confirms an index would serve them "
                    f"(est. {benefit_per_query * 1e3:.2f}ms/query saved at "
                    f"{num_buckets} buckets)"
                ),
                index_config=config,
                source_root=key.root,
                source_plan=scan,
                num_buckets=num_buckets,
                queries_matched=n,
            ))
        return out

    def _replay_filter(
        self, scan, indexed, included, num_buckets, plans, existing, seq: int
    ) -> bool:
        """True iff the REAL rules rewrite at least one observed plan
        with the hypothetical entry (and not already with an existing
        index) and the rewritten plan validates."""
        _REPLAYS.inc()
        with tempfile.TemporaryDirectory(prefix="hs_whatif_") as td:
            entry = hypothetical_entry(
                scan, indexed, included, num_buckets, td, name=f"__whatif_{seq}__"
            )
            if entry is None:
                return False
            for plan in plans:
                optimizable = prune_columns(push_down_filters(plan))
                # Already served by a real index? Then this shape needs no
                # new one — replay against the EXISTING catalog first.
                already = apply_rules(optimizable, list(existing), conf=self.session.conf)
                if any(s.bucket_spec is not None for s in already.leaves()):
                    continue
                rewritten = apply_rules(
                    optimizable, [*existing, entry], conf=self.session.conf
                )
                hit = any(
                    s.bucket_spec is not None and str(s.root) == str(td)
                    for s in rewritten.leaves()
                )
                if hit and _validates(rewritten):
                    return True
        return False

    # -- drop -------------------------------------------------------------
    def _drop_recs(self, records, existing, cost: CostModel) -> list[Recommendation]:
        """ACTIVE indexes the workload never touched. Needs a non-empty
        workload — with zero observed queries, "unused" is vacuous and
        recommending drops would be destructive guesswork."""
        if not records:
            return []
        used: set[str] = set()
        for rec in records:
            used.update(rec.index_names)
        out: list[Recommendation] = []
        for entry in existing:
            dir_name = Path(entry.content.root).name
            if dir_name in used:
                continue
            src_bytes = float(sum(f.size for f in entry.source.files))
            # Rent the index pays per refresh cycle: rebuilding it scans
            # the source again; storage rides along in the reason only.
            benefit = cost.estimate_scan_s(src_bytes)
            out.append(Recommendation(
                kind="drop",
                estimated_benefit_s=benefit,
                confidence=self._confidence(len(records), cost),
                reason=(
                    f"index {entry.name!r} served none of the "
                    f"{len(records)} observed queries; each refresh "
                    f"re-scans {src_bytes / 1e6:.1f}MB of source for "
                    f"nothing"
                ),
                index_name=entry.name,
                source_root=str(entry.content.root),
                queries_matched=0,
            ))
        return out

    # -- rebucket ---------------------------------------------------------
    def _rebucket_recs(self, records, existing, cost: CostModel) -> list[Recommendation]:
        """Workload-joined index pairs with unequal bucket counts: the
        ranker (JoinIndexRanker.score) can never hand the join its
        zero-exchange pair, so every such query pays a query-time
        re-bucketing exchange. Recommend re-bucketing the smaller index
        to the larger count."""
        by_root: dict[str, list[IndexLogEntry]] = defaultdict(list)
        for entry in existing:
            if entry.derived_dataset.kind != "CoveringIndex":
                continue
            src_root = (entry.source.plan or {}).get("root")
            if src_root:
                by_root[str(src_root)].append(entry)

        def candidate(root: str, keys: set[str]) -> IndexLogEntry | None:
            # A root can carry several indexes (the fact table does);
            # only one bucketed on exactly the join keys is join-usable.
            for e in by_root.get(root, ()):
                if {c.lower() for c in e.indexed_columns} == keys:
                    return e
            return None

        joined: dict[tuple[str, str], int] = defaultdict(int)
        for rec in records:
            for l_scan, r_scan, join in self._joined_scans(rec.plan):
                le = candidate(str(l_scan.root), {c.lower() for c in join.left_on})
                re_ = candidate(str(r_scan.root), {c.lower() for c in join.right_on})
                if le is None or re_ is None:
                    continue
                if le.num_buckets != re_.num_buckets:
                    joined[(le.name, re_.name)] += 1
        out: list[Recommendation] = []
        entries = {e.name: e for e in existing}
        for (lname, rname), n in sorted(joined.items()):
            le, re_ = entries[lname], entries[rname]
            # The ranker itself justifies the verdict: the aligned pair
            # must outrank the current mismatched one.
            target = max(le.num_buckets, re_.num_buckets)
            small = le if le.num_buckets < re_.num_buckets else re_
            aligned_beats = JoinIndexRanker.score((le, le)) < JoinIndexRanker.score((le, re_))
            if not aligned_beats:
                continue
            src_bytes = float(sum(f.size for f in small.source.files))
            # Saving per query: the mismatched side's re-bucketing
            # exchange (hash + regroup of its rows) goes away.
            benefit = n * (cost.per_operator_seconds + 0.25 * cost.estimate_scan_s(src_bytes))
            out.append(Recommendation(
                kind="rebucket",
                estimated_benefit_s=benefit,
                confidence=self._confidence(n, cost),
                reason=(
                    f"{n} observed joins pair {lname!r} ({le.num_buckets} "
                    f"buckets) with {rname!r} ({re_.num_buckets}); the "
                    f"ranker prefers equal counts (zero-exchange) — "
                    f"re-bucket {small.name!r} to {target}"
                ),
                index_name=small.name,
                num_buckets=target,
                queries_matched=n,
            ))
        return out

    @staticmethod
    def _joined_scans(plan: LogicalPlan):
        """(left raw-or-index source scan, right ditto, join) triples."""
        out = []

        def walk(p):
            if isinstance(p, Join):
                ls = _side_scan(p.left) or next(
                    (s for s in p.left.leaves()), None
                )
                rs = _side_scan(p.right) or next(
                    (s for s in p.right.leaves()), None
                )
                if isinstance(ls, Scan) and isinstance(rs, Scan):
                    out.append((ls, rs, p))
            for c in p.children():
                walk(c)

        walk(plan)
        return out

    # -- optimize ---------------------------------------------------------
    def _optimize_recs(self, existing, cost: CostModel) -> list[Recommendation]:
        """Fragmented indexes: incremental refresh appends delta dirs;
        past the policy threshold every query unions that many extra
        bucket-file sets."""
        max_deltas = int(self.session.conf.advisor_lifecycle_max_deltas)
        out: list[Recommendation] = []
        for entry in existing:
            n_dirs = len(entry.content.directories)
            if n_dirs <= max_deltas:
                continue
            src_bytes = float(sum(f.size for f in entry.source.files))
            benefit = (n_dirs - 1) * cost.per_operator_seconds + 0.1 * cost.estimate_scan_s(src_bytes)
            out.append(Recommendation(
                kind="optimize",
                estimated_benefit_s=benefit,
                confidence=1.0,  # fragmentation is directly observed, not inferred
                reason=(
                    f"index {entry.name!r} spans {n_dirs} version dirs "
                    f"(> maxDeltas={max_deltas}); compaction merges the "
                    f"delta buckets back into one set of files"
                ),
                index_name=entry.name,
                queries_matched=0,
            ))
        return out

    # -- shared -----------------------------------------------------------
    @staticmethod
    def _confidence(n_queries: int, cost: CostModel) -> float:
        """Evidence volume → [0, 1]: half from how many observed queries
        back the verdict (saturating at 8), half from how calibrated the
        cost model is (saturating at 4 contributing profiles)."""
        q = min(1.0, n_queries / 8.0)
        c = min(1.0, cost.samples / 4.0)
        return round(0.5 * q + 0.5 * c, 3)
