"""Adaptive query routing: measured-outcome demotion of index rewrites.

The round-5 verdict's product defect: the rewrite rules fire on every
eligible plan, but 18 of 91 TPC-DS slice queries measured BELOW 1x
indexed (down to 0.33x) — the rewrite is a bet, and for some plans the
bet loses. This ledger makes the bet empirical: per plan signature it
keeps EMA-smoothed wall times of the *indexed* and *raw* paths as
actually measured by ``session.run_query``, and once both sides have
evidence it **demotes** a signature whose indexed path measured slower —
the query thereafter plans straight against the source, structurally
eliminating the sub-1x tail while ≥1x queries keep their indexed plans.

Invalidation is versioned like the serve caches (serve/plan_cache.py):
the ledger state is stamped with the index-collection log versions; any
committed index mutation (create/refresh/optimize/delete/restore/vacuum)
bumps a log id, the stamp mismatches, and ALL entries drop — a demotion
earned against the old index generation never outlives it (re-promotion
on mutation is structural, not event-driven).

Persistence: ``<system_path>/_advisor/routing.json`` through the atomic,
retried ``file_utils.write_json`` — but the ledger is ADVISORY by
contract: a persistence failure is counted
(``advisor.routing.persist_failed``) and never fails a query.

Knobs (docs/advisor.md): ``hyperspace.advisor.routing.enabled`` (off by
default — routing changes plans, so it is an explicit opt-in),
``.demoteRatio`` (demote when indexed EMA > ratio x raw EMA),
``.alpha`` (EMA smoothing), ``.minSamples`` (evidence floor per side).
"""

from __future__ import annotations

import hashlib
import threading
from pathlib import Path

from hyperspace_tpu.obs import metrics as obs_metrics

ADVISOR_DIR = "_advisor"
LEDGER_FILE = "routing.json"

_RECORDS = obs_metrics.counter(
    "advisor.routing.records", "outcome samples recorded into the routing ledger"
)
_DEMOTIONS = obs_metrics.counter(
    "advisor.routing.demotions", "queries routed to source scan by the ledger"
)
_PERSIST_FAILED = obs_metrics.counter(
    "advisor.routing.persist_failed", "advisory ledger writes that failed"
)
_INVALIDATIONS = obs_metrics.counter(
    "advisor.routing.invalidations", "ledger wipes on index-collection mutation"
)


def collection_stamp(session) -> str:
    """Version stamp of the whole index collection — the MD5 fold of
    (index dir, latest log id) pairs the serve caches also key on. Any
    committed index mutation changes it."""
    from hyperspace_tpu.serve.plan_cache import collection_log_versions

    payload = repr(collection_log_versions(session)).encode()
    return hashlib.md5(payload).hexdigest()


def snapshot_stamp(snapshot) -> str:
    """Version stamp of a pinned snapshot's admitted world — the same
    MD5 fold over the snapshot's own (index dir, log id) pin tuple, so
    a pinned query keys the ledger on ITS read point instead of the
    live version vector: a concurrent commit must not wipe routing
    evidence a pinned reader cannot even see (snapshot-stamp
    discipline, HSL030)."""
    payload = repr(snapshot.stamp).encode()
    return hashlib.md5(payload).hexdigest()


class RoutingLedger:
    """Per-plan-signature outcome ledger with versioned invalidation.

    Persistence is debounced: a record() persists immediately when it
    CHANGES the signature's routing verdict (a demotion earned must
    survive the process), else every PERSIST_EVERY samples — an atomic
    fsync'd write per query would tax exactly the hot path routing
    exists to speed up. `flush()` forces the write (bench/shutdown)."""

    PERSIST_EVERY = 32

    def __init__(self, session):
        self._session = session
        self._lock = threading.Lock()
        # signature -> {"indexed": [ema, n], "raw": [ema, n]}
        self._entries: dict[str, dict] = {}
        self._stamp: str | None = None
        self._loaded = False
        self._unpersisted = 0

    @property
    def path(self) -> Path:
        return Path(self._session.conf.system_path) / ADVISOR_DIR / LEDGER_FILE

    # -- state ------------------------------------------------------------
    def _load_locked(self) -> None:
        """Lazy one-time load of the persisted ledger (under self._lock)."""
        if self._loaded:
            return
        self._loaded = True
        from hyperspace_tpu.utils import file_utils

        try:
            doc = file_utils.read_json(self.path)
            self._stamp = doc.get("stamp")
            self._entries = dict(doc.get("entries", {}))
        except (OSError, ValueError):
            # No ledger yet (first run) or an unreadable one — start
            # empty; the ledger re-earns its evidence.
            self._stamp = None
            self._entries = {}

    def _sync_stamp_locked(self, stamp: str) -> None:
        """Drop every entry when the index collection mutated since the
        ledger last recorded (structural re-promotion)."""
        if self._stamp != stamp:
            if self._stamp is not None and self._entries:
                _INVALIDATIONS.inc()
            self._entries = {}
            self._stamp = stamp

    # -- API ---------------------------------------------------------------
    def decide(self, signature: str, stamp: str | None = None) -> str:
        """Route `signature`: ``"indexed"`` (default — the rewrite keeps
        the benefit of the doubt) or ``"raw"`` once BOTH paths have
        enough samples and the indexed EMA measured slower than
        demoteRatio x the raw EMA. An operator/controller `pin`
        overrides the measured verdict outright."""
        conf = self._session.conf
        stamp = collection_stamp(self._session) if stamp is None else stamp
        with self._lock:
            self._load_locked()
            self._sync_stamp_locked(stamp)
            entry = self._entries.get(signature)
            if entry is not None and entry.get("pinned") in ("indexed", "raw"):
                if entry["pinned"] == "raw":
                    _DEMOTIONS.inc()
                return entry["pinned"]
            if entry is not None and self._demoted_locked(entry, conf):
                _DEMOTIONS.inc()
                return "raw"
            return "indexed"

    def pin(self, signature: str, mode: str = "raw",
            stamp: str | None = None) -> None:
        """Pin `signature` to a route unconditionally (the OpsController's
        recompile-storm response pins to ``"raw"`` so the signature stops
        feeding the jit cache). Pins ride the same versioned stamp as the
        measured evidence: any committed index mutation wipes them —
        structural re-promotion, exactly like demotions. Persisted
        immediately (a pin must survive the process)."""
        if mode not in ("indexed", "raw"):
            raise ValueError(f"unknown routing mode {mode!r} (indexed|raw)")
        stamp = collection_stamp(self._session) if stamp is None else stamp
        with self._lock:
            self._load_locked()
            self._sync_stamp_locked(stamp)
            self._entries.setdefault(signature, {})["pinned"] = mode
            self._unpersisted = 0
            doc = self._doc_locked()
        self._persist(doc)

    def record(self, signature: str, mode: str, wall_s: float,
               stamp: str | None = None) -> None:
        """Fold one measured outcome (`mode` is ``"indexed"``/``"raw"``)
        into the EMA for `signature` and persist. Advisory: persistence
        failures are counted, never raised."""
        if mode not in ("indexed", "raw"):
            raise ValueError(f"unknown routing mode {mode!r} (indexed|raw)")
        conf = self._session.conf
        alpha = float(conf.advisor_routing_alpha)
        stamp = collection_stamp(self._session) if stamp is None else stamp
        with self._lock:
            self._load_locked()
            self._sync_stamp_locked(stamp)
            entry = self._entries.setdefault(signature, {})
            verdict_before = self._demoted_locked(entry, conf)
            cell = entry.get(mode)
            if cell is None:
                entry[mode] = [float(wall_s), 1]
            else:
                cell[0] = alpha * float(wall_s) + (1.0 - alpha) * cell[0]
                cell[1] = int(cell[1]) + 1
            self._unpersisted += 1
            verdict_changed = self._demoted_locked(entry, conf) != verdict_before
            if not verdict_changed and self._unpersisted < self.PERSIST_EVERY:
                doc = None
            else:
                self._unpersisted = 0
                doc = self._doc_locked()
        _RECORDS.inc()
        if doc is not None:
            self._persist(doc)

    def _doc_locked(self) -> dict:
        """Deep copy of the state (under self._lock): the persist write
        runs outside the lock, and a peer thread's record() must not
        mutate what json is serializing."""
        return {
            "stamp": self._stamp,
            "entries": {
                k: {m: (list(c) if isinstance(c, list) else c) for m, c in v.items()}
                for k, v in self._entries.items()
            },
        }

    @staticmethod
    def _demoted_locked(entry: dict, conf) -> bool:
        idx, raw = entry.get("indexed"), entry.get("raw")
        n_min = max(int(conf.advisor_routing_min_samples), 1)
        if not idx or not raw or idx[1] < n_min or raw[1] < n_min:
            return False
        return idx[0] > float(conf.advisor_routing_demote_ratio) * raw[0]

    def flush(self) -> None:
        """Force-persist the in-memory state (advisory like every other
        ledger write)."""
        with self._lock:
            self._load_locked()
            self._unpersisted = 0
            doc = self._doc_locked()
        self._persist(doc)

    def _persist(self, doc: dict) -> None:
        from hyperspace_tpu.obs import trace as obs_trace
        from hyperspace_tpu.utils import file_utils

        try:
            file_utils.write_json(self.path, doc)
        except Exception as e:
            # Advisory by contract: the ledger influences plan CHOICE,
            # never correctness — a failed write only delays learning.
            _PERSIST_FAILED.inc()
            obs_trace.event("advisor.routing.persist_failed", error=str(e))

    def snapshot(self) -> dict:
        """Copy of the ledger state (tests / bench artifact)."""
        with self._lock:
            self._load_locked()
            return {
                "stamp": self._stamp,
                "entries": {k: dict(v) for k, v in self._entries.items()},
            }

    def demoted_signatures(self) -> list[str]:
        """Signatures decide() would currently route raw — measured
        demotions plus raw pins (report/bench evidence; does not bump
        the demotion counter)."""
        conf = self._session.conf
        out = []
        with self._lock:
            self._load_locked()
            for sig, entry in self._entries.items():
                if entry.get("pinned") == "raw" or (
                    entry.get("pinned") != "indexed"
                    and self._demoted_locked(entry, conf)
                ):
                    out.append(sig)
        return sorted(out)
