"""Workload capture: the per-query evidence the advisor learns from.

Every ``session.run_query`` appends one compact :class:`WorkloadRecord`
to the session's bounded :class:`WorkloadLog` — the logical plan (an
in-memory reference, not a copy), its structural signature, the measured
wall/bytes, and which indexes served it. The log is the advisor's input:
the what-if analyzer replays its plans through the rewrite rules against
hypothetical indexes (whatif.py), the cost model calibrates from its
profiles (cost.py), and the drop detector looks for indexes it never
names (an index no observed query touched is paying refresh/storage
rent for nothing).

Recording costs one dataclass + deque append per query and is bounded by
``hyperspace.advisor.workload.maxRecords`` — old traffic ages out, so a
workload shift re-trains the advisor automatically.
"""

from __future__ import annotations

import dataclasses
import threading
from collections import deque
from pathlib import Path

from hyperspace_tpu.plan.nodes import Filter, LogicalPlan, Project, Scan


@dataclasses.dataclass
class WorkloadRecord:
    """One observed query: identity, measured cost, index usage."""

    signature: str  # plan_signature of the LOGICAL plan (pre-optimize)
    plan: LogicalPlan  # in-memory reference (the advisor replays it)
    total_s: float
    bytes_scanned: int
    used_indexes: bool  # post-fallback/routing truth
    index_names: tuple[str, ...]  # index dirs that served this query
    profile: object = None  # QueryProfile (cost-model calibration input)
    routed: str | None = None  # advisor routing decision, None = routing off

    def to_json(self) -> dict:
        return {
            "signature": self.signature,
            "total_s": self.total_s,
            "bytes_scanned": self.bytes_scanned,
            "used_indexes": self.used_indexes,
            "index_names": list(self.index_names),
            "routed": self.routed,
        }


class WorkloadLog:
    """Bounded, thread-safe ring of recent :class:`WorkloadRecord`\\ s."""

    def __init__(self, max_records: int = 512):
        self._lock = threading.Lock()
        self._records: deque[WorkloadRecord] = deque(maxlen=int(max_records))

    def record(self, rec: WorkloadRecord) -> None:
        with self._lock:
            self._records.append(rec)

    def snapshot(self) -> list[WorkloadRecord]:
        with self._lock:
            return list(self._records)

    def clear(self) -> None:
        with self._lock:
            self._records.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)


def used_index_names(optimized_plan: LogicalPlan) -> tuple[str, ...]:
    """Index directory names the optimized plan reads (bucketed scans'
    roots are index dirs; their basename is the index name). Pure plan
    walk — no catalog round-trip on the query hot path."""
    names = []
    for leaf in optimized_plan.leaves():
        if leaf.bucket_spec is not None:
            names.append(Path(leaf.root).name)
    return tuple(sorted(set(names)))


@dataclasses.dataclass(frozen=True)
class PredicateShape:
    """One mined rewrite opportunity: a filter over a raw source scan."""

    root: str  # source dataset root
    fmt: str
    filter_columns: tuple[str, ...]  # lowercased, sorted — candidate keys
    required_columns: tuple[str, ...]  # lowercased — coverage set


def mine_predicate_shapes(plan: LogicalPlan) -> list[tuple[PredicateShape, Scan]]:
    """Filter-over-raw-scan shapes in `plan` — exactly the shapes
    FilterIndexRule rewrites (Project(Filter(Scan)) / Filter(Scan) /
    Filter(Project(Scan))), mined from the un-rewritten logical plan so
    the advisor sees what COULD be indexed, not what already is."""
    out: list[tuple[PredicateShape, Scan]] = []

    def shape(scan: Scan, predicate, output_cols) -> None:
        if scan.bucket_spec is not None:
            return  # already an index scan
        fcols = tuple(sorted(predicate.references()))
        req = tuple(sorted(fcols + tuple(c.lower() for c in output_cols)))
        if fcols:
            out.append((PredicateShape(scan.root, scan.format, fcols, req), scan))

    def walk(p: LogicalPlan) -> None:
        if isinstance(p, Project) and isinstance(p.child, Filter) and isinstance(p.child.child, Scan):
            shape(p.child.child, p.child.predicate, p.input_columns())
            return  # the inner Filter(Scan) is THIS shape, not a second one
        if isinstance(p, Filter) and isinstance(p.child, Scan):
            shape(p.child, p.predicate, p.child.scan_schema.names)
            return
        if (
            isinstance(p, Filter)
            and isinstance(p.child, Project)
            and p.child.is_simple
            and isinstance(p.child.child, Scan)
        ):
            shape(p.child.child, p.predicate, p.child.input_columns())
            return
        for c in p.children():
            walk(c)

    walk(plan)
    return out
