"""Calibrated cost model: measured profiles in, what-if estimates out.

The model is deliberately small and *fitted from this session's own
measurements* rather than hand-tuned constants: every completed
:class:`~hyperspace_tpu.obs.profile.QueryProfile` carries per-operator
measured wall time and bytes scanned (docs/observability.md), and the
advisor feeds those samples here. Calibration derives

- ``scan_seconds_per_byte`` — median wall/bytes over scan operators that
  actually decoded data (the IO+decode throughput of THIS machine);
- ``per_operator_seconds`` — median self-time of non-scan operators (the
  fixed per-operator overhead a rewrite cannot remove);
- ``plan_overhead_s`` — median gap between end-to-end wall and operator
  self-time (optimizer + marshalling, the cost an indexed plan pays on
  top of its operators).

Estimates are **monotonic in bytes by construction** (a * bytes + b with
a, b >= 0) — tests pin this, because a non-monotonic cost model can
"justify" any recommendation.
"""

from __future__ import annotations

import dataclasses
import statistics


_SCAN_OPS = ("TableScan", "IndexScan", "Scan")


@dataclasses.dataclass
class CostModel:
    """Fitted throughput/overhead constants (seconds, bytes)."""

    scan_seconds_per_byte: float = 5e-10  # ~2 GB/s decode: pre-fit default
    per_operator_seconds: float = 1e-4
    plan_overhead_s: float = 1e-3
    samples: int = 0

    @staticmethod
    def fit(profiles) -> "CostModel":
        """Calibrate from measured QueryProfiles; falls back to the
        defaults above until enough evidence exists (samples counts the
        profiles that contributed at least one operator sample)."""
        scan_rates: list[float] = []
        op_selfs: list[float] = []
        overheads: list[float] = []
        used = 0
        for prof in profiles:
            if prof is None or getattr(prof, "root", None) is None:
                continue
            contributed = False
            for op in prof.operators():
                b = op.detail.get("bytes")
                if op.op.startswith(_SCAN_OPS) and b:
                    scan_rates.append(op.self_s() / float(b))
                    contributed = True
                elif not op.op.startswith(_SCAN_OPS):
                    op_selfs.append(op.self_s())
                    contributed = True
            overheads.append(max(0.0, prof.total_s - prof.operator_total_s()))
            if contributed:
                used += 1
        model = CostModel(samples=used)
        if scan_rates:
            model.scan_seconds_per_byte = max(statistics.median(scan_rates), 1e-12)
        if op_selfs:
            model.per_operator_seconds = max(statistics.median(op_selfs), 0.0)
        if overheads:
            model.plan_overhead_s = max(statistics.median(overheads), 0.0)
        return model

    # -- estimates --------------------------------------------------------
    def estimate_scan_s(self, nbytes: float) -> float:
        """Wall seconds to scan+decode `nbytes` (linear, monotonic)."""
        return self.scan_seconds_per_byte * max(float(nbytes), 0.0)

    def estimate_query_s(self, nbytes: float, n_operators: int = 1) -> float:
        """End-to-end estimate for a plan scanning `nbytes` through
        `n_operators` operators."""
        return (
            self.estimate_scan_s(nbytes)
            + self.per_operator_seconds * max(int(n_operators), 0)
            + self.plan_overhead_s
        )

    def indexed_benefit_s(
        self, raw_bytes: float, num_buckets: int, n_operators: int = 1
    ) -> float:
        """Estimated per-query saving of a bucketed covering index over a
        raw scan for a point/selective predicate on the first indexed
        column: bucket pruning reads ~1/num_buckets of the data (the
        executor prunes whole bucket files on point predicates), while
        the indexed plan pays one extra plan overhead for the rewrite.
        Never negative-from-noise: callers treat <= 0 as "no benefit"."""
        raw = self.estimate_query_s(raw_bytes, n_operators)
        pruned = max(float(raw_bytes), 0.0) / max(int(num_buckets), 1)
        indexed = self.estimate_query_s(pruned, n_operators) + self.plan_overhead_s
        return raw - indexed

    def to_json(self) -> dict:
        return {
            "scan_seconds_per_byte": self.scan_seconds_per_byte,
            "per_operator_seconds": self.per_operator_seconds,
            "plan_overhead_s": self.plan_overhead_s,
            "samples": self.samples,
        }
