"""User-facing index specification.

Reference parity: index/IndexConfig.scala:28-166 — name + indexed columns +
included columns, case-insensitive equality and duplicate checks
(IndexConfig.scala:40-53), plus a fluent Builder (IndexConfig.scala:88-158).
"""

from __future__ import annotations

import dataclasses

from hyperspace_tpu.exceptions import HyperspaceError


@dataclasses.dataclass(frozen=True)
class IndexConfig:
    index_name: str
    indexed_columns: tuple[str, ...]
    included_columns: tuple[str, ...] = ()

    def __init__(self, index_name: str, indexed_columns, included_columns=()):
        object.__setattr__(self, "index_name", index_name)
        object.__setattr__(self, "indexed_columns", tuple(indexed_columns))
        object.__setattr__(self, "included_columns", tuple(included_columns))
        self._validate()

    def _validate(self) -> None:
        if not self.index_name.strip():
            raise HyperspaceError("index name cannot be empty")
        if self.index_name.strip().startswith("_"):
            # Underscore-prefixed directories under the system path are
            # metadata-plane state (the advisor ledger dir), invisible to
            # the catalog listing — an index named that way could never
            # be found again.
            raise HyperspaceError(
                f"index name {self.index_name!r} cannot start with '_' "
                "(reserved for metadata directories)"
            )
        if not self.indexed_columns:
            raise HyperspaceError("indexed columns cannot be empty")
        low_indexed = [c.lower() for c in self.indexed_columns]
        low_included = [c.lower() for c in self.included_columns]
        if len(set(low_indexed)) != len(low_indexed):
            raise HyperspaceError("duplicate indexed columns")
        if len(set(low_included)) != len(low_included):
            raise HyperspaceError("duplicate included columns")
        if set(low_indexed) & set(low_included):
            raise HyperspaceError("indexed and included columns overlap")

    @property
    def all_columns(self) -> list[str]:
        return list(self.indexed_columns) + list(self.included_columns)

    def __eq__(self, other) -> bool:
        """Case-insensitive equality (IndexConfig.scala:40-53)."""
        if not isinstance(other, IndexConfig):
            return NotImplemented
        return (
            self.index_name.lower() == other.index_name.lower()
            and [c.lower() for c in self.indexed_columns] == [c.lower() for c in other.indexed_columns]
            and sorted(c.lower() for c in self.included_columns)
            == sorted(c.lower() for c in other.included_columns)
        )

    def __hash__(self):
        return hash(
            (
                self.index_name.lower(),
                tuple(c.lower() for c in self.indexed_columns),
                tuple(sorted(c.lower() for c in self.included_columns)),
            )
        )

    class Builder:
        """Fluent builder (IndexConfig.scala:88-158)."""

        def __init__(self):
            self._name: str | None = None
            self._indexed: list[str] = []
            self._included: list[str] = []

        def index_name(self, name: str) -> "IndexConfig.Builder":
            if self._name is not None:
                raise HyperspaceError("index name is already set")
            if not name.strip():
                raise HyperspaceError("index name cannot be empty")
            self._name = name
            return self

        def indexed_columns(self, *cols: str) -> "IndexConfig.Builder":
            if self._indexed:
                raise HyperspaceError("indexed columns are already set")
            if not cols:
                raise HyperspaceError("indexed columns cannot be empty")
            self._indexed = list(cols)
            return self

        def included_columns(self, *cols: str) -> "IndexConfig.Builder":
            if self._included:
                raise HyperspaceError("included columns are already set")
            self._included = list(cols)
            return self

        def create(self) -> "IndexConfig":
            if self._name is None or not self._indexed:
                raise HyperspaceError("both index name and indexed columns are required")
            return IndexConfig(self._name, self._indexed, self._included)

    @staticmethod
    def builder() -> "IndexConfig.Builder":
        return IndexConfig.Builder()
