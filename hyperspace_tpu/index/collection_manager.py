"""Index collection management: wiring actions to per-index managers.

Reference parity: index/IndexManager.scala:24-81 (the 7-method interface),
index/IndexCollectionManager.scala:26-137 (wiring + getIndexes enumerating
every index dir under the system path), and
index/CachingIndexCollectionManager.scala:37-160 (read-path TTL cache,
cleared by every mutating API).
"""

from __future__ import annotations

from pathlib import Path

from hyperspace_tpu.actions import (
    CancelAction,
    CreateAction,
    DeleteAction,
    OptimizeAction,
    RefreshAction,
    RefreshIncrementalAction,
    RestoreAction,
    VacuumAction,
)
from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.index.index_config import IndexConfig
from hyperspace_tpu.metadata.cache import CreationTimeBasedCache
from hyperspace_tpu.metadata.data_manager import IndexDataManager
from hyperspace_tpu.metadata.log_entry import IndexLogEntry
from hyperspace_tpu.metadata.log_manager import IndexLogManager
from hyperspace_tpu.metadata.path_resolver import PathResolver
from hyperspace_tpu.plan.nodes import LogicalPlan
from hyperspace_tpu import states


class IndexCollectionManager:
    """Concrete manager: one log/data manager pair per index directory."""

    def __init__(
        self,
        conf: HyperspaceConf,
        writer_factory=None,
        log_manager_factory=None,
        data_manager_factory=None,
    ):
        self.conf = conf
        self.path_resolver = PathResolver(conf)
        # The DI seams (analog of index/factories.scala:22-52): the writer
        # builds index data; the log/data manager factories let tests
        # inject protocol mocks/fakes per index path.
        if writer_factory is None:
            def writer_factory():
                from hyperspace_tpu.execution.builder import DeviceIndexBuilder

                return DeviceIndexBuilder()

        self.writer_factory = writer_factory
        self.log_manager_factory = log_manager_factory or IndexLogManager
        self.data_manager_factory = data_manager_factory or IndexDataManager

    # -- manager wiring --------------------------------------------------
    def _managers(self, name: str) -> tuple[IndexLogManager, IndexDataManager, Path]:
        index_path = self.path_resolver.get_index_path(name)
        return (
            self.log_manager_factory(index_path),
            self.data_manager_factory(index_path),
            index_path,
        )

    # -- IndexManager interface ------------------------------------------
    def create(self, plan: LogicalPlan, config: IndexConfig) -> None:
        lm, dm, path = self._managers(config.index_name)
        CreateAction(plan, config, lm, dm, path, self.conf, self.writer_factory()).run()

    def create_vector(self, plan: LogicalPlan, config) -> None:
        from hyperspace_tpu.vector.index import VectorCreateAction

        lm, dm, path = self._managers(config.index_name)
        VectorCreateAction(plan, config, lm, dm, path, self.conf).run()

    def delete(self, name: str) -> None:
        lm, _, _ = self._managers(name)
        DeleteAction(lm).run()

    def restore(self, name: str) -> None:
        lm, _, _ = self._managers(name)
        RestoreAction(lm).run()

    def vacuum(self, name: str) -> None:
        lm, dm, _ = self._managers(name)
        VacuumAction(lm, dm).run()

    def refresh(self, name: str, mode: str = "full") -> None:
        lm, dm, path = self._managers(name)
        if mode not in ("full", "incremental"):
            raise HyperspaceError(f"unknown refresh mode {mode!r} (full|incremental)")
        if self._is_vector(lm):
            from hyperspace_tpu.vector.lifecycle import (
                VectorRefreshAction,
                VectorRefreshIncrementalAction,
            )

            action = VectorRefreshAction if mode == "full" else VectorRefreshIncrementalAction
            action(lm, dm, path, self.conf).run()
        elif mode == "full":
            RefreshAction(lm, dm, path, self.conf, self.writer_factory()).run()
        else:
            RefreshIncrementalAction(lm, dm, path, self.conf, self.writer_factory()).run()

    def optimize(self, name: str) -> None:
        lm, dm, _ = self._managers(name)
        if self._is_vector(lm):
            from hyperspace_tpu.vector.lifecycle import VectorOptimizeAction

            VectorOptimizeAction(lm, dm).run()
        else:
            OptimizeAction(lm, dm, self.writer_factory()).run()

    @staticmethod
    def _is_vector(lm) -> bool:
        entry = lm.get_latest_log()
        return (
            entry is not None
            and entry.derived_dataset is not None
            and entry.derived_dataset.kind == "VectorIndex"
        )

    def cancel(self, name: str) -> None:
        lm, _, _ = self._managers(name)
        if lm.get_latest_log() is None:
            raise HyperspaceError(f"index {name!r} does not exist")
        CancelAction(lm).run()

    # -- crash recovery ---------------------------------------------------
    def recover(self, name: str) -> dict:
        """Repair one index after a crashed writer (docs/fault_tolerance.md).

        Idempotent three-step state machine:

        1. **Torn tail**: trailing log entries that no longer parse (a
           writer died mid-write on a non-atomic filesystem, or injected
           truncation) are quarantined until the tail is readable.
        2. **Transient tail**: a latest entry in a transient state is
           rolled forward/back to the last stable state with the exact
           `cancel` semantics (cancel.py: VACUUMING → DOESNOTEXIST,
           otherwise the last stable state), and the `latestStable`
           pointer is refreshed — also repairing an `end()` that died
           between the final CAS write and the pointer swap.
        3. **Orphan GC**: `v__=N` dirs the latest stable entry does not
           reference (partial builds, superseded failed refreshes) are
           deleted. A DELETED entry still references its dirs (restore
           needs them); DOESNOTEXIST references none, so a crashed
           vacuum's remaining dirs are swept here.
        """
        from hyperspace_tpu import stats
        from hyperspace_tpu.config import DATA_VERSION_PREFIX

        lm, dm, _ = self._managers(name)
        report = {"rolled": False, "quarantined_entries": 0, "orphans_removed": 0}
        latest = None
        while True:
            latest_id = lm.get_latest_id()
            if latest_id is None:
                break
            try:
                latest = lm.get_log(latest_id)
                break
            except Exception:
                if not lm.quarantine_log(latest_id):
                    break
                report["quarantined_entries"] += 1
                stats.increment("recover.quarantined_entries")
        if latest is None:
            return report
        if latest.state not in states.STABLE_STATES:
            CancelAction(lm).run()
            report["rolled"] = True
            stats.increment("recover.rolled")
        # Refresh the pointer unconditionally: cheap, and repairs a crash
        # between end()'s final write and its pointer swap.
        lm.create_latest_stable_log(lm.get_latest_id())
        stable = lm.get_latest_stable_log()
        referenced: set[str] = set()
        if (
            stable is not None
            and stable.state != states.DOESNOTEXIST
            and stable.content is not None
        ):
            referenced = set(stable.content.directories)
        for vid in dm.get_version_ids():
            if f"{DATA_VERSION_PREFIX}{vid}" not in referenced:
                dm.delete(vid)
                report["orphans_removed"] += 1
                stats.increment("recover.orphans_removed")
        return report

    def _latest_for_listing(self, lm, dir_path: Path) -> IndexLogEntry | None:
        """One index dir's latest entry, lazily repairing crash damage.

        With `hyperspace.recover.onAccess` (default on), a torn latest
        entry recovers immediately, and a TRANSIENT latest entry recovers
        once it is older than `hyperspace.recover.graceSeconds` — the
        grace keeps a listing from cancelling a live writer's in-flight
        action, while a long-dead writer's index heals on first access
        instead of staying unusable until a manual cancel. Safe against
        the race anyway: recovery commits through the same CAS protocol,
        so a live writer that loses simply aborts."""
        import time

        try:
            entry = lm.get_latest_log()
        except Exception:
            entry = None
            if not self.conf.recover_on_access:
                raise
        if not self.conf.recover_on_access:
            return entry
        stale = (
            entry is not None
            and entry.state not in states.STABLE_STATES
            # Wall clock on purpose: entry.timestamp is a PERSISTED stamp
            # from a possibly-different process/boot — monotonic() cannot
            # compare across those.
            and time.time() - (entry.timestamp or 0) > self.conf.recover_grace_seconds  # noqa: HSL007
        )
        if entry is None or stale:
            try:
                self.recover(dir_path.name)
                entry = lm.get_latest_log()
            except Exception:
                # Lazy repair is best-effort by design (the listing must
                # not fail because one index is broken) — but count it:
                # a silent failure here would hide a dead index forever.
                from hyperspace_tpu import stats

                stats.increment("recover.on_access_failed")
        return entry

    def get_indexes(self, states_filter=(states.ACTIVE,)) -> list[IndexLogEntry]:
        """Enumerate every index dir under the system path and read each
        latest log (IndexCollectionManager.scala:87-105)."""
        out = []
        for d in self.path_resolver.list_index_paths():
            entry = self._latest_for_listing(self.log_manager_factory(d), d)
            if entry is not None and entry.state in states_filter:
                out.append(entry)
        return out

    def indexes(self):
        """Project all indexes to a summary DataFrame
        (IndexCollectionManager.scala:79-85, IndexSummary :151-173)."""
        import pandas as pd

        rows = []
        for entry in self.get_indexes(states_filter=tuple(states.ALL_STATES)):
            dd = entry.derived_dataset
            indexed = (
                list(dd.indexed_columns)
                if dd.kind == "CoveringIndex"
                else [dd.embedding_column]
            )
            rows.append(
                {
                    "name": entry.name,
                    "kind": dd.kind,
                    "indexedColumns": indexed,
                    "includedColumns": list(dd.included_columns),
                    "numBuckets": dd.num_buckets,
                    "schema": [f["name"] for f in dd.schema],
                    "indexLocation": str(Path(entry.content.root) / entry.content.directories[-1]),
                    "state": entry.state,
                }
            )
        return pd.DataFrame(rows, columns=[
            "name", "kind", "indexedColumns", "includedColumns", "numBuckets", "schema", "indexLocation", "state",
        ])


class CachingIndexCollectionManager(IndexCollectionManager):
    """Read-path cache of the ACTIVE index entries with TTL expiry;
    every mutating API clears the cache first
    (CachingIndexCollectionManager.scala:60-98)."""

    def __init__(self, conf: HyperspaceConf, writer_factory=None, **factories):
        super().__init__(conf, writer_factory, **factories)
        self._cache = CreationTimeBasedCache(conf.cache_expiry_seconds)

    def clear_cache(self) -> None:
        self._cache.clear()
        # Index mutations version the data files: uploads and derived
        # arrays of the superseded version can never hit again — drop
        # them instead of letting the dead working set pin HBM/host
        # memory until LRU pressure (round-3 advisor).
        from hyperspace_tpu.execution import device_cache

        device_cache.clear_all()

    def get_indexes(self, states_filter=(states.ACTIVE,)) -> list[IndexLogEntry]:
        if tuple(states_filter) == (states.ACTIVE,):
            cached = self._cache.get()
            if cached is not None:
                return cached
            entries = super().get_indexes(states_filter)
            self._cache.set(entries)
            return entries
        return super().get_indexes(states_filter)

    def create(self, plan, config):
        self.clear_cache()
        super().create(plan, config)

    def create_vector(self, plan, config):
        self.clear_cache()
        super().create_vector(plan, config)

    def delete(self, name):
        self.clear_cache()
        super().delete(name)

    def restore(self, name):
        self.clear_cache()
        super().restore(name)

    def vacuum(self, name):
        self.clear_cache()
        super().vacuum(name)

    def refresh(self, name, mode: str = "full"):
        self.clear_cache()
        super().refresh(name, mode)

    def optimize(self, name):
        self.clear_cache()
        super().optimize(name)

    def cancel(self, name):
        self.clear_cache()
        super().cancel(name)

    def recover(self, name):
        self.clear_cache()
        return super().recover(name)
