from hyperspace_tpu.index.index_config import IndexConfig

__all__ = ["IndexConfig"]
