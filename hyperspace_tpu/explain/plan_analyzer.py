"""Plan-diff explain: the observability story.

Reference parity: index/plananalysis/PlanAnalyzer.scala:34-410 — compile the
query twice (rules off / rules on), diff the plans highlighting replaced
subtrees, list the indexes actually used (matching scan roots against the
catalog), and in verbose mode report the per-operator occurrence diff —
whose headline number in the reference is removed ShuffleExchanges
(PhysicalOperatorAnalyzer.scala:46-50); here the analog is how many scans
became bucketed index scans (each is an exchange the executor never runs).
"""

from __future__ import annotations

from collections import Counter

from hyperspace_tpu.plan.nodes import Filter, Join, LogicalPlan, Project, Scan, Union


def pretty_plan(plan: LogicalPlan, indent: int = 0) -> str:
    pad = "  " * indent
    if isinstance(plan, Scan):
        kind = "IndexScan" if plan.bucket_spec is not None else "Scan"
        extra = ""
        if plan.bucket_spec is not None:
            extra = f" buckets={plan.bucket_spec[0]} bucketCols={plan.bucket_spec[1]}"
        return f"{pad}{kind} root={plan.root} cols={plan.scan_schema.names}{extra}"
    if isinstance(plan, Filter):
        return f"{pad}Filter {plan.predicate.to_json()}\n" + pretty_plan(plan.child, indent + 1)
    if isinstance(plan, Project):
        return f"{pad}Project {plan.columns}\n" + pretty_plan(plan.child, indent + 1)
    if isinstance(plan, Join):
        return (
            f"{pad}Join on {list(zip(plan.left_on, plan.right_on))}\n"
            + pretty_plan(plan.left, indent + 1)
            + "\n"
            + pretty_plan(plan.right, indent + 1)
        )
    if isinstance(plan, Union):
        return f"{pad}HybridScanUnion\n" + "\n".join(
            pretty_plan(c, indent + 1) for c in plan.inputs
        )
    return f"{pad}{type(plan).__name__}"


def _operator_counts(plan: LogicalPlan) -> Counter:
    c: Counter = Counter()

    def walk(p: LogicalPlan):
        if isinstance(p, Scan):
            c["IndexScan" if p.bucket_spec is not None else "Scan"] += 1
        else:
            c[type(p).__name__] += 1
        for ch in p.children():
            walk(ch)

    walk(plan)
    return c


def _used_indexes(plan: LogicalPlan, session) -> list[str]:
    """Match index-scan roots against the catalog
    (PlanAnalyzer.scala:129-152,209-221)."""
    roots = {s.root for s in plan.leaves() if s.bucket_spec is not None}
    used = []
    for entry in session.manager.get_indexes():
        if str(entry.content.root) in roots:
            used.append(entry.name)
    return used


def explain_string(plan: LogicalPlan, session, verbose: bool = False) -> str:
    """Run the rewriter off and on, diff (PlanAnalyzer.scala:163-178)."""
    was_enabled = session.is_hyperspace_enabled()
    try:
        session.enable_hyperspace()
        with_plan = session.optimized_plan(plan)
    finally:
        if not was_enabled:
            session.disable_hyperspace()

    before = pretty_plan(plan)
    after = pretty_plan(with_plan)
    out = []
    out.append("=" * 64)
    out.append("Plan with indexes:")
    out.append(after)
    out.append("=" * 64)
    out.append("Plan without indexes:")
    out.append(before)
    out.append("=" * 64)
    out.append("Indexes used:")
    for name in _used_indexes(with_plan, session):
        out.append(f"  {name}")
    if verbose:
        cb = _operator_counts(plan)
        ca = _operator_counts(with_plan)
        out.append("=" * 64)
        out.append("Physical operator stats:")
        for op in sorted(set(cb) | set(ca)):
            out.append(f"  {op}: {cb.get(op, 0)} -> {ca.get(op, 0)}")
        # The headline: every source scan turned into a bucketed index scan
        # is one exchange the executor never has to run.
        out.append(f"  ShuffleExchange-equivalents eliminated: {ca.get('IndexScan', 0)}")
    return "\n".join(out)
