"""Plan-diff explain: the observability story.

Reference parity: index/plananalysis/PlanAnalyzer.scala:34-410 — compile the
query twice (rules off / rules on), diff the plans highlighting replaced
subtrees, list the indexes actually used (matching scan roots against the
catalog), and in verbose mode report the per-operator occurrence diff —
whose headline number in the reference is removed ShuffleExchanges
(PhysicalOperatorAnalyzer.scala:46-50); here the analog is how many scans
became bucketed index scans (each is an exchange the executor never runs).
"""

from __future__ import annotations

from collections import Counter

from hyperspace_tpu.plan.nodes import Filter, Join, LogicalPlan, Project, Scan, Union


def _node_label(plan: LogicalPlan) -> str:
    """One-line description of a node WITHOUT its children."""
    if isinstance(plan, Scan):
        kind = "IndexScan" if plan.bucket_spec is not None else "Scan"
        extra = ""
        if plan.bucket_spec is not None:
            extra = f" buckets={plan.bucket_spec[0]} bucketCols={plan.bucket_spec[1]}"
        return f"{kind} root={plan.root} cols={plan.scan_schema.names}{extra}"
    if isinstance(plan, Filter):
        return f"Filter {plan.predicate.to_json()}"
    if isinstance(plan, Project):
        return f"Project {plan.output_names}"
    if isinstance(plan, Join):
        return f"Join on {list(zip(plan.left_on, plan.right_on))}"
    if isinstance(plan, Union):
        return "HybridScanUnion"
    from hyperspace_tpu.plan.nodes import Aggregate, Limit, Sort, Window

    if isinstance(plan, Aggregate):
        aggs = [f"{a.fn}({a.alias})" for a in plan.aggs]
        return f"Aggregate groupBy={plan.group_by} aggs={aggs}"
    if isinstance(plan, Window):
        funcs = [f"{f.fn}({f.alias})" for f in plan.funcs]
        return (
            f"Window partitionBy={plan.partition_by} orderBy={plan.order_by} "
            f"frame={plan.frame} funcs={funcs}"
        )
    if isinstance(plan, Sort):
        return f"Sort by={plan.by}"
    if isinstance(plan, Limit):
        return f"Limit {plan.n}"
    return type(plan).__name__


def _render_lines(plan: LogicalPlan, indent: int = 0, path: tuple = ()):
    """[(occurrence path, rendered line)] in pre-order. Paths (child-index
    tuples from the root) identify OCCURRENCES, not objects — plans are
    DAGs when a dataframe is reused, and a shared node highlighted in one
    leg must not light up its aliases elsewhere."""
    out = [(path, "  " * indent + _node_label(plan))]
    for i, c in enumerate(plan.children()):
        out.extend(_render_lines(c, indent + 1, path + (i,)))
    return out


def pretty_plan(plan: LogicalPlan, indent: int = 0) -> str:
    return "\n".join(
        line for _, line in _render_lines(plan, indent)
    )


def _mark_diff_trees(
    a, b, marked_a: set, marked_b: set, label, children, path: tuple = ()
) -> None:
    """Queue-style pairwise walk (PlanAnalyzer.scala:56-101): nodes whose
    labels match recurse into their children; any mismatch marks BOTH
    whole subtrees (by occurrence path) as differing. Shared by the
    logical and executed-physical diffs via (label, children) accessors."""

    def mark_subtree(p, acc: set, at: tuple) -> None:
        acc.add(at)
        for i, c in enumerate(children(p)):
            mark_subtree(c, acc, at + (i,))

    ca, cb = children(a), children(b)
    if label(a) != label(b) or len(ca) != len(cb):
        mark_subtree(a, marked_a, path)
        mark_subtree(b, marked_b, path)
        return
    for i, (x, y) in enumerate(zip(ca, cb)):
        _mark_diff_trees(x, y, marked_a, marked_b, label, children, path + (i,))


def _mark_diff(a: LogicalPlan, b: LogicalPlan, marked_a: set, marked_b: set) -> None:
    _mark_diff_trees(a, b, marked_a, marked_b, _node_label, lambda p: p.children())


def _render_highlighted(plan: LogicalPlan, marked: set, mode) -> str:
    lines = []
    for at, line in _render_lines(plan):
        lines.append(mode.highlight(line) if at in marked else line)
    return "\n".join(lines)


def _operator_counts(plan: LogicalPlan) -> Counter:
    c: Counter = Counter()

    def walk(p: LogicalPlan):
        if isinstance(p, Scan):
            c["IndexScan" if p.bucket_spec is not None else "Scan"] += 1
        else:
            c[type(p).__name__] += 1
        for ch in p.children():
            walk(ch)

    walk(plan)
    return c


def _used_indexes(plan: LogicalPlan, session) -> list[str]:
    """Match index-scan roots against the catalog
    (PlanAnalyzer.scala:129-152,209-221)."""
    roots = {s.root for s in plan.leaves() if s.bucket_spec is not None}
    used = []
    for entry in session.manager.get_indexes():
        if str(entry.content.root) in roots:
            used.append(entry.name)
    return used


def _physical_counts(root) -> Counter:
    c: Counter = Counter()
    for n in root.walk():
        c[n.op] += 1
    return c


def _render_physical(root, marked: set, mode, path: tuple = (), indent: int = 0) -> list:
    line = "  " * indent + root.label()
    out = [mode.highlight(line) if path in marked else line]
    for i, c in enumerate(root.children):
        out.extend(_render_physical(c, marked, mode, path + (i,), indent + 1))
    return out


def explain_executed(plan: LogicalPlan, session, mode=None) -> str:
    """EXECUTE the query twice (rules off / on) and diff the physical
    plans that actually ran — files read, kernels chosen, bucket/device
    counts, rows per operator. The analog of the reference diffing
    executedPlans (PlanAnalyzer.scala:163-178) with per-operator stats
    (PhysicalOperatorAnalyzer.scala:39-56); here the evidence is
    measured, not estimated, because the executor IS the physical layer.
    Note: this runs the query (twice); use explain() for a no-IO diff."""
    from hyperspace_tpu.explain.display_mode import display_mode_from_conf

    if mode is None:
        mode = display_mode_from_conf(getattr(session, "conf", None))

    was_enabled = session.is_hyperspace_enabled()
    try:
        from hyperspace_tpu.execution import io as _hio

        # COLD evidence on both sides: files_read counts physical (miss)
        # reads, so the shared decoded-table cache must not let either
        # run ride the other's warm state — the "files read: X -> Y"
        # line exists to show the INDEX's IO reduction.
        session.disable_hyperspace()
        _hio.clear_table_cache()
        session.run(plan)
        phys_without = session.last_physical_plan
        stats_without = session.last_query_stats
        session.enable_hyperspace()
        _hio.clear_table_cache()
        session.run(plan)
        phys_with = session.last_physical_plan
        stats_with = session.last_query_stats
        rewritten = session.optimized_plan(plan)
    finally:
        session._enabled = was_enabled

    marked_before: set = set()
    marked_after: set = set()
    _mark_diff_trees(
        phys_without, phys_with, marked_before, marked_after,
        lambda n: n.label(), lambda n: n.children,
    )

    out = []
    out.append("=" * 64)
    out.append("Executed plan with indexes:")
    out.extend(_render_physical(phys_with, marked_after, mode))
    out.append("=" * 64)
    out.append("Executed plan without indexes:")
    out.extend(_render_physical(phys_without, marked_before, mode))
    out.append("=" * 64)
    out.append("Indexes used:")
    for name in _used_indexes(rewritten, session):
        out.append(f"  {name}")
    out.append("=" * 64)
    out.append("Physical operator stats:")
    cb, ca = _physical_counts(phys_without), _physical_counts(phys_with)
    for op in sorted(set(cb) | set(ca)):
        out.append(f"  {op}: {cb.get(op, 0)} -> {ca.get(op, 0)}")
    out.append(
        f"  files read: {stats_without['files_read']} -> {stats_with['files_read']}"
    )
    out.append(
        f"  files pruned: {stats_without['files_pruned']} -> {stats_with['files_pruned']}"
    )
    out.append(
        f"  rows pruned: {stats_without['rows_pruned']} -> {stats_with['rows_pruned']}"
    )
    if stats_with.get("join_path"):
        out.append(
            f"  join path: {stats_without.get('join_path')} -> {stats_with['join_path']} "
            f"({stats_with.get('join_devices', 1)} device(s))"
        )
    return mode.finalize("\n".join(out))


def explain_analyze(plan: LogicalPlan, session) -> str:
    """EXPLAIN ANALYZE: run the query ONCE under the session's current
    enablement and render the measured QueryProfile — the operator tree
    that actually executed, annotated with per-operator wall time (and %
    of total), rows in/out, bytes decoded, kernel/venue choices, cache
    hit/miss deltas, and any corruption-fallback outcome. The analog of
    Postgres's EXPLAIN ANALYZE over the reference's static explain
    (PlanAnalyzer.scala only *estimates*; here the executor measures).

    Unlike explain(physical=True) this does not force a rules-off
    comparison run — it profiles the plan the session would really
    execute, which is what a production latency investigation wants."""
    from hyperspace_tpu.obs import profile as obs_profile

    session.run(plan)
    prof = session.last_profile()
    out = [obs_profile.render(prof)]
    rewritten = session.optimized_plan(plan)
    used = _used_indexes(rewritten, session)
    if used:
        out.append("indexes used: " + ", ".join(used))
    return "\n".join(out)


def explain_string(
    plan: LogicalPlan, session, verbose: bool = False, mode=None
) -> str:
    """Run the rewriter off and on, diff with differing subtrees
    highlighted in the configured display mode
    (PlanAnalyzer.scala:45-126, DisplayMode.scala:24-89)."""
    from hyperspace_tpu.explain.display_mode import display_mode_from_conf

    if mode is None:
        mode = display_mode_from_conf(getattr(session, "conf", None))

    from hyperspace_tpu.plan.prune import prune_columns
    from hyperspace_tpu.plan.pushdown import push_down_filters

    # Adaptive-routing verdict (docs/advisor.md): keyed on the ORIGINAL
    # plan's signature, exactly as run_query keys the ledger.
    routing_line = None
    conf = getattr(session, "conf", None)
    if conf is not None and getattr(conf, "advisor_routing_enabled", False):
        from hyperspace_tpu.signature import plan_signature

        demoted = plan_signature(plan) in set(
            session.routing_ledger().demoted_signatures()
        )
        routing_line = (
            "Adaptive routing: raw (indexed path measured slower; the "
            "rewrite below would NOT run)"
            if demoted
            else "Adaptive routing: indexed"
        )

    was_enabled = session.is_hyperspace_enabled()
    try:
        session.enable_hyperspace()
        with_plan = session.optimized_plan(plan)
    finally:
        if not was_enabled:
            session.disable_hyperspace()

    # Diff against the pushed-down, column-pruned baseline: those passes
    # run on BOTH sides (they are not index effects), so highlights show
    # only index rewrites.
    plan = prune_columns(push_down_filters(plan))
    marked_before: set = set()
    marked_after: set = set()
    _mark_diff(plan, with_plan, marked_before, marked_after)

    out = []
    out.append("=" * 64)
    out.append("Plan with indexes:")
    out.append(_render_highlighted(with_plan, marked_after, mode))
    out.append("=" * 64)
    out.append("Plan without indexes:")
    out.append(_render_highlighted(plan, marked_before, mode))
    out.append("=" * 64)
    out.append("Indexes used:")
    for name in _used_indexes(with_plan, session):
        out.append(f"  {name}")
    if routing_line is not None:
        out.append("=" * 64)
        out.append(routing_line)
    if verbose:
        cb = _operator_counts(plan)
        ca = _operator_counts(with_plan)
        out.append("=" * 64)
        out.append("Physical operator stats:")
        for op in sorted(set(cb) | set(ca)):
            out.append(f"  {op}: {cb.get(op, 0)} -> {ca.get(op, 0)}")
        # The headline: every source scan turned into a bucketed index scan
        # is one exchange the executor never has to run. Delta, not the
        # absolute after-count — a plan already holding index scans did not
        # have them "eliminated" by this rewrite.
        eliminated = ca.get("IndexScan", 0) - cb.get("IndexScan", 0)
        out.append(f"  ShuffleExchange-equivalents eliminated: {eliminated}")
    return mode.finalize("\n".join(out))
