"""Explain output display modes.

Reference parity: index/plananalysis/DisplayMode.scala:24-89 — the explain
text renders in three modes, each defining how differing plan fragments are
highlighted and how lines are terminated:

- plaintext: highlight with `<----` suffix markers;
- console: ANSI reverse-video highlight;
- html: <b>/</b>-style tags (overridable via conf, the notebook-injection
  hook of IndexConstants.scala:42-48), newlines as <br/>.

Selected via conf key `hyperspace.explain.displayMode`.
"""

from __future__ import annotations

# Declared in config.KNOWN_KEYS (the one hyperspace.* registry — HSL010);
# re-exported here for the existing import sites.
from hyperspace_tpu.config import (  # noqa: F401
    EXPLAIN_DISPLAY_MODE,
    EXPLAIN_HIGHLIGHT_BEGIN,
    EXPLAIN_HIGHLIGHT_END,
)


class DisplayMode:
    name = "base"
    newline = "\n"

    def highlight(self, line: str) -> str:
        raise NotImplementedError

    def finalize(self, text: str) -> str:
        return text


class PlainTextMode(DisplayMode):
    """Append a trailing marker to highlighted lines."""

    name = "plaintext"

    def highlight(self, line: str) -> str:
        return f"{line} <----"


class ConsoleMode(DisplayMode):
    """ANSI reverse video for highlighted lines."""

    name = "console"

    def highlight(self, line: str) -> str:
        return f"\x1b[7m{line}\x1b[27m"


class HTMLMode(DisplayMode):
    """Tag-wrapped highlights; tags overridable for notebook environments."""

    name = "html"
    newline = "<br/>"

    def __init__(self, begin_tag: str = "<b>", end_tag: str = "</b>"):
        self.begin_tag = begin_tag
        self.end_tag = end_tag

    def highlight(self, line: str) -> str:
        return f"{self.begin_tag}{line}{self.end_tag}"

    def finalize(self, text: str) -> str:
        # <pre> wrapper as in the reference (DisplayMode.scala) — without
        # it HTML collapses the leading-space indentation that carries the
        # plan-tree structure.
        return "<pre>" + text.replace("\n", self.newline) + "</pre>"


def display_mode_from_conf(conf) -> DisplayMode:
    name = "plaintext"
    if conf is not None:
        name = str(conf.get(EXPLAIN_DISPLAY_MODE, "plaintext")).lower()
    if name == "console":
        return ConsoleMode()
    if name == "html":
        begin, end = "<b>", "</b>"
        if conf is not None:
            begin = conf.get(EXPLAIN_HIGHLIGHT_BEGIN, begin)
            end = conf.get(EXPLAIN_HIGHLIGHT_END, end)
        return HTMLMode(begin, end)
    if name == "plaintext":
        return PlainTextMode()
    # Surface misconfiguration immediately (the reference's getDisplayMode
    # is an exhaustive match that errors on unknown values).
    raise ValueError(
        f"unknown {EXPLAIN_DISPLAY_MODE} value {name!r}; "
        "expected plaintext | console | html"
    )
