from hyperspace_tpu.explain.plan_analyzer import explain_string, pretty_plan

__all__ = ["explain_string", "pretty_plan"]
