"""Top-k selection on TPU: tiled Pallas kernel + lax.top_k fallback.

XLA lowers `lax.top_k` on TPU to a full sort — O(n log² n) bitonic passes
for a k of 10. The Pallas kernel instead streams score tiles through VMEM
once: each (query, tile) program unrolls k max/argmax/mask rounds on its
tile (k · 3 vector ops over data already in VMEM), emitting per-tile
partial top-k lists; one tiny `lax.top_k` over the [tiles·k] partials
merges the result. Work: O(n·k/T + tiles·k·log) ≈ one HBM pass.

This is the ANN/vector-index hot path (BASELINE config 5). CPU tests run
the same kernel in interpret mode; any Pallas failure falls back to
lax.top_k transparently (`topk(..., impl="xla")` forces the fallback).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from hyperspace_tpu import stats

_TILE = 2048
_MAX_PALLAS_K = 64

# (k, tile) combos whose Pallas lowering failed — only those fall back
# permanently; other shapes keep the fast path. Lock-guarded: concurrent
# serve-plane queries record failures from N worker threads.
import threading

_pallas_bad: set = set()
_pallas_bad_lock = threading.Lock()


def _next_mult(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


_QBLOCK = 8  # queries per program (TPU sublane granularity)


@functools.lru_cache(maxsize=32)
def _make_tile_kernel(k: int, tile: int, interpret: bool):
    from hyperspace_tpu.compat import resolve_pallas

    pl = resolve_pallas()

    out_lanes = _next_mult(k, 128)

    def kernel(x_ref, vals_ref, idx_ref):
        x = x_ref[...].astype(jnp.float32)  # (QBLOCK, tile)
        base = pl.program_id(1) * tile
        lanes = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
        for r in range(k):  # static unroll: k max/argmax/mask rounds
            m = jnp.max(x, axis=1)  # [QBLOCK]
            am = jnp.min(jnp.where(x == m[:, None], lanes, tile), axis=1)
            vals_ref[:, r] = m
            idx_ref[:, r] = am + base
            x = jnp.where(lanes == am[:, None], -jnp.inf, x)

    def run(scores):  # [q_pad, n_pad], q_pad % QBLOCK == n_pad % tile == 0
        q, n_pad = scores.shape
        tiles = n_pad // tile
        return pl.pallas_call(
            kernel,
            grid=(q // _QBLOCK, tiles),
            in_specs=[pl.BlockSpec((_QBLOCK, tile), lambda i, j: (i, j))],
            out_specs=[
                pl.BlockSpec((_QBLOCK, out_lanes), lambda i, j: (i, j)),
                pl.BlockSpec((_QBLOCK, out_lanes), lambda i, j: (i, j)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((q, tiles * out_lanes), jnp.float32),
                jax.ShapeDtypeStruct((q, tiles * out_lanes), jnp.int32),
            ],
            interpret=interpret,
        )(scores)

    # jit so repeated calls with the same shape hit the executable cache
    # instead of re-lowering the pallas_call every invocation.
    from hyperspace_tpu.compat import jit

    return jit(run, key="ops.topk.pallas_tile"), out_lanes


def _pallas_topk(scores: jnp.ndarray, k: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    q, n = scores.shape
    tile = min(_TILE, _next_mult(max(n, 128), 128))
    n_pad = _next_mult(n, tile)
    q_pad = _next_mult(q, _QBLOCK)
    if n_pad != n or q_pad != q:
        scores = jnp.pad(
            scores, ((0, q_pad - q), (0, n_pad - n)), constant_values=-np.inf
        )
    interpret = jax.default_backend() == "cpu"
    run, out_lanes = _make_tile_kernel(k, tile, interpret)
    vals, idx = run(scores)
    tiles = vals.shape[1] // out_lanes
    # Keep the k real lanes of each tile's 128-lane padded block.
    vals = vals.reshape(q_pad, tiles, out_lanes)[:q, :, :k].reshape(q, tiles * k)
    idx = idx.reshape(q_pad, tiles, out_lanes)[:q, :, :k].reshape(q, tiles * k)
    # Merge partials (tiny: tiles*k elements).
    mvals, mpos = jax.lax.top_k(vals, min(k, vals.shape[1]))
    midx = jnp.take_along_axis(idx, mpos, axis=1)
    return mvals, midx


def topk(scores, k: int, impl: str = "auto") -> tuple[np.ndarray, np.ndarray]:
    """Top-k (largest) per row of `scores` [q, n] → (values, indices)
    [q, k]. impl: "auto" (Pallas when eligible, else XLA), "pallas", "xla".
    """
    scores = jnp.asarray(scores)
    if scores.ndim == 1:
        v, i = topk(scores[None, :], k, impl)
        return v[0], i[0]
    # NaN scores are treated as -inf in BOTH paths: the Pallas kernel's
    # max/argmax rounds would otherwise never mask a NaN (x == NaN is
    # false) and emit an out-of-range index, and lax.top_k would rank NaN
    # first. -inf gives one deterministic, sane semantic for corrupt rows.
    scores = jnp.where(jnp.isnan(scores), -jnp.inf, scores)
    q, n = scores.shape
    k = min(k, n)
    tile = min(_TILE, _next_mult(max(n, 128), 128))
    use_pallas = impl == "pallas" or (
        impl == "auto" and k <= _MAX_PALLAS_K and n >= 512 and (k, tile) not in _pallas_bad
    )
    if use_pallas:
        try:
            v, i = _pallas_topk(scores, k)
            stats.increment("device.kernel.fused")
            return np.asarray(v), np.asarray(i)
        except Exception:  # noqa: BLE001 — fall back to the XLA path
            if impl == "pallas":
                raise
            with _pallas_bad_lock:
                _pallas_bad.add((k, tile))
            stats.increment("device.kernel.fallbacks")
    v, i = jax.lax.top_k(scores, k)
    return np.asarray(v), np.asarray(i)
